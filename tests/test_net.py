"""byol_tpu/serving/net/ — the wire front end (ISSUE 13 tentpole).

Layers, cheapest first:

1. **Protocol**: frame round-trips for both wire dtypes; every class of
   malformed request (bad framing, bad JSON, wrong version, wrong dtype,
   wrong shape, truncated/trailing payload, too many rows) maps to its
   typed 4xx — and decode can never produce a half-valid tensor.
2. **Server robustness** (stub engine, jax-free): each mapped 4xx comes
   back over a REAL socket with the server still serving afterwards; the
   deadline budget propagates (expired -> 408, saturation -> 429 with
   Retry-After, both within the budget — bounded and prompt, no hang).
3. **Lifecycle**: /healthz stays 200 while /readyz flips to 503 the
   moment a drain begins; a drain racing live client threads completes
   every accepted request and strands nothing (the SIGTERM hammer).
4. **Loadgen/smoke accounting** (ISSUE 13 satellite): failures are
   counted, surfaced, and turn the smoke exit code nonzero.
5. **Wire parity** (real engine on the CPU mesh): embeddings fetched
   over HTTP are bitwise equal to ``linear_eval.extract_features`` for
   exact-fill and padded buckets — the acceptance pin.
"""
import json
import struct
import threading
import time
import types
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import jax

from byol_tpu.serving.batcher import DynamicBatcher
from byol_tpu.serving.net import protocol
from byol_tpu.serving.net.client import (EmbedClient, WireClientError,
                                         parse_address)
from byol_tpu.serving.net.loadgen import run_closed_loop
from byol_tpu.serving.net.server import WireServer
from byol_tpu.serving.service import EmbeddingService
from tests.test_serving import _NUM_CLASSES, _StubEngine, _serve_cfg


# ---------------------------------------------------------------------------
# 1. protocol (no sockets, no jax)
# ---------------------------------------------------------------------------

_SHAPE = (4, 4, 3)


def _frame_bytes(header: dict, payload: bytes) -> bytes:
    head = json.dumps(header).encode()
    return struct.pack(">I", len(head)) + head + payload


class TestProtocol:
    def test_float32_roundtrip_is_exact(self):
        rng = np.random.RandomState(0)
        images = rng.rand(5, *_SHAPE).astype(np.float32)
        body = protocol.encode_request(images)
        decoded = protocol.decode_request(body, input_shape=_SHAPE,
                                          max_rows=16)
        np.testing.assert_array_equal(decoded, images)
        assert decoded.dtype == np.float32

    def test_uint8_conversion_rule_is_deterministic(self):
        """uint8 on the wire -> float32 x/255 on the host, the ONE
        documented rule — a uint8 client and a float32 client sending
        the converted array must produce identical model inputs."""
        rng = np.random.RandomState(1)
        u8 = rng.randint(0, 256, size=(3, *_SHAPE), dtype=np.uint8)
        decoded = protocol.decode_request(
            protocol.encode_request(u8), input_shape=_SHAPE, max_rows=16)
        expected = u8.astype(np.float32) / np.float32(255.0)
        np.testing.assert_array_equal(decoded, expected)
        # and the uint8 frame is ~4x smaller than the float32 one
        assert len(protocol.encode_request(u8)) < len(
            protocol.encode_request(expected)) / 2

    def test_single_image_lifted_to_one_row(self):
        img = np.zeros(_SHAPE, np.float32)
        decoded = protocol.decode_request(
            protocol.encode_request(img), input_shape=_SHAPE, max_rows=16)
        assert decoded.shape == (1,) + _SHAPE

    def test_response_roundtrip(self):
        emb = np.arange(8, dtype=np.float32).reshape(2, 4)
        out = protocol.decode_response(protocol.encode_response(emb))
        np.testing.assert_array_equal(out, emb)

    def test_encode_refuses_other_dtypes(self):
        with pytest.raises(ValueError, match="uint8 or float32"):
            protocol.encode_request(np.zeros((1, *_SHAPE), np.float64))

    @pytest.mark.parametrize("body,status,code", [
        (b"", 400, "bad_frame"),                      # shorter than prefix
        (b"\x00\x00\x00\x05ab", 400, "bad_frame"),    # ends inside header
        (struct.pack(">I", protocol.MAX_HEADER_BYTES + 1) + b"x",
         400, "bad_frame"),                           # header over the cap
        (_frame_bytes({"v": 99, "dtype": "uint8", "shape": [1, 4, 4, 3]},
                      bytes(48)), 400, "bad_version"),
        (struct.pack(">I", 7) + b"notjson", 400, "bad_header"),
        (_frame_bytes({"v": 1, "dtype": "float64",
                       "shape": [1, 4, 4, 3]}, bytes(8 * 48)),
         415, "unsupported_dtype"),
        (_frame_bytes({"v": 1, "dtype": "uint8", "shape": [1, 4, 4]},
                      bytes(16)), 400, "bad_shape"),  # ndim mismatch
        (_frame_bytes({"v": 1, "dtype": "uint8", "shape": [1, 9, 9, 3]},
                      bytes(243)), 400, "bad_shape"), # row-shape mismatch
        (_frame_bytes({"v": 1, "dtype": "uint8", "shape": [1, 4, 4, 3]},
                      bytes(10)), 400, "payload_size_mismatch"),  # short
        (_frame_bytes({"v": 1, "dtype": "uint8", "shape": [1, 4, 4, 3]},
                      bytes(99)), 400, "payload_size_mismatch"),  # long
        (_frame_bytes({"v": 1, "dtype": "uint8", "shape": [17, 4, 4, 3]},
                      bytes(17 * 48)), 413, "too_many_rows"),
    ])
    def test_malformed_requests_map_to_typed_4xx(self, body, status, code):
        with pytest.raises(protocol.WireError) as e:
            protocol.decode_request(body, input_shape=_SHAPE, max_rows=16)
        assert e.value.status == status and e.value.code == code

    def test_max_request_bytes_bounds_the_largest_legal_payload(self):
        cap = protocol.max_request_bytes(_SHAPE, max_rows=16)
        biggest = protocol.encode_request(
            np.zeros((16, *_SHAPE), np.float32))
        assert len(biggest) <= cap

    def test_parse_address(self):
        assert parse_address("127.0.0.1:8700") == ("127.0.0.1", 8700)
        with pytest.raises(ValueError, match="HOST:PORT"):
            parse_address("8700")
        with pytest.raises(ValueError, match="not an integer"):
            parse_address("host:80x0")


# ---------------------------------------------------------------------------
# 2 + 3. server over a real socket (stub engine — jax-free service)
# ---------------------------------------------------------------------------

def _stub_service(**kw) -> EmbeddingService:
    engine = _StubEngine(**kw.pop("engine_kw", {}))
    svc = EmbeddingService(
        engine,
        DynamicBatcher(max_batch=kw.pop("max_batch", 16),
                       max_queue=kw.pop("max_queue", 64),
                       max_wait_s=kw.pop("max_wait_s", 0.002)),
        **kw)
    svc.start(warmup=False)
    return svc


def _raw_post(host, port, body, headers=None, timeout=10.0):
    import http.client
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/embed", body=body,
                     headers={"Content-Type": "application/octet-stream",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


@pytest.fixture()
def stub_server():
    svc = _stub_service()
    server = WireServer(svc, "127.0.0.1", 0,
                        default_deadline_ms=10_000.0).start()
    yield server
    server.drain(grace_s=0.0, timeout_s=30.0)


def _good_body(rows=1):
    return protocol.encode_request(
        np.arange(rows * 48, dtype=np.float32).reshape(rows, *_SHAPE))


class TestServerRobustness:
    def test_embed_roundtrip_and_request_id_echo(self, stub_server):
        host, port = stub_server.address
        status, payload, headers = _raw_post(
            host, port, _good_body(), {"X-Request-Id": "req-abc"})
        assert status == 200
        assert headers.get("X-Request-Id") == "req-abc"
        out = protocol.decode_response(payload)
        # the stub echoes the first 4 features of each row
        np.testing.assert_array_equal(out, [[0.0, 1.0, 2.0, 3.0]])

    @pytest.mark.parametrize("body,status,code", [
        (b"garbage", 400, "bad_frame"),
        (_frame_bytes({"v": 1, "dtype": "float64",
                       "shape": [1, 4, 4, 3]}, bytes(8 * 48)),
         415, "unsupported_dtype"),
        (_frame_bytes({"v": 1, "dtype": "uint8", "shape": [1, 4, 4, 3]},
                      bytes(10)), 400, "payload_size_mismatch"),
        (_frame_bytes({"v": 1, "dtype": "uint8", "shape": [17, 4, 4, 3]},
                      bytes(17 * 48)), 413, "too_many_rows"),
    ])
    def test_each_4xx_leaves_the_server_serving(self, stub_server, body,
                                                status, code):
        """The acceptance pin: a malformed/oversized/wrong-dtype request
        is THAT client's mapped 4xx, and the very next good request on a
        fresh connection succeeds — parse errors can never kill the
        server or poison the worker."""
        host, port = stub_server.address
        got_status, payload, _ = _raw_post(host, port, body)
        assert got_status == status
        err = json.loads(payload)
        assert err["error"] == code
        ok_status, ok_payload, _ = _raw_post(host, port, _good_body())
        assert ok_status == 200
        assert protocol.decode_response(ok_payload).shape == (1, 4)

    def test_oversized_content_length_refused_before_read(self,
                                                          stub_server):
        host, port = stub_server.address
        status, payload, _ = _raw_post(
            host, port, b"",
            {"Content-Length": str(stub_server.max_body_bytes + 1)})
        assert status == 413
        assert json.loads(payload)["error"] == "too_large"
        # server healthy afterwards (new connection — the oversized one
        # was deliberately closed)
        assert _raw_post(host, port, _good_body())[0] == 200

    def test_missing_content_length_is_411(self, stub_server):
        import http.client
        host, port = stub_server.address
        conn = http.client.HTTPConnection(host, port, timeout=10.0)
        try:
            # bypass http.client's automatic Content-Length
            conn.putrequest("POST", "/v1/embed", skip_host=False)
            conn.putheader("Transfer-Encoding", "chunked")
            conn.endheaders()
            conn.send(b"0\r\n\r\n")
            resp = conn.getresponse()
            assert resp.status == 411
        finally:
            conn.close()

    def test_expired_deadline_is_408(self, stub_server):
        host, port = stub_server.address
        status, payload, _ = _raw_post(host, port, _good_body(),
                                       {"X-Deadline-Ms": "0"})
        assert status == 408
        assert json.loads(payload)["error"] == "deadline_expired"
        assert _raw_post(host, port, _good_body())[0] == 200

    def test_invalid_deadline_is_400(self, stub_server):
        host, port = stub_server.address
        for bad in ("abc", "NaN", "inf", "-inf", "-Infinity"):
            status, payload, _ = _raw_post(host, port, _good_body(),
                                           {"X-Deadline-Ms": bad})
            assert status == 400, bad
            assert json.loads(payload)["error"] == "bad_deadline"

    def test_health_ready_stats_endpoints(self, stub_server):
        host, port = stub_server.address
        with EmbedClient(host, port, timeout_s=10.0) as c:
            assert c.get("/healthz")[0] == 200
            assert c.get("/readyz")[0] == 200
            c.embed(np.zeros((1, *_SHAPE), np.float32))
            status, body = c.get("/statsz")
            assert status == 200
            stats = json.loads(body)
            assert stats["draining"] is False
            assert stats["serve_stats"]["requests"] >= 1.0
            # the wire-phase block reached the stats surface
            assert stats["serve_stats"]["wire"]["status"]["200"] >= 1
            assert c.get("/nope")[0] == 404

    def test_saturated_queue_answers_429_within_budget(self):
        """Backpressure maps to 429 + Retry-After and comes back INSIDE
        the deadline budget: a saturated service refuses promptly, it
        never hangs a client or strands a future."""
        svc = _stub_service(engine_kw={"dispatch_delay_s": 1.0},
                            max_queue=1, max_wait_s=0.0)
        server = WireServer(svc, "127.0.0.1", 0,
                            default_deadline_ms=10_000.0).start()
        host, port = server.address
        deadline_ms = 400.0
        results = []
        lock = threading.Lock()

        def one(idx):
            t0 = time.perf_counter()
            with EmbedClient(host, port, timeout_s=10.0,
                             max_attempts=1) as c:
                try:
                    c.embed(np.zeros((1, *_SHAPE), np.float32),
                            deadline_ms=deadline_ms)
                    status = 200
                except WireClientError as e:
                    status = e.status
            with lock:
                results.append((status, time.perf_counter() - t0))

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        try:
            statuses = [s for s, _ in results]
            assert len(results) == 6
            # with the engine busy 1s/batch and one queue slot, most of a
            # 6-way burst must be REFUSED (429) or expire waiting (408) —
            # and every answer lands well inside budget + slop, far
            # before the 1s compute would
            assert 429 in statuses, statuses
            assert all(s in (200, 408, 429) for s in statuses), statuses
            assert all(el < deadline_ms / 1e3 + 2.0
                       for _, el in results), results
            # the Retry-After header rides every 429
            status, payload, headers = _raw_post(
                host, port, _good_body(), {"X-Deadline-Ms": "50"})
            if status == 429:
                assert "Retry-After" in headers
        finally:
            server.drain(grace_s=0.0, timeout_s=30.0)


class TestLifecycle:
    def test_readyz_flips_503_during_drain_healthz_stays_200(self,
                                                             stub_server):
        host, port = stub_server.address
        with EmbedClient(host, port, timeout_s=10.0) as c:
            assert c.get("/readyz")[0] == 200
            stub_server.begin_drain()
            assert c.get("/readyz")[0] == 503
            # liveness must outlive readiness: the draining process is
            # healthy, it is just not taking NEW work
            assert c.get("/healthz")[0] == 200
            # and a new embed is refused with the draining 503
            with pytest.raises(WireClientError) as e:
                with EmbedClient(host, port, timeout_s=10.0,
                                 max_attempts=1) as c2:
                    c2.embed(np.zeros((1, *_SHAPE), np.float32))
            assert e.value.status == 503

    def test_drain_vs_inflight_hammer_strands_nothing(self):
        """The concurrent SIGTERM-vs-inflight pin: client threads hammer
        embeds while the main thread drains.  Every answered 200 carries
        a valid body, every accepted request completes (drain returns
        clean), refused requests see 503/transport errors — and no
        thread is left hanging."""
        svc = _stub_service(engine_kw={"dispatch_delay_s": 0.005},
                            max_queue=64)
        server = WireServer(svc, "127.0.0.1", 0,
                            default_deadline_ms=30_000.0).start()
        host, port = server.address
        stats = {"ok": 0, "refused": 0}
        errors = []
        lock = threading.Lock()

        def spam(idx):
            img = np.zeros((1, *_SHAPE), np.float32)
            with EmbedClient(host, port, timeout_s=15.0,
                             max_attempts=1, seed=idx) as c:
                while True:
                    try:
                        out = c.embed(img)
                        if out.shape != (1, 4):
                            with lock:
                                errors.append(f"bad shape {out.shape}")
                            return
                        with lock:
                            stats["ok"] += 1
                    except WireClientError as e:
                        if e.status in (0, 503):   # drained/closed: done
                            with lock:
                                stats["refused"] += 1
                            return
                        with lock:
                            errors.append(str(e))
                        return

        threads = [threading.Thread(target=spam, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.2)                  # let traffic build
        clean = server.drain(grace_s=0.0, timeout_s=30.0)
        for t in threads:
            t.join(timeout=30.0)
        assert not any(t.is_alive() for t in threads)
        assert clean, "drain timed out with requests in flight"
        assert not errors, errors
        assert stats["ok"] > 0           # real traffic was flowing
        # and the service fully stopped behind the drain
        from byol_tpu.serving.batcher import ServiceClosed
        with pytest.raises(ServiceClosed):
            svc.submit(np.zeros((1, *_SHAPE), np.float32))


# ---------------------------------------------------------------------------
# client backoff against a scripted server
# ---------------------------------------------------------------------------

class _ScriptedHandler(BaseHTTPRequestHandler):
    """Answers POSTs from a scripted status list (latched at the end)."""

    script = [200]
    calls = 0

    def log_message(self, *a):
        pass

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        cls = type(self)
        status = cls.script[min(cls.calls, len(cls.script) - 1)]
        cls.calls += 1
        if status == 200:
            body = protocol.encode_response(
                np.zeros((1, 4), np.float32))
            ctype = "application/octet-stream"
        else:
            body = json.dumps({"error": "scripted",
                               "message": "go away"}).encode()
            ctype = "application/json"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if status in (429, 503):
            self.send_header("Retry-After", "0.01")
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def scripted_server():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _ScriptedHandler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield httpd
    httpd.shutdown()
    httpd.server_close()


class TestClientBackoff:
    def test_retries_429_then_succeeds(self, scripted_server):
        _ScriptedHandler.script, _ScriptedHandler.calls = \
            [429, 429, 200], 0
        host, port = scripted_server.server_address[:2]
        with EmbedClient(host, port, timeout_s=5.0, max_attempts=5,
                         backoff_s=0.005, backoff_max_s=0.02,
                         seed=0) as c:
            out = c.embed(np.zeros((1, *_SHAPE), np.float32))
        assert out.shape == (1, 4)
        assert _ScriptedHandler.calls == 3       # 2 retries, then 200

    def test_gives_up_after_attempt_budget(self, scripted_server):
        _ScriptedHandler.script, _ScriptedHandler.calls = [503], 0
        host, port = scripted_server.server_address[:2]
        with EmbedClient(host, port, timeout_s=5.0, max_attempts=2,
                         backoff_s=0.005, backoff_max_s=0.02,
                         seed=0) as c:
            with pytest.raises(WireClientError) as e:
                c.embed(np.zeros((1, *_SHAPE), np.float32))
        assert e.value.status == 503
        assert _ScriptedHandler.calls == 2

    def test_non_retryable_4xx_raises_immediately(self, scripted_server):
        _ScriptedHandler.script, _ScriptedHandler.calls = [415], 0
        host, port = scripted_server.server_address[:2]
        with EmbedClient(host, port, timeout_s=5.0, max_attempts=5,
                         seed=0) as c:
            with pytest.raises(WireClientError) as e:
                c.embed(np.zeros((1, *_SHAPE), np.float32))
        assert e.value.status == 415
        assert _ScriptedHandler.calls == 1       # no retry on client bugs


# ---------------------------------------------------------------------------
# 4. loadgen + smoke exit-code accounting (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

class TestLoadgenAccounting:
    def test_failures_are_counted_not_swallowed(self):
        calls = {"n": 0}
        lock = threading.Lock()

        def embed(idx, img):
            with lock:
                calls["n"] += 1
                n = calls["n"]
            if n % 3 == 0:
                raise RuntimeError("boom")

        res = run_closed_loop(embed, _SHAPE, 20, 4, seed=0)
        assert res.completed + res.failed == 20
        assert res.failed == 20 // 3
        assert res.errors and "boom" in res.errors[0]
        assert not res.ok

    def test_all_success_is_ok(self):
        res = run_closed_loop(lambda i, img: None, _SHAPE, 12, 3)
        assert res.completed == 12 and res.failed == 0 and res.ok
        assert res.percentile_ms(50) >= 0.0

    def test_stream_setup_failure_fails_that_streams_share(self):
        def setup(idx):
            raise ConnectionRefusedError("no server")

        res = run_closed_loop(lambda i, img: None, _SHAPE, 8, 2,
                              stream_setup=setup)
        assert res.failed == 8 and res.completed == 0
        assert not res.ok

    def test_smoke_exit_code_pins_failure_nonzero(self):
        """The ISSUE 13 audit, pinned: a smoke run exits nonzero when ANY
        request failed or went missing — and zero only on a full sweep of
        successes."""
        from byol_tpu.serving.net.loadgen import LoadgenResult
        from byol_tpu.serving.cli import _smoke_rc
        assert _smoke_rc(LoadgenResult(requested=8, completed=8,
                                       failed=0), 8) == 0
        assert _smoke_rc(LoadgenResult(requested=8, completed=7,
                                       failed=1), 8) == 1
        assert _smoke_rc(LoadgenResult(requested=8, completed=7,
                                       failed=0), 8) == 1   # lost != ok


# ---------------------------------------------------------------------------
# 5. wire parity on the real engine (CPU mesh)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def wire_served(mesh8):
    """Real encoder on the 8-device CPU mesh behind the full wire stack:
    protocol -> HTTP -> batcher -> AOT engine."""
    from byol_tpu.core.config import resolve
    from byol_tpu.parallel.compile_plan import build_plan
    from byol_tpu.serving.buckets import BucketSpec
    from byol_tpu.serving.engine import ServingEngine
    from byol_tpu.training.build import build_net, init_variables
    from byol_tpu.training.linear_eval import frozen_representation_fn

    cfg = _serve_cfg()
    rcfg = resolve(cfg, num_train_samples=64, num_test_samples=16,
                   output_size=_NUM_CLASSES, input_shape=(16, 16, 3))
    net = build_net(rcfg)
    with mesh8:
        variables = init_variables(net, rcfg, jax.random.PRNGKey(3))
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    represent = frozen_representation_fn(net, params, batch_stats,
                                         half=False, normalize=False)
    engine = ServingEngine(represent, build_plan(mesh8),
                           input_shape=(16, 16, 3),
                           buckets=BucketSpec(min_bucket=8,
                                              max_bucket=16))
    service = EmbeddingService(
        engine, DynamicBatcher(max_batch=16, max_wait_s=0.005))
    service.start(warmup=True)
    server = WireServer(service, "127.0.0.1", 0,
                        default_deadline_ms=300_000.0).start()
    yield types.SimpleNamespace(net=net, params=params,
                                batch_stats=batch_stats,
                                service=service, server=server)
    server.drain(grace_s=0.0, timeout_s=60.0)


class TestWireParity:
    def test_wire_embeddings_bitwise_match_linear_eval(self, wire_served):
        """The acceptance pin: the wire adds framing, HTTP, batching,
        bucket padding, and pipelined dispatch — and not one bit of
        difference to the embeddings, for exact-fill AND padded
        buckets."""
        from tests.test_serving import _extractor_features
        rng = np.random.RandomState(11)
        images = rng.rand(16, 16, 16, 3).astype(np.float32)
        expected = _extractor_features(wire_served, images)
        host, port = wire_served.server.address
        with EmbedClient(host, port, timeout_s=300.0) as c:
            got_full = c.embed(images)            # exact fill: bucket 16
            got_padded = c.embed(images[:11])     # padded: bucket 16
            got_small = c.embed(images[:3])       # below floor: bucket 8
        np.testing.assert_array_equal(got_full, expected)
        np.testing.assert_array_equal(got_padded, expected[:11])
        np.testing.assert_array_equal(got_small, expected[:3])
        # the wire added no recompiles either
        assert wire_served.service.engine.compile_count == 2

    def test_uint8_wire_path_matches_converted_float(self, wire_served):
        """A uint8 client gets bitwise the embeddings of the documented
        x/255 float conversion (and ships 4x fewer payload bytes)."""
        from tests.test_serving import _extractor_features
        rng = np.random.RandomState(12)
        u8 = rng.randint(0, 256, size=(8, 16, 16, 3), dtype=np.uint8)
        as_float = u8.astype(np.float32) / np.float32(255.0)
        expected = _extractor_features(wire_served, as_float)
        host, port = wire_served.server.address
        with EmbedClient(host, port, timeout_s=300.0) as c:
            got = c.embed(u8)
        np.testing.assert_array_equal(got, expected)

    def test_wire_phases_reach_serve_stats(self, wire_served):
        """serve_stats' additive wire block carries the HTTP status
        histogram and read/parse/wait/write means, and round-trips the
        strict event schema."""
        from byol_tpu.observability.events import RunLog, read_events
        meter = wire_served.service.meter
        snap = meter.snapshot(time.perf_counter(), reset=False)
        wire = snap.get("wire")
        assert wire is not None
        assert wire["status"].get("200", 0) >= 1
        assert set(wire["phase_ms"]) <= {"read", "parse", "wait", "write"}
        assert wire["phase_ms"]["wait"] >= 0.0
        import tempfile, os
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "serve.jsonl")
            with RunLog(path) as log:
                meter.emit(log, time.perf_counter(), reset=False,
                           compile_count=2)
            events = list(read_events(path))
        assert events[0]["kind"] == "serve_stats"
        assert events[0]["wire"]["status"]["200"] >= 1
