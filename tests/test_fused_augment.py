"""Fused uint8→two-view augmentation kernel (ISSUE 14 tentpole).

The contracts under test:

- **View equivalence** (acceptance): ``fused_two_view`` matches
  ``device_augment.two_view`` under identical keys — crop and flip EXACT
  (the kernel contracts the very weight matrices scale_and_translate
  builds, with the flip folded as a column permutation), the jitter/
  grayscale/blur arithmetic within fp32 tolerance (1e-5) — under the
  ``step_guard`` transfer guard on uint8 AND float32 inputs.
- **Per-op decomposition** (satellite): crop / flip / jitter / grayscale
  each pinned in isolation through the shared ``_view_pipeline`` with
  FORCED gates, so an equivalence failure names the op, not just "views
  differ".
- **Train-step parity** (acceptance): ``--fused-augment on`` reaches the
  same loss metrics and post-step params as the unfused step-placement
  path at accum 1 AND 2 on the 8-device mesh, under ``guard_steps``.
- **Off-identity** (acceptance): ``--fused-augment off`` lowers
  byte-identical HLO to a step built with no fused-augment plumbing at
  all; ``on`` really traces a different program.
- **Key stream** (satellite): ``augment_keys`` never collides across
  (step, microbatch-index) pairs within a run's step range.
- **Gating**: resolve() and make_train_step reject the combinations the
  kernel does not serve, with actionable errors.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.core import config as config_lib
from byol_tpu.data import device_augment
from byol_tpu.ops import fused_augment
from byol_tpu.parallel.mesh import shard_batch_to_mesh
from byol_tpu.training.build import setup_training
from byol_tpu.training.steps import augment_keys
from tests.conftest import guard_steps, tree_maxdiff

SIZE = 24      # augment target (= model input)
RAW = 28       # stored raw image size (crops come from here)


def make_rcfg(fused, accum_steps=1, batch=16):
    c = config_lib.Config()
    c = c.replace(
        task=dataclasses.replace(c.task, batch_size=batch, epochs=2,
                                 augment_placement="step",
                                 fused_augment=fused,
                                 image_size_override=SIZE),
        model=dataclasses.replace(c.model, arch="resnet18",
                                  head_latent_size=64, projection_size=32),
        optim=dataclasses.replace(c.optim, warmup=1, lr=0.1,
                                  accum_steps=accum_steps),
        device=dataclasses.replace(c.device, num_replicas=8, half=False,
                                   seed=11),
    )
    return config_lib.resolve(c, num_train_samples=128, num_test_samples=32,
                              output_size=10, input_shape=(SIZE, SIZE, 3))


def _uint8_batch(n=6, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randint(0, 256, (n, RAW, RAW, 3),
                                   dtype=np.uint8))


# ---------------------------------------------------------------------------
# view equivalence: fused kernel == the unfused two-view program
# ---------------------------------------------------------------------------

class TestViewEquivalence:
    def test_fused_matches_two_view_uint8(self, step_guard):
        """ACCEPTANCE: identical keys -> matching views (crop/flip exact,
        arithmetic <= 1e-5) on the raw uint8 step-placement contract,
        under the transfer guard (no hidden host syncs in the fused
        path)."""
        imgs = _uint8_batch()
        key = jax.random.PRNGKey(5)
        ref = jax.jit(lambda k, im: device_augment.two_view(k, im, SIZE))
        fus = jax.jit(lambda k, im: fused_augment.fused_two_view(
            k, im, SIZE))
        v1a, v2a = step_guard(ref)(key, imgs)
        v1b, v2b = step_guard(fus)(key, imgs)
        assert float(jnp.max(jnp.abs(v1a - v1b))) < 1e-5
        assert float(jnp.max(jnp.abs(v2a - v2b))) < 1e-5
        assert v1b.dtype == jnp.float32
        assert v1b.shape == (imgs.shape[0], SIZE, SIZE, 3)

    def test_fused_matches_two_view_float32(self):
        """two_view also accepts float32 [0,1] images; the kernel's uint8
        convert is statically gated off on that dtype."""
        imgs = _uint8_batch().astype(jnp.float32) / 255.0
        key = jax.random.PRNGKey(9)
        v1a, v2a = device_augment.two_view(key, imgs, SIZE)
        v1b, v2b = fused_augment.fused_two_view(key, imgs, SIZE)
        assert float(jnp.max(jnp.abs(v1a - v1b))) < 1e-5
        assert float(jnp.max(jnp.abs(v2a - v2b))) < 1e-5

    def test_strength_zero_skips_hue_statically(self):
        """strength=0 degenerates every jitter factor to 1/theta to 0 and
        statically removes the hue branch in BOTH paths — they must still
        agree (the hue=0.2*strength>0 static gate is shared)."""
        imgs = _uint8_batch(4, seed=3)
        key = jax.random.PRNGKey(2)
        v1a, _ = device_augment.two_view(key, imgs, SIZE, strength=0.0)
        v1b, _ = fused_augment.fused_two_view(key, imgs, SIZE, strength=0.0)
        assert float(jnp.max(jnp.abs(v1a - v1b))) < 1e-5


# ---------------------------------------------------------------------------
# per-op decomposition: a failure names the op (satellite)
# ---------------------------------------------------------------------------

class TestDecomposition:
    """Each stage pinned in isolation: the crop weights against
    scale_and_translate itself, the flip fold, and the shared jitter/
    grayscale arithmetic through ``_view_pipeline`` with forced gates."""

    def _img_and_params(self, seed=0):
        rng = np.random.RandomState(seed)
        img = jnp.asarray(rng.rand(RAW, RAW, 3).astype(np.float32))
        p = device_augment.view_params(jax.random.PRNGKey(seed), RAW, RAW,
                                       1.0)
        return img, p

    def _prm(self, p, *, jitter, gray):
        return jnp.stack([jnp.float32(jitter), p.fb, p.fc, p.fs, p.theta,
                          jnp.float32(gray)])

    def test_crop_indices_exact(self):
        """The host-side weight matrices applied by the kernel's einsum
        reproduce device_augment.apply_crop (= scale_and_translate)
        BITWISE — the crop window math is the same, only realized as
        explicit per-row sampling weights."""
        for seed in range(8):
            img, p = self._img_and_params(seed)
            ref = device_augment.apply_crop(img, p.y0, p.x0, p.ch, p.cw,
                                            SIZE)
            wy, wx = fused_augment.crop_weight_mats(
                p._replace(flip=jnp.asarray(False)), RAW, RAW, SIZE)
            got = fused_augment._view_pipeline(
                img, wy, wx, self._prm(p, jitter=0.0, gray=0.0), hue=True)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(ref),
                                          err_msg=f"crop seed={seed}")

    def test_flip_exact(self):
        """Flip folded into wx's column order == flipping the cropped
        view, bitwise (a column permutation commutes with the row
        contraction and the clip)."""
        img, p = self._img_and_params(1)
        ref = device_augment.apply_crop(img, p.y0, p.x0, p.ch, p.cw,
                                        SIZE)[:, ::-1, :]
        wy, wx = fused_augment.crop_weight_mats(
            p._replace(flip=jnp.asarray(True)), RAW, RAW, SIZE)
        got = fused_augment._view_pipeline(
            img, wy, wx, self._prm(p, jitter=0.0, gray=0.0), hue=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_jitter_fp32_tolerance(self):
        """Forced jitter gate: the kernel stage == apply_color_jitter on
        the same crop (shared arithmetic; fusion-order noise only)."""
        img, p = self._img_and_params(2)
        crop = device_augment.apply_crop(img, p.y0, p.x0, p.ch, p.cw, SIZE)
        ref = device_augment.apply_color_jitter(crop, p.fb, p.fc, p.fs,
                                                p.theta, hue=True)
        wy, wx = fused_augment.crop_weight_mats(
            p._replace(flip=jnp.asarray(False)), RAW, RAW, SIZE)
        got = fused_augment._view_pipeline(
            img, wy, wx, self._prm(p, jitter=1.0, gray=0.0), hue=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=1e-6)

    def test_grayscale_exact(self):
        img, p = self._img_and_params(4)
        crop = device_augment.apply_crop(img, p.y0, p.x0, p.ch, p.cw, SIZE)
        ref = device_augment.apply_grayscale(crop)
        wy, wx = fused_augment.crop_weight_mats(
            p._replace(flip=jnp.asarray(False)), RAW, RAW, SIZE)
        got = fused_augment._view_pipeline(
            img, wy, wx, self._prm(p, jitter=0.0, gray=1.0), hue=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_weight_mat_matches_scale_and_translate_downscale(self):
        """The antialiased (kernel-widened) downsampling arm: a crop
        window LARGER than the output (ch > size) must still match —
        the 2-tap bilinear shortcut would not."""
        img = jnp.asarray(np.random.RandomState(7).rand(RAW, RAW, 3)
                          .astype(np.float32))
        y0 = jnp.float32(0.5)
        x0 = jnp.float32(1.0)
        ch = jnp.float32(RAW - 1.0)      # > SIZE: genuine downscale
        cw = jnp.float32(RAW - 2.0)
        ref = device_augment.apply_crop(img, y0, x0, ch, cw, SIZE)
        sy, sx = SIZE / ch, SIZE / cw
        wy = fused_augment._weight_mat(RAW, SIZE, sy, -y0 * sy)
        wx = fused_augment._weight_mat(RAW, SIZE, sx, -x0 * sx)
        got = jnp.clip(
            jnp.einsum(img, [0, 1, 2], wy, [0, 3], wx, [1, 4], [3, 4, 2],
                       precision=jax.lax.Precision.HIGHEST), 0.0, 1.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# augment_keys collision property (satellite)
# ---------------------------------------------------------------------------

class TestAugmentKeyStream:
    def test_no_collisions_over_run_step_range(self):
        """Property: across a run-sized (step, microbatch-index) range the
        derived keys are pairwise distinct — fold_in on the step counter
        and again on the microbatch index never lands two pairs on the
        same key (key reuse would correlate the two views' randomness
        across steps, the GL103 hazard at runtime)."""
        seed, k, steps = 7, 8, 64
        seen = set()
        for step in range(steps):
            keys = np.asarray(augment_keys(seed, jnp.asarray(step,
                                                             jnp.int32), k))
            assert keys.shape[0] == k
            seen.update(tuple(map(int, kk)) for kk in keys)
        assert len(seen) == steps * k

    def test_distinct_seeds_decorrelate(self):
        a = np.asarray(augment_keys(1, jnp.asarray(0, jnp.int32), 4))
        b = np.asarray(augment_keys(2, jnp.asarray(0, jnp.int32), 4))
        assert not np.array_equal(a, b)


# ---------------------------------------------------------------------------
# train-step parity + HLO identity (acceptance)
# ---------------------------------------------------------------------------

class TestTrainStepParity:
    @pytest.mark.parametrize("accum", [1, 2])
    def test_fused_matches_unfused_step(self, mesh8, step_guard, accum):
        """ACCEPTANCE: the fused-augment train step == the unfused
        step-placement step on the same raw uint8 stream — matching loss
        metrics AND post-step params at accum 1 and 2, under the transfer
        guard on the 8-device mesh."""
        states, metrics = {}, {}
        rng = np.random.RandomState(3)
        batch = {
            "images": rng.randint(0, 256, (16, RAW, RAW, 3),
                                  dtype=np.uint8),
            "label": rng.randint(0, 10, size=(16,)).astype(np.int32),
        }
        for fused in ("off", "on"):
            rcfg = make_rcfg(fused, accum_steps=accum)
            _, state, step, _, _ = setup_training(rcfg, mesh8,
                                                  jax.random.PRNGKey(0))
            sb = shard_batch_to_mesh(dict(batch), mesh8)
            state, m = step_guard(step)(state, sb)
            states[fused], metrics[fused] = state, m
        for k in metrics["off"]:
            np.testing.assert_allclose(
                float(metrics["on"][k]), float(metrics["off"][k]),
                rtol=2e-4, atol=2e-4, err_msg=f"metric {k} @ accum={accum}")
        assert tree_maxdiff(states["off"].params,
                            states["on"].params) < 5e-4
        assert tree_maxdiff(states["off"].batch_stats,
                            states["on"].batch_stats) < 1e-4
        assert int(states["on"].step) == int(states["off"].step)

    def test_fused_off_lowers_identical_hlo(self, mesh8):
        """The off arm's program must be byte-identical to a step built
        with NO fused-augment plumbing at all — make_train_step invoked
        exactly as the pre-fused-augment code invoked it."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from byol_tpu.core.precision import get_policy
        from byol_tpu.parallel.compile_plan import build_plan
        from byol_tpu.parallel.mesh import DATA_AXIS
        from byol_tpu.parallel.partitioning import state_shardings
        from byol_tpu.training.build import build_net, build_tx, step_config
        from byol_tpu.training.steps import make_train_step

        rcfg = make_rcfg("off")
        plan = build_plan(mesh8, zero1=False)
        _, state, train_step, _, _ = setup_training(
            rcfg, mesh8, jax.random.PRNGKey(0), plan=plan)
        rng = np.random.RandomState(0)
        batch = shard_batch_to_mesh(
            {"images": rng.randint(0, 256, (16, RAW, RAW, 3),
                                   dtype=np.uint8),
             "label": rng.randint(0, 10, size=(16,)).astype(np.int32)},
            mesh8)
        with mesh8:
            off_text = train_step.__wrapped__.lower(state, batch).as_text()

        bare = jax.jit(
            make_train_step(build_net(rcfg), build_tx(rcfg)[0],
                            step_config(rcfg), get_policy(False)),
            in_shardings=(state_shardings(state, mesh8),
                          NamedSharding(mesh8, P(DATA_AXIS))),
            out_shardings=(state_shardings(state, mesh8),
                           NamedSharding(mesh8, P())),
            donate_argnums=(0,))
        with mesh8:
            bare_text = bare.lower(state, batch).as_text()
        assert off_text == bare_text

    def test_fused_on_lowers_a_different_program(self, mesh8):
        texts = {}
        rng = np.random.RandomState(0)
        batch = shard_batch_to_mesh(
            {"images": rng.randint(0, 256, (16, RAW, RAW, 3),
                                   dtype=np.uint8),
             "label": rng.randint(0, 10, size=(16,)).astype(np.int32)},
            mesh8)
        for fused in ("off", "on"):
            rcfg = make_rcfg(fused)
            _, state, train_step, _, _ = setup_training(
                rcfg, mesh8, jax.random.PRNGKey(0))
            with mesh8:
                texts[fused] = train_step.__wrapped__.lower(
                    state, batch).as_text()
        assert texts["on"] != texts["off"]


# ---------------------------------------------------------------------------
# ops/common.py hoist (satellite): shared helpers, behavior pinned
# ---------------------------------------------------------------------------

class TestOpsCommonHoist:
    def test_fused_update_reexports_the_shared_helpers(self):
        """The hoist must be a move, not a fork: fused_update's public
        grid-sizing names ARE the ops/common.py objects (one
        implementation for every kernel)."""
        from byol_tpu.ops import common
        from byol_tpu.ops import fused_update as fu
        assert fu.resolve_block_rows is common.resolve_block_rows
        assert fu.TPU_BLOCK_ROWS == common.TPU_BLOCK_ROWS == 256

    def test_fat_tile_backs_the_interpreter_grid(self):
        """resolve_block_rows' interpreter arm == fat_tile(align=8): the
        fat-tile heuristic the fused_update tests pin is the shared one."""
        from byol_tpu.ops import common
        for n in (3, 100, 4096, 10_000):
            assert (common.resolve_block_rows(n, True)
                    == common.fat_tile(n, align=8))
        assert common.fat_tile(5, align=1) == 1           # unit grids
        assert common.fat_tile(170, align=1) == 11        # ceil(170/16)

    def test_resolve_interpret_explicit_wins(self):
        from byol_tpu.ops import common
        assert common.resolve_interpret(True) is True
        assert common.resolve_interpret(False) is False
        # None: backend-derived — on the CPU test box that means interpret
        assert common.resolve_interpret(None) is True


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------

class TestGating:
    def _resolve(self, c):
        return config_lib.resolve(c, num_train_samples=128,
                                  num_test_samples=32, output_size=10,
                                  input_shape=(SIZE, SIZE, 3))

    def test_resolve_rejects_loader_placement(self):
        c = config_lib.Config()
        c = c.replace(task=dataclasses.replace(
            c.task, batch_size=16, fused_augment="on",
            augment_placement="loader"))
        with pytest.raises(ValueError, match="augment-placement step"):
            self._resolve(c)

    def test_resolve_rejects_global_bn_accum(self):
        c = config_lib.Config()
        c = c.replace(
            task=dataclasses.replace(c.task, batch_size=16,
                                     fused_augment="on",
                                     augment_placement="step"),
            optim=dataclasses.replace(c.optim, accum_steps=2,
                                      accum_bn_mode="global"))
        with pytest.raises(ValueError, match="global"):
            self._resolve(c)

    def test_resolve_rejects_model_parallel(self):
        c = config_lib.Config()
        c = c.replace(
            task=dataclasses.replace(c.task, batch_size=16,
                                     fused_augment="on",
                                     augment_placement="step"),
            device=dataclasses.replace(c.device, num_replicas=4,
                                       model_parallel=2))
        with pytest.raises(ValueError, match="data axis only"):
            self._resolve(c)

    def test_resolve_rejects_bogus_mode(self):
        c = config_lib.Config()
        c = c.replace(task=dataclasses.replace(c.task, batch_size=16,
                                               fused_augment="chip"))
        with pytest.raises(ValueError, match="fused_augment"):
            self._resolve(c)

    def test_make_train_step_rejects_loader_placement(self):
        from byol_tpu.training.steps import StepConfig, make_train_step
        with pytest.raises(ValueError, match="augment_in_step"):
            make_train_step(None, None,
                            StepConfig(total_train_steps=10,
                                       fused_augment=True))

    def test_make_train_step_rejects_global_vmap(self):
        from byol_tpu.training.steps import StepConfig, make_train_step
        with pytest.raises(ValueError, match="global"):
            make_train_step(None, None,
                            StepConfig(total_train_steps=10,
                                       augment_in_step=True, image_size=16,
                                       fused_augment=True, accum_steps=2,
                                       accum_bn_mode="global"))
