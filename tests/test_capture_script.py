"""Sandbox tests for scripts/tpu_capture.sh — the staged, resumable,
per-stage-committing TPU capture.

The capture's stage logic (done-marker resume, immediate git commits,
failure fall-through, tunnel-loss exit) is the round-5 mechanism that
turns short tunnel windows into committed evidence; it must be correct
BEFORE the first real window, so it is exercised here against a
sandboxed git repo with stub bench/train/probe implementations.  The
stubs honor the real contracts: stdout JSON shapes, artifact files,
nonzero exits on failure, and the PROBE_STATE env toggle standing in
for tunnel health.
"""
import json
import os
import shutil
import subprocess

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")

_FAKE_BENCH = '''\
import json, os, sys
args = sys.argv[1:]
def w(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
if "--mvc" in args:
    if os.environ.get("FAIL_MVC"):
        sys.exit(1)
    w("bench_partial.json", {"results": [{"config": "tpu_first",
                                          "fit": True}]})
    print(json.dumps({"metric": "m", "value": 1.0, "unit": "u",
                      "vs_baseline": 1.0}))
elif "--profile" in args:
    if os.environ.get("FAIL_PROFILE"):
        sys.exit(1)
    d = args[args.index("--profile") + 1]
    os.makedirs(d, exist_ok=True)
    print(json.dumps({"metric": "profile", "value": 64}))
elif "--stem-ab" in args:
    print(json.dumps({"metric": "stem_ab_conv", "value": 1.0}))
    print(json.dumps({"metric": "stem_ab_space_to_depth", "value": 1.1}))
elif "--sweep" in args:
    w("bench_sweep.json", [{"batch_per_chip": 512}])
    print(json.dumps({"metric": "sweep", "value": 1, "complete": True}))
elif "--arch" in args:
    if os.environ.get("FAIL_VIT"):
        sys.exit(1)
    name = ("bench_partial_vit_b16_flash.json" if "flash" in args
            else "bench_partial_vit_b16.json")
    w(name, {"results": []})
    print(json.dumps({"metric": "vit", "value": 2.0}))
else:
    w("bench_partial.json", {"results": [{"config": "tpu_first",
                                          "fit": True}]})
    print(json.dumps({"metric": "headline", "value": 3.0}))
'''

_ALL_MARKERS = ("mvc.done", "trace_top_ops.txt", "stem_ab_stdout.json",
                "vit_dense_stdout.json", "vit_flash_stdout.json",
                "sweep_stdout.json", "headline_stdout.json", "synth.done")


@pytest.fixture()
def sandbox(tmp_path):
    sb = tmp_path / "repo"
    (sb / "scripts").mkdir(parents=True)
    shutil.copy(os.path.join(REPO, "scripts", "tpu_capture.sh"),
                sb / "scripts" / "tpu_capture.sh")
    # stub probe: tunnel health toggled by PROBE_STATE
    (sb / "scripts" / "tpu_probe.sh").write_text(
        'tpu_probe() { [ "${PROBE_STATE:-up}" = "up" ]; }\n')
    (sb / "scripts" / "trace_top_ops.py").write_text(
        'print("op table")\n')
    (sb / "bench.py").write_text(_FAKE_BENCH)
    (sb / "train.py").write_text('print("done: synth")\n')
    run = lambda *cmd: subprocess.run(cmd, cwd=sb, check=True,
                                      capture_output=True)
    run("git", "init", "-q")
    run("git", "config", "user.email", "t@t")
    run("git", "config", "user.name", "t")
    run("git", "add", "-A")
    run("git", "commit", "-qm", "init")
    return sb


def _capture(sb, **env):
    return subprocess.run(
        ["bash", "scripts/tpu_capture.sh"], cwd=sb, text=True,
        capture_output=True, env={**os.environ, **env}, timeout=120)


def _ncommits(sb):
    out = subprocess.run(["git", "rev-list", "--count", "HEAD"], cwd=sb,
                         capture_output=True, text=True, check=True)
    return int(out.stdout.strip())


class TestCaptureScript:
    def test_full_pass_commits_every_stage(self, sandbox):
        r = _capture(sandbox)
        assert r.returncode == 0, r.stdout + r.stderr
        art = sandbox / "evidence" / "tpu_r5"
        for marker in _ALL_MARKERS:
            assert (art / marker).exists(), marker
        # one commit per stage (8), on top of the init commit
        assert _ncommits(sandbox) == 9
        # artifacts are COMMITTED, not just written: the work tree is
        # clean for everything the stages touched
        status = subprocess.run(["git", "status", "--porcelain"],
                                cwd=sandbox, capture_output=True,
                                text=True).stdout
        assert status.strip() == "", status
        # the mvc stdout that was committed is the fake headline line
        assert json.loads((art / "mvc_stdout.json").read_text())[
            "value"] == 1.0

    def test_rerun_skips_done_stages(self, sandbox):
        assert _capture(sandbox).returncode == 0
        n = _ncommits(sandbox)
        r = _capture(sandbox)
        assert r.returncode == 0
        assert _ncommits(sandbox) == n        # nothing re-ran

    def test_tunnel_down_exits_2_without_markers(self, sandbox):
        r = _capture(sandbox, PROBE_STATE="down")
        assert r.returncode == 2
        art = sandbox / "evidence" / "tpu_r5"
        for marker in _ALL_MARKERS:
            assert not (art / marker).exists(), marker

    def test_stage_failure_falls_through_then_resumes(self, sandbox):
        # a deterministic failure in the ViT stages must not block the
        # sweep/headline/synth stages below them (round-4 review finding)
        r = _capture(sandbox, FAIL_VIT="1")
        assert r.returncode == 1, r.stdout + r.stderr
        art = sandbox / "evidence" / "tpu_r5"
        assert not (art / "vit_dense_stdout.json").exists()
        assert not (art / "vit_flash_stdout.json").exists()
        for marker in ("mvc.done", "sweep_stdout.json",
                       "headline_stdout.json", "synth.done"):
            assert (art / marker).exists(), marker
        n = _ncommits(sandbox)
        # next window: only the two ViT stages run, then all complete
        r = _capture(sandbox)
        assert r.returncode == 0
        assert (art / "vit_dense_stdout.json").exists()
        assert (art / "vit_flash_stdout.json").exists()
        assert _ncommits(sandbox) == n + 2
