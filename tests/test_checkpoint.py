"""Checkpoint round-trip + ModelSaver early-stop semantics.

The reference could not test its resume path at all (SURVEY.md §4); these
cover the ModelSaver contract (main.py:750-769) plus the Quirk Q6 fix:
``ema_step`` must survive a save/restore cycle so the cosine tau schedule
continues instead of restarting.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from byol_tpu.checkpoint import CheckpointStore, ModelSaver, abstract_like
from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                  TaskConfig, resolve)
from byol_tpu.parallel.mesh import MeshSpec, build_mesh, shard_batch_to_mesh
from byol_tpu.training.build import setup_training


def _tiny_setup(mesh, tmp_path, seed=0):
    cfg = Config(
        task=TaskConfig(task="fake", batch_size=16, epochs=4,
                        image_size_override=16),
        model=ModelConfig(arch="resnet18", head_latent_size=32,
                          projection_size=16),
        device=DeviceConfig(num_replicas=8, half=False, seed=seed),
    )
    rcfg = resolve(cfg, num_train_samples=64, num_test_samples=16,
                   output_size=10, input_shape=(16, 16, 3))
    return rcfg, setup_training(rcfg, mesh, jax.random.PRNGKey(seed))


def _batch(mesh, b=16, size=16, seed=0):
    rng = np.random.RandomState(seed)
    batch = {
        "view1": rng.rand(b, size, size, 3).astype(np.float32),
        "view2": rng.rand(b, size, size, 3).astype(np.float32),
        "label": rng.randint(0, 10, size=(b,)).astype(np.int32),
    }
    return shard_batch_to_mesh(batch, mesh)


@pytest.mark.slow
def test_roundtrip_preserves_full_state(mesh8, tmp_path):
    _, (net, state, train_step, _, _) = _tiny_setup(mesh8, tmp_path)
    batch = _batch(mesh8)
    for _ in range(3):
        state, _ = train_step(state, batch)

    store = CheckpointStore(str(tmp_path / "ckpt"))
    store.save(0, state)
    restored, epoch = store.restore(abstract_like(state))
    assert epoch == 0

    # Every leaf identical — params, target EMA tree, opt state, counters.
    flat_a = jax.tree_util.tree_leaves_with_path(state)
    flat_b = {jax.tree_util.keystr(k): v
              for k, v in jax.tree_util.tree_leaves_with_path(restored)}
    assert len(flat_a) == len(flat_b)
    for k, v in flat_a:
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(flat_b[jax.tree_util.keystr(k)]),
                                      err_msg=jax.tree_util.keystr(k))
    # Quirk Q6 fix: the tau-schedule counter is part of the checkpoint.
    assert int(restored.ema_step) == 3
    store.close()


@pytest.mark.slow
def test_resume_continues_training(mesh8, tmp_path):
    """Restored state must be usable by the jitted step and keep counting."""
    _, (net, state, train_step, _, _) = _tiny_setup(mesh8, tmp_path)
    batch = _batch(mesh8)
    state, _ = train_step(state, batch)
    store = CheckpointStore(str(tmp_path / "ckpt"))
    store.save(0, state)
    restored, _ = store.restore(abstract_like(state))
    restored, metrics = train_step(restored, batch)
    assert np.isfinite(float(metrics["loss_mean"]))
    assert int(restored.step) == 2 and int(restored.ema_step) == 2
    store.close()


def test_model_saver_burn_in_and_best(mesh8, tmp_path):
    _, (net, state, train_step, _, _) = _tiny_setup(mesh8, tmp_path)
    saver = ModelSaver(str(tmp_path / "ms"), early_stop=False,
                       burn_in_interval=2, keep=2)
    # epochs 0,1 are burn-in: saved for preemption-resume, but never "best".
    assert not saver(1.0, 0, state)
    assert not saver(0.9, 1, state)
    assert saver.has_checkpoint()
    assert "best_epoch" not in saver.store.read_meta()
    # epoch 2 improves -> becomes best.
    assert not saver(0.5, 2, state)
    assert saver.has_checkpoint()
    assert saver.store.read_meta()["best_epoch"] == 2
    # worse epoch still saved as "last" but best pointer stays.
    assert not saver(0.7, 3, state)
    meta = saver.store.read_meta()
    assert meta["best_epoch"] == 2 and meta["last_epoch"] == 3
    restored, next_epoch = saver.restore(state, best=True)
    assert next_epoch == 3
    saver.close()


def test_model_saver_early_stop_patience(tmp_path):
    state = {"w": jnp.arange(4.0)}
    saver = ModelSaver(str(tmp_path / "es"), early_stop=True,
                       burn_in_interval=0, max_early_stop_steps=3)
    assert not saver(1.0, 0, state)
    assert not saver(0.5, 1, state)     # improvement resets patience
    assert not saver(0.6, 2, state)     # stall 1
    assert not saver(0.6, 3, state)     # stall 2
    assert saver(0.7, 4, state)         # stall 3 -> stop
    saver.close()


def test_burn_in_does_not_hold_best(tmp_path):
    """A good burn-in metric must not shadow post-burn-in saves: the first
    epoch after burn-in is always saved as best."""
    state = {"w": jnp.ones((2,))}
    saver = ModelSaver(str(tmp_path / "bi"), early_stop=True,
                       burn_in_interval=2, max_early_stop_steps=5)
    assert not saver(0.1, 0, state)   # burn-in, better than anything later
    assert not saver(0.2, 1, state)   # burn-in
    assert not saver(1.0, 2, state)   # first real epoch -> must become best
    meta = saver.store.read_meta()
    assert meta["best_epoch"] == 2 and saver.best_metric == 1.0
    assert saver.stall_count == 0
    saver.close()


def test_model_saver_larger_is_better(tmp_path):
    state = {"w": jnp.ones((2,))}
    saver = ModelSaver(str(tmp_path / "acc"), early_stop=True,
                       larger_is_better=True, max_early_stop_steps=2)
    assert not saver(0.1, 0, state)
    assert not saver(0.3, 1, state)
    assert not saver(0.2, 2, state)
    assert saver(0.2, 3, state)
    assert saver.store.read_meta()["best_epoch"] == 1
    saver.close()


def test_early_stop_marker_is_durable(tmp_path):
    """Once a run early-stops, a relaunched ModelSaver must report it so
    fit() can short-circuit instead of re-burning patience epochs."""
    state = {"w": jnp.ones((2,))}
    saver = ModelSaver(str(tmp_path / "es2"), early_stop=True,
                       max_early_stop_steps=2)
    saver(0.5, 0, state)
    saver(0.9, 1, state)
    assert saver(0.9, 2, state)  # stop fires
    saver.close()
    relaunched = ModelSaver(str(tmp_path / "es2"), early_stop=True,
                            max_early_stop_steps=2)
    assert relaunched.stopped_early
    # and the best checkpoint is still restorable
    restored, next_epoch = relaunched.restore(state, best=True)
    assert next_epoch == 1
    relaunched.close()


def test_plain_resume_uses_last_not_best(tmp_path):
    """A plain relaunch must continue from the LAST checkpoint — restoring
    best would discard post-best training on every restart (round-1 advisor
    finding; reference contract main.py:753-754 resumes, best-restore is the
    early-stop terminal path main.py:767-769)."""
    saver = ModelSaver(str(tmp_path / "pl"), early_stop=False, keep=3)
    saver(0.5, 0, {"w": jnp.zeros((2,))})       # best
    saver(0.9, 1, {"w": jnp.ones((2,))})        # worse, last
    saver.close()
    relaunched = ModelSaver(str(tmp_path / "pl"), early_stop=False)
    restored, next_epoch = relaunched.restore({"w": jnp.zeros((2,))},
                                              best=False)
    assert next_epoch == 2                       # continues after epoch 1
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones((2,)))
    # stall count must NOT be reset by a plain (last) resume
    assert relaunched.stall_count == 1
    restored_best, next_best = relaunched.restore({"w": jnp.zeros((2,))},
                                                  best=True)
    assert next_best == 1
    np.testing.assert_array_equal(np.asarray(restored_best["w"]),
                                  np.zeros((2,)))
    relaunched.close()


def test_restore_falls_back_when_meta_points_at_missing_ckpt(tmp_path):
    """Crash between async-save schedule and commit: meta.json names a
    ckpt dir that never hit disk.  restore() must fall back to the newest
    on-disk checkpoint instead of raising (round-1 advisor finding)."""
    import shutil
    store = CheckpointStore(str(tmp_path / "crash"))
    store.save(0, {"w": jnp.zeros((2,))})
    store.save(1, {"w": jnp.ones((2,))}, metric=0.1, is_best=True)
    store._ckptr.wait_until_finished()
    # Simulate the crash: ckpt-1 committed in meta but gone from disk.
    shutil.rmtree(str(tmp_path / "crash" / "ckpt-1"))
    assert store.read_meta()["last_epoch"] == 1
    restored, epoch = store.restore(abstract_like({"w": jnp.zeros((2,))}))
    assert epoch == 0
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.zeros((2,)))
    # best also points at the vanished ckpt -> same fallback
    restored, epoch = store.restore(abstract_like({"w": jnp.zeros((2,))}),
                                    best=True)
    assert epoch == 0
    store.close()


def test_best_fallback_picks_best_surviving_metric(tmp_path):
    """When the best ckpt dir is lost pre-commit, restore(best=True) must
    pick the best-metric SURVIVING checkpoint, not simply the newest (which
    after an early-stop stall is typically the worst)."""
    import shutil
    store = CheckpointStore(str(tmp_path / "bf"))
    vals = {0: 0.5, 1: 0.2, 2: 0.9, 3: 0.1}
    for e, m in vals.items():
        store.save(e, {"w": jnp.full((2,), float(e))}, metric=m,
                    is_best=(m == min(list(vals.values())[:e + 1])),
                    keep=10)
    store._ckptr.wait_until_finished()
    shutil.rmtree(str(tmp_path / "bf" / "ckpt-3"))   # lose the best
    restored, epoch = store.restore(abstract_like({"w": jnp.zeros((2,))}),
                                    best=True)
    assert epoch == 1                                # 0.2 beats 0.5 and 0.9
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((2,), 1.0))
    store.close()


def test_explicit_epoch_restore_never_substitutes(tmp_path):
    """An explicitly requested epoch must raise if missing — silent
    substitution is only for meta-derived epochs."""
    store = CheckpointStore(str(tmp_path / "ex"))
    store.save(0, {"w": jnp.zeros((2,))})
    store._ckptr.wait_until_finished()
    with pytest.raises(Exception):
        store.restore(abstract_like({"w": jnp.zeros((2,))}), epoch=7)
    store.close()


def test_burn_in_preemption_resume(tmp_path):
    """Preemption during burn-in must be resumable: burn-in epochs are saved
    (as last) even though best/patience tracking is suppressed."""
    saver = ModelSaver(str(tmp_path / "bires"), early_stop=True,
                       burn_in_interval=10, max_early_stop_steps=3)
    saver(1.0, 0, {"w": jnp.zeros((2,))})
    saver(0.9, 1, {"w": jnp.ones((2,))})
    saver.close()
    relaunched = ModelSaver(str(tmp_path / "bires"), early_stop=True,
                            burn_in_interval=10, max_early_stop_steps=3)
    assert relaunched.has_checkpoint()
    restored, next_epoch = relaunched.restore({"w": jnp.zeros((2,))},
                                              best=False)
    assert next_epoch == 2
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones((2,)))
    assert relaunched.best_metric is None and relaunched.stall_count == 0
    relaunched.close()


def _zero1_setup(mesh, *, data=8, accum=1):
    """ZeRO-1 training on ``mesh``; reuses test_zero1's config (identical
    jit cache keys -> the tier-1 run compiles this program once)."""
    from byol_tpu.parallel.compile_plan import build_plan
    from tests.test_zero1 import _rcfg
    import dataclasses as _dc
    rcfg = _rcfg(zero1="on", accum=accum)
    if data != 8:
        rcfg = resolve(
            rcfg.cfg.replace(device=_dc.replace(rcfg.cfg.device,
                                                num_replicas=data)),
            num_train_samples=64, num_test_samples=16, output_size=10,
            input_shape=(16, 16, 3), representation_size=512)
    plan = build_plan(mesh, zero1=True)
    return plan, setup_training(rcfg, mesh, jax.random.PRNGKey(0),
                                plan=plan)


def _canon_equal(a, b):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = {jax.tree_util.keystr(k): v
          for k, v in jax.tree_util.tree_leaves_with_path(b)}
    assert len(fa) == len(fb)
    for k, v in fa:
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(fb[jax.tree_util.keystr(k)]),
            err_msg=jax.tree_util.keystr(k))


def test_zero1_roundtrip_on_multidevice_mesh(mesh8, tmp_path):
    """ISSUE 7 checkpoint satellite (1/2): ZeRO-1 flat-sharded state
    save/restores on the 8-virtual-device CPU mesh.  Checkpoints store the
    CANONICAL (unflattened, replicated) layout via the compile plan's
    codec — the round trip through to_canonical -> disk ->
    canonical_template -> from_canonical must be exact and the restored
    state must be steppable."""
    from tests.test_zero1 import _batch as z1_batch
    plan, (net, state, train_step, _, _) = _zero1_setup(mesh8)
    batch = shard_batch_to_mesh(z1_batch(seed=0), mesh8)
    state, _ = train_step(state, batch)

    store = CheckpointStore(str(tmp_path / "z1"))
    canon = plan.to_canonical(state)
    # the canonical view really is mesh-portable: no flat leaves, no
    # data-axis shards left anywhere
    for leaf in jax.tree_util.tree_leaves(
            (canon.opt_state, canon.target_params)):
        assert "data" not in str(leaf.sharding.spec)
    store.save(0, canon)
    restored, epoch = store.restore(plan.canonical_template(state))
    assert epoch == 0
    _canon_equal(canon, restored)

    # back to plan layout: flat-sharded again, and usable by the step
    live = plan.from_canonical(restored)
    from byol_tpu.parallel.mesh import DATA_AXIS
    assert any(DATA_AXIS in str(leaf.sharding.spec) for leaf in
               jax.tree_util.tree_leaves(live.opt_state)
               if getattr(leaf, "ndim", 0) == 1)
    _canon_equal(canon, plan.to_canonical(live))
    live, metrics = train_step(live, batch)
    assert np.isfinite(float(metrics["loss_mean"]))
    assert int(live.step) == 2 and int(live.ema_step) == 2
    store.close()


def test_zero1_reshard_on_restore_different_device_count(mesh8, tmp_path):
    """ISSUE 7 checkpoint satellite (2/2): a checkpoint written under an
    8-way ZeRO-1 plan restores cleanly into a 4-way plan (different shard
    count, different zero padding) — reshard-on-restore, exact because
    the canonical layout never depends on the mesh size."""
    from byol_tpu.parallel.mesh import MeshSpec, build_mesh
    from tests.test_zero1 import _batch as z1_batch
    plan8, (_, state8, step8, _, _) = _zero1_setup(mesh8)
    batch8 = shard_batch_to_mesh(z1_batch(seed=0), mesh8)
    state8, _ = step8(state8, batch8)
    store = CheckpointStore(str(tmp_path / "z18"))
    canon8 = plan8.to_canonical(state8)
    store.save(0, canon8)
    store._ckptr.wait_until_finished()

    mesh4 = build_mesh(MeshSpec(data=4), jax.devices()[:4])
    plan4, (_, state4, step4, _, _) = _zero1_setup(mesh4, data=4)
    restored, _ = store.restore(plan4.canonical_template(state4))
    live4 = plan4.from_canonical(restored)
    # the 4-way flat layout differs from the 8-way one (padding to 4, not
    # 8) but the canonical content must be exactly what the 8-way run saved
    _canon_equal(canon8, plan4.to_canonical(live4))
    # and training continues on the smaller mesh
    batch4 = shard_batch_to_mesh(z1_batch(seed=1), mesh4)
    live4, metrics = step4(live4, batch4)
    assert np.isfinite(float(metrics["loss_mean"]))
    assert int(live4.step) == 2
    store.close()


def _resident_setup(mesh, *, resident="on", zero1="on", data=8):
    """--flat-resident training on ``mesh``; reuses test_flat_state's
    config so the tier-1 run compiles each program once."""
    import dataclasses as _dc
    from tests.test_flat_state import _plan_for, _rcfg
    rcfg = _rcfg(resident=resident, zero1=zero1)
    if data != 8:
        rcfg = resolve(
            rcfg.cfg.replace(device=_dc.replace(rcfg.cfg.device,
                                                num_replicas=data)),
            num_train_samples=64, num_test_samples=16, output_size=10,
            input_shape=(16, 16, 3), representation_size=512)
    plan = _plan_for(mesh, rcfg)
    return plan, setup_training(rcfg, mesh, jax.random.PRNGKey(0),
                                plan=plan)


def test_resident_roundtrip_via_canonical_codec(mesh8, tmp_path):
    """ISSUE 18 checkpoint satellite (1/2): resident flat buffers never
    reach disk — ``to_canonical`` unpacks them to the shaped replicated
    trees (``flat_shadow`` drops to None, contributing no leaves), and
    ``from_canonical`` re-packs on restore.  The round trip is exact and
    the restored state is steppable with the resident step."""
    from tests.test_flat_state import _batch as fs_batch
    plan, (net, state, train_step, _, _) = _resident_setup(mesh8)
    batch = shard_batch_to_mesh(fs_batch(seed=0), mesh8)
    state, _ = train_step(state, batch)

    canon = plan.to_canonical(state)
    assert canon.flat_shadow is None
    # canonical view is layout-free: shaped leaves, nothing data-sharded
    for leaf in jax.tree_util.tree_leaves(
            (canon.opt_state, canon.target_params)):
        assert "data" not in str(leaf.sharding.spec)
    store = CheckpointStore(str(tmp_path / "res"))
    store.save(0, canon)
    restored, epoch = store.restore(plan.canonical_template(state))
    assert epoch == 0
    _canon_equal(canon, restored)

    live = plan.from_canonical(restored)
    assert live.flat_shadow is not None and live.flat_shadow.ndim == 1
    _canon_equal(canon, plan.to_canonical(live))
    live, metrics = train_step(live, batch)
    assert np.isfinite(float(metrics["loss_mean"]))
    assert int(live.step) == 2 and int(live.ema_step) == 2
    store.close()


def test_resident_ckpt_portable_across_flag_and_mesh(mesh8, tmp_path):
    """ISSUE 18 checkpoint satellite (2/2): because checkpoints store the
    canonical layout, a ckpt written under ``--flat-resident on`` (8-way)
    restores into a transient ``off`` plan AND into a 4-way resident
    plan — flag and shard count are both restore-time choices."""
    from tests.test_flat_state import _batch as fs_batch
    plan_on, (_, state_on, step_on, _, _) = _resident_setup(mesh8)
    batch8 = shard_batch_to_mesh(fs_batch(seed=0), mesh8)
    state_on, _ = step_on(state_on, batch8)
    store = CheckpointStore(str(tmp_path / "resport"))
    canon_on = plan_on.to_canonical(state_on)
    store.save(0, canon_on)
    store._ckptr.wait_until_finished()

    # on -> off: the transient fused plan consumes the same checkpoint
    plan_off, (_, state_off, step_off, _, _) = _resident_setup(
        mesh8, resident="off")
    restored, _ = store.restore(plan_off.canonical_template(state_off))
    live_off = plan_off.from_canonical(restored)
    assert live_off.flat_shadow is None
    _canon_equal(canon_on, plan_off.to_canonical(live_off))
    live_off, metrics = step_off(live_off, batch8)
    assert np.isfinite(float(metrics["loss_mean"]))

    # 8-way -> 4-way resident: different layout padding, same canonical
    mesh4 = build_mesh(MeshSpec(data=4), jax.devices()[:4])
    plan4, (_, state4, step4, _, _) = _resident_setup(mesh4, data=4)
    restored4, _ = store.restore(plan4.canonical_template(state4))
    live4 = plan4.from_canonical(restored4)
    _canon_equal(canon_on, plan4.to_canonical(live4))
    batch4 = shard_batch_to_mesh(fs_batch(seed=1), mesh4)
    live4, metrics4 = step4(live4, batch4)
    assert np.isfinite(float(metrics4["loss_mean"]))
    assert int(live4.step) == 2
    store.close()


def test_saver_state_survives_restart(tmp_path):
    """Patience/best metric persist across ModelSaver re-construction
    (the reference forgets both on restart)."""
    state = {"w": jnp.ones((2,))}
    saver = ModelSaver(str(tmp_path / "rs"), early_stop=True,
                       max_early_stop_steps=3)
    saver(0.5, 0, state)
    saver(0.9, 1, state)   # stall 1
    saver.close()
    saver2 = ModelSaver(str(tmp_path / "rs"), early_stop=True,
                        max_early_stop_steps=3)
    assert saver2.best_metric == 0.5
    assert saver2.stall_count == 1
    assert not saver2(0.9, 2, state)  # stall 2
    assert saver2(0.9, 3, state)      # stall 3 -> stop
    saver2.close()


def test_meta_nonfinite_metric_roundtrips_strict_json(tmp_path):
    """GL110 (ISSUE 13 satellite): a NaN eval metric must neither crash
    the meta.json write (allow_nan=False would raise on a bare float)
    nor land as a bare NaN token — it writes as the events.py string
    convention and reads back as the float it was."""
    import json
    import math

    store = CheckpointStore(str(tmp_path / "nan"))
    store.write_meta({"last_epoch": 3,
                      "history": [{"epoch": 3, "metric": float("nan")}],
                      "best_metric": float("-inf"),
                      # sanitize is not injective: a user STRING that
                      # merely spells the sentinel must survive the
                      # round trip verbatim (restore is scoped to the
                      # numeric keys this module writes)
                      "note": "NaN"})
    raw = open(str(tmp_path / "nan" / "meta.json")).read()
    # strict parse: parse_constant fires only on bare non-finite tokens
    parsed = json.loads(raw, parse_constant=lambda tok: (_ for _ in ())
                        .throw(AssertionError(f"bare {tok} token")))
    assert parsed["history"][0]["metric"] == "NaN"
    meta = store.read_meta()
    assert math.isnan(meta["history"][0]["metric"])
    assert meta["best_metric"] == float("-inf")
    assert meta["last_epoch"] == 3
    assert meta["note"] == "NaN"          # still a string
    store.close()
