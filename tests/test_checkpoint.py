"""Checkpoint round-trip + ModelSaver early-stop semantics.

The reference could not test its resume path at all (SURVEY.md §4); these
cover the ModelSaver contract (main.py:750-769) plus the Quirk Q6 fix:
``ema_step`` must survive a save/restore cycle so the cosine tau schedule
continues instead of restarting.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from byol_tpu.checkpoint import CheckpointStore, ModelSaver, abstract_like
from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                  TaskConfig, resolve)
from byol_tpu.parallel.mesh import MeshSpec, build_mesh, shard_batch_to_mesh
from byol_tpu.training.build import setup_training


def _tiny_setup(mesh, tmp_path, seed=0):
    cfg = Config(
        task=TaskConfig(task="fake", batch_size=16, epochs=4,
                        image_size_override=16),
        model=ModelConfig(arch="resnet18", head_latent_size=32,
                          projection_size=16),
        device=DeviceConfig(num_replicas=8, half=False, seed=seed),
    )
    rcfg = resolve(cfg, num_train_samples=64, num_test_samples=16,
                   output_size=10, input_shape=(16, 16, 3))
    return rcfg, setup_training(rcfg, mesh, jax.random.PRNGKey(seed))


def _batch(mesh, b=16, size=16, seed=0):
    rng = np.random.RandomState(seed)
    batch = {
        "view1": rng.rand(b, size, size, 3).astype(np.float32),
        "view2": rng.rand(b, size, size, 3).astype(np.float32),
        "label": rng.randint(0, 10, size=(b,)).astype(np.int32),
    }
    return shard_batch_to_mesh(batch, mesh)


def test_roundtrip_preserves_full_state(mesh8, tmp_path):
    _, (net, state, train_step, _, _) = _tiny_setup(mesh8, tmp_path)
    batch = _batch(mesh8)
    for _ in range(3):
        state, _ = train_step(state, batch)

    store = CheckpointStore(str(tmp_path / "ckpt"))
    store.save(0, state)
    restored, epoch = store.restore(abstract_like(state))
    assert epoch == 0

    # Every leaf identical — params, target EMA tree, opt state, counters.
    flat_a = jax.tree_util.tree_leaves_with_path(state)
    flat_b = {jax.tree_util.keystr(k): v
              for k, v in jax.tree_util.tree_leaves_with_path(restored)}
    assert len(flat_a) == len(flat_b)
    for k, v in flat_a:
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(flat_b[jax.tree_util.keystr(k)]),
                                      err_msg=jax.tree_util.keystr(k))
    # Quirk Q6 fix: the tau-schedule counter is part of the checkpoint.
    assert int(restored.ema_step) == 3
    store.close()


def test_resume_continues_training(mesh8, tmp_path):
    """Restored state must be usable by the jitted step and keep counting."""
    _, (net, state, train_step, _, _) = _tiny_setup(mesh8, tmp_path)
    batch = _batch(mesh8)
    state, _ = train_step(state, batch)
    store = CheckpointStore(str(tmp_path / "ckpt"))
    store.save(0, state)
    restored, _ = store.restore(abstract_like(state))
    restored, metrics = train_step(restored, batch)
    assert np.isfinite(float(metrics["loss_mean"]))
    assert int(restored.step) == 2 and int(restored.ema_step) == 2
    store.close()


def test_model_saver_burn_in_and_best(mesh8, tmp_path):
    _, (net, state, train_step, _, _) = _tiny_setup(mesh8, tmp_path)
    saver = ModelSaver(str(tmp_path / "ms"), early_stop=False,
                       burn_in_interval=2, keep=2)
    # epochs 0,1 are burn-in: metric tracked, nothing written.
    assert not saver(1.0, 0, state)
    assert not saver(0.9, 1, state)
    assert not saver.has_checkpoint()
    # epoch 2 improves -> becomes best.
    assert not saver(0.5, 2, state)
    assert saver.has_checkpoint()
    assert saver.store.read_meta()["best_epoch"] == 2
    # worse epoch still saved as "last" but best pointer stays.
    assert not saver(0.7, 3, state)
    meta = saver.store.read_meta()
    assert meta["best_epoch"] == 2 and meta["last_epoch"] == 3
    restored, next_epoch = saver.restore(state, best=True)
    assert next_epoch == 3
    saver.close()


def test_model_saver_early_stop_patience(tmp_path):
    state = {"w": jnp.arange(4.0)}
    saver = ModelSaver(str(tmp_path / "es"), early_stop=True,
                       burn_in_interval=0, max_early_stop_steps=3)
    assert not saver(1.0, 0, state)
    assert not saver(0.5, 1, state)     # improvement resets patience
    assert not saver(0.6, 2, state)     # stall 1
    assert not saver(0.6, 3, state)     # stall 2
    assert saver(0.7, 4, state)         # stall 3 -> stop
    saver.close()


def test_burn_in_does_not_hold_best(tmp_path):
    """A good burn-in metric must not shadow post-burn-in saves: the first
    epoch after burn-in is always saved as best."""
    state = {"w": jnp.ones((2,))}
    saver = ModelSaver(str(tmp_path / "bi"), early_stop=True,
                       burn_in_interval=2, max_early_stop_steps=5)
    assert not saver(0.1, 0, state)   # burn-in, better than anything later
    assert not saver(0.2, 1, state)   # burn-in
    assert not saver(1.0, 2, state)   # first real epoch -> must become best
    meta = saver.store.read_meta()
    assert meta["best_epoch"] == 2 and saver.best_metric == 1.0
    assert saver.stall_count == 0
    saver.close()


def test_model_saver_larger_is_better(tmp_path):
    state = {"w": jnp.ones((2,))}
    saver = ModelSaver(str(tmp_path / "acc"), early_stop=True,
                       larger_is_better=True, max_early_stop_steps=2)
    assert not saver(0.1, 0, state)
    assert not saver(0.3, 1, state)
    assert not saver(0.2, 2, state)
    assert saver(0.2, 3, state)
    assert saver.store.read_meta()["best_epoch"] == 1
    saver.close()


def test_early_stop_marker_is_durable(tmp_path):
    """Once a run early-stops, a relaunched ModelSaver must report it so
    fit() can short-circuit instead of re-burning patience epochs."""
    state = {"w": jnp.ones((2,))}
    saver = ModelSaver(str(tmp_path / "es2"), early_stop=True,
                       max_early_stop_steps=2)
    saver(0.5, 0, state)
    saver(0.9, 1, state)
    assert saver(0.9, 2, state)  # stop fires
    saver.close()
    relaunched = ModelSaver(str(tmp_path / "es2"), early_stop=True,
                            max_early_stop_steps=2)
    assert relaunched.stopped_early
    # and the best checkpoint is still restorable
    restored, next_epoch = relaunched.restore(state, best=True)
    assert next_epoch == 1
    relaunched.close()


def test_saver_state_survives_restart(tmp_path):
    """Patience/best metric persist across ModelSaver re-construction
    (the reference forgets both on restart)."""
    state = {"w": jnp.ones((2,))}
    saver = ModelSaver(str(tmp_path / "rs"), early_stop=True,
                       max_early_stop_steps=3)
    saver(0.5, 0, state)
    saver(0.9, 1, state)   # stall 1
    saver.close()
    saver2 = ModelSaver(str(tmp_path / "rs"), early_stop=True,
                        max_early_stop_steps=3)
    assert saver2.best_metric == 0.5
    assert saver2.stall_count == 1
    assert not saver2(0.9, 2, state)  # stall 2
    assert saver2(0.9, 3, state)      # stall 3 -> stop
    saver2.close()
