"""End-to-end trainer integration: fit() on fake data over the 8-device CPU
mesh — the smoke test the reference could only approximate with
``--debug-step`` on live hardware (SURVEY.md §4)."""
import dataclasses
import json
import os

import numpy as np
import pytest

from byol_tpu.cli import build_parser, config_from_args
from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                  OptimConfig, TaskConfig)
from byol_tpu.observability import Grapher
from byol_tpu.training.trainer import fit


def _tiny_cfg(tmp_path, **over):
    base = dict(
        task=TaskConfig(task="fake", batch_size=16, epochs=2,
                        image_size_override=16,
                        log_dir=str(tmp_path / "runs")),
        model=ModelConfig(arch="resnet18", head_latent_size=32,
                          projection_size=16,
                          model_dir=str(tmp_path / "models")),
        optim=OptimConfig(lr=0.05, warmup=1, optimizer="lars_momentum"),
        device=DeviceConfig(num_replicas=8, half=False, seed=7),
    )
    base.update(over)
    return Config(**base)


def _tiny_loader(cfg):
    # 32 train samples @ bs16 = 2 steps/epoch: the CI box has ONE core for
    # all 8 virtual devices, so every step costs seconds — keep counts tiny.
    from byol_tpu.data.loader import get_loader
    return get_loader(cfg, num_fake_samples=32)


@pytest.mark.slow
def test_fit_end_to_end(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    grapher = Grapher("jsonl", logdir=str(tmp_path / "runs"), run_name="t",
                      enabled=True)
    result = fit(cfg, loader=_tiny_loader(cfg), grapher=grapher,
                 verbose=False)
    assert result.epoch == 1 and not result.stopped_early
    assert np.isfinite(result.train_metrics["loss_mean"])
    assert np.isfinite(result.test_metrics["loss_mean"])
    assert set(result.test_metrics) >= {"loss_mean", "byol_loss_mean",
                                        "linear_loss_mean", "top1_mean",
                                        "top5_mean"}
    # the step counter must equal epochs * steps_per_epoch.
    assert int(result.state.step) == 2 * (32 // 16)
    # scalars reached the grapher, with train_/test_ prefixes
    lines = [json.loads(l) for l in
             open(tmp_path / "runs" / "t" / "metrics.jsonl")]
    keys = set()
    for l in lines:
        keys.update(l)
    assert "train_loss_mean" in keys and "test_loss_mean" in keys
    assert "lr_scalar" in keys
    # checkpoint written under model_dir/<run-name>
    runs = os.listdir(tmp_path / "models")
    assert len(runs) == 1
    assert any(d.startswith("ckpt-") for d in
               os.listdir(tmp_path / "models" / runs[0]))


@pytest.mark.slow
def test_fit_resume_continues_epochs(tmp_path):
    # debug_step keeps each epoch to one minibatch so the test exercises the
    # resume path, not the hot loop.
    cfg = _tiny_cfg(tmp_path,
                    device=DeviceConfig(num_replicas=8, half=False, seed=7,
                                        debug_step=True))
    r1 = fit(cfg, loader=_tiny_loader(cfg), verbose=False)
    # Same config -> same run dir -> a second fit() restores the best
    # checkpoint and continues with the restored step counters.
    r2 = fit(cfg, loader=_tiny_loader(cfg), verbose=False)
    assert int(r2.state.step) >= int(r1.state.step)


@pytest.mark.slow
def test_fit_with_valid_split(tmp_path):
    """--valid-fraction: the held-out split is evaluated and logged each
    epoch (num_valid_samples contract, reference main.py:421-423)."""
    cfg = _tiny_cfg(tmp_path,
                    task=TaskConfig(task="fake", batch_size=16, epochs=1,
                                    image_size_override=16,
                                    valid_fraction=0.25,
                                    log_dir=str(tmp_path / "runs")),
                    device=DeviceConfig(num_replicas=8, half=False, seed=7,
                                        debug_step=True))
    grapher = Grapher("jsonl", logdir=str(tmp_path / "runs"), run_name="v",
                      enabled=True)
    loader = _tiny_loader(cfg)
    assert loader.num_valid_samples == 8 and loader.num_train_samples == 24
    result = fit(cfg, loader=loader, grapher=grapher, verbose=False)
    assert np.isfinite(result.test_metrics["loss_mean"])
    keys = set()
    for l in open(tmp_path / "runs" / "v" / "metrics.jsonl"):
        keys.update(json.loads(l))
    assert "valid_loss_mean" in keys


@pytest.mark.slow
def test_fit_debug_step(tmp_path):
    cfg = _tiny_cfg(tmp_path,
                    device=DeviceConfig(num_replicas=8, half=False, seed=7,
                                        debug_step=True))
    result = fit(cfg, loader=_tiny_loader(cfg), verbose=False)
    assert int(result.state.step) == 2  # one minibatch per epoch x 2 epochs


@pytest.mark.slow
def test_fault_injection_then_resume(tmp_path):
    """--fault-at-step kills mid-run; a relaunch resumes from the last
    checkpoint and completes (the preemption drill of SURVEY.md §5.3 that
    the reference could only do by killing real jobs)."""
    cfg = _tiny_cfg(
        tmp_path,
        task=TaskConfig(task="fake", batch_size=16, epochs=3,
                        image_size_override=16,
                        log_dir=str(tmp_path / "runs"), uid="fault"),
        device=DeviceConfig(num_replicas=8, half=False, seed=7,
                            debug_step=True, fault_at_step=2))
    with pytest.raises(SystemExit, match="fault injected at step 2"):
        fit(cfg, loader=_tiny_loader(cfg), verbose=False)
    # relaunch without the fault: resumes and completes the 3 epochs
    cfg2 = cfg.replace(device=dataclasses.replace(cfg.device,
                                                  fault_at_step=0))
    result = fit(cfg2, loader=_tiny_loader(cfg2), verbose=False)
    assert result.epoch == 2
    assert np.isfinite(result.test_metrics["loss_mean"])


@pytest.mark.slow
def test_sigterm_preemption_saves_and_resumes(tmp_path):
    """A SIGTERM (pod preemption notice) mid-epoch must checkpoint the live
    state, exit 143, and leave a resumable run (SURVEY §5.3; the reference
    loses all progress since its last best-save)."""
    import signal as signal_mod
    from byol_tpu.data.loader import LoaderBundle
    cfg = _tiny_cfg(tmp_path, task=TaskConfig(
        task="fake", batch_size=16, epochs=2, image_size_override=16,
        log_dir=str(tmp_path / "runs"), uid="sig"))
    base = _tiny_loader(cfg)

    def sig_train_iter(epoch):
        it = base.make_train_iter(epoch)
        yield next(it)
        signal_mod.raise_signal(signal_mod.SIGTERM)   # preemption notice
        yield next(it)

    loader = LoaderBundle(make_train_iter=sig_train_iter,
                          make_test_iter=base.make_test_iter,
                          input_shape=base.input_shape,
                          num_train_samples=base.num_train_samples,
                          num_test_samples=base.num_test_samples,
                          output_size=base.output_size)
    with pytest.raises(SystemExit) as exc_info:
        fit(cfg, loader=loader, verbose=False)
    assert exc_info.value.code == 143
    # a checkpoint was written and a clean relaunch resumes + completes.
    # Resume is EXACT: SIGTERM hit after step 1 of epoch 0 (2 steps/epoch),
    # so the relaunch re-enters epoch 0 skipping 1 batch and finishes with
    # precisely epochs * steps_per_epoch optimizer steps.
    result = fit(cfg, loader=_tiny_loader(cfg), verbose=False)
    assert result.epoch == 1
    assert int(result.state.step) == 2 * 2
    assert np.isfinite(result.test_metrics["loss_mean"])


@pytest.mark.slow
def test_train_epoch_is_exactly_steps_per_epoch(tmp_path):
    """The trainer consumes EXACTLY steps_per_train_epoch batches per epoch
    regardless of what the host's iterator yields: a shard one batch short
    (interleaved image_folder host shards) WRAPS (DistributedSampler pad
    analog — on pods stopping early would deadlock the SPMD collectives),
    and a shard with extra batches stops at the count (the EMA tau schedule
    is keyed to steps_per_train_epoch, reference main.py:424-425)."""
    from byol_tpu.data.loader import LoaderBundle

    def make_iter(n_batches, train):
        def it(epoch):
            rng = np.random.RandomState(5 + epoch)
            for _ in range(n_batches):
                v = rng.rand(16, 16, 16, 3).astype(np.float32)
                yield {"view1": v, "view2": v,
                       "label": rng.randint(0, 10, size=(16,)).astype(
                           np.int32)}
        return it

    for yielded in (1, 3):      # one short of steps=2, one over
        loader = LoaderBundle(make_train_iter=make_iter(yielded, True),
                              make_test_iter=make_iter(1, False),
                              input_shape=(16, 16, 3),
                              num_train_samples=32,   # -> steps_per_epoch 2
                              num_test_samples=16, output_size=10)
        cfg = _tiny_cfg(tmp_path, task=TaskConfig(
            task="fake", batch_size=16, epochs=1, image_size_override=16,
            log_dir=str(tmp_path / "runs"), uid=f"steps{yielded}"))
        result = fit(cfg, loader=loader, verbose=False)
        assert int(result.state.step) == 2, yielded


@pytest.mark.slow
def test_fit_eval_remainder_batches(tmp_path):
    """A test set whose size divides by neither the batch size nor the
    8-device data axis (21 = 16 + 5) must work: eval pads the short batch to
    the fixed shape, masks the pad rows out of the metrics, and weights the
    epoch mean by valid rows (round-2 verdict Weak #3)."""
    from byol_tpu.data.loader import LoaderBundle

    def make_iter(n, train):
        def it(epoch):
            rng = np.random.RandomState(41 + epoch + train)
            end = n - n % 16 if train else n
            for lo in range(0, end, 16):
                m = min(16, n - lo)
                v = rng.rand(m, 16, 16, 3).astype(np.float32)
                yield {"view1": v, "view2": v,
                       "label": rng.randint(0, 10, size=(m,)).astype(np.int32)}
        return it

    loader = LoaderBundle(make_train_iter=make_iter(32, True),
                          make_test_iter=make_iter(21, False),
                          input_shape=(16, 16, 3), num_train_samples=32,
                          num_test_samples=21, output_size=10)
    cfg = _tiny_cfg(tmp_path, task=TaskConfig(
        task="fake", batch_size=16, epochs=1, image_size_override=16,
        log_dir=str(tmp_path / "runs"), uid="remainder"))
    result = fit(cfg, loader=loader, verbose=False)
    assert np.isfinite(result.test_metrics["loss_mean"])
    assert 0.0 <= result.test_metrics["top1_mean"] <= 100.0
    assert "_weight" not in result.test_metrics


def test_fit_rejects_out_of_range_inputs(tmp_path):
    from byol_tpu.data.loader import LoaderBundle

    def bad_iter(epoch):
        yield {"view1": np.full((16, 16, 16, 3), 1.5, np.float32),
               "view2": np.zeros((16, 16, 16, 3), np.float32),
               "label": np.zeros((16,), np.int32)}

    loader = LoaderBundle(make_train_iter=bad_iter, make_test_iter=bad_iter,
                          input_shape=(16, 16, 3), num_train_samples=16,
                          num_test_samples=16, output_size=10)
    cfg = _tiny_cfg(tmp_path)
    with pytest.raises(ValueError, match=r"\[0,1\]"):
        fit(cfg, loader=loader, verbose=False)


def test_cli_parser_reference_surface(tmp_path):
    """Every reference flag (SURVEY App B) parses; defaults match."""
    args = build_parser().parse_args([])
    assert args.batch_size == 4096 and args.epochs == 3000
    assert args.lr == 0.2 and args.optimizer == "lars_momentum"
    assert args.arch == "resnet50" and args.base_decay == 0.996
    assert args.warmup == 10 and args.weight_decay == 1e-6

    # --num-processes (host process count) is distinct from --num-replicas
    # (device-axis size): hosts driving several chips have different values.
    args = build_parser().parse_args([])
    assert args.num_processes == 0   # auto-detect from pod metadata
    # full reference device/visdom surface parses (visdom warns at runtime)
    args = build_parser().parse_args(
        ["--no-cuda", "--visdom-url", "http://x", "--visdom-port", "8097"])
    assert args.no_cuda and args.visdom_url == "http://x"

    args = build_parser().parse_args([
        "--task", "fake", "--batch-size", "16", "--epochs", "1",
        "--arch", "resnet18", "--debug-step", "--no-half",
        "--loss-norm-mode", "reference", "--ema-init-mode", "reference",
        "--schedule-granularity", "epoch"])
    cfg = config_from_args(args)
    assert cfg.task.batch_size == 16 and cfg.device.debug_step
    assert not cfg.device.half
    assert cfg.parity.loss_norm_mode == "reference"
    assert cfg.parity.ema_init_mode == "reference"
    assert cfg.parity.schedule_granularity == "epoch"


def test_cli_zero1_flag_and_fsdp_alias():
    """ISSUE 7: --zero1 {off,on} is the weight-update-sharding switch;
    the pre-ZeRO-1 --fsdp spelling survives as a deprecated alias."""
    assert config_from_args(build_parser().parse_args([])).device.zero1 \
        == "off"
    args = build_parser().parse_args(["--zero1", "on"])
    assert config_from_args(args).device.zero1 == "on"
    args = build_parser().parse_args(["--fsdp"])
    assert config_from_args(args).device.zero1 == "on"
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--zero1", "sharded"])
    # the alias must not silently override an EXPLICIT --zero1 off
    args = build_parser().parse_args(["--fsdp", "--zero1", "off"])
    with pytest.raises(SystemExit, match="conflicts"):
        config_from_args(args)


def test_preflight_cpu_pinned_skips_probe(monkeypatch):
    """Under an explicit cpu pin (the test conftest) there is nothing to
    probe — no subprocess may be spawned."""
    import subprocess
    from byol_tpu.core.preflight import preflight_backend

    def boom(*a, **k):  # pragma: no cover - must not be reached
        raise AssertionError("probe subprocess must not run under cpu pin")
    monkeypatch.setattr(subprocess, "run", boom)
    assert preflight_backend() is True


def test_cli_fails_fast_when_backend_unreachable(monkeypatch, capsys):
    """The train CLI must exit 2 (not hang in backend init) against a dead
    accelerator — the bench has carried this guard since round 3; a capture
    -pipeline train run hung forever without it."""
    from byol_tpu import cli
    from byol_tpu.core import preflight
    monkeypatch.setattr(preflight, "preflight_backend", lambda *a, **k: False)
    rc = cli.main(["--task", "fake", "--batch-size", "16", "--epochs", "1"])
    assert rc == 2
    assert "unreachable" in capsys.readouterr().err


def test_cli_skips_preflight_on_multihost(monkeypatch):
    """A standalone probe child cannot join a slice-wide TPU runtime, so
    distributed runs must skip the preflight (it would time out and
    misdiagnose a healthy pod) and go straight to rendezvous."""
    import pytest
    from byol_tpu import cli
    from byol_tpu.core import preflight
    from byol_tpu.parallel import mesh as mesh_lib

    def no_probe(*a, **k):
        raise AssertionError("preflight must not run on multi-host")
    monkeypatch.setattr(preflight, "preflight_backend", no_probe)

    class Sentinel(Exception):
        pass

    def fake_init(addr, num_processes=None, process_id=None):
        assert addr == "h0:29300"   # port default appended
        raise Sentinel()
    monkeypatch.setattr(mesh_lib, "initialize_distributed", fake_init)
    with pytest.raises(Sentinel):
        cli.main(["--task", "fake", "--distributed-master", "h0"])
