"""Resident flat update state (ISSUE 18 tentpole).

The contracts under test:

- **Parity** (acceptance): ``--flat-resident on`` matches the transient
  fused path's loss, eval loss, and post-step params / LARS momentum /
  EMA target within 1e-5 at accum 1 AND 2, zero1 off AND on, on the
  8-virtual-device CPU mesh, every step under the ``guard_steps``
  transfer-guard fixture — residency is a layout change, not a math
  change (a shard's resident chunk is byte-identical to the shard-local
  buffer the per-step pack built, parallel/flat_state.py docstring).
- **Off-identity** (acceptance): ``--flat-resident off`` lowers
  byte-identical HLO to a step built with no resident plumbing at all —
  the flag, the ``flat_ctx`` builder kwarg, and the StepConfig field
  change NOTHING until switched on; and ``on`` really traces a different
  program (the gate is live).
- **Bucketed gather** (satellite): the per-leaf ``Zero1Context.gather``
  lowers ~leaf-count all-gather ops; ``FlatResidentContext.gather_tree``
  lowers <= bucket-count — the coalescing claim, falsified by counting
  ``all-gather`` instructions in compiled HLO on CPU.
- **Layout units**: pack/unpack round-trips exactly for 1 and N shards,
  pack is idempotent over the ZeRO-1 global flat layout, bucket plans
  tile the row exactly within budget, and the resident buffer's padding
  is all zeros (the norm-inertness every parity claim rests on).
"""
import dataclasses
import math
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from byol_tpu.core import config as config_lib
from byol_tpu.parallel import flat_state as flat_lib
from byol_tpu.parallel import zero1 as zero1_lib
from byol_tpu.parallel.compile_plan import build_plan
from byol_tpu.parallel.mesh import DATA_AXIS, shard_batch_to_mesh
from byol_tpu.parallel.zero1 import Zero1Context
from byol_tpu.training.build import setup_training
from tests.conftest import guard_steps, tree_maxdiff as _tree_maxdiff

BATCH = 16
IMAGE = 16

ALL_GATHER_RE = re.compile(r"= \S+ all-gather\(")


def _rcfg(resident="off", zero1="off", accum=1):
    c = config_lib.Config()
    c = c.replace(
        task=dataclasses.replace(c.task, batch_size=BATCH, epochs=2,
                                 image_size_override=IMAGE),
        model=dataclasses.replace(c.model, arch="resnet18",
                                  head_latent_size=32, projection_size=16),
        optim=dataclasses.replace(c.optim, warmup=1, lr=0.1,
                                  accum_steps=accum, fused_update="on"),
        device=dataclasses.replace(c.device, num_replicas=8, half=False,
                                   zero1=zero1, flat_resident=resident),
    )
    return config_lib.resolve(c, num_train_samples=64, num_test_samples=16,
                              output_size=10, input_shape=(IMAGE, IMAGE, 3),
                              representation_size=512)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "view1": rng.rand(BATCH, IMAGE, IMAGE, 3).astype(np.float32),
        "view2": rng.rand(BATCH, IMAGE, IMAGE, 3).astype(np.float32),
        "label": rng.randint(0, 10, size=(BATCH,)).astype(np.int32),
    }


def _plan_for(mesh, rcfg):
    cfg = rcfg.cfg
    return build_plan(mesh, zero1=cfg.device.zero1 == "on",
                      flat_resident=cfg.device.flat_resident == "on",
                      bucket_mb=cfg.device.flat_bucket_mb)


def _run_arm(mesh, resident, zero1="off", accum=1, n=2):
    """n guarded train steps + one guarded eval from the seed-0 init;
    returns (plan, plan-layout state, CANONICAL state, metrics, eval
    loss).  The eval step exercises the bucketed EMA-target gather on the
    resident arm (the eval/linear-eval coalescing satellite)."""
    rcfg = _rcfg(resident=resident, zero1=zero1, accum=accum)
    plan = _plan_for(mesh, rcfg)
    net, state, train_step, eval_step, _ = setup_training(
        rcfg, mesh, jax.random.PRNGKey(0), plan=plan)
    train_step = guard_steps(train_step)
    metrics = None
    for i in range(n):
        batch = shard_batch_to_mesh(_batch(seed=i), mesh)
        state, metrics = train_step(state, batch)
    ev = guard_steps(eval_step)(state,
                                shard_batch_to_mesh(_batch(seed=99), mesh))
    return (plan, state, plan.to_canonical(state),
            {k: float(v) for k, v in metrics.items()},
            float(ev["loss_mean"]))


# ---------------------------------------------------------------------------
# parity: resident == transient, accum 1/2 x zero1 off/on  (acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("zero1", ["off", "on"])
@pytest.mark.parametrize("accum", [1, 2])
def test_resident_matches_transient(mesh8, zero1, accum):
    _, _, canon_off, m_off, ev_off = _run_arm(mesh8, "off", zero1=zero1,
                                              accum=accum)
    _, raw_on, canon_on, m_on, ev_on = _run_arm(mesh8, "on", zero1=zero1,
                                                accum=accum)

    # the resident arm really is resident: momentum + target are single
    # 1-D fp32 buffers, and under zero1 they (and the shadow) shard over
    # 'data' while params stay replicated shaped for the forward
    from byol_tpu.optim.factory import extract_sgdm_state
    trace, _ = extract_sgdm_state(raw_on.opt_state)
    assert isinstance(trace, jax.Array) and trace.ndim == 1
    assert isinstance(raw_on.target_params, jax.Array)
    assert raw_on.target_params.shape == trace.shape
    if zero1 == "on":
        assert raw_on.flat_shadow is not None
        assert raw_on.flat_shadow.shape == trace.shape
        for buf in (trace, raw_on.target_params, raw_on.flat_shadow):
            assert DATA_AXIS in str(buf.sharding.spec)
    else:
        assert raw_on.flat_shadow is None
    assert all(leaf.ndim > 0 or True for leaf in
               jax.tree_util.tree_leaves(raw_on.params))

    for k in m_off:
        np.testing.assert_allclose(
            m_on[k], m_off[k], rtol=1e-5,
            err_msg=f"metric {k} @ zero1={zero1} accum={accum}")
    np.testing.assert_allclose(ev_on, ev_off, rtol=1e-5)

    # post-step state in the canonical (shaped, replicated) view
    assert _tree_maxdiff(canon_off.params, canon_on.params) < 1e-5
    assert _tree_maxdiff(canon_off.opt_state, canon_on.opt_state) < 1e-5
    assert _tree_maxdiff(canon_off.target_params,
                         canon_on.target_params) < 1e-5
    assert canon_on.flat_shadow is None      # canonical ckpts carry none
    assert int(canon_on.step) == int(canon_off.step) == 2


# ---------------------------------------------------------------------------
# --flat-resident off HLO identity + on lowers a different program
# ---------------------------------------------------------------------------

def test_resident_off_lowers_identical_hlo(mesh8):
    """The off arm's program must be byte-identical to a fused step built
    with NO resident plumbing at all — make_train_step called exactly as
    the pre-resident code called it (no flat_ctx kwarg)."""
    from byol_tpu.core.precision import get_policy
    from byol_tpu.parallel.partitioning import state_shardings
    from byol_tpu.training.build import build_net, build_tx, step_config
    from byol_tpu.training.steps import make_train_step

    rcfg = _rcfg(resident="off")
    plan = _plan_for(mesh8, rcfg)
    net, state, train_step, _, _ = setup_training(
        rcfg, mesh8, jax.random.PRNGKey(0), plan=plan)
    batch = shard_batch_to_mesh(_batch(), mesh8)
    with mesh8:
        off_text = train_step.__wrapped__.lower(state, batch).as_text()

    tx, schedule = build_tx(rcfg)
    bare = jax.jit(
        make_train_step(build_net(rcfg), tx, step_config(rcfg),
                        get_policy(False), lr_schedule=schedule,
                        mesh=mesh8),
        in_shardings=(state_shardings(state, mesh8),
                      NamedSharding(mesh8, P(DATA_AXIS))),
        out_shardings=(state_shardings(state, mesh8),
                       NamedSharding(mesh8, P())),
        donate_argnums=(0,))
    with mesh8:
        bare_text = bare.lower(state, batch).as_text()
    assert off_text == bare_text


def test_resident_on_lowers_a_different_program(mesh8):
    texts = {}
    for resident in ("off", "on"):
        rcfg = _rcfg(resident=resident)
        plan = _plan_for(mesh8, rcfg)
        _, state, train_step, _, _ = setup_training(
            rcfg, mesh8, jax.random.PRNGKey(0), plan=plan)
        batch = shard_batch_to_mesh(_batch(), mesh8)
        with mesh8:
            texts[resident] = train_step.__wrapped__.lower(
                state, batch).as_text()
    assert texts["on"] != texts["off"]


# ---------------------------------------------------------------------------
# bucketed gather: all-gather count <= buckets, not leaves  (satellite)
# ---------------------------------------------------------------------------

def _toy_template():
    """~6 leaves, sizes chosen so a small bucket budget splits them into
    several buckets (sizes in fp32 elements per shard after padding)."""
    shapes = {"conv": (3, 3, 8, 16), "bn_scale": (16,), "bn_bias": (16,),
              "dense": (128, 64), "dense_bias": (64,), "probe": (64, 10)}
    return {k: jax.ShapeDtypeStruct(v, jnp.float32)
            for k, v in shapes.items()}


def _count_all_gathers(compiled_text):
    return len(ALL_GATHER_RE.findall(compiled_text))


def test_bucketed_gather_coalesces_collectives(mesh8):
    """Per-leaf gather: ~one all-gather per leaf.  Bucketed gather: at
    most one per bucket.  Counted in the compiled HLO, so the coalescing
    claim is falsifiable on CPU — the acceptance criterion."""
    n = len(mesh8.devices.flat)
    tmpl = _toy_template()
    n_leaves = len(jax.tree_util.tree_leaves(tmpl))
    layout = flat_lib.build_layout(tmpl, n)
    # tiny budget: every bucket is 1 KiB of gathered bytes -> >1 bucket,
    # but still far fewer than leaves after coalescing the small ones
    ctx = flat_lib.FlatResidentContext(mesh=mesh8, layout=layout,
                                       bucket_mb=1)
    n_buckets = len(ctx.buckets())
    assert 1 <= n_buckets < n_leaves

    z1 = Zero1Context(mesh=mesh8, num_shards=n, param_template=tmpl)
    rng = np.random.RandomState(0)
    tree = {k: jnp.asarray(rng.rand(*t.shape).astype(np.float32))
            for k, t in tmpl.items()}
    flat_tree = jax.device_put(
        jax.jit(z1.shard)(tree),
        jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh8, P(DATA_AXIS)), tmpl))
    buf = jax.device_put(flat_lib.pack_tree(tree, layout),
                         NamedSharding(mesh8, P(DATA_AXIS)))

    with mesh8:
        per_leaf = jax.jit(
            lambda t: z1.gather(t, tmpl)).lower(flat_tree).compile()
        bucketed = jax.jit(ctx.gather_tree).lower(buf).compile()
    count_leafwise = _count_all_gathers(per_leaf.as_text())
    count_bucketed = _count_all_gathers(bucketed.as_text())
    assert count_leafwise >= n_leaves // 2   # ~one per leaf (XLA may fold)
    assert 1 <= count_bucketed <= n_buckets
    assert count_bucketed < count_leafwise

    # and the bucketed gather is CORRECT, not just cheap
    with mesh8:
        gathered = jax.jit(ctx.gather_tree)(buf)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(gathered[k]),
                                      np.asarray(tree[k]))


# ---------------------------------------------------------------------------
# layout units: pack/unpack, idempotency, buckets, padding
# ---------------------------------------------------------------------------

class TestFlatLayout:
    @pytest.mark.parametrize("n", [1, 8])
    def test_pack_unpack_roundtrip(self, n):
        tmpl = _toy_template()
        layout = flat_lib.build_layout(tmpl, n)
        rng = np.random.RandomState(1)
        tree = {k: jnp.asarray(rng.rand(*t.shape).astype(np.float32))
                for k, t in tmpl.items()}
        buf = flat_lib.pack_tree(tree, layout)
        assert buf.shape == (layout.global_size,)
        assert layout.global_size == n * layout.grid_rows * 128
        back = flat_lib.unpack_tree(buf, layout)
        for k in tree:
            np.testing.assert_array_equal(np.asarray(back[k]),
                                          np.asarray(tree[k]))

    def test_pack_padding_is_zero(self):
        """Row padding, shard-remainder padding, and the grid tail are all
        zeros — the inertness the parity claims rest on."""
        tmpl = _toy_template()
        layout = flat_lib.build_layout(tmpl, 8)
        ones = {k: jnp.ones(t.shape, jnp.float32)
                for k, t in tmpl.items()}
        buf = np.asarray(flat_lib.pack_tree(ones, layout))
        total_real = sum(
            math.prod(t.shape) for t in jax.tree_util.tree_leaves(tmpl))
        assert int(buf.sum()) == total_real          # everything else is 0
        assert int((buf == 1.0).sum()) == total_real

    def test_pack_is_idempotent_over_zero1_flat_layout(self):
        """Packing the ZeRO-1 global flat tree (what prepare_state holds
        after the zero1 branch) must produce the SAME buffer as packing
        the shaped canonical tree — the single-pack setup path relies on
        it."""
        n = 8
        tmpl = _toy_template()
        layout = flat_lib.build_layout(tmpl, n)
        rng = np.random.RandomState(2)
        tree = {k: jnp.asarray(rng.rand(*t.shape).astype(np.float32))
                for k, t in tmpl.items()}
        flat_tree = {k: zero1_lib.flatten_leaf(v, n)
                     for k, v in tree.items()}
        np.testing.assert_array_equal(
            np.asarray(flat_lib.pack_tree(tree, layout)),
            np.asarray(flat_lib.pack_tree(flat_tree, layout)))

    def test_buckets_tile_the_row_within_budget(self):
        layout = flat_lib.build_layout(_toy_template(), 8)
        for mb in (1, 64):
            buckets = flat_lib.plan_buckets(layout, mb)
            # contiguous, ordered, leaf-aligned cover of all segments
            assert buckets[0][0] == 0
            seen = []
            for (c0, c1, idxs), nxt in zip(buckets, buckets[1:] + ((None,) * 3,)):
                assert c0 < c1
                assert c1 == (layout.seg.starts[idxs[-1]]
                              + layout.seg.padded[idxs[-1]])
                if nxt[0] is not None:
                    assert nxt[0] == c1
                seen.extend(idxs)
            assert seen == list(range(layout.seg.num_segments))
            # every multi-leaf bucket respects the budget (a single
            # oversized leaf may exceed it; it is never split)
            budget = mb * (1 << 20)
            for c0, c1, idxs in buckets:
                if len(idxs) > 1:
                    assert (c1 - c0) * layout.num_shards * 4 <= budget
        # large budget degenerates to one bucket
        assert len(flat_lib.plan_buckets(layout, 1 << 10)) == 1

    def test_single_shard_gather_has_no_collective(self, mesh8):
        """num_shards == 1: gather_tree is a pure carve — zero all-gather
        ops in the compiled HLO."""
        tmpl = _toy_template()
        layout = flat_lib.build_layout(tmpl, 1)
        ctx = flat_lib.FlatResidentContext(mesh=mesh8, layout=layout)
        rng = np.random.RandomState(3)
        tree = {k: jnp.asarray(rng.rand(*t.shape).astype(np.float32))
                for k, t in tmpl.items()}
        buf = flat_lib.pack_tree(tree, layout)
        with mesh8:
            compiled = jax.jit(ctx.gather_tree).lower(buf).compile()
            gathered = jax.jit(ctx.gather_tree)(buf)
        assert _count_all_gathers(compiled.as_text()) == 0
        for k in tree:
            np.testing.assert_array_equal(np.asarray(gathered[k]),
                                          np.asarray(tree[k]))

    def test_build_layout_rejects_bad_args(self):
        with pytest.raises(ValueError, match="num_shards"):
            flat_lib.build_layout(_toy_template(), 0)
        layout = flat_lib.build_layout(_toy_template(), 1)
        with pytest.raises(ValueError, match="bucket_mb"):
            flat_lib.plan_buckets(layout, 0)


# ---------------------------------------------------------------------------
# gating + provenance
# ---------------------------------------------------------------------------

class TestGating:
    def test_resolve_rejects_resident_without_fused(self):
        c = config_lib.Config()
        c = c.replace(device=dataclasses.replace(c.device,
                                                 flat_resident="on"))
        with pytest.raises(ValueError, match="fused-update"):
            config_lib.resolve(c, num_train_samples=64,
                               num_test_samples=16, output_size=10,
                               input_shape=(IMAGE, IMAGE, 3),
                               representation_size=512)

    def test_make_train_step_rejects_inconsistent_wiring(self):
        from byol_tpu.training.build import build_net, build_tx, step_config
        rcfg = _rcfg(resident="on")
        scfg = step_config(rcfg)
        assert scfg.flat_resident
        net = build_net(rcfg)
        tx, schedule = build_tx(rcfg)
        from byol_tpu.training.steps import make_train_step
        with pytest.raises(ValueError, match="flat_ctx"):
            make_train_step(net, tx, scfg, lr_schedule=schedule)
        bad = dataclasses.replace(scfg, fused_update=False,
                                  flat_resident=True)
        with pytest.raises(ValueError, match="fused_update"):
            make_train_step(net, tx, bad, lr_schedule=schedule)

    def test_build_plan_rejects_small_bucket(self, mesh8):
        with pytest.raises(ValueError, match="bucket_mb"):
            build_plan(mesh8, flat_resident=True, bucket_mb=0)


def test_plan_describe_carries_resident_fields(mesh8):
    d = build_plan(mesh8, zero1=True, flat_resident=True,
                   bucket_mb=32).describe()
    assert d["flat_resident"] == "on"
    assert d["flat_bucket_mb"] == 32
    assert build_plan(mesh8).describe()["flat_resident"] == "off"
