"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference had no tests and could only validate multi-node behavior by
launching on SLURM (SURVEY.md §4).  JAX lets us run the full SPMD program on
N virtual CPU devices instead — this must be configured before jax imports.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # the session env pins 'axon' (real TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

# sitecustomize.py pre-imports jax before this conftest runs, freezing the
# env-derived config; override through the config API (the XLA backend itself
# is still uninitialized at this point, so this takes effect).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.4.38) has no jax_num_cpu_devices; the XLA_FLAGS
    # device-count flag set above does the same job as long as the backend
    # is still uninitialized here (it is: sitecustomize only IMPORTS jax).
    pass
# Persistent compilation cache: repeated test runs (and repeated fit() calls
# within one run) reuse compiled executables instead of paying 30-60s XLA
# compiles per jit instance.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_compile_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import functools  # noqa: E402

import pytest  # noqa: E402


def guard_steps(fn):
    """Runtime complement to graphlint GL101/GL102: wrap a jitted step so
    every call (including the first, tracing+compiling one) runs under

    - ``jax.transfer_guard("disallow")`` — an IMPLICIT host<->device
      transfer inside the step (a ``float()``/``np.asarray`` sync point, a
      numpy constant smuggled into the traced graph) fails the test on CPU
      instead of stalling a TPU run.  Explicit transfers (``device_put``,
      ``device_get``) stay allowed — reading metrics AFTER the call is
      legitimate and must be spelled explicitly.
    - ``jax.checking_leaks()`` — a tracer escaping the traced scope (the
      classic closure-capture bug) raises instead of baking in a constant.
    """
    @functools.wraps(fn)
    def guarded(*args, **kwargs):
        with jax.transfer_guard("disallow"), jax.checking_leaks():
            return fn(*args, **kwargs)
    return guarded


def tree_maxdiff(a, b):
    """Max abs elementwise difference over two pytrees' paired leaves (fp32
    compare) — the parity comparator test_zero1.py and test_fused_update.py
    share."""
    import numpy as np

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return max(
        float(np.max(np.abs(np.asarray(x, np.float32)
                            - np.asarray(y, np.float32))))
        if np.asarray(x).size else 0.0
        for x, y in zip(la, lb))


@pytest.fixture(scope="session")
def step_guard():
    """Fixture handle for :func:`guard_steps` (importable directly as
    ``tests.conftest.guard_steps`` where a fixture is awkward)."""
    return guard_steps


@pytest.fixture(scope="session")
def mesh8():
    from byol_tpu.parallel.mesh import MeshSpec, build_mesh
    return build_mesh(MeshSpec(data=8))


@pytest.fixture(scope="session")
def mesh_dp_sp():
    """4-way data x 2-way sequence mesh for context-parallel tests."""
    from byol_tpu.parallel.mesh import MeshSpec, build_mesh
    return build_mesh(MeshSpec(data=4, sequence=2))
