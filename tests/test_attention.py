"""Attention backends: dense oracle vs Pallas flash vs ring (sequence-
parallel).  All three share one signature (ops/attention.py) — these tests
pin their numerical equivalence, which is what lets the ViT swap impls by
config name."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.ops.attention import dense_attention, get_attention_fn


def _qkv(key, b=2, h=2, s=64, d=16, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, h, s, d)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


def _reference(q, k, v):
    scale = q.shape[-1] ** -0.5
    s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q, np.float64),
                  np.asarray(k, np.float64)) * scale
    w = np.exp(s - s.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", w, np.asarray(v, np.float64))


def test_dense_matches_float64_reference():
    q, k, v = _qkv(jax.random.PRNGKey(0))
    np.testing.assert_allclose(dense_attention(q, k, v),
                               _reference(q, k, v), rtol=1e-5, atol=1e-5)


def test_flash_matches_dense_aligned():
    from byol_tpu.ops.flash_attention import flash_attention
    q, k, v = _qkv(jax.random.PRNGKey(1), s=128, d=16)
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    np.testing.assert_allclose(out, dense_attention(q, k, v),
                               rtol=1e-5, atol=1e-5)


def test_flash_masks_padded_keys():
    """S=197 (the ViT-B/224 token count) is not block-aligned: padded key
    positions must not leak probability mass."""
    from byol_tpu.ops.flash_attention import flash_attention
    q, k, v = _qkv(jax.random.PRNGKey(2), b=1, h=2, s=197, d=16)
    out = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    assert out.shape == q.shape
    np.testing.assert_allclose(out, dense_attention(q, k, v),
                               rtol=1e-5, atol=1e-5)


def test_flash_bf16():
    from byol_tpu.ops.flash_attention import flash_attention
    q, k, v = _qkv(jax.random.PRNGKey(3), s=64, d=16, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_flash_long_sequence_streams_kv():
    """S=4096 with 128-blocks: 32 K tiles walked on the grid.  At the old
    whole-K-resident layout this shape held the full padded K/V per program;
    the grid-streamed kernel must still match the dense oracle exactly
    (round-2 verdict: VMEM residency capped usable sequence length)."""
    from byol_tpu.ops.flash_attention import flash_attention
    q, k, v = _qkv(jax.random.PRNGKey(8), b=1, h=1, s=4096, d=8)
    out = flash_attention(q, k, v, block_q=128, block_k=128, interpret=True)
    np.testing.assert_allclose(out, dense_attention(q, k, v),
                               rtol=1e-5, atol=1e-5)


def test_ring_matches_dense_shard_map(mesh_dp_sp):
    """Ring attention over a real 2-way sequence axis (4 data x 2 sequence
    CPU mesh) must reproduce dense attention on the gathered sequence."""
    from byol_tpu.parallel.ring_attention import ring_attention
    q, k, v = _qkv(jax.random.PRNGKey(4), b=4, h=2, s=32, d=8)
    with mesh_dp_sp:
        out = ring_attention(q, k, v, mesh=mesh_dp_sp)
    np.testing.assert_allclose(out, dense_attention(q, k, v),
                               rtol=1e-5, atol=1e-5)


def test_ring_inside_jit(mesh_dp_sp):
    from byol_tpu.parallel.ring_attention import ring_attention
    q, k, v = _qkv(jax.random.PRNGKey(5), b=4, h=2, s=32, d=8)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh=mesh_dp_sp)

    np.testing.assert_allclose(f(q, k, v), dense_attention(q, k, v),
                               rtol=1e-5, atol=1e-5)


def test_ring_requires_sequence_axis():
    from byol_tpu.parallel.ring_attention import ring_attention
    q, k, v = _qkv(jax.random.PRNGKey(6), s=8, d=4)
    with pytest.raises(ValueError, match="sequence"):
        ring_attention(q, k, v)  # no mesh in scope


def test_get_attention_fn_registry():
    assert get_attention_fn("dense") is dense_attention
    from byol_tpu.ops.flash_attention import flash_attention
    assert get_attention_fn("flash") is flash_attention
    from byol_tpu.parallel.ring_attention import ring_attention
    assert get_attention_fn("ring") is ring_attention
    with pytest.raises(ValueError, match="unknown"):
        get_attention_fn("bogus")


def test_vit_with_flash_matches_dense():
    """ViT forward with attn_impl='flash' equals attn_impl='dense' on the
    same params — the swap is purely an implementation choice."""
    from byol_tpu.models.vit import ViT
    x = jax.random.uniform(jax.random.PRNGKey(7), (2, 32, 32, 3))
    dense_vit = ViT(width=32, depth=1, num_heads=4, patch_size=8)
    flash_vit = ViT(width=32, depth=1, num_heads=4, patch_size=8,
                    attn_impl="flash")
    variables = dense_vit.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(flash_vit.apply(variables, x),
                               dense_vit.apply(variables, x),
                               rtol=1e-4, atol=1e-5)
