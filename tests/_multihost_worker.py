"""Worker process for the two-process multi-host integration test.

Runs the REAL stack end-to-end under explicit rendezvous: CPU backend, two
processes x two devices, per-host data sharding, multi-host batch assembly
(jax.make_array_from_process_local_data path of shard_batch_to_mesh), one
jitted SPMD train step with cross-process collectives (Gloo), and prints the
loss for the parent to compare across ranks.
"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_compile_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def main() -> int:
    rank = int(sys.argv[1])
    port = sys.argv[2]
    from byol_tpu.parallel.mesh import (MeshSpec, build_mesh,
                                        initialize_distributed,
                                        shard_batch_to_mesh)
    initialize_distributed(f"localhost:{port}", num_processes=2,
                           process_id=rank)
    assert jax.process_count() == 2
    assert jax.device_count() == 4 and len(jax.local_devices()) == 2

    from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                      TaskConfig, resolve)
    from byol_tpu.data.loader import get_loader
    from byol_tpu.training.build import setup_training

    cfg = Config(
        task=TaskConfig(task="fake", batch_size=8, epochs=1,
                        image_size_override=16),
        model=ModelConfig(arch="resnet18", head_latent_size=32,
                          projection_size=16),
        device=DeviceConfig(num_replicas=4, half=False, seed=3),
    )
    # per-host shard: each process sees 8 of 16 samples, host batch 4
    loader = get_loader(cfg, num_fake_samples=16)
    batch = next(loader.train_loader)
    assert len(batch["label"]) == 4, batch["label"].shape

    rcfg = resolve(cfg, num_train_samples=loader.num_train_samples,
                   num_test_samples=loader.num_test_samples,
                   output_size=loader.output_size,
                   input_shape=loader.input_shape)
    mesh = build_mesh(MeshSpec(data=4))
    net, state, train_step, eval_step, _ = setup_training(
        rcfg, mesh, jax.random.PRNGKey(0))

    dev_batch = shard_batch_to_mesh(batch, mesh)
    assert dev_batch["label"].shape[0] == 8      # assembled GLOBAL batch
    state, metrics = train_step(state, dev_batch)
    loss = float(metrics["loss_mean"])           # forces cross-host psum
    print(f"RANK{rank} OK loss={loss:.6f} step={int(state.step)}")

    # Offline linear eval ACROSS processes (VERDICT r3 gap: the paper metric
    # must be computable on the pod config): SPMD feature extraction over
    # per-host loader shards, probe fit host-locally on the gathered global
    # features — both ranks must report the identical top-1.
    from byol_tpu.training.linear_eval import run_linear_eval_from_cfg
    le = run_linear_eval_from_cfg(cfg, state, loader=loader, mesh=mesh,
                                  epochs=2, seed=0)
    print(f"RANK{rank} LE top1={le.top1:.6f} ntrain={le.num_train} "
          f"ntest={le.num_test}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
