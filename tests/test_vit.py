"""ViT backbone: shapes, registry contract, BN-free property, BYOL wiring."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.models.registry import get_backbone, get_spec
from byol_tpu.models.vit import ViT


def _tiny_vit(**kw):
    kw.setdefault("width", 32)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("patch_size", 8)
    return ViT(**kw)


def test_feature_shape_and_dim():
    vit = _tiny_vit()
    x = jnp.zeros((2, 32, 32, 3))
    variables = vit.init(jax.random.PRNGKey(0), x)
    feats = vit.apply(variables, x)
    assert feats.shape == (2, 32)
    assert vit.feature_dim == 32


def test_registry_entries():
    for name, dim in (("vit_b16", 768), ("vit_l16", 1024), ("vit_s16", 384)):
        spec = get_spec(name)
        assert spec.feature_dim == dim
        assert not spec.has_batchnorm  # drives BN-exclusion mask skipping


def test_no_batch_stats_collection():
    """BN-free: init must produce params only — no mutable batch_stats, so
    SyncBN machinery has nothing to touch (SURVEY.md §7 hard part 6)."""
    vit = _tiny_vit()
    variables = vit.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    assert set(variables.keys()) == {"params"}


def test_gap_vs_cls_pooling():
    x = jnp.ones((2, 32, 32, 3))
    for pooling in ("cls", "gap"):
        vit = _tiny_vit(pooling=pooling)
        variables = vit.init(jax.random.PRNGKey(0), x)
        assert vit.apply(variables, x).shape == (2, 32)
    with pytest.raises(ValueError, match="pooling"):
        vit = _tiny_vit(pooling="bogus")
        vit.init(jax.random.PRNGKey(0), x)


def test_indivisible_patch_size_raises():
    vit = _tiny_vit(patch_size=7)
    with pytest.raises(ValueError, match="divisible"):
        vit.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))


def test_remat_matches_plain():
    x = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
    plain = _tiny_vit()
    rematted = _tiny_vit(remat=True)
    variables = plain.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(plain.apply(variables, x),
                               rematted.apply(variables, x),
                               rtol=1e-5, atol=1e-6)


def test_vit_byol_net_trains_one_step(mesh8):
    """Full BYOL train step over a ViT backbone on the 8-device mesh — the
    BN-free path must flow through loss/grads/EMA without batch_stats."""
    from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                      TaskConfig, resolve)
    from byol_tpu.parallel.mesh import shard_batch_to_mesh
    from byol_tpu.training.build import setup_training

    cfg = Config(
        task=TaskConfig(task="fake", batch_size=16, epochs=2,
                        image_size_override=16),
        model=ModelConfig(arch="vit_test", head_latent_size=32,
                          projection_size=16),
        device=DeviceConfig(num_replicas=8, half=False, seed=0),
    )
    # register a micro-ViT so the test stays fast on the 1-core CI box
    from byol_tpu.models import registry, vit as vit_lib
    if "vit_test" not in registry.available():
        registry.register("vit_test", registry.BackboneSpec(
            factory=lambda dtype=jnp.float32, small_inputs=False, **kw:
                vit_lib.ViT(width=32, depth=1, num_heads=4, patch_size=8,
                            dtype=dtype, **kw),
            feature_dim=32, has_batchnorm=False))
    rcfg = resolve(cfg, num_train_samples=32, num_test_samples=16,
                   output_size=10, input_shape=(16, 16, 3))
    net, state, train_step, eval_step, _ = setup_training(
        rcfg, mesh8, jax.random.PRNGKey(0))
    # The ViT backbone itself carries no BN stats; the projector/predictor
    # MLP heads do (Linear->BN1d->ReLU->Linear, main.py:194-205).
    assert "backbone" not in state.batch_stats
    assert set(state.batch_stats) <= {"projector", "predictor"}

    r = np.random.RandomState(0)
    batch = shard_batch_to_mesh(
        {"view1": r.rand(16, 16, 16, 3).astype(np.float32),
         "view2": r.rand(16, 16, 16, 3).astype(np.float32),
         "label": r.randint(0, 10, (16,)).astype(np.int32)}, mesh8)
    state, metrics = train_step(state, batch)
    assert np.isfinite(float(metrics["loss_mean"]))
    ev = eval_step(state, batch)
    assert np.isfinite(float(ev["loss_mean"]))
