"""Fused LARS+EMA weight-update kernel (ISSUE 12 tentpole).

The contracts under test:

- **Equivalence** (acceptance): ``--fused-update on`` matches the optax
  chain's loss and post-step params / LARS momentum / EMA target within
  1e-5 at accum 1 AND 2, zero1 off AND on, every step under the
  ``guard_steps`` transfer-guard fixture — the fused kernel is a
  reimplementation of the update math, not a new update rule.
- **Off-identity** (acceptance): ``--fused-update off`` lowers
  byte-identical HLO to a step built with no fused plumbing at all
  (defaults) — the flag, the ``lr_schedule``/``mesh`` builder kwargs, and
  the StepConfig field change NOTHING until switched on; and ``on``
  really traces a different program (the gate is live).
- **Kernel unit equivalence**: the fused update on synthetic trees ==
  the factory's lars_momentum chain + EMA tick, both layouts, both EMA
  modes — fast, model-free.
- **Segment map** (property): segments tile and cover the flat buffer
  exactly, pack/unpack round-trips, and the zero padding (block
  alignment + the ZeRO-1 shard tail) never contributes to any norm.
- **Telemetry** (PR 6 invariant): the health vector's trust stats under
  the fused path report the ratios the KERNEL applied — equal to the
  unfused path's reported==applied stats on the same step.
- **Gating**: resolve() rejects ``--fused-update on`` for configs the
  kernel does not implement (non-LARS optimizer, non-momentum inner,
  clip > 0).
"""
import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byol_tpu.core import config as config_lib
from byol_tpu.observability import health as health_lib
from byol_tpu.ops import fused_update as fused_lib
from byol_tpu.optim import lars as lars_lib
from byol_tpu.optim.factory import (MOMENTUM_DECAY, build_optimizer,
                                    extract_sgdm_state,
                                    fused_update_unsupported_reason,
                                    replace_sgdm_state)
from byol_tpu.parallel import zero1 as zero1_lib
from byol_tpu.parallel.compile_plan import build_plan
from byol_tpu.parallel.mesh import DATA_AXIS, shard_batch_to_mesh
from byol_tpu.training.build import setup_training
from tests.conftest import guard_steps, tree_maxdiff as _tree_maxdiff

BATCH = 16
IMAGE = 16


def _rcfg(fused="off", zero1="off", accum=1, telemetry="off"):
    c = config_lib.Config()
    c = c.replace(
        task=dataclasses.replace(c.task, batch_size=BATCH, epochs=2,
                                 image_size_override=IMAGE),
        model=dataclasses.replace(c.model, arch="resnet18",
                                  head_latent_size=32, projection_size=16),
        optim=dataclasses.replace(c.optim, warmup=1, lr=0.1,
                                  accum_steps=accum, fused_update=fused),
        device=dataclasses.replace(c.device, num_replicas=8, half=False,
                                   zero1=zero1, telemetry=telemetry),
    )
    return config_lib.resolve(c, num_train_samples=64, num_test_samples=16,
                              output_size=10, input_shape=(IMAGE, IMAGE, 3),
                              representation_size=512)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "view1": rng.rand(BATCH, IMAGE, IMAGE, 3).astype(np.float32),
        "view2": rng.rand(BATCH, IMAGE, IMAGE, 3).astype(np.float32),
        "label": rng.randint(0, 10, size=(BATCH,)).astype(np.int32),
    }


def _run_arm(mesh, fused, zero1="off", accum=1, n=2, telemetry="off"):
    """n guarded train steps from the seed-0 init; returns the CANONICAL
    state (the fused zero1 arm's momentum/EMA live flat-sharded) + the
    final metrics."""
    rcfg = _rcfg(fused=fused, zero1=zero1, accum=accum, telemetry=telemetry)
    plan = build_plan(mesh, zero1=(zero1 == "on"))
    net, state, train_step, _, _ = setup_training(
        rcfg, mesh, jax.random.PRNGKey(0), plan=plan)
    train_step = guard_steps(train_step)
    metrics = None
    for i in range(n):
        batch = shard_batch_to_mesh(_batch(seed=i), mesh)
        state, metrics = train_step(state, batch)
    return plan.to_canonical(state), metrics


# ---------------------------------------------------------------------------
# equivalence: fused == optax chain, accum 1/2 x zero1 off/on  (acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("zero1", ["off", "on"])
@pytest.mark.parametrize("accum", [1, 2])
def test_fused_matches_optax_chain(mesh8, zero1, accum):
    canon_off, m_off = _run_arm(mesh8, "off", zero1=zero1, accum=accum)
    canon_on, m_on = _run_arm(mesh8, "on", zero1=zero1, accum=accum)
    for k in m_off:
        np.testing.assert_allclose(
            float(m_on[k]), float(m_off[k]), rtol=1e-5,
            err_msg=f"metric {k} @ zero1={zero1} accum={accum}")
    assert _tree_maxdiff(canon_off.params, canon_on.params) < 1e-5
    assert _tree_maxdiff(canon_off.opt_state, canon_on.opt_state) < 1e-5
    assert _tree_maxdiff(canon_off.target_params,
                         canon_on.target_params) < 1e-5
    assert int(canon_on.step) == int(canon_off.step)


# ---------------------------------------------------------------------------
# --fused-update off HLO identity + on lowers a different program
# ---------------------------------------------------------------------------

def test_fused_off_lowers_identical_hlo(mesh8):
    """The off arm's program must be byte-identical to a step built with
    NO fused plumbing at all — make_train_step called exactly as the
    pre-fused-update code called it (no lr_schedule, no mesh)."""
    from byol_tpu.core.precision import get_policy
    from byol_tpu.parallel.partitioning import state_shardings
    from byol_tpu.training.build import build_net, build_tx, step_config
    from byol_tpu.training.steps import make_train_step
    from jax.sharding import NamedSharding, PartitionSpec as P

    rcfg = _rcfg()
    plan = build_plan(mesh8, zero1=False)
    net, state, train_step, _, _ = setup_training(
        rcfg, mesh8, jax.random.PRNGKey(0), plan=plan)
    batch = shard_batch_to_mesh(_batch(), mesh8)
    with mesh8:
        off_text = train_step.__wrapped__.lower(state, batch).as_text()

    bare = jax.jit(
        make_train_step(build_net(rcfg), build_tx(rcfg)[0],
                        step_config(rcfg), get_policy(False)),
        in_shardings=(state_shardings(state, mesh8),
                      NamedSharding(mesh8, P(DATA_AXIS))),
        out_shardings=(state_shardings(state, mesh8),
                       NamedSharding(mesh8, P())),
        donate_argnums=(0,))
    with mesh8:
        bare_text = bare.lower(state, batch).as_text()
    assert off_text == bare_text


def test_fused_on_lowers_a_different_program(mesh8):
    texts = {}
    for fused in ("off", "on"):
        rcfg = _rcfg(fused=fused)
        plan = build_plan(mesh8, zero1=False)
        _, state, train_step, _, _ = setup_training(
            rcfg, mesh8, jax.random.PRNGKey(0), plan=plan)
        batch = shard_batch_to_mesh(_batch(), mesh8)
        with mesh8:
            texts[fused] = train_step.__wrapped__.lower(state,
                                                        batch).as_text()
    assert texts["on"] != texts["off"]


# ---------------------------------------------------------------------------
# kernel unit equivalence (model-free, fast)
# ---------------------------------------------------------------------------

def _toy_tree(seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "conv": jnp.asarray(rng.randn(3, 3, 4, 8), jnp.float32) * 0.1,
        "bias": jnp.asarray(rng.randn(10), jnp.float32) * 0.01,
        "head": {"kernel": jnp.asarray(rng.randn(8, 130),
                                       jnp.float32) * 0.05,
                 "scale": jnp.ones((8,), jnp.float32)},
    }
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.randn(*p.shape), jnp.float32) * 0.01,
        params)
    return params, grads


class TestKernelEquivalence:
    WD = 1e-4

    def _chain(self, params, adapt_mask=None):
        tx, sched = build_optimizer(
            "lars_momentum", base_lr=0.2, global_batch_size=256,
            weight_decay=self.WD, total_units=100, warmup_units=10,
            adapt_mask=adapt_mask)
        st = tx.init(params)
        # non-trivial momentum + schedule position
        st = replace_sgdm_state(
            st, jax.tree_util.tree_map(lambda p: p * 0.05, params),
            jnp.asarray(30, jnp.int32))
        return tx, sched, st

    @pytest.mark.parametrize("ema_pre", [False, True])
    def test_replicated_layout(self, ema_pre):
        params, grads = _toy_tree()
        tx, sched, st = self._chain(params)
        target = jax.tree_util.tree_map(lambda p: p * 0.9, params)
        tau = jnp.asarray(0.99, jnp.float32)

        u, st2 = tx.update(grads, st, params)
        p_ref = optax.apply_updates(params, u)
        ema_src = params if ema_pre else p_ref
        t_ref = jax.tree_util.tree_map(
            lambda t, p: tau * t + (1 - tau) * p, target, ema_src)
        m_ref, count_ref = extract_sgdm_state(st2)

        trace, count = extract_sgdm_state(st)
        p_f, m_f, t_f, trust = fused_lib.fused_lars_ema_update(
            params, grads, trace, target, lr=sched(count), tau=tau,
            weight_decay=self.WD, momentum_decay=MOMENTUM_DECAY,
            ema_pre=ema_pre, interpret=True)
        assert _tree_maxdiff(p_f, p_ref) < 1e-6
        assert _tree_maxdiff(m_f, m_ref) < 1e-6
        assert _tree_maxdiff(t_f, t_ref) < 1e-6
        # the applied ratios == the shared-formula reference (optax path)
        wd_tx = lars_lib.lars_weight_decay(self.WD)
        tg, _ = wd_tx.update(grads, wd_tx.init(params), params)
        np.testing.assert_allclose(
            np.asarray(trust),
            np.asarray(lars_lib.trust_ratio_vector(tg, params)), rtol=1e-6)

    def test_zero1_layout_in_jit_under_guard(self, mesh8):
        """Flat leaf-partitioned layout: fused(shard_map + psum'd segment
        norms) == the shard-local optax chain the zero1 step runs, inside
        jit on the 8-device mesh, under the transfer guard."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        n = 8
        params, grads = _toy_tree()
        mask = lars_lib.default_exclusion_mask(params)
        flat_params = zero1_lib.flatten_tree(params, n)
        flat_grads = zero1_lib.flatten_tree(grads, n)
        tx, sched, st = self._chain(flat_params, adapt_mask=mask)
        flat_target = jax.tree_util.tree_map(lambda p: p * 0.9, flat_params)
        tau = jnp.asarray(0.99, jnp.float32)

        u, st2 = tx.update(flat_grads, st, flat_params)
        p_ref = optax.apply_updates(flat_params, u)
        t_ref = jax.tree_util.tree_map(
            lambda t, p: tau * t + (1 - tau) * p, flat_target, p_ref)
        m_ref, _ = extract_sgdm_state(st2)

        tmpl = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
        trace, count = extract_sgdm_state(st)
        sh = NamedSharding(mesh8, P(DATA_AXIS))
        put = lambda tree: jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), tree)

        @jax.jit
        def run(fp, fg, fm, ft, lr, tau_):
            return fused_lib.fused_lars_ema_update_zero1(
                fp, fg, fm, ft, param_template=tmpl, mesh=mesh8,
                num_shards=n, lr=lr, tau=tau_, weight_decay=self.WD,
                momentum_decay=MOMENTUM_DECAY, interpret=True)

        # scalars must reach the guarded jit EXPLICITLY placed — the real
        # step computes lr/tau in-graph; here they are call arguments
        rep = NamedSharding(mesh8, P())
        with mesh8:
            p_f, m_f, t_f, trust = guard_steps(run)(
                put(flat_params), put(flat_grads), put(trace),
                put(flat_target), jax.device_put(sched(count), rep),
                jax.device_put(tau, rep))
        assert _tree_maxdiff(p_f, p_ref) < 1e-6
        assert _tree_maxdiff(m_f, m_ref) < 1e-6
        assert _tree_maxdiff(t_f, t_ref) < 1e-6
        # outputs stay flat-sharded over data (the JIT all-gather that
        # follows in the step is unchanged)
        assert DATA_AXIS in str(
            jax.tree_util.tree_leaves(p_f)[0].sharding.spec)
        # psum'd norms == replicated-layout ratios (padding is inert)
        _, _, _, trust_rep = fused_lib.fused_lars_ema_update(
            params, grads,
            jax.tree_util.tree_map(lambda p: p * 0.05, params),
            jax.tree_util.tree_map(lambda p: p * 0.9, params),
            lr=sched(count), tau=tau, weight_decay=self.WD,
            momentum_decay=MOMENTUM_DECAY, interpret=True)
        np.testing.assert_allclose(np.asarray(trust),
                                   np.asarray(trust_rep), rtol=1e-5)

    def test_all_1d_tree_packs_identity_trust(self):
        """Nothing adapted (all-1D tree): the kernel applies ratio 1
        everywhere and reports the identity vector — the
        trust_ratio_vector contract for the same degenerate tree."""
        params = {"a": jnp.arange(5.0), "b": jnp.arange(7.0) * 0.1}
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        trace = jax.tree_util.tree_map(jnp.zeros_like, params)
        target = jax.tree_util.tree_map(lambda p: p * 0.5, params)
        p_f, m_f, t_f, trust = fused_lib.fused_lars_ema_update(
            params, grads, trace, target, lr=jnp.float32(0.1),
            tau=jnp.float32(0.9), weight_decay=self.WD,
            momentum_decay=MOMENTUM_DECAY, interpret=True)
        np.testing.assert_array_equal(np.asarray(trust), [1.0])
        # unadapted leaves: no wd fold-in, ratio 1 — plain sgd-momentum
        np.testing.assert_allclose(
            np.asarray(m_f["a"]), np.ones(5), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(p_f["a"]), np.asarray(params["a"]) - 0.1, rtol=1e-5)


# ---------------------------------------------------------------------------
# segment map property tests (satellite)
# ---------------------------------------------------------------------------

class TestSegmentMap:
    def test_property_tiles_and_covers(self):
        """Randomized leaf-size lists: segments are contiguous,
        row-aligned (128 lanes), cover the buffer exactly, and every row
        maps to exactly the segment containing it."""
        rng = np.random.RandomState(0)
        for trial in range(50):
            n_leaves = rng.randint(1, 12)
            sizes = [int(rng.randint(1, 5000)) for _ in range(n_leaves)]
            adapted = [bool(rng.randint(2)) for _ in range(n_leaves)]
            seg = fused_lib.build_segment_map(sizes, adapted)
            assert seg.starts[0] == 0
            for i in range(seg.num_segments):
                assert seg.padded[i] % 128 == 0
                assert seg.padded[i] - seg.sizes[i] < 128
                if i + 1 < seg.num_segments:
                    assert seg.starts[i + 1] == seg.starts[i] + seg.padded[i]
            assert seg.total == sum(seg.padded)
            assert seg.total % 128 == 0
            ids = seg.row_segment_ids()
            assert ids.shape == (seg.num_rows,)
            # row r covers elements [r*128, (r+1)*128) — they must all
            # fall inside segment ids[r]'s [start, start+padded) span
            for r in range(seg.num_rows):
                s = ids[r]
                assert seg.starts[s] <= r * 128
                assert (r + 1) * 128 <= seg.starts[s] + seg.padded[s]

    def test_resolve_block_rows(self):
        # compiled: VMEM-sized tiles; interpret: ~16 fat tiles, 8-aligned
        assert fused_lib.resolve_block_rows(10_000, False) \
            == fused_lib.TPU_BLOCK_ROWS
        br = fused_lib.resolve_block_rows(10_000, True)
        assert br % 8 == 0
        assert -(-10_000 // br) <= 16 + 1
        assert fused_lib.resolve_block_rows(3, True) == 8
        assert fused_lib.resolve_block_rows(10_000, True, 64) == 64
        with pytest.raises(ValueError, match="multiple of 8"):
            fused_lib.resolve_block_rows(100, True, 12)

    def test_pack_roundtrip_and_padding_is_zero(self):
        rng = np.random.RandomState(1)
        leaves = [jnp.asarray(rng.randn(3, 7), jnp.float32),
                  jnp.asarray(rng.randn(130), jnp.float32),
                  jnp.asarray(rng.randn(2, 2, 2), jnp.float32)]
        sizes = [l.size for l in leaves]
        seg = fused_lib.build_segment_map(sizes, [True] * 3)
        buf = fused_lib.pack_flat(leaves, seg)
        assert buf.shape == (seg.num_rows, 128)
        flat = np.asarray(buf).reshape(-1)
        for start, size, padded in zip(seg.starts, seg.sizes, seg.padded):
            np.testing.assert_array_equal(flat[start + size:start + padded],
                                          0.0)
        back = fused_lib.unpack_flat(buf, seg, leaves)
        for a, b in zip(back, leaves):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # grid-tail padding (buffer padded to whole grid tiles) is zero
        # and unpack still drops it
        buf2 = fused_lib.pack_flat(leaves, seg, grid_rows=seg.num_rows + 5)
        assert buf2.shape == (seg.num_rows + 5, 128)
        np.testing.assert_array_equal(
            np.asarray(buf2[seg.num_rows:]), 0.0)
        back2 = fused_lib.unpack_flat(buf2, seg, leaves)
        for a, b in zip(back2, leaves):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_padding_never_contributes_to_norms(self):
        """The kernel's segment norms on block-padded buffers == plain
        numpy norms of the unpadded leaves — for shaped leaves AND for
        the ZeRO-1 shard-local layout (flat-padded leaf tails)."""
        rng = np.random.RandomState(2)
        params = {"k": jnp.asarray(rng.randn(9, 13), jnp.float32),
                  "b": jnp.asarray(rng.randn(10), jnp.float32)}
        grads = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.randn(*p.shape), jnp.float32), params)
        trace = jax.tree_util.tree_map(jnp.zeros_like, params)
        target = jax.tree_util.tree_map(jnp.zeros_like, params)
        wd = 0.01
        _, _, _, trust = fused_lib.fused_lars_ema_update(
            params, grads, trace, target, lr=jnp.float32(0.0),
            tau=jnp.float32(1.0), weight_decay=wd,
            momentum_decay=MOMENTUM_DECAY, interpret=True)
        gp = np.asarray(grads["k"]) + wd * np.asarray(params["k"])
        expect = 1e-3 * np.linalg.norm(np.asarray(params["k"])) \
            / np.linalg.norm(gp)
        np.testing.assert_allclose(np.asarray(trust), [expect], rtol=1e-5)

    def test_local_flat_size_matches_flat_struct(self):
        for shape in [(), (5,), (3, 7), (64, 64)]:
            tmpl = jax.ShapeDtypeStruct(shape, jnp.float32)
            size = math.prod(shape) if shape else 1
            assert (zero1_lib.local_flat_size(tmpl, 8) * 8
                    == zero1_lib.padded_size(size, 8)
                    == zero1_lib.flat_struct(tmpl, 8).shape[0])

    def test_rejects_malformed_maps(self):
        with pytest.raises(ValueError, match="mask slots"):
            fused_lib.build_segment_map([4, 5], [True])
        with pytest.raises(ValueError, match="empty segment"):
            fused_lib.build_segment_map([4, 0], [True, False])


# ---------------------------------------------------------------------------
# telemetry: reported == applied under the fused path (satellite)
# ---------------------------------------------------------------------------

def test_fused_health_trust_stats_match_unfused(mesh8):
    """PR 6 invariant, extended to the kernel: the health vector's trust
    stats under --fused-update on come from the kernel's OWN segment
    norms, and must equal the unfused path's (whose reported==applied is
    pinned in test_telemetry.py) on the same step."""
    _, m_off = _run_arm(mesh8, "off", telemetry="epoch", n=1)
    _, m_on = _run_arm(mesh8, "on", telemetry="epoch", n=1)
    h_off = health_lib.unpack(m_off["health"])
    h_on = health_lib.unpack(m_on["health"])
    for k in ("trust_min", "trust_median", "trust_max", "update_norm",
              "grad_norm", "param_norm", "ema_drift"):
        np.testing.assert_allclose(h_on[k], h_off[k], rtol=1e-4,
                                   err_msg=f"health field {k}")


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------

class TestGating:
    def test_resolve_rejects_unsupported_configs(self):
        for optim_kw, match in [
            (dict(optimizer="lamb"), "LARS wrapper"),
            (dict(optimizer="lars_adam"), "lars_momentum"),
            (dict(optimizer="lars_momentum", clip=1.0), "clip"),
        ]:
            c = config_lib.Config()
            c = c.replace(optim=dataclasses.replace(
                c.optim, fused_update="on", **optim_kw))
            with pytest.raises(ValueError, match=match):
                config_lib.resolve(
                    c, num_train_samples=4096 * 8, num_test_samples=16,
                    output_size=10, input_shape=(IMAGE, IMAGE, 3))

    def test_resolve_rejects_model_parallel(self):
        """The replicated-layout kernel's shard_map uses fully-replicated
        specs — it would silently un-shard TP'd head opt-state leaves
        every step, so fused + model_parallel > 1 must fail fast like
        zero1 + model_parallel does."""
        c = config_lib.Config()
        c = c.replace(
            optim=dataclasses.replace(c.optim, fused_update="on"),
            device=dataclasses.replace(c.device, num_replicas=4,
                                       model_parallel=2))
        with pytest.raises(ValueError, match="model-parallel"):
            config_lib.resolve(
                c, num_train_samples=4096 * 4, num_test_samples=16,
                output_size=10, input_shape=(IMAGE, IMAGE, 3))

    def test_make_train_step_rejects_clip(self):
        """Programmatic callers bypass resolve(); a clip-bearing tx with
        fused_update=True must be rejected at build — the kernel does not
        replicate value clipping, and extract_sgdm_state alone would not
        notice (optax.clip carries an EmptyState)."""
        from byol_tpu.training.build import build_net, build_tx, step_config
        from byol_tpu.training.steps import make_train_step
        rcfg = _rcfg(fused="on")
        scfg = dataclasses.replace(step_config(rcfg), clip=1.0)
        with pytest.raises(ValueError, match="clip"):
            make_train_step(build_net(rcfg), build_tx(rcfg)[0], scfg,
                            lr_schedule=lambda c: 0.1)

    def test_default_config_is_supported(self):
        assert fused_update_unsupported_reason("lars_momentum", 0.0) is None
        assert fused_update_unsupported_reason("LARS_MOMENTUM", 0.0) is None

    def test_make_train_step_requires_schedule(self):
        from byol_tpu.training.build import build_net, build_tx, step_config
        from byol_tpu.training.steps import make_train_step
        rcfg = _rcfg(fused="on")
        scfg = step_config(rcfg)
        assert scfg.fused_update
        with pytest.raises(ValueError, match="lr_schedule"):
            make_train_step(build_net(rcfg), build_tx(rcfg)[0], scfg)

    def test_extract_replace_roundtrip_preserves_structure(self):
        params = {"w": jnp.ones((3, 4)), "b": jnp.zeros((4,))}
        tx, _ = build_optimizer(
            "lars_momentum", base_lr=0.1, global_batch_size=256,
            weight_decay=1e-6, total_units=10, warmup_units=1)
        st = tx.init(params)
        trace, count = extract_sgdm_state(st)
        st2 = replace_sgdm_state(
            st, jax.tree_util.tree_map(lambda x: x + 1.0, trace),
            count + 1)
        assert (jax.tree_util.tree_structure(st2)
                == jax.tree_util.tree_structure(st))
        trace2, count2 = extract_sgdm_state(st2)
        assert int(count2) == 1
        np.testing.assert_array_equal(np.asarray(trace2["w"]),
                                      np.asarray(trace["w"]) + 1.0)

    def test_extract_rejects_foreign_chain(self):
        params = {"w": jnp.ones((3, 4))}
        tx, _ = build_optimizer(
            "adam", base_lr=0.1, global_batch_size=256, weight_decay=0.0,
            total_units=10, warmup_units=1)
        with pytest.raises(ValueError, match="lars_momentum chain"):
            extract_sgdm_state(tx.init(params))
