"""Multi-step LARS parity vs an independent torch implementation.

`tests/test_optim.py` pins single-step LARS behavior against hand-computed
values; this test runs THREE steps against a torch implementation of the
reference's documented semantics (SURVEY.md C5; optimizers/lars.py:88-126:
wd folded into the grad BEFORE the trust ratio, adaptation skipped for
`ignore` (bias/BN) groups, trust ratio applied only when both norms > 0,
inner SGD-momentum with its own wd zeroed) — so the momentum-buffer
interaction across steps, not just one update, is confirmed against an
executable independent oracle.
"""
import numpy as np
import torch

import jax.numpy as jnp
import optax

from byol_tpu.optim.lars import lars

LR, MOM, WD, TRUST, EPS, STEPS = 0.1, 0.9, 1e-2, 1e-3, 0.0, 3


def _torch_lars_trajectory(k0, b0, grads):
    """Reference-semantics LARS+SGD(momentum) in torch, from the SURVEY
    behavioral contract (not a code copy): returns params after each step."""
    kernel = torch.tensor(k0.copy())
    bias = torch.tensor(b0.copy())
    buf = {"kernel": torch.zeros_like(kernel),
           "bias": torch.zeros_like(bias)}
    out = []
    for gk, gb in grads:
        gk = torch.tensor(gk.copy())
        gb = torch.tensor(gb.copy())
        # 1) fold wd into the kernel grad BEFORE adaptation (bias group
        #    carries wd=0 per the add_weight_decay contract)
        gk = gk + WD * kernel
        # 2-3) trust ratio on the kernel only, gated on both norms > 0
        pn, gn = kernel.norm(), gk.norm()
        if pn > 0 and gn > 0:
            gk = gk * (TRUST * pn / (gn + EPS))
        # 4) inner SGD-momentum with wd zeroed
        buf["kernel"] = MOM * buf["kernel"] + gk
        buf["bias"] = MOM * buf["bias"] + gb
        kernel = kernel - LR * buf["kernel"]
        bias = bias - LR * buf["bias"]
        out.append((kernel.numpy().copy(), bias.numpy().copy()))
    return out


class TestLarsMultiStepParity:
    def test_three_steps_match_torch_oracle(self):
        rng = np.random.RandomState(0)
        k0 = rng.randn(4, 3).astype(np.float32)
        b0 = rng.randn(3).astype(np.float32)
        grads = [(rng.randn(4, 3).astype(np.float32),
                  rng.randn(3).astype(np.float32)) for _ in range(STEPS)]

        expected = _torch_lars_trajectory(k0, b0, grads)

        params = {"kernel": jnp.asarray(k0), "bias": jnp.asarray(b0)}
        tx = lars(optax.sgd(LR, momentum=MOM), weight_decay=WD,
                  trust_coefficient=TRUST, eps=EPS)
        state = tx.init(params)
        for (gk, gb), (ek, eb) in zip(grads, expected):
            g = {"kernel": jnp.asarray(gk), "bias": jnp.asarray(gb)}
            updates, state = tx.update(g, state, params)
            params = optax.apply_updates(params, updates)
            np.testing.assert_allclose(np.asarray(params["kernel"]), ek,
                                       atol=1e-6)
            np.testing.assert_allclose(np.asarray(params["bias"]), eb,
                                       atol=1e-6)
