"""End-to-end train/eval step tests on the simulated 8-device mesh.

This is the distributed-without-a-cluster test layer the reference never had
(SURVEY.md §4): the SAME SPMD program that runs on a TPU pod runs here on 8
virtual CPU devices, with XLA inserting the gradient-allreduce / SyncBN
collectives from the GSPMD partitioning.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.core import config as config_lib
from byol_tpu.parallel.mesh import shard_batch_to_mesh
from byol_tpu.training.build import setup_training
from byol_tpu.training.state import create_train_state


def tiny_config(**overrides):
    c = config_lib.Config()
    c = c.replace(
        task=dataclasses.replace(c.task, batch_size=16, epochs=2),
        model=dataclasses.replace(c.model, arch="resnet18",
                                  head_latent_size=64, projection_size=32),
        optim=dataclasses.replace(c.optim, warmup=1, lr=0.1),
        device=dataclasses.replace(c.device, num_replicas=8, half=False),
    )
    for k, v in overrides.items():
        c = c.replace(**{k: v})
    return config_lib.resolve(c, num_train_samples=128, num_test_samples=32,
                              output_size=10, input_shape=(32, 32, 3),
                              representation_size=512)


def make_batch(rcfg, seed=0):
    rng = np.random.RandomState(seed)
    b = rcfg.global_batch_size
    h, w, c = rcfg.input_shape
    return {
        "view1": rng.rand(b, h, w, c).astype(np.float32),
        "view2": rng.rand(b, h, w, c).astype(np.float32),
        "label": rng.randint(0, rcfg.output_size, size=(b,)),
    }


def fresh(state):
    """Deep-copy device state: the train step donates its input buffer
    (donate_argnums), so each test works on its own copy."""
    return jax.tree_util.tree_map(jnp.copy, state)


@pytest.fixture(scope="module")
def training(mesh8_module, step_guard):
    rcfg = tiny_config()
    net, state, train_step, eval_step, sched = setup_training(
        rcfg, mesh8_module, jax.random.PRNGKey(0))
    # Guarded steps: implicit host transfers / tracer leaks inside the step
    # fail here, on CPU, in tier-1 — not on a TPU window (conftest.py).
    return rcfg, (net, state, step_guard(train_step), step_guard(eval_step),
                  sched)


@pytest.fixture(scope="module")
def mesh8_module():
    from byol_tpu.parallel.mesh import MeshSpec, build_mesh
    return build_mesh(MeshSpec(data=8))


class TestTrainStep:
    @pytest.mark.slow
    def test_loss_finite_and_decreasing(self, training, mesh8_module):
        rcfg, (net, state, train_step, eval_step, sched) = training
        state = fresh(state)
        losses = []
        for i in range(8):
            batch = shard_batch_to_mesh(make_batch(rcfg, seed=i % 2),
                                        mesh8_module)
            state, metrics = train_step(state, batch)
            losses.append(float(metrics["loss_mean"]))
        assert all(np.isfinite(losses))
        # The stream alternates TWO fixed batches (seed = i % 2) whose
        # intrinsic loss levels differ by ~0.2 — comparing losses[-1]
        # (a seed-1 step) against losses[0] (a seed-0 step) was the
        # documented flake: it measured the batch gap, not learning.
        # Compare each batch against ITSELF: both parities must trend
        # down over the repeated-data run (deterministic under the fixed
        # seeds; measured margins ~0.07/0.09 at head).
        assert losses[-2] < losses[0]      # seed-0 stream (steps 0 -> 6)
        assert losses[-1] < losses[1]      # seed-1 stream (steps 1 -> 7)

    @pytest.mark.slow
    def test_ema_and_counters_move(self, training, mesh8_module):
        rcfg, (net, state, train_step, _, _) = training
        state = fresh(state)
        batch = shard_batch_to_mesh(make_batch(rcfg), mesh8_module)
        # Step once to get past the warmup's t=0 factor of 0 (LinearWarmup
        # semantics, scheduler.py:45-62: the first unit runs at lr 0, so the
        # very first step legitimately leaves params unchanged).
        state, _ = train_step(state, batch)
        # Read everything BEFORE the next call: the step donates its input.
        before_step = int(state.step)
        before_ema_step = int(state.ema_step)
        tonp = lambda tree: [np.asarray(x)
                             for x in jax.tree_util.tree_leaves(tree)]
        before_target = tonp(state.target_params)
        before_params = tonp(state.params)
        batch = shard_batch_to_mesh(make_batch(rcfg, seed=1), mesh8_module)
        new_state, _ = train_step(state, batch)
        assert int(new_state.step) == before_step + 1
        assert int(new_state.ema_step) == before_ema_step + 1
        after_target = tonp(new_state.target_params)
        after_params = tonp(new_state.params)

        def total_diff(before, after):
            return sum(float(np.sum((a - b) ** 2))
                       for a, b in zip(before, after))

        assert total_diff(before_params, after_params) > 0.0
        assert total_diff(before_target, after_target) > 0.0

    def test_eval_step_metrics(self, training, mesh8_module):
        rcfg, (net, state, train_step, eval_step, _) = training
        state = fresh(state)
        batch = shard_batch_to_mesh(make_batch(rcfg), mesh8_module)
        metrics = eval_step(state, batch)
        for key in ("loss_mean", "byol_loss_mean", "linear_loss_mean",
                    "top1_mean", "top5_mean"):
            assert np.isfinite(float(metrics[key])), key

    def test_eval_does_not_mutate_state(self, training, mesh8_module):
        rcfg, (net, state, _, eval_step, _) = training
        state = fresh(state)
        batch = shard_batch_to_mesh(make_batch(rcfg), mesh8_module)
        bs_before = jax.tree_util.tree_leaves(state.batch_stats)[0].copy()
        _ = eval_step(state, batch)
        bs_after = jax.tree_util.tree_leaves(state.batch_stats)[0]
        np.testing.assert_array_equal(np.asarray(bs_before),
                                      np.asarray(bs_after))


class TestShardingSemantics:
    @pytest.mark.slow
    def test_global_batch_grads_match_single_device(self, mesh8_module,
                                                    step_guard):
        """The sharded step must produce the same result as an unsharded
        oracle on one device — DDP-allreduce + SyncBN equivalence
        (SURVEY.md §4 'distributed-without-a-cluster')."""
        rcfg = tiny_config()
        net, state, train_step, _, _ = setup_training(
            rcfg, mesh8_module, jax.random.PRNGKey(0))
        batch_np = make_batch(rcfg)
        batch = shard_batch_to_mesh(batch_np, mesh8_module)
        sharded_state, sharded_metrics = step_guard(train_step)(state, batch)

        # Single-device oracle: same net/params, jit with no sharding.
        # setup_training derives its init key via split_named (core/rng.py);
        # the oracle must follow the same derivation to share parameters.
        from byol_tpu.core.rng import split_named
        from byol_tpu.training.build import build_net, build_tx, step_config
        from byol_tpu.training.steps import make_train_step
        net1 = build_net(rcfg)
        tx1, _ = build_tx(rcfg)
        init_key = split_named(jax.random.PRNGKey(0),
                               ("params", "weight_init"))["params"]
        variables = net1.init(init_key, jnp.zeros((2, 32, 32, 3)),
                              train=True, method="warmup")
        state1 = create_train_state(variables, tx1)
        step1 = jax.jit(make_train_step(net1, tx1, step_config(rcfg)))
        dev = jax.devices()[0]
        batch1 = jax.device_put(batch_np, dev)
        state1 = jax.device_put(state1, dev)
        _, oracle_metrics = step1(state1, batch1)

        np.testing.assert_allclose(
            float(sharded_metrics["byol_loss_mean"]),
            float(oracle_metrics["byol_loss_mean"]), rtol=2e-4)
        np.testing.assert_allclose(
            float(sharded_metrics["loss_mean"]),
            float(oracle_metrics["loss_mean"]), rtol=2e-4)


class TestStateBuffers:
    def test_optimizer_state_never_aliases_params(self):
        """Optimizers like optax.scale_by_lbfgs store the param ARRAYS
        themselves in their init state; the donated TrainState must not
        contain one buffer twice or Execute() rejects the donation."""
        import optax
        params = {"w": jnp.ones((3,))}
        aliasing_tx = optax.GradientTransformation(
            init=lambda p: {"prev_params": p},     # aliases every param leaf
            update=lambda g, s, p=None: (g, s))
        st = create_train_state({"params": params}, aliasing_tx)
        leaf_ids = [id(x) for x in jax.tree_util.tree_leaves(st)
                    if isinstance(x, jax.Array)]
        assert len(leaf_ids) == len(set(leaf_ids))
        np.testing.assert_array_equal(
            np.asarray(st.opt_state["prev_params"]["w"]),
            np.asarray(st.params["w"]))


class TestNormalizeInputs:
    def test_imagenet_standardization_math(self):
        from byol_tpu.training.steps import (IMAGENET_MEAN, IMAGENET_STD,
                                             normalize_images)
        x = jnp.full((1, 2, 2, 3), 0.5, jnp.float32)
        y = np.asarray(normalize_images(x))
        expect = (0.5 - np.array(IMAGENET_MEAN)) / np.array(IMAGENET_STD)
        np.testing.assert_allclose(y[0, 0, 0], expect, rtol=1e-6)

    def test_grayscale_fallback_uses_channel_mean(self):
        from byol_tpu.training.steps import (IMAGENET_MEAN, IMAGENET_STD,
                                             normalize_images)
        g = jnp.full((1, 2, 2, 1), 0.5, jnp.float32)
        y = np.asarray(normalize_images(g))
        assert y.shape == (1, 2, 2, 1)
        expect = (0.5 - np.mean(IMAGENET_MEAN)) / np.mean(IMAGENET_STD)
        np.testing.assert_allclose(y[0, 0, 0, 0], expect, rtol=1e-6)

    def test_extractor_normalize_matches_manual(self, training):
        """The linear-eval extractor's normalize=True must equal feeding
        pre-normalized pixels to normalize=False — the trained input
        contract is ONE function (steps.normalize_images), not two
        implementations drifting apart."""
        from byol_tpu.training.linear_eval import encoder_apply_fn
        from byol_tpu.training.steps import normalize_images
        rcfg, (net, state, _, _, _) = training
        state = fresh(state)
        x = jnp.asarray(make_batch(rcfg)["view1"][:8])
        f_norm = encoder_apply_fn(net, state, normalize=True)(x)
        f_manual = encoder_apply_fn(net, state,
                                    normalize=False)(normalize_images(x))
        np.testing.assert_allclose(np.asarray(f_norm),
                                   np.asarray(f_manual), atol=1e-5)

    def test_step_config_carries_the_knob(self):
        import dataclasses as dc
        from byol_tpu.training.build import step_config
        rcfg = tiny_config()
        assert step_config(rcfg).normalize_inputs is False
        c = rcfg.cfg.replace(
            parity=dc.replace(rcfg.cfg.parity, normalize_inputs=True))
        rcfg_on = dc.replace(rcfg, cfg=c)
        assert step_config(rcfg_on).normalize_inputs is True


class TestParityModes:
    def test_reference_ema_init(self, mesh8_module):
        rcfg = tiny_config()
        from byol_tpu.training.build import build_net, build_tx
        net = build_net(rcfg)
        tx, _ = build_tx(rcfg)
        variables = net.init(jax.random.PRNGKey(0),
                             jnp.zeros((2, 32, 32, 3)), train=True,
                             method="warmup")
        # Quirk Q1: reference init => target = 0.004 * theta, ema_step = 1.
        st = create_train_state(variables, tx, ema_init_mode="reference")
        p = jax.tree_util.tree_leaves(variables["params"])[0]
        t = jax.tree_util.tree_leaves(st.target_params)[0]
        np.testing.assert_allclose(np.asarray(t), 0.004 * np.asarray(p),
                                   rtol=1e-6)
        assert int(st.ema_step) == 1
        # copy init: exact copy, step 0
        st2 = create_train_state(variables, tx, ema_init_mode="copy")
        t2 = jax.tree_util.tree_leaves(st2.target_params)[0]
        np.testing.assert_array_equal(np.asarray(t2), np.asarray(p))
        assert int(st2.ema_step) == 0
