"""byol_tpu/serving/ — the embedding service (ISSUE 8 tentpole).

Four layers, cheapest first:

1. **Buckets**: the pad-to-power-of-two vocabulary is total and unique —
   every request row count maps to exactly ONE bucket (the property that
   makes the compile count an invariant rather than a load artifact).
2. **Batcher**: pure host-side policy — coalescing, the max-wait flush
   deadline, overflow carry, bounded-queue backpressure, drain-on-close.
3. **Engine/service correctness**: served embeddings BITWISE-match the
   linear-eval extractor for the same checkpoint and inputs (the serving
   path may add batching, padding, sharding, and AOT compilation, but it
   must never add numerics), under the guard_steps transfer guard; the
   checkpoint restores onto FEWER devices than it trained on.
4. **Compile discipline**: compile count == number of distinct buckets
   touched, and warmed steady-state serving issues ZERO recompiles (the
   GL102 hazard pinned at runtime).
"""
import threading
import time
import types

import numpy as np
import pytest

import jax

from byol_tpu.serving.batcher import (Backpressure, DynamicBatcher,
                                      ServiceClosed)
from byol_tpu.serving.buckets import BucketSpec
from byol_tpu.serving.meter import ServingMeter, serve_log_line
from byol_tpu.serving.service import EmbeddingService
from tests.conftest import guard_steps


# ---------------------------------------------------------------------------
# 1. buckets
# ---------------------------------------------------------------------------

class TestBuckets:
    def test_every_row_count_maps_to_exactly_one_bucket(self):
        spec = BucketSpec(min_bucket=8, max_bucket=64)
        assert spec.sizes == (8, 16, 32, 64)
        for n in range(1, 65):
            b = spec.bucket_for(n)
            # coverage: the bucket holds the rows
            assert b in spec.sizes and b >= n
            # uniqueness/minimality: no SMALLER bucket could hold them,
            # so no other bucket can be "the" bucket for n
            smaller = [s for s in spec.sizes if s < b]
            assert all(s < n for s in smaller)
            # determinism
            assert spec.bucket_for(n) == b

    def test_single_bucket_spec(self):
        spec = BucketSpec(min_bucket=16, max_bucket=16)
        assert spec.sizes == (16,)
        assert spec.bucket_for(1) == 16 and spec.bucket_for(16) == 16

    def test_validation(self):
        with pytest.raises(ValueError):
            BucketSpec(min_bucket=6, max_bucket=64)      # not a pow2
        with pytest.raises(ValueError):
            BucketSpec(min_bucket=32, max_bucket=8)      # inverted
        spec = BucketSpec(min_bucket=8, max_bucket=32)
        with pytest.raises(ValueError):
            spec.bucket_for(33)                          # over the ceiling
        with pytest.raises(ValueError):
            spec.bucket_for(0)


# ---------------------------------------------------------------------------
# 2. batcher (no jax anywhere)
# ---------------------------------------------------------------------------

def _img(rows=1, size=4):
    return np.zeros((rows, size, size, 3), np.float32)


class TestBatcher:
    def test_coalesces_up_to_max_batch(self):
        b = DynamicBatcher(max_batch=8, max_wait_s=0.2)
        for _ in range(4):
            b.submit(_img(2), timeout=0.1)
        batch = b.next_batch()
        assert len(batch) == 4
        assert sum(r.rows for r in batch) == 8

    def test_overflow_request_is_carried_never_split(self):
        b = DynamicBatcher(max_batch=8, max_wait_s=0.2)
        b.submit(_img(6), timeout=0.1)
        b.submit(_img(5), timeout=0.1)     # 6+5 > 8: must not join
        first = b.next_batch()
        assert [r.rows for r in first] == [6]
        second = b.next_batch()
        assert [r.rows for r in second] == [5]

    def test_max_wait_deadline_flushes_partial_batch(self):
        b = DynamicBatcher(max_batch=64, max_wait_s=0.05)
        b.submit(_img(2), timeout=0.1)
        t0 = time.perf_counter()
        batch = b.next_batch()
        waited = time.perf_counter() - t0
        assert sum(r.rows for r in batch) == 2       # flushed well short
        assert waited < 5.0                          # of max_batch
        # and the deadline actually gated the flush (>= max_wait, minus
        # scheduler slop)
        assert waited >= 0.04

    def test_backpressure_when_queue_full(self):
        b = DynamicBatcher(max_batch=4, max_queue=2, max_wait_s=0.01)
        b.submit(_img(), timeout=0.1)
        b.submit(_img(), timeout=0.1)
        with pytest.raises(Backpressure):
            b.submit(_img(), timeout=0.05)
        # draining one frees a slot
        assert b.next_batch() is not None
        b.submit(_img(), timeout=0.5)

    def test_oversized_and_empty_requests_rejected(self):
        b = DynamicBatcher(max_batch=4)
        with pytest.raises(ValueError):
            b.submit(_img(5), timeout=0.1)
        with pytest.raises(ValueError):
            b.submit(_img(0), timeout=0.1)
        with pytest.raises(ValueError):
            b.submit(np.zeros((4, 4), np.float32), timeout=0.1)

    def test_single_image_lifted_to_one_row(self):
        b = DynamicBatcher(max_batch=4, max_wait_s=0.01)
        req = b.submit(np.zeros((4, 4, 3), np.float32), timeout=0.1)
        assert req.rows == 1
        assert b.next_batch()[0] is req

    def test_close_drains_then_ends(self):
        b = DynamicBatcher(max_batch=2, max_wait_s=0.01)
        b.submit(_img(), timeout=0.1)
        b.close()
        with pytest.raises(ServiceClosed):
            b.submit(_img(), timeout=0.1)
        assert b.next_batch() is not None    # queued work still served
        assert b.next_batch(poll_s=0.01) is None

    def test_fail_pending_resolves_raced_requests(self):
        """A submit that raced close() into an already-drained queue (the
        TOCTOU between the closed-check and the put) must still get its
        future RESOLVED — fail_pending covers the queue AND the carry
        slot, so no client can block forever on stop()."""
        b = DynamicBatcher(max_batch=8, max_wait_s=0.01)
        raced = b.submit(_img(), timeout=0.1)
        b.submit(_img(6), timeout=0.1)
        b.submit(_img(5), timeout=0.1)       # 1+6+5 > 8: carried
        b.next_batch()                        # drains 1+6, carries the 5
        assert b.fail_pending(ServiceClosed("stopped")) == 1   # the carry
        b._q.put(raced)                       # simulate the raced put
        assert b.fail_pending(ServiceClosed("stopped")) == 1
        with pytest.raises(ServiceClosed):
            raced.result(timeout=0.1)


# ---------------------------------------------------------------------------
# 3. meter + events
# ---------------------------------------------------------------------------

class TestServingMeter:
    def test_window_stats_and_reset(self):
        m = ServingMeter()
        t0 = 100.0
        m.record_batch(rows=6, bucket=8, t_now=t0)
        for lat in (0.010, 0.020, 0.030):
            m.record_latency(lat)
        m.record_enqueue(2)
        snap = m.snapshot(t0 + 1.0, reset=True)
        assert snap["requests"] == 3 and snap["batches"] == 1
        assert snap["fill_ratio"] == pytest.approx(6 / 8)
        assert snap["p50_ms"] == pytest.approx(20.0)
        assert snap["queue_depth"] == 2.0
        assert snap["rows_per_sec"] == pytest.approx(6.0)
        # window reset: empty stats, lifetime totals kept
        empty = m.snapshot(t0 + 2.0, reset=False)
        assert empty["requests"] == 0 and np.isnan(empty["p50_ms"])
        assert m.total_requests == 3 and m.total_batches == 1
        # the log line renders NaN windows without crashing
        assert "serve[" in serve_log_line(empty)

    def test_quantiles_at_small_sample_counts(self):
        """p50 <= p99 must hold from the FIRST sample on — tail math over
        one or two latencies (a cold service's first stats window) must
        interpolate, never crash or invert (ISSUE 9 satellite)."""
        m = ServingMeter()
        m.record_latency(0.010)
        one = m.snapshot(1.0, reset=False)
        assert one["p50_ms"] == pytest.approx(10.0)
        assert one["p99_ms"] == pytest.approx(10.0)      # 1 sample: p50==p99
        m.record_latency(0.030)
        two = m.snapshot(2.0, reset=True)
        assert two["requests"] == 2
        assert two["p50_ms"] <= two["p99_ms"] <= 30.0 + 1e-9
        m.record_latency(0.005)
        m.record_latency(0.007)
        m.record_latency(0.009)
        three = m.snapshot(3.0, reset=True)
        assert three["p50_ms"] == pytest.approx(7.0)
        assert three["p50_ms"] <= three["p99_ms"]

    def test_snapshot_under_load_never_drops_or_inverts(self):
        """Concurrent record_latency vs snapshot(reset=True): every sample
        lands in exactly ONE window (nothing lost to a reset race) and
        every window's percentiles stay ordered (ISSUE 9 satellite)."""
        m = ServingMeter()
        n_threads, per_thread = 4, 500
        stop = threading.Event()
        windows = []

        def producer(idx):
            rng = np.random.RandomState(idx)
            for _ in range(per_thread):
                m.record_latency(float(rng.uniform(0.001, 0.050)))

        def reader():
            while not stop.is_set():
                windows.append(m.snapshot(time.perf_counter(), reset=True))

        threads = [threading.Thread(target=producer, args=(i,))
                   for i in range(n_threads)]
        snap_thread = threading.Thread(target=reader)
        snap_thread.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        snap_thread.join()
        windows.append(m.snapshot(time.perf_counter(), reset=True))
        counted = sum(int(w["requests"]) for w in windows)
        assert counted == n_threads * per_thread     # reset drops nothing
        assert m.total_requests == n_threads * per_thread
        for w in windows:
            if w["requests"]:
                assert w["p50_ms"] <= w["p99_ms"] + 1e-9

    def test_lifecycle_phase_breakdown(self):
        """record_lifecycle folds per-request phase deltas into window
        means; snapshot exposes them as the additive ``phase_ms`` field
        and reset clears them."""
        m = ServingMeter()
        m.record_latency(0.010)
        m.record_lifecycle({"coalesce": 0.004, "stage": 0.001,
                            "dispatch": 0.003, "readback": 0.001,
                            "deliver": 0.001})
        m.record_lifecycle({"coalesce": 0.002, "stage": 0.001,
                            "dispatch": 0.001, "readback": 0.001,
                            "deliver": 0.001})
        snap = m.snapshot(1.0, reset=True)
        assert snap["phase_ms"]["coalesce"] == pytest.approx(3.0)
        assert snap["phase_ms"]["dispatch"] == pytest.approx(2.0)
        empty = m.snapshot(2.0, reset=False)
        assert "phase_ms" not in empty               # window reset cleared

    def test_serve_stats_event_roundtrip(self, tmp_path):
        from byol_tpu.observability.events import RunLog, read_events
        m = ServingMeter()
        m.record_batch(rows=4, bucket=8, t_now=1.0)
        m.record_latency(0.005)
        path = str(tmp_path / "serve.jsonl")
        with RunLog(path) as log:
            m.emit(log, 2.0, compile_count=3, streams=8)
            # an EMPTY window must also produce a valid line (NaN
            # percentiles -> "NaN" strings, still schema-valid)
            m.emit(log, 3.0)
        events = list(read_events(path))
        assert [e["kind"] for e in events] == ["serve_stats", "serve_stats"]
        assert events[0]["requests"] == 1 and events[0]["compile_count"] == 3
        assert events[1]["p50_ms"] == "NaN"


# ---------------------------------------------------------------------------
# 4. engine + service on the mesh (one shared model/checkpoint setup)
# ---------------------------------------------------------------------------

_NUM_CLASSES = 10


def _serve_cfg():
    from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                      TaskConfig)
    return Config(
        task=TaskConfig(task="fake", batch_size=16, epochs=2,
                        image_size_override=16),
        model=ModelConfig(arch="resnet18", head_latent_size=32,
                          projection_size=16),
        device=DeviceConfig(num_replicas=8, half=False, seed=0),
    )


@pytest.fixture(scope="module")
def served(mesh8, tmp_path_factory):
    """Train-state on the 8-device mesh -> checkpoint -> serving restore
    onto a 4-device mesh (FEWER devices than it trained on) -> a built
    (unstarted) service plus the pieces the tests compare against."""
    from byol_tpu.checkpoint import CheckpointStore
    from byol_tpu.core.config import resolve
    from byol_tpu.parallel.compile_plan import build_plan
    from byol_tpu.parallel.mesh import MeshSpec, build_mesh
    from byol_tpu.serving.engine import ServingEngine
    from byol_tpu.serving.service import (ServeConfig, build_service,
                                          restore_params_for_serving)
    from byol_tpu.training.build import build_net, build_tx, init_variables
    from byol_tpu.training.state import create_train_state

    cfg = _serve_cfg()
    rcfg = resolve(cfg, num_train_samples=64, num_test_samples=16,
                   output_size=_NUM_CLASSES, input_shape=(16, 16, 3))
    net = build_net(rcfg)
    plan8 = build_plan(mesh8)
    with mesh8:
        variables = init_variables(net, rcfg, jax.random.PRNGKey(0))
        tx, _ = build_tx(rcfg)
        state = create_train_state(variables, tx)
    state, _ = plan8.prepare_state(state, tx)

    ckpt_dir = str(tmp_path_factory.mktemp("serve") / "ckpt")
    store = CheckpointStore(ckpt_dir)
    store.save(0, plan8.to_canonical(state))   # identity for a replicated
    store._ckptr.wait_until_finished()         # plan; mesh-size portable
    store.close()

    mesh4 = build_mesh(MeshSpec(data=4), jax.devices()[:4])
    net_s, params, batch_stats, epoch = restore_params_for_serving(
        cfg, ckpt_dir, mesh4, num_classes=_NUM_CLASSES)
    assert epoch == 0
    service = build_service(
        cfg, ServeConfig(min_bucket=8, max_bucket=16, max_wait_ms=2.0,
                         num_classes=_NUM_CLASSES),
        checkpoint_dir=ckpt_dir, mesh=mesh4)
    yield types.SimpleNamespace(
        cfg=cfg, net=net_s, params=params, batch_stats=batch_stats,
        service=service, mesh4=mesh4, ckpt_dir=ckpt_dir)
    service.batcher.close()


def _extractor_features(served, images16):
    """The linear-eval ground truth: extract_features over the SAME
    restored checkpoint params (the offline-protocol path serving must
    bitwise-reproduce)."""
    from byol_tpu.training.linear_eval import (encoder_apply_fn,
                                               extract_features)
    state = types.SimpleNamespace(params=served.params,
                                  batch_stats=served.batch_stats)
    apply_fn = encoder_apply_fn(served.net, state, half=False,
                                normalize=False)
    feats, labels = extract_features(
        apply_fn,
        iter([{"view1": images16,
               "label": np.arange(len(images16), dtype=np.int32)}]))
    return feats


class TestServingCorrectness:
    def test_served_embeddings_bitwise_match_linear_eval(self, served):
        """The acceptance pin: batching, bucket padding, data-sharding,
        donation, and AOT compilation may change WHERE the flops run, but
        not a single bit of the embeddings the user gets — and the hot
        path runs clean under the guard_steps transfer guard (explicit
        device_put/device_get only)."""
        rng = np.random.RandomState(7)
        images = rng.rand(16, 16, 16, 3).astype(np.float32)
        expected = _extractor_features(served, images)

        engine = served.service.engine
        # exact-fill bucket (16 rows -> bucket 16)
        got_full = guard_steps(engine.embed)(images)
        np.testing.assert_array_equal(got_full, expected)
        # padded bucket (11 rows -> bucket 16, 5 pad rows sliced off):
        # pad rows must never bleed into real rows
        got_padded = guard_steps(engine.embed)(images[:11])
        np.testing.assert_array_equal(got_padded, expected[:11])
        # and below the floor (3 rows -> bucket 8)
        got_small = guard_steps(engine.embed)(images[:3])
        np.testing.assert_array_equal(got_small, expected[:3])

    def test_full_service_roundtrip_matches_too(self, served):
        """Same pin through the THREADED path: queue -> coalesce ->
        worker -> futures (the engine test above bypasses the batcher) —
        and every request that came back carries its COMPLETE lifecycle
        (enqueue -> coalesce -> stage -> dispatch -> readback -> deliver,
        monotonic, with a unique trace id): the ISSUE 9 acceptance pin
        that serving spans cover the full request path under the same
        scenario as the bitwise-parity check."""
        from byol_tpu.serving.batcher import LIFECYCLE_PHASES
        rng = np.random.RandomState(8)
        images = rng.rand(6, 16, 16, 3).astype(np.float32)
        expected = _extractor_features(served, images)
        svc = served.service
        if svc._thread is None:
            svc.start(warmup=True)
        reqs = [svc.submit(images[i]) for i in range(6)]
        got = np.stack([r.result(timeout=120.0)[0] for r in reqs])
        np.testing.assert_array_equal(got, expected)
        assert len({r.trace_id for r in reqs}) == len(reqs)
        for r in reqs:
            stamps = [r.marks[p] for p in LIFECYCLE_PHASES]
            assert len(stamps) == len(LIFECYCLE_PHASES)   # all phases hit
            assert stamps == sorted(stamps)               # causal order
            # the phase deltas reconstruct the meter's latency sample
            assert sum(r.lifecycle().values()) == pytest.approx(
                r.marks["deliver"] - r.marks["enqueue"])

    def test_restored_onto_fewer_devices(self, served):
        """The checkpoint trained on 8 devices; the serving mesh has 4 —
        the canonical codec makes that a non-event."""
        assert len(served.mesh4.devices.flat) == 4
        assert served.service.engine._plan.num_shards == 4


class TestBuildServiceValidation:
    def test_bad_bucket_config_fails_before_model_build(self, mesh8):
        """A bucket vocabulary incompatible with the serving mesh must be
        an immediate, actionable ValueError — not a traceback after the
        encoder build / checkpoint restore has already been paid."""
        import time as _time

        from byol_tpu.serving.service import ServeConfig, build_service
        t0 = _time.perf_counter()
        with pytest.raises(ValueError, match="multiple of the serving"):
            build_service(_serve_cfg(),
                          ServeConfig(min_bucket=4, max_bucket=16),
                          mesh=mesh8)          # 4 % 8 != 0
        assert _time.perf_counter() - t0 < 5.0   # pre-build fail-fast


class TestCompileDiscipline:
    def test_compile_count_equals_distinct_buckets_touched(self, served):
        """Lazy path (no warmup): the engine compiles exactly once per
        DISTINCT bucket, never per distinct request size."""
        from byol_tpu.parallel.compile_plan import build_plan
        from byol_tpu.serving.engine import ServingEngine
        from byol_tpu.training.linear_eval import frozen_representation_fn

        represent = frozen_representation_fn(
            served.net, served.params, served.batch_stats,
            half=False, normalize=False)
        engine = ServingEngine(
            represent, build_plan(served.mesh4), input_shape=(16, 16, 3),
            buckets=BucketSpec(min_bucket=8, max_bucket=16))
        rng = np.random.RandomState(0)
        assert engine.compile_count == 0
        touched = set()
        for rows in (3, 5, 1, 8, 7):          # all -> bucket 8
            engine.embed(rng.rand(rows, 16, 16, 3).astype(np.float32))
            touched.add(engine.buckets.bucket_for(rows))
        assert engine.compile_count == len(touched) == 1
        for rows in (9, 16, 12):              # all -> bucket 16
            engine.embed(rng.rand(rows, 16, 16, 3).astype(np.float32))
            touched.add(engine.buckets.bucket_for(rows))
        assert engine.compile_count == len(touched) == 2

    def test_zero_recompiles_after_warmup_steady_state(self, served):
        """The acceptance pin: a warmed service answers an arbitrary mix
        of request sizes with the compile counter FROZEN."""
        svc = served.service
        if svc._thread is None:
            svc.start(warmup=True)
        else:
            svc.engine.warmup()
        warm = svc.engine.compile_count
        assert warm == len(svc.engine.buckets.sizes)
        rng = np.random.RandomState(1)
        reqs = [svc.submit(
                    rng.rand(int(rng.randint(1, 17)), 16, 16, 3)
                    .astype(np.float32), timeout=10.0)
                for _ in range(24)]
        for r in reqs:
            r.result(timeout=120.0)
        assert svc.engine.compile_count == warm
        # and the meter saw it all
        assert svc.meter.total_requests >= 24


class _StubEngine:
    """Engine double for service-policy tests: instant, jax-free.

    Implements the worker's REAL surface (dispatch/readback, the
    pipelined split) — dispatch "computes" eagerly and readback hands the
    result over, so the stub exercises the worker's in-flight plumbing
    without an accelerator."""

    input_shape = (4, 4, 3)              # matches _img()'s default rows

    def __init__(self, fail_rows=(), dispatch_delay_s=0.0):
        self.buckets = BucketSpec(min_bucket=8, max_bucket=16)
        self.compile_count = len(self.buckets.sizes)
        self.fail_rows = set(fail_rows)
        self.dispatch_delay_s = dispatch_delay_s
        self.max_concurrent_inflight = 0
        self._inflight = 0

    def dispatch(self, rows, timeline=None):
        if rows.shape[0] in self.fail_rows:
            raise RuntimeError(f"boom at {rows.shape[0]} rows")
        if self.dispatch_delay_s:
            time.sleep(self.dispatch_delay_s)
        if timeline is not None:
            t = time.perf_counter()
            timeline.update(stage=t, dispatch=t)
        self._inflight += 1
        self.max_concurrent_inflight = max(self.max_concurrent_inflight,
                                           self._inflight)
        out = rows.reshape(rows.shape[0], -1)[:, :4].astype(np.float32)
        return types.SimpleNamespace(
            out=out, rows=int(rows.shape[0]),
            bucket=self.buckets.bucket_for(rows.shape[0]))

    def readback(self, inflight, timeline=None):
        self._inflight -= 1
        if timeline is not None:
            timeline["readback"] = time.perf_counter()
        return inflight.out

    def embed(self, rows, timeline=None):
        return self.readback(self.dispatch(rows, timeline), timeline)


class TestServicePolicy:
    def test_engine_failure_hits_only_that_batch(self):
        """An embed failure is relayed to the requests in THAT batch;
        the worker keeps serving the queue behind them."""
        svc = EmbeddingService(
            _StubEngine(fail_rows=(2,)),
            DynamicBatcher(max_batch=16, max_wait_s=0.01))
        svc.start(warmup=False)
        bad = [svc.submit(_img()) for _ in range(2)]      # coalesce to 2
        for r in bad:
            with pytest.raises(RuntimeError, match="boom"):
                r.result(timeout=10.0)
        time.sleep(0.05)                   # let the failed flush clear
        ok = svc.submit(_img(3))
        assert ok.result(timeout=10.0).shape == (3, 4)
        svc.stop()

    def test_stop_drains_accepted_requests(self):
        svc = EmbeddingService(
            _StubEngine(), DynamicBatcher(max_batch=16, max_wait_s=0.01))
        svc.start(warmup=False)
        reqs = [svc.submit(_img()) for _ in range(5)]
        svc.stop()
        for r in reqs:
            assert r.result(timeout=1.0).shape == (1, 4)
        with pytest.raises(ServiceClosed):
            svc.submit(_img())

    def test_result_return_is_a_meter_barrier(self):
        """By the time result() returns, the request's latency sample is
        already in the meter — a caller that joins its clients and
        immediately snapshots (the bench rungs, the CLI smoke) must not
        race the worker's bookkeeping."""
        svc = EmbeddingService(
            _StubEngine(), DynamicBatcher(max_batch=16, max_wait_s=0.001))
        svc.start(warmup=False)
        for i in range(5):
            svc.embed(_img(), timeout=10.0)
            assert svc.meter.total_requests == i + 1
        svc.stop()

    def test_mismatched_shape_rejected_in_client_thread(self):
        """A wrong-sized image is THAT client's ValueError at submit —
        it must never coalesce with valid requests and kill the worker
        (which would strand every future behind it)."""
        svc = EmbeddingService(
            _StubEngine(), DynamicBatcher(max_batch=16, max_wait_s=0.01))
        svc.start(warmup=False)
        with pytest.raises(ValueError, match="do not match"):
            svc.submit(np.zeros((8, 8, 3), np.float32))
        # the worker is alive and serving
        assert svc.embed(_img(), timeout=10.0).shape == (1, 4)
        svc.stop()

    def test_stop_racing_submits_strands_no_future(self):
        """Hammer close() against concurrent submitters: every Request a
        submit RETURNED must resolve (result or ServiceClosed) — the
        close-lock + fail_pending contract under real contention."""
        svc = EmbeddingService(
            _StubEngine(), DynamicBatcher(max_batch=16, max_wait_s=0.001))
        svc.start(warmup=False)
        accepted, lock = [], threading.Lock()

        def spam():
            while True:
                try:
                    req = svc.submit(_img(), timeout=0.05)
                except ServiceClosed:
                    return
                except Exception:
                    continue        # Backpressure: retry
                with lock:
                    accepted.append(req)

        threads = [threading.Thread(target=spam) for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        svc.stop()
        for t in threads:
            t.join(timeout=5.0)
        assert accepted
        for req in accepted:
            try:
                out = req.result(timeout=1.0)   # must NOT TimeoutError
                assert out.shape == (1, 4)
            except ServiceClosed:
                pass                            # refused is resolved too

    def test_padded_result_owns_its_rows(self, served):
        """engine.embed's padded-bucket result is a COPY, not a view of
        the full (bucket, D) buffer — a held single-row result must not
        pin bucket-times its own memory."""
        rng = np.random.RandomState(9)
        out = served.service.engine.embed(
            rng.rand(3, 16, 16, 3).astype(np.float32))   # bucket 8, n=3
        assert out.base is None

    def test_lifecycle_spans_and_trace_ids_through_worker(self):
        """The per-request flight path through the REAL worker loop (stub
        engine): coalesced requests share batch-level stage/dispatch/
        readback stamps, each keeps its own enqueue, the worker's
        serve/batch span carries the members' trace ids, and phase means
        reach the serve_stats snapshot."""
        from byol_tpu.observability import spans as spans_lib
        from byol_tpu.serving.batcher import LIFECYCLE_PHASES
        rec = spans_lib.SpanRecorder()
        svc = EmbeddingService(
            _StubEngine(), DynamicBatcher(max_batch=16, max_wait_s=0.01),
            recorder=rec)
        svc.start(warmup=False)
        reqs = [svc.submit(_img()) for _ in range(3)]
        for r in reqs:
            r.result(timeout=10.0)
        svc.stop()
        for r in reqs:
            assert set(LIFECYCLE_PHASES) <= set(r.marks)
            stamps = [r.marks[p] for p in LIFECYCLE_PHASES]
            assert stamps == sorted(stamps)
        batch_spans = [s for s in rec.records() if s.name == "serve/batch"]
        assert batch_spans
        spanned_ids = {tid for s in batch_spans
                       for tid in s.attrs["trace_ids"]}
        assert {r.trace_id for r in reqs} <= spanned_ids
        # lifetime totals prove the breakdown was fed once per request
        assert svc.meter.total_requests == 3

    def test_failed_request_keeps_partial_lifecycle(self):
        """An engine failure resolves the future with the error; the
        request still carries the phases it reached (enqueue/coalesce) —
        the post-mortem breadcrumb — and never a deliver stamp."""
        svc = EmbeddingService(
            _StubEngine(fail_rows=(2,)),
            DynamicBatcher(max_batch=16, max_wait_s=0.01))
        svc.start(warmup=False)
        bad = [svc.submit(_img()) for _ in range(2)]
        for r in bad:
            with pytest.raises(RuntimeError, match="boom"):
                r.result(timeout=10.0)
        for r in bad:
            assert "enqueue" in r.marks and "coalesce" in r.marks
            assert "deliver" not in r.marks
        svc.stop()

    def test_concurrent_streams_all_answered(self):
        svc = EmbeddingService(
            _StubEngine(), DynamicBatcher(max_batch=16, max_wait_s=0.002))
        svc.start(warmup=False)
        done = []
        lock = threading.Lock()

        def stream(n):
            for _ in range(n):
                out = svc.embed(_img(), timeout=30.0)
                with lock:
                    done.append(out.shape)

        threads = [threading.Thread(target=stream, args=(10,))
                   for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        svc.stop()
        assert len(done) == 80 and set(done) == {(1, 4)}
        assert svc.meter.total_requests == 80


# ---------------------------------------------------------------------------
# 5. async dispatch pipelining (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

class TestBatcherNonblockingProbe:
    def test_empty_vs_closed_vs_batch(self):
        """next_batch(block=False) distinguishes the three worker states:
        a batch when traffic is queued, EMPTY when open-but-idle (read
        back in-flight work now), None when closed AND drained (exit)."""
        from byol_tpu.serving.batcher import EMPTY
        b = DynamicBatcher(max_batch=8, max_wait_s=0.001)
        assert b.next_batch(block=False) is EMPTY
        b.submit(_img(), timeout=0.1)
        batch = b.next_batch(block=False)
        assert batch is not EMPTY and len(batch) == 1
        b.submit(_img(6), timeout=0.1)
        b.submit(_img(5), timeout=0.1)        # 6+5 > 8: carried
        b.next_batch(block=False)
        assert [r.rows for r in b.next_batch(block=False)] == [5]  # carry
        b.close()                             # counts as available
        assert b.next_batch(block=False) is None

    def test_trace_id_override(self):
        """A caller-supplied trace id (the wire's X-Request-Id) rides the
        request verbatim; absent, the counter assigns one."""
        b = DynamicBatcher(max_batch=8)
        req = b.submit(_img(), timeout=0.1, trace_id="wire-77")
        assert req.trace_id == "wire-77"
        auto = b.submit(_img(), timeout=0.1)
        assert isinstance(auto.trace_id, int)


class TestDispatchPipelining:
    def test_results_map_to_their_requests_and_match_unpipelined(self):
        """Same distinct-valued burst through pipeline off and on: every
        request gets ITS OWN rows back (no reordering, no cross-batch
        mixup) and the two modes' results are identical."""
        outs = {}
        for pipeline in ("off", "on"):
            svc = EmbeddingService(
                _StubEngine(),
                DynamicBatcher(max_batch=16, max_wait_s=0.005),
                pipeline=pipeline)
            reqs = []
            for i in range(40):   # > 2 batches: the pipeline must turn over
                img = np.full((1, 4, 4, 3), float(i), np.float32)
                reqs.append(svc.batcher.submit(img, timeout=1.0))
            svc.start(warmup=False)
            got = np.stack([r.result(timeout=30.0)[0] for r in reqs])
            svc.stop()
            np.testing.assert_array_equal(got,
                                          np.repeat(np.arange(40.0,
                                                    dtype=np.float32)[:, None],
                                                    4, axis=1))
            outs[pipeline] = got
        np.testing.assert_array_equal(outs["off"], outs["on"])

    def test_pipelined_worker_overlaps_two_batches(self):
        """The mechanism pin: with pipelining on, the worker dispatches
        batch i+1 BEFORE reading back batch i (stub engine observes two
        concurrent in-flight batches); with it off, never."""
        for pipeline, expected_max in (("off", 1), ("on", 2)):
            engine = _StubEngine()
            svc = EmbeddingService(
                engine, DynamicBatcher(max_batch=16, max_wait_s=0.005),
                pipeline=pipeline)
            # enqueue a burst BEFORE starting the worker: > max_batch rows
            # guarantees at least two coalesced batches back-to-back
            reqs = [svc.batcher.submit(_img(), timeout=1.0)
                    for _ in range(24)]
            svc.start(warmup=False)
            for r in reqs:
                r.result(timeout=30.0)
            svc.stop()
            assert engine.max_concurrent_inflight == expected_max, pipeline

    def test_pipelined_stop_drains_dispatched_batches(self):
        """stop() during a pipelined burst still resolves EVERY accepted
        request — dispatched-but-unread batches are read back on the
        drain path, not dropped."""
        svc = EmbeddingService(
            _StubEngine(dispatch_delay_s=0.002),
            DynamicBatcher(max_batch=8, max_wait_s=0.001),
            pipeline="on")
        svc.start(warmup=False)
        reqs = [svc.submit(_img()) for _ in range(30)]
        svc.stop()
        for r in reqs:
            assert r.result(timeout=1.0).shape == (1, 4)

    def test_pipeline_bitwise_parity_on_real_engine(self, served):
        """Off vs on around the SAME warmed engine (identical
        executables): bitwise-equal embeddings, zero extra compiles —
        pipelining changes host/device overlap, nothing else."""
        engine = served.service.engine
        rng = np.random.RandomState(21)
        images = rng.rand(12, 16, 16, 3).astype(np.float32)
        outs = {}
        for pipeline in ("off", "on"):
            svc = EmbeddingService(
                engine, DynamicBatcher(max_batch=16, max_wait_s=0.005),
                pipeline=pipeline)
            svc.start(warmup=True)
            compiles_before = engine.compile_count
            reqs = [svc.submit(images[i]) for i in range(12)]
            outs[pipeline] = np.stack(
                [r.result(timeout=120.0)[0] for r in reqs])
            svc.stop()
            assert engine.compile_count == compiles_before
        np.testing.assert_array_equal(outs["off"], outs["on"])

    def test_invalid_pipeline_mode_rejected(self):
        with pytest.raises(ValueError, match="pipeline"):
            EmbeddingService(_StubEngine(),
                             DynamicBatcher(max_batch=8),
                             pipeline="double")
