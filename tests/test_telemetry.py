"""Telemetry layer: packed health vector, async sink, JSONL run log.

Covers the ISSUE 6 acceptance surface:

- packed-vector correctness vs a NumPy reference on tiny pytrees
  (``health.health_stats`` + ``lars.trust_ratio_vector``);
- ``--telemetry off`` lowers the exact pre-telemetry graph: the health
  module is provably never traced (a raising stub), the metric pytree is
  byte-for-byte the pre-PR key set, and the lowered HLO text is identical
  across independent builds (and differs once telemetry is on);
- async-lag readback under the ``guard_steps`` transfer guard — the sink's
  explicit ``device_get`` never trips ``jax.transfer_guard("disallow")``
  and every sample is read with >= interval-step lag;
- the NaN-halt path via an injected non-finite gradient;
- the JSONL event schema round-trip (``events.RunLog`` -> ``read_events``).
"""
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.core import config as config_lib
from byol_tpu.observability import events as events_lib
from byol_tpu.observability import health
from byol_tpu.observability.telemetry import NanHaltError, TelemetrySink
from byol_tpu.optim import lars as lars_lib


# ---------------------------------------------------------------------------
# health.py vs NumPy reference
# ---------------------------------------------------------------------------

def _tiny_trees(seed=0, nan_in_grad=False):
    rng = np.random.RandomState(seed)
    params = {"w": rng.randn(3, 4).astype(np.float32),
              "b": rng.randn(4).astype(np.float32)}
    grads = {"w": rng.randn(3, 4).astype(np.float32),
             "b": rng.randn(4).astype(np.float32)}
    if nan_in_grad:
        grads["w"][0, 0] = np.nan
    updates = {"w": 0.1 * grads["w"], "b": 0.1 * grads["b"]}
    target = {"w": 0.9 * params["w"], "b": 0.9 * params["b"]}
    return params, grads, updates, target


def _np_global_norm(tree):
    return np.sqrt(sum(np.sum(np.square(v)) for v in tree.values()))


class TestHealthVector:
    def test_pack_unpack_roundtrip(self):
        vals = {k: float(i + 1) for i, k in enumerate(health.HEALTH_FIELDS)}
        vec = health.pack(vals)
        assert vec.shape == (len(health.HEALTH_FIELDS),)
        assert vec.dtype == jnp.float32
        out = health.unpack(np.asarray(vec))
        assert out == pytest.approx(vals)

    def test_pack_rejects_field_drift(self):
        vals = {k: 0.0 for k in health.HEALTH_FIELDS}
        with pytest.raises(ValueError, match="extra"):
            health.pack({**vals, "extra": 1.0})
        vals.pop("loss")
        with pytest.raises(ValueError, match="missing"):
            health.pack(vals)

    def test_health_stats_matches_numpy_reference(self):
        params, grads, updates, target = _tiny_trees()
        proj = np.random.RandomState(1).randn(8, 5).astype(np.float32)
        collapse = health.collapse_stats(jnp.asarray(proj))
        vec = health.health_stats(
            grads=grads, updates=updates, params=params,
            target_params=target, loss=jnp.float32(1.5),
            collapse=collapse,
            trust_ratios=lars_lib.trust_ratio_vector(grads, params))
        d = health.unpack(np.asarray(vec))

        assert d["grad_norm"] == pytest.approx(_np_global_norm(grads),
                                               rel=1e-5)
        assert d["update_norm"] == pytest.approx(
            0.1 * _np_global_norm(grads), rel=1e-5)
        assert d["param_norm"] == pytest.approx(_np_global_norm(params),
                                                rel=1e-5)
        drift = np.sqrt(sum(np.sum((params[k] - target[k]) ** 2)
                            for k in params))
        assert d["ema_drift"] == pytest.approx(drift, rel=1e-5)
        assert d["ema_drift_rel"] == pytest.approx(
            drift / _np_global_norm(params), rel=1e-5)
        # only 'w' (ndim 2) is LARS-adapted -> min == median == max
        ref_trust = 1e-3 * np.linalg.norm(params["w"]) / \
            np.linalg.norm(grads["w"])
        for k in ("trust_min", "trust_median", "trust_max"):
            assert d[k] == pytest.approx(ref_trust, rel=1e-5)
        # collapse reference: brute-force per-feature std + pairwise cosine
        assert d["collapse_feature_std"] == pytest.approx(
            np.mean(np.std(proj, axis=0)), rel=1e-4)
        u = proj / np.linalg.norm(proj, axis=1, keepdims=True)
        cos = [float(u[i] @ u[j]) for i in range(8) for j in range(8)
               if i != j]
        assert d["collapse_cosine_mean"] == pytest.approx(np.mean(cos),
                                                          abs=1e-5)
        assert d["nonfinite_count"] == 0.0
        assert d["loss"] == 1.5

    def test_nonfinite_count_sees_injected_nan(self):
        params, grads, updates, target = _tiny_trees(nan_in_grad=True)
        vec = health.health_stats(
            grads=grads, updates=updates, params=params,
            target_params=target, loss=jnp.float32(1.0),
            collapse=(jnp.float32(1.0), jnp.float32(0.0)),
            trust_ratios=jnp.ones((1,), jnp.float32))
        assert health.unpack(np.asarray(vec))["nonfinite_count"] == 1.0

    def test_collapsed_projections_signature(self):
        # every row identical = fully collapsed: std -> 0, cosine -> 1
        proj = jnp.tile(jnp.asarray([[1.0, 2.0, 3.0]]), (16, 1))
        fstd, cosm = health.collapse_stats(proj)
        assert float(fstd) == pytest.approx(0.0, abs=1e-6)
        assert float(cosm) == pytest.approx(1.0, abs=1e-5)


class TestLarsTrustStats:
    def test_vector_matches_applied_transform(self):
        """trust_ratio_vector reports exactly the ratio the optimizer
        multiplies in (shared _leaf_trust_ratio implementation)."""
        params, grads, _, _ = _tiny_trees(seed=3)
        tx = lars_lib.scale_by_lars_trust_ratio()
        scaled, _ = tx.update(grads, tx.init(params), params)
        ratios = np.asarray(lars_lib.trust_ratio_vector(grads, params))
        assert ratios.shape == (1,)              # only 'w' adapted
        np.testing.assert_allclose(np.asarray(scaled["w"]),
                                   grads["w"] * ratios[0], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(scaled["b"]), grads["b"])

    def test_all_1d_tree_returns_identity(self):
        ratios = lars_lib.trust_ratio_vector(
            {"b": jnp.ones((3,))}, {"b": jnp.ones((3,))})
        np.testing.assert_allclose(np.asarray(ratios), [1.0])

    def test_wd_folded_ratio_matches_lars_chain(self):
        """LARS folds weight decay into the gradient BEFORE the trust
        ratio; the stats must be computed on the same post-wd gradient
        (the fold-in steps.py replicates) to match what was applied."""
        params, grads, _, _ = _tiny_trees(seed=5)
        wd = 0.1
        chain = optax_chain_wd_trust(wd)
        applied, _ = chain.update(grads, chain.init(params), params)
        g_wd = {"w": grads["w"] + wd * params["w"], "b": grads["b"]}
        ratio = float(np.asarray(
            lars_lib.trust_ratio_vector(g_wd, params))[0])
        np.testing.assert_allclose(np.asarray(applied["w"]),
                                   g_wd["w"] * ratio, rtol=1e-6)
        # raw-gradient ratio would be wrong at this wd
        raw = float(np.asarray(
            lars_lib.trust_ratio_vector(grads, params))[0])
        assert abs(raw - ratio) / ratio > 1e-3


def optax_chain_wd_trust(wd):
    import optax
    return optax.chain(lars_lib.lars_weight_decay(wd),
                       lars_lib.scale_by_lars_trust_ratio())


def test_lars_in_chain_predicate_is_the_factory_one():
    """StepConfig.lars_in_chain must use the factory's own is-LARS
    normalization — a drifted copy (e.g. no .strip()) would pack identity
    trust ratios for a run where LARS is actually scaling updates."""
    from byol_tpu.optim.factory import is_lars_optimizer
    assert is_lars_optimizer("lars_momentum")
    assert is_lars_optimizer("  LARS_momentum  ")   # factory-normalized form
    assert not is_lars_optimizer("momentum")
    assert not is_lars_optimizer("lamb")


# ---------------------------------------------------------------------------
# in-step telemetry on the 8-device mesh
# ---------------------------------------------------------------------------

def _step_rcfg(telemetry="off"):
    c = config_lib.Config()
    c = c.replace(
        task=dataclasses.replace(c.task, batch_size=8, epochs=2),
        model=dataclasses.replace(c.model, arch="resnet18",
                                  head_latent_size=32, projection_size=16),
        optim=dataclasses.replace(c.optim, warmup=1, lr=0.1),
        device=dataclasses.replace(c.device, num_replicas=8, half=False,
                                   telemetry=telemetry),
    )
    return config_lib.resolve(c, num_train_samples=64, num_test_samples=16,
                              output_size=10, input_shape=(16, 16, 3),
                              representation_size=512)


def test_halt_policy_requires_telemetry():
    """--nan-policy halt with --telemetry off would silently enforce
    nothing (the sink only exists when telemetry is on): resolve() must
    reject the combination."""
    c = config_lib.Config()
    c = c.replace(device=dataclasses.replace(
        c.device, num_replicas=8, telemetry="off", nan_policy="halt"))
    with pytest.raises(ValueError, match="halt requires"):
        config_lib.resolve(c, num_train_samples=64, num_test_samples=16,
                           output_size=10, input_shape=(16, 16, 3))


def _make_batch(rcfg, seed=0, nan_at=None):
    rng = np.random.RandomState(seed)
    b = rcfg.global_batch_size
    h, w, c = rcfg.input_shape
    batch = {"view1": rng.rand(b, h, w, c).astype(np.float32),
             "view2": rng.rand(b, h, w, c).astype(np.float32),
             "label": rng.randint(0, rcfg.output_size, size=(b,))}
    if nan_at is not None:
        batch["view1"][nan_at] = np.nan
    return batch


def _lowered_text(rcfg, mesh):
    from byol_tpu.training.build import setup_training
    from byol_tpu.parallel.mesh import shard_batch_to_mesh
    net, state, train_step, _, _ = setup_training(rcfg, mesh,
                                                  jax.random.PRNGKey(0))
    batch = shard_batch_to_mesh(_make_batch(rcfg), mesh)
    with mesh:
        lowered = train_step.__wrapped__.lower(state, batch)
    return lowered.as_text()


class TestStepTelemetry:
    @pytest.fixture(scope="class")
    def telemetry_training(self, mesh8, step_guard):
        from byol_tpu.training.build import setup_training
        rcfg = _step_rcfg(telemetry="step")
        net, state, train_step, eval_step, _ = setup_training(
            rcfg, mesh8, jax.random.PRNGKey(0))
        return rcfg, state, step_guard(train_step)

    def test_health_in_metrics_and_finite(self, telemetry_training, mesh8):
        from byol_tpu.parallel.mesh import shard_batch_to_mesh
        rcfg, state, train_step = telemetry_training
        state = jax.tree_util.tree_map(jnp.copy, state)
        batch = shard_batch_to_mesh(_make_batch(rcfg), mesh8)
        state, metrics = train_step(state, batch)
        assert "health" in metrics
        d = health.unpack(np.asarray(jax.device_get(metrics["health"])))
        assert all(np.isfinite(v) for v in d.values()), d
        assert d["nonfinite_count"] == 0.0
        assert d["grad_norm"] > 0 and d["param_norm"] > 0
        assert 0 < d["trust_min"] <= d["trust_median"] <= d["trust_max"]
        assert d["collapse_feature_std"] > 0
        assert d["loss"] == pytest.approx(float(metrics["loss_mean"]),
                                          rel=1e-5)

    def test_injected_nan_halts_under_halt_policy(self, telemetry_training,
                                                  mesh8, tmp_path):
        """An injected non-finite input NaNs the gradients; the sink's
        readback must record the anomaly and raise under nan_policy=halt,
        with the anomaly + halt events in the run log."""
        from byol_tpu.parallel.mesh import shard_batch_to_mesh
        rcfg, state, train_step = telemetry_training
        state = jax.tree_util.tree_map(jnp.copy, state)
        batch = shard_batch_to_mesh(_make_batch(rcfg, nan_at=0), mesh8)
        state, metrics = train_step(state, batch)
        log = events_lib.RunLog(str(tmp_path / "run.jsonl"))
        sink = TelemetrySink(1, nan_policy="halt", events=log,
                             verbose=False)
        with pytest.raises(NanHaltError) as err:
            sink.offer(1, metrics["health"])
            sink.drain()
        assert err.value.record["nonfinite_count"] > 0
        log.close()
        kinds = [e["kind"] for e in
                 events_lib.read_events(str(tmp_path / "run.jsonl"))]
        assert "anomaly" in kinds and "halt" in kinds

    def test_off_never_traces_health(self, mesh8, monkeypatch):
        """--telemetry off is not 'health computed and discarded': the
        health module is never even CALLED during trace, so the lowered
        graph cannot contain its ops — 'identical HLO as before the PR'
        by construction."""
        def boom(**kw):
            raise AssertionError("health_stats traced under telemetry=off")
        monkeypatch.setattr(health, "health_stats", boom)
        text = _lowered_text(_step_rcfg(telemetry="off"), mesh8)
        assert text  # lowering succeeded without touching health_stats

    def test_off_metric_keys_are_pre_pr_contract(self, mesh8):
        from byol_tpu.training.build import setup_training
        rcfg = _step_rcfg(telemetry="off")
        net, state, train_step, _, _ = setup_training(
            rcfg, mesh8, jax.random.PRNGKey(0))
        from byol_tpu.parallel.mesh import shard_batch_to_mesh
        batch = shard_batch_to_mesh(_make_batch(rcfg), mesh8)
        with mesh8:
            _, m_shape = jax.eval_shape(train_step.__wrapped__, state,
                                        batch)
        assert set(m_shape) == {"loss_mean", "byol_loss_mean",
                                "linear_loss_mean", "top1_mean",
                                "top5_mean"}

    @pytest.mark.slow
    def test_off_lowering_identical_step_differs(self, mesh8):
        """The lowered-text pin: two independent telemetry-off builds
        produce byte-identical HLO (the off path adds nothing and is
        deterministic), while telemetry=step produces a different
        program (the gate is live)."""
        off1 = _lowered_text(_step_rcfg(telemetry="off"), mesh8)
        off2 = _lowered_text(_step_rcfg(telemetry="off"), mesh8)
        assert off1 == off2
        step = _lowered_text(_step_rcfg(telemetry="step"), mesh8)
        assert step != off1


# ---------------------------------------------------------------------------
# TelemetrySink: lag, guard-compat, anomaly rules
# ---------------------------------------------------------------------------

def _vec(**overrides):
    vals = {"grad_norm": 1.0, "update_norm": 0.1, "param_norm": 10.0,
            "ema_drift": 0.5, "ema_drift_rel": 0.05, "trust_min": 1e-3,
            "trust_median": 1e-3, "trust_max": 2e-3,
            "collapse_feature_std": 0.5, "collapse_cosine_mean": 0.1,
            "nonfinite_count": 0.0, "loss": 2.0}
    vals.update(overrides)
    return health.pack(vals)


class TestTelemetrySink:
    def test_lagged_readback_under_transfer_guard(self):
        """Samples are read back only once a NEWER sample exists (>= one
        interval of dispatch in between), and the explicit device_get
        stays legal under jax.transfer_guard('disallow') — the same guard
        the jitted steps run under in tests (guard_steps)."""
        sink = TelemetrySink(2, verbose=False)
        # vectors land on device OUTSIDE the guard (in real use they are
        # step outputs, already device-resident); the guard covers the
        # sink's readbacks — the part that runs in the dispatch loop
        v1, v2, v4 = _vec(), _vec(loss=2.0), _vec(loss=1.5)
        with jax.transfer_guard("disallow"):
            assert sink.offer(1, v1) == []          # off-interval: ignored
            assert sink.offer(2, v2) == []
            assert list(sink.records) == []         # newest stays pending
            sink.offer(4, v4)
        assert [r["step"] for r in sink.records] == [2.0]
        assert sink.records[0]["loss"] == 2.0
        sink.drain()
        assert [r["step"] for r in sink.records] == [2.0, 4.0]

    def test_epoch_mode_hold_keeps_only_latest(self):
        sink = TelemetrySink(1, verbose=False)
        sink.hold(1, _vec(loss=3.0))
        sink.hold(2, _vec(loss=2.5))
        assert len(sink.records) == 0
        sink.drain()
        assert [r["step"] for r in sink.records] == [2.0]

    def test_nan_warn_records_anomaly_without_raising(self):
        sink = TelemetrySink(1, nan_policy="warn", verbose=False)
        sink.offer(1, _vec(nonfinite_count=3.0))
        anomalies = sink.drain()
        assert [a["rule"] for a in anomalies] == ["nonfinite"]
        assert sink.anomalies and not sink.records[-1].get("halted")

    def test_nan_halt_raises(self):
        sink = TelemetrySink(1, nan_policy="halt", verbose=False)
        sink.offer(1, _vec(nonfinite_count=1.0))
        with pytest.raises(NanHaltError):
            sink.drain()

    def test_collapse_rule(self):
        sink = TelemetrySink(1, verbose=False)
        sink.offer(1, _vec(collapse_feature_std=1e-6,
                           collapse_cosine_mean=0.9999))
        anomalies = sink.drain()
        assert [a["rule"] for a in anomalies] == ["collapse"]

    def test_step_time_spike_rule(self):
        """Six steady samples then one 10x-slower interval must trip the
        spike rule (ring median comparison on dispatch timestamps)."""
        sink = TelemetrySink(1, verbose=False)
        walls = [0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 16.0]
        anomalies = []
        for i, w in enumerate(walls):
            anomalies += sink.offer(i + 1, _vec(), wall=w)
        anomalies += sink.drain()
        assert [a["rule"] for a in anomalies] == ["step_time_spike"]
        assert anomalies[0]["step"] == 8

    def test_epoch_boundary_gap_is_not_a_spike(self):
        """drain() (the epoch boundary) invalidates the timebase: the gap
        to the next epoch's first sample spans eval/checkpoint wall time
        and must not fire step_time_spike."""
        sink = TelemetrySink(1, verbose=False)
        anomalies = []
        for i, w in enumerate([0.0, 1.0, 2.0, 3.0, 4.0, 5.0]):
            anomalies += sink.offer(i + 1, _vec(), wall=w)
        anomalies += sink.drain()             # epoch boundary
        # next epoch starts 100s later (eval + checkpoint happened)
        anomalies += sink.offer(7, _vec(), wall=105.0)
        anomalies += sink.offer(8, _vec(), wall=106.0)
        anomalies += sink.drain()
        assert anomalies == []
        # the boundary-straddling sample carries no sec_per_step at all
        rec7 = next(r for r in sink.records if r["step"] == 7.0)
        assert "sec_per_step" not in rec7

    def test_validates_ctor_args(self):
        with pytest.raises(ValueError):
            TelemetrySink(0)
        with pytest.raises(ValueError):
            TelemetrySink(1, nan_policy="explode")


# ---------------------------------------------------------------------------
# events.py: schema round-trip
# ---------------------------------------------------------------------------

class TestRunLog:
    def test_roundtrip_all_kinds(self, tmp_path):
        p = str(tmp_path / "run.jsonl")
        with events_lib.RunLog(p) as log:
            log.emit("run_header", config={"a": 1}, jax_version="0",
                     backend="cpu")
            log.emit("step", step=50,
                     health={k: 0.0 for k in health.HEALTH_FIELDS})
            log.emit("epoch", epoch=0, split="train",
                     metrics={"loss_mean": 1.0},
                     input_pipeline={"h2d_bytes_per_step": 1.0})
            log.emit("anomaly", step=50, rule="collapse", detail="x")
            log.emit("checkpoint", epoch=0, best_metric=1.0)
            log.emit("run_end", epoch=0)
        got = list(events_lib.read_events(p))
        assert [e["kind"] for e in got] == [
            "run_header", "step", "epoch", "anomaly", "checkpoint",
            "run_end"]
        assert all(e["v"] == events_lib.SCHEMA_VERSION for e in got)
        assert got[1]["health"]["loss"] == 0.0

    def test_emit_validates_kind_and_required_fields(self, tmp_path):
        log = events_lib.RunLog(str(tmp_path / "r.jsonl"))
        with pytest.raises(ValueError, match="unknown event kind"):
            log.emit("not_a_kind", x=1)
        with pytest.raises(ValueError, match="missing required"):
            log.emit("epoch", epoch=0, split="train")  # no metrics
        log.close()

    def test_run_header_sharding_plan_validation(self, tmp_path):
        """ISSUE 7: the optional run_header.sharding_plan field must carry
        the full CompilePlan.describe() provenance or be rejected — a run
        log must never claim a plan it cannot name."""
        plan = {"mesh_shape": {"data": 8}, "axis_names": ["data"],
                "zero1": "on", "donate_argnums": {"train_step": [0]}}
        p = str(tmp_path / "r.jsonl")
        with events_lib.RunLog(p) as log:
            log.emit("run_header", config={}, jax_version="0",
                     backend="cpu", sharding_plan=plan)   # valid: accepted
            with pytest.raises(ValueError, match="sharding_plan"):
                log.emit("run_header", config={}, jax_version="0",
                         backend="cpu", sharding_plan={"zero1": "on"})
            with pytest.raises(ValueError, match="zero1"):
                bad = dict(plan, zero1=True)   # must be the 'off'|'on' str
                log.emit("run_header", config={}, jax_version="0",
                         backend="cpu", sharding_plan=bad)
            with pytest.raises(ValueError, match="sharding_plan"):
                log.emit("run_header", config={}, jax_version="0",
                         backend="cpu", sharding_plan=["not", "a", "dict"])
        (e,) = events_lib.read_events(p)
        assert e["sharding_plan"] == plan

    def test_reader_rejects_corrupt_and_drifted_lines(self, tmp_path):
        p = tmp_path / "r.jsonl"
        with events_lib.RunLog(str(p)) as log:
            log.emit("run_end")
        with open(p, "a") as f:
            f.write("{not json\n")
        with pytest.raises(ValueError, match=":2:"):
            list(events_lib.read_events(str(p)))
        p2 = tmp_path / "r2.jsonl"
        p2.write_text(json.dumps({"v": 999, "kind": "run_end",
                                  "t": 0.0}) + "\n")
        with pytest.raises(ValueError, match="schema version"):
            list(events_lib.read_events(str(p2)))

    def test_numpy_payloads_serialize(self, tmp_path):
        p = str(tmp_path / "r.jsonl")
        with events_lib.RunLog(p) as log:
            log.emit("epoch", epoch=np.int64(3), split="train",
                     metrics={"loss_mean": np.float32(1.5),
                              "vec": np.arange(3)})
        (e,) = events_lib.read_events(p)
        assert e["epoch"] == 3 and e["metrics"]["vec"] == [0, 1, 2]

    def test_nonfinite_floats_emit_strict_json(self, tmp_path):
        """The lines a NaN run produces are exactly the ones machine
        consumers must be able to read: Python's lenient writer would emit
        bare ``NaN``/``Infinity`` tokens (invalid JSON for jq/JS/serde) —
        the log maps non-finite floats to strings instead."""
        p = str(tmp_path / "r.jsonl")
        health_vals = {k: 0.0 for k in health.HEALTH_FIELDS}
        health_vals["loss"] = float("nan")
        health_vals["grad_norm"] = float("inf")
        health_vals["trust_min"] = np.float32("-inf")
        with events_lib.RunLog(p) as log:
            log.emit("step", step=50, health=health_vals,
                     extra=np.array([1.0, np.nan]))
        with open(p) as f:
            (line,) = f.read().splitlines()
        # strict parse: reject any bare non-finite constant token
        e = json.loads(line, parse_constant=lambda tok: pytest.fail(
            f"bare {tok} token in run-log line: not strict JSON"))
        assert e["health"]["loss"] == "NaN"
        assert e["health"]["grad_norm"] == "Infinity"
        assert e["health"]["trust_min"] == "-Infinity"
        assert e["health"]["update_norm"] == 0.0     # finite stays a float
        assert e["extra"] == [1.0, "NaN"]            # arrays sanitized too

    def test_best_effort_write_failure_disables_not_raises(self, tmp_path):
        """Observability must not kill the run it observes: with
        best_effort, an OSError on write (disk full, quota, ro fs)
        disables the log with a warning and later emits become no-ops;
        without best_effort the error propagates."""
        class _FullDisk:
            def write(self, s):
                raise OSError(28, "No space left on device")

            def close(self):
                pass

            closed = False

        p = str(tmp_path / "r.jsonl")
        log = events_lib.RunLog(p, best_effort=True)
        log.emit("run_end")
        log._f.close()
        log._f = _FullDisk()               # the fs goes away mid-run
        log.emit("run_end", epoch=1)       # must not raise
        assert log.disabled
        log.emit("run_end", epoch=2)       # disabled: no-op, no raise
        log.flush(); log.close()           # all no-ops once disabled
        assert [e["kind"] for e in events_lib.read_events(p)] == ["run_end"]
        # schema violations still raise even in best-effort mode
        with pytest.raises(ValueError):
            log.emit("not_a_kind")
        strict = events_lib.RunLog(p)
        strict._f = _FullDisk()
        with pytest.raises(OSError):       # default: propagate
            strict.emit("run_end")

    def test_best_effort_ctor_failure_disables_not_raises(self, tmp_path):
        """best_effort covers CONSTRUCTION too (an unopenable log_dir at
        startup), so trainer.fit and bench.py get the never-kill-the-run
        contract from RunLog itself instead of hand-rolled wrappers."""
        blocker = tmp_path / "blocker"
        blocker.write_text("not a dir")
        p = str(blocker / "run.jsonl")    # parent is a FILE: makedirs raises
        with pytest.raises(OSError):
            events_lib.RunLog(p)
        log = events_lib.RunLog(p, best_effort=True)
        assert log.disabled
        log.emit("run_end")               # no-op, must not raise
        log.flush()
        log.close()

    def test_lines_are_crash_safe_before_close(self, tmp_path):
        """Line-buffered append: every emitted event is durable on its own
        newline — a crash loses at most the in-flight line."""
        p = str(tmp_path / "r.jsonl")
        log = events_lib.RunLog(p)
        log.emit("run_end")
        # read WITHOUT close/flush: the line must already be on disk
        assert [e["kind"] for e in events_lib.read_events(p)] == ["run_end"]
        log.close()


# ---------------------------------------------------------------------------
# fit() integration: run.jsonl + halt
# ---------------------------------------------------------------------------

def _fit_cfg(tmp_path, **device_over):
    from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                      OptimConfig, TaskConfig)
    return Config(
        task=TaskConfig(task="fake", batch_size=16, epochs=2,
                        image_size_override=16,
                        log_dir=str(tmp_path / "runs")),
        model=ModelConfig(arch="resnet18", head_latent_size=32,
                          projection_size=16,
                          model_dir=str(tmp_path / "models")),
        optim=OptimConfig(lr=0.05, warmup=1, optimizer="lars_momentum"),
        device=DeviceConfig(num_replicas=8, half=False, seed=7,
                            debug_step=True, **device_over),
    )


@pytest.mark.slow
class TestFitRunLog:
    def _run_log(self, cfg):
        import os
        from byol_tpu.core.config import run_name
        return os.path.join(cfg.task.log_dir, run_name(cfg), "run.jsonl")

    def test_fit_emits_valid_run_log(self, tmp_path):
        from byol_tpu.data.loader import get_loader
        from byol_tpu.observability import Grapher
        from byol_tpu.training.trainer import fit
        cfg = _fit_cfg(tmp_path, telemetry="step", telemetry_interval=1)
        loader = get_loader(cfg, num_fake_samples=32)
        grapher = Grapher("jsonl", logdir=str(tmp_path / "runs"),
                          run_name="g", enabled=True)
        fit(cfg, loader=loader, grapher=grapher, verbose=False)
        got = list(events_lib.read_events(self._run_log(cfg)))
        kinds = [e["kind"] for e in got]
        assert kinds[0] == "run_header" and kinds[-1] == "run_end"
        assert {"step", "epoch", "checkpoint"} <= set(kinds)
        header = got[0]
        assert header["config"]["device"]["telemetry"] == "step"
        assert header["mesh_shape"].get("data") == 8
        steps = [e for e in got if e["kind"] == "step"]
        assert steps and all(set(health.HEALTH_FIELDS)
                             <= set(e["health"]) for e in steps)
        epochs = [e for e in got if e["kind"] == "epoch"]
        assert {e["split"] for e in epochs} == {"train", "test"}
        train_ep = next(e for e in epochs if e["split"] == "train")
        assert "input_pipeline" in train_ep
        assert "loss_mean" in train_ep["metrics"]
        # flight recorder (default --spans on): per-epoch + run-scope
        # goodput partitions (identity validated by the reader) and the
        # Chrome trace next to the log (ISSUE 9)
        goodputs = [e for e in got if e["kind"] == "goodput"]
        assert {e["scope"] for e in goodputs} >= {"epoch", "run"}
        import os
        trace = os.path.join(os.path.dirname(self._run_log(cfg)),
                             "trace.json")
        with open(trace) as f:
            assert json.load(f)["traceEvents"]

    def test_fit_survives_unopenable_run_log(self, tmp_path):
        """RunLog's best_effort only guards WRITES; the constructor's
        makedirs/open can raise at startup (quota, read-only fs) and must
        degrade to events=None instead of killing the run — same contract
        bench.py's _open_events applies."""
        from byol_tpu.data.loader import get_loader
        from byol_tpu.observability import Grapher
        from byol_tpu.training.trainer import fit
        cfg = _fit_cfg(tmp_path, telemetry="step", telemetry_interval=1)
        # run_name(cfg)'s parent component is a FILE: makedirs in
        # RunLog.__init__ raises (FileExistsError/NotADirectoryError,
        # both OSError)
        (tmp_path / "runs").mkdir()
        from byol_tpu.core.config import run_name
        (tmp_path / "runs" / run_name(cfg)).write_text("not a dir")
        loader = get_loader(cfg, num_fake_samples=32)
        grapher = Grapher("jsonl", logdir=str(tmp_path / "runs_g"),
                          run_name="g3", enabled=True)
        result = fit(cfg, loader=loader, grapher=grapher, verbose=False)
        assert result.epoch >= 0   # trained to completion, log disabled

    def test_fit_halts_on_injected_nan_with_state_dump(self, tmp_path):
        """A NaN smuggled into the train views must halt the run under
        --nan-policy halt and leave anomaly + halt + state_dump events in
        the run log — the acceptance-criteria drill."""
        from byol_tpu.data.loader import get_loader
        from byol_tpu.observability import Grapher
        from byol_tpu.training.trainer import fit
        cfg = _fit_cfg(tmp_path, telemetry="step", telemetry_interval=1,
                       nan_policy="halt")
        loader = get_loader(cfg, num_fake_samples=32)

        def nan_train_iter(epoch, _base=loader.make_train_iter):
            for batch in _base(epoch):
                batch = dict(batch)
                v = np.array(batch["view1"])
                v[0, 0, 0, 0] = np.nan   # passes the [0,1] range check
                batch["view1"] = v
                yield batch

        loader = dataclasses.replace(loader,
                                     make_train_iter=nan_train_iter)
        grapher = Grapher("jsonl", logdir=str(tmp_path / "runs"),
                          run_name="g2", enabled=True)
        with pytest.raises(NanHaltError):
            fit(cfg, loader=loader, grapher=grapher, verbose=False)
        got = list(events_lib.read_events(self._run_log(cfg)))
        kinds = [e["kind"] for e in got]
        assert "anomaly" in kinds and "halt" in kinds
        dump = next(e for e in got if e["kind"] == "state_dump")
        assert dump["reason"] == "nonfinite"
        assert dump["health"]["nonfinite_count"] > 0
        assert "state_step" in dump and "lr" in dump
