"""ZeRO-1 weight-update sharding (ISSUE 7 tentpole).

The contracts under test:

- **Parity** (acceptance): on the 8-virtual-device CPU mesh, ``--zero1 on``
  matches the replicated step's loss and post-step params / LARS momentum /
  EMA target to tight tolerance at accum 1 AND accum 2, with every step
  running under the ``guard_steps`` transfer-guard fixture (an implicit
  host sync inside the shard/gather plumbing fails here, on CPU).  The
  flat layout is numerics-preserving by construction — zero padding maps
  through the whole update chain as zeros and leaves every per-leaf l2
  norm (LARS trust ratios) unchanged (parallel/zero1.py docstring).
- **Off-identity** (acceptance): ``--zero1 off`` lowers byte-identical HLO
  to the pre-plan per-site jit wiring — the compile plan is a refactor of
  WHERE shardings are declared, not of the default program.
- **Layout**: under ZeRO-1 the momentum/EMA leaves really are flat (1-D)
  and sharded over ``data``; params stay replicated for the forward.
- The flat-layout helpers round-trip exactly, and ``CompilePlan.describe()``
  emits the JSON-serializable ``sharding_plan`` record the run-log header
  carries (observability/events.py validates it).
"""
import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from byol_tpu.core import config as config_lib
from byol_tpu.parallel import zero1 as zero1_lib
from byol_tpu.parallel.compile_plan import build_plan
from byol_tpu.parallel.mesh import DATA_AXIS, shard_batch_to_mesh
from byol_tpu.training.build import setup_training
from tests.conftest import guard_steps, tree_maxdiff as _tree_maxdiff

BATCH = 16
IMAGE = 16


def _rcfg(zero1="off", accum=1):
    c = config_lib.Config()
    c = c.replace(
        task=dataclasses.replace(c.task, batch_size=BATCH, epochs=2,
                                 image_size_override=IMAGE),
        model=dataclasses.replace(c.model, arch="resnet18",
                                  head_latent_size=32, projection_size=16),
        optim=dataclasses.replace(c.optim, warmup=1, lr=0.1,
                                  accum_steps=accum),
        device=dataclasses.replace(c.device, num_replicas=8, half=False,
                                   zero1=zero1),
    )
    return config_lib.resolve(c, num_train_samples=64, num_test_samples=16,
                              output_size=10, input_shape=(IMAGE, IMAGE, 3),
                              representation_size=512)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "view1": rng.rand(BATCH, IMAGE, IMAGE, 3).astype(np.float32),
        "view2": rng.rand(BATCH, IMAGE, IMAGE, 3).astype(np.float32),
        "label": rng.randint(0, 10, size=(BATCH,)).astype(np.int32),
    }


def _run_arm(mesh, zero1, accum, n=3):
    """n guarded train steps + one guarded eval from the seed-0 init.

    Returns (plan, plan-layout state, CANONICAL state, train metrics,
    eval loss) — the canonical view (plan.to_canonical) is what parity
    compares, since the ZeRO-1 arm's momentum/EMA live flat-sharded."""
    rcfg = _rcfg(zero1=zero1, accum=accum)
    plan = build_plan(mesh, zero1=(zero1 == "on"))
    net, state, train_step, eval_step, _ = setup_training(
        rcfg, mesh, jax.random.PRNGKey(0), plan=plan)
    train_step = guard_steps(train_step)
    metrics = None
    for i in range(n):
        batch = shard_batch_to_mesh(_batch(seed=i), mesh)
        state, metrics = train_step(state, batch)
    eval_batch = shard_batch_to_mesh(_batch(seed=99), mesh)
    ev = guard_steps(eval_step)(state, eval_batch)
    return (plan, state, plan.to_canonical(state),
            {k: float(v) for k, v in metrics.items()},
            float(ev["loss_mean"]))


# ---------------------------------------------------------------------------
# parity: zero1 on == replicated, accum 1 and 2  (acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("accum", [1, 2])
def test_zero1_matches_replicated(mesh8, accum):
    assert len(mesh8.devices.flat) >= 4      # acceptance: >= 4-device mesh
    plan_off, _, canon_off, m_off, ev_off = _run_arm(mesh8, "off", accum)
    plan_on, raw_on, canon_on, m_on, ev_on = _run_arm(mesh8, "on", accum)

    # the ZeRO-1 arm really shards: flat momentum/EMA leaves over 'data'
    flat_sharded = [
        leaf for leaf in jax.tree_util.tree_leaves(
            (raw_on.opt_state, raw_on.target_params))
        if getattr(leaf, "ndim", 0) == 1
        and DATA_AXIS in str(leaf.sharding.spec)]
    assert flat_sharded, "no momentum/EMA leaf is flat-sharded over data"
    # params stay replicated for the forward
    assert all(leaf.sharding.spec == P() for leaf in
               jax.tree_util.tree_leaves(raw_on.params))

    # loss identical arm-to-arm (same batches, same math)
    for k in m_off:
        np.testing.assert_allclose(m_on[k], m_off[k], rtol=1e-5,
                                   err_msg=f"metric {k} @ accum {accum}")
    np.testing.assert_allclose(ev_on, ev_off, rtol=1e-5)

    # post-step state: params / LARS momentum / EMA target, canonical view
    assert _tree_maxdiff(canon_off.params, canon_on.params) < 1e-5
    assert _tree_maxdiff(canon_off.target_params,
                         canon_on.target_params) < 1e-5
    assert _tree_maxdiff(canon_off.opt_state, canon_on.opt_state) < 1e-5
    assert int(canon_on.step) == int(canon_off.step) == 3
    assert int(canon_on.ema_step) == int(canon_off.ema_step) == 3


# ---------------------------------------------------------------------------
# --zero1 off HLO identity with the pre-plan wiring  (acceptance)
# ---------------------------------------------------------------------------

def test_zero1_off_lowers_pre_plan_hlo(mesh8):
    """The compile plan with zero1 off must lower the EXACT program the
    old per-site ``jax.jit`` wiring in training/build.py produced — same
    fn, same shardings, same donation, byte-identical text."""
    from byol_tpu.core.precision import get_policy
    from byol_tpu.parallel.partitioning import state_shardings
    from byol_tpu.training.build import build_net, build_tx, step_config
    from byol_tpu.training.steps import make_train_step

    rcfg = _rcfg()
    plan = build_plan(mesh8, zero1=False)
    net, state, train_step, _, _ = setup_training(
        rcfg, mesh8, jax.random.PRNGKey(0), plan=plan)
    batch = shard_batch_to_mesh(_batch(), mesh8)
    with mesh8:
        plan_text = train_step.__wrapped__.lower(state, batch).as_text()

    # the pre-plan construction, reconstructed inline (what build.py's
    # setup_training spelled before the compile plan owned the wiring)
    pre_step = jax.jit(
        make_train_step(build_net(rcfg), build_tx(rcfg)[0],
                        step_config(rcfg), get_policy(False)),
        in_shardings=(state_shardings(state, mesh8),
                      NamedSharding(mesh8, P(DATA_AXIS))),
        out_shardings=(state_shardings(state, mesh8),
                       NamedSharding(mesh8, P())),
        donate_argnums=(0,))
    with mesh8:
        pre_text = pre_step.lower(state, batch).as_text()
    assert plan_text == pre_text


def test_zero1_on_lowers_a_different_program(mesh8):
    """The gate is live: zero1 on traces the shard/gather program (a
    no-op flag would vacuously pass the identity test above)."""
    off = _rcfg("off")
    on = _rcfg("on")
    texts = {}
    for rcfg, z in ((off, False), (on, True)):
        plan = build_plan(mesh8, zero1=z)
        _, state, train_step, _, _ = setup_training(
            rcfg, mesh8, jax.random.PRNGKey(0), plan=plan)
        batch = shard_batch_to_mesh(_batch(), mesh8)
        with mesh8:
            texts[z] = train_step.__wrapped__.lower(state, batch).as_text()
    assert texts[True] != texts[False]


# ---------------------------------------------------------------------------
# flat-layout helpers
# ---------------------------------------------------------------------------

class TestFlatLayout:
    def test_padded_size(self):
        assert zero1_lib.padded_size(8, 4) == 8
        assert zero1_lib.padded_size(9, 4) == 12
        assert zero1_lib.padded_size(1, 8) == 8
        assert zero1_lib.padded_size(0, 8) == 0

    @pytest.mark.parametrize("shape", [(), (5,), (3, 7), (2, 3, 4)])
    def test_flatten_unflatten_roundtrip(self, shape):
        rng = np.random.RandomState(0)
        x = jnp.asarray(np.asarray(rng.rand(*shape), np.float32))
        flat = zero1_lib.flatten_leaf(x, 8)
        assert flat.ndim == 1 and flat.size % 8 == 0
        # the padding is zeros (the invariance the update chain relies on)
        n_real = int(np.prod(shape)) if shape else 1
        np.testing.assert_array_equal(np.asarray(flat[n_real:]), 0.0)
        tmpl = jax.ShapeDtypeStruct(shape, x.dtype)
        back = zero1_lib.unflatten_leaf(flat, tmpl)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
        assert zero1_lib.flat_struct(tmpl, 8).shape == flat.shape

    def test_to_layout_both_directions_and_passthrough(self):
        tree = {"k": jnp.arange(6.0).reshape(2, 3),
                "count": jnp.zeros((), jnp.int32)}
        canon_tmpl = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)
        flat_tmpl = jax.tree_util.tree_map(
            lambda t: (zero1_lib.flat_struct(t, 4)
                       if t.shape else t), canon_tmpl)
        flat = zero1_lib.to_layout(tree, flat_tmpl, 4)
        assert flat["k"].shape == (8,)
        assert flat["count"].shape == ()          # scalar passes through
        back = zero1_lib.to_layout(flat, canon_tmpl, 4)
        np.testing.assert_array_equal(np.asarray(back["k"]),
                                      np.asarray(tree["k"]))

    def test_to_layout_1d_nondivisible_leaf_roundtrips(self):
        """A canonical leaf that is ITSELF 1-D and non-divisible (the
        probe bias: size 10 under 8 shards -> flat (16,)) must round-trip
        — rank alone cannot pick the conversion direction (regression:
        flat->canonical misread the (10,) template as a flatten target)."""
        bias = jnp.arange(10.0)
        canon_tmpl = {"b": jax.ShapeDtypeStruct((10,), bias.dtype)}
        flat_tmpl = {"b": zero1_lib.flat_struct(canon_tmpl["b"], 8)}
        assert flat_tmpl["b"].shape == (16,)
        flat = zero1_lib.to_layout({"b": bias}, flat_tmpl, 8)
        assert flat["b"].shape == (16,)
        back = zero1_lib.to_layout(flat, canon_tmpl, 8)
        np.testing.assert_array_equal(np.asarray(back["b"]),
                                      np.asarray(bias))

    def test_to_layout_rejects_impossible_conversion(self):
        bad_tmpl = {"k": jax.ShapeDtypeStruct((5,), jnp.float32)}
        with pytest.raises(ValueError, match="layout conversion"):
            zero1_lib.to_layout({"k": jnp.zeros((2, 3))}, bad_tmpl, 4)


# ---------------------------------------------------------------------------
# plan provenance: the run-header sharding_plan record
# ---------------------------------------------------------------------------

def test_plan_describe_is_the_run_header_record(mesh8):
    d = build_plan(mesh8, zero1=True).describe()
    assert d["mesh_shape"] == {"data": 8, "sequence": 1, "model": 1}
    assert d["axis_names"] == ["data", "sequence", "model"]
    assert d["zero1"] == "on"
    assert d["donate_argnums"]["train_step"] == [0]
    assert set(d["donate_argnums"]) == {
        "train_step", "eval_step", "encoder_extractor", "spmd_extractor",
        "serve_step"}
    # the serving hot path donates its staged request batch (ISSUE 8)
    assert d["donate_argnums"]["serve_step"] == [0]
    json.dumps(d)                       # header-embeddable as-is
    assert build_plan(mesh8).describe()["zero1"] == "off"


def test_zero1_context_requires_prepare_state(mesh8):
    with pytest.raises(ValueError, match="prepare_state"):
        build_plan(mesh8, zero1=True).zero1_context()


def test_codec_requires_prepare_state(mesh8):
    """The checkpoint codec fails with the same explicit error as
    zero1_context on an unprepared plan — not a NoneType TypeError deep
    inside _convert."""
    state = {"opt_state": jnp.zeros((4,))}
    for method in ("to_canonical", "from_canonical", "canonical_template"):
        plan = build_plan(mesh8, zero1=True)
        with pytest.raises(ValueError, match="prepare_state"):
            getattr(plan, method)(state)
