"""GL101 fixture: host-device sync points inside traced code (must fire)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    y = jnp.sum(x)
    host = np.asarray(y)          # numpy materialization of a traced value
    fetched = jax.device_get(y)   # device->host transfer by definition
    return float(y) + host.mean() + fetched


def scan_body(carry, x):
    val = jnp.dot(carry, x)
    return carry, val.item()      # .item() blocks on a readback


def run(carry, xs):
    return jax.lax.scan(scan_body, carry, xs)
