"""Fixture: donated buffers riding in container literals — the wave-4
value-flow arms of GL113.  The donation kills the NAME, and every
container slot recorded as holding that name dies with it."""
from .wiring import train_step


def tuple_slot_reuse(state, batch):
    bundle = (state, batch)
    new_state, _ = train_step(state, batch)    # donates arg 0: state dead
    return bundle[0], new_state                # GL113: dead tuple slot


def dict_slot_reuse(state, batch):
    ckpt = {"state": state, "batch": batch}
    new_state, _ = train_step(state, batch)
    return ckpt["state"], new_state            # GL113: dead dict slot


def unpack_reuse(state, batch):
    bundle = (state, batch)
    new_state, _ = train_step(state, batch)
    s, b = bundle                              # alias of the dead slot
    return s, new_state                        # GL113: via tuple-unpack
