"""Fixture: binds the donating entry point at module level."""
from .compile_plan import Plan

plan = Plan()


def _step(state, batch):
    return state, batch


train_step = plan.jit_train_step(_step)
