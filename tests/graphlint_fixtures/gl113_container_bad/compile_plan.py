"""Fixture: plan whose train entry donates arg 0 — the donation the
wave-4 container flow must track through tuple/dict literals."""
import jax

DONATE = {
    "train_step": (0,),
}


class Plan:
    def jit_train_step(self, fn):
        return jax.jit(fn, donate_argnums=DONATE["train_step"])
