"""Fixture near-miss for GL114:

- both mutation sites hold the SAME ``with self._lock:`` guard (the
  EmbeddingService discipline);
- ``__init__`` stores happen before the thread exists and must not count
  as a public side;
- a class whose thread target is a LOCAL function (not ``self.<m>``)
  stands down entirely.
"""
import threading


class GuardedBatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0               # pre-thread store: not an entry
        self._thread = threading.Thread(target=self._run)

    def start(self):
        self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                self._pending -= 1

    def submit(self, item):
        with self._lock:
            self._pending += 1
        return item


class LocalTargetStandsDown:
    def __init__(self):
        self._pending = 0

        def worker():
            self._pending -= 1

        self._thread = threading.Thread(target=worker)

    def submit(self, item):
        self._pending += 1              # unguarded, but no self-target
        return item
