"""GL101 near-miss: clocks and spans at the HOST call sites (clean).

Timing the dispatch loop — outside any traced scope — is exactly what
observability/spans.py is for; the rule must not fire on the legitimate
pattern the trainer uses."""
import time

import jax
import jax.numpy as jnp

from byol_tpu.observability import spans


@jax.jit
def step(x):
    return jnp.dot(x, x)


def timed_epoch(batches):
    """Host-side span + clock around the traced call: legitimate."""
    t0 = time.perf_counter()
    out = None
    for b in batches:
        with spans.span("train/dispatch"):
            out = step(b)
    with spans.span("train/epoch_readback"):
        total = float(jnp.sum(out))
    return total, time.perf_counter() - t0
