"""Fixture: a plan whose resident train entry donates arg 0 — the
resident flat buffers (momentum/target/shadow) live inside that state,
so GL113 must see the donation through the builder indirection."""
import jax

DONATE = {
    "train_step": (0,),
}


class Plan:
    def jit_train_step(self, fn):
        return jax.jit(fn, donate_argnums=DONATE["train_step"])
