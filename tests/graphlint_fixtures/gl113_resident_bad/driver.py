"""Fixture: the driver holds a host reference to last step's resident
flat buffer across the donating call — ``shadow`` aliases the donated
state's buffer, and so does the direct ``state.flat_shadow`` read after
the donation (the cross-module resident reuse-after-donate)."""
from .wiring import train_step


def train(state, batches, sink):
    history = []
    for batch in batches:
        new_state, metrics = train_step(state, batch)  # donates state
        sink.offer(state.flat_shadow)  # GL113: resident buffer is dead
        state = new_state
        history.append(metrics)
    return state, history
