"""GL110 must fire: lenient json writers that can emit bare NaN tokens."""
import json


def write_metrics(path, metrics):
    # BAD: no allow_nan kwarg — the lenient default serializes a NaN
    # loss as the bare token `NaN`, which strict parsers reject
    with open(path, "w") as f:
        json.dump(metrics, f)


def render_line(metrics):
    # BAD: explicitly lenient
    return json.dumps(metrics, allow_nan=True)
