"""GL103 near-miss: split / fold_in / per-iteration rebind (clean)."""
import jax


def sample(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (4,))
    b = jax.random.uniform(k2, (4,))
    return a + b


def per_step(key, step):
    k = jax.random.fold_in(key, step)   # derivation, not reuse
    return jax.random.normal(k, (4,))


def rolling(key, n):
    outs = []
    for _ in range(n):
        key, sub = jax.random.split(key)   # rebound every iteration
        outs.append(jax.random.normal(sub, (4,)))
    return outs
