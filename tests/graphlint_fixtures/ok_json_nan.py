"""GL110 near-misses that must stay clean."""
import json
import math


def _sanitize(obj):
    if isinstance(obj, float) and not math.isfinite(obj):
        return "NaN"
    return obj


def write_metrics(path, metrics):
    # OK: strict writer (the events.py discipline)
    with open(path, "w") as f:
        json.dump({k: _sanitize(v) for k, v in metrics.items()}, f,
                  allow_nan=False)


def render_line(metrics):
    # OK: strict
    return json.dumps(metrics, allow_nan=False)


def forward(metrics, **kwargs):
    # OK: a **kwargs splat may carry allow_nan invisibly — stand down
    return json.dumps(metrics, **kwargs)


def computed(metrics, strict):
    # OK: non-literal allow_nan cannot be judged statically
    return json.dumps(metrics, allow_nan=not strict)


def loads_is_not_a_writer(line):
    # OK: the reader has no NaN-emission hazard
    return json.loads(line)
