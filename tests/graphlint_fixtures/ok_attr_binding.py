"""Fixture near-miss (stand-down pins for the wave-4 forwarder arm):

- the forwarder name is NOT unique project-wide (two classes define
  ``jit_embed``), so the unresolvable-receiver fallback must stand down
  even though the passed function contains host sync;
- a forwarder invoked through ``**kwargs`` plumbing never resolves its
  staged argument.
"""
import time

import jax


def _represent(batch):
    time.time()       # never proven traced: receiver/kwargs stand down
    return batch


class PlanA:
    def jit_embed(self, fn):
        return jax.jit(fn)


class PlanB:
    def jit_embed(self, fn):
        return fn                      # same name, different semantics


class Engine:
    def __init__(self, plan, cfg):
        # receiver unresolvable + 'jit_embed' ambiguous project-wide
        self._jitted = plan.jit_embed(_represent)
        # **kwargs plumbing: the staged argument never resolves
        self._other = plan.jit_embed(**cfg)
