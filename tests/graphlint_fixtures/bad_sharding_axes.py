"""GL107 must-fire corpus: sharding-spec drift.

Three bugs:
1. a PartitionSpec string literal naming an axis the declared vocabulary
   (DATA_AXIS / AXIS_NAMES below) does not contain — the classic typo that
   silently replicates what the author believed was sharded;
2. the same drift routed through a module-level string constant;
3. a ``jax.jit(..., in_shardings=...)`` outside parallel/compile_plan.py —
   a per-site sharding decision the compile plan exists to forbid.
"""
import functools

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
AXIS_NAMES = (DATA_AXIS, MODEL_AXIS)

GHOST_AXIS = "modle"          # the typo'd spelling of 'model'


def constrain(x):
    # BUG: 'dataa' is not a declared axis
    return jax.lax.with_sharding_constraint(x, P("dataa", None))


def constrain_via_const(x):
    # BUG: the constant resolves to 'modle', which nothing declares
    return jax.lax.with_sharding_constraint(x, P(GHOST_AXIS))


def jit_with_inline_shardings(mesh, fn):
    # BUG: in_shardings outside parallel/compile_plan.py
    sharded = NamedSharding(mesh, P(DATA_AXIS))
    return jax.jit(fn, in_shardings=(sharded,))


def partial_jit_with_inline_shardings(mesh, fn):
    # BUG: same hazard through functools.partial
    rep = NamedSharding(mesh, P())
    return functools.partial(jax.jit, out_shardings=rep)(fn)
