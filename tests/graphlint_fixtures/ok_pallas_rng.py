"""GL111 near-miss: randomness drawn OUTSIDE the pallas_call.

The in-tree contract (ops/fused_augment.py): stochastic parameters come
from the key stream on the host side of the call and reach the kernel as
operands — the kernel body is a deterministic function of its inputs.
The jax.random.* calls in the WRAPPER must not fire the rule.
"""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _jitter_kernel(x_ref, n_ref, o_ref):
    o_ref[...] = x_ref[...] + n_ref[...]          # noise is an operand


def jitter(key, x, interpret=False):
    noise = jax.random.uniform(key, x.shape)      # outside the kernel: ok
    return pl.pallas_call(
        _jitter_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, noise)
