"""GL111 must fire: jax.random.* inside a Pallas kernel body.

The uniform draw below only "works" under interpret= — threefry has no
Mosaic lowering, so CPU tier-1 would pass while the TPU build breaks.
The helper indirection must not hide it: the rule closes over bare-name
calls from the kernel body.
"""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _noise(shape):
    return jax.random.uniform(jax.random.PRNGKey(0), shape)   # in-kernel!


def _jitter_kernel(x_ref, o_ref):
    x = x_ref[...]
    o_ref[...] = x + _noise(x.shape)


def jitter(x, interpret=False):
    return pl.pallas_call(
        _jitter_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)


def _seeded_kernel(x_ref, o_ref, *, scale):
    o_ref[...] = x_ref[...] * scale + _noise(x_ref.shape)   # in-kernel!


def jitter_partial(x, interpret=False):
    # the partial-bound spelling (ops/fused_augment.py shape): the rule
    # must resolve `kernel = functools.partial(fn, ...)` too
    import functools
    kernel = functools.partial(_seeded_kernel, scale=2.0)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)
