"""Fixture: the JIT SITE — module A jits a function imported from module
B (impl.py).  No finding lands in this file; the findings land at the
definition site in impl.py, carrying this file's jit line."""
import jax

from .impl import step_impl

train_step = jax.jit(step_impl)
