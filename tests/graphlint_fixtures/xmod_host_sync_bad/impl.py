"""Fixture: the step IMPLEMENTATION — clean module-locally (nothing here
jits anything), but jit_site.py jits `step_impl`, so wave-3 propagation
must mark this def traced and fire GL101 at these lines with the jit
site named."""
import time

import numpy as np


def _metrics(y):
    # reached transitively from the traced def: also in traced scope
    return np.mean(y)


def step_impl(state, batch):
    t0 = time.perf_counter()          # GL101: host clock under the trace
    y = np.asarray(batch)             # GL101: host materialization
    m = _metrics(y)                   # GL101 fires inside _metrics too
    return state, (m, t0)
