"""GL101 near-miss: shape arithmetic and host code outside traces (clean)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    b = x.shape[0]
    scale = np.sqrt(b)            # numpy on a STATIC shape value: fine
    return x * float(scale)       # float() of a non-array: fine


def epoch_metrics(metrics):
    # host-side readback OUTSIDE any traced scope is legitimate
    return {k: float(np.asarray(v)) for k, v in metrics.items()}
