"""GL106 near-miss: every field settable, every flag consumed (clean)."""
import argparse
import dataclasses


@dataclasses.dataclass(frozen=True)
class TidyCfg:
    lr: float = 0.1
    momentum: float = 0.9


@dataclasses.dataclass(frozen=True)
class TidyTelemetryCfg:
    """Telemetry-shaped near-miss (ISSUE 6 corpus): every observability
    knob settable from a flag and every flag consumed — the wiring the
    real --telemetry*/--nan-policy flags keep (and the tree gate pins)."""

    telemetry: str = "off"
    telemetry_interval: int = 50
    nan_policy: str = "warn"


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--telemetry", type=str, default="off")
    p.add_argument("--telemetry-interval", type=int, default=50)
    p.add_argument("--nan-policy", type=str, default="warn")
    return p


def config_from_args(args):
    return TidyCfg(lr=args.lr, momentum=args.momentum), TidyTelemetryCfg(
        telemetry=args.telemetry,
        telemetry_interval=args.telemetry_interval,
        nan_policy=args.nan_policy)
