"""GL106 near-miss: every field settable, every flag consumed (clean)."""
import argparse
import dataclasses


@dataclasses.dataclass(frozen=True)
class TidyCfg:
    lr: float = 0.1
    momentum: float = 0.9


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    return p


def config_from_args(args):
    return TidyCfg(lr=args.lr, momentum=args.momentum)
