"""GL102 fixture: recompile hazards (must fire)."""
import jax
import jax.numpy as jnp


def fn(x, cfg):
    return x


def run_all(fns, xs):
    outs = []
    for f in fns:
        outs.append(jax.jit(f)(xs))     # fresh wrapper + empty cache per iter
    return outs


step = jax.jit(fn, static_argnums=(1,))


def call_with_unhashable(x):
    return step(x, [1, 2, 3])           # list in a static position


def make_step(scale):
    w = jnp.ones((3,)) * scale          # outer-scope array local

    @jax.jit
    def inner(z):
        return z + w                    # baked in as a compile-time constant
    return inner
