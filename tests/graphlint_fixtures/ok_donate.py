"""GL104 near-miss: result rebound over the donated input (clean)."""
import jax


def step_fn(state, batch):
    return state, {}


train_step = jax.jit(step_fn, donate_argnums=(0,))


def loop(state, batches):
    metrics = None
    for batch in batches:
        state, metrics = train_step(state, batch)   # canonical rebind
    return state, metrics
