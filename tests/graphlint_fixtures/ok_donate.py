"""GL104 near-miss: result rebound over the donated input (clean)."""
import jax


def step_fn(state, batch):
    return state, {}


train_step = jax.jit(step_fn, donate_argnums=(0,))


def loop(state, batches):
    metrics = None
    for batch in batches:
        state, metrics = train_step(state, batch)   # canonical rebind
    return state, metrics


def telemetry_loop(state, batches, sink):
    """Telemetry-shaped near-miss (ISSUE 6 corpus): the sink consumes the
    step's health OUTPUT — a fresh array, never an alias of the donated
    input state — and the state is rebound.  Must stay clean."""
    for step, batch in enumerate(batches):
        state, metrics = train_step(state, batch)   # rebind over donation
        sink.offer(step, metrics["health"])         # output, not the input
    return state
