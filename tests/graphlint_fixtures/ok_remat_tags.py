"""GL105 near-miss: tagged block matching the declared policy name (clean)."""
import flax.linen as nn
import jax
from jax.ad_checkpoint import checkpoint_name

GOOD_POLICY = jax.checkpoint_policies.save_only_these_names(
    "fixture_good_out")


class TaggedBlock(nn.Module):
    def __call__(self, x):
        return checkpoint_name(x * 2.0, "fixture_good_out")


def build():
    return nn.remat(TaggedBlock, policy=GOOD_POLICY)
