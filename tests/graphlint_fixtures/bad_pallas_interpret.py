"""GL109 must fire: pallas_call with no interpret= fallback.

A kernel spelled like this compiles Mosaic-only — CPU tier-1 and CI can
never execute it, so its numerics are untested off-TPU.
"""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _double_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def double(x):
    return pl.pallas_call(                       # no interpret= anywhere
        _double_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
