"""Fixture near-miss driver: the resident buffer is probed from the
STEP OUTPUT after the rebind — a fresh buffer, never an alias of the
donated input — and the non-donating eval entry reads state freely."""
from .wiring import eval_step, train_step


def train(state, batches, sink):
    history = []
    for batch in batches:
        state, metrics = train_step(state, batch)   # rebind over donation
        sink.offer(state.flat_shadow)   # this step's OUTPUT buffer: fine
        history.append(metrics)
    return state, history


def evaluate(state, batches):
    out = []
    for batch in batches:
        out.append(eval_step(state, batch))   # state read-only: no donation
    return state, out
