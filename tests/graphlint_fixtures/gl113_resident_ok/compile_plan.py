"""Fixture near-miss plan: same shape as gl113_resident_bad — donating
resident train entry plus a read-only eval entry."""
import jax

DONATE = {
    "train_step": (0,),
    "eval_step": (),
}


class Plan:
    def jit_train_step(self, fn):
        return jax.jit(fn, donate_argnums=DONATE["train_step"])

    def jit_eval_step(self, fn):
        return jax.jit(fn)
