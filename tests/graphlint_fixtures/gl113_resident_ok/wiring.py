"""Fixture near-miss wiring: binds both resident entry points."""
from .compile_plan import Plan

plan = Plan()


def _step(state, batch):
    return state, batch


train_step = plan.jit_train_step(_step)
eval_step = plan.jit_eval_step(_step)
