"""Fixture: the serving/engine.py:85 spelling — a jitted entry point
bound as ``self._jitted = plan.jit_embed(fn)``.  ``jit_embed`` is a
tracing FORWARDER (its param is staged via ``jax.jit`` in the body), so
the module-level function passed at the binding site runs under a trace
and its host sync must be flagged at the true definition site."""
import time

import jax


def _represent(batch):
    time.time()                       # GL101: host clock under trace
    return batch


class Plan:
    def jit_embed(self, fn):
        return jax.jit(fn, donate_argnums=(0,))


class Engine:
    def __init__(self, plan):
        self._jitted = plan.jit_embed(_represent)   # the binding site

    def embed(self, batch):
        return self._jitted(batch)
