"""GL102 near-miss: hoisted jit, hashable statics, inner locals (clean)."""
import jax
import jax.numpy as jnp


def fn(x, cfg):
    return x


step = jax.jit(fn, static_argnums=(1,))


def run_all(batches):
    outs = []
    for b in batches:                   # jit built ONCE, called in the loop
        outs.append(step(b, (1, 2, 3)))  # tuple static: hashable and stable
    return outs


def make_step(scale):
    @jax.jit
    def inner(z):
        y = jnp.ones((3,)) * scale      # inner's OWN local, not a capture
        return z + y
    return inner


def make_other(x):
    def sibling():
        arr = jnp.zeros((2,))           # a SIBLING scope's local
        return arr

    @jax.jit
    def inner(z):
        return z * 2.0                  # touches neither w nor arr
    return inner(x), sibling()
