"""Fixture near-miss: a plan whose builders wire exactly what DONATE
declares (the shipped parallel/compile_plan.py shape) — GL112 must stay
silent."""
import jax

DONATE = {
    "train_step": (0,),
    "eval_step": (),
}


class Plan:
    def jit_train_step(self, fn, state_sharding):
        return jax.jit(fn,
                       in_shardings=(state_sharding, None),
                       out_shardings=(state_sharding, None),
                       donate_argnums=DONATE["train_step"])

    def jit_eval_step(self, fn, state_sharding):
        return jax.jit(fn, in_shardings=(state_sharding, None))
