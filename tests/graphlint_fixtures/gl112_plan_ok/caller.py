"""Fixture near-miss: every entry is used through its builder; the one
inline jit stages a NON-entry helper, which is no business of the plan's."""
import jax

from .compile_plan import Plan


def train_step(state, batch):
    return state, batch


def eval_step(state, batch):
    return batch


def _preprocess(batch):
    return batch


plan = Plan()
step = plan.jit_train_step(train_step, None)
evaluate = plan.jit_eval_step(eval_step, None)

# not a plan entry: per-site wiring of private helpers is GL107's beat,
# not a plan-contract violation
prep = jax.jit(_preprocess, donate_argnums=(0,))
