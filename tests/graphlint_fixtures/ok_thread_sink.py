"""Fixture near-miss for GL115:

- every sink write (worker and public side) holds the same lock;
- a sink only ever written from the worker thread is single-writer and
  legal even without a lock;
- attributes that are not recognized sink constructors never count.
"""
import threading

from byol_tpu.observability.events import RunLog


class GuardedTelemetry:
    def __init__(self, path, transport):
        self._lock = threading.Lock()
        self.events = RunLog(path)
        self._worker_log = open(path + ".txt", "a")
        self._transport = transport          # opaque: not a sink
        self._thread = threading.Thread(target=self._run)

    def start(self):
        self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                self.events.emit("tick")
            self._worker_log.write("tick\n")  # worker-only: single writer

    def record(self, name):
        with self._lock:
            self.events.emit(name)
        self._transport.write(name)           # unresolvable sink: stand down
