"""GL108 near-miss corpus: everything here must stay clean.

Collectives over axes that ARE bound (by a vmap in this module, or by the
declared mesh vocabulary that shard_map/GSPMD binds at runtime), spelled
directly, via module constants, and in tuple form; plus the wrapper
pattern — an axis name arriving as a function parameter is unresolvable
and the rule must stand down, not guess.
"""
import jax
from jax import lax

DATA_AXIS = "data"
MODEL_AXIS = "model"
AXIS_NAMES = (DATA_AXIS, MODEL_AXIS)

ACCUM_AXIS = "accum"


def microbatch_mean(xs):
    def body(x):
        # bound by the surrounding vmap below — fine
        return lax.pmean(x * x, ACCUM_AXIS)
    return jax.vmap(body, axis_name=ACCUM_AXIS)(xs)


def mesh_reduce(x):
    # 'data' is a declared mesh axis (AXIS_NAMES): shard_map binds it
    return lax.psum(x, DATA_AXIS)


def mesh_reduce_tuple(x):
    # tuple form over declared axes only
    return lax.psum(x, (DATA_AXIS, "model"))


def wrapped_psum(x, axis_name=DATA_AXIS):
    # parameter axis: unresolvable — the rule must not guess
    return lax.psum(x, axis_name)


def my_rank():
    # axis_index over a declared mesh axis
    return lax.axis_index("data")
