"""GL104 fixture: resident-buffer reuse-after-donate (must fire).

Under ``--flat-resident on`` the flat momentum/target/shadow buffers ride
the donated state argument, so donating the state kills every resident
buffer reachable from it.  Holding last step's ``state.flat_shadow`` on
the host (for telemetry, a debug dump, ...) after the donating call reads
a buffer XLA already reused in place.
"""
import jax


def step_fn(state, batch):
    return state, {}


train_step = jax.jit(step_fn, donate_argnums=(0,))


def loop_with_shadow_probe(state, batches, sink):
    for batch in batches:
        new_state, metrics = train_step(state, batch)  # donates state
        sink.offer(state.flat_shadow)   # dead: the resident buffer rode
        state = new_state               # the donated state argument
    return state
