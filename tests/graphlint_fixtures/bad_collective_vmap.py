"""GL108 must-fire corpus: collectives over axis names nothing binds.

Three bugs:
1. a ``lax.pmean`` over ``'batch'`` inside a function vmapped with
   ``axis_name='i'`` — the classic rename drift: the vmap's axis was
   renamed, the collective inside was not, and the NameError fires at the
   vmap call site instead of here;
2. the same drift spelled through a module constant;
3. an ``all_gather`` over an axis neither any vmap nor the declared mesh
   vocabulary (AXIS_NAMES below) contains.
"""
import jax
from jax import lax

DATA_AXIS = "data"
AXIS_NAMES = (DATA_AXIS,)

STALE_AXIS = "microbatch"     # the pre-rename spelling nothing binds now


def microbatch_mean(xs):
    def body(x):
        # BUG: the surrounding vmap binds 'i', not 'batch'
        return lax.pmean(x * x, "batch")
    return jax.vmap(body, axis_name="i")(xs)


def microbatch_sum(xs):
    def body(x):
        # BUG: STALE_AXIS resolves to 'microbatch', which nothing binds
        return lax.psum(x, STALE_AXIS)
    return jax.vmap(body, axis_name="i")(xs)


def gather_everything(x):
    # BUG: 'shards' is neither a vmap axis nor a declared mesh axis
    return lax.all_gather(x, "shards")
