"""Fixture near-miss: containers whose slots stay legal — literal built
AFTER the rebinding donation, a container literal REBOUND over the stale
one, and a NON-literal container (stands down, zero-false-positive)."""
from .wiring import train_step


def literal_after_rebind(state, batch):
    state, _ = train_step(state, batch)     # result rebound over input
    bundle = (state, batch)                 # holds the fresh buffer
    return bundle[0]


def container_rebound(state, batch):
    ckpt = {"state": state}
    new_state, _ = train_step(state, batch)
    ckpt = {"state": new_state}             # slots dropped with the rebind
    return ckpt["state"]


def non_literal_stands_down(state, batch, pack):
    bundle = pack(state, batch)             # opaque container: stand down
    new_state, _ = train_step(state, batch)
    return bundle[0], new_state
