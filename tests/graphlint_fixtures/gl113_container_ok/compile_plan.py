"""Fixture near-miss plan: same shape as gl113_container_bad."""
import jax

DONATE = {
    "train_step": (0,),
}


class Plan:
    def jit_train_step(self, fn):
        return jax.jit(fn, donate_argnums=DONATE["train_step"])
