"""GL109 near-miss: pallas_call WITH the interpret= fallback plumbed.

The in-tree pattern (ops/flash_attention.py, ops/fused_update.py): the
caller-facing wrapper resolves ``interpret`` from config/backend detection
and passes it through, so CPU environments run the identical kernel under
the Pallas interpreter.
"""
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _double_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def double(x, interpret: Optional[bool] = None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return pl.pallas_call(
        _double_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x)
