"""Fixture: GL114 — an instance attribute mutated both on the spawned
worker thread and in a public method, with no common lock on the two
sites (the submit/close TOCTOU shape past PR reviews caught by hand)."""
import threading


class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = 0
        self._thread = threading.Thread(target=self._run)

    def start(self):
        self._thread.start()

    def _run(self):
        while True:
            self._pending -= 1          # GL114: worker-side store

    def submit(self, item):
        self._pending += 1              # public-side store, no common lock
        return item
