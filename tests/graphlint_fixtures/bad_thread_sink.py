"""Fixture: GL115 — non-thread-safe sinks (RunLog, open()-file) written
from both the spawned worker thread and a public method with no common
lock; interleaved writers corrupt the JSONL stream byte-wise."""
import threading

from byol_tpu.observability.events import RunLog


class Telemetry:
    def __init__(self, path):
        self._lock = threading.Lock()
        self.events = RunLog(path)
        self._raw = open(path + ".txt", "a")
        self._thread = threading.Thread(target=self._run)

    def start(self):
        self._thread.start()

    def _run(self):
        while True:
            self.events.emit("tick")        # GL115: RunLog, worker side
            self._raw.write("tick\n")       # GL115: file, worker side

    def record(self, name):
        self.events.emit(name)              # public side, no common lock
        self._raw.write(name + "\n")
