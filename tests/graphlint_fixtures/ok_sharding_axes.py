"""GL107 near-miss corpus: everything here must stay clean.

Specs name only declared axes (directly, via the module constants, and as
literals matching the declared vocabulary); ``None`` entries and
unresolvable dynamic specs are never judged; a plain ``jax.jit`` with no
sharding kwargs is not a plan violation; and a spec built from a name the
linter cannot resolve (a function argument) is left alone rather than
guessed at.
"""
import jax
from jax.sharding import PartitionSpec as P

DATA_AXIS = "data"
SEQUENCE_AXIS = "sequence"
MODEL_AXIS = "model"
AXIS_NAMES = (DATA_AXIS, SEQUENCE_AXIS, MODEL_AXIS)


def constrain(x):
    return jax.lax.with_sharding_constraint(x, P(DATA_AXIS, None))


def constrain_literal(x):
    # literal spelling of a declared axis: fine
    return jax.lax.with_sharding_constraint(x, P("model"))


def constrain_nested(x):
    # tuple entry naming declared axes only
    return jax.lax.with_sharding_constraint(x, P((DATA_AXIS, "sequence"),
                                                 None))


def constrain_dynamic(x, axis_name):
    # unresolvable name: the rule must stand down, not guess
    return jax.lax.with_sharding_constraint(x, P(axis_name))


@jax.jit
def plain_jit(x):
    # jit without sharding kwargs is not a plan violation
    return x + 1
