"""GL103 fixture: PRNG key consumed twice (must fire)."""
import jax


def sample(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))   # same key again: identical randomness
    return a + b
