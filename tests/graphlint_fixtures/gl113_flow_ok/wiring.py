"""Fixture near-miss wiring: binds both entry points; the local caller
rebinds the result over the donated input (the legal pattern)."""
from .compile_plan import Plan

plan = Plan()


def _step(state, batch):
    return state, batch


train_step = plan.jit_train_step(_step)
eval_step = plan.jit_eval_step(_step)


def local_ok(state, batch):
    state, metrics = train_step(state, batch)
    return state, metrics
