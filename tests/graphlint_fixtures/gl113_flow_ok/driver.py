"""Fixture near-miss driver: the loop rebinds the donated state every
iteration, and the NON-donating eval entry may reuse its inputs freely."""
from .wiring import eval_step, train_step


def train(state, batches):
    history = []
    for batch in batches:
        state, metrics = train_step(state, batch)
        history.append(metrics)
    return state, history


def evaluate(state, batches):
    out = []
    for batch in batches:
        out.append(eval_step(state, batch))   # state read-only: no donation
    return state, out
