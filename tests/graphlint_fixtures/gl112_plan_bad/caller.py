"""Fixture: call sites that bypass / disagree with the imported plan —
the GL112 arms that live OUTSIDE the plan module."""
import jax

from .compile_plan import Plan


def train_step(state, batch):
    return state, batch


plan = Plan()
wired = plan.jit_train_step(train_step, None)
used_eval = plan.jit_eval_step(train_step)

# GL112-bypass: donation agrees with DONATE["train_step"] but the entry
# is jitted here with inline shardings instead of through the builder
bypassed = jax.jit(train_step,
                   in_shardings=(None, None),
                   donate_argnums=(0,))

# GL112-mismatch: inline sharding kwarg present and the donation (none)
# disagrees with the declared (0,)
undonated = jax.jit(train_step, out_shardings=None)

# GL112-donate-undeclared: donates argument 1, which DONATE never declares
overdonated = jax.jit(train_step, donate_argnums=(0, 1))
