"""Fixture: a compile plan whose wiring DISAGREES with its own DONATE
declaration — every GL112 arm that lives inside the plan module.

`legacy_probe_step` is declared but no jit_legacy_probe_step call site
exists anywhere -> GL112-unused-entry.
"""
import jax

DONATE = {
    "train_step": (0,),
    "eval_step": (),
    "legacy_probe_step": (0,),      # GL112-unused-entry: nobody calls it
}


class Plan:
    def jit_train_step(self, fn, state_sharding):
        # GL112-donate-undeclared: donates argument 1 on top of the
        # declared (0,)
        return jax.jit(fn,
                       in_shardings=(state_sharding, None),
                       donate_argnums=(0, 1))

    def jit_eval_step(self, fn):
        # GL112-mismatch: wires ANOTHER entry's declaration
        return jax.jit(fn, donate_argnums=DONATE["train_step"])

    def jit_legacy_probe_step(self, fn):
        return jax.jit(fn, donate_argnums=DONATE["legacy_probe_step"])
