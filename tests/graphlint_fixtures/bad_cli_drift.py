"""GL106 fixture: dead config knob + unread flag (must fire)."""
import argparse
import dataclasses


@dataclasses.dataclass(frozen=True)
class DriftyCfg:
    lr: float = 0.1
    momentum: float = 0.9        # no builder passes it: dead knob


@dataclasses.dataclass(frozen=True)
class DriftyTelemetryCfg:
    """Telemetry-shaped GL106 case (ISSUE 6 corpus): the observability
    knobs are exactly the kind that rot — a sink interval nothing can set
    and a nan-policy flag nothing reads would silently un-observe a run."""

    telemetry: str = "off"
    telemetry_interval: int = 50   # no builder passes it: dead knob


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--drifty-ghost", type=int, default=0)  # never read
    p.add_argument("--telemetry", type=str, default="off")
    p.add_argument("--nan-ghost-policy", type=str, default="warn")  # unread
    return p


def config_from_args(args):
    return DriftyCfg(lr=args.lr), DriftyTelemetryCfg(
        telemetry=args.telemetry)
