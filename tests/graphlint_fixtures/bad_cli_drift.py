"""GL106 fixture: dead config knob + unread flag (must fire)."""
import argparse
import dataclasses


@dataclasses.dataclass(frozen=True)
class DriftyCfg:
    lr: float = 0.1
    momentum: float = 0.9        # no builder passes it: dead knob


def build_parser():
    p = argparse.ArgumentParser()
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--drifty-ghost", type=int, default=0)  # never read
    return p


def config_from_args(args):
    return DriftyCfg(lr=args.lr)
