"""Fixture near-miss jit site: jits the clean imported def and calls the
host-clock helper OUTSIDE the trace (the legal pattern: time the
dispatch, not the graph)."""
import jax

from .impl import step_impl, wall_clock

train_step = jax.jit(step_impl)


def timed_dispatch(state, batch):
    t0 = wall_clock()
    state, m = train_step(state, batch)
    return state, m, wall_clock() - t0
