"""Fixture near-miss: same two-module shape as xmod_host_sync_bad, but
the imported def is trace-clean (jnp only) and the host clock lives in a
helper that is NOT reachable from the traced def — cross-module
propagation must not over-mark."""
import time

import jax.numpy as jnp


def wall_clock():
    # host clock, but only ever called from untraced dispatch code
    return time.perf_counter()


def step_impl(state, batch):
    y = jnp.asarray(batch)
    return state, jnp.mean(y)
