"""Fixture: host sync inside a def staged for tracing through a REBOUND
``functools.partial`` chain — wave-4 value flow (tools/graphlint/flow.py)
must follow ``step = partial(step)`` back through the chain to the def
and mark it traced."""
import functools
import time

import jax


def _step(state, scale):
    time.time()                       # GL101: host clock under trace
    return state


def build():
    step = functools.partial(_step, scale=2.0)
    step = functools.partial(step)    # rebound chain hop
    return jax.jit(step)
