"""GL101 fixture: host clocks / span recording inside traced code (fires).

Everything here runs ONCE at trace time and is constant-folded into the
executable — the "timings" are frozen compile-time values that measure
nothing per step (the exact failure mode the spans-module docstring and
the ISSUE 9 satellite name)."""
import time

import jax
import jax.numpy as jnp

from byol_tpu.observability import spans


@jax.jit
def timed_step(x):
    t0 = time.perf_counter()          # constant-folded: trace-time clock
    y = jnp.sum(x * x)
    elapsed = time.perf_counter() - t0   # always ~the trace duration
    return y, elapsed


@jax.jit
def spanned_step(x):
    with spans.span("train/dispatch"):   # opens/closes once, at trace time
        return jnp.dot(x, x)


def scan_body(carry, x):
    wall = time.time()                # same bug under lax.scan's trace
    return carry + x, wall


def run(carry, xs):
    return jax.lax.scan(scan_body, carry, xs)
