"""GL104 near-miss: resident buffer read from the STEP OUTPUT (clean).

The legal way to observe the resident flat buffers is through the fresh
state the donating step returns — after the rebind, ``state.flat_shadow``
is this step's output buffer, never an alias of the donated input.
"""
import jax


def step_fn(state, batch):
    return state, {}


train_step = jax.jit(step_fn, donate_argnums=(0,))


def loop_with_shadow_probe(state, batches, sink):
    for batch in batches:
        state, metrics = train_step(state, batch)   # rebind over donation
        sink.offer(state.flat_shadow)   # fresh output buffer: fine
    return state
