"""GL105 fixture: names-based policy over an untagged block (must fire)."""
import flax.linen as nn
import jax

UNTAGGED_POLICY = jax.checkpoint_policies.save_only_these_names(
    "fixture_block_out")


class UntaggedBlock(nn.Module):
    def __call__(self, x):
        return x * 2.0              # no checkpoint_name tag: policy saves
                                    # NOTHING, silently


def build():
    return nn.remat(UntaggedBlock, policy=UNTAGGED_POLICY)
