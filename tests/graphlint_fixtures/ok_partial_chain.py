"""Fixture near-miss: the partial chain is BROKEN by an opaque call —
the staged callable does not resolve statically, so the def's host sync
must NOT be attributed to a trace (zero-false-positive stand-down)."""
import functools
import time

import jax


def _step(state, scale):
    time.time()          # host-side is fine: _step is never proven traced
    return state


def _decorate(fn):
    return fn


def build():
    step = functools.partial(_step, scale=2.0)
    step = _decorate(step)            # opaque hop: chain stands down
    return jax.jit(step)
