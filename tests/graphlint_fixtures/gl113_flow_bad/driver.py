"""Fixture: the driver module — imports the donor binding from wiring.py
and re-donates the same state every loop iteration without rebinding
(the canonical cross-module use-after-donate)."""
from .wiring import train_step


def train(state, batches):
    history = []
    for batch in batches:
        new_state, metrics = train_step(state, batch)  # GL113 on pass 2
        history.append(metrics)
    return new_state, history
