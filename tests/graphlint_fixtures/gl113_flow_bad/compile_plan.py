"""Fixture: a well-formed plan (GL112-clean) whose train entry donates
arg 0 — the donation GL113's flow analysis must see through the builder
indirection."""
import jax

DONATE = {
    "train_step": (0,),
}


class Plan:
    def jit_train_step(self, fn):
        return jax.jit(fn, donate_argnums=DONATE["train_step"])
