"""Fixture: the wiring module — binds the donating entry point at module
level, local use-after-donate included."""
from .compile_plan import Plan

plan = Plan()


def _step(state, batch):
    return state, batch


train_step = plan.jit_train_step(_step)


def local_reuse(state, batch):
    new_state, metrics = train_step(state, batch)
    return new_state, metrics, state    # GL113: `state` was donated
