"""GL104 fixture: use-after-donate (must fire)."""
import jax


def step_fn(state, batch):
    return state, {}


train_step = jax.jit(step_fn, donate_argnums=(0,))


def loop(state, batches):
    for batch in batches:
        new_state, metrics = train_step(state, batch)  # donates, no rebind
    return state                                       # reads a dead buffer
