"""GL104 fixture: use-after-donate (must fire)."""
import jax


def step_fn(state, batch):
    return state, {}


train_step = jax.jit(step_fn, donate_argnums=(0,))


def loop(state, batches):
    for batch in batches:
        new_state, metrics = train_step(state, batch)  # donates, no rebind
    return state                                       # reads a dead buffer


def telemetry_loop(state, batches, sink):
    """Telemetry-shaped GL104 case (ISSUE 6 corpus): offering the DONATED
    state to the sink instead of the step's health OUTPUT — the packed
    health vector is a fresh step output and never aliases the donated
    buffer; reading the donated state back is the bug."""
    for batch in batches:
        new_state, metrics = train_step(state, batch)  # donates state
        sink.offer(state)              # dead: state was donated above
        state = new_state
    return state
