"""Full head-path per-step numerical parity vs torch.

SURVEY.md §4 calls for a reference-vs-new per-step parity harness.  The
reference's backbone comes from torchvision (not installed here), but every
line of its own first-party math — projector/predictor MLPs with BN1d
(main.py:194-205), the symmetrized whole-tensor-Frobenius loss
(objective.py:6-25), backward, SGD-momentum step, and the EMA target update
(main.py:159-162) — is reproduced in torch IN THIS TEST and compared
against the byol_tpu implementation on identical weights and a fixed
feature batch: loss, gradients, post-step parameters, EMA'd target
parameters, and BN running statistics must all agree.

The single deliberate delta this pins: torch's BatchNorm updates running_var
with the UNBIASED batch variance while flax uses the biased one — the test
asserts the exact B/(B-1) relationship rather than papering over it.
"""
import numpy as np
import pytest
import torch
import torch.nn as tnn

import jax
import jax.numpy as jnp
import optax

from byol_tpu.models.heads import MLPHead
from byol_tpu.objectives.byol_loss import loss_function

F_IN, HID, OUT, B = 16, 32, 8, 12
LR, MOM, TAU = 0.1, 0.9, 0.99


def _torch_head(in_dim):
    return tnn.Sequential(tnn.Linear(in_dim, HID), tnn.BatchNorm1d(HID),
                          tnn.ReLU(), tnn.Linear(HID, OUT))


def _to_flax(seq):
    """torch Sequential(Linear, BN1d, ReLU, Linear) -> MLPHead variables."""
    def w(t):
        return jnp.asarray(t.detach().numpy())
    l1, bn, _, l2 = seq
    params = {"dense1": {"kernel": w(l1.weight).T, "bias": w(l1.bias)},
              "bn": {"scale": w(bn.weight), "bias": w(bn.bias)},
              "dense2": {"kernel": w(l2.weight).T, "bias": w(l2.bias)}}
    stats = {"bn": {"mean": w(bn.running_mean), "var": w(bn.running_var)}}
    return params, stats


def _flax_forward(head, params, stats, x1, x2):
    """Both views through one head, chaining BN running-stat updates the way
    two sequential torch forward calls do."""
    o1, upd = head.apply({"params": params, "batch_stats": stats}, x1,
                         train=True, mutable=["batch_stats"])
    o2, upd = head.apply({"params": params,
                          "batch_stats": upd["batch_stats"]}, x2,
                         train=True, mutable=["batch_stats"])
    return o1, o2, upd["batch_stats"]


class TestHeadPathStepParity:
    def test_loss_grads_step_ema_and_bn_stats_match_torch(self):
        torch.manual_seed(0)
        rng = np.random.RandomState(0)
        f1 = rng.rand(B, F_IN).astype(np.float32)
        f2 = rng.rand(B, F_IN).astype(np.float32)

        # ---- torch reference step (main.py semantics) --------------------
        proj, pred, tproj = _torch_head(F_IN), _torch_head(OUT), \
            _torch_head(F_IN)
        p1 = pred(proj(torch.from_numpy(f1)))
        p2 = pred(proj(torch.from_numpy(f2)))
        with torch.no_grad():       # target branch: train-mode BN, no grads
            t1 = tproj(torch.from_numpy(f1))
            t2 = tproj(torch.from_numpy(f2))

        def reg(x, y):              # objective.py:6-10 (whole-tensor norms)
            return -2.0 * (x * y).sum(-1) / (x.norm() * y.norm())

        loss_t = (reg(p1, t2) + reg(p2, t1)).mean()
        opt = torch.optim.SGD(list(proj.parameters())
                              + list(pred.parameters()), lr=LR, momentum=MOM)
        loss_t.backward()
        grad_t = proj[0].weight.grad.detach().numpy().copy()
        opt.step()
        with torch.no_grad():       # EMA with post-update params
            for tp, p in zip(tproj.parameters(), proj.parameters()):
                tp.mul_(TAU).add_((1.0 - TAU) * p)

        # ---- byol_tpu step on identical initial weights ------------------
        torch.manual_seed(0)        # rebuild the SAME initial nets
        proj0, pred0, tproj0 = _torch_head(F_IN), _torch_head(OUT), \
            _torch_head(F_IN)
        head = MLPHead(hidden_size=HID, output_size=OUT)
        pp, pbs = _to_flax(proj0)
        rp, rbs = _to_flax(pred0)
        tp_, tbs = _to_flax(tproj0)
        j1, j2 = jnp.asarray(f1), jnp.asarray(f2)

        tproj1, tproj2, _ = _flax_forward(head, tp_, tbs, j1, j2)

        def loss_fn(trainable):
            q1, q2, new_pbs = _flax_forward(
                head, trainable["proj"], pbs, j1, j2)
            # predictor sees each view separately, stats chained like torch
            o1, upd = head.apply(
                {"params": trainable["pred"], "batch_stats": rbs}, q1,
                train=True, mutable=["batch_stats"])
            o2, upd = head.apply(
                {"params": trainable["pred"],
                 "batch_stats": upd["batch_stats"]}, q2,
                train=True, mutable=["batch_stats"])
            loss = loss_function(o1, o2, tproj1, tproj2,
                                 norm_mode="reference")
            return loss, (new_pbs, upd["batch_stats"])

        trainable = {"proj": pp, "pred": rp}
        (loss_j, (new_pbs, _)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(trainable)
        tx = optax.sgd(LR, momentum=MOM)
        updates, _ = tx.update(grads, tx.init(trainable), trainable)
        new_trainable = optax.apply_updates(trainable, updates)
        new_tp = jax.tree_util.tree_map(
            lambda t, p: TAU * t + (1.0 - TAU) * p,
            tp_, new_trainable["proj"])

        # ---- parity assertions ------------------------------------------
        assert float(loss_j) == pytest.approx(float(loss_t.detach()), abs=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads["proj"]["dense1"]["kernel"]).T, grad_t,
            atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(new_trainable["proj"]["dense1"]["kernel"]).T,
            proj[0].weight.detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(new_trainable["pred"]["dense2"]["bias"]),
            pred[3].bias.detach().numpy(), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(new_tp["dense1"]["kernel"]).T,
            tproj[0].weight.detach().numpy(), atol=1e-5)

        # BN running mean matches exactly; running var differs ONLY by the
        # documented biased-vs-unbiased delta: both are 0.9^2*1 + linear
        # combinations of per-view batch variances, torch's scaled by
        # B/(B-1).  So flax_var = (torch_var - 0.9^2) * (B-1)/B + 0.9^2.
        np.testing.assert_allclose(
            np.asarray(new_pbs["bn"]["mean"]),
            proj[1].running_mean.detach().numpy(), atol=1e-5)
        torch_var = proj[1].running_var.detach().numpy()
        expected_flax_var = (torch_var - 0.81) * (B - 1) / B + 0.81
        np.testing.assert_allclose(
            np.asarray(new_pbs["bn"]["var"]), expected_flax_var, atol=1e-5)
