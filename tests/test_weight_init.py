"""Weight-initialization registry (--weight-initialization contract)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.models.init import apply_weight_init, available


def _params():
    return {
        "backbone": {"stem_conv": {"kernel": jnp.ones((3, 3, 3, 8))},
                     "stem_bn": {"scale": jnp.ones((8,)),
                                 "bias": jnp.zeros((8,))}},
        "probe": {"classifier": {"kernel": jnp.ones((8, 10)),
                                 "bias": jnp.zeros((10,))}},
    }


def test_none_is_identity():
    p = _params()
    out = apply_weight_init(p, jax.random.PRNGKey(0), None)
    assert out is p


def test_redraws_kernels_leaves_rest():
    p = _params()
    out = apply_weight_init(p, jax.random.PRNGKey(0), "xavier_uniform")
    # kernels changed
    assert not np.allclose(out["backbone"]["stem_conv"]["kernel"],
                           p["backbone"]["stem_conv"]["kernel"])
    assert not np.allclose(out["probe"]["classifier"]["kernel"],
                           p["probe"]["classifier"]["kernel"])
    # BN scale/bias and biases untouched
    np.testing.assert_array_equal(out["backbone"]["stem_bn"]["scale"],
                                  p["backbone"]["stem_bn"]["scale"])
    np.testing.assert_array_equal(out["probe"]["classifier"]["bias"],
                                  p["probe"]["classifier"]["bias"])


def test_deterministic_per_key():
    p = _params()
    a = apply_weight_init(p, jax.random.PRNGKey(1), "kaiming_normal")
    b = apply_weight_init(p, jax.random.PRNGKey(1), "kaiming_normal")
    c = apply_weight_init(p, jax.random.PRNGKey(2), "kaiming_normal")
    np.testing.assert_array_equal(a["probe"]["classifier"]["kernel"],
                                  b["probe"]["classifier"]["kernel"])
    assert not np.allclose(a["probe"]["classifier"]["kernel"],
                           c["probe"]["classifier"]["kernel"])


def test_every_registered_scheme_runs():
    p = _params()
    for name in available():
        out = apply_weight_init(p, jax.random.PRNGKey(0), name)
        k = np.asarray(out["backbone"]["stem_conv"]["kernel"])
        assert np.all(np.isfinite(k)), name


def test_unknown_scheme_raises():
    with pytest.raises(ValueError, match="unknown weight initialization"):
        apply_weight_init(_params(), jax.random.PRNGKey(0), "bogus")
