"""Step-fused augmentation + uint8 streaming input pipeline (ISSUE 3).

The contracts under test:
- ``augment_placement='step'`` ships RAW uint8 batches and the jitted train
  step augments per microbatch INSIDE the accumulation scan; with identical
  PRNG keys the fused path produces the SAME views as the loader-path
  ``two_view_batch`` (they trace the one ``device_augment.two_view``
  program), and a full train-step parity run reaches matching loss and
  post-step params on the same synthetic stream;
- the raw loader pipeline keeps the epoch-reseed/drop-remainder contract
  and rejects unservable combinations (image_folder, paper aug spec, the
  loader-dispatched device backend) at build time;
- the input-pipeline meters (time-to-next-batch / starvation, H2D bytes
  per step, prefetch queue depth) account correctly through
  ``prefetch_to_mesh``.

Augment/step calls run under ``guard_steps`` (conftest.py): a hidden host
sync or tracer leak inside the fused augmentation fails here, on CPU, in
tier-1 — not on a TPU window.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.core import config as config_lib
from byol_tpu.core.config import (Config, DeviceConfig, RegularizerConfig,
                                  TaskConfig)
from byol_tpu.data import get_loader
from byol_tpu.parallel.mesh import shard_batch_to_mesh
from byol_tpu.training.build import setup_training
from byol_tpu.training.steps import augment_keys
from tests.conftest import guard_steps

SIZE = 24      # augment target (= model input)
RAW = 28       # stored raw image size (crops come from here)


def make_rcfg(placement, accum_steps=1, batch=16):
    c = config_lib.Config()
    c = c.replace(
        task=dataclasses.replace(c.task, batch_size=batch, epochs=2,
                                 augment_placement=placement,
                                 image_size_override=SIZE),
        model=dataclasses.replace(c.model, arch="resnet18",
                                  head_latent_size=64, projection_size=32),
        optim=dataclasses.replace(c.optim, warmup=1, lr=0.1,
                                  accum_steps=accum_steps),
        device=dataclasses.replace(c.device, num_replicas=8, half=False,
                                   seed=11),
    )
    return config_lib.resolve(c, num_train_samples=128, num_test_samples=32,
                              output_size=10, input_shape=(SIZE, SIZE, 3))


def tree_maxdiff(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return max(float(np.max(np.abs(np.asarray(x, np.float32)
                                   - np.asarray(y, np.float32))))
               for x, y in zip(la, lb))


class TestViewEquivalence:
    def test_step_program_equals_loader_dispatch(self, step_guard):
        """ACCEPTANCE: identical keys -> identical views.  The step-fused
        path traces ``device_augment.two_view``; the loader device backend
        jit-dispatches ``two_view_batch``; both must agree exactly.  Run
        under the transfer guard: no hidden host syncs in either path."""
        from byol_tpu.data import device_augment
        rng = np.random.RandomState(0)
        imgs = jax.device_put(
            rng.randint(0, 256, (4, RAW, RAW, 3), dtype=np.uint8))
        key = jax.random.PRNGKey(5)
        fused = jax.jit(lambda k, im: device_augment.two_view(k, im, SIZE))
        v1a, v2a = step_guard(fused)(key, imgs)
        v1b, v2b = step_guard(device_augment.two_view_batch)(key, imgs, SIZE)
        np.testing.assert_array_equal(np.asarray(v1a), np.asarray(v1b))
        np.testing.assert_array_equal(np.asarray(v2a), np.asarray(v2b))

    def test_augment_keys_fresh_per_step_and_microbatch(self):
        """No key reuse (the GL103 contract, runtime edition): every
        (step, microbatch) pair draws a distinct key, reproducibly."""
        k0 = np.asarray(augment_keys(7, jnp.asarray(0, jnp.int32), 4))
        k0b = np.asarray(augment_keys(7, jnp.asarray(0, jnp.int32), 4))
        k1 = np.asarray(augment_keys(7, jnp.asarray(1, jnp.int32), 4))
        np.testing.assert_array_equal(k0, k0b)        # deterministic
        flat = {tuple(map(int, k)) for k in np.concatenate([k0, k1])}
        assert len(flat) == 8                         # all distinct


class TestTrainStepParity:
    @pytest.mark.parametrize("accum", [1, 2])
    def test_loader_vs_step_same_keys_match(self, mesh8, step_guard, accum):
        """ACCEPTANCE: the step-fused train step == the loader-placement
        train step fed the views it would have derived (augment_keys +
        strided microbatch partition + two_view_batch) — matching loss
        metrics AND post-step params on the same synthetic stream."""
        from byol_tpu.data.device_augment import two_view_batch
        rcfg_s = make_rcfg("step", accum_steps=accum)
        _, state_s, step_s, _, _ = setup_training(
            rcfg_s, mesh8, jax.random.PRNGKey(0))
        rcfg_l = make_rcfg("loader", accum_steps=accum)
        _, state_l, step_l, _, _ = setup_training(
            rcfg_l, mesh8, jax.random.PRNGKey(0))

        rng = np.random.RandomState(3)
        images = rng.randint(0, 256, (16, RAW, RAW, 3), dtype=np.uint8)
        labels = rng.randint(0, 10, size=(16,)).astype(np.int32)

        # reconstruct the views the fused step derives at state.step == 0
        keys = np.asarray(augment_keys(rcfg_s.cfg.device.seed,
                                       jnp.asarray(0, jnp.int32), accum))
        v1 = np.zeros((16, SIZE, SIZE, 3), np.float32)
        v2 = np.zeros_like(v1)
        for i in range(accum):
            a, b = two_view_batch(jnp.asarray(keys[i]),
                                  jnp.asarray(images[i::accum]), SIZE)
            v1[i::accum], v2[i::accum] = np.asarray(a), np.asarray(b)

        sb = shard_batch_to_mesh({"images": images, "label": labels}, mesh8)
        lb = shard_batch_to_mesh({"view1": v1, "view2": v2,
                                  "label": labels}, mesh8)
        state_s, m_s = step_guard(step_s)(state_s, sb)
        state_l, m_l = step_guard(step_l)(state_l, lb)
        for k in m_s:
            np.testing.assert_allclose(float(m_s[k]), float(m_l[k]),
                                       rtol=2e-4, atol=2e-4, err_msg=k)
        # identical views -> identical gradients up to fusion-order noise
        assert tree_maxdiff(state_s.params, state_l.params) < 5e-4
        assert tree_maxdiff(state_s.batch_stats, state_l.batch_stats) < 1e-4

    def test_step_counter_feeds_fresh_augmentation(self, mesh8, step_guard):
        """The same raw batch fed twice must NOT produce the same loss:
        keys derive from state.step, so step 2 re-augments differently
        (the set_all_epochs/fresh-randomness analog for the fused path)."""
        rcfg = make_rcfg("step", accum_steps=2)
        _, state, train_step, _, _ = setup_training(
            rcfg, mesh8, jax.random.PRNGKey(0))
        train_step = guard_steps(train_step)
        rng = np.random.RandomState(0)
        batch = shard_batch_to_mesh(
            {"images": rng.randint(0, 256, (16, RAW, RAW, 3),
                                   dtype=np.uint8),
             "label": rng.randint(0, 10, size=(16,)).astype(np.int32)},
            mesh8)
        state, m1 = train_step(state, batch)
        state, m2 = train_step(state, batch)
        assert int(state.step) == 2
        assert float(m1["byol_loss_mean"]) != float(m2["byol_loss_mean"])

    def test_step_config_requires_image_size(self):
        from byol_tpu.training.steps import StepConfig, make_train_step
        with pytest.raises(ValueError, match="image_size"):
            make_train_step(None, None,
                            StepConfig(total_train_steps=10,
                                       augment_in_step=True))


class TestRawPipeline:
    def _cfg(self, **task_overrides):
        task = dict(task="fake", batch_size=8, image_size_override=16,
                    augment_placement="step")
        task.update(task_overrides)
        return Config(task=TaskConfig(**task),
                      device=DeviceConfig(num_replicas=1, seed=3))

    def test_contract_raw_uint8_train_host_resize_eval(self):
        bundle = get_loader(self._cfg(), num_fake_samples=16)
        b = next(iter(bundle.train_loader))
        assert sorted(b) == ["images", "label"]
        assert b["images"].dtype == np.uint8
        assert b["images"].shape == (8, 16, 16, 3)
        assert b["label"].dtype == np.int32
        # eval keeps the host resize path: two identical float32 views
        tb = next(iter(bundle.test_loader))
        np.testing.assert_array_equal(tb["view1"], tb["view2"])
        assert tb["view1"].dtype == np.float32

    def test_epoch_reseed_changes_order(self):
        bundle = get_loader(self._cfg(), num_fake_samples=64)
        bundle.set_all_epochs(0)
        l0 = np.concatenate([b["label"] for b in bundle.train_loader])
        l0b = np.concatenate([b["label"] for b in bundle.train_loader])
        bundle.set_all_epochs(1)
        l1 = np.concatenate([b["label"] for b in bundle.train_loader])
        np.testing.assert_array_equal(l0, l0b)
        assert not np.array_equal(l0, l1)

    def test_drop_remainder(self):
        bundle = get_loader(self._cfg(batch_size=12), num_fake_samples=64)
        counts = [len(b["label"]) for b in bundle.train_loader]
        assert counts == [12] * 5

    def test_rejects_image_folder(self, tmp_path):
        cfg = self._cfg(task="image_folder", data_dir=str(tmp_path))
        with pytest.raises(ValueError, match="image_folder"):
            get_loader(cfg)

    def test_rejects_paper_aug_spec(self):
        cfg = Config(task=TaskConfig(task="fake", batch_size=8,
                                     image_size_override=16,
                                     augment_placement="step"),
                     regularizer=RegularizerConfig(aug_spec="paper"),
                     device=DeviceConfig(num_replicas=1, seed=3))
        with pytest.raises(ValueError, match="reference"):
            get_loader(cfg, num_fake_samples=16)

    def test_rejects_device_backend_combo(self):
        cfg = self._cfg(data_backend="device")
        with pytest.raises(ValueError, match="mutually exclusive"):
            get_loader(cfg, num_fake_samples=16)

    def test_resolve_rejects_bogus_placement(self):
        c = Config(task=TaskConfig(task="fake", batch_size=8,
                                   augment_placement="chip"))
        with pytest.raises(ValueError, match="augment_placement"):
            config_lib.resolve(c, num_train_samples=64, num_test_samples=16,
                               output_size=10, input_shape=(16, 16, 3))

    def test_range_check_uint8_contract(self):
        from byol_tpu.training.trainer import _range_check
        _range_check({"images": np.zeros((2, 4, 4, 3), np.uint8)})
        with pytest.raises(ValueError, match="uint8"):
            _range_check({"images": np.zeros((2, 4, 4, 3), np.float32)})


class TestInputPipelineMeter:
    def test_accounting(self):
        from byol_tpu.observability.meters import (InputPipelineMeter,
                                                   input_log_line)
        m = InputPipelineMeter(starvation_threshold_s=0.01)
        m.record_produced(100, 1)
        m.record_produced(300, 2)
        m.record_first_fill(0.3)      # pipeline fill: NOT starvation
        m.record_wait(0.002)          # under threshold: not starved
        m.record_wait(0.5)            # starved
        assert m.h2d_bytes_per_step() == 200.0
        assert m.avg_queue_depth() == 1.5
        assert m.starved_steps == 1
        assert m.batches_consumed == 3
        np.testing.assert_allclose(m.starved_seconds, 0.5)
        np.testing.assert_allclose(m.wait_seconds, 0.502)
        np.testing.assert_allclose(m.first_fill_seconds, 0.3)
        r = m.result()
        assert r["h2d_bytes_per_step"] == 200.0
        assert r["input_starved_steps"] == 1.0
        assert r["input_first_fill_seconds"] == 0.3
        line = input_log_line(3, m)
        assert "starved: 0.50 sec (1 steps)" in line
        assert "fill: 0.30 sec" in line

    def test_empty_meter_reads_zero(self):
        from byol_tpu.observability.meters import InputPipelineMeter
        m = InputPipelineMeter()
        assert m.h2d_bytes_per_step() == 0.0
        assert m.avg_queue_depth() == 0.0

    def test_prefetch_feeds_the_meter(self, mesh8):
        from byol_tpu.data.prefetch import prefetch_to_mesh
        from byol_tpu.observability.meters import InputPipelineMeter
        batches = [{"images": np.zeros((8, 4, 4, 3), np.uint8),
                    "label": np.zeros((8,), np.int32)} for _ in range(5)]
        per_batch = 8 * 4 * 4 * 3 + 8 * 4
        meter = InputPipelineMeter()
        out = list(prefetch_to_mesh(iter(batches), mesh8, meter=meter))
        assert len(out) == 5
        assert meter.batches_produced == 5
        assert meter.batches_consumed == 5
        assert meter.h2d_bytes_per_step() == float(per_batch)
        assert meter.wait_seconds >= 0.0

    def test_uint8_payload_is_8x_smaller_than_two_float_views(self):
        """The tentpole's H2D arithmetic, pinned: raw uint8 vs two float32
        views of the same geometry is exactly 8x."""
        from byol_tpu.data.prefetch import host_nbytes
        raw = {"images": np.zeros((4, 16, 16, 3), np.uint8)}
        views = {"view1": np.zeros((4, 16, 16, 3), np.float32),
                 "view2": np.zeros((4, 16, 16, 3), np.float32)}
        assert host_nbytes(views) == 8 * host_nbytes(raw)

    def test_host_nbytes_never_materializes_device_arrays(self):
        """data_backend='device' loaders yield jax device arrays; the
        producer-side byte count must come from metadata only — a
        np.asarray there would force a blocking D2H copy of both views
        per batch inside the prefetch producer (review finding, PR 3)."""
        from byol_tpu.data.prefetch import host_nbytes

        class _NoMaterialize:
            """Array stand-in that forbids conversion to numpy."""
            nbytes = 4 * 16 * 16 * 3 * 4
            def __array__(self, *a, **k):
                raise AssertionError("host_nbytes materialized the array")

        assert host_nbytes({"view1": _NoMaterialize()}) == 4 * 16 * 16 * 3 * 4
        # ShapeDtypeStruct-style leaves (no nbytes): shape/dtype fallback
        import jax as _jax
        sds = _jax.ShapeDtypeStruct((4, 16, 16, 3), np.uint8)
        assert host_nbytes({"images": sds}) == 4 * 16 * 16 * 3

    def test_first_batch_wait_is_fill_not_starvation(self, mesh8):
        """A slow FIRST batch (producer startup) must land in
        first_fill_seconds, not starved_seconds — otherwise every healthy
        epoch reports one starved step."""
        import time as _time
        from byol_tpu.data.prefetch import prefetch_to_mesh
        from byol_tpu.observability.meters import InputPipelineMeter

        def source():
            _time.sleep(0.15)     # producer startup / first-batch cost
            for i in range(3):
                yield {"x": np.full((8,), i, np.float32)}

        meter = InputPipelineMeter(starvation_threshold_s=0.05)
        out = list(prefetch_to_mesh(source(), mesh8, meter=meter))
        assert len(out) == 3
        assert meter.batches_consumed == 3
        assert meter.first_fill_seconds >= 0.1
        assert meter.starved_seconds < 0.1   # fill excluded from starvation
