"""Data pipeline tests: loader contract, augmentation invariants, sharding.

The [0,1] range checks reproduce the reference's hard input contract
(/root/reference/main.py:486-490); the rest is the test coverage the
reference never had (SURVEY.md §4).
"""
import os

import numpy as np
import pytest

from byol_tpu.core.config import Config, DeviceConfig, RegularizerConfig, TaskConfig
from byol_tpu.data import get_loader


def _fake_cfg(batch=16, size=24, seed=7):
    return Config(
        task=TaskConfig(task="fake", batch_size=batch,
                        image_size_override=size),
        device=DeviceConfig(num_replicas=1, seed=seed))


class TestFakeLoader:
    def test_contract(self):
        cfg = _fake_cfg()
        bundle = get_loader(cfg, num_fake_samples=64)
        assert bundle.input_shape == (24, 24, 3)
        assert bundle.num_train_samples == 64
        assert bundle.output_size == 10
        batch = next(bundle.train_loader)
        assert batch["view1"].shape == (16, 24, 24, 3)
        assert batch["view2"].shape == (16, 24, 24, 3)
        assert batch["label"].shape == (16,)
        assert batch["view1"].dtype == np.float32

    def test_unit_range_contract(self):
        # main.py:486-490: hard failure if pixels leave [0,1]
        bundle = get_loader(_fake_cfg(), num_fake_samples=64)
        for batch in bundle.train_loader:
            for k in ("view1", "view2"):
                assert batch[k].min() >= 0.0 and batch[k].max() <= 1.0

    def test_views_differ_in_train(self):
        bundle = get_loader(_fake_cfg(), num_fake_samples=64)
        batch = next(bundle.train_loader)
        assert not np.allclose(batch["view1"], batch["view2"])

    def test_test_views_identical_resize_only(self):
        bundle = get_loader(_fake_cfg(), num_fake_samples=64)
        batch = next(bundle.test_loader)
        np.testing.assert_array_equal(batch["view1"], batch["view2"])

    def test_drop_remainder_train_only(self):
        bundle = get_loader(_fake_cfg(batch=12), num_fake_samples=64)
        train_counts = [b["label"].shape[0] for b in bundle.train_loader]
        assert train_counts == [12] * 5          # 64 // 12, remainder dropped
        test_counts = [b["label"].shape[0] for b in bundle.test_loader]
        assert sum(test_counts) == 16            # full test set kept

    def test_valid_split_carved_from_train(self):
        # num_valid_samples contract (reference main.py:421-423): a seeded
        # held-out fraction of train, resize-only transform, disjoint sizes
        cfg = Config(
            task=TaskConfig(task="fake", batch_size=8,
                            image_size_override=24, valid_fraction=0.25),
            device=DeviceConfig(num_replicas=1, seed=7))
        bundle = get_loader(cfg, num_fake_samples=64)
        assert bundle.num_valid_samples == 16
        assert bundle.num_train_samples == 48
        batches = list(bundle.valid_loader)
        assert sum(len(b["label"]) for b in batches) == 16
        np.testing.assert_array_equal(batches[0]["view1"],
                                      batches[0]["view2"])  # eval transform
        # default: no valid split, and the property says how to get one
        none_bundle = get_loader(_fake_cfg(), num_fake_samples=64)
        assert none_bundle.num_valid_samples == 0
        with pytest.raises(ValueError, match="valid"):
            none_bundle.valid_loader

    def test_epoch_reseed_changes_order(self):
        # set_all_epochs analog of the DistributedSampler epoch reshuffle
        # (main.py:760)
        bundle = get_loader(_fake_cfg(), num_fake_samples=64)
        bundle.set_all_epochs(0)
        l0 = np.concatenate([b["label"] for b in bundle.train_loader])
        l0b = np.concatenate([b["label"] for b in bundle.train_loader])
        bundle.set_all_epochs(1)
        l1 = np.concatenate([b["label"] for b in bundle.train_loader])
        np.testing.assert_array_equal(l0, l0b)   # same epoch => deterministic
        assert not np.array_equal(l0, l1)        # new epoch => reshuffled


class TestImageFolder:
    @pytest.fixture
    def tree(self, tmp_path):
        from PIL import Image
        rng = np.random.RandomState(0)
        for split, n in (("train", 6), ("test", 3)):
            for cls in ("cat", "dog"):
                d = tmp_path / split / cls
                d.mkdir(parents=True)
                for i in range(n):
                    arr = rng.randint(0, 255, (48, 40, 3), dtype=np.uint8)
                    ext = "jpg" if i % 2 == 0 else "png"
                    Image.fromarray(arr).save(d / f"{i}.{ext}")
        return tmp_path

    def test_image_folder_loader(self, tree):
        cfg = Config(
            task=TaskConfig(task="image_folder", data_dir=str(tree),
                            batch_size=4, image_size_override=32),
            device=DeviceConfig(num_replicas=1, seed=0))
        bundle = get_loader(cfg)
        assert bundle.output_size == 2
        assert bundle.num_train_samples == 12
        assert bundle.num_test_samples == 6
        batch = next(bundle.train_loader)
        assert batch["view1"].shape == (4, 32, 32, 3)
        assert 0.0 <= batch["view1"].min() and batch["view1"].max() <= 1.0
        test_batch = next(bundle.test_loader)
        np.testing.assert_array_equal(test_batch["view1"],
                                      test_batch["view2"])
        # offline linear-eval input: TRAIN split under the EVAL transform
        te_batch = next(bundle.train_eval_loader)
        np.testing.assert_array_equal(te_batch["view1"], te_batch["view2"])
        assert te_batch["view1"].shape == (4, 32, 32, 3)

    @pytest.mark.parametrize("backend", ["tf", "native"])
    def test_cross_host_augmentation_decorrelation(self, tmp_path, backend,
                                                   monkeypatch):
        # ADVICE r4: per-sample augmentation seeds were shard-LOCAL, so
        # hosts at the same epoch position drew identical crop/jitter
        # parameters for different images.  process_index is now mixed
        # into the seed: same host => bit-identical streams (determinism
        # preserved), different host => different streams.
        #
        # Tree construction isolates the seed: every file within a class
        # is byte-identical, classes have EVEN counts, so under 2-host
        # interleaved sharding both shards carry identical (image, label)
        # sequences and the per-epoch shuffle (same seed, same length)
        # orders them identically — any view difference is augmentation.
        from PIL import Image
        if backend == "native":
            from byol_tpu.data import native_aug
            if not (native_aug.available() and native_aug.has_jpeg()):
                pytest.skip("native backend unavailable")
        rng = np.random.RandomState(7)
        for split, n in (("train", 4), ("test", 2)):
            for cls in ("cat", "dog"):
                d = tmp_path / split / cls
                d.mkdir(parents=True)
                arr = rng.randint(0, 255, (48, 40, 3), dtype=np.uint8)
                for i in range(n):
                    Image.fromarray(arr).save(d / f"{i}.jpg", quality=95)

        def first_views(pidx):
            import jax as jax_mod
            monkeypatch.setattr(jax_mod, "process_index", lambda: pidx)
            monkeypatch.setattr(jax_mod, "process_count", lambda: 2)
            cfg = Config(
                task=TaskConfig(task="image_folder", data_dir=str(tmp_path),
                                batch_size=4, image_size_override=32,
                                data_backend=backend),
                device=DeviceConfig(num_replicas=1, seed=0))
            bundle = get_loader(cfg)
            bundle.set_all_epochs(0)
            b = next(bundle.train_loader)
            return np.asarray(b["view1"]), np.asarray(b["label"])

        v_h0, l_h0 = first_views(0)
        v_h0b, _ = first_views(0)
        v_h1, l_h1 = first_views(1)
        np.testing.assert_array_equal(l_h0, l_h1)     # identical shards
        np.testing.assert_array_equal(v_h0, v_h0b)    # deterministic
        assert not np.array_equal(v_h0, v_h1)         # decorrelated

    def test_valid_root_on_disk(self, tree):
        # an on-disk valid/ root wins over valid_fraction (image_folder)
        from PIL import Image
        rng = np.random.RandomState(9)
        for cls in ("cat", "dog"):
            d = tree / "valid" / cls
            d.mkdir(parents=True)
            for i in range(2):
                arr = rng.randint(0, 255, (48, 40, 3), dtype=np.uint8)
                Image.fromarray(arr).save(d / f"{i}.jpg")
        cfg = Config(
            task=TaskConfig(task="image_folder", data_dir=str(tree),
                            batch_size=4, image_size_override=32,
                            valid_fraction=0.5),
            device=DeviceConfig(num_replicas=1, seed=0))
        bundle = get_loader(cfg)
        assert bundle.num_valid_samples == 4
        assert bundle.num_train_samples == 12      # train untouched
        batch = next(bundle.valid_loader)
        np.testing.assert_array_equal(batch["view1"], batch["view2"])

    def test_valid_fraction_carves_image_folder(self, tree):
        cfg = Config(
            task=TaskConfig(task="image_folder", data_dir=str(tree),
                            batch_size=4, image_size_override=32,
                            valid_fraction=0.25),
            device=DeviceConfig(num_replicas=1, seed=0))
        bundle = get_loader(cfg)
        assert bundle.num_valid_samples == 3       # 12 * 0.25
        assert bundle.num_train_samples == 9

    def test_missing_root_raises(self, tmp_path):
        cfg = Config(task=TaskConfig(task="image_folder",
                                     data_dir=str(tmp_path), batch_size=4))
        with pytest.raises(FileNotFoundError):
            get_loader(cfg)

    def test_reference_task_name_aliases(self, tree):
        # the reference's task names (main.py:38-39, README.md:93) keep
        # working; the DALI variant maps to the same canonical spec
        for alias in ("multi_augment_image_folder",
                      "dali_multi_augment_image_folder"):
            cfg = Config(
                task=TaskConfig(task=alias, data_dir=str(tree),
                                batch_size=4, image_size_override=32),
                device=DeviceConfig(num_replicas=1, seed=0))
            bundle = get_loader(cfg)
            assert bundle.output_size == 2


class TestDeviceAugment:
    """Equivalence/contract tests for the on-device augmentation — run
    under the ``guard_steps`` transfer guard (conftest.py), so a hidden
    host sync or tracer leak inside the jitted augmentation fails here on
    CPU exactly like the train/eval steps' guard does.  Inputs are
    device_put EXPLICITLY: only implicit transfers are the bug."""

    def test_two_view_batch(self, step_guard):
        import jax
        from byol_tpu.data.device_augment import two_view_batch
        guarded = step_guard(two_view_batch)
        rng = np.random.RandomState(0)
        imgs = jax.device_put(
            rng.randint(0, 255, (4, 40, 40, 3), dtype=np.uint8))
        v1, v2 = guarded(jax.random.PRNGKey(0), imgs, 32)
        assert v1.shape == v2.shape == (4, 32, 32, 3)
        assert float(v1.min()) >= 0.0 and float(v1.max()) <= 1.0
        assert not np.allclose(np.asarray(v1), np.asarray(v2))
        # deterministic under the same key
        w1, _ = guarded(jax.random.PRNGKey(0), imgs, 32)
        np.testing.assert_allclose(np.asarray(v1), np.asarray(w1))

    def test_per_image_independence(self, step_guard):
        import jax
        from byol_tpu.data.device_augment import two_view_batch
        imgs = jax.device_put(np.tile(
            np.linspace(0, 1, 40 * 40 * 3, dtype=np.float32
                        ).reshape(1, 40, 40, 3), (3, 1, 1, 1)))
        v1, _ = step_guard(two_view_batch)(jax.random.PRNGKey(1), imgs, 32)
        assert not np.allclose(np.asarray(v1[0]), np.asarray(v1[1]))

    def test_device_backend_wired_into_loader(self):
        """--data-backend device must produce on-chip two-view train batches
        (the [0,1] contract included) with the same LoaderBundle interface,
        and keep eval on the host resize path (equal views)."""
        cfg = Config(
            task=TaskConfig(task="fake", batch_size=8,
                            image_size_override=16, data_backend="device"),
            device=DeviceConfig(num_replicas=1, seed=3))
        bundle = get_loader(cfg, num_fake_samples=16)
        b = next(iter(bundle.train_loader))
        v1 = np.asarray(b["view1"])
        assert v1.shape == (8, 16, 16, 3)
        assert v1.min() >= 0.0 and v1.max() <= 1.0
        assert not np.allclose(v1, np.asarray(b["view2"]))
        # epoch reseed (set_all_epochs contract) changes the view stream
        bundle.set_all_epochs(1)
        b2 = next(iter(bundle.train_loader))
        assert not np.allclose(v1, np.asarray(b2["view1"]))
        # eval: host resize, both view slots identical
        tb = next(iter(bundle.test_loader))
        np.testing.assert_array_equal(np.asarray(tb["view1"]),
                                      np.asarray(tb["view2"]))


class TestSynthDataset:
    def test_learnable_and_disjoint(self):
        """synth must be (a) learnable — class identity recoverable from
        pixels — and (b) split properly: same class templates, different
        samples across train/test."""
        from byol_tpu.data.readers import load_synth
        x, y = load_synth(600, 32, train=True)
        xt, yt = load_synth(300, 32, train=False)
        assert x.dtype == np.uint8 and x.shape == (600, 32, 32, 3)
        means = np.stack([x[y == k].mean(0) for k in range(10)])
        d = ((xt[:, None].astype(np.float32)
              - means[None].astype(np.float32)) ** 2).sum((2, 3, 4))
        acc = (np.argmin(d, axis=1) == yt).mean()
        assert acc > 0.9          # far above 10% chance
        # deterministic per (seed, split); train != test streams
        x2, _ = load_synth(600, 32, train=True)
        np.testing.assert_array_equal(x, x2)

    def test_loader_task(self):
        cfg = Config(task=TaskConfig(task="synth", batch_size=8,
                                     image_size_override=32),
                     device=DeviceConfig(num_replicas=1, seed=0))
        bundle = get_loader(cfg)
        assert bundle.output_size == 10
        assert bundle.num_train_samples == 20_000
        b = next(iter(bundle.train_loader))
        assert b["view1"].shape == (8, 32, 32, 3)


class TestPrefetch:
    def test_prefetch_yields_all(self, mesh8):
        from byol_tpu.data.prefetch import prefetch_to_mesh
        batches = [{"view1": np.full((8, 4), i, np.float32)}
                   for i in range(5)]
        out = list(prefetch_to_mesh(iter(batches), mesh8))
        assert len(out) == 5
        for i, b in enumerate(out):
            assert float(np.asarray(b["view1"])[0, 0]) == i


class TestReaders:
    def test_download_gating(self, tmp_path):
        from byol_tpu.data import readers
        with pytest.raises(FileNotFoundError):
            readers.load_cifar10(str(tmp_path), train=True, download=False)

    def test_cifar10_from_disk(self, tmp_path):
        # write the standard cifar-10-batches-py pickle layout
        import pickle
        from byol_tpu.data import readers
        root = tmp_path / "cifar-10-batches-py"
        root.mkdir()
        rng = np.random.RandomState(0)
        for name, n in [(f"data_batch_{i}", 20) for i in range(1, 6)] + [
                ("test_batch", 10)]:
            blob = {b"data": rng.randint(0, 255, (n, 3072), dtype=np.uint8),
                    b"labels": rng.randint(0, 10, n).tolist()}
            with open(root / name, "wb") as f:
                pickle.dump(blob, f)
        x, y = readers.load_cifar10(str(tmp_path), train=True)
        assert x.shape == (100, 32, 32, 3) and y.shape == (100,)
        x, y = readers.load_cifar10(str(tmp_path), train=False)
        assert x.shape == (10, 32, 32, 3)

    def test_fake(self):
        from byol_tpu.data import readers
        x, y = readers.load_fake(32, 16, seed=3)
        assert x.shape == (32, 16, 16, 3) and x.dtype == np.uint8
        x2, _ = readers.load_fake(32, 16, seed=3)
        np.testing.assert_array_equal(x, x2)


class TestDigits:
    """Real offline image data (sklearn's bundled UCI digits): contract,
    fixed split, and loader integration."""

    def test_contract_and_fixed_split(self):
        from byol_tpu.data import readers
        x, y = readers.load_digits_img(train=True)
        xt, yt = readers.load_digits_img(train=False)
        assert x.shape == (1500, 32, 32, 3) and x.dtype == np.uint8
        assert xt.shape == (297, 32, 32, 3)
        assert set(np.unique(y)) == set(range(10))
        assert set(np.unique(yt)) == set(range(10))
        # grayscale replicated to RGB; full dynamic range used
        np.testing.assert_array_equal(x[..., 0], x[..., 1])
        assert x.max() == 255 and x.min() == 0
        # the split is pinned: deterministic AND disjoint
        x2, y2 = readers.load_digits_img(train=True)
        np.testing.assert_array_equal(x, x2)
        tr = {xx.tobytes() for xx in x[:200]}
        assert not any(xx.tobytes() in tr for xx in xt[:100])

    def test_nearest_class_mean_learnable(self):
        # same learnability bar as synth: class identity recoverable from
        # pixels, so a BYOL+probe run on digits has real signal to find
        from byol_tpu.data import readers
        x, y = readers.load_digits_img(train=True)
        xt, yt = readers.load_digits_img(train=False)
        means = np.stack([x[y == k].mean(0) for k in range(10)])
        d = ((xt[:, None].astype(np.float32)
              - means[None].astype(np.float32)) ** 2).sum((2, 3, 4))
        acc = (np.argmin(d, axis=1) == yt).mean()
        assert acc > 0.8          # far above 10% chance

    def test_loader_task(self):
        cfg = Config(task=TaskConfig(task="digits", batch_size=8,
                                     image_size_override=32),
                     device=DeviceConfig(num_replicas=1, seed=0))
        bundle = get_loader(cfg)
        assert bundle.output_size == 10
        assert bundle.num_train_samples == 1500
        assert bundle.num_test_samples == 297
        b = next(iter(bundle.train_loader))
        assert b["view1"].shape == (8, 32, 32, 3)
        assert 0.0 <= float(np.min(b["view1"]))
        assert float(np.max(b["view1"])) <= 1.0


class TestPaperAugSpec:
    def test_view_params_table(self):
        from byol_tpu.data import augment
        ref0 = augment.view_params("reference", 0)
        assert ref0 == augment.view_params("reference", 1)   # symmetric
        assert ref0["blur_p"] == 0.5 and ref0["solarize_p"] == 0.0
        p0 = augment.view_params("paper", 0)
        p1 = augment.view_params("paper", 1)
        assert p0["blur_p"] == 1.0 and p0["solarize_p"] == 0.0
        assert p1["blur_p"] == 0.1 and p1["solarize_p"] == 0.2
        assert p0["jitter"] == (0.4, 0.4, 0.2, 0.1)
        with pytest.raises(ValueError, match="unknown aug spec"):
            augment.view_params("bogus", 0)

    def test_solarize_op(self):
        import tensorflow as tf
        from byol_tpu.data.augment import solarize
        x = tf.constant([[0.1, 0.4], [0.6, 0.9]])
        out = solarize(x[..., None]).numpy()[..., 0]
        np.testing.assert_allclose(out, [[0.1, 0.4], [0.4, 0.1]], atol=1e-6)

    def test_paper_two_views_contract(self):
        """Paper-spec views keep the [0,1]/shape contract and view 1 is
        ALWAYS blurred (p=1.0): a high-frequency image must come out with
        lower total variation in view 1 than the raw crop scale suggests."""
        import tensorflow as tf
        from byol_tpu.data import augment
        rng = np.random.RandomState(0)
        img = tf.constant(rng.rand(64, 64, 3).astype(np.float32))
        v1, v2 = augment.two_views(img, 32, tf.constant([3, 7], tf.int32),
                                   spec="paper")
        for v in (v1, v2):
            assert v.shape == (32, 32, 3)
            assert float(tf.reduce_min(v)) >= 0.0
            assert float(tf.reduce_max(v)) <= 1.0
        # blur p=1.0 on view1: white-noise input loses high-freq energy
        tv = lambda t: float(tf.reduce_mean(tf.abs(t[1:] - t[:-1])))
        raw = augment.random_resized_crop(img, 32, tf.constant([9, 9]))
        assert tv(v1) < tv(raw)

    def test_loader_rejects_paper_spec_off_tf_backend(self):
        from byol_tpu.core.config import RegularizerConfig
        cfg = Config(task=TaskConfig(task="fake", batch_size=8,
                                     image_size_override=16,
                                     data_backend="device"),
                     regularizer=RegularizerConfig(aug_spec="paper"),
                     device=DeviceConfig(num_replicas=1, seed=0))
        with pytest.raises(ValueError, match="tf data backend"):
            get_loader(cfg, num_fake_samples=16)

    def test_loader_paper_spec_end_to_end(self):
        from byol_tpu.core.config import RegularizerConfig
        cfg = Config(task=TaskConfig(task="fake", batch_size=8,
                                     image_size_override=16),
                     regularizer=RegularizerConfig(aug_spec="paper"),
                     device=DeviceConfig(num_replicas=1, seed=0))
        bundle = get_loader(cfg, num_fake_samples=16)
        b = next(iter(bundle.train_loader))
        v1 = np.asarray(b["view1"])
        assert v1.shape == (8, 16, 16, 3)
        assert v1.min() >= 0.0 and v1.max() <= 1.0
        assert not np.allclose(v1, np.asarray(b["view2"]))


class TestGaussianBlurOracle:
    def test_blur_matches_torch_reflect_conv(self):
        """Pin the blur math (kernel construction, separable application,
        reflect-101 borders — the cv2 convention shared with the native C++
        backend) against a torch depthwise-conv oracle at a fixed sigma."""
        import tensorflow as tf
        import torch
        import torch.nn.functional as F
        from byol_tpu.data.augment import gaussian_blur

        sigma, k = 1.3, 5
        img = np.random.RandomState(0).rand(12, 12, 3).astype(np.float32)
        got = gaussian_blur(tf.constant(img), k, seed=(1, 2),
                            sigma_range=(sigma, sigma)).numpy()

        x = np.arange(k) - k // 2
        g = np.exp(-(x ** 2) / (2.0 * sigma ** 2)).astype(np.float32)
        g /= g.sum()
        t = torch.from_numpy(img.transpose(2, 0, 1))[None]       # (1,3,H,W)
        t = F.pad(t, (k // 2,) * 4, mode="reflect")
        kx = torch.from_numpy(g).view(1, 1, 1, k).repeat(3, 1, 1, 1)
        ky = torch.from_numpy(g).view(1, 1, k, 1).repeat(3, 1, 1, 1)
        t = F.conv2d(t, kx, groups=3)
        t = F.conv2d(t, ky, groups=3)
        want = t[0].numpy().transpose(1, 2, 0)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_blur_preserves_constant_image_at_borders(self):
        """Zero padding would dim border pixels of a constant image; the
        reflect-padded blur must return it unchanged everywhere."""
        import tensorflow as tf
        from byol_tpu.data.augment import gaussian_blur
        img = np.full((10, 10, 3), 0.7, np.float32)
        out = gaussian_blur(tf.constant(img), 5, seed=(3, 4)).numpy()
        np.testing.assert_allclose(out, img, rtol=1e-5, atol=1e-6)

    def test_device_blur_preserves_constant_image_at_borders(self,
                                                             step_guard):
        """Same border contract for the on-device (JAX) blur backend."""
        import jax
        import jax.numpy as jnp
        from byol_tpu.data import device_augment
        img = jnp.full((10, 10, 3), 0.7, jnp.float32)
        blur = step_guard(jax.jit(device_augment.gaussian_blur,
                                  static_argnums=(2,)))
        out = blur(jax.random.PRNGKey(0), img, 5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(img),
                                   rtol=1e-5, atol=1e-6)
