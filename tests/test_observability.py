"""Grapher contract, metric accumulation, epoch log format."""
import json
import os

import numpy as np
import pytest

from byol_tpu.observability import (Grapher, MetricAccumulator, StepTimer,
                                    epoch_log_line, make_grid)
from byol_tpu.observability.grapher import is_image_key, is_scalar_key


def test_scalar_image_key_filters():
    # main.py:502-544: only *_mean/*_scalar plot; only *_img(s) image.
    assert is_scalar_key("loss_mean") and is_scalar_key("lr_scalar")
    assert not is_scalar_key("loss") and not is_scalar_key("mean_loss")
    assert is_image_key("aug1_img") and is_image_key("aug_imgs")
    assert not is_image_key("image_grid")


def test_jsonl_backend_roundtrip(tmp_path):
    g = Grapher("jsonl", logdir=str(tmp_path), run_name="r", enabled=True)
    g.register_plots({"loss_mean": 1.5, "ignored": 2.0}, step=3,
                     prefix="train")
    g.add_text("config", "{}", 0)
    g.close()
    lines = [json.loads(l) for l in
             open(tmp_path / "r" / "metrics.jsonl")]
    assert any(l.get("train_loss_mean") == 1.5 for l in lines)
    assert not any("train_ignored" in l for l in lines)


def test_tensorboard_backend_writes(tmp_path):
    g = Grapher("tensorboard", logdir=str(tmp_path), run_name="tb",
                enabled=True)
    g.register_plots({"loss_mean": 0.5}, step=0)
    g.register_images({"aug1_imgs": np.random.rand(4, 8, 8, 3)}, step=0)
    g.close()
    files = os.listdir(tmp_path / "tb")
    assert any("tfevents" in f for f in files)


def test_both_backend_writes_tb_and_jsonl(tmp_path):
    g = Grapher("both", logdir=str(tmp_path), run_name="b", enabled=True)
    g.register_plots({"loss_mean": 2.5}, step=1, prefix="train")
    g.close()
    files = os.listdir(tmp_path / "b")
    assert any("tfevents" in f for f in files)
    lines = [json.loads(l) for l in open(tmp_path / "b" / "metrics.jsonl")]
    assert any(l.get("train_loss_mean") == 2.5 for l in lines)


def test_disabled_grapher_is_noop(tmp_path):
    g = Grapher("tensorboard", logdir=str(tmp_path), run_name="off",
                enabled=False)
    g.register_plots({"loss_mean": 0.5}, step=0)
    g.close()
    assert not os.path.exists(tmp_path / "off")


def test_jsonl_nonfinite_scalar_stays_strict_json(tmp_path):
    """GL110 (ISSUE 13 satellite): a diverged run's NaN/inf metric lands
    in metrics.jsonl as the events.py string convention — every line
    stays STRICT JSON (no bare NaN tokens), parseable by readers that
    reject Python's lenient extension."""
    g = Grapher("jsonl", logdir=str(tmp_path), run_name="n", enabled=True)
    g.register_plots({"loss_mean": float("nan"),
                      "grad_mean": float("inf")}, step=1, prefix="train")
    g.close()

    def strict(line):
        # parse_constant fires only on NaN/Infinity/-Infinity tokens —
        # exactly what must never appear
        return json.loads(line, parse_constant=lambda tok: (_ for _ in ())
                          .throw(AssertionError(f"bare {tok} token")))

    lines = [strict(l) for l in open(tmp_path / "n" / "metrics.jsonl")]
    assert any(l.get("train_loss_mean") == "NaN" for l in lines)
    assert any(l.get("train_grad_mean") == "Infinity" for l in lines)


def test_make_grid_shape_and_downscale():
    grid = make_grid(np.random.rand(10, 128, 128, 3), max_px=64)
    rows, cols = 3, 4  # ceil(sqrt(10))=4 cols, ceil(10/4)=3 rows
    assert grid.shape == (rows * 64, cols * 64, 3)
    assert grid.min() >= 0.0 and grid.max() <= 1.0


def test_metric_accumulator_epoch_average():
    acc = MetricAccumulator()
    acc.update({"loss_mean": np.float32(2.0), "top1_mean": np.float32(0.5)})
    acc.update({"loss_mean": np.float32(4.0), "top1_mean": np.float32(1.0)})
    out = acc.result()
    assert out["loss_mean"] == 3.0 and out["top1_mean"] == 0.75
    assert acc.count == 2


def test_epoch_log_line_format():
    line = epoch_log_line("train", 3, 1024, 12.5,
                          {"loss_mean": 1.0, "byol_loss_mean": 0.5,
                           "linear_loss_mean": 0.5, "top1_mean": 0.25,
                           "top5_mean": 0.75})
    assert "train[Epoch 3][1024 samples][12.50 sec]" in line
    assert "top1: 0.2500" in line


def test_step_timer_rate():
    t = StepTimer(global_batch=100, n_chips=4)
    assert t.images_per_sec_per_chip() == 0.0  # nothing recorded yet
    t.record_epoch(steps=2, elapsed_s=2.0)     # 2 synced steps over 2s
    assert abs(t.images_per_sec_per_chip() - 100 * 2 / 2.0 / 4) < 1e-9
    t.record_epoch(steps=0, elapsed_s=0.0)     # degenerate epoch: keep last
    assert t.images_per_sec_per_chip() > 0.0


def test_watchdog_dumps_stacks_on_stall(tmp_path):
    """Armed watchdog with no pet() within the timeout must dump all thread
    stacks to the file (the hung-collective diagnostic, SURVEY §5.2)."""
    import time as time_mod
    from byol_tpu.observability.watchdog import Watchdog
    path = tmp_path / "wd.txt"
    with open(path, "w") as f:
        wd = Watchdog(0.3, exit=False, file=f)
        wd.pet()
        time_mod.sleep(1.0)   # stall past the deadline
        wd.stop()
    text = path.read_text()
    assert "Timeout" in text and "Thread" in text


def test_watchdog_disabled_and_petted_paths(tmp_path):
    import time as time_mod
    from byol_tpu.observability.watchdog import Watchdog
    path = tmp_path / "wd2.txt"
    with open(path, "w") as f:
        wd = Watchdog(0.0, exit=False, file=f)   # disabled
        wd.pet()
        wd.stop()
        wd = Watchdog(5.0, exit=False, file=f)   # petted in time
        wd.pet()
        time_mod.sleep(0.05)
        wd.stop()
    assert path.read_text() == ""


def test_metric_accumulator_weighted_by_valid_count():
    """Eval metrics carry _weight (valid rows under pad+mask batching); the
    epoch mean must weight batches by it, and _weight must not leak out."""
    acc = MetricAccumulator()
    acc.update({"top1_mean": np.float32(100.0), "_weight": np.float32(3.0)})
    acc.update({"top1_mean": np.float32(0.0), "_weight": np.float32(1.0)})
    out = acc.result()
    assert out["top1_mean"] == 75.0          # (100*3 + 0*1) / 4
    assert "_weight" not in out


class TestProfiling:
    """observability/profiling.py CPU smoke — previously the only untested
    observability module.  The trainer wraps its dispatch/readback phases in
    ``annotate`` regions, so these pins are what keep captured traces
    labeled."""

    def test_trace_writes_profile_dir(self, tmp_path):
        import jax.numpy as jnp
        from byol_tpu.observability import profiling
        with profiling.trace(str(tmp_path)):
            (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
        # jax.profiler lays out <logdir>/plugins/profile/<ts>/*.xplane.pb
        prof = tmp_path / "plugins" / "profile"
        assert prof.is_dir()
        captures = list(prof.iterdir())
        assert captures, "trace() produced no capture directory"
        assert any(f.suffix == ".pb" or f.name.endswith(".json.gz")
                   for f in captures[0].iterdir())

    def test_trace_stops_on_exception(self, tmp_path):
        """The context manager must stop the trace on an exception so a
        failed epoch does not leave the profiler running (a second
        start_trace would raise)."""
        from byol_tpu.observability import profiling
        with pytest.raises(RuntimeError, match="boom"):
            with profiling.trace(str(tmp_path / "a")):
                raise RuntimeError("boom")
        with profiling.trace(str(tmp_path / "b")):   # must not raise
            pass

    def test_annotate_nests_and_reenters(self):
        import jax.numpy as jnp
        from byol_tpu.observability import profiling
        # nesting (the trainer's train_dispatch > step layout) and re-entry
        # (one region per epoch) must both be clean, traced or not
        with profiling.annotate("outer"):
            with profiling.annotate("inner"):
                (jnp.ones((2, 2)) + 1).block_until_ready()
        for _ in range(2):
            with profiling.annotate("outer"):
                pass


class TestFlopsAccounting:
    """observability/flops.py: XLA cost analysis vs the bench hand table,
    pinned against each other so neither silently drifts."""

    def test_cost_analysis_matches_hand_table(self):
        import bench
        from byol_tpu.observability import flops as fl
        state, train_step, batch, _ = bench._build(
            8, 32, "resnet18", half=False, fuse_views=True,
            ema_update_mode="post")
        got = fl.cost_analysis_flops(train_step, state, batch)
        assert got is not None
        # cost analysis is of the pre-partitioning (logical) HLO: whole
        # global batch, which _build sizes as 8 x n_devices
        import jax
        per_sample = got / (8 * len(jax.devices()))
        hand = bench._flops_per_sample("resnet18", 32)
        # hand table counts backward as exactly 2x forward; XLA counts the
        # true backward (first conv needs no input grad) -> ~0.88 ratio
        assert 0.7 < per_sample / hand < 1.1, (per_sample, hand)

    def test_mfu_none_off_accelerator(self):
        import pytest
        from byol_tpu.observability.flops import chip_peak_tflops, mfu
        assert chip_peak_tflops("cpu") is None
        assert mfu(100.0, 1e9, None) is None
        assert mfu(100.0, None, 197.0) is None
        assert mfu(776.1, 65.4e9, 197.0) == pytest.approx(0.2577, abs=2e-3)
