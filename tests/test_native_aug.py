"""Native C++ augmentation pipeline (the DALI-equivalent backend)."""
import numpy as np
import pytest

from byol_tpu.data import native_aug

pytestmark = pytest.mark.skipif(not native_aug.available(),
                                reason="no C++ toolchain")


def _imgs(n=8, h=40, w=48, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (n, h, w, 3), dtype=np.uint8)


def test_two_views_shape_range_and_decorrelation():
    v1, v2 = native_aug.augment_two_views(_imgs(), 32, seed=1)
    assert v1.shape == v2.shape == (8, 32, 32, 3)
    assert v1.dtype == np.float32
    # the [0,1] input contract the trainer enforces (main.py:486-490)
    for v in (v1, v2):
        assert v.min() >= 0.0 and v.max() <= 1.0
    # two views of the same image must differ (independent streams)
    assert not np.allclose(v1, v2)


def test_determinism_and_seed_sensitivity():
    imgs = _imgs()
    a1, a2 = native_aug.augment_two_views(imgs, 32, seed=7, index_base=100)
    b1, b2 = native_aug.augment_two_views(imgs, 32, seed=7, index_base=100)
    np.testing.assert_array_equal(a1, b1)
    np.testing.assert_array_equal(a2, b2)
    c1, _ = native_aug.augment_two_views(imgs, 32, seed=8, index_base=100)
    assert not np.allclose(a1, c1)


def test_multithreaded_matches_single_thread():
    imgs = _imgs(n=16)
    s1, s2 = native_aug.augment_two_views(imgs, 24, seed=3, num_threads=1)
    m1, m2 = native_aug.augment_two_views(imgs, 24, seed=3, num_threads=8)
    np.testing.assert_array_equal(s1, m1)
    np.testing.assert_array_equal(s2, m2)


def test_resize_batch_matches_uint8_identity():
    """Resize to the source size must reproduce the image (up to 1/255)."""
    imgs = _imgs(n=2, h=16, w=16)
    out = native_aug.resize_batch(imgs, 16)
    np.testing.assert_allclose(out, imgs.astype(np.float32) / 255.0,
                               atol=1e-6)


def test_loader_native_backend_end_to_end():
    from byol_tpu.core.config import Config, DeviceConfig, TaskConfig
    from byol_tpu.data.loader import get_loader

    cfg = Config(task=TaskConfig(task="fake", batch_size=16,
                                 image_size_override=16,
                                 data_backend="native"),
                 device=DeviceConfig(num_replicas=8, seed=0))
    loader = get_loader(cfg, num_fake_samples=48)
    batches = list(loader.train_loader)
    assert len(batches) == 3  # 48 // 16, drop remainder
    b = batches[0]
    assert b["view1"].shape == (16, 16, 16, 3)
    assert b["label"].dtype == np.int32
    assert 0.0 <= b["view1"].min() and b["view1"].max() <= 1.0
    # epoch reseed changes the draw (set_all_epochs contract, main.py:760)
    loader.set_all_epochs(1)
    b1 = next(iter(loader.train_loader))
    assert not np.array_equal(b["view1"], b1["view1"])
    # eval: resize-only, both view slots identical
    eb = next(iter(loader.test_loader))
    np.testing.assert_array_equal(eb["view1"], eb["view2"])


def test_augment_distribution_sanity():
    """Statistical smoke: over many samples, ~50% flips/blurs, ~20%
    grayscale.  Catches gate/draw seed-coupling regressions (the bug class
    fixed in the TF path) without pinning exact streams."""
    imgs = np.tile(
        np.linspace(0, 255, 32 * 32 * 3, dtype=np.uint8).reshape(
            1, 32, 32, 3), (400, 1, 1, 1))
    v1, _ = native_aug.augment_two_views(imgs, 32, seed=11,
                                         color_jitter_strength=0.0)
    # with cj strength 0 the pipeline is crop+flip+gray+blur; count grayscale
    # outputs: all three channels equal everywhere
    gray = np.all(np.abs(v1[..., 0] - v1[..., 1]) < 1e-6, axis=(1, 2))
    frac_gray = gray.mean()
    assert 0.1 < frac_gray < 0.32, frac_gray
