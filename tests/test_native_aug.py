"""Native C++ augmentation pipeline (the DALI-equivalent backend)."""
import numpy as np
import pytest

from byol_tpu.data import native_aug

pytestmark = pytest.mark.skipif(not native_aug.available(),
                                reason="no C++ toolchain")


def _imgs(n=8, h=40, w=48, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (n, h, w, 3), dtype=np.uint8)


def test_two_views_shape_range_and_decorrelation():
    v1, v2 = native_aug.augment_two_views(_imgs(), 32, seed=1)
    assert v1.shape == v2.shape == (8, 32, 32, 3)
    assert v1.dtype == np.float32
    # the [0,1] input contract the trainer enforces (main.py:486-490)
    for v in (v1, v2):
        assert v.min() >= 0.0 and v.max() <= 1.0
    # two views of the same image must differ (independent streams)
    assert not np.allclose(v1, v2)


def test_determinism_and_seed_sensitivity():
    imgs = _imgs()
    a1, a2 = native_aug.augment_two_views(imgs, 32, seed=7, index_base=100)
    b1, b2 = native_aug.augment_two_views(imgs, 32, seed=7, index_base=100)
    np.testing.assert_array_equal(a1, b1)
    np.testing.assert_array_equal(a2, b2)
    c1, _ = native_aug.augment_two_views(imgs, 32, seed=8, index_base=100)
    assert not np.allclose(a1, c1)


def test_multithreaded_matches_single_thread():
    imgs = _imgs(n=16)
    s1, s2 = native_aug.augment_two_views(imgs, 24, seed=3, num_threads=1)
    m1, m2 = native_aug.augment_two_views(imgs, 24, seed=3, num_threads=8)
    np.testing.assert_array_equal(s1, m1)
    np.testing.assert_array_equal(s2, m2)


def test_resize_batch_matches_uint8_identity():
    """Resize to the source size must reproduce the image (up to 1/255)."""
    imgs = _imgs(n=2, h=16, w=16)
    out = native_aug.resize_batch(imgs, 16)
    np.testing.assert_allclose(out, imgs.astype(np.float32) / 255.0,
                               atol=1e-6)


def test_loader_native_backend_end_to_end():
    from byol_tpu.core.config import Config, DeviceConfig, TaskConfig
    from byol_tpu.data.loader import get_loader

    cfg = Config(task=TaskConfig(task="fake", batch_size=16,
                                 image_size_override=16,
                                 data_backend="native"),
                 device=DeviceConfig(num_replicas=8, seed=0))
    loader = get_loader(cfg, num_fake_samples=48)
    batches = list(loader.train_loader)
    assert len(batches) == 3  # 48 // 16, drop remainder
    b = batches[0]
    assert b["view1"].shape == (16, 16, 16, 3)
    assert b["label"].dtype == np.int32
    assert 0.0 <= b["view1"].min() and b["view1"].max() <= 1.0
    # epoch reseed changes the draw (set_all_epochs contract, main.py:760)
    loader.set_all_epochs(1)
    b1 = next(iter(loader.train_loader))
    assert not np.array_equal(b["view1"], b1["view1"])
    # eval: resize-only, both view slots identical
    eb = next(iter(loader.test_loader))
    np.testing.assert_array_equal(eb["view1"], eb["view2"])


def _jpeg_bytes(arr, quality=95):
    import io

    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


jpeg_only = pytest.mark.skipif(not native_aug.has_jpeg(),
                               reason="built without libjpeg")


@jpeg_only
class TestJpegFusedDecode:
    """The libjpeg fused decode+crop path — the DALI-analog for image trees
    (reference main.py:356-382, README.md:90-93)."""

    def test_two_views_shape_range_determinism(self):
        blobs = [_jpeg_bytes(img) for img in _imgs(n=6, h=64, w=80)]
        a1, a2 = native_aug.jpeg_augment_two_views(blobs, 32, seed=5)
        assert a1.shape == a2.shape == (6, 32, 32, 3)
        for v in (a1, a2):
            assert v.min() >= 0.0 and v.max() <= 1.0
        assert not np.allclose(a1, a2)           # independent view streams
        b1, b2 = native_aug.jpeg_augment_two_views(blobs, 32, seed=5)
        np.testing.assert_array_equal(a1, b1)    # deterministic
        np.testing.assert_array_equal(a2, b2)

    def test_multithreaded_matches_single_thread(self):
        blobs = [_jpeg_bytes(img) for img in _imgs(n=12, h=50, w=60)]
        s1, s2 = native_aug.jpeg_augment_two_views(blobs, 24, seed=3,
                                                   num_threads=1)
        m1, m2 = native_aug.jpeg_augment_two_views(blobs, 24, seed=3,
                                                   num_threads=8)
        np.testing.assert_array_equal(s1, m1)
        np.testing.assert_array_equal(s2, m2)

    def test_resize_matches_array_path_at_full_scale(self):
        """When no DCT scaling kicks in (target ~ source size), the fused
        path must reproduce the decode-then-resize reference exactly: the
        same bilinear kernel runs over the same libjpeg-decoded pixels."""
        import io

        from PIL import Image
        arr = _imgs(n=1, h=64, w=64)[0]
        blob = _jpeg_bytes(arr)
        decoded = np.asarray(Image.open(io.BytesIO(blob)).convert("RGB"))
        fused = native_aug.jpeg_resize_batch([blob], 60)
        oracle = native_aug.resize_batch(decoded[None], 60)
        np.testing.assert_allclose(fused, oracle, atol=1e-6)

    def test_dct_scaled_resize_close_to_full_decode(self):
        """With DCT scaling active (small target), the result is a slightly
        low-passed version of the full-res pipeline — close, not equal."""
        import io

        from PIL import Image
        # smooth gradient image: scaling artifacts stay tiny
        g = np.linspace(0, 255, 128, dtype=np.uint8)
        arr = np.stack(np.broadcast_arrays(g[:, None], g[None, :],
                                           g[:, None]), -1)
        blob = _jpeg_bytes(np.ascontiguousarray(arr), quality=98)
        decoded = np.asarray(Image.open(io.BytesIO(blob)).convert("RGB"))
        fused = native_aug.jpeg_resize_batch([blob], 32)     # scale 2/8
        oracle = native_aug.resize_batch(decoded[None], 32)
        assert np.abs(fused - oracle).mean() < 0.02

    def test_crop_window_statistics_match_array_path(self):
        """Same (seed, index, view) streams drive both paths, so the crop
        windows and post-crop draws coincide; only decoded pixel values may
        differ (DCT scaling).  On a flat image the outputs must agree."""
        arr = np.full((96, 96, 3), 128, np.uint8)
        blob = _jpeg_bytes(arr, quality=100)
        j1, j2 = native_aug.jpeg_augment_two_views([blob], 32, seed=9,
                                                   index_base=4)
        a1, a2 = native_aug.augment_two_views(arr[None], 32, seed=9,
                                              index_base=4)
        np.testing.assert_allclose(j1, a1, atol=0.02)
        np.testing.assert_allclose(j2, a2, atol=0.02)

    def test_non_jpeg_falls_back_to_pil(self):
        import io

        from PIL import Image
        arr = _imgs(n=1, h=40, w=40)[0]
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")
        v1, v2 = native_aug.jpeg_augment_two_views(
            [buf.getvalue()], 24, seed=2)
        assert v1.max() > 0.0                    # fallback decoded something
        # and the fallback is the SAME stream as the array path
        a1, a2 = native_aug.augment_two_views(arr[None], 24, seed=2,
                                              index_base=0)
        np.testing.assert_array_equal(v1, a1)
        np.testing.assert_array_equal(v2, a2)

    def test_corrupt_jpeg_yields_zeros_not_crash(self):
        good = _jpeg_bytes(_imgs(n=1)[0])
        v1, _ = native_aug.jpeg_augment_two_views(
            [b"\xff\xd8\xff\xe0garbage", good[:50], good], 16, seed=0)
        assert v1[2].max() > 0.0                 # good image decoded
        np.testing.assert_array_equal(v1[0], 0)  # corrupt -> zeroed
        np.testing.assert_array_equal(v1[1], 0)

    def test_image_folder_native_backend_loader(self, tmp_path):
        from PIL import Image

        from byol_tpu.core.config import (Config, DeviceConfig, TaskConfig)
        from byol_tpu.data.loader import get_loader
        rng = np.random.RandomState(0)
        for split, n in (("train", 8), ("test", 4)):
            for cls in ("a", "b"):
                d = tmp_path / split / cls
                d.mkdir(parents=True)
                for i in range(n):
                    arr = rng.randint(0, 255, (48, 56, 3), dtype=np.uint8)
                    Image.fromarray(arr).save(d / f"{i}.jpg")
        cfg = Config(task=TaskConfig(task="image_folder",
                                     data_dir=str(tmp_path), batch_size=4,
                                     image_size_override=32,
                                     data_backend="native"),
                     device=DeviceConfig(num_replicas=1, seed=3))
        loader = get_loader(cfg)
        assert loader.num_train_samples == 16
        batches = list(loader.train_loader)
        assert len(batches) == 4
        b = batches[0]
        assert b["view1"].shape == (4, 32, 32, 3)
        assert 0.0 <= b["view1"].min() and b["view1"].max() <= 1.0
        assert not np.allclose(b["view1"], b["view2"])
        # determinism + epoch reseed (set_all_epochs contract)
        again = next(iter(loader.train_loader))
        np.testing.assert_array_equal(b["view1"], again["view1"])
        loader.set_all_epochs(1)
        b1 = next(iter(loader.train_loader))
        assert not np.array_equal(b["view1"], b1["view1"])
        # eval: resize-only, identical view slots
        eb = next(iter(loader.test_loader))
        np.testing.assert_array_equal(eb["view1"], eb["view2"])
        # abandoning an iterator mid-epoch (debug_step / early break) must
        # release the producer thread, not leak it blocked on the queue
        import gc
        import threading
        import time as time_lib
        before = threading.active_count()
        it = iter(loader.train_loader)
        next(it)
        it.close()
        del it
        gc.collect()
        for _ in range(50):                      # producer exits within 5s
            if threading.active_count() <= before:
                break
            time_lib.sleep(0.1)
        assert threading.active_count() <= before


def test_augment_distribution_sanity():
    """Statistical smoke: over many samples, ~50% flips/blurs, ~20%
    grayscale.  Catches gate/draw seed-coupling regressions (the bug class
    fixed in the TF path) without pinning exact streams."""
    imgs = np.tile(
        np.linspace(0, 255, 32 * 32 * 3, dtype=np.uint8).reshape(
            1, 32, 32, 3), (400, 1, 1, 1))
    v1, _ = native_aug.augment_two_views(imgs, 32, seed=11,
                                         color_jitter_strength=0.0)
    # with cj strength 0 the pipeline is crop+flip+gray+blur; count grayscale
    # outputs: all three channels equal everywhere
    gray = np.all(np.abs(v1[..., 0] - v1[..., 1]) < 1e-6, axis=(1, 2))
    frac_gray = gray.mean()
    assert 0.1 < frac_gray < 0.32, frac_gray
