"""ResNet backbone forward parity vs independent torch builds.

Covers ResNet-18 (BasicBlock) and ResNet-50 (Bottleneck).

torchvision is not installed here, so the torch side is built IN THIS TEST
from the torchvision ResNet architecture definition (7x7/2 stem + BN +
ReLU + 3x3/2 maxpool, post-activation BasicBlocks with 1x1 downsample on
shape change, same for Bottlenecks, global average pool — the structure the reference consumes
via ``models.__dict__[args.arch]``, main.py:190-193).  Its randomly
initialized weights are mapped onto :class:`byol_tpu.models.resnet.ResNet`
and the two must produce the same features in train mode (BN on batch
statistics), pinning conv padding, stride, BN, pooling, and residual-path
conventions across frameworks where the model's FLOPs actually live.

The flax model is built with ``zero_init_residual=False`` to match
torchvision's default (the gate exists for exactly this parity,
resnet.py).
"""
import functools

import numpy as np
import pytest
import torch
import torch.nn as tnn
import torch.nn.functional as F

import jax.numpy as jnp

from byol_tpu.models.resnet import make_resnet


class TorchBasicBlock(tnn.Module):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.conv1 = tnn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(cout)
        self.conv2 = tnn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False),
                tnn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.down is None else self.down(x)
        y = F.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return F.relu(y + idn)


class TorchBottleneck(tnn.Module):
    def __init__(self, cin, width, stride, inner_mult=1):
        super().__init__()
        # inner_mult=2 is torchvision's wide_resnet*_2 (width_per_group=128):
        # only the inner convs widen; the block output stays width*4
        cout = width * 4
        inner = width * inner_mult
        self.conv1 = tnn.Conv2d(cin, inner, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(inner)
        self.conv2 = tnn.Conv2d(inner, inner, 3, stride, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(inner)
        self.conv3 = tnn.Conv2d(inner, cout, 1, bias=False)
        self.bn3 = tnn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False),
                tnn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.down is None else self.down(x)
        y = F.relu(self.bn1(self.conv1(x)))
        y = F.relu(self.bn2(self.conv2(y)))
        y = self.bn3(self.conv3(y))
        return F.relu(y + idn)


class TorchResNet(tnn.Module):
    def __init__(self, block_cls, stage_sizes):
        super().__init__()
        self.stage_sizes = stage_sizes
        self.stem = tnn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn = tnn.BatchNorm2d(64)
        widths = [64, 128, 256, 512]
        expansion = 1 if block_cls is TorchBasicBlock else 4
        layers, cin = [], 64
        for i, (w, n) in enumerate(zip(widths, stage_sizes)):
            for j in range(n):
                stride = 2 if (i > 0 and j == 0) else 1
                layers.append(block_cls(cin, w, stride))
                cin = w * expansion
        self.blocks = tnn.ModuleList(layers)

    def forward(self, x):
        x = F.relu(self.bn(self.stem(x)))
        x = F.max_pool2d(x, 3, 2, 1)
        for b in self.blocks:
            x = b(x)
        return x.mean(dim=(2, 3))


def _wj(t):
    return jnp.asarray(t.detach().numpy())


def _conv_k(conv):                      # OIHW -> HWIO
    return _wj(conv.weight).transpose(2, 3, 1, 0)


def _bn_vars(bn):
    return ({"scale": _wj(bn.weight), "bias": _wj(bn.bias)},
            {"mean": _wj(bn.running_mean), "var": _wj(bn.running_var)})



def _map_block(b):
    """One torch block -> (params, batch_stats) subtrees (shared by the
    full-net mapper and the single-block tests so the two can't drift)."""
    p, s = {}, {}
    for k in ("conv1", "conv2", "conv3"):
        if hasattr(b, k):
            p[k] = {"kernel": _conv_k(getattr(b, k))}
    for k in ("bn1", "bn2", "bn3"):
        if hasattr(b, k):
            p[k], s[k] = _bn_vars(getattr(b, k))
    if b.down is not None:
        p["downsample_conv"] = {"kernel": _conv_k(b.down[0])}
        p["downsample_bn"], s["downsample_bn"] = _bn_vars(b.down[1])
    return p, s


def _map_params(tm: TorchResNet):
    params = {"stem_conv": {"kernel": _conv_k(tm.stem)}}
    stats = {}
    params["stem_bn"], stats["stem_bn"] = _bn_vars(tm.bn)
    idx = 0
    for i, n in enumerate(tm.stage_sizes):
        for j in range(n):
            b = tm.blocks[idx]
            idx += 1
            name = f"stage{i + 1}_block{j + 1}"
            params[name], stats[name] = _map_block(b)
    return params, stats


def _randomize_running_stats(tm):
    # non-trivial running stats so eval mode actually exercises them
    with torch.no_grad():
        for m in tm.modules():
            if isinstance(m, tnn.BatchNorm2d):
                m.running_mean.uniform_(-0.5, 0.5)
                m.running_var.uniform_(0.5, 1.5)


class TestResNetForwardParity:
    def test_resnet18_train_mode_features_match_torch(self):
        # Train mode (BN on batch statistics) is only numerically comparable
        # while the late stages keep enough spatial extent: at small images
        # the last stage normalizes over ~batch-many values per channel and
        # train-mode BN amplifies fp32 noise unboundedly when two values
        # nearly coincide (verified: identical inputs through the same
        # stride-2 block match to 1e-14 in fp64 at every spatial size, so
        # the divergence is conditioning, not conventions).  rn18@64px is
        # well-conditioned; rn50 train-mode parity is covered by the exact
        # single-block tests + the eval-mode full net below.
        torch.manual_seed(0)
        tm = TorchResNet(TorchBasicBlock, [2, 2, 2, 2])
        tm.train()
        x = np.random.RandomState(0).rand(4, 3, 64, 64).astype(np.float32)
        with torch.no_grad():
            want = tm(torch.from_numpy(x)).numpy()

        fm = make_resnet("resnet18", zero_init_residual=False)
        params, stats = _map_params(tm)
        got = fm.apply({"params": params, "batch_stats": stats},
                       jnp.asarray(x.transpose(0, 2, 3, 1)),   # NCHW->NHWC
                       train=True, mutable=["batch_stats"])[0]
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("arch,block_cls,stages", [
        ("resnet18", TorchBasicBlock, [2, 2, 2, 2]),
        ("resnet50", TorchBottleneck, [3, 4, 6, 3]),
        # torchvision wide convention: the two inner convs at 2x, dim 2048
        ("wide_resnet50_2",
         functools.partial(TorchBottleneck, inner_mult=2),
         [3, 4, 6, 3]),
    ])
    def test_eval_mode_uses_running_stats_like_torch(self, arch, block_cls,
                                                     stages):
        torch.manual_seed(1)
        tm = TorchResNet(block_cls, stages)
        _randomize_running_stats(tm)
        tm.eval()
        x = np.random.RandomState(1).rand(2, 3, 32, 32).astype(np.float32)
        with torch.no_grad():
            want = tm(torch.from_numpy(x)).numpy()

        fm = make_resnet(arch, zero_init_residual=False)
        params, stats = _map_params(tm)
        got = fm.apply({"params": params, "batch_stats": stats},
                       jnp.asarray(x.transpose(0, 2, 3, 1)),
                       train=False, mutable=False)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("stride", [1, 2])
    @pytest.mark.parametrize("block", ["basic", "bottleneck"])
    def test_single_block_train_mode_exact(self, block, stride):
        """Each block type in isolation, train-mode BN, identical inputs —
        must match torch to fp32 tightness at every tested size (this is
        the convention check the full-net train comparison can't give for
        deep stacks, see test_resnet18_train_mode_features_match_torch)."""
        import functools
        import flax.linen as nn
        from byol_tpu.models.resnet import BasicBlock, Bottleneck
        conv = functools.partial(nn.Conv, use_bias=False)
        norm = functools.partial(nn.BatchNorm, use_running_average=False,
                                 momentum=0.9, epsilon=1e-5)
        torch.manual_seed(0)
        if block == "basic":
            tb = TorchBasicBlock(16, 16 if stride == 1 else 32, stride)
            fb = BasicBlock(filters=tb.conv1.out_channels,
                            strides=(stride, stride), conv=conv, norm=norm,
                            zero_init_last_bn=False)
        else:
            tb = TorchBottleneck(16, 8, stride)
            fb = Bottleneck(filters=8, strides=(stride, stride), conv=conv,
                            norm=norm, zero_init_last_bn=False)
        tb.train()
        x = np.random.RandomState(0).rand(2, 16, 8, 8).astype(np.float32)
        with torch.no_grad():
            want = tb(torch.from_numpy(x)).numpy().transpose(0, 2, 3, 1)
        p, s = _map_block(tb)
        got, _ = fb.apply({"params": p, "batch_stats": s},
                          jnp.asarray(x.transpose(0, 2, 3, 1)),
                          mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-5, atol=1e-5)
