"""ResNet-18 backbone forward parity vs an independent torch build.

torchvision is not installed here, so the torch side is built IN THIS TEST
from the torchvision ResNet architecture definition (7x7/2 stem + BN +
ReLU + 3x3/2 maxpool, post-activation BasicBlocks with 1x1 downsample on
shape change, global average pool — the structure the reference consumes
via ``models.__dict__[args.arch]``, main.py:190-193).  Its randomly
initialized weights are mapped onto :class:`byol_tpu.models.resnet.ResNet`
and the two must produce the same features in train mode (BN on batch
statistics), pinning conv padding, stride, BN, pooling, and residual-path
conventions across frameworks where the model's FLOPs actually live.

The flax model is built with ``zero_init_residual=False`` to match
torchvision's default (the gate exists for exactly this parity,
resnet.py).
"""
import numpy as np
import torch
import torch.nn as tnn
import torch.nn.functional as F

import jax.numpy as jnp

from byol_tpu.models.resnet import make_resnet


class TorchBasicBlock(tnn.Module):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.conv1 = tnn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(cout)
        self.conv2 = tnn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(cout)
        self.down = None
        if stride != 1 or cin != cout:
            self.down = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False),
                tnn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.down is None else self.down(x)
        y = F.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return F.relu(y + idn)


class TorchResNet18(tnn.Module):
    def __init__(self):
        super().__init__()
        self.stem = tnn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn = tnn.BatchNorm2d(64)
        widths, blocks = [64, 128, 256, 512], [2, 2, 2, 2]
        layers, cin = [], 64
        for i, (w, n) in enumerate(zip(widths, blocks)):
            for j in range(n):
                stride = 2 if (i > 0 and j == 0) else 1
                layers.append(TorchBasicBlock(cin, w, stride))
                cin = w
        self.blocks = tnn.ModuleList(layers)

    def forward(self, x):
        x = F.relu(self.bn(self.stem(x)))
        x = F.max_pool2d(x, 3, 2, 1)
        for b in self.blocks:
            x = b(x)
        return x.mean(dim=(2, 3))


def _wj(t):
    return jnp.asarray(t.detach().numpy())


def _conv_k(conv):                      # OIHW -> HWIO
    return _wj(conv.weight).transpose(2, 3, 1, 0)


def _bn_vars(bn):
    return ({"scale": _wj(bn.weight), "bias": _wj(bn.bias)},
            {"mean": _wj(bn.running_mean), "var": _wj(bn.running_var)})


def _map_params(tm: TorchResNet18):
    params = {"stem_conv": {"kernel": _conv_k(tm.stem)}}
    stats = {}
    params["stem_bn"], stats["stem_bn"] = _bn_vars(tm.bn)
    idx = 0
    for i, n in enumerate([2, 2, 2, 2]):
        for j in range(n):
            b = tm.blocks[idx]
            idx += 1
            name = f"stage{i + 1}_block{j + 1}"
            p = {"conv1": {"kernel": _conv_k(b.conv1)},
                 "conv2": {"kernel": _conv_k(b.conv2)}}
            s = {}
            p["bn1"], s["bn1"] = _bn_vars(b.bn1)
            p["bn2"], s["bn2"] = _bn_vars(b.bn2)
            if b.down is not None:
                p["downsample_conv"] = {"kernel": _conv_k(b.down[0])}
                p["downsample_bn"], s["downsample_bn"] = _bn_vars(b.down[1])
            params[name] = p
            stats[name] = s
    return params, stats


class TestResNetForwardParity:
    def test_train_mode_features_match_torch(self):
        torch.manual_seed(0)
        tm = TorchResNet18()
        tm.train()
        x = np.random.RandomState(0).rand(4, 3, 64, 64).astype(np.float32)
        with torch.no_grad():
            want = tm(torch.from_numpy(x)).numpy()

        fm = make_resnet("resnet18", zero_init_residual=False)
        params, stats = _map_params(tm)
        got = fm.apply({"params": params, "batch_stats": stats},
                       jnp.asarray(x.transpose(0, 2, 3, 1)),   # NCHW->NHWC
                       train=True, mutable=["batch_stats"])[0]
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-4)

    def test_eval_mode_uses_running_stats_like_torch(self):
        torch.manual_seed(1)
        tm = TorchResNet18()
        # non-trivial running stats so eval mode actually exercises them
        with torch.no_grad():
            for m in tm.modules():
                if isinstance(m, tnn.BatchNorm2d):
                    m.running_mean.uniform_(-0.5, 0.5)
                    m.running_var.uniform_(0.5, 1.5)
        tm.eval()
        x = np.random.RandomState(1).rand(2, 3, 32, 32).astype(np.float32)
        with torch.no_grad():
            want = tm(torch.from_numpy(x)).numpy()

        fm = make_resnet("resnet18", zero_init_residual=False)
        params, stats = _map_params(tm)
        got = fm.apply({"params": params, "batch_stats": stats},
                       jnp.asarray(x.transpose(0, 2, 3, 1)),
                       train=False, mutable=False)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-4)
