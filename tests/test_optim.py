"""Optimizer tests: LARS trust ratio vs hand-computed values, exclusion
masks, schedules, factory composition."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byol_tpu.optim.factory import build_optimizer
from byol_tpu.optim.lars import (default_exclusion_mask, lars,
                                 scale_by_lars_trust_ratio)
from byol_tpu.optim.schedules import (cosine_ema_decay, epoch_granular,
                                      linear_scaled_lr, warmup_cosine)


class TestLars:
    def test_trust_ratio_hand_computed(self):
        # reference lars.py:102-108: g' = g * trust_coef*|p|/(|g_wd|+eps)
        params = {"kernel": jnp.asarray([[3.0, 4.0]])}      # |p| = 5
        grads = {"kernel": jnp.asarray([[0.6, 0.8]])}       # |g| = 1
        tx = scale_by_lars_trust_ratio(trust_coefficient=0.001, eps=0.0)
        out, _ = tx.update(grads, tx.init(params), params)
        np.testing.assert_allclose(
            np.asarray(out["kernel"]),
            np.asarray(grads["kernel"]) * 0.001 * 5.0, rtol=1e-6)

    def test_zero_norm_ratio_is_identity(self):
        # lars.py:105-107: adaptive_lr stays 1.0 unless both norms > 0.
        params = {"kernel": jnp.zeros((2, 2))}
        grads = {"kernel": jnp.ones((2, 2))}
        tx = scale_by_lars_trust_ratio()
        out, _ = tx.update(grads, tx.init(params), params)
        np.testing.assert_allclose(np.asarray(out["kernel"]), 1.0)

    def test_exclusion_mask_ndim_rule(self):
        params = {"dense": {"kernel": jnp.ones((4, 4)),
                            "bias": jnp.ones((4,))},
                  "bn": {"scale": jnp.ones((4,)), "bias": jnp.ones((4,))}}
        mask = default_exclusion_mask(params)
        assert mask["dense"]["kernel"] is True
        assert mask["dense"]["bias"] is False
        assert mask["bn"]["scale"] is False

    def test_bias_not_adapted_not_decayed(self):
        params = {"kernel": jnp.asarray([[3.0, 4.0]]),
                  "bias": jnp.asarray([1.0])}
        grads = {"kernel": jnp.asarray([[0.6, 0.8]]),
                 "bias": jnp.asarray([0.5])}
        tx = lars(optax.sgd(1.0), weight_decay=0.1)
        out, _ = tx.update(grads, tx.init(params), params)
        # bias: plain SGD, no wd, no trust ratio -> update = -lr * g
        np.testing.assert_allclose(np.asarray(out["bias"]), -0.5, rtol=1e-6)
        # kernel: g_wd = g + 0.1*p; ratio = 1e-3*|p|/|g_wd|
        g_wd = np.array([[0.6, 0.8]]) + 0.1 * np.array([[3.0, 4.0]])
        ratio = 1e-3 * 5.0 / np.linalg.norm(g_wd)
        np.testing.assert_allclose(np.asarray(out["kernel"]),
                                   -g_wd * ratio, rtol=1e-5)


class TestSchedules:
    def test_warmup_then_cosine_shape(self):
        # LinearWarmup semantics: factor t/warmup, first unit at 0
        # (scheduler.py:45-62); cosine spans total-warmup afterwards.
        s = warmup_cosine(1.0, warmup_units=10, total_units=110)
        assert float(s(0)) == 0.0
        assert float(s(5)) == pytest.approx(0.5)
        assert float(s(10)) == pytest.approx(1.0)       # cosine start
        assert float(s(60)) == pytest.approx(0.5)       # cosine midpoint
        assert float(s(110)) == pytest.approx(0.0, abs=1e-6)

    def test_fixed_schedule(self):
        s = warmup_cosine(2.0, warmup_units=4, total_units=100, kind="fixed")
        assert float(s(2)) == pytest.approx(1.0)
        assert float(s(50)) == pytest.approx(2.0)

    def test_unimplemented_kind_raises(self):
        # parity: 'step' advertised but NotImplementedError (main.py:292-293)
        with pytest.raises(NotImplementedError):
            warmup_cosine(1.0, 1, 10, kind="step")

    def test_epoch_granular_staircase(self):
        s = epoch_granular(lambda e: jnp.asarray(e, jnp.float32), 100)
        assert float(s(99)) == 0.0
        assert float(s(100)) == 1.0
        assert float(s(199)) == 1.0

    def test_linear_lr_scaling_only_sgd_momentum(self):
        # main.py:333-334
        assert linear_scaled_lr(0.2, 4096, "momentum") == pytest.approx(3.2)
        assert linear_scaled_lr(0.2, 4096, "sgd") == pytest.approx(3.2)
        assert linear_scaled_lr(0.2, 4096, "adam") == 0.2

    def test_cosine_ema_decay_curve(self):
        # main.py:160: tau(0)=base, tau(K)=1
        assert float(cosine_ema_decay(0, 100, 0.996)) == pytest.approx(0.996)
        assert float(cosine_ema_decay(100, 100, 0.996)) == pytest.approx(1.0)
        assert float(cosine_ema_decay(50, 100, 0.996)) == pytest.approx(
            1 - (1 - 0.996) / 2)


class TestFactory:
    def _params(self):
        return {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))}

    @pytest.mark.parametrize("name", [
        "sgd", "momentum", "adam", "rmsprop", "adadelta", "lamb",
        "lars_momentum", "lars_sgd", "lars_adam"])
    def test_registry_builds_and_steps(self, name):
        tx, sched = build_optimizer(
            name, base_lr=0.1, global_batch_size=256, weight_decay=1e-6,
            total_units=100, warmup_units=10)
        params = self._params()
        state = tx.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        updates, _ = tx.update(grads, state, params)
        assert all(jnp.all(jnp.isfinite(u))
                   for u in jax.tree_util.tree_leaves(updates))

    def test_lbfgs_minimizes_quadratic(self):
        """lbfgs (main.py:317) is jit-native here: L-BFGS direction with the
        schedule LR (no closure line search).  It must actually minimize."""
        tx, _ = build_optimizer(
            "lbfgs", base_lr=0.5, global_batch_size=256, weight_decay=0.0,
            total_units=100, warmup_units=0, lr_schedule_kind="fixed")
        target = jnp.asarray([3.0, -2.0])
        params = {"w": jnp.zeros(2)}
        state = tx.init(params)

        @jax.jit
        def step(params, state):
            grads = jax.grad(
                lambda p: jnp.sum((p["w"] - target) ** 2))(params)
            updates, state = tx.update(grads, state, params)
            return optax.apply_updates(params, updates), state

        for _ in range(30):
            params, state = step(params, state)
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   np.asarray(target), atol=1e-2)

    def test_ema_scaling_rule(self):
        """tau^kappa batch-size scaling (arXiv 2307.13813): halving the
        batch relative to the reference square-roots the decay... inverse:
        kappa = batch/ref, tau_eff = tau^kappa."""
        from byol_tpu.core.config import Config, DeviceConfig, ModelConfig, \
            RegularizerConfig, TaskConfig, resolve
        from byol_tpu.training.build import step_config

        def scfg_for(batch, ref):
            cfg = Config(
                task=TaskConfig(task="fake", batch_size=batch, epochs=1,
                                image_size_override=16),
                model=ModelConfig(arch="resnet18", base_decay=0.996,
                                  ema_scaling_reference_batch=ref),
                regularizer=RegularizerConfig(polyak_ema=0.999),
                device=DeviceConfig(num_replicas=1))
            rcfg = resolve(cfg, num_train_samples=4 * batch,
                           num_test_samples=batch, output_size=10,
                           input_shape=(16, 16, 3))
            return step_config(rcfg)

        assert scfg_for(512, 0).base_decay == 0.996          # rule off
        assert scfg_for(512, 512).base_decay == pytest.approx(0.996)
        assert scfg_for(1024, 512).base_decay == pytest.approx(0.996 ** 2)
        assert scfg_for(256, 512).base_decay == pytest.approx(0.996 ** 0.5)
        # the rule covers EVERY model EMA: Polyak averaging scales too
        assert scfg_for(512, 0).polyak_ema == 0.999
        assert scfg_for(1024, 512).polyak_ema == pytest.approx(0.999 ** 2)

    def test_unknown_raises(self):
        with pytest.raises(ValueError, match="unknown optimizer"):
            build_optimizer("frobnicate", base_lr=0.1, global_batch_size=256,
                            weight_decay=0.0, total_units=10, warmup_units=0)

    def test_clip_applied_first(self):
        # clip_grad_value_ analog (main.py:619-622): elementwise clamp.
        tx, _ = build_optimizer(
            "sgd", base_lr=1.0, global_batch_size=256, weight_decay=0.0,
            total_units=10, warmup_units=0, lr_schedule_kind="fixed",
            clip=0.5)
        params = self._params()
        grads = jax.tree_util.tree_map(lambda p: 10.0 * jnp.ones_like(p),
                                       params)
        updates, _ = tx.update(grads, tx.init(params), params)
        # warmup_units=0 => factor 1 => lr=1*batch-scale... sgd scales lr:
        # 256/256 = 1.0; update = -clip(g) = -0.5
        np.testing.assert_allclose(np.asarray(updates["kernel"]), -0.5)
