"""Objective golden tests.

The 'reference' norm mode must match the reference formula
(/root/reference/objective.py:6-25) computed independently with torch on the
same inputs; the 'paper' mode must equal 2 - 2*cosine_similarity per sample.
"""
import jax.numpy as jnp
import numpy as np
import torch

from byol_tpu.objectives.byol_loss import loss_function, regression_loss
from byol_tpu.objectives.metrics import cross_entropy, topk_accuracy


def _torch_reference_loss(p1, p2, t1, t2):
    """Reference math (objective.py:8-9,23-25), written against torch as an
    independent oracle: -2*sum(x*y,-1)/(|X|_F*|Y|_F), symmetrized, mean."""
    def reg(x, y):
        return -2 * torch.sum(x * y, dim=-1) / (x.norm() * y.norm())
    return torch.mean(reg(p1, t2) + reg(p2, t1)).item()


class TestReferenceMode:
    def test_matches_torch_oracle(self):
        rng = np.random.RandomState(0)
        p1, p2, t1, t2 = [rng.randn(8, 16).astype(np.float32)
                          for _ in range(4)]
        ours = loss_function(jnp.asarray(p1), jnp.asarray(p2),
                             jnp.asarray(t1), jnp.asarray(t2),
                             norm_mode="reference")
        golden = _torch_reference_loss(*map(torch.from_numpy,
                                            (p1, p2, t1, t2)))
        np.testing.assert_allclose(float(ours), golden, rtol=1e-5)

    def test_batch_coupling_quirk(self):
        # Quirk Q2: in reference mode, per-sample losses are coupled through
        # the whole-tensor norms — scaling ONE row changes every row's loss.
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        y = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        base = regression_loss(x, y, "reference")
        x2 = x.at[0].multiply(100.0)
        pert = regression_loss(x2, y, "reference")
        assert not np.allclose(base[1:], pert[1:])
        # paper mode: rows independent
        base_p = regression_loss(x, y, "paper")
        pert_p = regression_loss(x2, y, "paper")
        np.testing.assert_allclose(base_p[1:], pert_p[1:], rtol=1e-6)


class TestPaperMode:
    def test_equals_neg2_cosine(self):
        rng = np.random.RandomState(2)
        x = rng.randn(8, 16).astype(np.float32)
        y = rng.randn(8, 16).astype(np.float32)
        ours = regression_loss(jnp.asarray(x), jnp.asarray(y), "paper")
        cos = torch.nn.functional.cosine_similarity(
            torch.from_numpy(x), torch.from_numpy(y), dim=-1).numpy()
        np.testing.assert_allclose(np.asarray(ours), -2.0 * cos, rtol=1e-4,
                                   atol=1e-6)

    def test_aligned_vectors_minimize(self):
        x = jnp.ones((4, 8))
        assert np.allclose(regression_loss(x, x, "paper"), -2.0, atol=1e-5)
        assert np.allclose(regression_loss(x, -x, "paper"), 2.0, atol=1e-5)


class TestMetrics:
    def test_topk_percent(self):
        logits = jnp.asarray([[9.0, 1.0, 0.0, 0.0, 0.0, 0.5],
                              [0.0, 9.0, 1.0, 0.2, 0.1, 0.3],
                              [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]])
        labels = jnp.asarray([0, 2, 0])
        top1, top5 = topk_accuracy(logits, labels)
        assert float(top1) == pytest_approx(1 / 3 * 100)
        assert float(top5) == pytest_approx(2 / 3 * 100)

    def test_cross_entropy_matches_torch(self):
        rng = np.random.RandomState(3)
        logits = rng.randn(8, 10).astype(np.float32)
        labels = rng.randint(0, 10, size=(8,))
        ours = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
        golden = torch.nn.functional.cross_entropy(
            torch.from_numpy(logits), torch.from_numpy(labels)).item()
        np.testing.assert_allclose(ours, golden, rtol=1e-5)


def pytest_approx(x, rel=1e-5):
    import pytest
    return pytest.approx(x, rel=rel)


class TestMaskedMetrics:
    """Pad+mask eval batching: metrics over a padded batch with a mask must
    equal the same metrics over the unpadded batch, in both norm modes."""

    def _padded(self, arrs, pad_to):
        out = []
        for a in arrs:
            pad = np.zeros((pad_to - a.shape[0],) + a.shape[1:], a.dtype)
            out.append(np.concatenate([a, pad], axis=0))
        return out

    def test_masked_loss_matches_unpadded(self):
        rng = np.random.RandomState(3)
        arrs = [rng.randn(5, 16).astype(np.float32) for _ in range(4)]
        padded = self._padded(arrs, 8)
        mask = jnp.asarray([1.0] * 5 + [0.0] * 3)
        for mode in ("paper", "reference"):
            want = loss_function(*map(jnp.asarray, arrs), norm_mode=mode)
            got = loss_function(*map(jnp.asarray, padded), norm_mode=mode,
                                mask=mask)
            np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    def test_masked_cls_metrics_match_unpadded(self):
        rng = np.random.RandomState(4)
        logits = rng.randn(5, 10).astype(np.float32)
        labels = rng.randint(0, 10, size=(5,)).astype(np.int32)
        plogits, = self._padded([logits], 8)
        # pad labels with an arbitrary (wrong-by-construction) class
        plabels = np.concatenate([labels, np.zeros((3,), np.int32)])
        mask = jnp.asarray([1.0] * 5 + [0.0] * 3)
        np.testing.assert_allclose(
            float(cross_entropy(jnp.asarray(plogits), jnp.asarray(plabels),
                                mask=mask)),
            float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels))),
            rtol=1e-5)
        want = topk_accuracy(jnp.asarray(logits), jnp.asarray(labels))
        got = topk_accuracy(jnp.asarray(plogits), jnp.asarray(plabels),
                            mask=mask)
        for w, g in zip(want, got):
            np.testing.assert_allclose(float(g), float(w), rtol=1e-5)
