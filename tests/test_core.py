"""Core config / mesh / precision tests."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.core import config as config_lib
from byol_tpu.core.precision import BF16, FP32, get_policy
from byol_tpu.parallel.mesh import (MeshSpec, build_mesh, data_sharding,
                                    replicated, shard_batch_to_mesh)


def _cfg(**task):
    c = config_lib.Config()
    return c.replace(task=dataclasses.replace(c.task, **task))


class TestResolve:
    def test_reference_derivation_math(self):
        # Reference math at main.py:420-425,725: global batch 1024 over 8
        # replicas -> 128/replica; 50000 train samples -> 6250/replica;
        # steps = 6250 // 128 = 48 (drop remainder); total = epochs * steps.
        cfg = _cfg(batch_size=1024, epochs=100)
        r = config_lib.resolve(cfg, num_train_samples=50000,
                               num_test_samples=10000, output_size=10,
                               input_shape=(224, 224, 3),
                               num_valid_samples=5000)
        assert r.batch_size_per_replica == 128
        assert r.num_train_samples == 6250
        assert r.steps_per_train_epoch == 48
        assert r.total_train_steps == 4800
        assert r.num_test_samples == 10000  # test not sharded (main.py:422)
        assert r.num_valid_samples == 625   # valid sharded like train
                                            # (main.py:423)

    def test_indivisible_batch_raises(self):
        cfg = _cfg(batch_size=100)
        with pytest.raises(ValueError, match="not divisible"):
            config_lib.resolve(cfg, num_train_samples=1000,
                               num_test_samples=100, output_size=10,
                               input_shape=(32, 32, 3))

    def test_zero_steps_raises(self):
        cfg = _cfg(batch_size=4096)
        with pytest.raises(ValueError, match="steps_per_train_epoch"):
            config_lib.resolve(cfg, num_train_samples=1000,
                               num_test_samples=100, output_size=10,
                               input_shape=(32, 32, 3))

    def test_zero1_mode_validated(self):
        """ISSUE 7: bad --zero1 values and the zero1 x TP clash are
        rejected at resolve(), not at trace time."""
        kw = dict(num_train_samples=1000, num_test_samples=100,
                  output_size=10, input_shape=(32, 32, 3))
        cfg = _cfg(batch_size=64)
        bad = cfg.replace(
            device=dataclasses.replace(cfg.device, zero1="sharded"))
        with pytest.raises(ValueError, match="zero1"):
            config_lib.resolve(bad, **kw)
        clash = cfg.replace(
            device=dataclasses.replace(cfg.device, zero1="on",
                                       model_parallel=2))
        with pytest.raises(ValueError, match="model-parallel"):
            config_lib.resolve(clash, **kw)
        ok = cfg.replace(
            device=dataclasses.replace(cfg.device, zero1="on"))
        assert config_lib.resolve(ok, **kw).cfg.device.zero1 == "on"

    def test_run_name_deterministic(self):
        cfg = _cfg(uid="exp1")
        assert config_lib.run_name(cfg) == config_lib.run_name(cfg)
        cfg2 = _cfg(uid="exp1", batch_size=2048)
        assert config_lib.run_name(cfg) != config_lib.run_name(cfg2)


class TestMesh:
    def test_build_8dev(self, mesh8):
        assert mesh8.shape == {"data": 8, "sequence": 1, "model": 1}

    def test_dp_sp_mesh(self, mesh_dp_sp):
        assert mesh_dp_sp.shape == {"data": 4, "sequence": 2, "model": 1}

    def test_bad_shape_raises(self):
        with pytest.raises(ValueError):
            build_mesh(MeshSpec(data=3))  # 8 devices not divisible

    def test_shard_batch(self, mesh8):
        x = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
        gx = shard_batch_to_mesh(x, mesh8)
        assert gx.sharding == data_sharding(mesh8)
        np.testing.assert_array_equal(np.asarray(gx), x)

    def test_replicated_sharding(self, mesh8):
        p = jax.device_put(jnp.ones((4, 4)), replicated(mesh8))
        assert p.sharding.is_fully_replicated


class TestPrecision:
    def test_policy_selection(self):
        assert get_policy(True) is BF16
        assert get_policy(False) is FP32

    def test_bf16_casts_only_floats(self):
        tree = {"w": jnp.ones((2, 2), jnp.float32),
                "i": jnp.ones((2,), jnp.int32)}
        out = BF16.cast_to_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["i"].dtype == jnp.int32
        back = BF16.cast_to_param(out)
        assert back["w"].dtype == jnp.float32
