"""Microbatch gradient accumulation + selective remat + threaded prefetch.

The contracts under test (ISSUE 1 tentpole):
- ``accum_steps=k`` at microbatch ``m`` with ``accum_bn_mode='global'``
  produces the SAME post-update params as one step at batch ``k*m`` (fp32
  tolerance), with the optimizer step count advancing ONCE — the exactness
  oracle for the accumulation plumbing (grad averaging, metric weighting,
  single LARS update + EMA tick, cross-microbatch BN-stat sync);
- the scan modes ('average' / 'microbatch') share that plumbing and differ
  from the big batch only in BN-statistics granularity;
- selective remat policies change NOTHING numerically — same loss, same
  post-step state as the un-rematted graph;
- ``prefetch_to_mesh`` (now a background producer thread) preserves order,
  propagates source-iterator exceptions, and shuts its thread down.
"""
import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byol_tpu.core import config as config_lib
from byol_tpu.parallel.mesh import shard_batch_to_mesh
from byol_tpu.training.build import setup_training
from tests.conftest import guard_steps

BATCH = 32


def tiny_config(**optim_overrides):
    model_overrides = optim_overrides.pop("model", {})
    batch = optim_overrides.pop("batch_size", BATCH)
    c = config_lib.Config()
    c = c.replace(
        task=dataclasses.replace(c.task, batch_size=batch, epochs=2),
        model=dataclasses.replace(c.model, arch="resnet18",
                                  head_latent_size=64, projection_size=32,
                                  **model_overrides),
        optim=dataclasses.replace(c.optim, warmup=1, lr=0.1,
                                  **optim_overrides),
        device=dataclasses.replace(c.device, num_replicas=8, half=False),
    )
    return config_lib.resolve(c, num_train_samples=128, num_test_samples=32,
                              output_size=10, input_shape=(32, 32, 3),
                              representation_size=512)


def make_batch(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "view1": rng.rand(BATCH, 32, 32, 3).astype(np.float32),
        "view2": rng.rand(BATCH, 32, 32, 3).astype(np.float32),
        "label": rng.randint(0, 10, size=(BATCH,)).astype(np.int32),
    }


def run_steps(rcfg, mesh, n=3):
    """n train steps from the seed-0 init; returns (final state, metrics).

    Steps run under guard_steps (conftest.py): an implicit host transfer or
    tracer leak inside the accumulation scan fails tier-1 here, on CPU."""
    net, state, train_step, _, _ = setup_training(
        rcfg, mesh, jax.random.PRNGKey(0))
    train_step = guard_steps(train_step)
    metrics = None
    for i in range(n):
        batch = shard_batch_to_mesh(make_batch(seed=i), mesh)
        state, metrics = train_step(state, batch)
    return state, {k: float(v) for k, v in metrics.items()}


def tree_maxdiff(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return max(float(np.max(np.abs(np.asarray(x, np.float32)
                                   - np.asarray(y, np.float32))))
               for x, y in zip(la, lb))


class TestAccumulationParity:
    def test_global_mode_matches_big_batch(self, mesh8):
        """ACCEPTANCE: k-microbatch accumulated step == single batch-(k*m)
        step for accum_bn_mode='global' — params bitwise-close after 3 real
        LARS updates, BN running stats in sync, step counter advanced once
        per effective batch (3, not 3*k)."""
        big, big_m = run_steps(tiny_config(), mesh8)
        acc, acc_m = run_steps(
            tiny_config(accum_steps=4, accum_bn_mode="global"), mesh8)
        assert int(acc.step) == int(big.step) == 3
        assert int(acc.ema_step) == int(big.ema_step) == 3
        # fp32 reduction-order noise only (measured ~3e-5 on unit-scale
        # params after 3 updates)
        assert tree_maxdiff(big.params, acc.params) < 5e-4
        assert tree_maxdiff(big.target_params, acc.target_params) < 5e-4
        assert tree_maxdiff(big.batch_stats, acc.batch_stats) < 1e-4
        for k in big_m:
            np.testing.assert_allclose(acc_m[k], big_m[k], rtol=1e-3,
                                       atol=1e-3, err_msg=k)

    @pytest.mark.parametrize("bn_mode", ["average", "microbatch"])
    def test_scan_modes_step_and_stay_finite(self, mesh8, bn_mode):
        """The production scan modes: one optimizer step per effective
        batch, finite metrics, moving params and running stats.  (They
        deliberately differ from the big batch in BN granularity, so no
        equality assertion — that is what 'global' is for.)"""
        rcfg = tiny_config(accum_steps=4, accum_bn_mode=bn_mode)
        net, state, train_step, _, _ = setup_training(
            rcfg, mesh8, jax.random.PRNGKey(0))
        train_step = guard_steps(train_step)
        # device_get is zero-copy on CPU and the jitted step DONATES the
        # state, so the buffer is overwritten in place — snapshot by copy.
        bs_before = jax.tree_util.tree_map(
            lambda x: np.array(x, copy=True),
            jax.device_get(state.batch_stats))
        state, m1 = train_step(state, shard_batch_to_mesh(make_batch(0),
                                                          mesh8))
        state, m2 = train_step(state, shard_batch_to_mesh(make_batch(1),
                                                          mesh8))
        assert int(state.step) == 2          # optimizer steps, not k*2
        assert int(state.ema_step) == 2
        for k, v in {**m1, **m2}.items():
            assert np.isfinite(float(v)), k
        assert tree_maxdiff(bs_before, state.batch_stats) > 0.0

    def test_scan_modes_share_gradients(self, mesh8):
        """'average' and 'microbatch' normalize identically (per
        microbatch); from identical init their FIRST step must produce
        identical losses/gradients — they diverge only through the
        running-stat tick, which the first forward does not read."""
        _, m_avg = run_steps(tiny_config(accum_steps=4,
                                         accum_bn_mode="average"),
                             mesh8, n=1)
        _, m_mb = run_steps(tiny_config(accum_steps=4,
                                        accum_bn_mode="microbatch"),
                            mesh8, n=1)
        for k in m_avg:
            np.testing.assert_allclose(m_mb[k], m_avg[k], rtol=1e-5,
                                       err_msg=k)

    def test_resolve_rejects_indivisible_accum(self):
        with pytest.raises(ValueError, match="accum_steps"):
            tiny_config(accum_steps=5)      # 32 % (5*8) != 0
        with pytest.raises(ValueError, match="accum_bn_mode"):
            tiny_config(accum_steps=4, accum_bn_mode="bogus")


class TestAccumBNModeDelta:
    """ROADMAP open item, quantified: ``accum_bn_mode='average'`` ticks the
    BN running stats with the microbatch-averaged batch statistics — its
    running VARIANCE is a mean of microbatch variances, not the global
    variance ``'global'`` computes.  Eval-time BN reads these stats, so the
    delta must be measured before recommending 'average' for paper-recipe
    runs.  Measured here at accum 16 (the paper-scale 4096/256 ratio) and
    recorded in RESULTS.md."""

    def _run(self, mesh, bn_mode, batches, eval_batch):
        rcfg = tiny_config(accum_steps=16, accum_bn_mode=bn_mode,
                           batch_size=128)
        net, state, train_step, eval_step, _ = setup_training(
            rcfg, mesh, jax.random.PRNGKey(0))
        train_step = guard_steps(train_step)
        for b in batches:
            state, _ = train_step(state, shard_batch_to_mesh(b, mesh))
        em = guard_steps(eval_step)(state,
                                    shard_batch_to_mesh(eval_batch, mesh))
        return state, {k: float(v) for k, v in em.items()}

    @pytest.mark.slow    # two accum-16 compiles (~100 s cold); the numbers
    # it pins are recorded in RESULTS.md — tier-1 already covers the
    # accumulation plumbing via TestAccumulationParity
    def test_average_vs_global_eval_delta_accum16(self, mesh8):
        rng = np.random.RandomState(0)
        mk = lambda: {"view1": rng.rand(128, 32, 32, 3).astype(np.float32),
                      "view2": rng.rand(128, 32, 32, 3).astype(np.float32),
                      "label": rng.randint(0, 10, 128).astype(np.int32)}
        batches, eval_batch = [mk(), mk()], mk()
        st_avg, ev_avg = self._run(mesh8, "average", batches, eval_batch)
        st_glo, ev_glo = self._run(mesh8, "global", batches, eval_batch)

        # running-variance divergence: relative, per leaf ending in 'var'
        from jax import tree_util as tu
        fa = {tu.keystr(k): np.asarray(v)
              for k, v in tu.tree_leaves_with_path(st_avg.batch_stats)}
        fg = {tu.keystr(k): np.asarray(v)
              for k, v in tu.tree_leaves_with_path(st_glo.batch_stats)}
        rel = np.concatenate([
            (np.abs(fa[k] - fg[k]) / (np.abs(fg[k]) + 1e-6)).ravel()
            for k in fa if "var" in k])
        # The modes genuinely differ (mean-of-variances != global variance)
        # but only at the sub-percent level at accum 16 after 2 ticks:
        # measured mean 7.3e-4, max 1.4e-2 (RESULTS.md "accum_bn_mode
        # eval delta").  Bounds leave ~3x headroom over the measurement.
        assert rel.mean() > 0.0
        assert rel.mean() < 2.5e-3, rel.mean()
        assert rel.max() < 5e-2, rel.max()

        # eval-time metric deltas through those stats: measured loss_mean
        # delta 1.4e-2 (byol-dominated), linear CE 2.5e-4, top1/top5 equal.
        assert abs(ev_avg["loss_mean"] - ev_glo["loss_mean"]) < 5e-2
        assert abs(ev_avg["linear_loss_mean"]
                   - ev_glo["linear_loss_mean"]) < 5e-3
        assert ev_avg["top1_mean"] == ev_glo["top1_mean"]


class TestMicrobatchSplit:
    def test_strided_partition_covers_batch(self):
        from byol_tpu.training.steps import _microbatch_split
        x = jnp.arange(12)
        out = np.asarray(_microbatch_split(x, 3))
        assert out.shape == (3, 4)
        # microbatch i takes rows i, i+k, i+2k, ...
        np.testing.assert_array_equal(out[0], [0, 3, 6, 9])
        np.testing.assert_array_equal(out[1], [1, 4, 7, 10])
        assert sorted(out.ravel().tolist()) == list(range(12))
        with pytest.raises(ValueError, match="not divisible"):
            _microbatch_split(x, 5)


class TestRematPolicies:
    @pytest.mark.parametrize("policy", ["dots", "save_block_out"])
    def test_policy_is_numerically_inert(self, mesh8, policy):
        """Remat trades FLOPs for memory; the math must not move: same
        metrics and same post-step params as the un-rematted graph."""
        plain, plain_m = run_steps(tiny_config(), mesh8, n=2)
        remat, remat_m = run_steps(
            tiny_config(model={"remat_policy": policy}), mesh8, n=2)
        for k in plain_m:
            np.testing.assert_allclose(remat_m[k], plain_m[k], rtol=1e-4,
                                       atol=1e-4, err_msg=k)
        assert tree_maxdiff(plain.params, remat.params) < 5e-4

    def test_policy_composes_with_accumulation(self, mesh8):
        """The headline configuration: scan accumulation + selective remat
        in one step.  Still one optimizer step, finite metrics."""
        rcfg = tiny_config(accum_steps=4, accum_bn_mode="average",
                           model={"remat_policy": "dots"})
        state, metrics = run_steps(rcfg, mesh8, n=1)
        assert int(state.step) == 1
        for k, v in metrics.items():
            assert np.isfinite(v), k

    def test_unknown_policy_fails_fast(self):
        from byol_tpu.core.remat import resolve_policy_name, wrap_block
        with pytest.raises(ValueError, match="unknown remat policy"):
            resolve_policy_name(False, "dotz")
        with pytest.raises(ValueError, match="unknown remat policy"):
            wrap_block(object, "everything")
        with pytest.raises(ValueError):
            tiny_config(model={"remat_policy": "dotz"})

    def test_legacy_bool_maps_to_full(self):
        from byol_tpu.core.remat import resolve_policy_name
        assert resolve_policy_name(True, "none") == "full"
        assert resolve_policy_name(False, "none") == "none"
        # explicit policy wins over the bool
        assert resolve_policy_name(True, "dots") == "dots"

    def test_all_named_policies_resolve(self):
        from byol_tpu.core.remat import POLICY_NAMES, checkpoint_policy
        for name in POLICY_NAMES:
            checkpoint_policy(name)   # no typo'd jax attribute lookups

    def test_names_policy_rejects_untagged_graph(self):
        """Runtime complement to graphlint GL105: a names-based policy over
        a graph with NO checkpoint_name tags must raise (it would silently
        save nothing — the known compile hazard), while tagged graphs and
        non-names policies pass.  The build path runs this check in
        setup_training, so test_policy_is_numerically_inert also exercises
        it end-to-end with the real ResNet."""
        from byol_tpu.core import remat

        def untagged(x):
            return x * 2.0

        def tagged(x):
            return remat.tag_block_out(x * 2.0)

        x = jnp.ones((4,))
        assert remat.BLOCK_OUT in remat.tags_in_trace(tagged, x)
        with pytest.raises(remat.RematTagError, match="save_block_out"):
            remat.assert_tags_in_trace(untagged, x,
                                       policy_name="save_block_out")
        with pytest.raises(remat.RematTagError, match="offload_block_out"):
            remat.assert_tags_in_trace(untagged, x,
                                       policy_name="offload_block_out")
        # non-names policies don't key on tags: no trace, no error
        assert remat.assert_tags_in_trace(
            untagged, x, policy_name="dots") == set()
        # tagged graph under a names policy: validated, tags returned
        assert remat.BLOCK_OUT in remat.assert_tags_in_trace(
            tagged, x, policy_name="save_block_out")


class TestThreadedPrefetch:
    def _threads(self):
        return {t.name for t in threading.enumerate()}

    def test_order_preserved_and_device_resident(self, mesh8):
        from byol_tpu.data.prefetch import prefetch_to_mesh
        src = [{"x": np.full((8,), i, np.float32)} for i in range(7)]
        out = list(prefetch_to_mesh(iter(src), mesh8, size=2))
        assert len(out) == 7
        for i, batch in enumerate(out):
            assert isinstance(batch["x"], jax.Array)
            np.testing.assert_array_equal(np.asarray(batch["x"]),
                                          src[i]["x"])

    def test_source_exception_propagates(self, mesh8):
        from byol_tpu.data.prefetch import prefetch_to_mesh

        def source():
            yield {"x": np.zeros((8,), np.float32)}
            yield {"x": np.ones((8,), np.float32)}
            raise RuntimeError("loader blew up")

        it = prefetch_to_mesh(source(), mesh8, size=2)
        assert float(np.asarray(next(it)["x"])[0]) == 0.0
        assert float(np.asarray(next(it)["x"])[0]) == 1.0
        with pytest.raises(RuntimeError, match="loader blew up"):
            next(it)

    def test_consumer_break_stops_producer_thread(self, mesh8):
        from byol_tpu.data.prefetch import prefetch_to_mesh

        produced = []

        def source():
            for i in range(1000):
                produced.append(i)
                yield {"x": np.full((8,), i, np.float32)}

        it = prefetch_to_mesh(source(), mesh8, size=2)
        next(it)
        it.close()       # consumer leaves early (break / early stop)
        deadline = time.time() + 5.0
        while ("prefetch_to_mesh" in self._threads()
               and time.time() < deadline):
            time.sleep(0.05)
        assert "prefetch_to_mesh" not in self._threads()
        # bounded production: at most the queue depth + in-flight items,
        # nowhere near the 1000-item source
        assert len(produced) < 10

    def test_producer_overlaps_consumer(self, mesh8):
        """The point of the thread: production happens while the consumer
        is busy.  With a slow consumer and queue depth 2, batch 3 must be
        produced BEFORE the consumer asks for it."""
        from byol_tpu.data.prefetch import prefetch_to_mesh
        produced = threading.Event()

        def source():
            for i in range(4):
                if i == 2:
                    produced.set()
                yield {"x": np.full((8,), i, np.float32)}

        it = prefetch_to_mesh(source(), mesh8, size=2)
        next(it)                      # consume one; 2 more should buffer
        assert produced.wait(timeout=5.0)
        list(it)

    def test_rejects_nonpositive_size(self, mesh8):
        from byol_tpu.data.prefetch import prefetch_to_mesh
        with pytest.raises(ValueError, match="size"):
            next(prefetch_to_mesh(iter([]), mesh8, size=0))
