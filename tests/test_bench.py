"""Unit tests for bench.py's measurement-protection machinery.

The bench burned two rounds on robustness bugs (VERDICT.md r1/r2) and then
nearly lost its TPU evidence twice more (backend-death mislabeling, partial
-file truncation) — these tests pin the protections:

- `_flush_partial` must never destroy a pre-existing partial file (first
  flush moves it to `<path>.prev`);
- `_config_failed` must distinguish did-not-fit (ladder steps down) from
  backend death on a CPU parent (a host backend cannot die);
- the MFU accounting must follow the 2-FLOPs-per-MAC convention of the
  quoted chip peaks (the r2 VERDICT's ~12% figure was a 1-FLOP/MAC
  mismatch of the same measurement).
"""
import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    """Import bench.py as a throwaway module with cwd in a temp dir."""
    monkeypatch.chdir(tmp_path)
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestFlushPreservation:
    def test_first_flush_backs_up_existing_file(self, bench, tmp_path):
        prior = {"results": [{"config": "precious"}]}
        with open("bench_partial.json", "w") as f:
            json.dump(prior, f)
        bench._record("new_run", x=1)
        with open("bench_partial.json") as f:
            assert json.load(f)["results"][0]["config"] == "new_run"
        with open("bench_partial.json.prev") as f:
            assert json.load(f) == prior

    def test_later_flushes_do_not_rotate_again(self, bench):
        bench._record("a")
        bench._record("b")
        with open("bench_partial.json") as f:
            assert [r["config"] for r in json.load(f)["results"]] == ["a", "b"]
        assert not os.path.exists("bench_partial.json.prev")


class TestFailureClassification:
    def test_ordinary_failure_steps_ladder_down(self, bench):
        assert bench._config_failed(
            "t", RuntimeError("RESOURCE_EXHAUSTED: out of memory")) is False
        assert bench._backend_dead is False

    def test_unavailable_on_cpu_parent_is_config_local(self, bench):
        # a host backend cannot die; the marker alone must not abort the run
        assert bench._config_failed(
            "t", RuntimeError("UNAVAILABLE: transient")) is False
        assert bench._backend_dead is False

    def test_non_marker_errors_never_probe(self, bench, monkeypatch):
        import subprocess

        def boom(*a, **k):  # pragma: no cover - must not be reached
            raise AssertionError("probe subprocess must not run")
        monkeypatch.setattr(subprocess, "run", boom, raising=False)
        bench._reraise_if_backend_dead(ValueError("shape mismatch"))


class TestStaleFallback:
    """Backend unreachable at capture time -> emit the last committed TPU
    measurement marked stale (parseable), or die with a clear message when
    no artifact exists to fall back to."""

    _ARTIFACT = {
        "results": [
            {"config": "tpu_first", "batch_per_chip": 256, "fit": True,
             "images_per_sec_per_chip": 776.11, "mfu": 0.2577},
            {"config": "reference_faithful", "batch_per_chip": 128,
             "fit": True, "images_per_sec_per_chip": 495.7, "mfu": 0.165},
        ],
        "arch": "resnet50", "device_kind": "TPU v5 lite",
    }

    def test_emits_stale_committed_measurement(self, bench, capsys):
        with open("bench_partial.json", "w") as f:
            json.dump(self._ARTIFACT, f)
        bench._preflight_backend = lambda *a, **k: False
        bench.main()
        out = json.loads(capsys.readouterr().out)
        assert out["stale"] is True
        assert out["value"] == 776.11
        assert out["vs_baseline"] == pytest.approx(1.566, abs=1e-3)
        assert "unreachable" in out["note"]

    def test_dies_without_tpu_artifact(self, bench):
        bench._preflight_backend = lambda *a, **k: False
        with pytest.raises(SystemExit, match="no committed TPU artifact"):
            bench.main()

    def test_falls_back_to_prev_after_rotation(self, bench):
        # an intervening run (e.g. a sweep) rotates the committed artifact
        # to .prev and fills the live file with rows the fallback can't
        # use — the .prev measurement must still be found
        with open("bench_partial.json.prev", "w") as f:
            json.dump(self._ARTIFACT, f)
        with open("bench_partial.json", "w") as f:
            json.dump({"results": [{"config": "sweep_bs512", "fit": False}],
                       "device_kind": "TPU v5 lite"}, f)
        bench._preflight_backend = lambda *a, **k: False
        import io, contextlib
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            bench.main()
        out = json.loads(buf.getvalue())
        assert out["stale"] is True and out["value"] == 776.11
        assert ".prev" in out["note"]

    def test_non_headline_modes_refuse_stale_fallback(self, bench, capsys):
        import sys as _sys
        with open("bench_partial.json", "w") as f:
            json.dump(self._ARTIFACT, f)
        bench._preflight_backend = lambda *a, **k: False
        old = _sys.argv
        _sys.argv = ["bench.py", "--sweep"]
        try:
            with pytest.raises(SystemExit, match="needs live hardware"):
                bench.main()
        finally:
            _sys.argv = old

    def test_cpu_artifact_does_not_masquerade_as_tpu(self, bench):
        cpu_art = dict(self._ARTIFACT, device_kind="cpu")
        with open("bench_partial.json", "w") as f:
            json.dump(cpu_art, f)
        bench._preflight_backend = lambda *a, **k: False
        with pytest.raises(SystemExit, match="no committed TPU artifact"):
            bench.main()


class TestSweepResume:
    """A sweep re-run after a mid-sweep tunnel drop must converge: reuse
    measured rows, never re-attempt the known compile-OOM (un-rematted
    bs1024, whose compile attempt once crashed the remote-compile service),
    and order the risky rematted-1024 rows last."""

    _PRIOR = {
        "device_kind": "TPU v5 lite",
        "results": [
            {"config": "sweep_bs512_remat0_fuse1", "batch_per_chip": 512,
             "fit": True, "remat": False, "fuse_views": True,
             "images_per_sec_per_chip": 709.4, "mfu": 0.235},
            {"config": "sweep_bs384_remat0_fuse1", "batch_per_chip": 384,
             "fit": False},
        ],
    }

    @staticmethod
    def _fake_tpu(bench, monkeypatch, kind="TPU v5 lite"):
        import types
        monkeypatch.setattr(
            bench.jax, "devices",
            lambda: [types.SimpleNamespace(device_kind=kind)])

    def test_prior_rows_scanned_from_live_and_prev(self, bench, monkeypatch):
        self._fake_tpu(bench, monkeypatch)
        with open("bench_partial.json.prev", "w") as f:
            json.dump(self._PRIOR, f)
        with open("bench_partial.json", "w") as f:
            json.dump({"device_kind": "TPU v5 lite", "results": [
                {"config": "tpu_first", "fit": True},            # not sweep_*
                {"config": "sweep_bs256_remat1_fuse1", "fit": True,
                 "batch_per_chip": 256, "remat": True, "fuse_views": True,
                 "images_per_sec_per_chip": 800.0, "mfu": 0.27}]}, f)
        prior = bench._sweep_prior_rows()
        assert set(prior) == {"sweep_bs512_remat0_fuse1",
                              "sweep_bs384_remat0_fuse1",
                              "sweep_bs256_remat1_fuse1"}

    def test_other_device_kind_rows_are_not_reused(self, bench, monkeypatch):
        # rows captured on a different chip generation (or the cpu
        # fallback) are incomparable — never carried into this run
        self._fake_tpu(bench, monkeypatch, kind="TPU v4")
        for kind in ("cpu", "TPU v5 lite"):
            with open("bench_partial.json", "w") as f:
                json.dump(dict(self._PRIOR, device_kind=kind), f)
            assert bench._sweep_prior_rows() == {}

    def test_resume_of_a_resumed_sweep(self, bench, monkeypatch):
        # a thrice-interrupted sweep reloads rows that were themselves
        # recorded by a resume (they carry reused=True) — must not crash
        self._fake_tpu(bench, monkeypatch)
        prior = {"device_kind": "TPU v5 lite", "results": [
            {"config": "sweep_bs512_remat0_fuse1", "batch_per_chip": 512,
             "fit": True, "remat": False, "fuse_views": True, "reused": True,
             "images_per_sec_per_chip": 709.4, "mfu": 0.235}]}
        with open("bench_partial.json", "w") as f:
            json.dump(prior, f)
        monkeypatch.setattr(bench, "_throughput",
                            lambda bs, *a, **k: 100.0)
        monkeypatch.setattr(bench.jax, "default_backend", lambda: "tpu")
        bench._sweep("resnet50", 224, [1024, 512, 256], lambda v: 0.1)
        rows = json.load(open("bench_sweep.json"))
        assert sum(r.get("images_per_sec_per_chip") == 709.4
                   for r in rows) == 1

    def test_truncated_sweep_exits_nonzero(self, bench, monkeypatch,
                                           capsys):
        # a backend death mid-grid must not exit 0: the staged capture
        # marks a stage done on success, and a truncated sweep marked
        # complete would never resume its remaining rows
        self._fake_tpu(bench, monkeypatch)
        calls = []

        def dying_throughput(bs, *a, **kw):
            calls.append(bs)
            if len(calls) >= 2:
                bench._backend_dead = True   # as _config_failed would set
                raise RuntimeError("UNAVAILABLE: Socket closed")
            return 100.0
        monkeypatch.setattr(bench, "_throughput", dying_throughput)
        monkeypatch.setattr(bench, "_config_failed",
                            lambda ctx, e: bench._backend_dead)
        monkeypatch.setattr(bench.jax, "default_backend", lambda: "tpu")
        with pytest.raises(SystemExit) as exc:
            bench._sweep("resnet50", 224, [512, 256], lambda v: 0.1)
        assert exc.value.code == 3
        out = json.loads(capsys.readouterr().out)
        assert out["complete"] is False and out["value"] == 1
        # the row measured before the death was still written
        assert len(json.load(open("bench_sweep.json"))) == 1

    def test_sweep_table_rotated_not_clobbered(self, bench, monkeypatch):
        # a partial re-run must never destroy a complete prior table: the
        # existing bench_sweep.json moves to .prev before the new write
        self._fake_tpu(bench, monkeypatch)
        complete = [{"batch_per_chip": 512, "images_per_sec_per_chip": 1.0}]
        with open("bench_sweep.json", "w") as f:
            json.dump(complete, f)
        monkeypatch.setattr(bench, "_throughput", lambda bs, *a, **k: 100.0)
        monkeypatch.setattr(bench.jax, "default_backend", lambda: "tpu")
        bench._sweep("resnet50", 224, [512, 256], lambda v: 0.1)
        assert json.load(open("bench_sweep.json.prev")) == complete
        assert json.load(open("bench_sweep.json"))[0][
            "images_per_sec_per_chip"] == 100.0

    def _measure_with_prior_1024_row(self, bench, monkeypatch, row_extra):
        self._fake_tpu(bench, monkeypatch)
        with open("bench_partial.json", "w") as f:
            json.dump({"device_kind": "TPU v5 lite", "results": [
                dict({"config": "sweep_bs1024_remat1_fuse1",
                      "batch_per_chip": 1024, "fit": False}, **row_extra)]},
                      f)
        measured = []

        def fake_throughput(bs, image_size, arch, **kw):
            measured.append((bs, kw["remat"], kw["fuse_views"]))
            return 100.0
        monkeypatch.setattr(bench, "_throughput", fake_throughput)
        monkeypatch.setattr(bench.jax, "default_backend", lambda: "tpu")
        bench._sweep("resnet50", 224, [1024, 512, 256], lambda v: 0.1)
        return measured

    def test_oom_rows_at_1024_stay_reused(self, bench, monkeypatch):
        # the >=1024 compile-OOMs are the multi-minute failures (one crashed
        # the remote-compile service) — fit=False rows whose recorded error
        # carries a genuine OOM signature ARE reused
        measured = self._measure_with_prior_1024_row(
            bench, monkeypatch,
            {"error": "JaxRuntimeError('INTERNAL: ... tpu_compile_helper "
                      "subprocess exit code 1')"})
        assert (1024, True, True) not in measured
        assert (1024, True, False) in measured   # distinct config still runs

    def test_transient_1024_failures_are_reattempted(self, bench,
                                                     monkeypatch):
        # a tunnel drop that slipped past the liveness probe must not
        # permanently mask the one config where bs1024 might fit: without
        # an OOM signature (or with no recorded error at all) re-attempt
        measured = self._measure_with_prior_1024_row(
            bench, monkeypatch, {"error": "UNAVAILABLE: Socket closed"})
        assert (1024, True, True) in measured

    def test_grid_reuses_prior_and_never_reattempts_oom_1024(
            self, bench, monkeypatch):
        self._fake_tpu(bench, monkeypatch)
        with open("bench_partial.json", "w") as f:
            json.dump(self._PRIOR, f)
        measured = []

        def fake_throughput(bs, image_size, arch, **kw):
            measured.append((bs, kw["remat"], kw["fuse_views"]))
            return 100.0
        monkeypatch.setattr(bench, "_throughput", fake_throughput)
        monkeypatch.setattr(bench.jax, "default_backend", lambda: "tpu")
        bench._sweep("resnet50", 224, [1024, 512, 256, 128, 64, 32],
                     lambda v: 0.1)
        # the measured (fit=True) row was not re-measured...
        assert (512, False, True) not in measured
        # ...but a sub-1024 fit=False row IS re-attempted: it may be a
        # mislabeled transient, and its re-measure is cheap
        assert (384, False, True) in measured
        # un-rematted 1024 never attempted; rematted 1024 attempted LAST
        assert all(remat for bs, remat, _ in measured if bs == 1024)
        assert [m for m in measured if m[0] == 1024] == measured[-2:]
        # no rung below 256 in the sweep grid
        assert min(bs for bs, _, _ in measured) >= 256
        rows = json.load(open("bench_sweep.json"))
        reused = [r for r in rows
                  if r.get("images_per_sec_per_chip") == 709.4]
        assert len(reused) == 1      # measured row carried into the table


class TestMVC:
    """--mvc (minimum-viable capture) must fit a short tunnel window:
    one rung per headline family at the best KNOWN batch size, the
    rematted bs512 row under the sweep naming contract, and a fresh
    (never stale) headline line."""

    _PRIOR = {
        "device_kind": "TPU v5 lite", "arch": "resnet50",
        "results": [
            {"config": "tpu_first", "batch_per_chip": 512, "fit": True,
             "images_per_sec_per_chip": 715.6, "mfu": 0.238},
            {"config": "tpu_first", "batch_per_chip": 256, "fit": True,
             "images_per_sec_per_chip": 776.1, "mfu": 0.258},
            {"config": "reference_faithful", "batch_per_chip": 128,
             "fit": True, "images_per_sec_per_chip": 495.7, "mfu": 0.165},
        ],
    }

    @staticmethod
    def _fake_tpu(bench, monkeypatch):
        import types
        monkeypatch.setattr(
            bench.jax, "devices",
            lambda: [types.SimpleNamespace(device_kind="TPU v5 lite")])

    def test_refuses_stale_fallback(self, bench, monkeypatch):
        import sys as _sys
        with open("bench_partial.json", "w") as f:
            json.dump(self._PRIOR, f)
        bench._preflight_backend = lambda *a, **k: False
        monkeypatch.setattr(_sys, "argv", ["bench.py", "--mvc"])
        with pytest.raises(SystemExit, match="needs live hardware"):
            bench.main()

    def test_prior_best_rungs_prefers_fastest_fit(self, bench, monkeypatch):
        self._fake_tpu(bench, monkeypatch)
        with open("bench_partial.json", "w") as f:
            json.dump(self._PRIOR, f)
        rungs = bench._prior_best_rungs()
        # bs256 is the FASTER tpu_first rung even though 512 also fits
        assert rungs["tpu_first"] == 256
        assert rungs["reference_faithful"] == 128

    def test_other_device_kind_rungs_ignored(self, bench, monkeypatch):
        import types
        monkeypatch.setattr(
            bench.jax, "devices",
            lambda: [types.SimpleNamespace(device_kind="TPU v4")])
        with open("bench_partial.json", "w") as f:
            json.dump(self._PRIOR, f)
        assert bench._prior_best_rungs() == {}

    def _run_mvc(self, bench, monkeypatch, capsys, fail_at=()):
        self._fake_tpu(bench, monkeypatch)
        with open("bench_partial.json", "w") as f:
            json.dump(self._PRIOR, f)
        measured = []

        def fake_throughput(bs, image_size, arch, **kw):
            measured.append((bs, kw.get("remat", False),
                             kw["ema_update_mode"], kw["half"]))
            if (bs, kw.get("remat", False)) in fail_at:
                raise RuntimeError("XLA compile error")
            return 700.0
        monkeypatch.setattr(bench, "_throughput", fake_throughput)
        # main() stamps device metadata on _partial before dispatching to
        # _mvc; the sweep-reuse contract keys on it
        bench._partial.update(device_kind="TPU v5 lite", arch="resnet50")
        bench._mvc("resnet50", 224, [1024, 512, 256, 128, 64, 32], True,
                   lambda v: 0.25, "dense")
        out = json.loads(capsys.readouterr().out)
        return measured, out

    def test_one_rung_per_family_plus_remat_row(self, bench, monkeypatch,
                                                capsys):
        measured, out = self._run_mvc(bench, monkeypatch, capsys)
        # exactly one rung per family, at the prior best-known batch
        assert measured == [
            (256, False, "post", True),            # tpu_first @ prior best
            (128, False, "reference_pre", False),  # reference_faithful
            (256, False, "reference_pre", True),   # bf16 middle rung
            (512, True, "post", True),             # the rematted sweep row
        ]
        assert out["value"] == 700.0 and "stale" not in out
        assert out["vs_baseline"] == 1.0
        assert out["dtype_gain"] == 1.0 and out["redesign_gain"] == 1.0
        # the remat row is recorded under the sweep naming contract, so a
        # later full --sweep reuses it (_sweep_prior_rows)
        rows = json.load(open("bench_partial.json"))["results"]
        remat = [r for r in rows
                 if r["config"] == "sweep_bs512_remat1_fuse1"]
        assert remat and remat[0]["fit"] and remat[0]["remat"] is True
        prior = bench._sweep_prior_rows()
        assert "sweep_bs512_remat1_fuse1" in prior

    def test_failed_rung_steps_down_once(self, bench, monkeypatch, capsys):
        measured, out = self._run_mvc(bench, monkeypatch, capsys,
                                      fail_at={(256, False)})
        # 256 fails for tpu_first AND bf16_ref; each steps down exactly once
        assert (128, False, "post", True) in measured
        assert (128, False, "reference_pre", True) in measured
        assert out["value"] == 700.0

    def test_headline_survives_missing_families(self, bench, monkeypatch,
                                                capsys):
        # every non-primary family failing entirely must still print a
        # fresh headline (vs_baseline null), never crash the capture
        measured, out = self._run_mvc(
            bench, monkeypatch, capsys,
            fail_at={(128, False), (64, False), (512, True)})
        assert out["value"] == 700.0
        assert out["vs_baseline"] is None and "dtype_gain" not in out


class TestKnownOOM:
    """The un-rematted rn50@224 bs1024 compile once crashed the
    remote-compile service for hours — no ladder may ever re-attempt it."""

    def test_truth_table(self, bench):
        assert bench._known_oom(1024, "resnet50", 224)
        assert bench._known_oom(1024, "resnet50", 224, remat=False)
        assert not bench._known_oom(1024, "resnet50", 224, remat=True)
        assert not bench._known_oom(512, "resnet50", 224)
        assert not bench._known_oom(1024, "vit_b16", 224)   # own ladders
        assert not bench._known_oom(1024, "resnet50", 96)   # start below

    def test_headline_ladder_skips_and_records(self, bench, monkeypatch,
                                               capsys):
        import sys as _sys
        import types
        monkeypatch.setattr(
            bench.jax, "devices",
            lambda: [types.SimpleNamespace(device_kind="TPU v5 lite")])
        monkeypatch.setattr(bench.jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(bench.jax.config, "update", lambda *a: None)
        monkeypatch.setattr(_sys, "argv", ["bench.py"])
        bench._preflight_backend = lambda *a, **k: True
        attempted = []

        def fake_throughput(bs, *a, **kw):
            attempted.append(bs)
            return 500.0
        monkeypatch.setattr(bench, "_throughput", fake_throughput)
        bench.main()
        assert 1024 not in attempted       # never compiled
        rows = json.load(open("bench_partial.json"))["results"]
        skipped = [r for r in rows if r.get("batch_per_chip") == 1024]
        assert skipped and all("documented" in r["error"] for r in skipped)
        out = json.loads(capsys.readouterr().out)
        assert out["value"] == 500.0 and "stale" not in out


class TestMFUAccounting:
    def test_flops_per_sample_uses_8_forward_equivalents(self, bench):
        # 2 online + 2 target fwds + backward(2x) = 8 fwd-images, 2 FLOPs/MAC
        got = bench._flops_per_sample("resnet50", 224)
        assert got == pytest.approx(8 * 4.089e9 * 2, rel=1e-6)

    def test_unknown_shape_returns_none(self, bench):
        assert bench._flops_per_sample("resnet50", 96) is not None
        assert bench._flops_per_sample("resnet99", 224) is None


class TestArchOverride:
    """--arch (BASELINE config-5 ViT swap) must isolate its evidence file
    and carry its own FLOPs accounting."""

    def test_vit_arch_uses_own_partial_path(self, bench, monkeypatch):
        import sys as _sys
        monkeypatch.setattr(_sys, "argv", ["bench.py", "--arch", "vit_b16"])
        bench._preflight_backend = lambda *a, **k: False
        # no committed vit artifact in this cwd -> clean SystemExit, and the
        # committed resnet artifact path is never consulted or rotated
        with pytest.raises(SystemExit, match="no committed TPU artifact"):
            bench.main()
        assert bench._PARTIAL_PATH == "bench_partial_vit_b16.json"
        assert not os.path.exists("bench_partial.json.prev")

    def test_vit_flops_accounting(self, bench):
        # 8 forward-image-equivalents x 17.56 GMACs x 2 FLOPs/MAC
        assert bench._flops_per_sample("vit_b16", 224) == pytest.approx(
            8 * 17.56 * 2 * 1e9)

    def test_unknown_arch_has_no_mfu(self, bench):
        assert bench._flops_per_sample("resnet200w2", 224) is None

    def test_arch_typo_fails_fast(self, bench, monkeypatch):
        import sys as _sys
        monkeypatch.setattr(_sys, "argv", ["bench.py", "--arch", "vit_b_16"])
        with pytest.raises(SystemExit, match="unknown arch"):
            bench.main()


class TestInputLadderPlumbing:
    """ISSUE 3 bench surface: every row records h2d_bytes_per_step, and the
    --input-ladder / --dry-compile plumbing carries --augment-placement."""

    def test_batch_h2d_bytes_concrete_and_abstract(self, bench):
        import numpy as np
        import jax as _jax
        concrete = {"view1": np.zeros((2, 4, 4, 3), np.float32),
                    "view2": np.zeros((2, 4, 4, 3), np.float32),
                    "label": np.zeros((2,), np.int32)}
        want = 2 * (2 * 4 * 4 * 3 * 4) + 2 * 4
        assert bench._batch_h2d_bytes(concrete) == want
        abstract = {"images": _jax.ShapeDtypeStruct((2, 4, 4, 3), np.uint8),
                    "label": _jax.ShapeDtypeStruct((2,), np.int32)}
        assert bench._batch_h2d_bytes(abstract) == 2 * 4 * 4 * 3 + 2 * 4

    def test_abstract_batch_placements(self, bench, mesh8):
        import numpy as np
        raw = bench._abstract_batch(8, 16, mesh8, augment_placement="step")
        assert sorted(raw) == ["images", "label"]
        assert raw["images"].dtype == np.uint8
        views = bench._abstract_batch(8, 16, mesh8)
        assert sorted(views) == ["label", "view1", "view2"]
        assert views["view1"].dtype == np.float32
        # the 8x H2D contract, end to end through the helper pair
        assert (bench._batch_h2d_bytes(views) - 8 * 4
                == 8 * (bench._batch_h2d_bytes(raw) - 8 * 4))

    def test_gate_args_forward_placement_and_arch(self, bench):
        args = bench._gate_args(512, 256, "dots", "average", "dense",
                                "vit_b16", placement="step")
        assert "--augment-placement" in args
        assert args[args.index("--augment-placement") + 1] == "step"
        assert args[args.index("--arch") + 1] == "vit_b16"

    def test_input_gate_phase_names_both_placements(self, bench,
                                                    monkeypatch):
        ran = []

        def fake_gates(rungs, timeout):
            ran.extend(name for name, _ in rungs)
            return {name: {"status": "ok", "row": {}} for name, _ in rungs}
        monkeypatch.setattr(bench, "_run_compile_gates", fake_gates)
        gates = bench._input_gate_phase(False, None, "dense")
        # CPU fallback ladder: one effective rung, both placements
        assert ran == ["input_eff32_mb16_loader", "input_eff32_mb16_step"]
        assert set(gates) == set(ran)
