"""ViT attention / encoder-block parity vs torch.

The reference has no attention anywhere (ResNet path, main.py:190-193) and
torchvision is absent, so there is no reference ViT to be faithful to —
but the multi-head attention and transformer-block CONVENTIONS (packed QKV
projection layout, per-head scaling, softmax axis, pre-LN residual wiring)
can still be pinned against torch's ``nn.MultiheadAttention``, the
ecosystem-standard implementation.  This closes the last model family the
torch parity harness (PARITY.md §4) did not cover.

Alignment notes: torch's in_proj packs rows [Wq; Wk; Wv] while the flax
``qkv`` Dense packs output columns [q | k | v] — mapped by transposing and
concatenating along axis 1.  The MLP comparison uses
``tnn.GELU(approximate='tanh')`` to match ``jax.nn.gelu``'s default tanh
approximation, and the torch LayerNorms are built with ``eps=1e-6`` to
match flax's default (torch's is 1e-5 — a real convention delta this test
would otherwise paper over; measured, it shifts block outputs by ~1e-4).
"""
import numpy as np
import torch
import torch.nn as tnn

import jax.numpy as jnp

from byol_tpu.models.vit import EncoderBlock, SelfAttention

B, S, D, H = 2, 10, 32, 4


def _wj(t):
    return jnp.asarray(t.detach().numpy())


def _map_attention(mha: tnn.MultiheadAttention):
    wq, wk, wv = mha.in_proj_weight.chunk(3)     # each (D, D)
    bq, bk, bv = mha.in_proj_bias.chunk(3)
    return {
        "qkv": {"kernel": jnp.concatenate(
                    [_wj(wq).T, _wj(wk).T, _wj(wv).T], axis=1),
                "bias": jnp.concatenate([_wj(bq), _wj(bk), _wj(bv)])},
        "proj": {"kernel": _wj(mha.out_proj.weight).T,
                 "bias": _wj(mha.out_proj.bias)},
    }


class TestAttentionParity:
    def test_self_attention_matches_torch_mha(self):
        torch.manual_seed(0)
        mha = tnn.MultiheadAttention(D, H, batch_first=True)
        x = np.random.RandomState(0).rand(B, S, D).astype(np.float32)
        with torch.no_grad():
            want, _ = mha(torch.from_numpy(x), torch.from_numpy(x),
                          torch.from_numpy(x), need_weights=False)
        att = SelfAttention(num_heads=H)
        got = att.apply({"params": _map_attention(mha)}, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), want.numpy(),
                                   rtol=1e-5, atol=1e-5)


class TorchPreLNBlock(tnn.Module):
    """Pre-LN transformer block wired exactly like models/vit.EncoderBlock."""

    def __init__(self, d, h, mlp_ratio=4):
        super().__init__()
        self.ln1 = tnn.LayerNorm(d, eps=1e-6)
        self.attn = tnn.MultiheadAttention(d, h, batch_first=True)
        self.ln2 = tnn.LayerNorm(d, eps=1e-6)
        self.fc1 = tnn.Linear(d, mlp_ratio * d)
        self.fc2 = tnn.Linear(mlp_ratio * d, d)
        self.gelu = tnn.GELU(approximate="tanh")   # = jax.nn.gelu default

    def forward(self, x):
        y = self.ln1(x)
        x = x + self.attn(y, y, y, need_weights=False)[0]
        y = self.ln2(x)
        return x + self.fc2(self.gelu(self.fc1(y)))


class TestEncoderBlockParity:
    def test_pre_ln_block_matches_torch(self):
        torch.manual_seed(1)
        tb = TorchPreLNBlock(D, H)
        x = np.random.RandomState(1).rand(B, S, D).astype(np.float32)
        with torch.no_grad():
            want = tb(torch.from_numpy(x)).numpy()

        def ln(m):
            return {"scale": _wj(m.weight), "bias": _wj(m.bias)}

        params = {
            "ln1": ln(tb.ln1),
            "attn": _map_attention(tb.attn),
            "ln2": ln(tb.ln2),
            "mlp": {"fc1": {"kernel": _wj(tb.fc1.weight).T,
                            "bias": _wj(tb.fc1.bias)},
                    "fc2": {"kernel": _wj(tb.fc2.weight).T,
                            "bias": _wj(tb.fc2.bias)}},
        }
        block = EncoderBlock(num_heads=H)
        got = block.apply({"params": params}, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-5, atol=1e-5)
