"""Worker for the two-process UNEVEN-SHARD image_folder integration test.

The hard multi-host case the round-4 machinery exists for: an ImageFolder
tree whose interleaved per-host shards differ in size, so naive per-host
iteration would give hosts different train/eval batch counts and deadlock
the SPMD collectives.  Covers, across two real OS processes (Gloo):

- train: ``epoch_batches`` pins every host to steps_per_train_epoch
  (wrap/truncate) — the epoch completes with the step counters equal;
- eval: ``lockstep_iter`` pad-feeds the short host;
- offline linear eval: SPMD extraction + lockstep drain + Quirk-Q9
  round-robin de-dup — both ranks must report identical results.

argv: rank port tree_dir
"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_compile_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def main() -> int:
    rank, port, tree = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    from byol_tpu.parallel.mesh import (MeshSpec, build_mesh,
                                        initialize_distributed)
    initialize_distributed(f"localhost:{port}", num_processes=2,
                           process_id=rank)
    assert jax.process_count() == 2

    from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                      OptimConfig, TaskConfig)
    from byol_tpu.data.loader import get_loader
    from byol_tpu.training.linear_eval import run_linear_eval_from_cfg
    from byol_tpu.training.trainer import fit

    cfg = Config(
        # 11 train files -> interleaved shards of 6 and 5; host batch 2 ->
        # hosts would naively run 3 vs 2 train batches.  7 test files ->
        # eval remainder batches of different counts under shard_eval.
        task=TaskConfig(task="image_folder", data_dir=tree, batch_size=4,
                        epochs=1, image_size_override=16, grapher="null",
                        log_dir="/tmp/mh_if_runs"),
        model=ModelConfig(arch="resnet18", head_latent_size=32,
                          projection_size=16, fuse_views=True,
                          model_dir=f"/tmp/mh_if_models_{port}"),
        optim=OptimConfig(lr=0.1, warmup=1),
        device=DeviceConfig(num_replicas=4, half=False, seed=3,
                            shard_eval=True, save_on_signal=False),
    )
    loader = get_loader(cfg, shard_eval=True)
    assert loader.num_train_samples == 11 and loader.num_test_samples == 7
    result = fit(cfg, loader=loader, verbose=False)
    # steps_per_train_epoch = (11 // 4) // (4 // 4) = 2 on EVERY host
    assert int(result.state.step) == 2, int(result.state.step)
    print(f"RANK{rank} FIT ok step={int(result.state.step)} "
          f"test_loss={result.test_metrics['loss_mean']:.6f}")

    le = run_linear_eval_from_cfg(cfg, result.state, loader=loader,
                                  mesh=result.mesh, epochs=2, seed=0)
    print(f"RANK{rank} LE top1={le.top1:.6f} ntrain={le.num_train} "
          f"ntest={le.num_test}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
