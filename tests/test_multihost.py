"""Two-process multi-host integration: explicit rendezvous + cross-process
collectives on CPU (Gloo) through the real training stack.

The reference could only validate multi-node behavior by launching SLURM
jobs and watching NCCL connect or error (SURVEY.md §4); here two OS
processes rendezvous via ``jax.distributed.initialize``, shard the loader
per host, assemble global batches with
``jax.make_array_from_process_local_data`` (the multi-host branch of
``shard_batch_to_mesh``) and run one SPMD train step whose gradient psum
crosses the process boundary.
"""
import os
import re
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


@pytest.mark.slow
def test_two_process_train_step():
    # per-invocation port: concurrent suite runs must not collide, and a
    # leaked listener from a previous run must not poison this one
    port = str(20000 + os.getpid() % 20000)
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(_WORKER))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(rank), port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True) for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out)
    finally:
        for p in procs:         # never leak workers (they hold the port)
            if p.poll() is None:
                p.kill()
                p.communicate()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank{rank} failed:\n{out[-3000:]}"
    losses = []
    for out in outs:
        m = re.search(r"OK loss=(-?\d+\.\d+) step=1", out)
        assert m, out[-2000:]
        losses.append(float(m.group(1)))
    # SPMD: both ranks computed the same global loss
    assert losses[0] == losses[1]

    # multi-host offline linear eval: both ranks extracted the same global
    # feature matrix (per-host shards gathered over the mesh) and fit the
    # identical probe — top1 and the de-duplicated counts must agree
    evals = []
    for out in outs:
        m = re.search(r"LE top1=(-?\d+\.\d+) ntrain=(\d+) ntest=(\d+)", out)
        assert m, out[-2000:]
        evals.append((float(m.group(1)), int(m.group(2)), int(m.group(3))))
    assert evals[0] == evals[1]
    # the TRAIN features span both hosts' shards (8 + 8) and the replicated
    # test set was kept once, not twice (Quirk Q9 de-dup)
    assert evals[0][1] == 16 and evals[0][2] == 4


_IF_WORKER = os.path.join(os.path.dirname(__file__),
                          "_multihost_imagefolder_worker.py")


@pytest.mark.slow
def test_two_process_imagefolder_uneven_shards(tmp_path):
    """The hard pod case: an image_folder tree whose interleaved per-host
    shards are UNEVEN (11 train / 7 test files over 2 hosts).  Naive
    iteration would hand the hosts different train/eval batch counts and
    deadlock the SPMD collectives; the run must instead complete a full
    fit() (epoch pinned to steps_per_train_epoch on every host, eval in
    lockstep) plus the SPMD offline linear eval, with both ranks reporting
    identical step counters, losses, and probe results."""
    import numpy as np
    from PIL import Image

    rng = np.random.RandomState(7)
    for split, n in (("train", 11), ("test", 7)):
        for i in range(n):
            cls = i % 2
            d = tmp_path / split / f"{cls}"
            d.mkdir(parents=True, exist_ok=True)
            arr = rng.randint(0, 255, (24, 24, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.jpg")

    port = str(21000 + os.getpid() % 20000)
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(_IF_WORKER))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, _IF_WORKER, str(rank), port, str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True) for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=900)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank{rank} failed:\n{out[-3000:]}"
    fits, evals = [], []
    for out in outs:
        m = re.search(r"FIT ok step=(\d+) test_loss=(-?\d+\.\d+)", out)
        assert m, out[-2000:]
        fits.append((int(m.group(1)), float(m.group(2))))
        m = re.search(r"LE top1=(-?\d+\.\d+) ntrain=(\d+) ntest=(\d+)", out)
        assert m, out[-2000:]
        evals.append((float(m.group(1)), int(m.group(2)), int(m.group(3))))
    assert fits[0] == fits[1]        # same steps, same SPMD test loss
    assert evals[0] == evals[1]      # identical probe on both ranks
    # all 11 train files' features were gathered (6 + 5 across hosts)
    assert evals[0][1] == 11 and evals[0][2] == 7
