"""Executable BASELINE.json config ladder.

BASELINE.json names five headline configurations (CIFAR smoke through the
ViT-B/16 encoder swap).  Each must BUILD and take one finite training step
through the public ``setup_training`` path — at tiny shapes, so this runs
on the CPU mesh; the full-scale versions only change sizes, not code
paths.  This is the SURVEY.md §7 stage-10 "config ladder" made an
executable regression rather than prose.
"""
import numpy as np
import pytest

import jax

from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                  OptimConfig, TaskConfig, resolve)
from byol_tpu.parallel.mesh import MeshSpec, build_mesh, shard_batch_to_mesh
from byol_tpu.training.build import setup_training

# (label, arch, image, GLOBAL batch (TaskConfig.batch_size — split across
#  the data axis by resolve()), data-axis size, half, extra model kw)
LADDER = [
    ("c1_cifar_smoke", "resnet18", 16, 16, 1, False, {}),
    ("c2_in100_syncbn_lars", "resnet50", 32, 8, 1, False, {}),
    ("c3_in1k_pod_dp8", "resnet50", 32, 16, 8, False, {"fuse_views": True}),
    ("c4_rn200w2_bf16", "resnet200w2", 16, 4, 1, True, {"fuse_views": True}),
    ("c5_vit_b16", "vit_b16", 32, 4, 1, False, {"pooling": "gap"}),
]


@pytest.mark.slow
@pytest.mark.parametrize("label,arch,image,batch,dp,half,extra",
                         LADDER, ids=[r[0] for r in LADDER])
def test_baseline_config_builds_and_steps(label, arch, image, batch, dp,
                                          half, extra):
    if dp > jax.device_count():
        pytest.skip(f"needs {dp} devices")
    mesh = build_mesh(MeshSpec(data=dp),
                      jax.devices()[:dp])
    cfg = Config(
        task=TaskConfig(task="fake", batch_size=batch, epochs=2,
                        image_size_override=image),
        model=ModelConfig(arch=arch, head_latent_size=64,
                          projection_size=32, **extra),
        optim=OptimConfig(lr=0.2, warmup=1, optimizer="lars_momentum"),
        device=DeviceConfig(num_replicas=dp, half=half, seed=0),
    )
    rcfg = resolve(cfg, num_train_samples=4 * batch, num_test_samples=batch,
                   output_size=10, input_shape=(image, image, 3))
    net, state, train_step, eval_step, _ = setup_training(
        rcfg, mesh, jax.random.PRNGKey(0))

    from tests.test_train_step import make_batch
    data = shard_batch_to_mesh(make_batch(rcfg), mesh)
    state, metrics = train_step(state, data)
    loss = float(metrics["loss_mean"])
    assert np.isfinite(loss), f"{label}: non-finite loss {loss}"
    eval_metrics = eval_step(state, data)
    assert np.isfinite(float(eval_metrics["loss_mean"]))
