"""Flight recorder + goodput accounting + offline report (ISSUE 9).

Four layers, bottom-up:

1. **SpanRecorder semantics**: monotonic begin/end, per-thread nesting
   depth, bounded ring with drop accounting, and the spans-off contract —
   the NULL recorder records NOTHING and returns one shared no-op context
   manager (the hot loop's ``--spans off`` path).
2. **Goodput folding**: spans partition wall time into productive +
   named badput buckets that sum EXACTLY to the window (the 1% identity
   events.py validates on every ``goodput`` line), only depth-0 spans
   attribute, and contiguous windows cover the whole run.
3. **Chrome trace export**: the written file is valid Chrome-trace JSON
   (``traceEvents`` with name/ts/dur/pid/tid complete events).
4. **Offline report**: ``byol_tpu.observability.report`` renders the
   waterfall / step-time trend / serving breakdown / anomaly timeline
   from a log ALONE and fails (rc=1) on a violated partition.
"""
import json
import threading
import time

import pytest

from byol_tpu.observability import goodput as goodput_lib
from byol_tpu.observability import spans as spans_lib
from byol_tpu.observability.events import RunLog, read_events, validate_event


# ---------------------------------------------------------------------------
# 1. recorder semantics
# ---------------------------------------------------------------------------

class TestSpanRecorder:
    def test_span_records_name_duration_and_order(self):
        rec = spans_lib.SpanRecorder()
        with rec.span("train/dispatch", step=3):
            time.sleep(0.01)
        with rec.span("input/wait"):
            pass
        records = rec.records()
        assert [r.name for r in records] == ["train/dispatch", "input/wait"]
        assert records[0].seconds >= 0.009
        assert records[0].t1 <= records[1].t0   # sequential, monotonic
        assert records[0].attrs == {"step": 3}
        assert records[1].attrs is None
        assert records[0].seq < records[1].seq

    def test_nesting_tracks_depth_and_inner_closes_first(self):
        rec = spans_lib.SpanRecorder()
        with rec.span("outer"):
            with rec.span("inner"):
                pass
        inner, outer = rec.records()   # closed-order append: inner first
        assert (inner.name, inner.depth) == ("inner", 1)
        assert (outer.name, outer.depth) == ("outer", 0)
        # depth resets for the next top-level span
        with rec.span("again"):
            pass
        assert rec.records()[-1].depth == 0

    def test_depth_is_per_thread(self):
        rec = spans_lib.SpanRecorder()
        seen = {}

        def worker():
            with rec.span("thread/top"):
                pass
            seen["rec"] = [r for r in rec.records()
                           if r.name == "thread/top"][0]

        with rec.span("main/outer"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        # the other thread's span is depth 0 even while main is nested
        assert seen["rec"].depth == 0
        assert seen["rec"].tid != threading.get_ident()

    def test_exception_still_closes_and_records(self):
        rec = spans_lib.SpanRecorder()
        with pytest.raises(RuntimeError, match="boom"):
            with rec.span("train/dispatch"):
                raise RuntimeError("boom")
        assert [r.name for r in rec.records()] == ["train/dispatch"]
        # depth unwound: a following span is top-level again
        with rec.span("next"):
            pass
        assert rec.records()[-1].depth == 0

    def test_ring_bound_evicts_oldest_and_counts_dropped(self):
        rec = spans_lib.SpanRecorder(capacity=4)
        for i in range(7):
            with rec.span(f"s{i}"):
                pass
        assert [r.name for r in rec.records()] == ["s3", "s4", "s5", "s6"]
        assert rec.dropped == 3

    def test_records_since_seq(self):
        rec = spans_lib.SpanRecorder()
        with rec.span("a"):
            pass
        mark = rec.last_seq()
        with rec.span("b"):
            pass
        assert [r.name for r in rec.records(since_seq=mark)] == ["b"]

    def test_null_recorder_records_nothing(self):
        """The --spans off contract: one shared no-op context manager, no
        clock read, no ring append — the hot loop is untouched."""
        null = spans_lib.NULL
        ctx1 = null.span("train/dispatch", step=1)
        ctx2 = null.span("anything/else")
        assert ctx1 is ctx2          # ONE shared object: zero allocation
        with ctx1:
            pass
        assert null.records() == []
        assert null.dropped == 0
        assert not null.enabled

    def test_module_default_recorder(self):
        rec = spans_lib.SpanRecorder()
        old = spans_lib.get_default()
        try:
            spans_lib.set_default(rec)
            with spans_lib.span("via/default"):
                pass
            assert [r.name for r in rec.records()] == ["via/default"]
        finally:
            spans_lib.set_default(old)
        # default-default is NULL: module-level span() is opt-in
        assert old is spans_lib.NULL


# ---------------------------------------------------------------------------
# 2. goodput folding
# ---------------------------------------------------------------------------

def _spin(rec, name, seconds, **attrs):
    with rec.span(name, **attrs):
        time.sleep(seconds)


class TestGoodputFold:
    def test_partition_sums_to_wall_exactly(self):
        rec = spans_lib.SpanRecorder()
        meter = goodput_lib.GoodputMeter(rec)
        _spin(rec, "train/dispatch", 0.02)
        _spin(rec, "input/wait", 0.01)
        _spin(rec, "eval/run", 0.01)
        p = meter.fold(scope="epoch", epoch=0)
        total = p["productive_seconds"] + sum(p["badput"].values())
        assert total == pytest.approx(p["wall_seconds"], rel=1e-9)
        assert p["productive_seconds"] >= 0.019
        assert p["badput"]["input_wait"] >= 0.009
        assert p["badput"]["eval"] >= 0.009
        assert p["badput"]["host_other"] >= 0.0
        assert 0.0 < p["goodput_fraction"] < 1.0
        # the emitted event passes the schema's 1% identity check
        validate_event({"v": 1, "kind": "goodput", "t": 0.0, **p})

    def test_only_top_level_spans_attribute(self):
        """A nested span's seconds live inside its parent — counting both
        would exceed wall time."""
        rec = spans_lib.SpanRecorder()
        meter = goodput_lib.GoodputMeter(rec)
        with rec.span("train/epoch_readback"):
            _spin(rec, "telemetry/drain", 0.02)   # nested: NOT badput
        p = meter.fold()
        assert p["badput"]["telemetry_readback"] == 0.0
        assert p["productive_seconds"] >= 0.019

    def test_windows_are_contiguous_and_final_totals(self):
        rec = spans_lib.SpanRecorder()
        meter = goodput_lib.GoodputMeter(rec)
        _spin(rec, "train/dispatch", 0.01)
        p0 = meter.fold(scope="epoch", epoch=0)
        _spin(rec, "checkpoint/save", 0.01)
        p1 = meter.fold(scope="epoch", epoch=1)
        time.sleep(0.005)                          # tail after last fold
        run = meter.final()
        assert run["scope"] == "run"
        # run wall covers construction -> final with nothing counted twice
        assert run["wall_seconds"] == pytest.approx(
            p0["wall_seconds"] + p1["wall_seconds"] + 0.005, abs=0.05)
        assert run["wall_seconds"] >= (p0["wall_seconds"]
                                       + p1["wall_seconds"])
        assert run["productive_seconds"] == pytest.approx(
            p0["productive_seconds"] + p1["productive_seconds"], rel=1e-9)
        assert run["badput"]["checkpoint"] == pytest.approx(
            p1["badput"]["checkpoint"], rel=1e-9)
        total = run["productive_seconds"] + sum(run["badput"].values())
        assert total == pytest.approx(run["wall_seconds"], rel=1e-9)

    def test_fold_emits_goodput_and_span_stats_events(self, tmp_path):
        rec = spans_lib.SpanRecorder()
        meter = goodput_lib.GoodputMeter(rec)
        for _ in range(3):
            _spin(rec, "train/dispatch", 0.002)
        _spin(rec, "input/wait", 0.002)
        path = str(tmp_path / "run.jsonl")
        with RunLog(path) as log:
            meter.fold(scope="epoch", epoch=5, events=log,
                       images_per_sec_per_chip=100.0)
            meter.final(events=log)
        got = list(read_events(path))
        kinds = [e["kind"] for e in got]
        assert kinds == ["goodput", "span_stats", "goodput"]
        ep, stats, run = got
        assert ep["scope"] == "epoch" and ep["epoch"] == 5
        assert ep["images_per_sec_per_chip"] == 100.0
        assert run["scope"] == "run" and run["windows"] == 2
        s = stats["spans"]["train/dispatch"]
        assert s["count"] == 3 and s["seconds"] >= 0.005
        assert s["p50_ms"] <= s["p99_ms"] <= s["max_ms"]

    def test_goodput_event_schema_rejects_leaky_partition(self):
        bad = {"v": 1, "kind": "goodput", "t": 0.0, "scope": "epoch",
               "wall_seconds": 10.0, "productive_seconds": 5.0,
               "badput": {"input_wait": 1.0}}    # 4s unaccounted
        with pytest.raises(ValueError, match="sum"):
            validate_event(bad)

    def test_bucket_vocabulary(self):
        assert goodput_lib.bucket_of("input/wait") == "input_wait"
        assert goodput_lib.bucket_of("input/fill") == "input_wait"
        assert goodput_lib.bucket_of("startup/compile") == "startup_compile"
        assert goodput_lib.bucket_of("telemetry/readback") \
            == "telemetry_readback"
        assert goodput_lib.bucket_of("eval/run") == "eval"
        assert goodput_lib.bucket_of("checkpoint/save") == "checkpoint"
        assert goodput_lib.bucket_of("train/dispatch") is None
        assert goodput_lib.bucket_of("unknown/thing") is None
        assert goodput_lib.OTHER_BUCKET in goodput_lib.BADPUT_BUCKETS


# ---------------------------------------------------------------------------
# 3. chrome trace export
# ---------------------------------------------------------------------------

class TestChromeTraceExport:
    def test_exported_file_is_valid_chrome_trace(self, tmp_path):
        rec = spans_lib.SpanRecorder()
        with rec.span("train/dispatch", step=1):
            with rec.span("serve/stage", trace_ids=[1, 2]):
                pass
        path = str(tmp_path / "trace.json")
        n = spans_lib.export_chrome_trace(rec.records(), path)
        assert n == 2
        with open(path) as f:
            trace = json.load(f)
        events = trace["traceEvents"]
        xs = [e for e in events if e.get("ph") == "X"]
        assert len(xs) == 2
        for e in xs:
            assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
            assert e["dur"] >= 0.0
        # sorted by start time: the nested span starts after its parent
        assert xs[0]["name"] == "train/dispatch"
        assert xs[1]["args"] == {"trace_ids": [1, 2]}
        # process metadata present (multi-file Perfetto sessions)
        assert any(e.get("ph") == "M" for e in events)

    def test_export_creates_parent_dirs_and_handles_empty(self, tmp_path):
        path = str(tmp_path / "deep" / "dir" / "trace.json")
        n = spans_lib.export_chrome_trace([], path)
        assert n == 0
        with open(path) as f:
            assert json.load(f)["traceEvents"][0]["ph"] == "M"


# ---------------------------------------------------------------------------
# 4. offline report
# ---------------------------------------------------------------------------

def _write_log(tmp_path, events):
    path = str(tmp_path / "run.jsonl")
    with RunLog(path) as log:
        for kind, payload in events:
            log.emit(kind, **payload)
    return path


class TestReport:
    def _sample_events(self):
        return [
            ("run_header", {"config": {}, "jax_version": "0",
                            "backend": "cpu", "run_name": "r"}),
            ("epoch", {"epoch": 0, "split": "train", "metrics": {},
                       "step_time_p50_s": 0.1, "step_time_p99_s": 0.3}),
            ("goodput", {"scope": "epoch", "epoch": 0, "wall_seconds": 10.0,
                         "productive_seconds": 8.0,
                         "badput": {"input_wait": 1.5, "host_other": 0.5}}),
            ("goodput", {"scope": "run", "wall_seconds": 10.0,
                         "productive_seconds": 8.0,
                         "badput": {"input_wait": 1.5, "host_other": 0.5}}),
            ("serve_stats", {"requests": 4, "batches": 2, "p50_ms": 3.0,
                             "p99_ms": 9.0,
                             "phase_ms": {"coalesce": 1.0, "stage": 0.5,
                                          "dispatch": 1.0, "readback": 0.4,
                                          "deliver": 0.1}}),
            ("anomaly", {"step": 17, "rule": "collapse",
                         "detail": "feature_std low"}),
            ("run_end", {}),
        ]

    def test_report_renders_all_sections_rc0(self, tmp_path, capsys):
        from byol_tpu.observability import report
        path = _write_log(tmp_path, self._sample_events())
        rc = report.main([path])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Goodput waterfall" in out and "80.0%" in out
        assert "input_wait" in out
        assert "Step-time trend" in out and "100.00ms" in out
        assert "Serving latency breakdown" in out and "coalesce" in out
        assert "Anomaly timeline" in out and "collapse" in out

    def test_report_fails_without_goodput_events(self, tmp_path, capsys):
        from byol_tpu.observability import report
        path = _write_log(tmp_path, [
            ("run_header", {"config": {}, "jax_version": "0",
                            "backend": "cpu"}),
            ("run_end", {}),
        ])
        rc = report.main([path])
        assert rc == 1
        assert "no goodput events" in capsys.readouterr().out

    def test_violated_partition_is_rc1_with_diagnostic(self, tmp_path,
                                                       capsys):
        """A goodput line whose buckets do NOT sum to wall must reach the
        renderer (rc 1 + the '!! partition off' diagnostic) — the strict
        reader raising on it would misreport the exact failure this
        command exists to show as an unreadable file (rc 2)."""
        import json as _json
        from byol_tpu.observability import report
        p = tmp_path / "broken.jsonl"
        bad = {"v": 1, "kind": "goodput", "t": 0.0, "scope": "run",
               "wall_seconds": 100.0, "productive_seconds": 10.0,
               "badput": {"input_wait": 1.0}}       # 89s unaccounted
        p.write_text(_json.dumps(bad) + "\n")
        rc = report.main([str(p)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "partition off by" in out
        # an EPOCH-scope violation must mark its own table row too, not
        # just flip the exit code while every printed line looks healthy
        p_ep = tmp_path / "broken_epoch.jsonl"
        ok_run = {"v": 1, "kind": "goodput", "t": 0.0, "scope": "run",
                  "wall_seconds": 10.0, "productive_seconds": 9.0,
                  "badput": {"host_other": 1.0}}
        bad_ep = {**bad, "scope": "epoch", "epoch": 3}
        p_ep.write_text(_json.dumps(ok_run) + "\n"
                        + _json.dumps(bad_ep) + "\n")
        rc = report.main([str(p_ep)])
        out = capsys.readouterr().out
        assert rc == 1
        epoch_row = next(l for l in out.splitlines()
                         if l.strip().startswith("3 "))
        assert "partition off by" in epoch_row
        # but a goodput line that is schema-broken in any OTHER way is
        # still an unreadable log (rc 2), not a renderable one
        p2 = tmp_path / "drifted.jsonl"
        p2.write_text(_json.dumps({"v": 1, "kind": "goodput", "t": 0.0,
                                   "scope": "run"}) + "\n")
        assert report.main([str(p2)]) == 2

    def test_report_rejects_corrupt_log(self, tmp_path, capsys):
        from byol_tpu.observability import report
        p = tmp_path / "bad.jsonl"
        p.write_text("{not json\n")
        assert report.main([str(p)]) == 2

    def test_report_usage(self):
        from byol_tpu.observability import report
        assert report.main([]) == 2

    def test_report_cli_subcommand_dispatch(self, tmp_path):
        """``python -m byol_tpu report`` reaches report.main — the no-live-
        process analysis entry point."""
        import subprocess
        import sys as _sys
        path = _write_log(tmp_path, self._sample_events())
        proc = subprocess.run(
            [_sys.executable, "-m", "byol_tpu", "report", path],
            capture_output=True, text=True, timeout=180)
        assert proc.returncode == 0, proc.stderr
        assert "Goodput waterfall" in proc.stdout


# ---------------------------------------------------------------------------
# scripts/validate_events.py --require (the CI goodput gate)
# ---------------------------------------------------------------------------

class TestValidateEventsRequire:
    def _run(self, *args):
        import pathlib
        import subprocess
        import sys as _sys
        repo = pathlib.Path(__file__).resolve().parent.parent
        return subprocess.run(
            [_sys.executable, str(repo / "scripts" / "validate_events.py"),
             *args], capture_output=True, text=True, timeout=120)

    def test_require_present_passes_absent_fails(self, tmp_path):
        rec = spans_lib.SpanRecorder()
        meter = goodput_lib.GoodputMeter(rec)
        _spin(rec, "train/dispatch", 0.001)
        with_goodput = str(tmp_path / "with.jsonl")
        with RunLog(with_goodput) as log:
            meter.fold(events=log)
        without = str(tmp_path / "without.jsonl")
        with RunLog(without) as log:
            log.emit("run_end")
        ok = self._run("--require", "goodput,span_stats", with_goodput)
        assert ok.returncode == 0, ok.stderr
        bad = self._run("--require", "goodput,span_stats", without)
        assert bad.returncode == 1
        assert "goodput" in bad.stderr
        # without --require the same file validates fine
        assert self._run(without).returncode == 0


# ---------------------------------------------------------------------------
# StepTimer step-time quantiles (meters.py satellite)
# ---------------------------------------------------------------------------

class TestStepTimeQuantiles:
    def test_quantiles_from_ticks(self):
        from byol_tpu.observability import StepTimer
        t = StepTimer(global_batch=8, n_chips=1)
        assert t.epoch_step_quantiles() is None          # no ticks
        stamps = [0.0, 0.1, 0.2, 0.3, 0.8]   # intervals .1,.1,.1,.5
        for s in stamps:
            t._ticks.append(s)
        q = t.epoch_step_quantiles()
        assert q["step_time_p50_s"] == pytest.approx(0.1)
        assert q["step_time_p99_s"] > q["step_time_p50_s"]
        assert q["step_time_max_s"] == pytest.approx(0.5)

    def test_too_few_ticks_is_none_and_reset_clears(self):
        from byol_tpu.observability import StepTimer
        t = StepTimer(global_batch=8, n_chips=1)
        for s in (0.0, 0.1, 0.2):            # 2 intervals: below the floor
            t._ticks.append(s)
        assert t.epoch_step_quantiles() is None
        for s in (0.3, 0.4):
            t._ticks.append(s)
        assert t.epoch_step_quantiles() is not None
        t.reset_ticks()
        assert t.epoch_step_quantiles() is None

    def test_tick_appends_perf_counter(self):
        from byol_tpu.observability import StepTimer
        t = StepTimer(global_batch=8, n_chips=1)
        t.tick()
        t.tick()
        assert len(t._ticks) == 2
        assert t._ticks[0] <= t._ticks[1]
