"""Offline linear-evaluation protocol (training/linear_eval.py) — the BYOL
paper's metric, complementing the reference's concurrent probe
(main.py:249-252; BASELINE.md asks for both)."""
import numpy as np

from byol_tpu.training.linear_eval import (extract_features, linear_eval,
                                           train_linear_probe)


def _blobs(n, d=16, classes=4, seed=0, spread=4.0):
    centers = np.random.RandomState(42).randn(classes, d) * spread
    rng = np.random.RandomState(seed)        # samples vary, centers fixed
    y = rng.randint(0, classes, size=(n,))
    x = centers[y] + rng.randn(n, d)
    return x.astype(np.float32), y.astype(np.int64)


def test_probe_separates_gaussian_blobs():
    x, y = _blobs(800)
    xt, yt = _blobs(200, seed=1)
    w, b = train_linear_probe(x, y, num_classes=4, epochs=10, lr=0.5)
    acc = (np.argmax(xt @ w + b, axis=1) == yt).mean()
    assert acc > 0.95


def test_extract_features_pads_remainder_batch():
    """A final short batch must be padded to the compiled shape and the pad
    rows sliced away — features/labels line up exactly."""
    calls = []

    def apply_fn(x):
        calls.append(x.shape)
        return x.reshape(len(x), -1)[:, :4] * 2.0

    def batches():
        rng = np.random.RandomState(0)
        for n in (8, 8, 3):                       # 19 samples, remainder 3
            yield {"view1": rng.rand(n, 2, 2, 3).astype(np.float32),
                   "view2": None,
                   "label": np.arange(n).astype(np.int32)}

    feats, labels = extract_features(apply_fn, batches())
    assert feats.shape == (19, 4) and labels.shape == (19,)
    assert all(s[0] == 8 for s in calls)          # one static batch shape


def test_linear_eval_end_to_end_on_features():
    """Identity encoder over separable 'images': full pipeline returns high
    top-1 and a populated result."""
    def apply_fn(x):
        return x.reshape(len(x), -1)

    def mk(n, seed):
        x, y = _blobs(n, d=12, classes=3, seed=seed)
        def it():
            for lo in range(0, n, 16):
                xb = x[lo:lo + 16].reshape(-1, 2, 2, 3)
                yield {"view1": xb, "view2": xb,
                       "label": y[lo:lo + 16].astype(np.int32)}
        return it()

    res = linear_eval(apply_fn, mk(600, 0), mk(200, 1), num_classes=3,
                      epochs=10, lr=0.5)
    assert res.top1 > 90.0
    assert res.num_train == 600 and res.num_test == 200


def test_spmd_extraction_matches_host_path(mesh8):
    """The SPMD (pod) extraction path — global batch assembly, replicated
    all-gather, mask-based pad dropping — must return exactly the host
    path's features/labels on a single process."""
    import jax.numpy as jnp

    from byol_tpu.training.linear_eval import extract_features_spmd

    w = np.random.RandomState(3).randn(12, 5).astype(np.float32)

    class Net:
        def apply(self, variables, x, train, mutable):
            return {"representation":
                    x.reshape(len(x), -1) @ variables["params"]["w"]}

    class State:
        params = {"w": jnp.asarray(w)}
        batch_stats = {}

    from byol_tpu.training.linear_eval import encoder_extractor_spmd
    apply_spmd = encoder_extractor_spmd(Net(), State(), mesh8, half=False)

    def batches():
        rng = np.random.RandomState(0)
        for n in (8, 8, 3):                       # remainder batch of 3
            yield {"view1": rng.rand(n, 2, 2, 3).astype(np.float32),
                   "label": np.arange(n).astype(np.int32)}

    feats, labels = extract_features_spmd(apply_spmd, batches(), mesh8,
                                          host_batch=8)
    host_feats, host_labels = extract_features(
        lambda x: x.reshape(len(x), -1) @ w, batches())
    assert feats.shape == (19, 5) and labels.shape == (19,)
    np.testing.assert_allclose(feats, host_feats, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(labels, host_labels)
