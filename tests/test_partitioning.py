"""Tensor-parallel sharding rules + dp x tp / dp x sp train steps on the
virtual CPU mesh — the multi-strategy coverage the reference never had
(SURVEY.md §2.2: DP was its only strategy)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                  TaskConfig, resolve)
from byol_tpu.parallel.mesh import (MODEL_AXIS, MeshSpec, build_mesh,
                                    shard_batch_to_mesh)
from byol_tpu.parallel.partitioning import leaf_pspec, state_shardings
from byol_tpu.training.build import setup_training


def _setup(mesh, *, data, model=1, sequence=1, arch="resnet18", image=16,
           zero1="off", **model_kw):
    cfg = Config(
        task=TaskConfig(task="fake", batch_size=2 * data, epochs=2,
                        image_size_override=image),
        model=ModelConfig(arch=arch, head_latent_size=64, projection_size=32,
                          **model_kw),
        device=DeviceConfig(num_replicas=data, half=False, seed=0,
                            model_parallel=model,
                            sequence_parallel=sequence, zero1=zero1),
    )
    rcfg = resolve(cfg, num_train_samples=8 * data, num_test_samples=2 * data,
                   output_size=10, input_shape=(image, image, 3))
    return cfg, setup_training(rcfg, mesh, jax.random.PRNGKey(0))


def _batch(mesh, b, image=16, seed=0):
    r = np.random.RandomState(seed)
    return shard_batch_to_mesh(
        {"view1": r.rand(b, image, image, 3).astype(np.float32),
         "view2": r.rand(b, image, image, 3).astype(np.float32),
         "label": r.randint(0, 10, (b,)).astype(np.int32)}, mesh)


def test_leaf_pspec_rules():
    class Key:  # stand-in for jax tree path entries
        def __init__(self, key):
            self.key = key

    kernel2d = np.zeros((8, 4))
    bias1d = np.zeros((4,))
    path = (Key("params"), Key("projector"), Key("dense1"), Key("kernel"))
    assert leaf_pspec(path, kernel2d) == P(None, MODEL_AXIS)
    path = (Key("params"), Key("predictor"), Key("dense1"), Key("bias"))
    assert leaf_pspec(path, bias1d) == P(MODEL_AXIS)
    path = (Key("params"), Key("projector"), Key("dense2"), Key("kernel"))
    assert leaf_pspec(path, kernel2d) == P(MODEL_AXIS, None)
    path = (Key("params"), Key("projector"), Key("dense2"), Key("bias"))
    assert leaf_pspec(path, bias1d) == P()
    path = (Key("params"), Key("backbone"), Key("stem_conv"), Key("kernel"))
    assert leaf_pspec(path, kernel2d) == P()
    # BN inside a TP'd head follows the hidden dim
    path = (Key("batch_stats"), Key("predictor"), Key("bn"), Key("mean"))
    assert leaf_pspec(path, bias1d) == P(MODEL_AXIS)


def test_dp_mesh_is_fully_replicated(mesh8):
    shardings = state_shardings({"a": np.zeros((4, 4))}, mesh8)
    assert shardings["a"].spec == P()


def test_zero1_plan_sharding_rules(mesh8):
    """The compile plan layers ZeRO-1 on the base rules: flat (1-D,
    shard-divisible) leaves under opt_state/target_params get P(data);
    params and non-flat leaves keep the base (replicated) layout.  Full
    step-level coverage lives in tests/test_zero1.py — this pins the spec
    assignment logic itself (the old fsdp_leaf_pspec heuristic's successor,
    parallel/compile_plan.py)."""
    from byol_tpu.parallel.compile_plan import build_plan
    from byol_tpu.parallel.mesh import DATA_AXIS

    plan = build_plan(mesh8, zero1=True)
    state = {
        "opt_state": {"mu": np.zeros((64,)),        # flat, divisible
                      "odd": np.zeros((6,)),        # 1-D but not % 8
                      "kernel": np.zeros((8, 8))},  # not flat
        "target_params": {"w": np.zeros((128,))},
        "params": {"w": np.zeros((64,))},           # forward-critical
    }
    sh = plan.state_sharding(state)
    assert sh["opt_state"]["mu"].spec == P(DATA_AXIS)
    assert sh["target_params"]["w"].spec == P(DATA_AXIS)
    assert sh["opt_state"]["odd"].spec == P()
    assert sh["opt_state"]["kernel"].spec == P()
    assert sh["params"]["w"].spec == P()
    # replicated plan: identity with the base rules
    off = build_plan(mesh8, zero1=False).state_sharding(state)
    assert all(s.spec == P() for s in jax.tree_util.tree_leaves(off))


def test_zero1_rejects_tensor_parallel(mesh8):
    """ZeRO-1's flat layout would clobber the TP 'model'-axis opt-state
    sharding — rejected at plan build (and at config resolve())."""
    from byol_tpu.parallel.compile_plan import build_plan
    devices = jax.devices()[:8]
    mesh_tp = build_mesh(MeshSpec(data=4, model=2), devices)
    with pytest.raises(ValueError, match="model_parallel"):
        build_plan(mesh_tp, zero1=True)
    with pytest.raises(ValueError, match="model-parallel"):
        _setup(mesh8, data=4, model=2, zero1="on")


@pytest.mark.slow
def test_tp_train_step_matches_dp():
    """Same seed, same batch: a dp x tp run must produce the same loss as
    pure dp (TP is a layout choice, not a numerics choice)."""
    devices = jax.devices()[:8]
    mesh_dp = build_mesh(MeshSpec(data=8), devices)
    mesh_tp = build_mesh(MeshSpec(data=4, model=2), devices)

    _, (_, state_dp, step_dp, _, _) = _setup(mesh_dp, data=8)
    _, (_, state_tp, step_tp, _, _) = _setup(mesh_tp, data=4, model=2)

    # the TP layout must actually shard the head params
    spec = state_tp.params["projector"]["dense1"]["kernel"].sharding.spec
    assert MODEL_AXIS in spec
    # and the optimizer state inherits the same layout by path
    flat = jax.tree_util.tree_leaves_with_path(state_tp.opt_state)
    tp_opt = [jax.tree_util.keystr(p) for p, leaf in flat
              if getattr(leaf, "ndim", 0) == 2
              and MODEL_AXIS in str(leaf.sharding.spec)]
    assert tp_opt, "no optimizer-state leaf is TP-sharded"

    b_dp = _batch(mesh_dp, 16)
    b_tp = _batch(mesh_tp, 8)
    state_dp, m_dp = step_dp(state_dp, b_dp)
    state_tp, m_tp = step_tp(state_tp, b_tp)
    # batches differ (16 vs 8) so losses differ; what must agree is that
    # both run and stay finite, and that identical inputs agree:
    assert np.isfinite(float(m_dp["loss_mean"]))
    assert np.isfinite(float(m_tp["loss_mean"]))


@pytest.mark.slow
def test_tp_same_batch_matches_dp_numerics():
    """Identical global batch through dp-8 and dp4 x tp2: same loss."""
    devices = jax.devices()[:8]
    mesh_dp = build_mesh(MeshSpec(data=8), devices)
    mesh_tp = build_mesh(MeshSpec(data=4, model=2), devices)
    _, (_, state_dp, step_dp, _, _) = _setup(mesh_dp, data=8)
    _, (_, state_tp, step_tp, _, _) = _setup(mesh_tp, data=4, model=2)
    # resolve() divides the global batch by num_replicas for step math only;
    # the actual arrays are global — feed the same 8-sample batch to both.
    b = _batch(mesh_dp, 8, seed=3)
    b2 = _batch(mesh_tp, 8, seed=3)
    _, m_dp = step_dp(state_dp, b)
    _, m_tp = step_tp(state_tp, b2)
    np.testing.assert_allclose(float(m_dp["loss_mean"]),
                               float(m_tp["loss_mean"]), rtol=2e-4)


def _tiny_vit_arch():
    from byol_tpu.models import registry
    if "vit_sp_test" not in registry.available():
        from byol_tpu.models import vit as vit_lib
        registry.register("vit_sp_test", registry.BackboneSpec(
            factory=lambda dtype=jnp.float32, small_inputs=False, **kw:
                vit_lib.ViT(width=32, depth=1, num_heads=4, patch_size=8,
                            dtype=dtype, **kw),
            feature_dim=32, has_batchnorm=False))
    return "vit_sp_test"


@pytest.mark.slow
def test_dp_sp_tp_combined_mesh_matches_dp():
    """ALL THREE axes at once — data=2 x sequence=2 x model=2: ViT ring
    attention over 'sequence' while the projector/predictor shard over
    'model'.  Loss must match a pure-DP dense-attention run on the same
    global batch (ring-vs-dense and TP-vs-replicated are each
    numerics-preserving; the combination must be too)."""
    arch = _tiny_vit_arch()
    devices = jax.devices()[:8]
    mesh_dp = build_mesh(MeshSpec(data=8), devices)
    mesh_3ax = build_mesh(MeshSpec(data=2, sequence=2, model=2), devices)
    _, (_, state_dp, step_dp, _, _) = _setup(
        mesh_dp, data=8, arch=arch, image=32, attn_impl="dense",
        pooling="gap")
    _, (_, state_3, step_3, eval_3, _) = _setup(
        mesh_3ax, data=2, sequence=2, model=2, arch=arch, image=32,
        attn_impl="ring", pooling="gap")
    # the TP layout must actually shard the head kernels over 'model'
    spec = state_3.params["projector"]["dense1"]["kernel"].sharding.spec
    assert MODEL_AXIS in spec
    b = _batch(mesh_dp, 8, image=32, seed=5)
    b2 = _batch(mesh_3ax, 8, image=32, seed=5)
    _, m_dp = step_dp(state_dp, b)
    state_3, m_3 = step_3(state_3, b2)
    np.testing.assert_allclose(float(m_dp["loss_mean"]),
                               float(m_3["loss_mean"]), rtol=2e-4)
    ev = eval_3(state_3, b2)
    assert np.isfinite(float(ev["loss_mean"]))


@pytest.mark.slow
def test_sp_ring_vit_train_step(mesh_dp_sp):
    """Full BYOL train step with ring attention over the sequence axis."""
    _tiny_vit_arch()
    _, (_, state, train_step, eval_step, _) = _setup(
        mesh_dp_sp, data=4, sequence=2, arch="vit_sp_test", image=32,
        attn_impl="ring", pooling="gap")
    b = _batch(mesh_dp_sp, 8, image=32)
    state, metrics = train_step(state, b)
    assert np.isfinite(float(metrics["loss_mean"]))
    ev = eval_step(state, b)
    assert np.isfinite(float(ev["loss_mean"]))


def test_dcn_multislice_layout_and_validation():
    """Multi-slice mesh (SURVEY §5.8): the data axis is slice-major, each
    slice's block contiguous, and mis-specified topologies fail loudly."""
    from byol_tpu.parallel.mesh import _slice_granules
    devices = jax.devices()[:8]
    g0, g1 = list(devices[:4]), list(devices[4:])
    mesh = build_mesh(MeshSpec(data=8, dcn_data=2), devices,
                      dcn_granules=[g0, g1])
    assert dict(mesh.shape) == {"data": 8, "sequence": 1, "model": 1}
    assert list(mesh.devices[:4].flat) == g0
    assert list(mesh.devices[4:].flat) == g1
    # sequence/model axes never span slices: with data=2 x model=2 over two
    # 2-device granules, each data row's model pair stays inside one granule
    mesh_tp = build_mesh(MeshSpec(data=2, model=2, dcn_data=2), devices[:4],
                         dcn_granules=[devices[:2], devices[2:4]])
    assert list(mesh_tp.devices[0].flat) == list(devices[:2])
    assert list(mesh_tp.devices[1].flat) == list(devices[2:4])

    with pytest.raises(ValueError, match="granules"):
        build_mesh(MeshSpec(data=8, dcn_data=3), devices,
                   dcn_granules=[g0, g1])
    with pytest.raises(ValueError, match="not divisible"):
        build_mesh(MeshSpec(data=6, dcn_data=4), devices[:6],
                   dcn_granules=[[d] for d in devices[:4]])
    with pytest.raises(ValueError, match="granule sizes"):
        build_mesh(MeshSpec(data=8, dcn_data=2), devices,
                   dcn_granules=[devices[:3], devices[3:]])

    # discovery groups by slice_index when present, else process_index,
    # ordered by key so every host builds the identical mesh
    class D:
        def __init__(self, pid, sid=None):
            self.process_index = pid
            if sid is not None:
                self.slice_index = sid
    ds = [D(0, 1), D(0, 0), D(1, 1), D(1, 0)]
    gs = _slice_granules(ds)
    assert [[d.slice_index for d in g] for g in gs] == [[0, 0], [1, 1]]
    ds = [D(1), D(0), D(1), D(0)]
    gs = _slice_granules(ds)
    assert [[d.process_index for d in g] for g in gs] == [[0, 0], [1, 1]]


@pytest.mark.slow
def test_dcn_multislice_matches_dp_numerics():
    """The slice-major layout is a DEVICE-ORDER choice, not a numerics
    choice: the same global batch through a 2-slice mesh (with granule
    order deliberately permuted vs the flat enumeration) must produce the
    same loss as the flat dp-8 mesh."""
    devices = jax.devices()[:8]
    mesh_dp = build_mesh(MeshSpec(data=8), devices)
    mesh_dc = build_mesh(MeshSpec(data=8, dcn_data=2), devices,
                         dcn_granules=[devices[4:], devices[:4]])
    assert ([d.id for d in mesh_dc.devices.flat]
            != [d.id for d in mesh_dp.devices.flat])
    _, (_, state_dp, step_dp, _, _) = _setup(mesh_dp, data=8)
    _, (_, state_dc, step_dc, _, _) = _setup(mesh_dc, data=8)
    b = _batch(mesh_dp, 16, seed=7)
    b2 = _batch(mesh_dc, 16, seed=7)
    _, m_dp = step_dp(state_dp, b)
    _, m_dc = step_dc(state_dc, b2)
    np.testing.assert_allclose(float(m_dp["loss_mean"]),
                               float(m_dc["loss_mean"]), rtol=2e-4)


def test_dcn_granules_must_cover_devices():
    """Size-consistent but overlapping/foreign granules must fail loudly,
    not silently build a mesh with duplicate devices."""
    devices = jax.devices()[:8]
    with pytest.raises(ValueError, match="disjoint"):
        build_mesh(MeshSpec(data=8, dcn_data=2), devices,
                   dcn_granules=[devices[:4], devices[:4]])
