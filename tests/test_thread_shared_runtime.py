"""Runtime complement to graphlint GL114/GL115 (ISSUE 19).

The static rules flag two host-concurrency shapes on the threaded
serving/input surface; these tests prove each flagged exemplar is a REAL
interleaving hazard — and that the lock discipline the rules demand
actually removes it — mirroring the guard_steps/RematTagError precedent
(every static check ships with a runtime demonstration of the bug it
prevents).

Interleavings are CHOREOGRAPHED with events/barriers, not scheduled by
hammering: a single CPython ``f.write``/``+=`` is near-atomic under the
GIL, so a naive two-thread loop can pass for hours while the race stays
latent.  The choreography forces the exact interleaving the OS is
allowed to produce, making both the failure and the fixed assertion
deterministic.
"""
import threading

import pytest

from byol_tpu.observability.events import RunLog, read_events


# ---------------------------------------------------------------- GL114
class UnguardedBatcher:
    """The bad_thread_attr.py exemplar: read-modify-write on a shared
    instance attribute with no lock.  ``before_write`` exposes the window
    between the read and the write so the test can park another thread's
    update inside it."""

    def __init__(self):
        self.pending = 0

    def increment(self, before_write=None):
        v = self.pending
        if before_write is not None:
            before_write()
        self.pending = v + 1


class GuardedBatcher:
    """The ok_thread_attr.py fix: the SAME read-modify-write under one
    common lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pending = 0

    def increment(self, before_write=None):
        with self._lock:
            v = self.pending
            if before_write is not None:
                before_write()
            self.pending = v + 1


class TestGL114LostUpdate:
    def test_unguarded_read_modify_write_loses_an_update(self):
        """Two increments run; one visibly vanishes — the hazard GL114
        flags statically.  The worker's whole update lands inside the
        public caller's read->write window, then the stale write
        clobbers it."""
        b = UnguardedBatcher()

        def interleave():
            t = threading.Thread(target=b.increment)
            t.start()
            t.join()        # worker's increment fully applied... for now

        b.increment(before_write=interleave)
        assert b.pending == 1           # two increments, one survivor

    def test_common_lock_preserves_both_updates(self):
        """Same choreography against the guarded class: the worker's
        increment blocks on the lock until the public caller's window
        closes, so both updates land."""
        b = GuardedBatcher()
        worker = threading.Thread(target=b.increment)

        def spawn_racer():
            worker.start()
            # the worker cannot finish while we hold the lock: its whole
            # increment is parked outside our read->write window
            assert worker.is_alive()

        b.increment(before_write=spawn_racer)
        worker.join()
        assert b.pending == 2


# ---------------------------------------------------------------- GL115
class SplitWriter:
    """File proxy that splits every write in half around a barrier —
    forcing the two-writer byte interleaving the OS is free to produce
    whenever two threads share one stream without a lock."""

    def __init__(self, f, barrier):
        self._f = f
        self._barrier = barrier

    def write(self, s):
        mid = len(s) // 2
        self._f.write(s[:mid])
        if self._barrier is not None:
            self._barrier.wait(timeout=10)
        self._f.write(s[mid:])

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    @property
    def closed(self):
        return self._f.closed


class TestGL115SinkInterleaving:
    def test_unguarded_concurrent_emit_corrupts_the_stream(self, tmp_path):
        """Two threads emit through one RunLog with no lock; the forced
        mid-line handoff interleaves the JSONL bytes and the strict
        reader rejects the file — the hazard GL115 flags statically."""
        path = str(tmp_path / "events.jsonl")
        log = RunLog(path)
        log._f = SplitWriter(log._f, threading.Barrier(2))

        t = threading.Thread(target=log.emit, args=("checkpoint",),
                             kwargs={"epoch": 2})
        t.start()
        log.emit("checkpoint", epoch=1)
        t.join()
        log.close()

        with pytest.raises(ValueError):
            list(read_events(path))

    def test_lock_serialized_emit_survives_the_same_pressure(self,
                                                             tmp_path):
        """The fix the rule message prescribes: one lock around emit.
        The same split-writer perturbation cannot interleave bytes
        because the lock keeps whole emits exclusive."""
        path = str(tmp_path / "events.jsonl")
        log = RunLog(path)
        log._f = SplitWriter(log._f, barrier=None)
        lock = threading.Lock()
        n_each = 5

        def emit_many(base):
            for i in range(n_each):
                with lock:
                    log.emit("checkpoint", epoch=base + i)

        t = threading.Thread(target=emit_many, args=(100,))
        t.start()
        emit_many(0)
        t.join()
        log.close()

        events = list(read_events(path))
        assert len(events) == 2 * n_each
        assert {e["epoch"] for e in events} == (
            set(range(n_each)) | set(range(100, 100 + n_each)))
