"""graphlint self-tests: rule corpus, suppressions, reporters, tree gate.

Three layers:

1. **Rule corpus** (tests/graphlint_fixtures/): one deliberately-bugged
   snippet per rule (must fire) and one near-miss per rule (must stay
   clean) — the false-positive contract that lets the tree gate demand
   ZERO findings rather than "few".
2. **Engine semantics**: suppression comments (justified ones suppress,
   unjustified ones become GL001 findings), syntax errors (GL000), JSON
   reporter shape.
3. **Tree gate**: ``python -m tools.graphlint byol_tpu/`` exits 0 — this
   pytest IS the CI wiring (ROADMAP tier-1 DOTS_PASSED gates the lint);
   scripts/lint.sh shells the same entrypoint for humans.

The linter is pure-AST (never imports the code under analysis), so these
tests run in milliseconds with no jax/TPU initialization.
"""
import json
import pathlib
import subprocess
import sys

import pytest

from tools.graphlint import engine
from tools.graphlint.reporters import json_report
from tools.graphlint.rules import all_rules

FIXTURES = pathlib.Path(__file__).resolve().parent / "graphlint_fixtures"
REPO = pathlib.Path(__file__).resolve().parent.parent

# (rule id, must-fire fixture, must-stay-clean fixture)
RULE_CASES = [
    ("GL101", "bad_host_sync.py", "ok_host_sync.py"),
    # host clocks / span recording under a trace get constant-folded —
    # the flight-recorder (ISSUE 9) shape of the same rule
    ("GL101", "bad_span_clock.py", "ok_span_clock.py"),
    ("GL102", "bad_recompile.py", "ok_recompile.py"),
    ("GL103", "bad_prng.py", "ok_prng.py"),
    ("GL104", "bad_donate.py", "ok_donate.py"),
    ("GL105", "bad_remat_tags.py", "ok_remat_tags.py"),
    ("GL106", "bad_cli_drift.py", "ok_cli_drift.py"),
    ("GL107", "bad_sharding_axes.py", "ok_sharding_axes.py"),
    ("GL108", "bad_collective_vmap.py", "ok_collective_vmap.py"),
    ("GL109", "bad_pallas_interpret.py", "ok_pallas_interpret.py"),
    # lenient json writers emit bare NaN tokens strict parsers reject —
    # the PR 6 run-log lesson as a rule (ISSUE 13 satellite)
    ("GL110", "bad_json_nan.py", "ok_json_nan.py"),
    # host-RNG primitives have no in-kernel lowering; randomness must be
    # drawn outside the pallas_call (ISSUE 14 satellite)
    ("GL111", "bad_pallas_rng.py", "ok_pallas_rng.py"),
    # wave 3 (ISSUE 17): multi-file fixture PACKAGES — cross-module traced
    # scope (jit in one file, host sync in the imported callee), the
    # compile-plan contract, and cross-module donation flow
    ("GL101", "xmod_host_sync_bad", "xmod_host_sync_ok"),
    ("GL112", "gl112_plan_bad", "gl112_plan_ok"),
    ("GL113", "gl113_flow_bad", "gl113_flow_ok"),
    # ISSUE 18: --flat-resident buffers ride the donated state — holding
    # last step's state.flat_shadow on the host after the donating call
    # is the resident shape of use-after-donate, local and cross-module
    ("GL104", "bad_resident_reuse.py", "ok_resident_reuse.py"),
    ("GL113", "gl113_resident_bad", "gl113_resident_ok"),
    # wave 4 (ISSUE 19): value-flow resolution — traced scope through
    # rebound functools.partial chains and through attribute-bound
    # forwarder results (the serving/engine.py:85 spelling); the ok
    # twins pin the unresolvable-receiver and **kwargs stand-downs
    ("GL101", "bad_partial_chain.py", "ok_partial_chain.py"),
    ("GL101", "bad_attr_binding.py", "ok_attr_binding.py"),
    # donated buffers riding tuple/dict literals + tuple-unpack aliasing
    ("GL113", "gl113_container_bad", "gl113_container_ok"),
    # host-concurrency lints over the threaded serving/input surface
    ("GL114", "bad_thread_attr.py", "ok_thread_attr.py"),
    ("GL115", "bad_thread_sink.py", "ok_thread_sink.py"),
]


def run_rule(path, rule_id):
    findings, _, _ = engine.run([str(path)], all_rules(), select={rule_id})
    return [f for f in findings if f.rule == rule_id]


class TestRuleCorpus:
    @pytest.mark.parametrize("rule_id,bad,ok", RULE_CASES)
    def test_bugged_snippet_triggers(self, rule_id, bad, ok):
        findings = run_rule(FIXTURES / bad, rule_id)
        assert findings, f"{rule_id} must fire on {bad}"

    @pytest.mark.parametrize("rule_id,bad,ok", RULE_CASES)
    def test_near_miss_stays_clean(self, rule_id, bad, ok):
        findings = run_rule(FIXTURES / ok, rule_id)
        assert findings == [], (
            f"{rule_id} false positive on {ok}: "
            + "; ".join(f.message for f in findings))

    def test_corpus_reports_all_rule_ids_and_exits_nonzero(self):
        """Acceptance: the bugged corpus trips EVERY rule through the real
        CLI entrypoint, with a non-zero exit."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", str(FIXTURES),
             "--format", "json"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        for rule_id, _, _ in RULE_CASES:
            assert payload["counts_by_rule"].get(rule_id, 0) > 0, (
                f"{rule_id} missing from corpus sweep: "
                f"{payload['counts_by_rule']}")
        assert payload["clean"] is False


class TestPallasLocationArm:
    """GL109's second arm: a pallas_call INSIDE the byol_tpu package but
    outside byol_tpu/ops/ is a finding even with interpret= plumbed (the
    fixture corpus lives outside the package, so it can only exercise the
    interpret arm)."""

    KERNEL = ("import jax\n"
              "from jax.experimental import pallas as pl\n\n\n"
              "def _k(x_ref, o_ref):\n"
              "    o_ref[...] = x_ref[...]\n\n\n"
              "def f(x, interpret=False):\n"
              "    return pl.pallas_call(\n"
              "        _k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
              "        interpret=interpret)(x)\n")

    def test_kernel_outside_ops_fires(self, tmp_path):
        mod = tmp_path / "byol_tpu" / "models" / "sneaky.py"
        mod.parent.mkdir(parents=True)
        mod.write_text(self.KERNEL)
        findings = run_rule(mod, "GL109")
        assert findings and "outside byol_tpu/ops/" in findings[0].message

    def test_kernel_inside_ops_is_clean(self, tmp_path):
        mod = tmp_path / "byol_tpu" / "ops" / "fine.py"
        mod.parent.mkdir(parents=True)
        mod.write_text(self.KERNEL)
        assert run_rule(mod, "GL109") == []

    def test_kwargs_splat_stands_down(self, tmp_path):
        """A call forwarding **kwargs may carry interpret= invisibly —
        the zero-false-positive contract says stand down, not guess."""
        mod = tmp_path / "byol_tpu" / "ops" / "fwd.py"
        mod.parent.mkdir(parents=True)
        mod.write_text(
            "import jax\n"
            "from jax.experimental import pallas as pl\n\n\n"
            "def _k(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...]\n\n\n"
            "def f(x, **kw):\n"
            "    return pl.pallas_call(\n"
            "        _k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
            "        **kw)(x)\n")
        assert run_rule(mod, "GL109") == []


class TestPallasRngPartialBinding:
    """GL111 must resolve the `kernel = functools.partial(fn, ...);
    pl.pallas_call(kernel, ...)` spelling — the shape the FLAGSHIP
    in-tree kernel (ops/fused_augment.py) uses.  Isolated here (no other
    kernel putting the callee in scope), so a regression in the
    partial-binding resolution fails THIS test, not just the corpus."""

    TEMPLATE = ("import functools\n\n"
                "import jax\n"
                "from jax.experimental import pallas as pl\n\n\n"
                "def _k(x_ref, o_ref, *, scale):\n"
                "    o_ref[...] = x_ref[...] * scale{body}\n\n\n"
                "def f(x, interpret=False):\n"
                "    kernel = functools.partial(_k, scale=2.0)\n"
                "    return pl.pallas_call(\n"
                "        kernel,\n"
                "        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
                "        interpret=interpret,\n"
                "    )(x)\n")

    def test_partial_bound_kernel_with_rng_fires(self, tmp_path):
        mod = tmp_path / "partial_rng.py"
        mod.write_text(self.TEMPLATE.format(
            body=" + jax.random.uniform(jax.random.PRNGKey(0),"
                 " x_ref.shape)"))
        findings = run_rule(mod, "GL111")
        assert findings and "Pallas kernel body" in findings[0].message

    def test_partial_bound_kernel_without_rng_is_clean(self, tmp_path):
        mod = tmp_path / "partial_clean.py"
        mod.write_text(self.TEMPLATE.format(body=""))
        assert run_rule(mod, "GL111") == []


class TestEngineSemantics:
    def test_justified_suppression_suppresses(self, tmp_path):
        src = ("import jax\n\n\ndef f(key):\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    b = jax.random.normal(key, (2,))"
               "  # graphlint: disable=GL103 -- fixture: reuse is the test\n"
               "    return a + b\n")
        p = tmp_path / "sup.py"
        p.write_text(src)
        findings, _, _ = engine.run([str(p)], all_rules())
        assert findings == []

    def test_unjustified_suppression_is_gl001(self, tmp_path):
        src = ("import jax\n\n\ndef f(key):\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    b = jax.random.normal(key, (2,))"
               "  # graphlint: disable=GL103\n"
               "    return a + b\n")
        p = tmp_path / "sup.py"
        p.write_text(src)
        findings, _, _ = engine.run([str(p)], all_rules())
        assert [f.rule for f in findings] == [engine.UNJUSTIFIED]

    def test_suppression_on_comment_line_covers_next_line(self, tmp_path):
        src = ("import jax\n\n\ndef f(key):\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    # graphlint: disable=GL103 -- fixture: suppress-above\n"
               "    b = jax.random.normal(key, (2,))\n"
               "    return a + b\n")
        p = tmp_path / "sup.py"
        p.write_text(src)
        findings, _, _ = engine.run([str(p)], all_rules())
        assert findings == []

    def test_suppression_covers_only_named_rule(self, tmp_path):
        src = ("import jax\n\n\ndef f(key):\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    b = jax.random.normal(key, (2,))"
               "  # graphlint: disable=GL101 -- wrong rule named\n"
               "    return a + b\n")
        p = tmp_path / "sup.py"
        p.write_text(src)
        findings, _, _ = engine.run([str(p)], all_rules())
        assert "GL103" in {f.rule for f in findings}

    def test_suppression_text_inside_string_is_inert(self, tmp_path):
        """Suppression-like text in a docstring/string (a usage example)
        must neither emit GL001 nor suppress real findings — comments are
        found via tokenize, not a regex over raw source lines."""
        src = ('"""Example:\n'
               "    val = float(x)  # graphlint: disable=GL101\n"
               '"""\n'
               "import jax\n\n\ndef f(key):\n"
               "    msg = 'x  # graphlint: disable=GL103 -- not a comment'\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    b = jax.random.normal(key, (2,))\n"
               "    return a + b, msg\n")
        p = tmp_path / "doc.py"
        p.write_text(src)
        findings, _, _ = engine.run([str(p)], all_rules())
        rules = [f.rule for f in findings]
        assert engine.UNJUSTIFIED not in rules     # docstring: no phantom
        assert "GL103" in rules                    # string didn't suppress

    def test_remat_rule_ignores_same_named_class_elsewhere(self, tmp_path):
        """A class sharing its NAME with a remat-wrapped class in another
        module is not judged — wrap sites bind to the defining module via
        import resolution, not bare-name union across the lint root."""
        (tmp_path / "a.py").write_text(
            "import flax.linen as nn\n"
            "import jax\n"
            "from jax.ad_checkpoint import checkpoint_name\n\n"
            "POL = jax.checkpoint_policies.save_only_these_names('t_out')\n\n\n"
            "class Block(nn.Module):\n"
            "    def __call__(self, x):\n"
            "        return checkpoint_name(x, 't_out')\n\n\n"
            "wrapped = nn.remat(Block, policy=POL)\n")
        (tmp_path / "b.py").write_text(
            "class Block:\n"                 # unrelated, never wrapped
            "    def render(self):\n"
            "        return 'html'\n")
        findings, _, _ = engine.run(
            [str(tmp_path / "a.py"), str(tmp_path / "b.py")],
            all_rules(), select={"GL105"})
        assert findings == [], [f.message for f in findings]

    def test_syntax_error_is_gl000(self, tmp_path):
        p = tmp_path / "broken.py"
        p.write_text("def f(:\n")
        findings, _, _ = engine.run([str(p)], all_rules())
        assert [f.rule for f in findings] == [engine.PARSE_ERROR]

    def test_json_reporter_shape(self, tmp_path):
        p = tmp_path / "clean.py"
        p.write_text("x = 1\n")
        findings, files, stats = engine.run([str(p)], all_rules())
        payload = json.loads(json_report(findings, files, [str(p)], stats))
        assert payload["clean"] is True
        assert payload["files_scanned"] == 1
        assert payload["findings"] == []
        assert payload["schema_version"] == 4
        assert payload["suppressions_by_rule"] == {}
        # schema v3: per-rule wall time (incl. the shared whole-program
        # pass under its own key) + resolution counters
        timing = payload["timing"]
        assert engine.PROJECT_PASS in timing["rule_wall_seconds"]
        assert all(sec >= 0 for sec in timing["rule_wall_seconds"].values())
        assert timing["total_seconds"] >= 0
        res = payload["resolution"]
        for field in ("files_indexed", "modules_indexed",
                      "symbols_resolved", "symbols_unresolved",
                      "cross_module_traced"):
            assert isinstance(res[field], int)
        # schema v4: the value-flow prepass is timed under its own key
        # and its resolution counters land in a "flow" section
        assert engine.FLOW_PASS in timing["rule_wall_seconds"]
        fl = payload["flow"]
        for field in ("partial_chains_resolved",
                      "attribute_bindings_resolved", "forwarded_traced",
                      "thread_classes_analyzed"):
            assert isinstance(fl[field], int)

    def test_out_json_with_text_stdout(self, tmp_path):
        """One run, both reports: text on stdout, JSON at --out *.json —
        the scripts/lint.sh evidence path."""
        p = tmp_path / "clean.py"
        p.write_text("x = 1\n")
        out = tmp_path / "report.json"
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", str(p),
             "--out", str(out)],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        assert "finding(s) in 1 file(s) scanned" in proc.stdout  # text
        payload = json.loads(out.read_text())                    # json
        assert payload["clean"] is True


class TestWholeProgram:
    """Wave 3 (ISSUE 17) acceptance: cross-module traced scope and the
    compile-plan contract, asserted at the finding level (the corpus
    tests only assert fire/stay-silent)."""

    def test_gl101_fires_at_definition_with_jit_site_named(self):
        """Module A jits a function imported from module B: GL101 must
        land in B (impl.py) — NOT in A — and carry A's jit site."""
        findings = run_rule(FIXTURES / "xmod_host_sync_bad", "GL101")
        assert findings, "cross-module traced scope did not propagate"
        assert all(f.path.endswith("impl.py") for f in findings), (
            [f.path for f in findings])
        assert any("jax.jit at" in f.message
                   and "jit_site.py:8" in f.message for f in findings), (
            [f.message for f in findings])

    def test_gl101_transitive_callee_is_traced(self):
        """The traced def's module-local callee (_metrics) is in traced
        scope too — the closure, not just the entry def."""
        findings = run_rule(FIXTURES / "xmod_host_sync_bad", "GL101")
        lines = {f.line for f in findings}
        assert 12 in lines, (  # np.mean inside _metrics
            f"no finding inside the transitive callee: {sorted(lines)}")

    def test_gl111_resolves_imported_kernel(self, tmp_path):
        """A pallas_call staging a kernel imported from another module
        flags the RNG at the kernel's definition site, naming the
        staging site."""
        (tmp_path / "kern.py").write_text(
            "import jax\n\n\n"
            "def noisy_kernel(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...] + jax.random.uniform(\n"
            "        jax.random.PRNGKey(0), x_ref.shape)\n")
        (tmp_path / "call.py").write_text(
            "import jax\n"
            "from jax.experimental import pallas as pl\n\n"
            "from kern import noisy_kernel\n\n\n"
            "def f(x):\n"
            "    return pl.pallas_call(\n"
            "        noisy_kernel,\n"
            "        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
            "    )(x)\n")
        findings = run_rule(tmp_path, "GL111")
        assert findings, "imported kernel did not resolve"
        assert all(f.path.endswith("kern.py") for f in findings)
        assert any("kernel staged via pallas_call" in f.message
                   for f in findings)

    def test_gl112_all_arms_fire_with_distinct_codes(self):
        findings = run_rule(FIXTURES / "gl112_plan_bad", "GL112")
        tags = {m for f in findings
                for m in ("GL112-bypass", "GL112-mismatch",
                          "GL112-donate-undeclared", "GL112-unused-entry")
                if f"[{m}]" in f.message}
        assert tags == {"GL112-bypass", "GL112-mismatch",
                        "GL112-donate-undeclared", "GL112-unused-entry"}, (
            f"arms missing: {[f.message for f in findings]}")

    def test_gl112_site_mismatch_and_undeclared_donation(self):
        """The acceptance pair: a per-site donation kwarg disagreeing
        with the plan, and a donated-but-undeclared argument — both at
        call sites OUTSIDE the plan module."""
        findings = run_rule(FIXTURES / "gl112_plan_bad", "GL112")
        caller = [f for f in findings if f.path.endswith("caller.py")]
        assert any("[GL112-mismatch]" in f.message for f in caller)
        assert any("[GL112-donate-undeclared]" in f.message for f in caller)

    def test_gl112_unused_entry_stands_down_without_call_sites(self):
        """Linting the plan file ALONE: no builder call sites exist in
        the selection, so unused-entry is a property of the selection,
        not the program — it must stand down."""
        findings = run_rule(
            FIXTURES / "gl112_plan_bad" / "compile_plan.py", "GL112")
        assert not any("[GL112-unused-entry]" in f.message
                       for f in findings), [f.message for f in findings]

    def test_gl113_cross_module_donor_names_binding_site(self):
        """driver.py imports the donor from wiring.py: the loop reuse
        fires in driver.py with the wiring.py binding line named."""
        findings = run_rule(FIXTURES / "gl113_flow_bad", "GL113")
        driver = [f for f in findings if f.path.endswith("driver.py")]
        assert driver, [f.path for f in findings]
        assert any("wiring.py:12" in f.message for f in driver), (
            [f.message for f in driver])
        assert any("'train_step'" in f.message for f in driver)

    def test_gl113_local_reuse_fires(self):
        findings = run_rule(FIXTURES / "gl113_flow_bad", "GL113")
        assert any(f.path.endswith("wiring.py") for f in findings)

    def test_gl113_needs_no_gl104_donor(self):
        """GL104 stays silent on the plan-builder donors (no literal
        jax.jit assignment in scope) — the gap GL113 exists to close."""
        findings = run_rule(FIXTURES / "gl113_flow_bad", "GL104")
        assert findings == [], [f.message for f in findings]

    def test_ok_fixture_plans_clean_under_full_rule_set(self):
        """GL107's plan-module exemption is structural (any compile_plan.py
        with a static DONATE — GL112's plan_registry), so a fixture plan
        is never told to move its shardings into the canonical plan: the
        ok packages must be clean under EVERY rule, not just their own."""
        for pkg in ("gl112_plan_ok", "gl113_flow_ok", "xmod_host_sync_ok"):
            findings, _, _ = engine.run([str(FIXTURES / pkg)], all_rules())
            assert findings == [], (pkg, [f.message for f in findings])

    def test_unresolvable_import_stands_down(self, tmp_path):
        """jitting a function imported from OUTSIDE the lint root must
        not guess: no cross-module findings, counted as unresolved."""
        (tmp_path / "site.py").write_text(
            "import jax\n"
            "from somewhere_else import impl_fn\n\n"
            "fast = jax.jit(impl_fn)\n")
        findings, _, stats = engine.run([str(tmp_path)], all_rules(),
                                        select={"GL101"})
        assert findings == []
        assert stats.resolution["cross_module_traced"] == 0

    def test_text_report_prints_slowest_rules(self):
        """scripts/lint.sh surfaces the slowest rules from this footer —
        the guard that keeps the whole-program pass honest about cost."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint",
             "tools/graphlint/astutil.py"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "slowest:" in proc.stdout
        assert engine.PROJECT_PASS in proc.stdout
        assert "resolution:" in proc.stdout


class TestTrendAlarm:
    """ROADMAP rule-wave-2 (d): the suppression-trend ratchet.  A rule's
    suppression count growing vs the committed evidence baseline fails the
    run even when every finding is suppressed (= lint-clean)."""

    SUPPRESSED = ("import jax\n\n\ndef f(key):\n"
                  "    a = jax.random.uniform(key)\n"
                  "    # graphlint: disable=GL103 -- fixture: deliberate\n"
                  "    b = jax.random.uniform(key)\n"
                  "    return a + b\n")

    def _baseline(self, tmp_path, suppressions):
        base = tmp_path / "baseline.json"
        base.write_text(json.dumps({"schema_version": 2,
                                    "suppressions_by_rule": suppressions}))
        return base

    def _run(self, tmp_path, baseline, out=None):
        cmd = [sys.executable, "-m", "tools.graphlint",
               str(tmp_path / "code.py"), "--trend-baseline", str(baseline)]
        if out is not None:
            cmd += ["--out", str(out)]
        return subprocess.run(cmd, capture_output=True, text=True, cwd=REPO)

    def test_grown_suppression_count_fails(self, tmp_path):
        (tmp_path / "code.py").write_text(self.SUPPRESSED)
        base = self._baseline(tmp_path, {"GL103": 0})
        out = tmp_path / "report.json"
        proc = self._run(tmp_path, base, out)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "trend alarm" in proc.stderr and "GL103: 0 -> 1" in proc.stderr
        # an alarmed run must not rewrite the evidence (the ratchet would
        # vanish on the next run)
        assert not out.exists()

    def test_stable_count_passes_and_writes_evidence(self, tmp_path):
        (tmp_path / "code.py").write_text(self.SUPPRESSED)
        base = self._baseline(tmp_path, {"GL103": 1})
        out = tmp_path / "report.json"
        proc = self._run(tmp_path, base, out)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(out.read_text())
        assert payload["suppressions_by_rule"] == {"GL103": 1}

    def test_shrunk_count_passes(self, tmp_path):
        (tmp_path / "code.py").write_text("x = 1\n")
        base = self._baseline(tmp_path, {"GL103": 3})
        proc = self._run(tmp_path, base)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_missing_baseline_skips_with_note(self, tmp_path):
        (tmp_path / "code.py").write_text(self.SUPPRESSED)
        out = tmp_path / "report.json"
        proc = self._run(tmp_path, tmp_path / "nope.json", out)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "skipping the suppression-trend check" in proc.stderr
        assert out.exists()   # first run seeds the baseline

    def test_corrupt_baseline_is_usage_error(self, tmp_path):
        (tmp_path / "code.py").write_text("x = 1\n")
        bad = tmp_path / "baseline.json"
        bad.write_text("{not json")
        proc = self._run(tmp_path, bad)
        assert proc.returncode == 2

    def test_lint_sh_default_run_ratchets(self):
        """The shipped wiring: scripts/lint.sh passes the committed
        evidence file as the baseline (inspect, don't execute — the real
        run rewrites the committed evidence)."""
        text = (REPO / "scripts" / "lint.sh").read_text()
        assert "--trend-baseline evidence/graphlint.json" in text


class TestValueFlow:
    """Wave-4 pins (ISSUE 19): the flow layer's resolution lands findings
    at true definition sites and names the staging/binding site."""

    def test_attr_binding_site_named(self):
        """Acceptance: the serving/engine.py:85 spelling — an entry point
        bound as self._jitted = plan.jit_embed(fn) — is analyzed as
        traced, flagged at fn's DEFINITION with the binding site named."""
        findings = run_rule(FIXTURES / "bad_attr_binding.py", "GL101")
        assert len(findings) == 1
        assert findings[0].line == 12            # the def, not the call
        assert "jit_embed" in findings[0].message
        assert "bad_attr_binding.py:23" in findings[0].message

    def test_partial_chain_fires_at_definition(self):
        findings = run_rule(FIXTURES / "bad_partial_chain.py", "GL101")
        assert [f.line for f in findings] == [12]

    def test_gl113_container_arms_all_fire(self):
        """Tuple-literal slot, dict-literal slot, and tuple-unpack alias
        each produce exactly one finding."""
        findings = run_rule(FIXTURES / "gl113_container_bad", "GL113")
        assert len(findings) == 3
        msgs = " | ".join(f.message for f in findings)
        assert "bundle[0]" in msgs
        assert "ckpt['state']" in msgs

    def test_gl114_names_both_sites_and_spawn(self):
        findings = run_rule(FIXTURES / "bad_thread_attr.py", "GL114")
        assert len(findings) == 1
        m = findings[0].message
        assert "'_run'" in m and "'submit'" in m and "spawned" in m

    def test_gl115_flags_each_sink_once(self):
        findings = run_rule(FIXTURES / "bad_thread_sink.py", "GL115")
        assert len(findings) == 2                # RunLog + open()-file
        msgs = " | ".join(f.message for f in findings)
        assert "RunLog" in msgs and "open()-file" in msgs


class TestTreeGate:
    def test_shipped_tree_lints_clean(self):
        """Acceptance: the shipped byol_tpu/ tree exits 0 through the SAME
        entrypoint scripts/lint.sh runs — tier-1 DOTS_PASSED gates the
        lint."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", "byol_tpu/"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, (
            "graphlint found new issues in byol_tpu/:\n" + proc.stdout)

    def test_linter_lints_itself_clean(self):
        """Self-hosting (ISSUE 17): tools/graphlint/ passes its own sweep
        — scripts/lint.sh runs both roots by default."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", "tools/graphlint/"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, (
            "graphlint found issues in itself:\n" + proc.stdout)

    def test_list_rules_catalog(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", "--list-rules", "."],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        for rule_id in ("GL101", "GL102", "GL103", "GL104", "GL105",
                        "GL106", "GL001", "GL000"):
            assert rule_id in proc.stdout

    def test_driver_surface_lints_clean(self):
        """Wave-4 widened sweep (ISSUE 19): the driver/tooling surface —
        scripts/*.py, bench.py, train.py — exits 0 through the same
        entrypoint scripts/lint.sh now covers."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", "scripts/",
             "bench.py", "train.py"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, (
            "graphlint found issues in the driver surface:\n"
            + proc.stdout)

    def test_full_widened_sweep_wall_budget(self):
        """The full widened sweep (every root scripts/lint.sh runs,
        value-flow prepass included) stays under the 60s wall budget."""
        findings, _, stats = engine.run(
            [str(REPO / p) for p in ("byol_tpu", "tools/graphlint",
                                     "scripts", "bench.py", "train.py")],
            all_rules())
        assert findings == [], [f.message for f in findings]
        assert stats.total_seconds <= 60.0, stats.rule_seconds
        assert engine.FLOW_PASS in stats.rule_seconds

    def test_missing_path_exits_2(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint",
             "no/such/path_xyz.py"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 2
