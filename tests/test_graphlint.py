"""graphlint self-tests: rule corpus, suppressions, reporters, tree gate.

Three layers:

1. **Rule corpus** (tests/graphlint_fixtures/): one deliberately-bugged
   snippet per rule (must fire) and one near-miss per rule (must stay
   clean) — the false-positive contract that lets the tree gate demand
   ZERO findings rather than "few".
2. **Engine semantics**: suppression comments (justified ones suppress,
   unjustified ones become GL001 findings), syntax errors (GL000), JSON
   reporter shape.
3. **Tree gate**: ``python -m tools.graphlint byol_tpu/`` exits 0 — this
   pytest IS the CI wiring (ROADMAP tier-1 DOTS_PASSED gates the lint);
   scripts/lint.sh shells the same entrypoint for humans.

The linter is pure-AST (never imports the code under analysis), so these
tests run in milliseconds with no jax/TPU initialization.
"""
import json
import pathlib
import subprocess
import sys

import pytest

from tools.graphlint import engine
from tools.graphlint.reporters import json_report
from tools.graphlint.rules import all_rules

FIXTURES = pathlib.Path(__file__).resolve().parent / "graphlint_fixtures"
REPO = pathlib.Path(__file__).resolve().parent.parent

# (rule id, must-fire fixture, must-stay-clean fixture)
RULE_CASES = [
    ("GL101", "bad_host_sync.py", "ok_host_sync.py"),
    # host clocks / span recording under a trace get constant-folded —
    # the flight-recorder (ISSUE 9) shape of the same rule
    ("GL101", "bad_span_clock.py", "ok_span_clock.py"),
    ("GL102", "bad_recompile.py", "ok_recompile.py"),
    ("GL103", "bad_prng.py", "ok_prng.py"),
    ("GL104", "bad_donate.py", "ok_donate.py"),
    ("GL105", "bad_remat_tags.py", "ok_remat_tags.py"),
    ("GL106", "bad_cli_drift.py", "ok_cli_drift.py"),
    ("GL107", "bad_sharding_axes.py", "ok_sharding_axes.py"),
    ("GL108", "bad_collective_vmap.py", "ok_collective_vmap.py"),
    ("GL109", "bad_pallas_interpret.py", "ok_pallas_interpret.py"),
    # lenient json writers emit bare NaN tokens strict parsers reject —
    # the PR 6 run-log lesson as a rule (ISSUE 13 satellite)
    ("GL110", "bad_json_nan.py", "ok_json_nan.py"),
    # host-RNG primitives have no in-kernel lowering; randomness must be
    # drawn outside the pallas_call (ISSUE 14 satellite)
    ("GL111", "bad_pallas_rng.py", "ok_pallas_rng.py"),
]


def run_rule(path, rule_id):
    findings, _ = engine.run([str(path)], all_rules(), select={rule_id})
    return [f for f in findings if f.rule == rule_id]


class TestRuleCorpus:
    @pytest.mark.parametrize("rule_id,bad,ok", RULE_CASES)
    def test_bugged_snippet_triggers(self, rule_id, bad, ok):
        findings = run_rule(FIXTURES / bad, rule_id)
        assert findings, f"{rule_id} must fire on {bad}"

    @pytest.mark.parametrize("rule_id,bad,ok", RULE_CASES)
    def test_near_miss_stays_clean(self, rule_id, bad, ok):
        findings = run_rule(FIXTURES / ok, rule_id)
        assert findings == [], (
            f"{rule_id} false positive on {ok}: "
            + "; ".join(f.message for f in findings))

    def test_corpus_reports_all_rule_ids_and_exits_nonzero(self):
        """Acceptance: the bugged corpus trips EVERY rule through the real
        CLI entrypoint, with a non-zero exit."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", str(FIXTURES),
             "--format", "json"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        for rule_id, _, _ in RULE_CASES:
            assert payload["counts_by_rule"].get(rule_id, 0) > 0, (
                f"{rule_id} missing from corpus sweep: "
                f"{payload['counts_by_rule']}")
        assert payload["clean"] is False


class TestPallasLocationArm:
    """GL109's second arm: a pallas_call INSIDE the byol_tpu package but
    outside byol_tpu/ops/ is a finding even with interpret= plumbed (the
    fixture corpus lives outside the package, so it can only exercise the
    interpret arm)."""

    KERNEL = ("import jax\n"
              "from jax.experimental import pallas as pl\n\n\n"
              "def _k(x_ref, o_ref):\n"
              "    o_ref[...] = x_ref[...]\n\n\n"
              "def f(x, interpret=False):\n"
              "    return pl.pallas_call(\n"
              "        _k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
              "        interpret=interpret)(x)\n")

    def test_kernel_outside_ops_fires(self, tmp_path):
        mod = tmp_path / "byol_tpu" / "models" / "sneaky.py"
        mod.parent.mkdir(parents=True)
        mod.write_text(self.KERNEL)
        findings = run_rule(mod, "GL109")
        assert findings and "outside byol_tpu/ops/" in findings[0].message

    def test_kernel_inside_ops_is_clean(self, tmp_path):
        mod = tmp_path / "byol_tpu" / "ops" / "fine.py"
        mod.parent.mkdir(parents=True)
        mod.write_text(self.KERNEL)
        assert run_rule(mod, "GL109") == []

    def test_kwargs_splat_stands_down(self, tmp_path):
        """A call forwarding **kwargs may carry interpret= invisibly —
        the zero-false-positive contract says stand down, not guess."""
        mod = tmp_path / "byol_tpu" / "ops" / "fwd.py"
        mod.parent.mkdir(parents=True)
        mod.write_text(
            "import jax\n"
            "from jax.experimental import pallas as pl\n\n\n"
            "def _k(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...]\n\n\n"
            "def f(x, **kw):\n"
            "    return pl.pallas_call(\n"
            "        _k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
            "        **kw)(x)\n")
        assert run_rule(mod, "GL109") == []


class TestPallasRngPartialBinding:
    """GL111 must resolve the `kernel = functools.partial(fn, ...);
    pl.pallas_call(kernel, ...)` spelling — the shape the FLAGSHIP
    in-tree kernel (ops/fused_augment.py) uses.  Isolated here (no other
    kernel putting the callee in scope), so a regression in the
    partial-binding resolution fails THIS test, not just the corpus."""

    TEMPLATE = ("import functools\n\n"
                "import jax\n"
                "from jax.experimental import pallas as pl\n\n\n"
                "def _k(x_ref, o_ref, *, scale):\n"
                "    o_ref[...] = x_ref[...] * scale{body}\n\n\n"
                "def f(x, interpret=False):\n"
                "    kernel = functools.partial(_k, scale=2.0)\n"
                "    return pl.pallas_call(\n"
                "        kernel,\n"
                "        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),\n"
                "        interpret=interpret,\n"
                "    )(x)\n")

    def test_partial_bound_kernel_with_rng_fires(self, tmp_path):
        mod = tmp_path / "partial_rng.py"
        mod.write_text(self.TEMPLATE.format(
            body=" + jax.random.uniform(jax.random.PRNGKey(0),"
                 " x_ref.shape)"))
        findings = run_rule(mod, "GL111")
        assert findings and "Pallas kernel body" in findings[0].message

    def test_partial_bound_kernel_without_rng_is_clean(self, tmp_path):
        mod = tmp_path / "partial_clean.py"
        mod.write_text(self.TEMPLATE.format(body=""))
        assert run_rule(mod, "GL111") == []


class TestEngineSemantics:
    def test_justified_suppression_suppresses(self, tmp_path):
        src = ("import jax\n\n\ndef f(key):\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    b = jax.random.normal(key, (2,))"
               "  # graphlint: disable=GL103 -- fixture: reuse is the test\n"
               "    return a + b\n")
        p = tmp_path / "sup.py"
        p.write_text(src)
        findings, _ = engine.run([str(p)], all_rules())
        assert findings == []

    def test_unjustified_suppression_is_gl001(self, tmp_path):
        src = ("import jax\n\n\ndef f(key):\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    b = jax.random.normal(key, (2,))"
               "  # graphlint: disable=GL103\n"
               "    return a + b\n")
        p = tmp_path / "sup.py"
        p.write_text(src)
        findings, _ = engine.run([str(p)], all_rules())
        assert [f.rule for f in findings] == [engine.UNJUSTIFIED]

    def test_suppression_on_comment_line_covers_next_line(self, tmp_path):
        src = ("import jax\n\n\ndef f(key):\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    # graphlint: disable=GL103 -- fixture: suppress-above\n"
               "    b = jax.random.normal(key, (2,))\n"
               "    return a + b\n")
        p = tmp_path / "sup.py"
        p.write_text(src)
        findings, _ = engine.run([str(p)], all_rules())
        assert findings == []

    def test_suppression_covers_only_named_rule(self, tmp_path):
        src = ("import jax\n\n\ndef f(key):\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    b = jax.random.normal(key, (2,))"
               "  # graphlint: disable=GL101 -- wrong rule named\n"
               "    return a + b\n")
        p = tmp_path / "sup.py"
        p.write_text(src)
        findings, _ = engine.run([str(p)], all_rules())
        assert "GL103" in {f.rule for f in findings}

    def test_suppression_text_inside_string_is_inert(self, tmp_path):
        """Suppression-like text in a docstring/string (a usage example)
        must neither emit GL001 nor suppress real findings — comments are
        found via tokenize, not a regex over raw source lines."""
        src = ('"""Example:\n'
               "    val = float(x)  # graphlint: disable=GL101\n"
               '"""\n'
               "import jax\n\n\ndef f(key):\n"
               "    msg = 'x  # graphlint: disable=GL103 -- not a comment'\n"
               "    a = jax.random.normal(key, (2,))\n"
               "    b = jax.random.normal(key, (2,))\n"
               "    return a + b, msg\n")
        p = tmp_path / "doc.py"
        p.write_text(src)
        findings, _ = engine.run([str(p)], all_rules())
        rules = [f.rule for f in findings]
        assert engine.UNJUSTIFIED not in rules     # docstring: no phantom
        assert "GL103" in rules                    # string didn't suppress

    def test_remat_rule_ignores_same_named_class_elsewhere(self, tmp_path):
        """A class sharing its NAME with a remat-wrapped class in another
        module is not judged — wrap sites bind to the defining module via
        import resolution, not bare-name union across the lint root."""
        (tmp_path / "a.py").write_text(
            "import flax.linen as nn\n"
            "import jax\n"
            "from jax.ad_checkpoint import checkpoint_name\n\n"
            "POL = jax.checkpoint_policies.save_only_these_names('t_out')\n\n\n"
            "class Block(nn.Module):\n"
            "    def __call__(self, x):\n"
            "        return checkpoint_name(x, 't_out')\n\n\n"
            "wrapped = nn.remat(Block, policy=POL)\n")
        (tmp_path / "b.py").write_text(
            "class Block:\n"                 # unrelated, never wrapped
            "    def render(self):\n"
            "        return 'html'\n")
        findings, _ = engine.run(
            [str(tmp_path / "a.py"), str(tmp_path / "b.py")],
            all_rules(), select={"GL105"})
        assert findings == [], [f.message for f in findings]

    def test_syntax_error_is_gl000(self, tmp_path):
        p = tmp_path / "broken.py"
        p.write_text("def f(:\n")
        findings, _ = engine.run([str(p)], all_rules())
        assert [f.rule for f in findings] == [engine.PARSE_ERROR]

    def test_json_reporter_shape(self, tmp_path):
        p = tmp_path / "clean.py"
        p.write_text("x = 1\n")
        findings, files = engine.run([str(p)], all_rules())
        payload = json.loads(json_report(findings, files, [str(p)]))
        assert payload["clean"] is True
        assert payload["files_scanned"] == 1
        assert payload["findings"] == []
        assert payload["schema_version"] == 2
        assert payload["suppressions_by_rule"] == {}

    def test_out_json_with_text_stdout(self, tmp_path):
        """One run, both reports: text on stdout, JSON at --out *.json —
        the scripts/lint.sh evidence path."""
        p = tmp_path / "clean.py"
        p.write_text("x = 1\n")
        out = tmp_path / "report.json"
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", str(p),
             "--out", str(out)],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        assert "finding(s) in 1 file(s) scanned" in proc.stdout  # text
        payload = json.loads(out.read_text())                    # json
        assert payload["clean"] is True


class TestTrendAlarm:
    """ROADMAP rule-wave-2 (d): the suppression-trend ratchet.  A rule's
    suppression count growing vs the committed evidence baseline fails the
    run even when every finding is suppressed (= lint-clean)."""

    SUPPRESSED = ("import jax\n\n\ndef f(key):\n"
                  "    a = jax.random.uniform(key)\n"
                  "    # graphlint: disable=GL103 -- fixture: deliberate\n"
                  "    b = jax.random.uniform(key)\n"
                  "    return a + b\n")

    def _baseline(self, tmp_path, suppressions):
        base = tmp_path / "baseline.json"
        base.write_text(json.dumps({"schema_version": 2,
                                    "suppressions_by_rule": suppressions}))
        return base

    def _run(self, tmp_path, baseline, out=None):
        cmd = [sys.executable, "-m", "tools.graphlint",
               str(tmp_path / "code.py"), "--trend-baseline", str(baseline)]
        if out is not None:
            cmd += ["--out", str(out)]
        return subprocess.run(cmd, capture_output=True, text=True, cwd=REPO)

    def test_grown_suppression_count_fails(self, tmp_path):
        (tmp_path / "code.py").write_text(self.SUPPRESSED)
        base = self._baseline(tmp_path, {"GL103": 0})
        out = tmp_path / "report.json"
        proc = self._run(tmp_path, base, out)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "trend alarm" in proc.stderr and "GL103: 0 -> 1" in proc.stderr
        # an alarmed run must not rewrite the evidence (the ratchet would
        # vanish on the next run)
        assert not out.exists()

    def test_stable_count_passes_and_writes_evidence(self, tmp_path):
        (tmp_path / "code.py").write_text(self.SUPPRESSED)
        base = self._baseline(tmp_path, {"GL103": 1})
        out = tmp_path / "report.json"
        proc = self._run(tmp_path, base, out)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(out.read_text())
        assert payload["suppressions_by_rule"] == {"GL103": 1}

    def test_shrunk_count_passes(self, tmp_path):
        (tmp_path / "code.py").write_text("x = 1\n")
        base = self._baseline(tmp_path, {"GL103": 3})
        proc = self._run(tmp_path, base)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_missing_baseline_skips_with_note(self, tmp_path):
        (tmp_path / "code.py").write_text(self.SUPPRESSED)
        out = tmp_path / "report.json"
        proc = self._run(tmp_path, tmp_path / "nope.json", out)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "skipping the suppression-trend check" in proc.stderr
        assert out.exists()   # first run seeds the baseline

    def test_corrupt_baseline_is_usage_error(self, tmp_path):
        (tmp_path / "code.py").write_text("x = 1\n")
        bad = tmp_path / "baseline.json"
        bad.write_text("{not json")
        proc = self._run(tmp_path, bad)
        assert proc.returncode == 2

    def test_lint_sh_default_run_ratchets(self):
        """The shipped wiring: scripts/lint.sh passes the committed
        evidence file as the baseline (inspect, don't execute — the real
        run rewrites the committed evidence)."""
        text = (REPO / "scripts" / "lint.sh").read_text()
        assert "--trend-baseline evidence/graphlint.json" in text


class TestTreeGate:
    def test_shipped_tree_lints_clean(self):
        """Acceptance: the shipped byol_tpu/ tree exits 0 through the SAME
        entrypoint scripts/lint.sh runs — tier-1 DOTS_PASSED gates the
        lint."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", "byol_tpu/"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, (
            "graphlint found new issues in byol_tpu/:\n" + proc.stdout)

    def test_list_rules_catalog(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint", "--list-rules", "."],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0
        for rule_id in ("GL101", "GL102", "GL103", "GL104", "GL105",
                        "GL106", "GL001", "GL000"):
            assert rule_id in proc.stdout

    def test_missing_path_exits_2(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.graphlint",
             "no/such/path_xyz.py"],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 2
