"""Model layer tests: backbone shapes, registry feature dims, heads."""
import jax
import jax.numpy as jnp
import pytest

from byol_tpu.models import registry
from byol_tpu.models.byol_net import build_byol_net
from byol_tpu.models.heads import MLPHead
from byol_tpu.models.resnet import make_resnet


class TestRegistry:
    def test_unknown_arch_raises(self):
        with pytest.raises(ValueError, match="unknown arch"):
            registry.get_spec("resnet9000")

    @pytest.mark.parametrize("name,dim", [
        ("resnet18", 512), ("resnet50", 2048), ("resnet50w2", 4096),
    ])
    def test_feature_dims_match_params(self, name, dim):
        # The registry's declared dim must equal the module's actual output
        # dim — this is the Quirk Q8 fix (no hand-matched
        # --representation-size).
        module, reg_dim = registry.get_backbone(name, small_inputs=True)
        assert reg_dim == dim
        variables = module.init(jax.random.PRNGKey(0),
                                jnp.zeros((1, 32, 32, 3)), train=False)
        out = module.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False,
                           mutable=False)
        assert out.shape == (2, dim)


class TestResNet:
    def test_resnet18_imagenet_stem_downsamples(self):
        m = make_resnet("resnet18")
        variables = m.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=False)
        out = m.apply(variables, jnp.ones((2, 64, 64, 3)), train=False,
                      mutable=False)
        assert out.shape == (2, 512)

    def test_bn_updates_in_train_mode_only(self):
        m = make_resnet("resnet18", small_inputs=True)
        variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                           train=True)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
        _, upd = m.apply(variables, x, train=True, mutable=["batch_stats"])
        before = variables["batch_stats"]["stem_bn"]["mean"]
        after = upd["batch_stats"]["stem_bn"]["mean"]
        assert not jnp.allclose(before, after)
        out_eval = m.apply(variables, x, train=False, mutable=False)
        assert out_eval.shape == (4, 512)

    def test_space_to_depth_stem_matches_conv_stem_exactly(self):
        # The s2d stem is a pure reparametrization of the 7x7/2 conv: same
        # param tree (params/stem_conv/kernel, (7,7,3,w)), same outputs,
        # same gradients — so checkpoints are interchangeable between stems.
        conv_net = make_resnet("resnet18", stem="conv")
        s2d_net = make_resnet("resnet18", stem="space_to_depth")
        x = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3))
        variables = conv_net.init(jax.random.PRNGKey(0), x, train=False)
        k_shape = variables["params"]["stem_conv"]["kernel"].shape
        assert k_shape == (7, 7, 3, 64)
        out_conv = conv_net.apply(variables, x, train=False, mutable=False)
        out_s2d = s2d_net.apply(variables, x, train=False, mutable=False)
        assert jnp.max(jnp.abs(out_conv - out_s2d)) < 1e-4

        def loss(net):
            return lambda v: jnp.sum(
                net.apply(v, x, train=False, mutable=False) ** 2)
        g_conv = jax.grad(loss(conv_net))(variables)
        g_s2d = jax.grad(loss(s2d_net))(variables)
        gk_conv = g_conv["params"]["stem_conv"]["kernel"]
        gk_s2d = g_s2d["params"]["stem_conv"]["kernel"]
        assert jnp.max(jnp.abs(gk_conv - gk_s2d)) < 1e-3

    def test_space_to_depth_stem_rejects_odd_spatial(self):
        m = make_resnet("resnet18", stem="space_to_depth")
        with pytest.raises(ValueError, match="even spatial"):
            m.init(jax.random.PRNGKey(0), jnp.zeros((1, 33, 33, 3)),
                   train=False)

    @pytest.mark.slow
    def test_space_to_depth_stem_through_setup_training(self):
        # The stem knob is inert below the CIFAR-stem threshold (image <=
        # 64), so this must run at a REAL imagenet-stem size — a 16px smoke
        # would silently test the wrong path.  With identical seeds the
        # two stems share init (same param tree), so one train step must
        # produce matching losses.
        import numpy as np
        from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                          TaskConfig, resolve)
        from byol_tpu.parallel.mesh import (MeshSpec, build_mesh,
                                            shard_batch_to_mesh)
        from byol_tpu.training.build import setup_training

        losses = {}
        for stem in ("conv", "space_to_depth"):
            mesh = build_mesh(MeshSpec(data=1), jax.devices()[:1])
            cfg = Config(
                task=TaskConfig(task="fake", batch_size=4, epochs=2,
                                image_size_override=96),
                model=ModelConfig(arch="resnet18", head_latent_size=32,
                                  projection_size=16, stem=stem),
                device=DeviceConfig(num_replicas=1, half=False, seed=0),
            )
            rcfg = resolve(cfg, num_train_samples=16, num_test_samples=4,
                           output_size=10, input_shape=(96, 96, 3))
            net, state, train_step, _, _ = setup_training(
                rcfg, mesh, jax.random.PRNGKey(0))
            k = state.params["backbone"]["stem_conv"]["kernel"]
            assert k.shape == (7, 7, 3, 64)    # reparametrized, not re-shaped
            rng = np.random.RandomState(0)
            batch = shard_batch_to_mesh({
                "view1": rng.rand(4, 96, 96, 3).astype(np.float32),
                "view2": rng.rand(4, 96, 96, 3).astype(np.float32),
                "label": rng.randint(0, 10, size=(4,)).astype(np.int32),
            }, mesh)
            _, metrics = train_step(state, batch)
            losses[stem] = float(metrics["loss_mean"])
        assert losses["conv"] == pytest.approx(losses["space_to_depth"],
                                               rel=1e-4)


class TestHeads:
    def test_mlp_head_shapes(self):
        # Projector contract: Linear(rep->4096)+BN+ReLU+Linear(4096->256)
        # (reference main.py:194-199).
        head = MLPHead(hidden_size=4096, output_size=256)
        variables = head.init(jax.random.PRNGKey(0), jnp.zeros((2, 512)),
                              train=True)
        k1 = variables["params"]["dense1"]["kernel"]
        k2 = variables["params"]["dense2"]["kernel"]
        assert k1.shape == (512, 4096) and k2.shape == (4096, 256)
        out, _ = head.apply(variables, jnp.ones((3, 512)), train=True,
                            mutable=["batch_stats"])
        assert out.shape == (3, 256)


class TestBYOLNet:
    def test_forward_dict_and_probe_stopgrad(self):
        net = build_byol_net("resnet18", num_classes=10,
                            head_latent_size=64, projection_size=32,
                            small_inputs=True)
        x = jnp.ones((2, 32, 32, 3))
        variables = net.init(jax.random.PRNGKey(0), x, train=True,
                             method="warmup")
        out, _ = net.apply(variables, x, train=True,
                           mutable=["batch_stats"])
        assert out["representation"].shape == (2, 512)
        assert out["projection"].shape == (2, 32)
        assert out["prediction"].shape == (2, 32)

        # Probe gradient must not flow into the representation input
        # (main.py:250-252 stop-grad; Quirk Q11).
        def probe_loss(reprs):
            logits = net.apply({"params": variables["params"]}, reprs,
                               method="classify")
            return jnp.sum(logits ** 2)

        g = jax.grad(probe_loss)(jnp.ones((2, 512)))
        assert jnp.allclose(g, 0.0)
