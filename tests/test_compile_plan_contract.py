"""Runtime witness for GL112's static claim (ISSUE 17 satellite).

graphlint's GL112 diffs jit wiring against the compile plan's declared
``DONATE`` data *syntactically*; this module diffs the SAME declaration
against what XLA actually compiled, so the contract is pinned from both
sides: if a builder ever donates or places something ``describe()`` does
not declare, either GL112 (source) or this test (compiled artifact)
breaks.

What the compiled executable exposes (jax 0.4.x, CPU backend included):

- donation surfaces as an ``input_output_alias`` table in
  ``compiled.as_text()`` (and per-arg ``tf.aliasing_output`` attributes
  in the lowered StableHLO) — present iff the entry point donates;
- placement surfaces as ``compiled.input_shardings`` /
  ``compiled.output_shardings`` NamedShardings, which must match the
  plan's ``batch_sharding`` / ``replicated`` properties.

Trivial step bodies stand in for the real ones — donation and sharding
are properties of the jit WRAPPER (the plan's builders), not of the
wrapped computation, and tiny bodies keep the five compiles cheap.
"""
import warnings

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from byol_tpu.parallel.compile_plan import (DONATE, build_plan,
                                            jit_encoder_extractor)

BATCH = 16      # divisible by the 8-way data axis


def _state():
    return {"w": jnp.ones((4, 4)), "m": jnp.zeros((4, 4))}


def _batch():
    return jnp.ones((BATCH, 8))


def _train_fn(state, batch):
    w = state["w"] + jnp.sum(batch)
    return {"w": w, "m": state["m"] * 0.9}, jnp.mean(batch)


def _eval_fn(state, batch):
    return jnp.mean(state["w"]) + jnp.mean(batch)


def _extract_fn(x, y, mask):
    return x * 2.0, y, mask


def _serve_fn(x):
    return x @ jnp.ones((8, 4))


def _compiled(jitted, *args):
    return jitted.lower(*args).compile()


def _aliases(compiled) -> bool:
    return "input_output_alias" in compiled.as_text()


def _flat_input_shardings(compiled):
    return jax.tree_util.tree_leaves(compiled.input_shardings)


class TestDescribeMatchesDonate:
    def test_describe_reports_every_entry(self, mesh8):
        plan = build_plan(mesh8)
        desc = plan.describe()
        assert desc["donate_argnums"] == {
            k: list(v) for k, v in DONATE.items()}

    def test_every_entry_has_a_builder(self, mesh8):
        """A DONATE key without a jit_<entry> builder is dead wiring —
        the runtime face of GL112-unused-entry."""
        plan = build_plan(mesh8)
        for entry in DONATE:
            if entry == "encoder_extractor":
                assert callable(jit_encoder_extractor)
            else:
                assert callable(getattr(plan, f"jit_{entry}")), entry


class TestCompiledDonationMatchesPlan:
    """For each entry point: the compiled executable carries an
    input_output_alias table IFF the plan declares a donation."""

    def _compiled_for(self, plan, entry):
        state = _state()
        state_sh = plan.state_sharding(state)
        if entry == "train_step":
            return _compiled(plan.jit_train_step(_train_fn, state_sh),
                             state, _batch())
        if entry == "eval_step":
            return _compiled(plan.jit_eval_step(_eval_fn, state_sh),
                             state, _batch())
        if entry == "spmd_extractor":
            return _compiled(plan.jit_spmd_extractor(_extract_fn),
                             _batch(), jnp.ones((BATCH,)),
                             jnp.ones((BATCH,)))
        if entry == "serve_step":
            return _compiled(plan.jit_serve_step(_serve_fn), _batch())
        assert entry == "encoder_extractor"
        return _compiled(jit_encoder_extractor(_serve_fn), _batch())

    @pytest.mark.parametrize("entry", sorted(DONATE))
    def test_alias_table_iff_donation_declared(self, mesh8, entry):
        """Declared donation leaves a compiled trace either way XLA takes
        it: an input_output_alias table when the buffer is reusable
        (train_step: state leaves alias same-shaped outputs), or the
        "donated buffers were not usable" warning when the geometry
        forbids aliasing (serve_step here: a data-sharded input cannot
        alias a replicated output on this toy shape — the donation still
        frees the staging buffer's HBM early on TPU).  An entry declared
        non-donating must produce NEITHER."""
        plan = build_plan(mesh8)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            compiled = self._compiled_for(plan, entry)
        dropped = any("donated buffers were not usable"
                      in str(w.message).lower() for w in caught)
        donated = _aliases(compiled) or dropped
        declared = bool(DONATE[entry])
        assert donated == declared, (
            f"{entry}: plan declares donate={DONATE[entry]} but the "
            f"compiled executable says aliasing={_aliases(compiled)}, "
            f"dropped-donation-warning={dropped}")

    def test_train_step_aliases_the_state_argument(self, mesh8):
        """Not just *some* alias: the donated argnum 0 is the state —
        every state leaf input must be aliased to an output."""
        plan = build_plan(mesh8)
        state = _state()
        jitted = plan.jit_train_step(_train_fn, plan.state_sharding(state))
        lowered_text = jitted.lower(state, _batch()).as_text()
        n_state_leaves = len(jax.tree_util.tree_leaves(state))
        assert lowered_text.count("tf.aliasing_output") == n_state_leaves


class TestCompiledShardingsMatchPlan:
    def test_train_step_batch_over_data_metrics_replicated(self, mesh8):
        plan = build_plan(mesh8)
        state = _state()
        state_sh = plan.state_sharding(state)
        compiled = _compiled(plan.jit_train_step(_train_fn, state_sh),
                             state, _batch())
        in_sh = _flat_input_shardings(compiled)
        # last input leaf is the batch: sharded over the data axis
        assert in_sh[-1].is_equivalent_to(plan.batch_sharding, 2), (
            in_sh[-1])
        # metrics output (last leaf) comes back replicated
        out_sh = jax.tree_util.tree_leaves(compiled.output_shardings)
        assert out_sh[-1].is_equivalent_to(plan.replicated, 0), out_sh[-1]

    def test_serve_step_input_sharded_output_replicated(self, mesh8):
        plan = build_plan(mesh8)
        compiled = _compiled(plan.jit_serve_step(_serve_fn), _batch())
        (in_sh,) = _flat_input_shardings(compiled)
        assert in_sh.is_equivalent_to(plan.batch_sharding, 2), in_sh
        (out_sh,) = jax.tree_util.tree_leaves(compiled.output_shardings)
        assert out_sh.is_equivalent_to(plan.replicated, 2), out_sh

    def test_spmd_extractor_outputs_all_replicated(self, mesh8):
        """The replicated out_shardings IS the cross-host all-gather of
        the linear-eval extraction — all three outputs replicated."""
        plan = build_plan(mesh8)
        compiled = _compiled(plan.jit_spmd_extractor(_extract_fn),
                             _batch(), jnp.ones((BATCH,)),
                             jnp.ones((BATCH,)))
        for sh in jax.tree_util.tree_leaves(compiled.output_shardings):
            assert sh.spec == P() or all(a is None for a in sh.spec), sh

    def test_batch_sharding_is_data_axis(self, mesh8):
        plan = build_plan(mesh8)
        assert plan.batch_sharding.spec == P("data")
        assert plan.replicated.spec == P()


class TestResidentDonationContract:
    """ISSUE 18 satellite: under ``--flat-resident on`` the resident flat
    buffers ride the donated state argument of the REAL train step —
    their inputs carry aliasing attributes and the compiled executable
    keeps an input_output_alias table — and the per-step pack
    concatenates of the transient layout are gone from the hot path."""

    @pytest.fixture(scope="class")
    def resident_arms(self, mesh8):
        """Lowered + compiled real train steps, zero1+fused, resident
        off/on — built once for the class (the compiles are the expensive
        part)."""
        from tests.test_flat_state import _batch, _plan_for, _rcfg
        from byol_tpu.parallel.mesh import shard_batch_to_mesh
        from byol_tpu.training.build import setup_training
        arms = {}
        for resident in ("off", "on"):
            rcfg = _rcfg(resident=resident, zero1="on")
            plan = _plan_for(mesh8, rcfg)
            _, state, train_step, _, _ = setup_training(
                rcfg, mesh8, jax.random.PRNGKey(0), plan=plan)
            batch = shard_batch_to_mesh(_batch(), mesh8)
            with mesh8:
                lowered = train_step.__wrapped__.lower(state, batch)
                arms[resident] = {
                    "lowered": lowered.as_text(),
                    "compiled": lowered.compile().as_text(),
                    "global_size": (plan._flat_layout.global_size
                                    if resident == "on" else None),
                }
        return arms

    def test_resident_buffers_are_aliased_inputs(self, resident_arms):
        """Every resident buffer input (the flat_shadow, the momentum
        trace inside opt_state, the target buffer — the three 1-D fp32
        args of the layout's distinctive global size) must carry a
        tf.aliasing_output attribute in the lowered step — donated
        step-over-step, never copied — and the compiled executable keeps
        an input_output_alias table."""
        import re
        arm = resident_arms["on"]
        sig = next(line for line in arm["lowered"].splitlines()
                   if "func public @main" in line)
        # split on argument boundaries rather than parsing the attribute
        # dicts: attrs like mhlo.sharding carry nested braces inside
        # quoted strings, so each chunk is everything up to the next %arg
        params = sig.split("@main(", 1)[1].rsplit(") -> ", 1)[0]
        args = re.split(r",\s+(?=%arg\d+: )", params)
        buf_ty = f"tensor<{arm['global_size']}xf32>"
        buffers = [a for a in args if buf_ty in a]
        assert len(buffers) == 3, (buf_ty, args)
        for a in buffers:
            assert "tf.aliasing_output" in a, a
        assert "input_output_alias" in arm["compiled"]

    def test_resident_step_drops_the_pack_concatenates(self, resident_arms):
        """The transient fused step packs params/grads/momentum/target
        every step (pack_flat's concatenate feeding the kernel); resident
        keeps only the gradient pack.  Three of the four concatenates
        must be gone from the compiled hot path."""
        concat = lambda text: len(
            [1 for line in text.splitlines()
             if " concatenate(" in line or " concatenate.(" in line])
        n_off = concat(resident_arms["off"]["compiled"])
        n_on = concat(resident_arms["on"]["compiled"])
        assert n_on <= n_off - 3, (
            f"resident step still packs: {n_on} concatenates vs "
            f"{n_off} transient")
