"""Benchmark: BYOL training-step throughput, images/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no throughput numbers (BASELINE.md), so the baseline
here is measured in-process: a reference-faithful configuration (fp32, four
separate encoder forwards with per-view BN batches — the semantics of
/root/reference/main.py:244-247 — and pre-update EMA, main.py:255) versus the
TPU-first default (bf16 compute, fused two-view forward).  ``vs_baseline`` is
the speedup of the TPU-first path over that faithful translation on the same
chip, i.e. what the TPU-native redesign buys.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _build(batch_size: int, image_size: int, arch: str, *, half: bool,
           fuse_views: bool, ema_update_mode: str):
    from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                      ParityConfig, TaskConfig, resolve)
    from byol_tpu.parallel.mesh import MeshSpec, build_mesh, shard_batch_to_mesh
    from byol_tpu.training.build import setup_training

    n_dev = len(jax.devices())
    mesh = build_mesh(MeshSpec(data=n_dev))
    cfg = Config(
        task=TaskConfig(task="fake", batch_size=batch_size * n_dev, epochs=100,
                        image_size_override=image_size),
        model=ModelConfig(arch=arch, fuse_views=fuse_views),
        device=DeviceConfig(num_replicas=n_dev, half=half, seed=0),
        parity=ParityConfig(ema_update_mode=ema_update_mode),
    )
    rcfg = resolve(cfg, num_train_samples=1_281_167, num_test_samples=50_000,
                   output_size=1000,
                   input_shape=(image_size, image_size, 3))
    net, state, train_step, _, _ = setup_training(
        rcfg, mesh, jax.random.PRNGKey(0))

    b = cfg.task.batch_size
    rng = np.random.RandomState(0)
    batch = {
        "view1": rng.rand(b, image_size, image_size, 3).astype(np.float32),
        "view2": rng.rand(b, image_size, image_size, 3).astype(np.float32),
        "label": rng.randint(0, 1000, size=(b,)).astype(np.int32),
    }
    batch = shard_batch_to_mesh(batch, mesh)
    return state, train_step, batch


def _throughput(batch_size: int, image_size: int, arch: str, *, half: bool,
                fuse_views: bool, ema_update_mode: str,
                steps: int = 20) -> float:
    """Images/sec/chip for one configuration (global images / sec / n_dev)."""
    state, train_step, batch = _build(
        batch_size, image_size, arch, half=half, fuse_views=fuse_views,
        ema_update_mode=ema_update_mode)
    # warmup: compile + 2 steady steps.  NB: sync via a scalar READBACK, not
    # block_until_ready — on tunneled platforms (axon) block_until_ready
    # returns at dispatch-ack and wildly overstates throughput; a D2H read
    # of a value that depends on the whole step chain cannot lie.
    for _ in range(3):
        state, metrics = train_step(state, batch)
    float(metrics["loss_mean"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, batch)
    float(metrics["loss_mean"])
    dt = time.perf_counter() - t0
    n_dev = len(jax.devices())
    global_batch = batch["label"].shape[0]
    return global_batch * steps / dt / n_dev


def main():
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        arch, image_size = "resnet50", 224
        candidates = [512, 256, 128, 64, 32]
    else:  # CPU fallback so the bench never hard-fails off-hardware
        arch, image_size = "resnet18", 32
        candidates = [64, 32]

    def best_throughput(**kw):
        """Largest-fitting batch from the candidate ladder — each config is
        measured at ITS OWN best batch size, as a real user would run it."""
        for bs in candidates:
            try:
                return _throughput(bs, image_size, arch, **kw)
            except Exception as e:  # OOM at this batch — try smaller
                msg = str(e)
                if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg:
                    continue
                raise
        return None

    value = best_throughput(half=True, fuse_views=True,
                            ema_update_mode="post")
    baseline = best_throughput(half=False, fuse_views=False,
                               ema_update_mode="reference_pre", steps=10)
    if value is None or baseline is None:
        raise RuntimeError("no batch size fit in memory")

    print(json.dumps({
        "metric": f"{arch}_byol_train_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / baseline, 3),
    }))


if __name__ == "__main__":
    main()
