"""Benchmark: BYOL training-step throughput, images/sec/chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu"}.

The reference publishes no throughput numbers (BASELINE.md), so the baseline
here is measured in-process: a reference-faithful configuration (fp32, four
separate encoder forwards with per-view BN batches — the semantics of
/root/reference/main.py:244-247 — and pre-update EMA, main.py:255) versus the
TPU-first default (bf16 compute, fused two-view forward).  ``vs_baseline`` is
the speedup of the TPU-first path over that faithful translation on the same
chip, i.e. what the TPU-native redesign buys.

Robustness contract (hard-learned — this script burned two benchmark rounds):
- ANY failure while building/measuring one batch-ladder candidate is treated
  as "that batch did not fit" (logged to stderr with the real traceback) and
  the ladder steps down.  Compile-time OOM on this platform surfaces as
  ``JaxRuntimeError: INTERNAL: ... tpu_compile_helper subprocess exit code
  1`` — not RESOURCE_EXHAUSTED — so string-matching specific OOM spellings
  is a losing game.
- Every measured result is flushed to ``bench_partial.json`` IMMEDIATELY, so
  a later failure (e.g. the fp32 baseline config) can never zero out an
  already-measured number.
- If the baseline config fails at every ladder rung, the primary result is
  still printed with ``vs_baseline: null`` rather than crashing.

MFU: analytic model FLOPs / measured step time / chip peak.  FLOPs count
multiply-add as 2 (the same convention as the quoted chip peaks).  Per
sample: 2 online forwards + 2 target forwards + backward (~2x the online
forwards) = 8 encoder-forward-equivalents; head MLP/probe FLOPs are <1% of
the RN50 trunk at 224px and are ignored.

Every measured row now carries ``compile_seconds`` and
``hbm_high_water_bytes`` (from ``jit(...).lower(...).compile()
.memory_analysis()``), so spill/OOM regimes are visible in BENCH_*.json
without reading OOM dumps.

Usage:
  python bench.py                  # the two headline configs -> one JSON line
  python bench.py --mvc            # minimum-viable capture: one rung per
                                   #   family + the rematted bs512 sweep row,
                                   #   sized for a short tunnel window
  python bench.py --sweep          # batch x remat x fuse grid -> bench_sweep.json
  python bench.py --profile DIR    # jax.profiler trace of the headline config
  python bench.py --stem-ab        # conv vs space_to_depth stem A/B
  python bench.py --data           # host data pipeline: tf vs native C++
  python bench.py --accum-ladder   # microbatch-accumulation ladder: effective
                                   #   512/1024/4096 at the per-chip-optimal
                                   #   microbatch (256), each rung's compile
                                   #   gated behind a killable subprocess
                                   #   timeout; records compile_seconds +
                                   #   HBM high-water + img/s/chip
  python bench.py --dry-compile    # AOT-compile ONE accumulation config
                                   #   (default: effective 4096 @ microbatch
                                   #   256, --remat-policy dots) and report
                                   #   memory_analysis() without executing;
                                   #   --augment-placement loader|step picks
                                   #   the input contract (float32 views vs
                                   #   raw uint8 + in-step augmentation)
  python bench.py --input-ladder   # augment-placement A/B: loader-aug
                                   #   float32 vs step-aug uint8 at effective
                                   #   512/1024/4096 @ microbatch 256; every
                                   #   row records h2d_bytes_per_step + HBM
                                   #   high-water (same compile gating as
                                   #   --accum-ladder)
  python bench.py --telemetry-ab   # telemetry-overhead A/B: --telemetry off
                                   #   vs step @ --telemetry-interval 50,
                                   #   full observation cost (in-graph
                                   #   health vector + lagged sink
                                   #   readback); budget < 2%
  python bench.py --spans-ab       # flight-recorder overhead A/B: ONE
                                   #   compiled executable timed with the
                                   #   spans-off no-op recorder vs a live
                                   #   SpanRecorder wrapping every dispatch
                                   #   + the readback, INTERLEAVED reps +
                                   #   median (spans are host-side only,
                                   #   so the arms share the identical
                                   #   program and box drift cancels);
                                   #   budget < 2%.  The spans arm also
                                   #   emits goodput/span_stats events into
                                   #   bench_events.jsonl and exports
                                   #   bench_trace.json (Chrome trace)
  python bench.py --zero1-ab       # ZeRO-1 weight-update-sharding A/B
                                   #   (--dry-compile flavored: AOT compile
                                   #   only, no execution): replicated vs
                                   #   --zero1 on at the accumulation
                                   #   target config; every row records
                                   #   hbm_high_water_bytes + the per-chip
                                   #   optimizer_state_bytes column (which
                                   #   must scale ~1/N with mesh size).
                                   #   --cpu-devices N sizes the virtual
                                   #   CPU mesh for off-hardware captures
  python bench.py --resident-ab    # resident flat update-state A/B
                                   #   (--zero1 on --fused-update on both
                                   #   arms): transient per-step
                                   #   pack/unpack + per-leaf gather
                                   #   (--flat-resident off) vs resident
                                   #   buffers aliased in place + bucketed
                                   #   all-gather (on); wall rate +
                                   #   dispatch-span p50 per arm, plus an
                                   #   in-process microbench of the bare
                                   #   pack+kernel+unpack vs the resident
                                   #   kernel call
  python bench.py --augment-ab     # fused-augmentation A/B: the step-
                                   #   placement config with the XLA op
                                   #   chain (--fused-augment off) vs the
                                   #   fused Pallas kernel (on), both arms
                                   #   AOT-compiled and timed under a live
                                   #   SpanRecorder (wall + train/dispatch
                                   #   span p50 -> bench_events.jsonl), plus
                                   #   an in-process microbench row: bare
                                   #   two_view XLA chain vs fused call
  python bench.py --serve-ladder   # embedding-service latency/throughput
                                   #   at 1/8/64 closed-loop streams;
                                   #   --serve-pipeline off|on|ab A/Bs the
                                   #   worker dispatch pipelining on the
                                   #   same warmed engine
  python bench.py --wire-ladder    # the WIRE TAX: in-process vs
                                   #   over-HTTP (serving/net/) per rung —
                                   #   client-observed p50/p99 for both
                                   #   arms and the per-rung delta

Every run also appends structured events (run header + one ``bench_row``
per measured config) to ``bench_events.jsonl`` — the same schema-versioned
JSONL format trainer.fit writes as ``run.jsonl``
(byol_tpu/observability/events.py), so one reader parses runs and benches.
"""
from __future__ import annotations

import json
import os
import sys
import time
import traceback

import jax
import numpy as np

# fwd GMACs per image (multiply-accumulates; FLOPs = 2x). torchvision-style
# counts for the conv trunk; heads ignored (sub-1% at these shapes).
_GMACS = {
    ("resnet50", 224): 4.089,
    ("resnet50", 96): 0.76,
    ("resnet18", 224): 1.814,
    ("resnet18", 32): 0.557,   # CIFAR stem (3x3 s1, no maxpool)
    # ViT-B/16 @224 (BASELINE.json config 5): 197 tokens; per block
    # 4*S*D^2 qkvo + 2*S^2*D attn + 8*S*D^2 MLP = 1.454 GMACs, x12 blocks
    # + 0.116 patch embed = 17.56 GMACs/forward-image.
    ("vit_b16", 224): 17.56,
}

# Chip peak table lives with the framework's MFU accounting (the trainer
# reports live MFU from the same source, observability/flops.py).
from byol_tpu.observability.flops import chip_peak_tflops as _chip_peak_tflops

# Strict-JSON output contract (GL110): every JSON line/file this script
# emits goes through the event sink's sanitize + allow_nan=False path,
# so an anomalous run (NaN loss, inf step time) still prints parseable
# JSON instead of bare NaN/Infinity tokens.
from byol_tpu.observability.events import sanitize as _sanitize_json


def _json_line(obj) -> str:
    return json.dumps(_sanitize_json(obj), allow_nan=False)



def _flops_per_sample(arch: str, image_size: int) -> float | None:
    gmacs = _GMACS.get((arch, image_size))
    if gmacs is None:
        return None
    # 2 online + 2 target fwds + bwd (2x online's 2 fwds) = 8 fwd-images.
    return 8.0 * gmacs * 2.0 * 1e9


class _Rate(float):
    """img/s/chip that also carries per-rung compile/memory side-channel
    stats (``compile_seconds``, ``hbm_high_water_bytes``, ...) for the JSON
    rows — arithmetic call sites keep treating it as a plain float."""

    stats: dict = {}

    def __new__(cls, value, stats=None):
        r = super().__new__(cls, value)
        r.stats = dict(stats or {})
        return r


def _row_stats(val) -> dict:
    return dict(getattr(val, "stats", {}) or {})


def _memory_stats(compiled) -> dict:
    """Extract the HBM picture from ``compiled.memory_analysis()``.

    ``hbm_high_water_bytes`` is the executable's device-memory high-water
    mark: arguments + outputs + XLA temp buffers, minus donated aliases
    (donation makes the output share the argument buffer).  Best-effort:
    a backend without the analysis yields {} rather than failing the rung.
    """
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    out = {}
    for key in ("temp_size_in_bytes", "argument_size_in_bytes",
                "output_size_in_bytes", "alias_size_in_bytes",
                "generated_code_size_in_bytes"):
        v = getattr(mem, key, None)
        if v is not None:
            out[key] = int(v)
    peak = getattr(mem, "peak_memory_in_bytes", None)
    if peak is not None and int(peak) > 0:
        out["hbm_high_water_bytes"] = int(peak)
    elif "temp_size_in_bytes" in out:
        out["hbm_high_water_bytes"] = (
            out.get("argument_size_in_bytes", 0)
            + out.get("output_size_in_bytes", 0)
            - out.get("alias_size_in_bytes", 0)
            + out["temp_size_in_bytes"])
    return out


def _build(batch_size: int, image_size: int, arch: str, *, half: bool,
           fuse_views: bool, ema_update_mode: str, remat: bool = False,
           stem: str = "conv", attn_impl: str = "dense",
           accum_steps: int = 1, accum_bn_mode: str = "average",
           remat_policy: str = "none", augment_placement: str = "loader",
           telemetry: str = "off", zero1: str = "off",
           fused_update: str = "off", fused_augment: str = "off",
           flat_resident: str = "off", materialize_batch: bool = True):
    from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                      OptimConfig, ParityConfig, TaskConfig,
                                      resolve)
    from byol_tpu.parallel.mesh import MeshSpec, build_mesh, shard_batch_to_mesh
    from byol_tpu.training.build import setup_training

    n_dev = len(jax.devices())
    mesh = build_mesh(MeshSpec(data=n_dev))
    cfg = Config(
        task=TaskConfig(task="fake", batch_size=batch_size * n_dev, epochs=100,
                        image_size_override=image_size,
                        augment_placement=augment_placement,
                        fused_augment=fused_augment),
        model=ModelConfig(arch=arch, fuse_views=fuse_views, remat=remat,
                          remat_policy=remat_policy,
                          stem=stem, attn_impl=attn_impl),
        optim=OptimConfig(accum_steps=accum_steps,
                          accum_bn_mode=accum_bn_mode,
                          fused_update=fused_update),
        device=DeviceConfig(num_replicas=n_dev, half=half, seed=0,
                            telemetry=telemetry, zero1=zero1,
                            flat_resident=flat_resident),
        parity=ParityConfig(ema_update_mode=ema_update_mode),
    )
    rcfg = resolve(cfg, num_train_samples=1_281_167, num_test_samples=50_000,
                   output_size=1000,
                   input_shape=(image_size, image_size, 3))
    net, state, train_step, _, _ = setup_training(
        rcfg, mesh, jax.random.PRNGKey(0))

    b = cfg.task.batch_size
    if not materialize_batch:
        # Compile-only paths lower against shapes + shardings; no pixels.
        return (state, train_step,
                _abstract_batch(b, image_size, mesh,
                                augment_placement=augment_placement), mesh)
    # fp32-native generation: RandomState.rand materializes a float64
    # intermediate, which at the effective-4096 rung is a ~40 GB host
    # transient PER VIEW — enough to OOM the 1-core TPU host before the
    # measurement starts.
    rng = np.random.default_rng(0)
    if augment_placement == "step":
        # raw-uint8 contract (loader._raw_pipeline): the step augments
        batch = {
            "images": rng.integers(0, 256, (b, image_size, image_size, 3),
                                   dtype=np.uint8),
            "label": rng.integers(0, 1000, size=(b,)).astype(np.int32),
        }
    else:
        batch = {
            "view1": rng.random((b, image_size, image_size, 3),
                                dtype=np.float32),
            "view2": rng.random((b, image_size, image_size, 3),
                                dtype=np.float32),
            "label": rng.integers(0, 1000, size=(b,)).astype(np.int32),
        }
    batch = shard_batch_to_mesh(batch, mesh)
    return state, train_step, batch, mesh


def _batch_h2d_bytes(batch) -> int:
    """Host bytes one step's input batch ships over PCIe/H2D — works for
    concrete arrays and for the compile-only ShapeDtypeStruct batches.
    ONE implementation shared with the trainer's input meter
    (data/prefetch.py host_nbytes), so the bench column and the epoch log
    can never disagree."""
    from byol_tpu.data.prefetch import host_nbytes
    return host_nbytes(batch)


def _optimizer_state_bytes(state) -> int | None:
    """PER-CHIP bytes of the weight-update state (optimizer state + EMA
    target): the HBM the ZeRO-1 A/B exists to measure.  Computed from each
    leaf's SHARDING (``shard_shape``), not its global shape — a flat
    leaf-partitioned tree reports ~1/N of its replicated size, which is
    exactly the per-chip truth ``memory_analysis()``'s aggregate argument
    bytes cannot break out.  Best-effort: states without shardings (or
    non-TrainState pytrees) yield None rather than failing the rung."""
    import math
    try:
        leaves = jax.tree_util.tree_leaves(
            (state.opt_state, state.target_params))
        total = 0
        for leaf in leaves:
            shape = tuple(getattr(leaf, "shape", ()) or ())
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None:
                shape = tuple(sharding.shard_shape(shape))
            itemsize = np.dtype(leaf.dtype).itemsize
            total += int(math.prod(shape)) * itemsize
        return total
    except Exception:
        return None


def _aot_compile(train_step, state, batch, mesh):
    """AOT lower+compile the step ONCE; returns (compiled, stats).

    The explicit lower/compile (instead of compile-on-first-call) is what
    makes ``compile_seconds`` and ``memory_analysis()`` observable per rung;
    the returned executable is then used for the measurement itself, so the
    rung still compiles exactly once.
    """
    fn = getattr(train_step, "__wrapped__", train_step)
    t0 = time.perf_counter()
    with mesh:
        compiled = fn.lower(state, batch).compile()
    stats = {"compile_seconds": round(time.perf_counter() - t0, 2),
             "h2d_bytes_per_step": _batch_h2d_bytes(batch)}
    opt_bytes = _optimizer_state_bytes(state)
    if opt_bytes is not None:
        stats["optimizer_state_bytes"] = opt_bytes
    stats.update(_memory_stats(compiled))
    return compiled, stats


def _throughput(batch_size: int, image_size: int, arch: str, *, half: bool,
                fuse_views: bool, ema_update_mode: str, remat: bool = False,
                stem: str = "conv", attn_impl: str = "dense",
                accum_steps: int = 1, accum_bn_mode: str = "average",
                remat_policy: str = "none",
                augment_placement: str = "loader", steps: int = 20) -> _Rate:
    """Images/sec/chip for one configuration (global images / sec / n_dev);
    the returned float carries compile/HBM stats (``_Rate.stats``)."""
    state, train_step, batch, mesh = _build(
        batch_size, image_size, arch, half=half, fuse_views=fuse_views,
        ema_update_mode=ema_update_mode, remat=remat, stem=stem,
        attn_impl=attn_impl, accum_steps=accum_steps,
        accum_bn_mode=accum_bn_mode, remat_policy=remat_policy,
        augment_placement=augment_placement)
    compiled, stats = _aot_compile(train_step, state, batch, mesh)
    # warmup: 3 steady steps.  NB: sync via a scalar READBACK, not
    # block_until_ready — on tunneled platforms (axon) block_until_ready
    # returns at dispatch-ack and wildly overstates throughput; a D2H read
    # of a value that depends on the whole step chain cannot lie.
    for _ in range(3):
        state, metrics = compiled(state, batch)
    float(metrics["loss_mean"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = compiled(state, batch)
    float(metrics["loss_mean"])
    dt = time.perf_counter() - t0
    n_dev = len(jax.devices())
    global_batch = batch["label"].shape[0]
    return _Rate(global_batch * steps / dt / n_dev, stats)


_PARTIAL_PATH = "bench_partial.json"
_partial: dict = {"results": []}

# Failure classification for the "any failure = didn't fit" contract: a DEAD
# BACKEND is not a fit failure.  Mislabeling it poisons the ladder (every
# later rung "fails to fit" too) and burns hours hanging per config — seen
# live when the axon tunnel dropped mid-sweep and bs256 (which fits and
# measures 776 img/s) was recorded fit=False after a 25-minute hang.
_BACKEND_DEAD_MARKERS = ("UNAVAILABLE", "backend setup", "DEADLINE_EXCEEDED",
                         "Socket closed", "failed to connect")


class BackendDied(RuntimeError):
    """The accelerator backend is gone; no further config can measure."""


_backend_dead = False


def _note_backend_dead(context: str) -> None:
    global _backend_dead
    _backend_dead = True
    print(f"bench: backend became unavailable during {context}; "
          "skipping all remaining configs (measured results preserved in "
          f"{_PARTIAL_PATH})", file=sys.stderr)
    _record("backend_died", context=context)


def _reraise_if_backend_dead(exc: BaseException) -> None:
    """Raise BackendDied iff ``exc`` looks backend-fatal AND a liveness probe
    confirms it.  The markers are broad (UNAVAILABLE is gRPC's generic
    transient status), so a probe matmul disambiguates: a recoverable
    per-config failure that merely mentions those words keeps the ladder
    stepping down instead of aborting the whole bench."""
    msg = str(exc)
    if not any(m in msg for m in _BACKEND_DEAD_MARKERS):
        return
    backend = jax.default_backend()
    if backend == "cpu":
        return  # host backend cannot "die"; the failure is config-local
    import subprocess
    # The child must prove THIS backend is alive — without the platform
    # assert, jax's silent CPU fallback would pass the matmul on a dead
    # accelerator and mislabel the death as did-not-fit (re-poisoning the
    # ladder, the exact failure this probe exists to prevent).  The child
    # inherits the normal platform plugin, so a dead accelerator either
    # hangs it (timeout) or falls back to cpu (assert fires): both nonzero.
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             f"assert jax.default_backend() == {backend!r}, "
             "jax.default_backend(); "
             "print(float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum()))"],
            timeout=60.0, capture_output=True, text=True)
        if probe.returncode == 0:
            return   # backend alive: the failure was config-local
    except subprocess.TimeoutExpired:
        pass
    raise BackendDied(
        "accelerator backend became unavailable mid-run (error matched "
        "a backend-death marker and a 60s probe matmul failed); aborting "
        "the remaining configs (already-measured results are preserved in "
        f"{_PARTIAL_PATH})") from exc


def _config_failed(context: str, exc: BaseException) -> bool:
    """Shared per-config failure path: classify, log, record.

    Returns True if the backend is dead (recorded via _note_backend_dead;
    the caller must stop measuring), False for an ordinary did-not-fit
    (logged; the caller records ``fit=False`` and steps the ladder down).
    """
    try:
        _reraise_if_backend_dead(exc)
    except BackendDied:
        traceback.print_exc()
        _note_backend_dead(context)
        return True
    print(f"bench: {context} failed (treating as did-not-fit):",
          file=sys.stderr)
    traceback.print_exc()
    return False


def _oom_signature(exc_text: str) -> bool:
    """Does a recorded failure look like a deterministic memory/compile
    failure (safe to pin across runs), as opposed to a transient tunnel
    error that deserves a re-attempt?  On this platform compile-OOM spells
    itself ``tpu_compile_helper subprocess exit code 1`` with "Ran out of
    memory" only lowercase deep in the dump (VERDICT r2)."""
    low = exc_text.lower()
    return ("resource_exhausted" in low or "out of memory" in low
            or "ran out of memory" in low or "tpu_compile_helper" in low)


def _known_oom(bs: int, arch: str, image_size: int,
               remat: bool = False) -> bool:
    """Is this rung the documented deterministic compile-OOM?  The
    un-rematted resnet50@224 bs1024 compile took 25+ minutes and crashed
    the remote-compile service for hours (round 2).  The sweep grid rule
    is "never re-attempted without remat"; this predicate extends the
    same rule to the headline and profile ladders, which previously
    started at that rung on every fresh run."""
    return (not remat and bs >= 1024 and arch == "resnet50"
            and image_size == 224)


_flushed_paths: set = set()


def _flush_partial():
    try:
        # A fresh run must never DESTROY prior evidence: the first write of
        # this process moves any existing file to <path>.prev instead of
        # truncating it.  (Learned the hard way: an import-time classifier
        # check once overwrote the committed TPU artifact with a single
        # backend_died stub.)
        if _PARTIAL_PATH not in _flushed_paths:
            if os.path.exists(_PARTIAL_PATH):
                os.replace(_PARTIAL_PATH, _PARTIAL_PATH + ".prev")
            # only after the backup succeeded: a failed replace must retry
            # next flush, never fall through to truncating the evidence
            _flushed_paths.add(_PARTIAL_PATH)
        with open(_PARTIAL_PATH, "w") as f:
            json.dump(_sanitize_json(_partial), f, indent=2,
                      allow_nan=False)
            f.write("\n")
    except OSError as e:  # read-only fs must not kill the measurement
        print(f"bench: could not write {_PARTIAL_PATH}: {e}", file=sys.stderr)


_events = None          # observability.events.RunLog, opened by main()


def _open_events(path: str = "bench_events.jsonl") -> None:
    """Open the structured bench event log (same JSONL schema as
    trainer.fit's run.jsonl, observability/events.py) and stamp the run
    header.  Deliberately backend-client-free: the header reads only the
    static jax config, so it is safe to call BEFORE the accum-ladder gate
    children claim the single-client TPU.  RunLog(best_effort=True)
    swallows construction and write failures alike — a read-only fs must
    not kill the measurement (same contract as _flush_partial)."""
    global _events
    from byol_tpu.observability.events import RunLog
    _events = RunLog(path, best_effort=True)
    _events.emit("run_header",
                 config={"argv": sys.argv[1:], "tool": "bench.py"},
                 jax_version=jax.__version__,
                 backend=str(jax.config.jax_platforms or "auto"))


def _record(name: str, **fields):
    global _events
    _partial["results"].append({"config": name, **fields})
    _flush_partial()
    if _events is not None:
        # every bench row doubles as a structured event — one reader
        # (observability/events.py) parses runs and benches alike.
        # Best-effort like _flush_partial: a disk that fills mid-sweep
        # must not kill hours of measurement.
        try:
            _events.emit("bench_row", config=name, **fields)
        except (OSError, TypeError, ValueError) as e:
            print(f"bench: event log write failed ({e!r}); disabling "
                  "bench_events.jsonl for the rest of the run",
                  file=sys.stderr)
            _events = None


# Killable backend preflight — shared with the train CLI (which learned the
# hard way that it needs one too: a capture-pipeline train run hung forever
# in backend init against a dead tunnel where this bench failed fast).
# Kept under the private name so tests can stub bench._preflight_backend.
from byol_tpu.core.preflight import preflight_backend as _preflight_backend


def _emit_stale_or_die() -> None:
    """Backend unreachable: fall back to the last COMMITTED TPU measurement,
    explicitly marked stale, rather than dying with no parseable output.
    The driver records bench stdout every round; a third rc=1 round would
    carry less information than the honest 'here is the last real TPU
    number, the chip was unreachable at capture time'."""
    errs, prior, best, best_base, src = [], None, None, None, None
    # The live file may have been rotated to .prev by an intervening run
    # (e.g. a sweep) that recorded no tpu_first rows — consult both.
    for path in (_PARTIAL_PATH, _PARTIAL_PATH + ".prev"):
        try:
            with open(path) as f:
                cand = json.load(f)
            if "tpu" not in str(cand.get("device_kind", "")).lower():
                raise ValueError(f"no TPU results in {path}")
            fits = [r for r in cand["results"]
                    if r.get("config") == "tpu_first" and r.get("fit")]
            base = [r for r in cand["results"]
                    if r.get("config") == "reference_faithful"
                    and r.get("fit")]
            best = max(fits, key=lambda r: r["images_per_sec_per_chip"])
            best_base = (max(base,
                             key=lambda r: r["images_per_sec_per_chip"])
                         if base else None)
            prior, src = cand, path
            break
        except Exception as e:
            errs.append(f"{path}: {e}")
    if prior is None:
        raise SystemExit(
            "bench: accelerator unreachable and no committed TPU artifact "
            f"to fall back to ({'; '.join(errs)}); rerun when a probe "
            "matmul succeeds.")
    arch = prior.get("arch", "resnet50")
    value = best["images_per_sec_per_chip"]
    print(_json_line({
        "metric": f"{arch}_byol_train_images_per_sec_per_chip",
        "value": value,
        "unit": "images/sec/chip",
        "vs_baseline": (round(value / best_base["images_per_sec_per_chip"], 3)
                        if best_base else None),
        "mfu": (round(best["mfu"], 4)
                if best.get("mfu") is not None else None),
        "stale": True,
        "note": ("accelerator backend unreachable at capture time; value is "
                 f"the last committed TPU measurement from {src} "
                 f"({prior.get('device_kind')})"),
    }))


def main():
    if "--data" in sys.argv[1:]:
        _data_pipeline_bench()     # host-only: no accelerator preflight
        return
    # --cpu-devices N: size a virtual CPU mesh for off-hardware captures
    # (the --zero1-ab 1/N scaling rows need several mesh sizes).  Must run
    # before any backend touch; forces the cpu platform so a half-up TPU
    # tunnel cannot race the override into a mixed backend.
    n_cpu = _int_flag("--cpu-devices", 0)
    if n_cpu:
        from byol_tpu.core.preflight import force_cpu_devices
        force_cpu_devices(n_cpu)
    # Optional arch override (e.g. --arch vit_b16, the BASELINE.json
    # config-5 encoder swap).  Non-default archs measure into their OWN
    # partial file so they can never rotate away the committed resnet50
    # evidence artifact (and the stale-fallback path stays arch-consistent).
    arch_override = None
    if "--arch" in sys.argv[1:]:
        i = sys.argv.index("--arch") + 1
        if i >= len(sys.argv):
            raise SystemExit("usage: bench.py --arch <registry name>")
        arch_override = sys.argv[i]
        # Fail fast on typos: otherwise every ladder rung "fails to fit"
        # and the exit misdiagnoses a misspelling as a memory ceiling.
        from byol_tpu.models.registry import get_spec
        try:
            get_spec(arch_override)
        except ValueError as e:
            raise SystemExit(f"bench: {e}")
    # Attention backend for ViT archs (--attn dense|flash|ring): lets the
    # Pallas flash kernel A/B against XLA dense on the same ladder.
    attn_impl = "dense"
    if "--attn" in sys.argv[1:]:
        i = sys.argv.index("--attn") + 1
        if i >= len(sys.argv) or sys.argv[i] not in ("dense", "flash",
                                                     "ring"):
            # fail fast like --arch: a typo here would otherwise record
            # every ladder rung as "did not fit" (trace-time error)
            raise SystemExit("usage: bench.py --attn dense|flash|ring")
        attn_impl = sys.argv[i]
    global _PARTIAL_PATH
    if arch_override and arch_override != "resnet50":
        _PARTIAL_PATH = f"bench_partial_{arch_override}.json"
    if attn_impl != "dense":
        _PARTIAL_PATH = _PARTIAL_PATH.replace(
            ".json", f"_{attn_impl}.json")
    if "--dry-compile" not in sys.argv[1:]:
        # --dry-compile is also the accum/input-ladder GATE CHILD body: a
        # header per child would interleave N+1 run_headers into the
        # parent sweep's event stream (and a standalone dry-compile emits
        # its one JSON line on stdout — nothing to log here either)
        _open_events()
    # Persistent compile cache: every config's XLA compile costs minutes over
    # the tunneled backend; caching makes sweep re-runs (and headline re-runs
    # after a mid-sweep backend drop) nearly free to resume.
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if not _preflight_backend():
        mode = {"--sweep", "--profile", "--stem-ab", "--mvc",
                "--accum-ladder", "--dry-compile", "--input-ladder",
                "--telemetry-ab", "--spans-ab", "--zero1-ab",
                "--fused-ab", "--resident-ab", "--augment-ab",
                "--serve-ladder", "--wire-ladder"} \
            & set(sys.argv[1:])
        if mode:
            # only the headline has a committed artifact to fall back to;
            # a stale headline-shaped line in a sweep/profile capture file
            # would masquerade as that mode's output
            raise SystemExit(
                f"bench: accelerator unreachable; {sorted(mode)[0]} needs "
                "live hardware (no stale fallback for non-headline modes)")
        _emit_stale_or_die()
        return
    accum_gates = input_gates = None
    if "--accum-ladder" in sys.argv[1:] or "--input-ladder" in sys.argv[1:]:
        # Gate children must claim the single-client TPU before the
        # in-process backend init below pins it to this process.
        is_accel = _probe_backend_is_accel()
        if "--accum-ladder" in sys.argv[1:]:
            accum_gates = _accum_gate_phase(is_accel, arch_override,
                                            attn_impl)
        if "--input-ladder" in sys.argv[1:]:
            input_gates = _input_gate_phase(is_accel, arch_override,
                                            attn_impl)
    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        arch, image_size = arch_override or "resnet50", 224
        candidates = [1024, 512, 256, 128, 64, 32]
        if arch != "resnet50":
            # Non-default archs start below the 1024 rung: the un-rematted
            # rn50 bs1024 compile-OOM once took 25+ min and crashed the
            # remote-compile service — no first contact with a new arch
            # should risk that rung.
            candidates = [512, 256, 128, 64, 32]
    else:  # CPU fallback so the bench never hard-fails off-hardware
        arch, image_size = "resnet18", 32
        candidates = [64, 32]
        # CPU smokes must not clobber the committed TPU evidence artifact
        _PARTIAL_PATH = "bench_partial_cpu.json"

    flops_per_sample = _flops_per_sample(arch, image_size)
    peak = _chip_peak_tflops()
    _partial.update(arch=arch, image_size=image_size,
                    device_kind=jax.devices()[0].device_kind,
                    n_devices=len(jax.devices()),
                    peak_bf16_tflops=peak)

    def mfu_of(img_per_sec_per_chip: float) -> float | None:
        if flops_per_sample is None or peak is None or not on_tpu:
            return None
        return img_per_sec_per_chip * flops_per_sample / (peak * 1e12)

    def best_throughput(name: str, **kw):
        """Best throughput over the candidate ladder — each config measured
        at ITS OWN best batch size, as a real user would run it.  ANY
        per-candidate failure counts as "didn't fit" (see module doc).
        The largest FITTING batch is not always the fastest (near-OOM
        batches can spill/fragment), so on TPU the next rung down is
        measured too and the max of the two is returned (CPU fallback keeps
        a single rung — it exists for liveness, not measurement)."""
        rungs = 2 if on_tpu else 1
        measured = 0
        best = None
        for bs in candidates:
            if _backend_dead:
                break
            if _known_oom(bs, arch, image_size, kw.get("remat", False)):
                _record(name, batch_per_chip=bs, fit=False, reused=True,
                        error="skipped: documented un-rematted bs1024 "
                              "compile-OOM (remote-compile-service crasher)")
                continue
            try:
                val = _throughput(bs, image_size, arch, **kw)
            except Exception as e:
                if _config_failed(f"config={name} bs/chip={bs}", e):
                    break
                _record(name, batch_per_chip=bs, fit=False,
                        error=repr(e)[:300])
                continue
            _record(name, batch_per_chip=bs, fit=True,
                    images_per_sec_per_chip=round(val, 2), mfu=mfu_of(val),
                    **_row_stats(val),
                    **{k: v for k, v in kw.items() if k != "steps"})
            best = val if best is None else max(best, val)
            measured += 1
            if measured >= rungs:
                break
        return best

    if "--stem-ab" in sys.argv[1:]:
        # A/B the headline config's stem: plain 7x7/2 conv vs the
        # space-to-depth rearrangement (identical numerics; layout only).
        if not on_tpu:
            raise SystemExit(
                "bench: --stem-ab needs the TPU config — the CPU fallback "
                "(resnet18@32) uses the CIFAR stem, where the stem knob is "
                "inert and an A/B would compare identical models")
        for stem in ("conv", "space_to_depth"):
            val = best_throughput(f"stem_{stem}", half=True, fuse_views=True,
                                  ema_update_mode="post", stem=stem)
            print(_json_line({"metric": f"stem_ab_{stem}",
                              "value": round(val, 2) if val else None,
                              "unit": "images/sec/chip",
                              "vs_baseline": None,
                              "mfu": (round(mfu_of(val), 4)
                                      if val and mfu_of(val) else None)}))
        return
    if "--sweep" in sys.argv[1:]:
        _sweep(arch, image_size, candidates, mfu_of)
        return
    if "--profile" in sys.argv[1:]:
        i = sys.argv.index("--profile") + 1
        if i >= len(sys.argv):
            raise SystemExit("usage: bench.py --profile <logdir>")
        _profile(arch, image_size, candidates, sys.argv[i])
        return
    if "--mvc" in sys.argv[1:]:
        _mvc(arch, image_size, candidates, on_tpu, mfu_of, attn_impl)
        return
    if "--dry-compile" in sys.argv[1:]:
        _dry_compile(arch, image_size, on_tpu, attn_impl)
        return
    if "--accum-ladder" in sys.argv[1:]:
        _accum_ladder(arch, image_size, on_tpu, mfu_of, attn_impl,
                      accum_gates)
        return
    if "--input-ladder" in sys.argv[1:]:
        _input_ladder(arch, image_size, on_tpu, mfu_of, attn_impl,
                      input_gates)
        return
    if "--telemetry-ab" in sys.argv[1:]:
        _telemetry_ab(arch, image_size, on_tpu, attn_impl)
        return
    if "--spans-ab" in sys.argv[1:]:
        _spans_ab(arch, image_size, on_tpu, attn_impl)
        return
    if "--zero1-ab" in sys.argv[1:]:
        _zero1_ab(arch, image_size, on_tpu, attn_impl)
        return
    if "--fused-ab" in sys.argv[1:]:
        _fused_ab(arch, image_size, on_tpu, attn_impl)
        return
    if "--resident-ab" in sys.argv[1:]:
        _resident_ab(arch, image_size, on_tpu, attn_impl)
        return
    if "--augment-ab" in sys.argv[1:]:
        _augment_ab(arch, image_size, on_tpu, attn_impl)
        return
    if "--serve-ladder" in sys.argv[1:]:
        _serve_ladder(arch, image_size, on_tpu, attn_impl)
        return
    if "--wire-ladder" in sys.argv[1:]:
        _wire_ladder(arch, image_size, on_tpu, attn_impl)
        return

    value = best_throughput("tpu_first", half=True, fuse_views=True,
                            ema_update_mode="post", attn_impl=attn_impl)
    if value is None:
        # Checked BEFORE the baseline/bf16 ladders: their rungs are only
        # reported relative to a measured primary, and with a dead backend
        # (or a model that fits nowhere) each extra family would burn the
        # remaining tunnel window stepping down a ladder that cannot
        # change the outcome.
        if _backend_dead:
            raise RuntimeError(
                "backend became unavailable before the primary config "
                "measured any batch size — NOT a memory ceiling; re-run "
                f"when the backend is back (partial log in {_PARTIAL_PATH})")
        raise RuntimeError(
            "no batch size fit in memory for the primary config; "
            f"per-candidate tracebacks above, partial log in {_PARTIAL_PATH}")
    baseline = best_throughput("reference_faithful", half=False,
                               fuse_views=False,
                               ema_update_mode="reference_pre", steps=10,
                               attn_impl=attn_impl)
    # Middle rung: reference SEMANTICS (four forwards, pre-update EMA) at
    # bf16.  Separates what dtype buys from what the redesign buys:
    #   vs_baseline      = tpu_first / fp32-reference   (total win)
    #   bf16_ref/baseline = dtype alone
    #   tpu_first/bf16_ref = redesign alone (fuse_views + post-EMA)
    bf16_ref = best_throughput("reference_semantics_bf16", half=True,
                               fuse_views=False,
                               ema_update_mode="reference_pre", steps=10,
                               attn_impl=attn_impl)
    _print_headline(arch, value, baseline, bf16_ref, mfu_of)


def _prior_best_rungs() -> dict:
    """Best-known FITTING batch size per config name from the committed
    partial artifact (live file or its ``.prev`` backup), same device
    class only.  Must be called BEFORE the run's first ``_record`` (which
    rotates the live file to ``.prev``)."""
    best: dict = {}
    kind = jax.devices()[0].device_kind
    for path in (_PARTIAL_PATH + ".prev", _PARTIAL_PATH):   # live file wins
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        if d.get("device_kind") != kind:
            continue
        for r in d.get("results", []):
            if r.get("fit") and "images_per_sec_per_chip" in r:
                name = str(r.get("config", ""))
                cur = best.get(name)
                if cur is None or r["images_per_sec_per_chip"] > cur[0]:
                    best[name] = (r["images_per_sec_per_chip"],
                                  r["batch_per_chip"])
    return {k: v[1] for k, v in best.items()}


def _mvc(arch, image_size, candidates, on_tpu, mfu_of, attn_impl):
    """Minimum-viable capture (``--mvc``): convert a SHORT tunnel window
    into a fresh, non-stale headline plus the one sweep row four rounds
    of outages never landed (the rematted bs512 family).

    BENCH_r01–r04 all shipped ``"stale": true`` because the monolithic
    capture pipeline needed tens of minutes of continuous tunnel uptime,
    while the windows the tunnel actually offers can be far shorter.
    This mode measures ONE rung per headline family — the best
    known-fitting rung from the committed partial when available, else
    the historically-fitting default — with a single step-down fallback
    and few timing steps: minutes of tunnel time, not tens.  It prints
    the same headline JSON line as the default mode (measured fresh, so
    never "stale"), and records the rematted row under the
    ``sweep_bs*_remat1_fuse1`` naming contract so a later full
    ``--sweep`` reuses it instead of re-measuring
    (see ``_sweep_prior_rows``)."""
    prior = _prior_best_rungs() if on_tpu else {}
    top = max(candidates)

    def rungs_for(name, defaults):
        lst = ([prior[name]] if name in prior else [])
        lst += [d for d in defaults if d not in lst]
        lst = [b for b in lst if b <= top]
        return (lst or list(candidates))[:2]    # known-good + one fallback

    def fam(name, defaults, *, steps, **kw):
        for bs in rungs_for(name, defaults):
            if _backend_dead:
                return None
            try:
                val = _throughput(bs, image_size, arch, steps=steps,
                                  attn_impl=attn_impl, **kw)
            except Exception as e:
                if _config_failed(f"mvc {name} bs={bs}", e):
                    return None
                _record(name, batch_per_chip=bs, fit=False,
                        error=repr(e)[:300])
                continue
            _record(name, batch_per_chip=bs, fit=True,
                    images_per_sec_per_chip=round(val, 2), mfu=mfu_of(val),
                    **_row_stats(val), **kw)
            return val                   # MVC: first fitting rung only
        return None

    value = fam("tpu_first", [256, 128], steps=10, half=True,
                fuse_views=True, ema_update_mode="post")
    if value is None:
        if _backend_dead:
            raise RuntimeError(
                "mvc: backend became unavailable before the primary config "
                f"measured — re-run when it is back (log in {_PARTIAL_PATH})")
        raise RuntimeError(
            f"mvc: no rung fit for the primary config ({_PARTIAL_PATH})")
    baseline = fam("reference_faithful", [128, 64], steps=5, half=False,
                   fuse_views=False, ema_update_mode="reference_pre")
    bf16_ref = fam("reference_semantics_bf16", [256, 128], steps=5,
                   half=True, fuse_views=False,
                   ema_update_mode="reference_pre")
    # The one sweep row no round has landed: rematted bs512 — the stated
    # hypothesis for the un-rematted bs512 spill (RESULTS.md §1).
    remat_bs = 512 if top >= 512 else top
    name = f"sweep_bs{remat_bs}_remat1_fuse1"
    if not _backend_dead:
        try:
            val = _throughput(remat_bs, image_size, arch, steps=10,
                              half=True, fuse_views=True, remat=True,
                              ema_update_mode="post", attn_impl=attn_impl)
            _record(name, fit=True, batch_per_chip=remat_bs, remat=True,
                    fuse_views=True,
                    images_per_sec_per_chip=round(val, 2), mfu=mfu_of(val),
                    **_row_stats(val))
        except Exception as e:
            if not _config_failed(f"mvc {name}", e):
                _record(name, batch_per_chip=remat_bs, fit=False,
                        error=repr(e)[:300])
    _print_headline(arch, value, baseline, bf16_ref, mfu_of,
                    note="minimum-viable capture (--mvc): one rung per "
                         "family")


def _print_headline(arch, value, baseline, bf16_ref, mfu_of, note=None):
    """The one headline JSON line — shared by the default mode and --mvc
    so the output contract can never diverge between them (downstream
    round tooling parses these lines)."""
    mfu = mfu_of(value)
    out = {
        "metric": f"{arch}_byol_train_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": (round(value / baseline, 3)
                        if baseline is not None else None),
        "mfu": round(mfu, 4) if mfu is not None else None,
    }
    if note:
        out["note"] = note
    if bf16_ref is not None:
        out["bf16_reference_semantics"] = round(bf16_ref, 2)
        if baseline is not None:
            out["dtype_gain"] = round(bf16_ref / baseline, 3)
        out["redesign_gain"] = round(value / bf16_ref, 3)
    print(_json_line(out))


def _profile(arch, image_size, candidates, logdir):
    """Capture a jax.profiler trace of a few steady-state headline-config
    steps (TensorBoard profile plugin / Perfetto readable) — the tuning
    input for the MFU push (RESULTS.md §1).

    Like ``best_throughput``, the FASTEST of the top two fitting rungs is
    the one traced — the largest fitting batch can be the slower, spilling
    one, and a trace of the degraded config would misdirect the tuning.
    Rungs are measured one at a time with nothing retained (holding rung
    A's buffers while building rung B would change B's memory picture);
    the winner is rebuilt for the trace (compile is cached)."""
    rates = []                                  # (rate, bs)
    for bs in candidates:
        if _known_oom(bs, arch, image_size):
            continue
        try:
            rates.append((_throughput(bs, image_size, arch, half=True,
                                      fuse_views=True,
                                      ema_update_mode="post", steps=5), bs))
        except Exception as e:
            if _config_failed(f"profile bs={bs}", e):
                raise SystemExit(
                    "bench: backend died during --profile; nothing to trace")
            continue
        if len(rates) >= 2:
            break
    if not rates:
        raise RuntimeError("no batch size fit for profiling")
    bs = max(rates)[1]
    state, train_step, batch, _ = _build(bs, image_size, arch, half=True,
                                         fuse_views=True,
                                         ema_update_mode="post")
    for _ in range(3):                          # compile (cached) + warm
        state, metrics = train_step(state, batch)
    float(metrics["loss_mean"])
    jax.profiler.start_trace(logdir)
    for _ in range(5):
        state, metrics = train_step(state, batch)
    float(metrics["loss_mean"])                 # readback inside the trace
    jax.profiler.stop_trace()
    print(_json_line({"metric": "profile", "value": bs,
                      "unit": "batch/chip", "vs_baseline": None,
                      "logdir": logdir}))


def _data_pipeline_bench():
    """Host data-layer throughput: tf.data vs the native C++ backend.

    Quantifies the DALI-analog claim (SURVEY §2.4: NVIDIA DALI ->
    tf.data / custom C++ host pipeline): images/sec of fully-augmented
    two-view batches produced per host, measured through the real loader
    path (``get_loader`` -> per-epoch iterators).  Pure host work — runs
    identically with or without an accelerator attached.
    """
    # Host-only measurement, but the loader touches jax (process_index for
    # per-host sharding) — pin the cpu platform so a wedged TPU tunnel can
    # never hang what is advertised as a pure-host benchmark.
    jax.config.update("jax_platforms", "cpu")

    from byol_tpu.core.config import Config, DeviceConfig, TaskConfig
    from byol_tpu.data import native_aug
    from byol_tpu.data.loader import get_loader

    size, bs, n = 96, 256, 2048
    backends = ["tf"] + (["native"] if native_aug.available() else [])
    rates = {}
    for backend in backends:
        cfg = Config(
            task=TaskConfig(task="synth", batch_size=bs, epochs=1,
                            image_size_override=size, data_backend=backend),
            device=DeviceConfig(num_replicas=1, seed=0))
        bundle = get_loader(cfg, num_synth_samples=n)
        for _ in bundle.train_loader:          # warm: thread pools, tf graph
            pass                               # (streaming: one batch live)
        epochs = 3
        t0 = time.perf_counter()
        batches = 0
        for e in range(epochs):
            bundle.set_all_epochs(e)
            for _ in bundle.train_loader:
                batches += 1
        dt = time.perf_counter() - t0
        rates[backend] = bs * batches / dt
        print(f"bench: data backend {backend}: {rates[backend]:.1f} img/s "
              f"(two-view {size}px batches, {batches} batches)",
              file=sys.stderr)
    if "native" not in rates:
        print("bench: native C++ backend unavailable (no toolchain/.so); "
              "reporting tf only", file=sys.stderr)

    # --data-threads 1,2,4,8: measure the native pipeline's thread-scaling
    # curve over the JPEG tree.  The RESULTS §1 feeding math (66.3
    # img/s/core x host cores >= chip demand) was a 1-core extrapolation;
    # this turns it into measurement on the first multi-core host (TPU
    # hosts have 24+ vCPU/chip).  nproc is recorded with the curve so an
    # oversubscribed 1-core run can't masquerade as real scaling.
    threads = None
    if "--data-threads" in sys.argv[1:]:
        i = sys.argv.index("--data-threads") + 1
        if i >= len(sys.argv):
            raise SystemExit("usage: bench.py --data --data-threads 1,2,4,8")
        try:
            threads = [int(t) for t in sys.argv[i].split(",")]
            if not threads or any(t < 1 for t in threads):
                raise ValueError
        except ValueError:
            raise SystemExit("usage: bench.py --data --data-threads 1,2,4,8")

    try:
        jpeg_rates = _jpeg_tree_bench(threads=threads)
    except Exception as e:     # degrade, never discard the measured rates
        print(f"bench: jpeg_224 stage failed ({e!r}); array rates stand",
              file=sys.stderr)
        jpeg_rates = None

    primary = rates.get("native", rates["tf"])
    print(_json_line({
        "metric": "host_data_pipeline_images_per_sec",
        "value": round(primary, 1),
        "unit": "images/sec/host",
        "vs_baseline": (round(rates["native"] / rates["tf"], 3)
                        if "native" in rates else None),
        "note": "two-view augmented batches; vs_baseline = native/tf",
        "jpeg_224": jpeg_rates,
    }))


def _jpeg_tree_bench(threads=None):
    """224px fused-JPEG-decode ladder over an on-disk ImageFolder tree —
    the configuration the DALI analog exists for (reference main.py:356-382
    serves ImageNet JPEG trees).  Synthetic ~500x375 JPEGs with smooth
    content so compression ratio and decode cost look like photographs,
    not noise.  Reports img/s per host for the tf fused-decode path and the
    native libjpeg fused decode+crop path, plus the per-core rate (this box
    has few cores; TPU pod hosts have 100+ — the per-core number is what
    scales).

    ``threads``: optional list of worker counts; the native path is then
    re-measured at each count and the curve reported under
    ``native_thread_curve`` (with ``cores`` = nproc alongside, so the
    reader can tell real scaling from oversubscription)."""
    import os
    import shutil
    import tempfile

    from byol_tpu.core.config import Config, DeviceConfig, TaskConfig
    from byol_tpu.data import native_aug
    from byol_tpu.data.loader import get_loader

    try:
        from PIL import Image
    except ImportError:
        print("bench: PIL unavailable; skipping jpeg_224 stage",
              file=sys.stderr)
        return None

    root = tempfile.mkdtemp(prefix="byol_jpeg_bench_")
    rng = np.random.RandomState(0)
    n_imgs, hw = 256, (375, 500)
    try:
        for split, n in (("train", n_imgs), ("test", 8)):
            for cls in ("a", "b"):
                d = os.path.join(root, split, cls)
                os.makedirs(d)
                for i in range(n // 2):
                    # low-frequency content: upsampled 12x16 noise ->
                    # photograph-like JPEG entropy (~100 KB at q87)
                    low = rng.randint(0, 255, (12, 16, 3), np.uint8)
                    img = Image.fromarray(low).resize(
                        (hw[1], hw[0]), Image.BILINEAR)
                    img.save(os.path.join(d, f"{i}.jpg"), quality=87)
        backends = ["tf"] + (["native"] if native_aug.available()
                             and native_aug.has_jpeg() else [])
        out = {}
        bs = 64

        def measure(backend, workers):
            cfg = Config(
                task=TaskConfig(task="image_folder", data_dir=root,
                                batch_size=bs, epochs=1,
                                image_size_override=224,
                                data_backend=backend),
                device=DeviceConfig(num_replicas=1, seed=0,
                                    workers_per_replica=workers))
            bundle = get_loader(cfg)
            for _ in bundle.train_loader:      # warm: tf graph/thread pools
                pass
            t0 = time.perf_counter()
            batches = 0
            for e in range(2):
                bundle.set_all_epochs(e)
                for _ in bundle.train_loader:
                    batches += 1
            dt = time.perf_counter() - t0
            return bs * batches / dt, batches

        default_workers = min(os.cpu_count() or 1, 16)
        for backend in backends:
            rate, batches = measure(backend, default_workers)
            out[backend] = round(rate, 1)
            print(f"bench: jpeg_224 backend {backend}: {rate:.1f} img/s "
                  f"({rate / (os.cpu_count() or 1):.1f} img/s/core, "
                  f"{batches} two-view batches)", file=sys.stderr)
        if threads and "native" in out:
            curve = {}
            for t in threads:
                rate, _ = measure("native", t)
                curve[str(t)] = round(rate, 1)
                print(f"bench: jpeg_224 native @{t} threads: "
                      f"{rate:.1f} img/s", file=sys.stderr)
            out["native_thread_curve"] = curve
        out["cores"] = os.cpu_count() or 1
        out["note"] = ("fused decode+crop, two 224px views/img; scale by "
                       "host cores vs the chip's img/s consumption")
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _int_flag(name: str, default: int) -> int:
    if name in sys.argv[1:]:
        i = sys.argv.index(name) + 1
        if i >= len(sys.argv):
            raise SystemExit(f"usage: bench.py ... {name} <value>")
        return int(sys.argv[i])
    return default


def _str_flag(name: str, default: str) -> str:
    if name in sys.argv[1:]:
        i = sys.argv.index(name) + 1
        if i >= len(sys.argv):
            raise SystemExit(f"usage: bench.py ... {name} <value>")
        return sys.argv[i]
    return default


_V5E_HBM_BYTES = 16 * 2 ** 30            # the budget the ladder reports against


def _abstract_batch(batch_size: int, image_size: int, mesh,
                    augment_placement: str = "loader"):
    """ShapeDtypeStruct batch for compile-only paths: lowering needs shapes
    and shardings, not 5 GB of host random pixels."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from byol_tpu.parallel.mesh import DATA_AXIS
    sh = NamedSharding(mesh, P(DATA_AXIS))
    b = batch_size
    if augment_placement == "step":
        return {
            "images": jax.ShapeDtypeStruct((b, image_size, image_size, 3),
                                           np.uint8, sharding=sh),
            "label": jax.ShapeDtypeStruct((b,), np.int32, sharding=sh),
        }
    return {
        "view1": jax.ShapeDtypeStruct((b, image_size, image_size, 3),
                                      np.float32, sharding=sh),
        "view2": jax.ShapeDtypeStruct((b, image_size, image_size, 3),
                                      np.float32, sharding=sh),
        "label": jax.ShapeDtypeStruct((b,), np.int32, sharding=sh),
    }


def _dry_compile(arch, image_size, on_tpu, attn_impl):
    """AOT-compile ONE accumulation config and report memory_analysis()
    without executing a step (``--dry-compile``).

    Defaults to the paper-scale target: effective 4096 per chip at the
    measured-optimal microbatch 256 (accum_steps 16) with the 'dots'
    selective policy.  Prints one JSON line with compile_seconds + the HBM
    high-water mark and whether it clears the v5e 16 GiB budget.  Also the
    killable subprocess body behind the accumulation ladder's compile-
    timeout gate (a wedged XLA compile dies with the subprocess — the
    45-minute full-remat lesson).
    """
    eff = _int_flag("--effective-batch", 4096 if on_tpu else 64)
    mb = _int_flag("--microbatch", 256 if on_tpu else 16)
    policy = _str_flag("--remat-policy", "dots")
    bn_mode = _str_flag("--accum-bn-mode", "average")
    placement = _str_flag("--augment-placement", "loader")
    from byol_tpu.core.remat import validate_policy
    validate_policy(policy)                  # fail fast on typos
    if placement not in ("loader", "step"):
        raise SystemExit(
            "usage: bench.py ... --augment-placement loader|step")
    if eff % mb:
        raise SystemExit(
            f"bench: effective batch {eff} not divisible by microbatch {mb}")
    accum = eff // mb
    # Same wiring as every measured rung (_build), but against an ABSTRACT
    # batch (shapes + shardings): the compile-only path must not allocate
    # effective-4096 of host pixels — and sharing _build keeps the gate's
    # config from drifting away from the config the ladder then measures.
    state, train_step, batch, mesh = _build(
        eff, image_size, arch, half=True, fuse_views=True,
        ema_update_mode="post", attn_impl=attn_impl, accum_steps=accum,
        accum_bn_mode=bn_mode, remat_policy=policy,
        augment_placement=placement, materialize_batch=False)
    compiled, stats = _aot_compile(train_step, state, batch, mesh)
    del compiled
    hbm = stats.get("hbm_high_water_bytes")
    print(_json_line({
        "metric": "dry_compile_hbm_high_water_bytes",
        "value": hbm,
        "unit": "bytes",
        "vs_baseline": None,
        "arch": arch, "image_size": image_size,
        "effective_batch_per_chip": eff,
        "microbatch_per_chip": mb,
        "accum_steps": accum,
        "remat_policy": policy,
        "accum_bn_mode": bn_mode,
        "augment_placement": placement,
        "device_kind": jax.devices()[0].device_kind,
        "under_v5e_16gib": (None if hbm is None
                            else bool(hbm < _V5E_HBM_BYTES)),
        **stats,
    }))


def _accum_flags(on_tpu):
    """Shared knob parsing for the accumulation ladder and its gate phase
    (one source of truth: the gate children must compile exactly the rungs
    the ladder then measures)."""
    mb = _int_flag("--microbatch", 256 if on_tpu else 16)
    policy = _str_flag("--remat-policy", "dots")
    bn_mode = _str_flag("--accum-bn-mode", "average")
    timeout = _int_flag("--compile-timeout", 900)
    from byol_tpu.core.remat import validate_policy
    validate_policy(policy)
    # CPU fallback: ONE tiny rung — liveness, not measurement (a CPU "chip"
    # sustains ~1 img/s on this model; a second rung would run for minutes).
    effectives = [512, 1024, 4096] if on_tpu else [32]
    return mb, policy, bn_mode, timeout, effectives


def _probe_backend_is_accel(timeout_s: float = 180.0) -> bool:
    """Is the default backend an accelerator — answered WITHOUT creating
    the in-process client.  ``jax.default_backend()`` would claim the
    single-client TPU for this process, and the accum-ladder gate children
    must still be able to claim it after this returns."""
    import subprocess
    if str(jax.config.jax_platforms or "") == "cpu":
        return False
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return False
    if probe.returncode != 0:
        return False
    out = probe.stdout.strip().splitlines()
    return bool(out) and out[-1] != "cpu"


def _run_compile_gates(rungs, timeout):
    """Run each rung's ``--dry-compile`` gate in a killable subprocess
    BEFORE the parent initializes its own backend client.

    Ordering is load-bearing on TPU: the backend is single-process-
    exclusive (a second client hangs in backend init while any process
    holds the chip — see the tpu_watch notes), so a gate child spawned
    after the parent's client exists would hang until the timeout and
    every rung would record a spurious wedged-compile signature.  Children
    run strictly before and sequentially, each releasing the chip on exit
    and leaving its compile in the persistent cache, which makes the
    parent's measurement compile nearly free.

    ``rungs``: ``[(rung_name, extra_dry_compile_argv)]``.  Returns
    ``{rung_name: {"status": "ok"|"timeout"|"error", ...}}`` for the
    ladder to consume after the parent initializes.
    """
    import subprocess
    gates = {}
    for name, extra in rungs:
        gate_cmd = [sys.executable, os.path.abspath(__file__),
                    "--dry-compile"] + extra
        try:
            gate = subprocess.run(gate_cmd, timeout=timeout,
                                  capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            gates[name] = {"status": "timeout", "timeout": timeout}
            print(f"bench: {name}: compile gate timed out after {timeout}s",
                  file=sys.stderr)
            continue
        if gate.returncode != 0:
            gates[name] = {"status": "error",
                           "err": (gate.stderr or "").strip()[-300:]}
            continue
        try:
            row = json.loads(gate.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            row = {}
        gates[name] = {"status": "ok", "row": row}
    return gates


def _gate_args(eff, mb, policy, bn_mode, attn_impl, arch_override,
               placement="loader"):
    """argv for one --dry-compile gate child; the gate must compile the
    SAME model the ladder measures (an un-forwarded --arch would
    wedge-protect the default arch while the parent compiled the
    overridden one unprotected)."""
    extra = ["--effective-batch", str(eff), "--microbatch", str(mb),
             "--remat-policy", policy, "--accum-bn-mode", bn_mode,
             "--attn", attn_impl, "--augment-placement", placement]
    if arch_override:
        extra += ["--arch", arch_override]
    return extra


def _accum_gate_phase(on_tpu, arch_override, attn_impl):
    """Compile gates for the accumulation ladder (see _run_compile_gates)."""
    mb, policy, bn_mode, timeout, effectives = _accum_flags(on_tpu)
    rungs = [(f"accum_eff{eff}_mb{mb}_{policy}",
              _gate_args(eff, mb, policy, bn_mode, attn_impl, arch_override))
             for eff in effectives]
    return _run_compile_gates(rungs, timeout)


def _input_gate_phase(on_tpu, arch_override, attn_impl):
    """Compile gates for the input-pipeline ladder: BOTH placements per
    effective-batch rung (loader-aug float32 views vs step-aug uint8)."""
    mb, policy, bn_mode, timeout, effectives = _accum_flags(on_tpu)
    rungs = [(f"input_eff{eff}_mb{mb}_{placement}",
              _gate_args(eff, mb, policy, bn_mode, attn_impl, arch_override,
                         placement))
             for eff in effectives
             for placement in ("loader", "step")]
    return _run_compile_gates(rungs, timeout)


def _accum_ladder(arch, image_size, on_tpu, mfu_of, attn_impl, gates):
    """Accumulation ladder (``--accum-ladder``): effective batch
    512/1024/4096 per chip, ALL at the per-chip-optimal microbatch 256
    (RESULTS.md §1: bs256 is the throughput peak; bs512 spills; bs1024
    OOMs un-rematted).

    Every rung's compile already ran in a killable subprocess
    (``--dry-compile`` body, :func:`_accum_gate_phase`, BEFORE this
    process claimed the backend) under ``--compile-timeout`` seconds —
    the compile-timeout gate: a wedged XLA compile (the 45-minute
    full-remat incident) is killed without taking the process or the
    remote-compile service down, and the rung records ``fit=False`` with
    a timeout signature.  On a clean gate pass this function measures
    throughput in-process; the persistent compile cache makes the second
    compile nearly free.  Rows record compile_seconds,
    hbm_high_water_bytes, and img/s/chip.
    """
    mb, policy, bn_mode, timeout, effectives = _accum_flags(on_tpu)
    timing_steps = 10 if on_tpu else 3
    rungs = []
    for eff in effectives:
        if _backend_dead:
            break
        accum = eff // mb
        name = f"accum_eff{eff}_mb{mb}_{policy}"
        gate = gates.get(name) or {"status": "error",
                                   "err": "no gate result for this rung"}
        if gate["status"] == "timeout":
            _record(name, fit=False, effective_batch_per_chip=eff,
                    microbatch_per_chip=mb, accum_steps=accum,
                    remat_policy=policy,
                    error=f"compile-timeout gate: exceeded {timeout}s "
                          "(wedged-compile signature; subprocess killed)")
            continue
        if gate["status"] == "error":
            err = gate["err"]
            if _config_failed(f"accum gate {name}", RuntimeError(err)):
                break
            _record(name, fit=False, effective_batch_per_chip=eff,
                    microbatch_per_chip=mb, accum_steps=accum,
                    remat_policy=policy, error=f"gate subprocess: {err}")
            continue
        gate_row = gate.get("row", {})
        try:
            val = _throughput(eff, image_size, arch, half=True,
                              fuse_views=True, ema_update_mode="post",
                              attn_impl=attn_impl, accum_steps=accum,
                              accum_bn_mode=bn_mode, remat_policy=policy,
                              steps=timing_steps)
        except Exception as e:
            if _config_failed(f"accum ladder {name}", e):
                break
            _record(name, fit=False, effective_batch_per_chip=eff,
                    microbatch_per_chip=mb, accum_steps=accum,
                    remat_policy=policy, error=repr(e)[:300],
                    gate_hbm_high_water_bytes=gate_row.get(
                        "hbm_high_water_bytes"))
            continue
        row = {"effective_batch_per_chip": eff, "microbatch_per_chip": mb,
               "accum_steps": accum, "remat_policy": policy,
               "accum_bn_mode": bn_mode,
               "images_per_sec_per_chip": round(val, 2),
               "mfu": mfu_of(val), **_row_stats(val)}
        if "hbm_high_water_bytes" not in row and gate_row:
            row["hbm_high_water_bytes"] = gate_row.get(
                "hbm_high_water_bytes")
        rungs.append(row)
        _record(name, fit=True, **row)
        print(f"bench: {name}: {float(val):.1f} img/s/chip "
              f"compile={row.get('compile_seconds')}s "
              f"hbm={row.get('hbm_high_water_bytes')}", file=sys.stderr)
    print(_json_line({"metric": "accum_ladder", "value": len(rungs),
                      "unit": "rungs", "vs_baseline": None,
                      "microbatch_per_chip": mb, "remat_policy": policy,
                      "rungs": rungs,
                      "complete": not _backend_dead}))
    if _backend_dead:
        raise SystemExit(3)   # same truncation contract as --sweep


def _input_ladder(arch, image_size, on_tpu, mfu_of, attn_impl, gates):
    """Input-pipeline ladder (``--input-ladder``): loader-placement
    (two float32 views shipped from the host) vs step-placement (raw uint8
    shipped, views materialized per microbatch inside the accumulation
    scan) at effective 512/1024/4096 per chip @ microbatch 256 — the
    augment-placement A/B ISSUE 3 exists for.

    Every row records ``h2d_bytes_per_step`` (the ~8x payload difference),
    ``hbm_high_water_bytes`` (step placement must be strictly lower: only
    one microbatch of views is ever live), ``compile_seconds`` and
    img/s/chip.  Same killable-subprocess compile gating as the
    accumulation ladder (:func:`_input_gate_phase` ran BEFORE this process
    claimed the backend).
    """
    mb, policy, bn_mode, timeout, effectives = _accum_flags(on_tpu)
    timing_steps = 10 if on_tpu else 3
    rungs = []
    grid = [(eff, placement) for eff in effectives
            for placement in ("loader", "step")]
    for eff, placement in grid:
        if _backend_dead:
            break
        accum = eff // mb
        name = f"input_eff{eff}_mb{mb}_{placement}"
        tags = {"effective_batch_per_chip": eff, "microbatch_per_chip": mb,
                "accum_steps": accum, "remat_policy": policy,
                "augment_placement": placement}
        gate = gates.get(name) or {"status": "error",
                                   "err": "no gate result for this rung"}
        if gate["status"] == "timeout":
            _record(name, fit=False, **tags,
                    error=f"compile-timeout gate: exceeded {timeout}s "
                          "(wedged-compile signature; subprocess killed)")
            continue
        if gate["status"] == "error":
            err = gate["err"]
            if _config_failed(f"input gate {name}", RuntimeError(err)):
                break
            _record(name, fit=False, **tags,
                    error=f"gate subprocess: {err}")
            continue
        gate_row = gate.get("row", {})
        try:
            val = _throughput(eff, image_size, arch, half=True,
                              fuse_views=True, ema_update_mode="post",
                              attn_impl=attn_impl, accum_steps=accum,
                              accum_bn_mode=bn_mode, remat_policy=policy,
                              augment_placement=placement,
                              steps=timing_steps)
        except Exception as e:
            if _config_failed(f"input ladder {name}", e):
                break
            _record(name, fit=False, **tags, error=repr(e)[:300],
                    gate_hbm_high_water_bytes=gate_row.get(
                        "hbm_high_water_bytes"))
            continue
        row = {**tags, "accum_bn_mode": bn_mode,
               "images_per_sec_per_chip": round(val, 2),
               "mfu": mfu_of(val), **_row_stats(val)}
        if "hbm_high_water_bytes" not in row and gate_row:
            row["hbm_high_water_bytes"] = gate_row.get(
                "hbm_high_water_bytes")
        rungs.append(row)
        _record(name, fit=True, **row)
        print(f"bench: {name}: {float(val):.1f} img/s/chip "
              f"h2d={row.get('h2d_bytes_per_step')} "
              f"hbm={row.get('hbm_high_water_bytes')}", file=sys.stderr)
    print(_json_line({"metric": "input_ladder", "value": len(rungs),
                      "unit": "rungs", "vs_baseline": None,
                      "microbatch_per_chip": mb, "remat_policy": policy,
                      "rungs": rungs,
                      "complete": not _backend_dead}))
    if _backend_dead:
        raise SystemExit(3)   # same truncation contract as --sweep


def _telemetry_ab(arch, image_size, on_tpu, attn_impl):
    """Telemetry-overhead A/B (``--telemetry-ab``): the SAME config measured
    with ``telemetry='off'`` (the exact pre-telemetry graph — pinned by the
    HLO-identity test) and ``telemetry='step'`` with the TelemetrySink
    polling at ``--telemetry-interval`` (default 50) in the timing loop —
    i.e. the FULL observation cost: the in-graph health reductions plus the
    sink's lagged explicit device_get.  Prints one JSON line with both
    rates and ``overhead_pct``; the acceptance budget is < 2%.
    """
    from byol_tpu.observability.telemetry import TelemetrySink
    interval = _int_flag("--telemetry-interval", 50)
    # CPU rung: smallest batch that still pays >= one interval-50 sink
    # readback in the timing loop — the 1-core box sustains ~0.5 step/s on
    # the fallback model, so 55 steps x 2 arms is minutes, not tens
    bs = 256 if on_tpu else 16
    steps = 120 if on_tpu else 55
    rates = {}
    for mode in ("off", "step"):
        state, train_step, batch, mesh = _build(
            bs, image_size, arch, half=on_tpu, fuse_views=True,
            ema_update_mode="post", attn_impl=attn_impl, telemetry=mode)
        compiled, stats = _aot_compile(train_step, state, batch, mesh)
        sink = (TelemetrySink(interval, nan_policy="warn", verbose=False)
                if mode == "step" else None)
        for _ in range(3):                       # warm; sync via readback
            state, metrics = compiled(state, batch)
        float(metrics["loss_mean"])
        t0 = time.perf_counter()
        for i in range(steps):
            state, metrics = compiled(state, batch)
            if sink is not None:
                sink.offer(i + 1, metrics["health"])
        if sink is not None:
            sink.drain()
        float(metrics["loss_mean"])
        dt = time.perf_counter() - t0
        n_dev = len(jax.devices())
        rates[mode] = batch["label"].shape[0] * steps / dt / n_dev
        _record(f"telemetry_{mode}", fit=True, batch_per_chip=bs,
                telemetry=mode,
                telemetry_interval=interval if mode == "step" else None,
                images_per_sec_per_chip=round(rates[mode], 2), **stats)
        print(f"bench: telemetry_{mode}: {rates[mode]:.1f} img/s/chip",
              file=sys.stderr)
    overhead = 1.0 - rates["step"] / rates["off"]
    print(_json_line({
        "metric": "telemetry_step_overhead_pct",
        "value": round(100.0 * overhead, 2),
        "unit": "%",
        "vs_baseline": None,
        "off_images_per_sec_per_chip": round(rates["off"], 2),
        "step_images_per_sec_per_chip": round(rates["step"], 2),
        "telemetry_interval": interval,
        "batch_per_chip": bs, "arch": arch, "image_size": image_size,
        "timing_steps": steps,
        "device_kind": jax.devices()[0].device_kind,
    }))


def _spans_ab(arch, image_size, on_tpu, attn_impl):
    """Flight-recorder overhead A/B (``--spans-ab``): ONE compiled
    executable, timed with the spans-off path (the shared no-op
    :data:`spans.NULL` returned by ``--spans off``, which records
    NOTHING) and with a live :class:`spans.SpanRecorder` wrapping every
    step dispatch plus the closing readback — exactly the trainer's
    hot-loop instrumentation.  Spans are host-side only, so both arms can
    (and must) run the IDENTICAL program: the arms are INTERLEAVED across
    reps and compared by median, because on a noisy shared box the
    build-to-build / minute-to-minute drift is several percent — an order
    of magnitude above the span cost under measurement.  Prints one JSON
    line with both median rates and ``overhead_pct``; the acceptance
    budget is < 2% (the telemetry bar).

    The spans arm additionally exercises the whole downstream pipeline on
    real measurements: a goodput fold into ``bench_events.jsonl``
    (``goodput`` + ``span_stats`` events) and a Chrome-trace export to
    ``bench_trace.json`` — so the capture CI validates the full
    span -> goodput -> trace path, not just the timer deltas.
    """
    from byol_tpu.observability import goodput as goodput_lib
    from byol_tpu.observability import spans as spans_lib
    bs = 256 if on_tpu else 16
    steps = 30 if on_tpu else 15       # per rep; 4 interleaved reps/arm
    reps = 4
    # ONE build, ONE executable for BOTH arms: spans are host-side only —
    # unlike telemetry they change nothing in the graph — so the honest
    # A/B times the IDENTICAL program and varies only the recorder.
    # Interleaved reps (off, on, off, on, ...) with a median across reps
    # cancel the box's slow drift (page cache, thermals, neighbors): a
    # sequential two-arm design on this class of box shows arm-to-arm
    # deltas of several percent from drift alone, an order of magnitude
    # above the span cost it is trying to measure.
    state, train_step, batch, mesh = _build(
        bs, image_size, arch, half=on_tpu, fuse_views=True,
        ema_update_mode="post", attn_impl=attn_impl)
    compiled, stats = _aot_compile(train_step, state, batch, mesh)
    recorder = spans_lib.SpanRecorder()
    recorders = {"off": spans_lib.NULL, "on": recorder}
    n_dev = len(jax.devices())
    for _ in range(3):                       # warm; sync via readback
        state, metrics = compiled(state, batch)
    float(metrics["loss_mean"])
    rates = {"off": [], "on": []}
    on_wall = 0.0                # ONLY the on-arm windows: the goodput
    for _ in range(reps):        # payload must not attribute warmup/off
        for mode in ("off", "on"):   # time it never observed
            rec = recorders[mode]
            t0 = time.perf_counter()
            for _ in range(steps):
                with rec.span("train/dispatch"):
                    state, metrics = compiled(state, batch)
            with rec.span("train/epoch_readback"):
                float(metrics["loss_mean"])
            dt = time.perf_counter() - t0
            if mode == "on":
                on_wall += dt
            rates[mode].append(batch["label"].shape[0] * steps / dt
                               / n_dev)
    # falsifiable spans-off pin: the off arm's span() must be the ONE
    # shared no-op object (zero allocation, nothing recorded by
    # construction — asserting NULL.records()==[] would be vacuous)
    assert (recorders["off"].span("train/dispatch")
            is recorders["off"].span("train/epoch_readback")), \
        "the spans-off path must hand back the shared no-op span"
    assert len(recorder.records()) == reps * (steps + 1), \
        "recorder must hold one span per dispatch + readback per rep"
    # goodput over the on-arm windows alone (attribute() keeps the
    # partition identity exact against their summed wall)
    wall, productive, badput = goodput_lib.attribute(recorder.records(),
                                                     on_wall)
    payload = {"scope": "epoch", "wall_seconds": wall,
               "productive_seconds": productive, "badput": badput,
               "goodput_fraction": (productive / wall if wall > 0
                                    else 0.0),
               "label": "spans_ab", "timing_steps": reps * steps}
    if _events is not None:
        _events.emit("goodput", **payload)
        _events.emit("span_stats", scope="epoch", label="spans_ab",
                     spans=goodput_lib.span_stats(recorder.records()))
    spans_lib.export_chrome_trace(recorder.records(), "bench_trace.json")
    print(f"bench: spans_on goodput {payload['goodput_fraction']:.3f} "
          f"(wall {payload['wall_seconds']:.2f}s over the on-arm "
          "windows); trace -> bench_trace.json", file=sys.stderr)
    med = {m: float(np.median(rs)) for m, rs in rates.items()}
    # The per-span PRIMITIVE cost, measured in-process on a fresh
    # recorder (so the ring/trace/goodput above stay clean): two
    # perf_counter reads + a TraceAnnotation enter/exit + a deque append.
    # This is the number a noisy box CAN resolve — wall-clock arm deltas
    # at the < 2% scale are swamped by the +/-20% rep-to-rep drift the
    # rep_rates columns document — and spans_per_step x span_cost /
    # step_time bounds the true overhead from the same run's
    # measurements.  (On stable-clock TPU silicon the wall-clock A/B is
    # the headline; there the rep spread collapses.)
    micro_rec = spans_lib.SpanRecorder()
    n_micro = 200_000
    t0 = time.perf_counter()
    for _ in range(n_micro):
        with micro_rec.span("micro/span"):
            pass
    span_cost_s = (time.perf_counter() - t0) / n_micro
    step_s = batch["label"].shape[0] / (med["off"] * n_dev)
    implied = span_cost_s / step_s       # 1 dispatch span per step
    for mode in ("off", "on"):
        _record(f"spans_{mode}", fit=True, batch_per_chip=bs, spans=mode,
                images_per_sec_per_chip=round(med[mode], 2),
                rep_rates=[round(r, 2) for r in rates[mode]],
                span_cost_us=round(span_cost_s * 1e6, 3), **stats)
        print(f"bench: spans_{mode}: {med[mode]:.2f} img/s/chip "
              f"(reps {[round(r, 2) for r in rates[mode]]})",
              file=sys.stderr)
    overhead = 1.0 - med["on"] / med["off"]
    print(_json_line({
        "metric": "spans_overhead_pct",
        "value": round(100.0 * overhead, 2),
        "unit": "%",
        "vs_baseline": None,
        "off_images_per_sec_per_chip": round(med["off"], 2),
        "on_images_per_sec_per_chip": round(med["on"], 2),
        "off_rep_rates": [round(r, 2) for r in rates["off"]],
        "on_rep_rates": [round(r, 2) for r in rates["on"]],
        "span_cost_us": round(span_cost_s * 1e6, 3),
        "step_seconds": round(step_s, 4),
        "implied_overhead_pct": round(100.0 * implied, 6),
        "batch_per_chip": bs, "arch": arch, "image_size": image_size,
        "timing_steps": steps, "reps": reps,
        "device_kind": jax.devices()[0].device_kind,
    }))


def _zero1_ab(arch, image_size, on_tpu, attn_impl):
    """ZeRO-1 A/B (``--zero1-ab``): the SAME accumulation config AOT-
    compiled twice — replicated (``--zero1 off``, the pre-plan graph) vs
    flat leaf-partitioned weight-update sharding (``--zero1 on``) — with
    no execution (the ``--dry-compile`` discipline: memory_analysis() is
    the deliverable, and the off-hardware CPU mesh can report it too).

    Per row: ``hbm_high_water_bytes`` (executable high-water) and
    ``optimizer_state_bytes`` — per-chip bytes of LARS momentum + the EMA
    target computed from the leaf SHARDINGS, the column that must scale
    ~1/N with mesh size when ZeRO-1 is doing its job.  The printed JSON
    line carries both rows plus the on/off ratio; expected ratio ~=
    (1/N + padding) with params replicated either way.
    """
    eff = _int_flag("--effective-batch", 4096 if on_tpu else 64)
    mb = _int_flag("--microbatch", 256 if on_tpu else 16)
    policy = _str_flag("--remat-policy", "dots")
    bn_mode = _str_flag("--accum-bn-mode", "average")
    from byol_tpu.core.remat import validate_policy
    validate_policy(policy)
    if eff % mb:
        raise SystemExit(
            f"bench: effective batch {eff} not divisible by microbatch {mb}")
    accum = eff // mb
    rows = {}
    for z in ("off", "on"):
        name = f"zero1_{z}"
        tags = {"zero1": z, "effective_batch_per_chip": eff,
                "microbatch_per_chip": mb, "accum_steps": accum,
                "remat_policy": policy, "accum_bn_mode": bn_mode,
                "n_devices": len(jax.devices())}
        try:
            # shares _build with every measured rung: the A/B's config
            # cannot drift from the config the ladders measure
            state, train_step, batch, mesh = _build(
                eff, image_size, arch, half=on_tpu, fuse_views=True,
                ema_update_mode="post", attn_impl=attn_impl,
                accum_steps=accum, accum_bn_mode=bn_mode,
                remat_policy=policy, zero1=z, materialize_batch=False)
            compiled, stats = _aot_compile(train_step, state, batch, mesh)
            del compiled, state, train_step
        except Exception as e:
            if _config_failed(f"zero1-ab arm {name}", e):
                break
            _record(name, fit=False, **tags, error=repr(e)[:300])
            continue
        rows[z] = {**tags, **stats}
        _record(name, fit=True, **rows[z])
        print(f"bench: {name}: opt_state={stats.get('optimizer_state_bytes')}"
              f" hbm={stats.get('hbm_high_water_bytes')} "
              f"compile={stats.get('compile_seconds')}s", file=sys.stderr)
    ratio = None
    if "off" in rows and "on" in rows:
        off_b = rows["off"].get("optimizer_state_bytes")
        on_b = rows["on"].get("optimizer_state_bytes")
        # _optimizer_state_bytes is best-effort (None on exotic states):
        # either arm missing the column degrades the ratio, not the run
        if off_b and on_b:
            ratio = round(on_b / off_b, 4)
    print(_json_line({
        "metric": "zero1_ab_optimizer_state_bytes",
        "value": rows.get("on", {}).get("optimizer_state_bytes"),
        "unit": "bytes/chip",
        "vs_baseline": ratio,       # on/off — ~1/N + padding
        "replicated_optimizer_state_bytes":
            rows.get("off", {}).get("optimizer_state_bytes"),
        "hbm_high_water_off": rows.get("off", {}).get(
            "hbm_high_water_bytes"),
        "hbm_high_water_on": rows.get("on", {}).get("hbm_high_water_bytes"),
        "n_devices": len(jax.devices()),
        "arch": arch, "image_size": image_size,
        "effective_batch_per_chip": eff, "microbatch_per_chip": mb,
        "accum_steps": accum, "remat_policy": policy,
        "device_kind": jax.devices()[0].device_kind,
    }))


def _fused_ab(arch, image_size, on_tpu, attn_impl):
    """Fused-update A/B (``--fused-ab``): the SAME config AOT-compiled
    with the optax chain (``--fused-update off``, the exact pre-fused
    graph — pinned by the HLO-identity test) and with the fused Pallas
    LARS+EMA kernel (``on``; ops/fused_update.py), each arm timed with a
    live :class:`spans.SpanRecorder` wrapping every step dispatch plus
    the closing readback — so the win is attributed in the same
    flight-recorder currency the trainer logs (wall rate + per-step
    dispatch-span stats into ``bench_events.jsonl``).

    Also records an IN-PROCESS kernel microbenchmark row: the bare weight
    update (optax chain + apply_updates + EMA tick vs the fused kernel)
    on a synthetic multi-leaf tree, timed on its own executable — the
    number that isolates the update from the forward/backward around it.
    NB on CPU the fused arm runs the kernel under the Pallas INTERPRETER
    (correctness-grade, not speed-grade — interpret mode dispatches one
    XLA op per kernel instruction): the CPU capture documents the
    mechanism and the event plumbing; the TPU row is where the HBM-sweep
    arithmetic pays.
    """
    import jax.numpy as jnp

    from byol_tpu.observability import goodput as goodput_lib
    from byol_tpu.observability import spans as spans_lib
    from byol_tpu.optim.factory import (MOMENTUM_DECAY, build_optimizer,
                                        extract_sgdm_state)
    from byol_tpu.ops import fused_update as fused_lib
    bs = 256 if on_tpu else 16
    steps = 60 if on_tpu else 30
    rates, span_p50 = {}, {}
    for mode in ("off", "on"):
        state, train_step, batch, mesh = _build(
            bs, image_size, arch, half=on_tpu, fuse_views=True,
            ema_update_mode="post", attn_impl=attn_impl, fused_update=mode)
        compiled, stats = _aot_compile(train_step, state, batch, mesh)
        recorder = spans_lib.SpanRecorder()
        for _ in range(3):                       # warm; sync via readback
            state, metrics = compiled(state, batch)
        float(metrics["loss_mean"])
        t0 = time.perf_counter()
        for _ in range(steps):
            with recorder.span("train/dispatch"):
                state, metrics = compiled(state, batch)
        with recorder.span("train/epoch_readback"):
            float(metrics["loss_mean"])
        dt = time.perf_counter() - t0
        n_dev = len(jax.devices())
        rates[mode] = batch["label"].shape[0] * steps / dt / n_dev
        sstats = goodput_lib.span_stats(recorder.records())
        span_p50[mode] = sstats.get("train/dispatch", {}).get("p50_ms")
        if _events is not None:
            _events.emit("span_stats", scope="epoch",
                         label=f"fused_{mode}", spans=sstats)
        _record(f"fused_{mode}", fit=True, batch_per_chip=bs,
                fused_update=mode,
                images_per_sec_per_chip=round(rates[mode], 2),
                dispatch_span_p50_ms=span_p50[mode], **stats)
        print(f"bench: fused_{mode}: {rates[mode]:.2f} img/s/chip "
              f"(dispatch p50 {span_p50[mode]}ms)", file=sys.stderr)

    # ---- in-process kernel microbenchmark ------------------------------
    # synthetic tree: a few conv-shaped kernels + 1-D bias/BN leaves, big
    # enough that per-dispatch overhead is not the whole measurement
    rng = np.random.default_rng(0)
    leaf_shapes = ([(3, 3, 256, 256)] * 4 + [(1024, 512), (512,), (256,)]
                   if on_tpu else
                   [(3, 3, 32, 64), (3, 3, 64, 64), (128, 256), (64,),
                    (256,)])
    params = {f"l{i}": jnp.asarray(rng.standard_normal(s) * 0.05,
                                   jnp.float32)
              for i, s in enumerate(leaf_shapes)}
    n_elems = sum(int(np.prod(s)) for s in leaf_shapes)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape) * 0.01,
                              jnp.float32), params)
    target = jax.tree_util.tree_map(lambda p: p * 0.9, params)
    wd = 1e-6
    tau = 0.99
    tx, sched = build_optimizer(
        "lars_momentum", base_lr=0.2, global_batch_size=4096,
        weight_decay=wd, total_units=100, warmup_units=10)
    opt_state = tx.init(params)
    trace, count = extract_sgdm_state(opt_state)
    lr = sched(count)

    @jax.jit
    def optax_update(g, st, p, t):
        u, st2 = tx.update(g, st, p)
        import optax as _optax
        p2 = _optax.apply_updates(p, u)
        t2 = jax.tree_util.tree_map(
            lambda tt, pp: tau * tt + (1 - tau) * pp, t, p2)
        return p2, st2, t2

    @jax.jit
    def fused(g, m, p, t):
        return fused_lib.fused_lars_ema_update(
            p, g, m, t, lr=lr, tau=tau, weight_decay=wd,
            momentum_decay=MOMENTUM_DECAY)

    def bench_fn(fn, args, reps=5, inner=3):
        out = fn(*args)                       # compile + warm
        jax.block_until_ready(out)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(inner):
                jax.block_until_ready(fn(*args))
            times.append((time.perf_counter() - t0) / inner)
        return float(np.median(times))

    t_optax = bench_fn(optax_update, (grads, opt_state, params, target))
    t_fused = bench_fn(fused, (grads, trace, params, target))
    row = {
        "params": n_elems,
        "optax_chain_us": round(t_optax * 1e6, 1),
        "fused_kernel_us": round(t_fused * 1e6, 1),
        "fused_speedup": round(t_optax / t_fused, 3),
        "interpret_mode": not on_tpu,
    }
    _record("fused_microbench", fit=True, **row)
    overhead = 1.0 - rates["on"] / rates["off"]
    print(_json_line({
        "metric": "fused_update_ab",
        "value": round(rates["on"], 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(rates["on"] / rates["off"], 4),
        "off_images_per_sec_per_chip": round(rates["off"], 2),
        "on_images_per_sec_per_chip": round(rates["on"], 2),
        "step_overhead_pct": round(100.0 * overhead, 2),
        "dispatch_span_p50_ms": span_p50,
        "microbench": row,
        "batch_per_chip": bs, "arch": arch, "image_size": image_size,
        "timing_steps": steps,
        "device_kind": jax.devices()[0].device_kind,
    }))


def _resident_ab(arch, image_size, on_tpu, attn_impl):
    """Resident flat update-state A/B (``--resident-ab``): the ZeRO-1 +
    fused-update config AOT-compiled with the transient layout
    (``--flat-resident off`` — momentum/target packed and unpacked every
    step, EMA target gathered leaf-by-leaf) and with resident flat
    buffers (``on`` — packed once at setup, aliased in place step over
    step, bucketed all-gather), each arm timed with a live
    :class:`spans.SpanRecorder` wrapping every step dispatch plus the
    closing readback (wall rate + dispatch-span p50 ->
    ``bench_events.jsonl``).

    Also records an IN-PROCESS microbenchmark row isolating what
    residency deletes: the transient entry (pack params/grads/momentum/
    target + kernel + unpack all four) vs the resident entry (pack
    params/grads only, kernel consumes the resident buffers in place) on
    the same synthetic multi-leaf tree.  NB on CPU both arms run the
    kernel under the Pallas INTERPRETER — correctness-grade plumbing
    capture, not speed-grade; the TPU row is where the deleted VMEM
    round trips pay.
    """
    import jax.numpy as jnp

    from byol_tpu.observability import goodput as goodput_lib
    from byol_tpu.observability import spans as spans_lib
    from byol_tpu.optim.factory import (MOMENTUM_DECAY, build_optimizer,
                                        extract_sgdm_state)
    from byol_tpu.ops import fused_update as fused_lib
    from byol_tpu.parallel import flat_state as flat_lib
    bs = 256 if on_tpu else 16
    steps = 60 if on_tpu else 30
    rates, span_p50 = {}, {}
    for mode in ("off", "on"):
        state, train_step, batch, mesh = _build(
            bs, image_size, arch, half=on_tpu, fuse_views=True,
            ema_update_mode="post", attn_impl=attn_impl,
            zero1="on", fused_update="on", flat_resident=mode)
        compiled, stats = _aot_compile(train_step, state, batch, mesh)
        recorder = spans_lib.SpanRecorder()
        for _ in range(3):                       # warm; sync via readback
            state, metrics = compiled(state, batch)
        float(metrics["loss_mean"])
        t0 = time.perf_counter()
        for _ in range(steps):
            with recorder.span("train/dispatch"):
                state, metrics = compiled(state, batch)
        with recorder.span("train/epoch_readback"):
            float(metrics["loss_mean"])
        dt = time.perf_counter() - t0
        n_dev = len(jax.devices())
        rates[mode] = batch["label"].shape[0] * steps / dt / n_dev
        sstats = goodput_lib.span_stats(recorder.records())
        span_p50[mode] = sstats.get("train/dispatch", {}).get("p50_ms")
        if _events is not None:
            _events.emit("span_stats", scope="epoch",
                         label=f"resident_{mode}", spans=sstats)
        _record(f"resident_{mode}", fit=True, batch_per_chip=bs,
                flat_resident=mode, zero1="on", fused_update="on",
                images_per_sec_per_chip=round(rates[mode], 2),
                dispatch_span_p50_ms=span_p50[mode], **stats)
        print(f"bench: resident_{mode}: {rates[mode]:.2f} img/s/chip "
              f"(dispatch p50 {span_p50[mode]}ms)", file=sys.stderr)

    # ---- in-process microbenchmark: transient entry vs resident entry --
    rng = np.random.default_rng(0)
    leaf_shapes = ([(3, 3, 256, 256)] * 4 + [(1024, 512), (512,), (256,)]
                   if on_tpu else
                   [(3, 3, 32, 64), (3, 3, 64, 64), (128, 256), (64,),
                    (256,)])
    params = {f"l{i}": jnp.asarray(rng.standard_normal(s) * 0.05,
                                   jnp.float32)
              for i, s in enumerate(leaf_shapes)}
    n_elems = sum(int(np.prod(s)) for s in leaf_shapes)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape) * 0.01,
                              jnp.float32), params)
    target = jax.tree_util.tree_map(lambda p: p * 0.9, params)
    wd = 1e-6
    tau = 0.99
    tx, sched = build_optimizer(
        "lars_momentum", base_lr=0.2, global_batch_size=4096,
        weight_decay=wd, total_units=100, warmup_units=10)
    opt_state = tx.init(params)
    trace, count = extract_sgdm_state(opt_state)
    lr = sched(count)
    layout = flat_lib.build_layout(params, 1)
    m_buf = jax.jit(lambda t: flat_lib.pack_tree(t, layout))(trace)
    t_buf = jax.jit(lambda t: flat_lib.pack_tree(t, layout))(target)

    @jax.jit
    def transient(g, m, p, t):
        return fused_lib.fused_lars_ema_update(
            p, g, m, t, lr=lr, tau=tau, weight_decay=wd,
            momentum_decay=MOMENTUM_DECAY)

    @jax.jit
    def resident(g, mb, p, tb):
        return fused_lib.fused_lars_ema_update_resident(
            p, g, mb, tb, layout=layout, lr=lr, tau=tau, weight_decay=wd,
            momentum_decay=MOMENTUM_DECAY)

    def bench_fn(fn, args, reps=5, inner=3):
        out = fn(*args)                       # compile + warm
        jax.block_until_ready(out)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(inner):
                jax.block_until_ready(fn(*args))
            times.append((time.perf_counter() - t0) / inner)
        return float(np.median(times))

    t_transient = bench_fn(transient, (grads, trace, params, target))
    t_resident = bench_fn(resident, (grads, m_buf, params, t_buf))
    row = {
        "params": n_elems,
        "transient_entry_us": round(t_transient * 1e6, 1),
        "resident_entry_us": round(t_resident * 1e6, 1),
        "resident_speedup": round(t_transient / t_resident, 3),
        "interpret_mode": not on_tpu,
    }
    _record("resident_microbench", fit=True, **row)
    print(_json_line({
        "metric": "flat_resident_ab",
        "value": round(rates["on"], 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(rates["on"] / rates["off"], 4),
        "off_images_per_sec_per_chip": round(rates["off"], 2),
        "on_images_per_sec_per_chip": round(rates["on"], 2),
        "dispatch_span_p50_ms": span_p50,
        "microbench": row,
        "batch_per_chip": bs, "arch": arch, "image_size": image_size,
        "timing_steps": steps,
        "device_kind": jax.devices()[0].device_kind,
    }))


def _augment_ab(arch, image_size, on_tpu, attn_impl):
    """Fused-augmentation A/B (``--augment-ab``): the step-placement
    config (raw uint8 batches, in-step two-view augmentation) AOT-compiled
    with the XLA op chain (``--fused-augment off`` — the exact unfused
    graph, pinned byte-identical by test) and with the fused Pallas
    augmentation kernel (``on``; ops/fused_augment.py), each arm timed
    under a live :class:`spans.SpanRecorder` wrapping every step dispatch
    plus the closing readback — wall rate + per-step dispatch-span stats
    into ``bench_events.jsonl`` as bench_row + span_stats, the same
    flight-recorder currency the trainer logs.

    Also records an IN-PROCESS input-path microbenchmark row: the bare
    two-view augmentation (``device_augment.two_view`` XLA chain vs
    ``fused_two_view``) on a synthetic uint8 batch, each on its own
    executable — the number that isolates the input path from the model
    around it.  NB on CPU the fused arm runs under the Pallas INTERPRETER
    (one XLA op dispatched per kernel instruction — correctness-grade,
    not speed-grade): the CPU capture documents mechanism and event
    plumbing; the TPU row (ROADMAP capture batch) is the perf claim.
    """
    import jax.numpy as jnp

    from byol_tpu.data import device_augment
    from byol_tpu.observability import goodput as goodput_lib
    from byol_tpu.observability import spans as spans_lib
    from byol_tpu.ops import fused_augment as fused_aug_lib
    bs = 256 if on_tpu else 16
    steps = 60 if on_tpu else 30
    rates, span_p50 = {}, {}
    for mode in ("off", "on"):
        state, train_step, batch, mesh = _build(
            bs, image_size, arch, half=on_tpu, fuse_views=True,
            ema_update_mode="post", attn_impl=attn_impl,
            augment_placement="step", fused_augment=mode)
        compiled, stats = _aot_compile(train_step, state, batch, mesh)
        recorder = spans_lib.SpanRecorder()
        for _ in range(3):                       # warm; sync via readback
            state, metrics = compiled(state, batch)
        float(metrics["loss_mean"])
        t0 = time.perf_counter()
        for _ in range(steps):
            with recorder.span("train/dispatch"):
                state, metrics = compiled(state, batch)
        with recorder.span("train/epoch_readback"):
            float(metrics["loss_mean"])
        dt = time.perf_counter() - t0
        n_dev = len(jax.devices())
        rates[mode] = batch["label"].shape[0] * steps / dt / n_dev
        sstats = goodput_lib.span_stats(recorder.records())
        span_p50[mode] = sstats.get("train/dispatch", {}).get("p50_ms")
        if _events is not None:
            _events.emit("span_stats", scope="epoch",
                         label=f"augment_{mode}", spans=sstats)
        _record(f"augment_{mode}", fit=True, batch_per_chip=bs,
                fused_augment=mode, augment_placement="step",
                images_per_sec_per_chip=round(rates[mode], 2),
                dispatch_span_p50_ms=span_p50[mode], **stats)
        print(f"bench: augment_{mode}: {rates[mode]:.2f} img/s/chip "
              f"(dispatch p50 {span_p50[mode]}ms)", file=sys.stderr)

    # ---- in-process input-path microbenchmark --------------------------
    # the bare two-view program on a raw uint8 microbatch: XLA op chain
    # vs one fused kernel call (+ its blur conv), both jitted standalone
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(
        0, 256, (bs, image_size, image_size, 3), dtype=np.uint8))
    key = jax.random.PRNGKey(0)

    @jax.jit
    def xla_chain(k, im):
        return device_augment.two_view(k, im, image_size)

    @jax.jit
    def fused(k, im):
        return fused_aug_lib.fused_two_view(k, im, image_size)

    def bench_fn(fn, args, reps=5, inner=3):
        out = fn(*args)                       # compile + warm
        jax.block_until_ready(out)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(inner):
                jax.block_until_ready(fn(*args))
            times.append((time.perf_counter() - t0) / inner)
        return float(np.median(times))

    t_chain = bench_fn(xla_chain, (key, imgs))
    # graphlint: disable=GL103 -- A/B arms deliberately consume the same key: the fused kernel must see the XLA chain's exact random draws
    t_fused = bench_fn(fused, (key, imgs))
    row = {
        "batch": bs,
        "image_size": image_size,
        "xla_chain_us": round(t_chain * 1e6, 1),
        "fused_kernel_us": round(t_fused * 1e6, 1),
        "fused_speedup": round(t_chain / t_fused, 3),
        "interpret_mode": not on_tpu,
    }
    _record("augment_microbench", fit=True, **row)
    overhead = 1.0 - rates["on"] / rates["off"]
    print(_json_line({
        "metric": "fused_augment_ab",
        "value": round(rates["on"], 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(rates["on"] / rates["off"], 4),
        "off_images_per_sec_per_chip": round(rates["off"], 2),
        "on_images_per_sec_per_chip": round(rates["on"], 2),
        "step_overhead_pct": round(100.0 * overhead, 2),
        "dispatch_span_p50_ms": span_p50,
        "microbench": row,
        "batch_per_chip": bs, "arch": arch, "image_size": image_size,
        "timing_steps": steps,
        "device_kind": jax.devices()[0].device_kind,
    }))


def _serve_setup(arch, image_size, on_tpu):
    """Shared --serve-ladder/--wire-ladder startup: validate the bucket/
    mesh constraints, build the config + serve config, return everything
    a rung loop needs.  One helper so the two ladders cannot drift."""
    from byol_tpu.core.config import (Config, DeviceConfig, ModelConfig,
                                      TaskConfig)
    from byol_tpu.parallel.mesh import MeshSpec, build_mesh
    from byol_tpu.serving.service import ServeConfig

    streams_list = [int(s) for s in
                    _str_flag("--serve-streams", "1,8,64").split(",")]
    budget = _int_flag("--serve-requests", 2048 if on_tpu else 256)
    max_batch = _int_flag("--serve-max-batch", 64)
    n_dev = len(jax.devices())
    if n_dev & (n_dev - 1):
        # fail fast with the actionable constraint, not a BucketSpec /
        # engine divisibility error after the model is already built:
        # buckets are powers of two and shard their rows over the mesh
        raise SystemExit(
            f"bench: serve ladders need a power-of-two device count "
            f"(got {n_dev}): bucket shapes are powers of two and must "
            "shard evenly over the data axis; pass --cpu-devices 2|4|8|...")
    min_bucket = _int_flag("--serve-min-bucket", max(8, n_dev))
    if min_bucket > max_batch:
        raise SystemExit(
            f"bench: serve min bucket {min_bucket} (default max(8, "
            f"n_devices)) exceeds --serve-max-batch {max_batch}; raise "
            "the max batch or lower --serve-min-bucket")
    wait_ms = float(_str_flag("--serve-wait-ms", "5.0"))
    half = bool(on_tpu)      # bf16 embed on real silicon, fp32 on CPU

    mesh = build_mesh(MeshSpec(data=n_dev))
    cfg = Config(
        task=TaskConfig(task="fake", batch_size=max(max_batch, n_dev),
                        epochs=1, image_size_override=image_size),
        model=ModelConfig(arch=arch),
        device=DeviceConfig(num_replicas=n_dev, half=half),
    )
    serve_cfg = ServeConfig(min_bucket=min_bucket, max_bucket=max_batch,
                            max_wait_ms=wait_ms,
                            stats_interval_s=1e9)   # rows emit explicitly
    return (streams_list, budget, max_batch, min_bucket, wait_ms, half,
            n_dev, mesh, cfg, serve_cfg)


def _serve_ladder(arch, image_size, on_tpu, attn_impl):
    """Serve ladder (``--serve-ladder``): latency vs throughput for the
    embedding service (byol_tpu/serving/) at 1/8/64 concurrent synthetic
    client streams.

    Each rung drives a closed-loop budget of single-image requests through
    the FULL serving stack — bounded queue, request coalescing, bucket
    padding, pinned-host staging, AOT embed, readback — and records the
    request-latency tail (p50/p99 ms), achieved rows/sec, batch fill
    ratio, and the engine compile counter.  The counter column is the
    zero-recompile contract made visible: after the warmup phase it must
    not move, or a rung's latency includes XLA compiles (the GL102 hazard
    on the latency path) and the row says so.

    CPU-runnable with ``--cpu-devices N`` (random-init encoder — latency
    is independent of parameter values); on TPU the same command measures
    the real serving config.  Knobs: ``--serve-streams 1,8,64``,
    ``--serve-requests <budget/rung>``, ``--serve-max-batch``,
    ``--serve-min-bucket``, ``--serve-wait-ms``, and ``--serve-pipeline
    off|on|ab`` — 'ab' re-runs the whole ladder with worker dispatch
    pipelining off then on (same engine, same executables: the delta is
    pure host/device overlap), the ISSUE 13 before/after row.
    """
    import dataclasses
    import time

    from byol_tpu.serving.batcher import DynamicBatcher
    from byol_tpu.serving.net.loadgen import run_closed_loop
    from byol_tpu.serving.service import EmbeddingService, build_service

    (streams_list, budget, max_batch, min_bucket, wait_ms, half,
     n_dev, mesh, cfg, serve_cfg) = _serve_setup(arch, image_size, on_tpu)
    pipe_flag = _str_flag("--serve-pipeline", "on")
    if pipe_flag not in ("off", "on", "ab"):
        raise SystemExit("usage: bench.py --serve-ladder "
                         "--serve-pipeline off|on|ab")
    arms = ("off", "on") if pipe_flag == "ab" else (pipe_flag,)

    engine = None
    warmup_s = 0.0
    ladder = []
    for pipeline in arms:
        if engine is None:
            service = build_service(
                cfg, dataclasses.replace(serve_cfg, pipeline=pipeline),
                mesh=mesh)
            engine = service.engine
            t0 = time.perf_counter()
            service.start()   # AOT-compiles the whole bucket vocabulary
            warmup_s = time.perf_counter() - t0
            print(f"bench: serve warmup: {engine.compile_count} bucket "
                  f"programs {list(engine.buckets.sizes)} in "
                  f"{warmup_s:.1f}s", file=sys.stderr)
        else:
            # second arm reuses the warmed ENGINE (identical executables
            # — the A/B delta is worker overlap, not compilation) under a
            # fresh batcher/worker
            service = EmbeddingService(
                engine,
                DynamicBatcher(max_batch=max_batch,
                               max_queue=serve_cfg.max_queue,
                               max_wait_s=wait_ms / 1e3),
                stats_interval_s=1e9, pipeline=pipeline)
            service.start(warmup=False)
        shape = engine.input_shape
        try:
            for n_streams in streams_list:
                # untimed warm pass: first execution of each bucket
                # program pays one-time backend setup that is not
                # steady-state latency
                run_closed_loop(
                    lambda i, img: service.embed(img, timeout=600.0),
                    shape, max(2 * n_streams, 8), n_streams, seed=17)
                service.meter.snapshot(time.perf_counter())  # reset window
                rung_base = engine.compile_count  # per-rung baseline: a
                res = run_closed_loop(            # compile counts in the
                    lambda i, img: service.embed(img, timeout=600.0),
                    shape, budget, n_streams,     # rung it ran in
                    seed=n_streams)
                done, elapsed = res.completed, res.elapsed_s
                recompiles = engine.compile_count - rung_base
                # one serve_stats event per rung next to the bench_row —
                # the serving schema exercised by the capture CI validates
                snap = service.meter.emit(
                    _events, time.perf_counter(), streams=n_streams,
                    compile_count=engine.compile_count)
                row = {
                    "streams": n_streams, "requests": done,
                    "failed": res.failed,
                    "pipeline": pipeline,
                    "p50_ms": round(snap["p50_ms"], 3),
                    "p99_ms": round(snap["p99_ms"], 3),
                    "mean_ms": round(snap["mean_ms"], 3),
                    "throughput_img_per_sec": round(done / elapsed, 2),
                    "throughput_img_per_sec_per_chip":
                        round(done / elapsed / n_dev, 2),
                    "fill_ratio": round(snap["fill_ratio"], 4),
                    "queue_depth": round(snap["queue_depth"], 2),
                    "batches": int(snap["batches"]),
                    "recompiles_after_warmup": recompiles,
                    "max_batch": max_batch, "min_bucket": min_bucket,
                    "max_wait_ms": wait_ms, "n_devices": n_dev,
                    "half": half,
                    "warmup_compile_seconds": round(warmup_s, 2),
                }
                ladder.append(row)
                _record(f"serve_s{n_streams}_pipe_{pipeline}", fit=True,
                        **row)
                print(f"bench: serve s{n_streams} pipe={pipeline}: "
                      f"p50 {row['p50_ms']}ms p99 {row['p99_ms']}ms "
                      f"{row['throughput_img_per_sec']} img/s "
                      f"fill {row['fill_ratio']} "
                      f"recompiles {recompiles}", file=sys.stderr)
        finally:
            service.stop()
    print(_json_line({
        "metric": "serve_ladder_p99_ms",
        "value": ladder[-1]["p99_ms"] if ladder else None,
        "unit": "ms @ most-concurrent rung",
        "vs_baseline": None,
        "arch": arch, "image_size": image_size,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "recompiles_after_warmup": sum(r["recompiles_after_warmup"]
                                       for r in ladder),
        "rows": ladder,
    }))


def _wire_ladder(arch, image_size, on_tpu, attn_impl):
    """Wire ladder (``--wire-ladder``): the WIRE TAX measured — the same
    closed-loop streams driven twice per rung, once through the
    in-process ``service.embed`` path and once over HTTP through the
    serving/net front end (protocol encode → POST /v1/embed → decode),
    against ONE warmed service.  Client-observed p50/p99 per arm; the
    per-rung delta is what the network front door costs on top of the
    batching/AOT machinery (localhost floor — real networks add RTT on
    top, but the protocol + HTTP + framing overhead is all here).

    Knobs: the --serve-* family (shared with --serve-ladder) plus
    ``--wire-deadline-ms`` (per-request X-Deadline-Ms; generous default —
    the ladder measures latency, not admission policy).
    """
    import time

    from byol_tpu.serving.net.client import EmbedClient
    from byol_tpu.serving.net.loadgen import run_closed_loop
    from byol_tpu.serving.net.server import WireServer
    from byol_tpu.serving.service import build_service

    (streams_list, budget, max_batch, min_bucket, wait_ms, half,
     n_dev, mesh, cfg, serve_cfg) = _serve_setup(arch, image_size, on_tpu)
    deadline_ms = float(_str_flag("--wire-deadline-ms", "600000"))

    service = build_service(cfg, serve_cfg, mesh=mesh)
    t0 = time.perf_counter()
    service.start()           # AOT-compiles the whole bucket vocabulary
    warmup_s = time.perf_counter() - t0
    engine = service.engine
    print(f"bench: wire warmup: {engine.compile_count} bucket programs "
          f"{list(engine.buckets.sizes)} in {warmup_s:.1f}s",
          file=sys.stderr)
    server = WireServer(service, "127.0.0.1", 0,
                        default_deadline_ms=deadline_ms).start()
    host, port = server.address
    print(f"bench: wire front end at http://{host}:{port}",
          file=sys.stderr)
    shape = engine.input_shape
    ladder = []

    def inproc_fn(idx, img):
        service.embed(img, timeout=deadline_ms / 1e3)

    clients = {}

    def wire_setup(idx):
        # create-if-absent: the warm pass dials each stream's connection
        # and the measured pass must REUSE it — re-dialing here would put
        # the TCP connect the warm pass exists to absorb back into the
        # first measured sample of every stream (at 64 streams / 256
        # requests that is a quarter of the published p99's samples)
        if idx not in clients:
            clients[idx] = EmbedClient(host, port,
                                       timeout_s=deadline_ms / 1e3 + 5.0,
                                       seed=idx)

    def wire_fn(idx, img):
        clients[idx].embed(img, deadline_ms=deadline_ms)

    try:
        for n_streams in streams_list:
            rows_by_arm = {}
            for arm, fn, setup in (("inproc", inproc_fn, None),
                                   ("wire", wire_fn, wire_setup)):
                # untimed warm pass (per arm: the wire arm's first
                # requests also pay connection dialing)
                run_closed_loop(fn, shape, max(2 * n_streams, 8),
                                n_streams, seed=17, stream_setup=setup)
                service.meter.snapshot(time.perf_counter())  # reset
                rung_base = engine.compile_count
                res = run_closed_loop(fn, shape, budget, n_streams,
                                      seed=n_streams, stream_setup=setup)
                snap = service.meter.emit(
                    _events, time.perf_counter(), streams=n_streams,
                    arm=arm, compile_count=engine.compile_count)
                row = {
                    "streams": n_streams, "arm": arm,
                    "requests": res.completed, "failed": res.failed,
                    # CLIENT-observed latency (loadgen's clock): the
                    # meter's enqueue->deliver window cannot see wire
                    # time by construction
                    "p50_ms": round(res.percentile_ms(50), 3),
                    "p99_ms": round(res.percentile_ms(99), 3),
                    "throughput_img_per_sec":
                        round(res.throughput(), 2),
                    "serve_p50_ms": round(snap["p50_ms"], 3),
                    "fill_ratio": round(snap["fill_ratio"], 4),
                    "recompiles_after_warmup":
                        engine.compile_count - rung_base,
                    "max_batch": max_batch, "min_bucket": min_bucket,
                    "max_wait_ms": wait_ms, "n_devices": n_dev,
                    "half": half,
                }
                rows_by_arm[arm] = row
                ladder.append(row)
                _record(f"wire_s{n_streams}_{arm}", fit=True, **row)
            tax_p50 = round(rows_by_arm["wire"]["p50_ms"]
                            - rows_by_arm["inproc"]["p50_ms"], 3)
            tax_p99 = round(rows_by_arm["wire"]["p99_ms"]
                            - rows_by_arm["inproc"]["p99_ms"], 3)
            print(f"bench: wire s{n_streams}: inproc p50 "
                  f"{rows_by_arm['inproc']['p50_ms']}ms, wire p50 "
                  f"{rows_by_arm['wire']['p50_ms']}ms -> tax "
                  f"{tax_p50}ms (p99 tax {tax_p99}ms)", file=sys.stderr)
    finally:
        for c in clients.values():
            c.close()
        server.drain(grace_s=0.0, timeout_s=60.0)   # stops the service
    print(_json_line({
        "metric": "wire_ladder_p50_tax_ms",
        "value": (round(ladder[-1]["p50_ms"] - ladder[-2]["p50_ms"], 3)
                  if len(ladder) >= 2 else None),
        "unit": "ms wire-minus-inproc @ most-concurrent rung",
        "vs_baseline": None,
        "arch": arch, "image_size": image_size,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": n_dev,
        "rows": ladder,
    }))


def _sweep_prior_rows() -> dict:
    """Sweep rows measured by a previous, interrupted attempt.

    The tunnel drops mid-sweep regularly (round 2: after one row; round 3:
    the remote-compile service itself crashed 25 minutes into a compile), so
    a re-run must converge instead of starting over: any ``sweep_*`` row in
    the live partial file or its ``.prev`` backup — same device class only —
    is reused rather than re-measured.  Must be called BEFORE the first
    ``_record`` of the run (which rotates the live file to ``.prev``)."""
    prior: dict = {}
    kind = jax.devices()[0].device_kind
    for path in (_PARTIAL_PATH + ".prev", _PARTIAL_PATH):   # live file wins
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        if d.get("device_kind") != kind:   # a v5e row is not a v4/v6e row
            continue
        for r in d.get("results", []):
            name = str(r.get("config", ""))
            if name.startswith("sweep_") and "fit" in r:
                prior[name] = r
    return prior


def _sweep(arch, image_size, candidates, mfu_of):
    """Tuning grid: batch x remat x fuse_views, bf16. Results accumulate in
    bench_partial.json (incremental) and bench_sweep.json (final table).

    Hard-learned grid rules:
    - Rungs 512/384/256 only: smaller batches are strictly slower on this
      model class (the headline ladder's 128-class rungs trail by >30%),
      and un-rematted bs1024 is a known compile-OOM whose ~25-minute
      compile attempt once crashed the tunnel's remote-compile service —
      it is never re-attempted without remat.
    - The rematted bs1024 rows (the one config where 1024 might newly fit)
      go LAST, so a compile-service crash there cannot cost other rows.
    - Rows from a previous interrupted sweep are reused (_sweep_prior_rows)
      so re-runs after a tunnel drop finish the grid instead of repeating
      it.
    """
    top = max(candidates)
    rungs = [bs for bs in (512, 384, 256) if bs <= top]
    if not rungs:        # CPU-fallback ladder (tiny model): keep liveness
        rungs = list(candidates)
    grid = [(remat, fuse, bs)
            for remat in (False, True) for fuse in (True, False)
            for bs in rungs]
    if top >= 1024:
        grid += [(True, True, 1024), (True, False, 1024)]
    prior = _sweep_prior_rows() if jax.default_backend() != "cpu" else {}
    rows = []
    for remat, fuse, bs in grid:
        if _backend_dead:
            break
        name = f"sweep_bs{bs}_remat{int(remat)}_fuse{int(fuse)}"
        # Reuse rule: fit=True rows always; fit=False rows only at the
        # >=1024 rungs (the multi-minute compile-OOMs worth never
        # repeating) AND only when the recorded error carries a genuine
        # OOM signature — a transient tunnel error that slipped past the
        # liveness probe must not permanently mask a config that fits.
        # Smaller rungs' fit=False rows always re-measure (cheap).
        if name in prior and (
                prior[name].get("fit")
                or (bs >= 1024
                    and _oom_signature(str(prior[name].get("error", ""))))):
            # strip 'reused' too: a thrice-interrupted sweep reloads rows
            # that were themselves recorded by a resume
            r = {k: v for k, v in prior[name].items()
                 if k not in ("config", "reused")}
            _record(name, reused=True, **r)
            print(f"bench: {name}: reusing prior measurement "
                  f"(fit={r.get('fit')}, "
                  f"{r.get('images_per_sec_per_chip')})", file=sys.stderr)
            if r.get("fit"):
                rows.append({k: r[k] for k in
                             ("batch_per_chip", "remat", "fuse_views",
                              "images_per_sec_per_chip", "mfu")
                             if k in r})
            continue
        try:
            val = _throughput(bs, image_size, arch, half=True,
                              fuse_views=fuse, remat=remat,
                              ema_update_mode="post", steps=10)
        except Exception as e:
            if _config_failed(name, e):
                break
            _record(name, batch_per_chip=bs, fit=False,
                    error=repr(e)[:300])
            continue
        row = {"batch_per_chip": bs, "remat": remat,
               "fuse_views": fuse,
               "images_per_sec_per_chip": round(val, 2),
               "mfu": mfu_of(val)}
        rows.append(row)
        _record(name, fit=True, **row, **_row_stats(val))
        print(f"bench: {name}: {val:.1f} img/s/chip "
              f"mfu={row['mfu']}", file=sys.stderr)
    # CPU-fallback tables must not shadow the committed TPU table, an early
    # backend death must not truncate it to [], and a non-default arch
    # writes its OWN table (same isolation contract as _PARTIAL_PATH — a
    # vit sweep must never rotate away the committed resnet50 table).
    if jax.default_backend() == "cpu":
        sweep_path = "bench_sweep_cpu.json"
    elif arch != "resnet50":
        sweep_path = f"bench_sweep_{arch}.json"
    else:
        sweep_path = "bench_sweep.json"
    if rows:
        try:
            if os.path.exists(sweep_path):
                # same evidence-preservation contract as _flush_partial: a
                # partial re-run must never destroy a complete prior table
                os.replace(sweep_path, sweep_path + ".prev")
            with open(sweep_path, "w") as f:
                json.dump(_sanitize_json(rows), f, indent=2,
                          allow_nan=False)
                f.write("\n")
        except OSError as e:  # same contract as _flush_partial
            print(f"bench: could not write {sweep_path}: {e}",
                  file=sys.stderr)
    else:
        print(f"bench: no rows measured; leaving {sweep_path} untouched",
              file=sys.stderr)
    print(_json_line({"metric": "sweep", "value": len(rows),
                      "unit": "configs", "vs_baseline": None,
                      "complete": not _backend_dead}))
    if _backend_dead:
        # A truncated grid must not exit 0: the capture pipeline keys a
        # stage's done-marker off a successful exit, and a partial sweep
        # marked complete would never measure its remaining rows (the
        # resume machinery in _sweep_prior_rows exists precisely to finish
        # it on the next window).
        raise SystemExit(3)


if __name__ == "__main__":
    main()
