#!/usr/bin/env python
"""Launcher — ``python train.py <flags>`` like the reference's main.py.

One process per TPU host (the mp.spawn/one-proc-per-node topology switch of
/root/reference/main.py:786-814 collapses under JAX: device enumeration and
cross-host collectives are owned by the runtime; multi-host rendezvous is
``--distributed-master``)."""
from byol_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
