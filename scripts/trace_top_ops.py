"""Summarize a jax.profiler trace: top ops by total device time.

Usage: python scripts/trace_top_ops.py /tmp/byol_profile [N]

Reads the newest ``*.trace.json.gz`` under the logdir (the TensorBoard
profile plugin layout ``plugins/profile/<ts>/``), aggregates complete events
on device OP tracks by name, and prints the top-N ops with total time and
share of the trace's device-busy time.  When the trace carries per-thread
names (jax traces name them "XLA Ops" / "XLA Modules" / "Steps"), only the
op threads are aggregated — module/step region events span their children
and would otherwise double-count.  This turns ``bench.py --profile`` output
into the tuning table RESULTS.md wants (where does non-conv time go)
without needing a TensorBoard UI, which this headless box lacks.
"""
from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import sys


def find_trace(logdir: str) -> str:
    pats = [os.path.join(logdir, "plugins", "profile", "*", "*.trace.json.gz"),
            os.path.join(logdir, "**", "*.trace.json.gz")]
    hits: list[str] = []
    for p in pats:
        hits = glob.glob(p, recursive=True)
        if hits:
            break
    if not hits:
        raise SystemExit(f"no *.trace.json.gz under {logdir}")
    return max(hits, key=os.path.getmtime)


def summarize(trace_path: str, top_n: int = 30):
    with gzip.open(trace_path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # pid -> process name; device tracks are the TPU/accelerator pids
    pid_names = {}
    tid_names = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pid_names[e["pid"]] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tid_names[(e["pid"], e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    device_pids = {pid for pid, name in pid_names.items()
                   if any(k in name.lower()
                          for k in ("tpu", "device", "xla", "accelerator"))
                   and "host" not in name.lower()}
    if not device_pids:   # fall back to every non-host pid
        device_pids = {pid for pid, name in pid_names.items()
                       if "host" not in name.lower()}
    # Module/step region events contain their child ops; keep only the op
    # threads when the trace names threads, else take everything.
    op_tids = {k for k, name in tid_names.items()
               if k[0] in device_pids and "op" in name.lower()}

    def on_op_track(e):
        if e.get("pid") not in device_pids:
            return False
        return not op_tids or (e["pid"], e.get("tid")) in op_tids

    def aggregate(keep):
        total = collections.Counter()
        count = collections.Counter()
        busy = 0.0
        for e in events:
            if e.get("ph") != "X" or not keep(e):
                continue
            dur = float(e.get("dur", 0.0))   # microseconds
            name = e.get("name", "?")
            total[name] += dur
            count[name] += 1
            busy += dur
        return total, count, busy

    total, count, busy = aggregate(on_op_track)
    if not total:
        # Unfamiliar track layout (e.g. a CPU-backend trace, where ops land
        # on host threads): better an over-inclusive table than an empty one.
        print("trace_top_ops: no events on recognized device-op tracks; "
              "falling back to ALL complete events (region events may "
              "double-count their children)", file=sys.stderr)
        total, count, busy = aggregate(lambda e: True)
    rows = [(t, count[n], n) for n, t in total.most_common(top_n)]
    return rows, busy


def main():
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    logdir = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    path = find_trace(logdir)
    rows, busy = summarize(path, top_n)
    print(f"trace: {path}")
    print(f"device-busy time: {busy / 1e3:.2f} ms (sum over op events; "
          "totals and shares double-count if ops overlap on parallel "
          "tracks)")
    print(f"{'total_ms':>10} {'calls':>7} {'share':>7}  op")
    for t, c, name in rows:
        share = f"{t / busy:>6.1%}" if busy > 0 else "   n/a"
        print(f"{t / 1e3:>10.3f} {c:>7} {share}  {name[:100]}")


if __name__ == "__main__":
    main()
