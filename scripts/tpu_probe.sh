# Shared TPU-tunnel probe, sourced by tpu_watch.sh and tpu_capture.sh so
# the two can never drift (the watcher's copy once gained the
# chip-in-use guard while the capture's lacked it).
#
# 60s-timeout matmul with a scalar D2H readback — block_until_ready lies
# over axon (returns at dispatch-ack) — plus a platform assert: on a dead
# accelerator jax silently falls back to cpu, which must count as DOWN.
#
# Callers that might race another chip holder add their own pgrep guard
# BEFORE calling (the TPU is single-process-exclusive; probing a busy
# chip hangs without meaning the tunnel is down).
tpu_probe() {
    timeout 60 python - <<'EOF' > /dev/null 2>&1
import jax, jax.numpy as jnp
assert jax.default_backend() != "cpu", jax.default_backend()
x = jnp.ones((256, 256))
print(float((x @ x).sum()))
EOF
}
