#!/usr/bin/env bash
# Round-3 TPU capture.  Differs from tpu_evidence.sh in that it preserves
# each stage's bench_partial.json (every bench.py invocation rewrites that
# file) and tees all stdout/stderr to /tmp logs for post-hoc analysis.
# Stage order puts NEW information first (the tunnel can drop at any time);
# the headline re-run goes last: its tpu_first ladder is compile-cached by
# the sweep, though its fp32 reference_faithful baseline is NOT in the
# sweep grid and still compiles cold — if the tunnel dies before the last
# stage, the committed bench_partial.json already carries a full headline
# run.
set -u
cd "$(dirname "$0")/.."
mkdir -p /tmp/tpu_capture

echo "== 1/6 sweep =="
python bench.py --sweep > /tmp/tpu_capture/sweep_stdout.json 2> /tmp/tpu_capture/sweep_stderr.log
echo "rc=$?"
cp -f bench_partial.json /tmp/tpu_capture/sweep_partial.json 2>/dev/null

echo "== 2/6 vit_b16 headline (BASELINE config 5) =="
python bench.py --arch vit_b16 > /tmp/tpu_capture/vit_stdout.json 2> /tmp/tpu_capture/vit_stderr.log
echo "rc=$?"
# vit measures into its own partial file; never touches bench_partial.json

echo "== 3/6 stem A/B =="
python bench.py --stem-ab > /tmp/tpu_capture/stem_ab_stdout.json 2> /tmp/tpu_capture/stem_ab_stderr.log
echo "rc=$?"
cp -f bench_partial.json /tmp/tpu_capture/stem_ab_partial.json 2>/dev/null

echo "== 4/6 profile =="
rm -rf /tmp/byol_profile   # a stale trace must not masquerade as this run's
python bench.py --profile /tmp/byol_profile > /tmp/tpu_capture/profile_stdout.json 2> /tmp/tpu_capture/profile_stderr.log
profile_rc=$?
echo "rc=$profile_rc"
if [ "$profile_rc" -eq 0 ]; then
    python scripts/trace_top_ops.py /tmp/byol_profile 40 > /tmp/tpu_capture/trace_top_ops.txt 2>&1
else
    # a stale table from a previous capture must not survive a failed stage
    echo "profile failed rc=$profile_rc; no trace" > /tmp/tpu_capture/trace_top_ops.txt
fi

echo "== 5/6 synth learning evidence =="
python train.py --task synth --batch-size 512 --epochs 12 \
    --arch resnet18 --image-size-override 32 --head-latent-size 512 \
    --projection-size 128 --lr 0.8 --warmup 2 --fuse-views \
    --linear-eval --uid synth_evidence \
    --log-dir runs --model-dir /tmp/synth_models \
    > /tmp/tpu_capture/synth_stdout.log 2> /tmp/tpu_capture/synth_stderr.log
echo "rc=$?"

echo "== 6/6 headline bench =="
python bench.py > /tmp/tpu_capture/headline_stdout.json 2> /tmp/tpu_capture/headline_stderr.log
echo "rc=$?"
cp -f bench_partial.json /tmp/tpu_capture/headline_partial.json 2>/dev/null
echo "== capture done =="
