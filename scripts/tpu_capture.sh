#!/usr/bin/env bash
# Round-3 TPU capture: headline bench, tuning sweep, profile trace, synth
# learning run.  Differs from tpu_evidence.sh in that it preserves each
# stage's bench_partial.json (every bench.py invocation rewrites that file)
# and tees all stdout/stderr to /tmp logs for post-hoc analysis.
set -u
cd "$(dirname "$0")/.."
mkdir -p /tmp/tpu_capture

echo "== 1/4 headline bench =="
python bench.py > /tmp/tpu_capture/headline_stdout.json 2> /tmp/tpu_capture/headline_stderr.log
echo "rc=$?"
cp -f bench_partial.json /tmp/tpu_capture/headline_partial.json 2>/dev/null

echo "== 2/4 sweep =="
python bench.py --sweep > /tmp/tpu_capture/sweep_stdout.json 2> /tmp/tpu_capture/sweep_stderr.log
echo "rc=$?"
cp -f bench_partial.json /tmp/tpu_capture/sweep_partial.json 2>/dev/null

echo "== 3/4 profile =="
python bench.py --profile /tmp/byol_profile > /tmp/tpu_capture/profile_stdout.json 2> /tmp/tpu_capture/profile_stderr.log
echo "rc=$?"

echo "== 4/4 synth learning evidence =="
python train.py --task synth --batch-size 512 --epochs 12 \
    --arch resnet18 --image-size-override 32 --head-latent-size 512 \
    --projection-size 128 --lr 0.8 --warmup 2 --fuse-views \
    --linear-eval --uid synth_evidence \
    --log-dir runs --model-dir /tmp/synth_models \
    > /tmp/tpu_capture/synth_stdout.log 2> /tmp/tpu_capture/synth_stderr.log
echo "rc=$?"
echo "== capture done =="
