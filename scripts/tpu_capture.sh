#!/usr/bin/env bash
# Round-5 TPU capture: priority-ordered, individually-COMMITTING,
# RESUMABLE stages.
#
# Four rounds of tunnel outages (BENCH_r01-r04 all stale) proved the
# monolithic round-3/4 pipeline needs tens of minutes of continuous
# uptime, while the tunnel's actual windows can be shorter.  This version
# converts ANY window into committed evidence:
#   - stages run in descending information value; stage 1 is the
#     minimum-viable capture (bench.py --mvc: fresh non-stale headline +
#     the rematted bs512 sweep row) sized for a <10-minute window;
#   - every successful stage commits its artifacts to git IMMEDIATELY
#     (evidence/tpu_r5/ + the root bench files), so a mid-capture drop
#     loses only the in-flight stage;
#   - a committed stage marker makes re-runs skip finished stages: the
#     watcher relaunches this script on every reachable window until it
#     exits 0 (all stages done);
#   - the tunnel is re-probed between stages; a dead probe exits 2 so the
#     watcher resumes waiting instead of burning the window on doomed
#     invocations.  A stage that FAILS with the tunnel still alive falls
#     through to the next stage (it retries next window) — a
#     deterministic failure in one stage must not block the stages below
#     it;
#   - partial rows from a failed stage are still committed (bench.py
#     flushes incrementally and exits nonzero on truncation, so a
#     mid-stage drop can never be mistaken for stage completion).
# Exit codes: 0 = all stages complete, 2 = tunnel lost, 1 = some stage
# failed with the tunnel alive (retry next window).
set -u
cd "$(dirname "$0")/.."
. scripts/tpu_probe.sh
ART=evidence/tpu_r5
mkdir -p "$ART" /tmp/tpu_capture

snapshot_watch() {
    # the outage/uptime record travels with every stage commit (VERDICT
    # r4 item 7: keep the outage log honest, in-repo, with timestamps)
    cp -f /tmp/tpu_watch/status "$ART/watch_status.txt" 2>/dev/null || true
}

commit_stage() {    # commit_stage <name> [path]...
    local name="$1"; shift
    snapshot_watch
    # keep only paths that exist: one unmatched pathspec makes git add
    # AND git commit abort entirely, silently committing nothing (e.g.
    # bench_sweep.json.prev is absent until a second sweep run)
    local paths=("$ART") p
    for p in "$@"; do [ -e "$p" ] && paths+=("$p"); done
    git add -A -- "${paths[@]}" 2>/dev/null
    # pathspec form: never sweeps up unrelated in-progress edits
    git commit -q -m "TPU capture: $name" -- "${paths[@]}" || true
}

require_tunnel() {
    if ! tpu_probe; then
        echo "== tunnel lost before stage $1; exiting for resume =="
        commit_stage "watch-status snapshot"
        exit 2
    fi
}

FAILED=0

# ---- stage 1: minimum-viable capture (<10 min) ------------------------
# Fresh non-stale headline (one rung per family at best-known batch) +
# the rematted bs512 sweep row no round has landed.
if [ ! -e "$ART/mvc.done" ]; then
    require_tunnel mvc
    echo "== stage mvc =="
    python bench.py --mvc > /tmp/tpu_capture/mvc_stdout.json \
                         2> /tmp/tpu_capture/mvc_stderr.log
    rc=$?
    if [ "$rc" -eq 0 ] && grep -q '"value"' /tmp/tpu_capture/mvc_stdout.json; then
        cp -f /tmp/tpu_capture/mvc_stdout.json "$ART/mvc_stdout.json"
        cp -f /tmp/tpu_capture/mvc_stderr.log "$ART/mvc_stderr.log"
        touch "$ART/mvc.done"
        commit_stage "minimum-viable headline + rematted bs512 row" \
            bench_partial.json bench_partial.json.prev
    else
        echo "mvc failed rc=$rc (stderr tail):"
        tail -5 /tmp/tpu_capture/mvc_stderr.log
        # partial rows (if any) are still worth committing
        commit_stage "partial mvc rows" \
            bench_partial.json bench_partial.json.prev
        FAILED=1
    fi
fi

# ---- stage 2: profile trace + top-ops table ---------------------------
# The MFU-lever input: which non-conv op is #1.  Compile mostly cached
# from stage 1.
if [ ! -e "$ART/trace_top_ops.txt" ]; then
    require_tunnel profile
    echo "== stage profile =="
    rm -rf /tmp/byol_profile    # a stale trace must not masquerade
    python bench.py --profile /tmp/byol_profile \
        > /tmp/tpu_capture/profile_stdout.json \
        2> /tmp/tpu_capture/profile_stderr.log
    rc=$?
    if [ "$rc" -eq 0 ]; then
        # /tmp first: a failed table-build must not leave the stage
        # marker ($ART/trace_top_ops.txt) behind and mask the failure
        if python scripts/trace_top_ops.py /tmp/byol_profile 40 \
               > /tmp/tpu_capture/trace_top_ops.txt 2>&1; then
            mv /tmp/tpu_capture/trace_top_ops.txt "$ART/trace_top_ops.txt"
            cp -f /tmp/tpu_capture/profile_stdout.json "$ART/profile_stdout.json"
            commit_stage "profile trace top-ops table" \
                bench_partial.json bench_partial.json.prev
        else
            echo "trace_top_ops failed:"
            tail -5 /tmp/tpu_capture/trace_top_ops.txt
            FAILED=1
        fi
    else
        echo "profile failed rc=$rc"
        tail -5 /tmp/tpu_capture/profile_stderr.log
        FAILED=1
    fi
fi

# ---- stage 3: stem A/B ------------------------------------------------
if [ ! -e "$ART/stem_ab_stdout.json" ]; then
    require_tunnel stem_ab
    echo "== stage stem_ab =="
    python bench.py --stem-ab > /tmp/tpu_capture/stem_ab_stdout.json \
                             2> /tmp/tpu_capture/stem_ab_stderr.log
    rc=$?
    if [ "$rc" -eq 0 ] && grep -q '"stem_ab' /tmp/tpu_capture/stem_ab_stdout.json; then
        cp -f /tmp/tpu_capture/stem_ab_stdout.json "$ART/stem_ab_stdout.json"
        commit_stage "stem conv vs space_to_depth A/B" \
            bench_partial.json bench_partial.json.prev
    else
        echo "stem_ab failed rc=$rc"
        tail -5 /tmp/tpu_capture/stem_ab_stderr.log
        FAILED=1
    fi
fi

# ---- stage 4: ViT-B/16 dense (BASELINE config 5, first-ever rows) -----
if [ ! -e "$ART/vit_dense_stdout.json" ]; then
    require_tunnel vit_dense
    echo "== stage vit_dense =="
    python bench.py --arch vit_b16 > /tmp/tpu_capture/vit_dense_stdout.json \
                                  2> /tmp/tpu_capture/vit_dense_stderr.log
    rc=$?
    if [ "$rc" -eq 0 ] && grep -q '"value"' /tmp/tpu_capture/vit_dense_stdout.json; then
        cp -f /tmp/tpu_capture/vit_dense_stdout.json "$ART/vit_dense_stdout.json"
        commit_stage "ViT-B/16 dense-attention rows" bench_partial_vit_b16.json
    else
        echo "vit_dense failed rc=$rc"
        tail -5 /tmp/tpu_capture/vit_dense_stderr.log
        commit_stage "partial vit_dense rows" bench_partial_vit_b16.json
        FAILED=1
    fi
fi

# ---- stage 5: ViT-B/16 Pallas flash A/B -------------------------------
if [ ! -e "$ART/vit_flash_stdout.json" ]; then
    require_tunnel vit_flash
    echo "== stage vit_flash =="
    python bench.py --arch vit_b16 --attn flash \
        > /tmp/tpu_capture/vit_flash_stdout.json \
        2> /tmp/tpu_capture/vit_flash_stderr.log
    rc=$?
    if [ "$rc" -eq 0 ] && grep -q '"value"' /tmp/tpu_capture/vit_flash_stdout.json; then
        cp -f /tmp/tpu_capture/vit_flash_stdout.json "$ART/vit_flash_stdout.json"
        commit_stage "ViT-B/16 Pallas flash-attention rows" \
            bench_partial_vit_b16_flash.json
    else
        echo "vit_flash failed rc=$rc"
        tail -5 /tmp/tpu_capture/vit_flash_stderr.log
        commit_stage "partial vit_flash rows" bench_partial_vit_b16_flash.json
        FAILED=1
    fi
fi

# ---- stage 6: full sweep (reuses MVC's remat row + committed rows) ----
# bench.py exits 3 when a backend death truncated the grid, so a partial
# sweep can never be marked done here.
if [ ! -e "$ART/sweep_stdout.json" ]; then
    require_tunnel sweep
    echo "== stage sweep =="
    python bench.py --sweep > /tmp/tpu_capture/sweep_stdout.json \
                           2> /tmp/tpu_capture/sweep_stderr.log
    rc=$?
    if [ "$rc" -eq 0 ]; then
        cp -f /tmp/tpu_capture/sweep_stdout.json "$ART/sweep_stdout.json"
        commit_stage "remat x fuse x batch sweep table" \
            bench_sweep.json bench_sweep.json.prev \
            bench_partial.json bench_partial.json.prev
    else
        echo "sweep failed rc=$rc"
        tail -5 /tmp/tpu_capture/sweep_stderr.log
        # an interrupted sweep still measured rows -> commit for resume
        commit_stage "partial sweep rows" \
            bench_sweep.json bench_sweep.json.prev \
            bench_partial.json bench_partial.json.prev
        FAILED=1
    fi
fi

# ---- stage 7: full headline ladder ------------------------------------
# The complete two-rung-per-family run (compile-cached by earlier
# stages); leaves the committed root artifact in its richest state.
if [ ! -e "$ART/headline_stdout.json" ]; then
    require_tunnel headline
    echo "== stage headline =="
    python bench.py > /tmp/tpu_capture/headline_stdout.json \
                   2> /tmp/tpu_capture/headline_stderr.log
    rc=$?
    if [ "$rc" -eq 0 ] && ! grep -q '"stale"' /tmp/tpu_capture/headline_stdout.json; then
        cp -f /tmp/tpu_capture/headline_stdout.json "$ART/headline_stdout.json"
        commit_stage "full headline ladder" \
            bench_partial.json bench_partial.json.prev
    else
        echo "headline failed/stale rc=$rc"
        tail -5 /tmp/tpu_capture/headline_stderr.log
        commit_stage "partial headline rows" \
            bench_partial.json bench_partial.json.prev
        FAILED=1
    fi
fi

# ---- stage 8: synth learning-evidence run (longest, lowest priority) --
if [ ! -e "$ART/synth.done" ]; then
    require_tunnel synth
    echo "== stage synth =="
    python train.py --task synth --batch-size 512 --epochs 12 \
        --arch resnet18 --image-size-override 32 --head-latent-size 512 \
        --projection-size 128 --lr 0.8 --warmup 2 --fuse-views \
        --linear-eval --uid synth_evidence \
        --log-dir runs --model-dir /tmp/synth_models \
        > /tmp/tpu_capture/synth_stdout.log 2> /tmp/tpu_capture/synth_stderr.log
    rc=$?
    if [ "$rc" -eq 0 ]; then
        tail -30 /tmp/tpu_capture/synth_stdout.log > "$ART/synth_tail.log"
        touch "$ART/synth.done"
        commit_stage "TPU synth learning-evidence run"
    else
        echo "synth failed rc=$rc"
        tail -5 /tmp/tpu_capture/synth_stderr.log
        FAILED=1
    fi
fi

if [ "$FAILED" -ne 0 ]; then
    echo "== capture pass finished with failed stage(s); will retry =="
    exit 1
fi
echo "== capture complete: all stages done =="
exit 0
