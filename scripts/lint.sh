#!/usr/bin/env bash
# Static-analysis gate: graphlint over the shipped byol_tpu/ tree, over
# tools/graphlint/ itself (self-hosting, ISSUE 17: the linter must hold
# to its own rules — GL103 name hygiene, GL110 strict JSON, ...), and
# (wave 4, ISSUE 19) over the driver/tooling surface too: scripts/*.py,
# bench.py, train.py — the files that print the evidence JSON and bind
# the jitted entry points, where GL110/GL102-shaped bugs actually lived.
#
# Default run (no args) produces both outputs from ONE engine run:
#   - human text on stdout (findings as path:line:col: RULE message),
#     ending with the schema-v3 timing footer — total wall time + the
#     slowest rules, incl. the shared whole-program "project-resolution"
#     pass — so the cross-module layer can't silently blow up lint time;
#   - machine JSON at evidence/graphlint.json (schema in
#     tools/graphlint/reporters.py), committed so rule-count trends are
#     diffable across PRs.
# It also enforces the suppression-trend ratchet (--trend-baseline): the
# run FAILS when any rule's suppression count grew vs the committed
# evidence file, and on an alarm the evidence file is left untouched so
# the grown count can never silently become the new baseline.
#
# Extra args (e.g. `scripts/lint.sh --select GL103`) pass through but
# SKIP the evidence write and the trend ratchet — a partial-rule sweep
# must never overwrite (or ratchet against) the committed full-sweep
# trend file.
#
# Exit: 0 clean, 1 findings, 2 usage error — same contract as
# `python -m tools.graphlint`.  Tier-1 shells the same entrypoint
# (tests/test_graphlint.py::TestTreeGate), so DOTS_PASSED gates the lint
# even where this script never runs.
set -uo pipefail
cd "$(dirname "$0")/.."

# pure-AST tool: force the cheap backend so an axon/TPU session env can
# never make a lint hang on accelerator init
export JAX_PLATFORMS=cpu

if [ "$#" -eq 0 ]; then
    mkdir -p evidence
    exec python -m tools.graphlint byol_tpu/ tools/graphlint/ \
        scripts/ bench.py train.py \
        --trend-baseline evidence/graphlint.json \
        --out evidence/graphlint.json
fi
exec python -m tools.graphlint byol_tpu/ tools/graphlint/ \
    scripts/ bench.py train.py "$@"
