#!/usr/bin/env bash
# One-command TPU evidence capture for RESULTS.md — run when a real chip is
# attached (jax.devices() shows TPU).  Produces, in order:
#   1. the headline benchmark artifact     -> bench_partial.json + stdout line
#   2. the batch x remat x fuse sweep      -> bench_sweep.json
#   3. a profiler trace of the best config -> /tmp/byol_profile
#   4. a learnable-dataset training run with decreasing BYOL loss and an
#      offline linear probe                -> runs/<uid>/metrics.jsonl
# Each stage is independent; a failure in one does not block the next.
set -u
cd "$(dirname "$0")/.."

echo "== 1/4 headline bench =="
python bench.py || echo "bench failed (see stderr)"

echo "== 2/4 sweep =="
python bench.py --sweep || echo "sweep failed"

echo "== 3/4 profile =="
python bench.py --profile /tmp/byol_profile || echo "profile failed"

echo "== 4/4 synth learning evidence =="
python train.py --task synth --batch-size 512 --epochs 12 \
    --arch resnet18 --image-size-override 32 --head-latent-size 512 \
    --projection-size 128 --lr 0.8 --warmup 2 --fuse-views \
    --linear-eval --uid synth_evidence \
    --log-dir runs --model-dir /tmp/synth_models || echo "evidence run failed"
echo "metrics at runs/<run-name>/ (tfevents); commit them with RESULTS.md"
