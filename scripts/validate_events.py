#!/usr/bin/env python
"""Validate JSONL event logs against the observability/events.py schema.

CI wiring (ISSUE 8 satellite): every event stream the repo emits —
trainer.fit's run.jsonl, bench.py's bench_events.jsonl, the serving CLI's
serve.jsonl — claims the same schema; this script round-trips each given
file through the STRICT reader (``read_events``: per-line JSON parse +
per-kind required-field validation) so a writer drifting from the schema
fails the build instead of silently producing logs no tool can parse.

Usage: ``python scripts/validate_events.py [--require k1,k2] FILE ...``
Exits non-zero on the first invalid file, naming the line.  A missing
file is an error (CI passes exactly the files the preceding steps
produced); an empty file is an error too — a step that claims to emit
events and emits none is itself drift.

``--require goodput,span_stats`` additionally demands that EVERY given
file carry at least one event of each named kind — the ISSUE 9 CI gate:
a smoke fit whose run.jsonl lacks the goodput partition means the span
-> goodput pipeline silently detached from the trainer.  (The kinds
themselves — including the goodput partition identity, badput buckets
summing to wall time within 1% — are validated per line by the schema
module; this flag only asserts presence.)

Pure-stdlib + numpy import chain (events.py), no jax: safe to run before
or after any backend-touching step.
"""
from __future__ import annotations

import collections
import importlib.util
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_events_module():
    """Load observability/events.py by PATH, bypassing the byol_tpu
    package __init__ (which drags in telemetry and therefore jax) — the
    schema module itself needs only stdlib + numpy, and this script must
    stay runnable in environments with no accelerator stack."""
    path = os.path.join(_ROOT, "byol_tpu", "observability", "events.py")
    spec = importlib.util.spec_from_file_location("_events_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def validate(path: str, require=()) -> str:
    events_mod = _load_events_module()
    kinds = collections.Counter()
    for event in events_mod.read_events(path):
        kinds[event["kind"]] += 1
    if not kinds:
        raise ValueError(f"{path}: no events — the emitting step wrote an "
                         "empty log")
    missing = [k for k in require if not kinds.get(k)]
    if missing:
        raise ValueError(
            f"{path}: required event kind(s) {missing} absent "
            f"(present: {sorted(kinds)}) — the emitter detached from the "
            "schema it claims")
    return ", ".join(f"{k}={n}" for k, n in sorted(kinds.items()))


def main(argv) -> int:
    argv = list(argv)
    require = ()
    if argv and argv[0] == "--require":
        if len(argv) < 2:
            print(__doc__, file=sys.stderr)
            return 2
        require = tuple(k for k in argv[1].split(",") if k)
        argv = argv[2:]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    for path in argv:
        try:
            summary = validate(path, require=require)
        except (OSError, ValueError) as e:
            print(f"validate_events: FAIL {e}", file=sys.stderr)
            return 1
        print(f"validate_events: ok {path} ({summary})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
