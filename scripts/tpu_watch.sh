#!/usr/bin/env bash
# Background TPU-tunnel watcher (rounds 1-4 outage pattern: the tunnel
# drops for hours, then comes back — no reachable window may be missed).
# Loops a 60s-timeout probe matmul every ~5 min; on success, waits for
# any running pytest to finish (one CPU core: host starvation would
# distort TPU step timings) and launches scripts/tpu_capture.sh.
#
# Round-5 change: the capture is STAGED and RESUMABLE (each stage commits
# its artifacts; done-markers skip finished stages), so this watcher no
# longer exits after the first capture attempt — it keeps looping until
# the capture exits 0 (all stages complete).  A short window that lands
# only stage 1 is a success, not a lost round.
set -u
cd "$(dirname "$0")/.."
. scripts/tpu_probe.sh
mkdir -p /tmp/tpu_watch
# append, never truncate: the status file is the round's outage record
# (committed as evidence alongside any stale bench)
echo "watch started $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status

probe() {
    # NB only probe when no other process holds the chip: the TPU is
    # single-process-exclusive and a probe against a busy chip hangs
    # without meaning the tunnel is down.
    if pgrep -f "tpu_capture.sh" > /dev/null; then
        return 1
    fi
    tpu_probe
}

while true; do
    if probe; then
        echo "probe OK $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
        # wait for pytest to clear (and re-check the tunnel while waiting)
        while pgrep -f "pytest" > /dev/null; do
            echo "waiting for pytest $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
            sleep 60
        done
        if probe; then
            echo "launching capture $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
            # Pause any CPU evidence run for the duration (pattern matches
            # run_evidence.py AND run_evidence_seeds.py): one host core —
            # its load would distort the TPU-side step timings.
            EV_PIDS=$(pgrep -f "run_evidence" || true)
            # resume the frozen run EVEN IF this watcher dies mid-capture
            # (SIGTERM/HUP/kill): a stopped multi-hour training run that
            # nothing ever CONTs is a silent total loss
            [ -n "$EV_PIDS" ] && trap "kill -CONT $EV_PIDS 2>/dev/null" EXIT
            [ -n "$EV_PIDS" ] && kill -STOP $EV_PIDS 2>/dev/null
            bash scripts/tpu_capture.sh > /tmp/tpu_watch/capture.log 2>&1
            rc=$?
            [ -n "$EV_PIDS" ] && kill -CONT $EV_PIDS 2>/dev/null
            trap - EXIT
            echo "capture attempt rc=$rc $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
            if [ "$rc" -eq 0 ]; then
                echo "all capture stages complete $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
                exit 0
            fi
            # rc=2: tunnel lost mid-capture — finished stages are already
            # committed; keep looping for the next window
        fi
    else
        echo "probe down $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
    fi
    sleep 300
done
