#!/usr/bin/env bash
# Background TPU-tunnel watcher (round-3 outage pattern: the tunnel drops
# for hours, then comes back — the first reachable window must not be
# missed).  Loops a 60s-timeout probe matmul every ~5 min; on first
# success, waits for any running pytest to finish (one CPU core: host
# starvation would distort TPU step timings) and launches
# scripts/tpu_capture.sh.  Writes state to /tmp/tpu_watch/.
set -u
cd "$(dirname "$0")/.."
mkdir -p /tmp/tpu_watch
echo "watch started $(date -u +%FT%TZ)" > /tmp/tpu_watch/status

probe() {
    # NB only probe when no other process holds the chip: the TPU is
    # single-process-exclusive and a probe against a busy chip hangs
    # without meaning the tunnel is down.
    if pgrep -f "tpu_capture.sh" > /dev/null; then
        return 1
    fi
    timeout 60 python - <<'EOF' > /dev/null 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
print(float((x @ x).sum()))
EOF
}

while true; do
    if probe; then
        echo "probe OK $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
        # wait for pytest to clear (and re-check the tunnel while waiting)
        while pgrep -f "pytest" > /dev/null; do
            echo "waiting for pytest $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
            sleep 60
        done
        if probe; then
            echo "launching capture $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
            # Pause any CPU-mesh evidence run for the duration: one host
            # core — its load would distort the TPU-side step timings.
            EV_PIDS=$(pgrep -f run_evidence.py || true)
            # resume the frozen run EVEN IF this watcher dies mid-capture
            # (SIGTERM/HUP/kill): a stopped multi-hour training run that
            # nothing ever CONTs is a silent total loss
            [ -n "$EV_PIDS" ] && trap "kill -CONT $EV_PIDS 2>/dev/null" EXIT
            [ -n "$EV_PIDS" ] && kill -STOP $EV_PIDS 2>/dev/null
            bash scripts/tpu_capture.sh > /tmp/tpu_watch/capture.log 2>&1
            rc=$?
            [ -n "$EV_PIDS" ] && kill -CONT $EV_PIDS 2>/dev/null
            echo "capture done rc=$rc $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
            exit 0
        fi
    else
        echo "probe down $(date -u +%FT%TZ)" >> /tmp/tpu_watch/status
    fi
    sleep 300
done
