"""Immutable, typed configuration for byol_tpu.

Replaces the reference's module-global mutable ``args`` (see
/root/reference/main.py:35-119, mutated at main.py:119,128-130,420-425,725,
727-729,787).  Flag names mirror the reference CLI surface (SURVEY.md App B)
so users of the reference find the same knobs; derived quantities
(steps_per_epoch with drop-remainder, total_train_steps, per-replica sample
counts — reference main.py:420-425) are computed exactly once by
``resolve()`` and frozen.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Optional, Tuple


def _frozen(cls):
    return dataclasses.dataclass(frozen=True)(cls)


@_frozen
class TaskConfig:
    """Task / dataset group (reference main.py:37-53)."""

    task: str = "image_folder"          # ref default 'multi_augment_image_folder'
    data_dir: str = "./data"
    batch_size: int = 4096              # GLOBAL batch (ref main.py:41-42)
    epochs: int = 3000
    download: bool = False
    image_size_override: Optional[int] = 224  # ref main.py:46-47
    log_dir: str = "./runs"
    uid: str = ""                       # run identity (ref main.py:52-53)
    # Metric writer: 'tensorboard' | 'jsonl' | 'both' | 'null' — the
    # reference's visdom|tensorboard switch analog (main.py:452-460; visdom
    # dropped, jsonl added so committed evidence is machine-readable).
    grapher: str = "both"
    # Augmentation backend for array datasets: 'tf' (tf.data host), 'native'
    # (multithreaded C++ host kernel, data/native/), or 'device' (on-chip
    # jitted two-view augmentation, data/device_augment.py).  The latter two
    # are the DALI equivalents (reference main.py:356-382).
    data_backend: str = "tf"
    # Where the two-view train augmentation runs:
    # - 'loader': the train iterator yields materialized float32 views
    #   (whatever backend produced them) — ~8x the H2D bytes of the raw
    #   pixels at 224px (two float32 views per uint8 image).
    # - 'step'  : the train iterator yields RAW uint8 batches
    #   ({'images': (B,H,W,C) uint8, 'label': (B,)}) and the jitted train
    #   step derives per-microbatch PRNG keys from state.step and runs
    #   device_augment inside the accumulation scan — only ONE microbatch
    #   of float32 views is ever live in HBM and the separate augment
    #   dispatch disappears (training/steps.py).
    augment_placement: str = "loader"
    # Fused in-step augmentation (ops/fused_augment.py): 'on' replaces the
    # per-view chain of ~7 XLA ops the step-placement augmentation traces
    # (crop-gather, flip, jitter, grayscale — each an HBM sweep of the
    # microbatch) with one Pallas kernel pass per image (uint8 convert +
    # crop + flip + jitter + grayscale in VMEM; the separable blur stays
    # an MXU depthwise conv on the kernel's output), shard-local over the
    # data axis.  Requires augment_placement='step' (validated at
    # resolve()); 'off' lowers the exact unfused graph (HLO identity
    # pinned by test).
    fused_augment: str = "off"
    # Dataset size for the offline-learnable 'synth' task (test split is
    # 1/10th); committed evidence runs use this to stay reproducible from
    # the CLI alone.  0 = loader default (20k).
    num_synth_samples: int = 0
    # Fraction of the train split held out as a validation set (the
    # datasets-submodule loaders exposed num_valid_samples, reference
    # main.py:421-423).  0 = no valid split.  image_folder also accepts an
    # on-disk valid/ root, which wins over the fraction.
    valid_fraction: float = 0.0


@_frozen
class ModelConfig:
    """Model group (reference main.py:56-70)."""

    arch: str = "resnet50"
    representation_size: int = 2048     # must match arch in the ref (Quirk Q8);
                                        # here it is DERIVED from the registry
                                        # unless explicitly overridden.
    projection_size: int = 256          # ref main.py:61-62
    head_latent_size: int = 4096        # ref main.py:63-64 (projector hidden)
    base_decay: float = 0.996           # EMA tau_0 (ref main.py:65-66)
    # EMA scaling rule ("How to Scale Your EMA", arXiv 2307.13813): when
    # training at a different global batch than the recipe was tuned for,
    # tau must scale as tau^kappa (kappa = batch/reference_batch) to keep
    # the target-network dynamics batch-size invariant.  0 disables.
    ema_scaling_reference_batch: int = 0
    weight_initialization: Optional[str] = None  # ref main.py:67-68
    model_dir: str = ".models"
    # TPU-native additions (no reference analog):
    fuse_views: bool = False            # concat the two views into one encoder
                                        # call (2 fwds instead of 4). Changes BN
                                        # batch statistics vs the reference's
                                        # per-view forwards (main.py:244-247),
                                        # so off by default; turn on for perf.
    remat: bool = False                 # legacy all-or-nothing jax.checkpoint
                                        # of every encoder block (= policy
                                        # 'full'); kept for back-compat.
    remat_policy: str = "none"          # named SELECTIVE checkpoint policy
                                        # (core/remat.py POLICY_NAMES:
                                        # none|full|nothing|dots|
                                        # dots_no_batch|save_block_out|
                                        # offload_block_out); wins over the
                                        # bool when not 'none'.
    stem: str = "conv"                  # resnet stem: 'conv' (7x7/2) or
                                        # 'space_to_depth' (identical numerics,
                                        # MXU-friendly 4x4/1 rearrangement).
    attn_impl: str = "dense"            # ViT attention backend: 'dense'
                                        # (XLA), 'flash' (Pallas), 'ring'
                                        # (sequence-parallel over the mesh).
    pooling: str = "cls"                # ViT feature pooling: 'cls' | 'gap'.


@_frozen
class RegularizerConfig:
    """Regularizer group (reference main.py:72-78)."""

    color_jitter_strength: float = 1.0
    # 'reference': the symmetric torchvision stack (main.py:386-397).
    # 'paper': BYOL's asymmetric recipe (arXiv 2006.07733 App B — solarize +
    # asymmetric blur; the spec behind 74.3% that the reference never had).
    # tf data backend only.
    aug_spec: str = "reference"
    weight_decay: float = 1e-6
    polyak_ema: float = 0.0
    convert_to_sync_bn: bool = True     # under GSPMD jit, BN is cross-replica
                                        # by construction; False forces
                                        # per-device stats via shard_map.


@_frozen
class OptimConfig:
    """Optimization group (reference main.py:80-91)."""

    clip: float = 0.0                   # grad VALUE clip (ref main.py:619-622)
    lr: float = 0.2                     # base LR before linear scaling
    lr_update_schedule: str = "cosine"  # fixed | cosine (ref main.py:85-86)
    warmup: int = 10                    # warmup epochs (ref main.py:87)
    optimizer: str = "lars_momentum"    # registry key; 'lars_' prefix composes
    early_stop: bool = False
    # Microbatched gradient accumulation: split each global batch into
    # accum_steps microbatches inside the jitted step (lax.scan), accumulate
    # gradients, and apply ONE optimizer update + EMA tick.  The LR schedule,
    # step counters, EMA tau, and throughput accounting all see OPTIMIZER
    # steps — batch_size stays the EFFECTIVE global batch.  1 = off.
    accum_steps: int = 1
    # BN-statistics granularity under accumulation (per-microbatch
    # normalization is inherent to one-pass accumulation; this knob controls
    # how running stats tick and offers an exact-semantics oracle):
    # - 'average'    (default): normalize per microbatch; ONE running-stat
    #                tick per optimizer step using the microbatch-averaged
    #                statistics (big-batch tick granularity).
    # - 'microbatch': normalize per microbatch; k sequential running-stat
    #                ticks (the semantics of k small steps between updates).
    # - 'global'    : EXACT big-batch semantics — microbatches run under a
    #                vmapped named axis and every BatchNorm syncs statistics
    #                across it (SyncBN over microbatches), so normalization,
    #                gradients, and the single running-stat tick all match
    #                one batch-(k*m) step to fp tolerance.  Costs the
    #                big-batch memory back (all microbatches in flight):
    #                a semantics oracle for parity tests, not an HBM saver.
    accum_bn_mode: str = "average"
    # Fused LARS+EMA weight update (ops/fused_update.py): 'on' replaces the
    # optax chain + EMA tick — ~3 full-parameter elementwise HBM sweeps per
    # optimizer step — with one Pallas kernel pass over a flat segmented
    # buffer (segment norms -> trust ratios -> wd/momentum/param/EMA in one
    # read-modify-write), shard-local under --zero1 on.  Requires the
    # lars_momentum chain with --clip 0 (validated at resolve()); 'off'
    # lowers the exact unfused graph (HLO identity pinned by test).
    fused_update: str = "off"


@_frozen
class DeviceConfig:
    """Device / debug / distributed group (reference main.py:99-117)."""

    num_replicas: int = 8               # data-parallel size (mesh 'data' axis)
    workers_per_replica: int = 2
    distributed_master: str = ""        # JAX coordinator address analog
    distributed_rank: int = 0           # process_index analog
    distributed_port: int = 29300
    debug_step: bool = False            # single-minibatch smoke (ref main.py:110)
    seed: int = 1234
    # Aux hygiene (SURVEY.md §5.2/§5.3 — absent in the reference):
    check_numerics: bool = False        # jax_debug_nans: fail fast on NaN/inf
                                        # (legacy blanket check; prefer
                                        # --telemetry + --nan-policy: the
                                        # in-graph nonfinite count costs no
                                        # per-op host sync)
    # Training-health telemetry (observability/{health,telemetry,events}):
    telemetry: str = "off"              # 'off' (identical HLO to a pre-
                                        # telemetry step) | 'epoch' (one
                                        # health record at the epoch
                                        # readback) | 'step' (async lagged
                                        # readback every telemetry_interval
                                        # optimizer steps)
    telemetry_interval: int = 50        # optimizer steps between sampled
                                        # health records under 'step'
    nan_policy: str = "warn"            # non-finite grads/loss response:
                                        # 'warn' (anomaly event) | 'halt'
                                        # (state-dump event + raise)
    spans: str = "on"                   # host-side flight recorder
                                        # (observability/spans.py): 'on'
                                        # records hot-loop phase spans +
                                        # goodput/span_stats events + a
                                        # Chrome trace per run (< 2%
                                        # overhead, bench --spans-ab);
                                        # 'off' hands the hot loop a
                                        # shared no-op (records nothing)
    fault_at_step: int = 0              # >0: kill the process at step N to
                                        # exercise preemption/resume paths
    save_on_signal: bool = True         # SIGTERM (pod preemption notice) ->
                                        # checkpoint immediately, exit 143
    watchdog_timeout: float = 0.0       # >0: dump all stacks + die if an
                                        # epoch readback stalls this many
                                        # seconds (hung-collective detector)
    shard_eval: bool = False            # shard the test set across hosts
                                        # (Quirk Q9: reference evaluates the
                                        # full test set on every rank)
    half: bool = True                   # bf16 compute policy (apex-O2 analog,
                                        # ref main.py:122-124; no loss scaling
                                        # needed on TPU bf16)
    # TPU-native mesh shape: data x model x sequence. model/sequence default 1.
    model_parallel: int = 1
    sequence_parallel: int = 1
    dcn_data_parallel: int = 1          # ICI slices the data axis spans
                                        # (multi-slice pods: in-slice ICI +
                                        # cross-slice DCN collectives)
    zero1: str = "off"                  # ZeRO-1 weight-update sharding
                                        # (arXiv 2004.13336): 'on' shards
                                        # LARS momentum + the EMA target
                                        # flat leaf-partitioned over the
                                        # data axis (params stay replicated
                                        # for the forward; ~Nx less aux-
                                        # state HBM per chip); 'off' lowers
                                        # the replicated graph unchanged.
                                        # parallel/{compile_plan,zero1}.py
    flat_resident: str = "off"          # resident flat update state
                                        # (parallel/flat_state.py): 'on'
                                        # keeps LARS momentum, the EMA
                                        # target, and (under zero1) the
                                        # param shadow as ONE flat fp32
                                        # buffer each across steps — packed
                                        # once at setup, zero per-step
                                        # pack/unpack, gathers bucketed.
                                        # Requires --fused-update on;
                                        # 'off' lowers the transient graph
                                        # unchanged.
    flat_bucket_mb: int = 64            # bucket budget (MiB of gathered
                                        # bytes) for the resident layout's
                                        # coalesced all-gathers


@_frozen
class ParityConfig:
    """Faithfulness switches for reference quirks (SURVEY.md App A)."""

    loss_norm_mode: str = "paper"       # 'paper' per-row l2 | 'reference'
                                        # whole-tensor Frobenius (objective.py:8-9)
    ema_init_mode: str = "copy"         # 'copy' (paper) | 'reference'
                                        # (Quirk Q1: mean starts at 0.004*theta)
    schedule_granularity: str = "step"  # 'step' | 'epoch' (Quirk Q5)
    normalize_inputs: bool = False      # ref never normalizes (Quirk Q3)
    ema_update_mode: str = "post"       # 'post' (paper: EMA of post-update
                                        # params) | 'reference_pre' (ref EMAs
                                        # pre-update params inside forward,
                                        # main.py:255)
    zero_init_residual: bool = True     # zero-init last BN scale per block
                                        # (large-batch trick); False matches
                                        # torchvision/reference init
                                        # (main.py:436, default init)


@_frozen
class Config:
    task: TaskConfig = TaskConfig()
    model: ModelConfig = ModelConfig()
    regularizer: RegularizerConfig = RegularizerConfig()
    optim: OptimConfig = OptimConfig()
    device: DeviceConfig = DeviceConfig()
    parity: ParityConfig = ParityConfig()

    def replace(self, **sections) -> "Config":
        return dataclasses.replace(self, **sections)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        # config scalars are user-supplied finite knobs; a NaN landing in
        # one is a bug worth a loud ValueError, not a bare token in the
        # serialized config (GL110 strict-JSON discipline)
        return json.dumps(self.to_dict(), indent=2, sort_keys=True,
                          allow_nan=False)


@_frozen
class ResolvedConfig:
    """Config + derived quantities, computed once (vs reference smuggling them
    through the mutable global ``args`` at main.py:420-425,725)."""

    cfg: Config
    input_shape: Tuple[int, int, int]       # (H, W, C) — NHWC, TPU-native layout
    num_train_samples: int                  # per-replica (ref main.py:421)
    num_test_samples: int                   # NOT sharded in ref (main.py:422)
    output_size: int                        # number of classes
    steps_per_train_epoch: int              # drop-remainder (ref main.py:424)
    total_train_steps: int                  # ref main.py:425
    batch_size_per_replica: int             # global // num_replicas (ref main.py:725)
    representation_size: int                # derived from arch registry (fixes Q8)
    num_valid_samples: int = 0              # per-replica (ref main.py:423).
                                            # Informational parity surface:
                                            # the reference derives it onto
                                            # args and barely consumes it;
                                            # loader counts stay the
                                            # authoritative split sizes.

    @property
    def global_batch_size(self) -> int:
        return self.cfg.task.batch_size

    @property
    def accum_steps(self) -> int:
        return self.cfg.optim.accum_steps

    @property
    def microbatch_size(self) -> int:
        """GLOBAL microbatch size: the batch each accumulation scan
        iteration forwards (= effective batch when accumulation is off)."""
        return self.cfg.task.batch_size // self.cfg.optim.accum_steps


def resolve(cfg: Config, *, num_train_samples: int, num_test_samples: int,
            output_size: int, input_shape: Tuple[int, int, int],
            representation_size: Optional[int] = None,
            num_valid_samples: int = 0) -> ResolvedConfig:
    """Derive load-bearing quantities exactly as the reference does.

    Reference math (main.py:420-425,725):
      - per-replica batch  = global_batch // num_replicas
      - per-replica train samples = num_train_samples // num_replicas
      - per-replica valid samples = num_valid_samples // num_replicas
        (main.py:423 divides valid like train; test stays global)
      - steps_per_train_epoch = per_replica_samples // per_replica_batch  (drop remainder)
      - total_train_steps = epochs * steps_per_train_epoch
    These feed the EMA tau schedule (main.py:160,425) so they must match.
    """
    n_rep = cfg.device.num_replicas
    if cfg.task.batch_size % n_rep != 0:
        raise ValueError(
            f"global batch {cfg.task.batch_size} not divisible by "
            f"num_replicas {n_rep}")
    accum = cfg.optim.accum_steps
    if accum < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum}")
    if cfg.task.batch_size % (accum * n_rep) != 0:
        # each scan iteration must shard its microbatch over the data axis
        # without resharding: n_rep | (batch / accum)
        raise ValueError(
            f"global batch {cfg.task.batch_size} not divisible by "
            f"accum_steps x num_replicas = {accum} x {n_rep}")
    if cfg.optim.accum_bn_mode not in ("average", "microbatch", "global"):
        raise ValueError(
            f"unknown accum_bn_mode {cfg.optim.accum_bn_mode!r}; "
            "'average' | 'microbatch' | 'global'")
    if cfg.task.augment_placement not in ("loader", "step"):
        raise ValueError(
            f"unknown augment_placement {cfg.task.augment_placement!r}; "
            "'loader' | 'step'")
    if cfg.device.telemetry not in ("off", "epoch", "step"):
        raise ValueError(
            f"unknown telemetry mode {cfg.device.telemetry!r}; "
            "'off' | 'epoch' | 'step'")
    if cfg.device.telemetry_interval < 1:
        raise ValueError(
            f"telemetry_interval must be >= 1, got "
            f"{cfg.device.telemetry_interval}")
    if cfg.device.nan_policy not in ("warn", "halt"):
        raise ValueError(
            f"unknown nan_policy {cfg.device.nan_policy!r}; "
            "'warn' | 'halt'")
    if cfg.device.spans not in ("on", "off"):
        raise ValueError(
            f"unknown spans mode {cfg.device.spans!r}; 'on' | 'off'")
    if cfg.device.zero1 not in ("off", "on"):
        raise ValueError(
            f"unknown zero1 mode {cfg.device.zero1!r}; 'off' | 'on'")
    if cfg.device.zero1 == "on" and cfg.device.model_parallel > 1:
        # ZeRO-1 is data-parallel weight-update sharding; a TP'd head's
        # opt-state leaves are already sharded over 'model'
        # (parallel/partitioning.py) and the flat layout would clobber that
        raise ValueError(
            "--zero1 on does not compose with --model-parallel > 1 "
            "(tensor parallelism already shards those optimizer-state "
            "leaves over the 'model' axis)")
    if cfg.optim.fused_update not in ("off", "on"):
        raise ValueError(
            f"unknown fused_update mode {cfg.optim.fused_update!r}; "
            "'off' | 'on'")
    if cfg.optim.fused_update == "on":
        # the kernel implements exactly the lars_momentum chain; any other
        # optimizer config would silently train with different math
        from byol_tpu.optim.factory import fused_update_unsupported_reason
        reason = fused_update_unsupported_reason(cfg.optim.optimizer,
                                                 cfg.optim.clip)
        if reason is not None:
            raise ValueError(f"--fused-update on: {reason}")
        if cfg.device.model_parallel > 1:
            # the replicated-layout kernel runs under a shard_map with
            # fully-replicated specs — it would silently all-gather the
            # TP-sharded head params/opt-state leaves every step (the
            # same non-composition --zero1 on rejects above)
            raise ValueError(
                "--fused-update on does not compose with "
                "--model-parallel > 1 (tensor parallelism shards head "
                "opt-state leaves over 'model'; the fused kernel's flat "
                "buffer would un-shard them every step)")
    if cfg.device.flat_resident not in ("off", "on"):
        raise ValueError(
            f"unknown flat_resident mode {cfg.device.flat_resident!r}; "
            "'off' | 'on'")
    if cfg.device.flat_resident == "on":
        if cfg.optim.fused_update != "on":
            raise ValueError(
                "--flat-resident on requires --fused-update on: the "
                "resident buffers are laid out for (and consumed by) the "
                "fused kernel — the optax chain has no flat entry point")
        if cfg.device.model_parallel > 1:
            raise ValueError(
                "--flat-resident on lays the update state out over the "
                "data axis; it does not compose with --model-parallel > 1")
        if cfg.device.flat_bucket_mb < 1:
            raise ValueError(
                "--flat-bucket-mb must be >= 1, got "
                f"{cfg.device.flat_bucket_mb}")
    if cfg.task.fused_augment not in ("off", "on"):
        raise ValueError(
            f"unknown fused_augment mode {cfg.task.fused_augment!r}; "
            "'off' | 'on'")
    if cfg.task.fused_augment == "on":
        if cfg.task.augment_placement != "step":
            raise ValueError(
                "--fused-augment on requires --augment-placement step: "
                "the kernel fuses the IN-STEP augmentation path (raw "
                "uint8 batches augmented inside the accumulation scan); "
                "with loader placement there is no in-step chain to fuse")
        if cfg.optim.accum_bn_mode == "global" and accum > 1:
            raise ValueError(
                "--fused-augment on does not compose with --accum-bn-mode "
                "global: the global oracle vmaps microbatches, and the "
                "augment kernel's pallas_call/shard_map cannot run under "
                "that vmap — use 'average' or 'microbatch'")
        if (cfg.device.model_parallel > 1
                or cfg.device.sequence_parallel > 1):
            raise ValueError(
                "--fused-augment on spans the data axis only (the "
                "kernel's shard_map augments each chip's batch shard); "
                "model/sequence-parallel meshes are not yet supported — "
                "run those with --fused-augment off")
    if cfg.device.nan_policy == "halt" and cfg.device.telemetry == "off":
        # the sink that enforces halt only exists when telemetry is on —
        # accepting this combination would silently train through NaNs,
        # the exact failure the policy exists to stop
        raise ValueError(
            "--nan-policy halt requires --telemetry epoch|step (the "
            "non-finite check lives in the telemetry health vector; with "
            "telemetry off nothing would enforce the halt)")
    from byol_tpu.core.remat import resolve_policy_name
    resolve_policy_name(cfg.model.remat, cfg.model.remat_policy)  # fail fast
    per_replica_batch = cfg.task.batch_size // n_rep
    per_replica_train = num_train_samples // n_rep
    steps_per_epoch = per_replica_train // per_replica_batch
    if steps_per_epoch == 0:
        raise ValueError(
            f"steps_per_train_epoch is 0: {per_replica_train} per-replica "
            f"samples < per-replica batch {per_replica_batch}")
    rep_size = representation_size
    if rep_size is None:
        # Derive from the backbone registry (the Quirk Q8 fix) — the config
        # field is only a fallback for archs not yet registered.
        try:
            from byol_tpu.models.registry import get_spec
            rep_size = get_spec(cfg.model.arch).feature_dim
        except ValueError:
            rep_size = cfg.model.representation_size
    return ResolvedConfig(
        cfg=cfg,
        input_shape=tuple(input_shape),
        num_train_samples=per_replica_train,
        num_test_samples=num_test_samples,
        output_size=output_size,
        steps_per_train_epoch=steps_per_epoch,
        total_train_steps=cfg.task.epochs * steps_per_epoch,
        batch_size_per_replica=per_replica_batch,
        representation_size=rep_size,
        num_valid_samples=num_valid_samples // n_rep,
    )


def run_name(cfg: Config) -> str:
    """Deterministic run name from config + uid.

    Contract of ``helpers.utils.get_name(args)`` (reference main.py:454,460):
    run identity names the TB logdir / checkpoint dir.
    """
    blob = cfg.to_json().encode()
    digest = hashlib.sha1(blob).hexdigest()[:8]
    uid = cfg.task.uid or "byol"
    return f"{uid}_{cfg.model.arch}_b{cfg.task.batch_size}_{digest}"
