"""Mixed-precision dtype policy.

TPU-native replacement for NVIDIA apex AMP O2 (reference main.py:122-124,
745-746, 613-617): compute in bfloat16, keep params / BN statistics / EMA
trees in float32.  bf16 has fp32's exponent range, so the apex loss-scaling
machinery (amp.scale_loss, main.py:614-615) has no TPU equivalent and is
intentionally absent.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32

    def cast_to_compute(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def cast_to_param(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.param_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)

    def cast_to_output(self, tree):
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.output_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


FP32 = Policy()
# apex-O2 analog: bf16 activations/compute, fp32 master params + BN stats.
BF16 = Policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
              output_dtype=jnp.float32)


def get_policy(half: bool) -> Policy:
    """Map the reference's ``--half`` flag (main.py:116-117) to a policy."""
    return BF16 if half else FP32
