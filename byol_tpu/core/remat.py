"""Named selective rematerialization policies.

The all-or-nothing per-block ``nn.remat`` (``--remat``) saves nothing but
block inputs, so the backward pass re-runs every conv in a block — and the
resulting backward graph wedged XLA's compiler for 45+ minutes at the bs1024
rung (RESULTS.md §1 outage history).  Selective policies keep the expensive
tensors (MXU outputs: conv / dot results) and recompute only the cheap
elementwise/normalization chains between them, which both bounds the FLOPs
overhead (<~30% for conv nets) and keeps the backward HLO close enough to
the un-rematted graph that compile times stay sane.

Policy names (``--remat-policy``, ``core.config.ModelConfig.remat_policy``):

- ``none``          : no rematerialization (policy plumbing inert).
- ``full``          : per-block ``nn.remat`` with the default save-nothing
                      behavior — the legacy ``--remat`` flag, kept for
                      comparison; known compile hazard at large batch.
- ``nothing``       : explicit ``nothing_saveable`` policy (same residual
                      footprint as ``full``, spelled as a policy so it goes
                      through the same code path as the selective ones).
- ``dots``          : ``dots_saveable`` — save every conv/matmul result,
                      recompute elementwise/BN/activation chains.  The
                      recommended default for ResNet/ViT under microbatch
                      accumulation.
- ``dots_no_batch`` : ``dots_with_no_batch_dims_saveable`` — save only
                      contractions with no batch dims (weight-gradient
                      style); leaner than ``dots``, more recompute.
- ``save_block_out``: save ONLY the tensors tagged ``block_out`` (each
                      residual-block / encoder-block output,
                      ``checkpoint_name`` tags in models/resnet.py and
                      models/vit.py); everything inside a block is
                      recomputed.  The minimal-HBM non-offloading policy.
- ``offload_block_out``: as ``save_block_out`` but the tagged block outputs
                      are offloaded to pinned host memory instead of held
                      in HBM (``save_and_offload_only_these_names``).
                      Requires a backend with pinned-host support; validate
                      with :func:`validate_policy` before building.

Models apply a policy per residual/encoder block via :func:`wrap_block`, so
the checkpoint boundary is the block — the granularity the stage/block
``checkpoint_name`` tags are designed around.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax

# The tag models put on every residual/encoder block output (see
# models/resnet.py and models/vit.py).  Offloadable / save-only policies key
# on this name.
BLOCK_OUT = "block_out"

POLICY_NAMES = ("none", "full", "nothing", "dots", "dots_no_batch",
                "save_block_out", "offload_block_out")

# Policies that key on checkpoint_name tags: if the traced graph carries no
# tag, these silently degrade to save-nothing — the exact backward graph
# that wedged XLA for 45 minutes at the bs1024 rung.
NAMES_BASED_POLICIES = ("save_block_out", "offload_block_out")


class RematTagError(ValueError):
    """A names-based remat policy matched zero checkpoint_name tags."""


def checkpoint_policy(name: str) -> Optional[Callable[..., Any]]:
    """Resolve a policy name to a ``jax.checkpoint`` policy callable.

    ``none`` and ``full`` return None (no policy argument: ``none`` means no
    remat at all; ``full`` means remat with the default save-nothing rule).
    """
    cp = jax.checkpoint_policies
    if name in ("none", "full"):
        return None
    if name == "nothing":
        return cp.nothing_saveable
    if name == "dots":
        return cp.dots_saveable
    if name == "dots_no_batch":
        return cp.dots_with_no_batch_dims_saveable
    if name == "save_block_out":
        return cp.save_only_these_names(BLOCK_OUT)
    if name == "offload_block_out":
        return cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=[BLOCK_OUT],
            offload_src="device", offload_dst="pinned_host")
    raise ValueError(
        f"unknown remat policy {name!r}; known: {POLICY_NAMES}")


def validate_policy(name: str) -> str:
    """Fail fast on typos (the --arch/--attn lesson from bench.py: a bad
    knob must not surface as every ladder rung 'failing to fit')."""
    if name not in POLICY_NAMES:
        raise ValueError(
            f"unknown remat policy {name!r}; known: {POLICY_NAMES}")
    return name


def wrap_block(block_cls, policy_name: str):
    """Wrap a flax Module class in ``nn.remat`` per the named policy.

    ``none`` returns the class untouched; ``full`` is plain ``nn.remat``
    (save nothing); every other name attaches the selective policy.
    """
    validate_policy(policy_name)
    if policy_name == "none":
        return block_cls
    policy = checkpoint_policy(policy_name)
    if policy is None:
        return nn.remat(block_cls)
    return nn.remat(block_cls, policy=policy)


def resolve_policy_name(remat: bool, remat_policy: str) -> str:
    """Merge the legacy ``--remat`` bool with the named-policy knob.

    The bool is kept as a back-compat alias for ``full``; an explicit
    policy name wins over it.
    """
    validate_policy(remat_policy)
    if remat_policy != "none":
        return remat_policy
    return "full" if remat else "none"


def tag_block_out(x):
    """Tag a block output so named policies can save/offload it.

    A no-op unless a surrounding ``jax.checkpoint`` uses a names-based
    policy; safe (identity) everywhere else, including eval and init.
    """
    from jax.ad_checkpoint import checkpoint_name
    return checkpoint_name(x, BLOCK_OUT)


def _collect_tags(jaxpr, tags: set) -> None:
    """Gather every ``checkpoint_name`` tag in a jaxpr, recursing into
    sub-jaxprs (remat bodies, scan/cond/pjit/custom-vjp closures)."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "name":
            tags.add(eqn.params.get("name"))
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else (val,)):
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    _collect_tags(inner, tags)


def tags_in_trace(fn, *args, **kwargs) -> set:
    """The set of ``checkpoint_name`` tags ``fn``'s traced graph carries.

    Abstract trace only (``jax.make_jaxpr``): no compile, no execution —
    cheap enough to run at setup time on CPU.
    """
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    tags: set = set()
    _collect_tags(closed.jaxpr, tags)
    return tags


def assert_tags_in_trace(fn, *args, policy_name: str, **kwargs) -> set:
    """Runtime complement to graphlint's GL105: raise :class:`RematTagError`
    when a names-based policy would match zero tags in ``fn``'s traced
    graph (instead of silently saving nothing).

    No-op (returns an empty set without tracing) for policies that do not
    key on tags.  The AST rule catches statically-visible drift; this
    catches models assembled dynamically, where the linter cannot see the
    block class.
    """
    if policy_name not in NAMES_BASED_POLICIES:
        return set()
    tags = tags_in_trace(fn, *args, **kwargs)
    if BLOCK_OUT not in tags:
        raise RematTagError(
            f"remat policy {policy_name!r} keys on checkpoint_name tag "
            f"{BLOCK_OUT!r}, but the traced graph carries no such tag "
            f"(found: {sorted(t for t in tags if t) or 'none'}). The "
            "policy would silently save NOTHING — the save-nothing "
            "backward graph is the known XLA compile hazard. A model "
            "block probably lost its tag_block_out call.")
    return tags
