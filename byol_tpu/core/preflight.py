"""Killable accelerator preflight for unattended entry points.

A SIGTERM-killed TPU run can wedge the tunneled backend such that the NEXT
process's backend initialization blocks forever inside native code — where
it cannot be interrupted from Python.  An unattended run (a benchmark, a
scheduled training job, the reference's `sbatch run.sh` analog) then hangs
with no explanation instead of failing.  The reference had no equivalent
guard — a dead NCCL peer likewise hung or crashed the job and the operator
was told to expect it (reference README.md:42); this module is the
fail-fast upgrade on that story (SURVEY.md §5.3).

The probe runs a matmul WITH a scalar readback in a subprocess that can be
killed on timeout, and asserts the child actually landed on the configured
accelerator platform: on a dead accelerator jax silently falls back to cpu,
which would otherwise make the probe pass and defer the hang (or a
silent-CPU training run) to the caller.
"""
from __future__ import annotations

import subprocess
import sys

import jax


def preflight_backend(timeout_s: float = 180.0) -> bool:
    """Probe backend initialization in a killable subprocess.

    Returns True when the backend is usable (or the run is explicitly
    pinned to CPU, where there is nothing to probe); False — with the
    diagnosis on stderr — when the accelerator is unreachable.
    """
    platforms = str(jax.config.jax_platforms or "")
    if platforms == "cpu":
        return True  # explicitly pinned to CPU (tests/smokes): no probe
    # When a non-cpu platform is explicitly configured (e.g. a site plugin
    # forces "axon,cpu"), a probe child that lands on cpu means the
    # accelerator died and jax silently fell back — which must count as
    # unreachable, not as a healthy backend.
    expect_accel = bool(platforms) and platforms.split(",")[0] != "cpu"
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum()); "
             "print(jax.default_backend())"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        print(f"byol_tpu: backend failed to initialize within "
              f"{timeout_s:.0f}s — the TPU tunnel is likely wedged (a "
              "previously killed TPU process leaves it hung for hours).",
              file=sys.stderr)
        return False
    if probe.returncode != 0:
        print("byol_tpu: backend probe failed:\n" + probe.stderr[-2000:],
              file=sys.stderr)
        return False
    child_backend = probe.stdout.strip().splitlines()[-1] if probe.stdout \
        else ""
    if expect_accel and child_backend == "cpu":
        print(f"byol_tpu: platforms={platforms!r} configures an accelerator "
              "but the probe landed on cpu — the accelerator is dead and "
              "jax silently fell back.", file=sys.stderr)
        return False
    return True


def force_cpu_devices(n: int) -> None:
    """Pin the CPU platform and size an N-device virtual mesh — the
    ``--cpu-devices N`` semantics shared by bench.py and the serve CLI
    (tests/conftest.py performs the same dance inline: it must run
    before this package imports).

    Must be called before anything initializes the XLA backend; forcing
    the platform first means a half-up TPU tunnel cannot race the
    override into a mixed backend.  The XLA_FLAGS spelling is the
    pre-0.4.38 fallback for jax builds without ``jax_num_cpu_devices``.
    """
    import os
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()
