"""RNG plumbing.

Replaces the reference's ad-hoc global seeding (main.py:710-715: numpy + torch
+ cuda manual_seed) with explicit JAX PRNG key threading.  Keys are split
per-purpose and per-step; data augmentation keys are additionally folded with
the step counter so every step sees fresh, reproducible randomness — the
analog of DistributedSampler's epoch reseed (main.py:760).
"""
from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp


def root_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def split_named(key: jax.Array, names: Sequence[str]) -> Dict[str, jax.Array]:
    keys = jax.random.split(key, len(names))
    return dict(zip(names, keys))


def for_step(key: jax.Array, step) -> jax.Array:
    """Per-step derived key; `step` may be a traced int32 scalar."""
    return jax.random.fold_in(key, jnp.asarray(step, jnp.uint32))
