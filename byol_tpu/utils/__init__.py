"""Framework utilities — the ``helpers.utils`` contract (SURVEY.md §2.3).

Call-site-for-call-site equivalents of the reference's helpers submodule
surface: run-metadata introspection (AWS instance id main.py:128-130, SLURM
id main.py:775-777) and parameter counting (main.py:447-449).
``number_of_gpus``/launch topology (main.py:800-801) has no analog — JAX
owns device enumeration.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def get_slurm_id() -> Optional[str]:
    """SLURM job identity for run metadata (main.py:775-777)."""
    job = os.environ.get("SLURM_JOB_ID")
    task = os.environ.get("SLURM_ARRAY_TASK_ID")
    if job and task:
        return f"{job}_{task}"
    return job


def get_aws_instance_id(timeout: float = 0.25) -> Optional[str]:
    """EC2 instance id via the metadata endpoint (main.py:128-130); returns
    None quickly off-cloud."""
    import urllib.request
    try:
        with urllib.request.urlopen(  # noqa: S310
                "http://169.254.169.254/latest/meta-data/instance-id",
                timeout=timeout) as r:
            return r.read().decode()
    except Exception:
        return None


def get_tpu_env() -> dict:
    """TPU-native run metadata (the AWS/SLURM analog for pods)."""
    keys = ("TPU_WORKER_ID", "TPU_ACCELERATOR_TYPE", "TPU_PROCESS_BOUNDS",
            "MEGASCALE_SLICE_ID")
    return {k: os.environ[k] for k in keys if k in os.environ}


def number_of_parameters(params: Any) -> int:
    """Total parameter count of a pytree (main.py:447-449)."""
    return sum(int(np.prod(p.shape))
               for p in jax.tree_util.tree_leaves(params)
               if hasattr(p, "shape"))

# (``helpers.utils.dummy_context`` — the train-mode branch of the reference's
# no_grad switch, main.py:584 — has no JAX analog: there is no grad mode to
# toggle, so the symbol is deliberately not provided.)
