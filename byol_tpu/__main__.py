"""``python -m byol_tpu [serve] ...`` — train by default, serve on demand.

Subcommand dispatch lives here (not in cli.py) so the training surface
keeps its reference-mirroring flag-only interface: ``python -m byol_tpu
--task cifar10 ...`` trains exactly as before, ``python -m byol_tpu serve
--checkpoint ...`` stands up the embedding service (byol_tpu/serving/).
"""
import sys


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        from byol_tpu.serving.cli import main as serve_main
        return serve_main(argv[1:])
    from byol_tpu.cli import main as train_main
    return train_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
