"""``python -m byol_tpu [serve|report] ...`` — train by default.

Subcommand dispatch lives here (not in cli.py) so the training surface
keeps its reference-mirroring flag-only interface: ``python -m byol_tpu
--task cifar10 ...`` trains exactly as before, ``python -m byol_tpu serve
--checkpoint ...`` stands up the embedding service (byol_tpu/serving/),
and ``python -m byol_tpu report <run.jsonl>`` renders the offline goodput
/ step-time / serving / anomaly analysis from an event log alone
(observability/report.py — no live process or accelerator needed).
"""
import sys


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        from byol_tpu.serving.cli import main as serve_main
        return serve_main(argv[1:])
    if argv and argv[0] == "report":
        from byol_tpu.observability.report import main as report_main
        return report_main(argv[1:])
    from byol_tpu.cli import main as train_main
    return train_main(argv)


if __name__ == "__main__":
    raise SystemExit(main())
