"""Array dataset readers: CIFAR-10/100, MNIST, FashionMNIST, digits, fake.

The reference delegates simple datasets (with ``--download``) to its
``datasets`` submodule (/root/reference/main.py:44-45; SURVEY.md §2.3).  Here
they are read from the standard on-disk binary formats into numpy arrays once
and streamed through tf.data; ``download=True`` fetches the archives when the
environment has egress and fails with a clear message when it does not.

The ``fake`` backend (no reference analog — SURVEY.md §4 test strategy) is a
deterministic synthetic dataset for tests and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile
import urllib.request
from typing import Tuple

import numpy as np

Arrays = Tuple[np.ndarray, np.ndarray]  # images uint8 NHWC, labels int64


_URLS = {
    "cifar10": "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
    "cifar100": "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz",
    "mnist": "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "fashion_mnist":
        "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/",
}


def _download(url: str, dest: str) -> None:
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    try:
        urllib.request.urlretrieve(url, dest)  # noqa: S310
    except Exception as e:
        raise RuntimeError(
            f"could not download {url} (no egress?): {e}; place the archive "
            f"at {dest} manually") from e


def load_cifar10(data_dir: str, train: bool, download: bool = False) -> Arrays:
    root = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(root):
        tgz = os.path.join(data_dir, "cifar-10-python.tar.gz")
        if not os.path.exists(tgz):
            if not download:
                raise FileNotFoundError(
                    f"{root} not found; pass download=True (--download)")
            _download(_URLS["cifar10"], tgz)
        with tarfile.open(tgz) as tar:
            tar.extractall(data_dir)  # noqa: S202
    names = ([f"data_batch_{i}" for i in range(1, 6)] if train
             else ["test_batch"])
    imgs, labels = [], []
    for n in names:
        with open(os.path.join(root, n), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(d[b"data"])
        labels.extend(d[b"labels"])
    x = np.concatenate(imgs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(x), np.asarray(labels, np.int64)


def load_cifar100(data_dir: str, train: bool,
                  download: bool = False) -> Arrays:
    root = os.path.join(data_dir, "cifar-100-python")
    if not os.path.isdir(root):
        tgz = os.path.join(data_dir, "cifar-100-python.tar.gz")
        if not os.path.exists(tgz):
            if not download:
                raise FileNotFoundError(
                    f"{root} not found; pass download=True (--download)")
            _download(_URLS["cifar100"], tgz)
        with tarfile.open(tgz) as tar:
            tar.extractall(data_dir)  # noqa: S202
    with open(os.path.join(root, "train" if train else "test"), "rb") as f:
        d = pickle.load(f, encoding="bytes")
    x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(x), np.asarray(d[b"fine_labels"], np.int64)


def _load_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic = int.from_bytes(data[2:3], "big")
    ndim = data[3]
    dims = [int.from_bytes(data[4 + 4 * i:8 + 4 * i], "big")
            for i in range(ndim)]
    del magic
    return np.frombuffer(data, np.uint8, offset=4 + 4 * ndim).reshape(dims)


def _load_mnist_like(name: str, data_dir: str, train: bool,
                     download: bool) -> Arrays:
    root = os.path.join(data_dir, name)
    prefix = "train" if train else "t10k"
    files = [f"{prefix}-images-idx3-ubyte", f"{prefix}-labels-idx1-ubyte"]
    paths = []
    for f in files:
        for cand in (os.path.join(root, f), os.path.join(root, f + ".gz")):
            if os.path.exists(cand):
                paths.append(cand)
                break
        else:
            if not download:
                raise FileNotFoundError(
                    f"{os.path.join(root, f)}[.gz] not found; pass "
                    f"download=True (--download)")
            dest = os.path.join(root, f + ".gz")
            _download(_URLS[name] + f + ".gz", dest)
            paths.append(dest)
    images = _load_idx(paths[0])[..., np.newaxis]          # N,28,28,1
    images = np.tile(images, (1, 1, 1, 3))                 # grayscale -> RGB
    return images, _load_idx(paths[1]).astype(np.int64)


def load_mnist(data_dir: str, train: bool, download: bool = False) -> Arrays:
    return _load_mnist_like("mnist", data_dir, train, download)


def load_fashion_mnist(data_dir: str, train: bool,
                       download: bool = False) -> Arrays:
    return _load_mnist_like("fashion_mnist", data_dir, train, download)


def load_fake(num_samples: int = 512, image_size: int = 32,
              num_classes: int = 10, seed: int = 0) -> Arrays:
    rng = np.random.RandomState(seed)
    x = rng.randint(0, 256, size=(num_samples, image_size, image_size, 3),
                    dtype=np.uint8)
    y = rng.randint(0, num_classes, size=(num_samples,)).astype(np.int64)
    return x, y


def load_synth(num_samples: int = 10_000, image_size: int = 32,
               num_classes: int = 10, seed: int = 0, train: bool = True
               ) -> Arrays:
    """Procedural LEARNABLE dataset for offline learning-dynamics evidence.

    ``fake`` is pure noise (nothing to learn); ``synth`` gives each class a
    fixed smooth color template (4x4 noise upsampled bilinearly to full
    resolution) and renders samples as template + per-sample brightness +
    pixel noise.  Smooth templates keep local crops correlated with class
    identity, so BYOL's crop-invariance objective has real signal and the
    concurrent linear probe must beat chance by a wide margin if (and only
    if) representation learning works.  Templates depend only on
    (num_classes, image_size), never on ``train``, so train/test share
    classes but not samples.
    """
    tmpl_rng = np.random.RandomState(123)           # class identity, fixed
    rng = np.random.RandomState(seed + (0 if train else 10_007))
    # smooth per-class color fields in [0.2, 0.8]
    coarse = tmpl_rng.rand(num_classes, 4, 4, 3)
    xs = np.linspace(0, 3, image_size)
    i0 = np.clip(np.floor(xs).astype(int), 0, 2)
    frac = xs - i0                                  # (S,)
    def _up(t):                                     # bilinear 4x4 -> S x S
        t = (t[i0] * (1 - frac)[:, None, None]
             + t[i0 + 1] * frac[:, None, None])                 # rows
        t = (t[:, i0] * (1 - frac)[None, :, None]
             + t[:, i0 + 1] * frac[None, :, None])              # cols
        return t
    templates = np.stack([0.2 + 0.6 * _up(c) for c in coarse])  # (C,S,S,3)

    y = rng.randint(0, num_classes, size=(num_samples,))
    gain = rng.uniform(0.6, 1.0, size=(num_samples, 1, 1, 1))
    bias = rng.uniform(-0.1, 0.1, size=(num_samples, 1, 1, 1))
    noise = rng.normal(0.0, 0.06, size=(num_samples, image_size,
                                        image_size, 3))
    x = np.clip(templates[y] * gain + bias + noise, 0.0, 1.0)
    return (x * 255).astype(np.uint8), y.astype(np.int64)


def load_digits_img(data_dir: str = "", train: bool = True,
                    download: bool = False) -> Arrays:
    """Real handwritten-digit images (sklearn's bundled UCI digits), no
    network needed: the one REAL image dataset available in an egress-free
    environment.  1,797 8x8 grayscale digits -> nearest-upsampled to 32x32
    RGB uint8 so the standard augmentation stack (random resized crop at
    32px, color ops) applies unchanged.  Fills the simple-dataset role the
    reference delegates to its datasets submodule (main.py:44-45) when the
    canonical archives (CIFAR/MNIST) cannot be fetched.

    The split is a fixed seeded permutation (1,500 train / 297 test) —
    sklearn defines no canonical split; pinning one keeps runs comparable.
    ``data_dir``/``download`` are accepted for ARRAY_LOADERS signature
    compatibility and ignored (the data ships inside sklearn).
    """
    del data_dir, download
    try:
        from sklearn.datasets import load_digits as _sk_load
    except ImportError as e:
        raise RuntimeError(
            "--task digits needs scikit-learn (bundles the UCI digits "
            "images); it is not installed") from e
    d = _sk_load()
    x = (d.images / 16.0 * 255.0).astype(np.uint8)      # (1797, 8, 8)
    x = x.repeat(4, axis=1).repeat(4, axis=2)           # 8x8 -> 32x32
    x = np.tile(x[..., np.newaxis], (1, 1, 1, 3))       # grayscale -> RGB
    y = d.target.astype(np.int64)
    perm = np.random.RandomState(42).permutation(len(x))
    split = 1500
    idx = perm[:split] if train else perm[split:]
    return np.ascontiguousarray(x[idx]), y[idx]


ARRAY_LOADERS = {
    "cifar10": (load_cifar10, 10),
    "cifar100": (load_cifar100, 100),
    "mnist": (load_mnist, 10),
    "fashion_mnist": (load_fashion_mnist, 10),
    "digits": (load_digits_img, 10),
}
