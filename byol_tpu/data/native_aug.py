"""ctypes binding + lazy build for the native C++ augmentation pipeline.

The reference's native data path is NVIDIA DALI (C++/CUDA, SURVEY.md §2.4);
ours is ``data/native/image_pipeline.cpp`` — a multithreaded C++ kernel
producing two augmented float32 views per uint8 image with the canonical
augmentation spec.  This module compiles it on first use (g++, ~2s, cached
next to the source) and exposes numpy-in/numpy-out entry points; when no
toolchain or binary is available the loader silently stays on the tf.data
backend, so the native path is strictly opt-in acceleration
(``data_backend='native'``).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(__file__), "native")
_SRC = os.path.join(_SRC_DIR, "image_pipeline.cpp")
_LIB = os.path.join(_SRC_DIR, "libbyol_aug.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _build() -> None:
    base = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
            "-o", _LIB, _SRC]
    # Prefer the JPEG-fused build (libjpeg-turbo: fused decode+crop, the
    # DALI analog for image trees); fall back to the array-only build when
    # the system lacks jpeglib.h / -ljpeg.
    proc = subprocess.run(base + ["-DBYOL_WITH_JPEG", "-ljpeg"],
                          capture_output=True, text=True)
    if proc.returncode == 0:
        return
    proc = subprocess.run(base, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed: {proc.stderr[-2000:]}")


def load(rebuild: bool = False) -> ctypes.CDLL:
    """Load (building if needed) the native library; raises on failure."""
    global _lib, _build_error
    with _lock:
        if _lib is not None and not rebuild:
            return _lib
        if _build_error and not rebuild:
            raise RuntimeError(_build_error)
        try:
            if rebuild or not os.path.exists(_LIB) or (
                    os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_LIB)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            f32p = ctypes.POINTER(ctypes.c_float)
            lib.byol_augment_two_views.argtypes = [
                u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                f32p, f32p, ctypes.c_int, ctypes.c_float,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int]
            lib.byol_augment_two_views.restype = None
            lib.byol_resize_batch.argtypes = [
                u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                f32p, ctypes.c_int, ctypes.c_int]
            lib.byol_resize_batch.restype = None
            lib.byol_has_jpeg.argtypes = []
            lib.byol_has_jpeg.restype = ctypes.c_int
            if lib.byol_has_jpeg():
                u64p = ctypes.POINTER(ctypes.c_uint64)
                i32p = ctypes.POINTER(ctypes.c_int32)
                lib.byol_jpeg_augment_two_views.argtypes = [
                    u8p, u64p, u64p, ctypes.c_int, f32p, f32p,
                    ctypes.c_int, ctypes.c_float, ctypes.c_uint64,
                    ctypes.c_uint64, ctypes.c_int, i32p]
                lib.byol_jpeg_augment_two_views.restype = None
                lib.byol_jpeg_resize_batch.argtypes = [
                    u8p, u64p, u64p, ctypes.c_int, f32p, ctypes.c_int,
                    ctypes.c_int, i32p]
                lib.byol_jpeg_resize_batch.restype = None
            _lib = lib
            _build_error = None
            return lib
        except Exception as e:  # toolchain missing, load failure, ...
            _build_error = str(e)
            raise


def available() -> bool:
    try:
        load()
        return True
    except Exception:
        return False


def _check_batch(images: np.ndarray) -> np.ndarray:
    if images.ndim != 4 or images.shape[-1] != 3:
        raise ValueError(f"expected (N, H, W, 3) uint8, got {images.shape}")
    return np.ascontiguousarray(images, dtype=np.uint8)


def augment_two_views(images: np.ndarray, size: int, *,
                      color_jitter_strength: float = 1.0, seed: int = 0,
                      index_base: int = 0,
                      num_threads: Optional[int] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """(N, H, W, 3) uint8 -> two (N, size, size, 3) float32 views in [0,1]."""
    lib = load()
    images = _check_batch(images)
    n, h, w, _ = images.shape
    if num_threads is None:
        num_threads = min(os.cpu_count() or 1, 16)
    v1 = np.empty((n, size, size, 3), np.float32)
    v2 = np.empty((n, size, size, 3), np.float32)
    lib.byol_augment_two_views(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n, h, w,
        v1.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        v2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        size, float(color_jitter_strength), seed & (2**64 - 1),
        index_base & (2**64 - 1), num_threads)
    return v1, v2


def resize_batch(images: np.ndarray, size: int, *,
                 num_threads: Optional[int] = None) -> np.ndarray:
    """Resize-only eval transform (reference main.py:398, Quirk Q3)."""
    lib = load()
    images = _check_batch(images)
    n, h, w, _ = images.shape
    if num_threads is None:
        num_threads = min(os.cpu_count() or 1, 16)
    out = np.empty((n, size, size, 3), np.float32)
    lib.byol_resize_batch(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n, h, w,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), size,
        num_threads)
    return out


# ---- fused JPEG decode (the DALI-analog path for image trees) -------------

def has_jpeg() -> bool:
    """True when the loaded binary links libjpeg (fused decode available)."""
    try:
        return bool(load().byol_has_jpeg())
    except Exception:
        return False


def _pack_blobs(blobs) -> tuple:
    sizes = np.array([len(b) for b in blobs], np.uint64)
    offsets = np.zeros(len(blobs), np.uint64)
    np.cumsum(sizes[:-1], out=offsets[1:])
    blob = np.frombuffer(b"".join(blobs), np.uint8)
    return blob, offsets, sizes


def _decode_fallback(data: bytes) -> Optional[np.ndarray]:
    """PIL decode for the rare file the C++ path flags (non-JPEG extension
    lying about its content, CMYK, corrupt-but-PIL-tolerant)."""
    import io
    try:
        from PIL import Image
        return np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
    except Exception:
        return None


def jpeg_augment_two_views(blobs, size: int, *,
                           color_jitter_strength: float = 1.0, seed: int = 0,
                           index_base: int = 0,
                           num_threads: Optional[int] = None
                           ) -> Tuple[np.ndarray, np.ndarray]:
    """list of JPEG byte strings -> two (N, size, size, 3) float32 views.

    Fused decode+crop per view in C++ (only the sampled RandomResizedCrop
    window is decoded, DCT-scaled); files the native decoder rejects are
    re-decoded via PIL and fed through the uint8-array augment path with
    the SAME (seed, index, view) streams, so a mixed tree stays
    deterministic."""
    lib = load()
    if not lib.byol_has_jpeg():
        raise RuntimeError("native library built without libjpeg")
    n = len(blobs)
    if num_threads is None:
        num_threads = min(os.cpu_count() or 1, 16)
    blob, offsets, sizes = _pack_blobs(blobs)
    v1 = np.empty((n, size, size, 3), np.float32)
    v2 = np.empty((n, size, size, 3), np.float32)
    ok = np.empty((n,), np.int32)
    lib.byol_jpeg_augment_two_views(
        blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n, v1.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        v2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        size, float(color_jitter_strength), seed & (2**64 - 1),
        index_base & (2**64 - 1), num_threads,
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    for i in np.nonzero(ok == 0)[0]:
        img = _decode_fallback(blobs[i])
        if img is None:
            continue           # undecodable: keep the zeroed output
        a, b = augment_two_views(img[None], size,
                                 color_jitter_strength=color_jitter_strength,
                                 seed=seed, index_base=index_base + int(i),
                                 num_threads=1)
        v1[i], v2[i] = a[0], b[0]
    return v1, v2


def jpeg_resize_batch(blobs, size: int, *,
                      num_threads: Optional[int] = None) -> np.ndarray:
    """list of JPEG byte strings -> (N, size, size, 3) float32, resize-only
    (eval transform)."""
    lib = load()
    if not lib.byol_has_jpeg():
        raise RuntimeError("native library built without libjpeg")
    n = len(blobs)
    if num_threads is None:
        num_threads = min(os.cpu_count() or 1, 16)
    blob, offsets, sizes = _pack_blobs(blobs)
    out = np.empty((n, size, size, 3), np.float32)
    ok = np.empty((n,), np.int32)
    lib.byol_jpeg_resize_batch(
        blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), size,
        num_threads,
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    for i in np.nonzero(ok == 0)[0]:
        img = _decode_fallback(blobs[i])
        if img is None:
            continue
        out[i] = resize_batch(img[None], size, num_threads=1)[0]
    return out
