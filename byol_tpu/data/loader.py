"""Dataset loader bundle — the ``datasets.loader.get_loader`` contract.

Reconstructed API surface (SURVEY.md §2.3; call sites
/root/reference/main.py:24,413-423,430,475,579,760):

  bundle = get_loader(cfg)          # dispatch on cfg.task.task
  bundle.train_loader               # iterable of {'view1','view2','label'}
  bundle.test_loader                # ditto (two resized views, Quirk Q9 note)
  bundle.input_shape                # (H, W, C)
  bundle.num_train_samples          # GLOBAL counts (resolve() divides per
  bundle.num_test_samples           #  replica, core/config.py)
  bundle.output_size                # number of classes
  bundle.set_all_epochs(epoch)      # epoch reseed (DistributedSampler analog)

TPU-native differences:
- batches are dicts of numpy arrays sized for THIS HOST
  (global_batch / process_count); the trainer shards them onto the mesh's
  ``data`` axis (parallel/mesh.py), which is the per-replica split the
  reference does by mutating args.batch_size (main.py:725);
- the train set is sharded per host by ``jax.process_index()`` (the
  DistributedSampler analog); test is NOT sharded, matching the reference
  (main.py:422, Quirk Q9), unless ``shard_eval=True``;
- iteration uses drop-remainder batching, matching steps_per_train_epoch
  (main.py:424).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from byol_tpu.core.config import Config
from byol_tpu.data import readers

Batch = Dict[str, np.ndarray]


@dataclasses.dataclass
class LoaderBundle:
    """Loader bundle; iterables re-seed from the epoch set via
    ``set_all_epochs`` (reference main.py:760)."""

    make_train_iter: Callable[[int], Iterator[Batch]]  # epoch -> iterator
    make_test_iter: Callable[[int], Iterator[Batch]]
    input_shape: Tuple[int, int, int]
    num_train_samples: int
    num_test_samples: int
    output_size: int
    epoch: int = 0
    # TRAIN split under the EVAL transform (resize-only, unshuffled) — what
    # the offline linear-eval protocol trains its probe on (training/
    # linear_eval.py).  Optional: None for hand-built test bundles.
    make_train_eval_iter: Optional[Callable[[int], Iterator[Batch]]] = None
    # Whether the TEST split was sharded per host at build time (get_loader's
    # shard_eval).  Consumers (multi-host linear eval) key de-duplication off
    # this rather than re-reading the config, so a caller-built loader can't
    # silently disagree with the flag it was built under.
    eval_sharded: bool = False
    # Validation split (reference main.py:421-423: the datasets submodule
    # exposed num_valid_samples next to train/test; sharded per host like
    # train).  Built when cfg.task.valid_fraction > 0 or, for image_folder,
    # when a valid/ root exists on disk.  Eval transform (resize-only).
    make_valid_iter: Optional[Callable[[int], Iterator[Batch]]] = None
    num_valid_samples: int = 0

    def set_all_epochs(self, epoch: int) -> None:
        self.epoch = epoch

    @property
    def train_loader(self) -> Iterator[Batch]:
        return self.make_train_iter(self.epoch)

    @property
    def test_loader(self) -> Iterator[Batch]:
        return self.make_test_iter(self.epoch)

    @property
    def train_eval_loader(self) -> Iterator[Batch]:
        if self.make_train_eval_iter is None:
            raise ValueError("this LoaderBundle provides no train-eval "
                             "(resize-only train split) iterator")
        return self.make_train_eval_iter(self.epoch)

    @property
    def valid_loader(self) -> Iterator[Batch]:
        if self.make_valid_iter is None:
            raise ValueError(
                "this LoaderBundle has no validation split: set "
                "--valid-fraction > 0 (or provide a valid/ root for "
                "image_folder)")
        return self.make_valid_iter(self.epoch)


def pad_batch(batch: Batch, target: int) -> Batch:
    """Pad a (possibly short) batch up to ``target`` rows and attach a
    validity ``mask`` (1.0 = real row).  Every eval batch then has ONE
    static shape — a single XLA compile — and a final batch that isn't
    divisible by the mesh's data axis still shards cleanly.  Consumers
    (trainer eval step, linear-eval extraction) mask pad rows out of every
    metric."""
    n = len(next(iter(batch.values())))
    if n > target:
        raise ValueError(
            f"pad_batch: batch has {n} rows > target {target}; the caller's "
            "host batch derivation disagrees with the loader's batch size")
    mask = np.zeros((target,), np.float32)
    mask[:n] = 1.0
    out = {}
    for k, v in batch.items():
        v = np.asarray(v)
        if n < target:
            pad = np.zeros((target - n,) + v.shape[1:], v.dtype)
            v = np.concatenate([v, pad], axis=0)
        out[k] = v
    out["mask"] = mask
    return out


def carve_valid_split(n: int, fraction: float, seed: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """-> (valid_indices, train_indices): the seeded permutation's head is
    held out (reference main.py:421-423 num_valid_samples contract).  ONE
    implementation shared by the array and image_folder paths so both tasks
    split identically and every host agrees."""
    if not 0.0 <= fraction < 1.0:
        raise ValueError(f"valid_fraction must be in [0, 1), got {fraction}")
    n_valid = int(n * fraction)
    perm = np.random.RandomState(seed ^ 0x5eed).permutation(n)
    return perm[:n_valid], perm[n_valid:]


def _process_info() -> Tuple[int, int]:
    import jax
    return jax.process_index(), jax.process_count()


def _shard_arrays(x: np.ndarray, y: np.ndarray, index: int, count: int):
    """Contiguous per-host shard (DistributedSampler analog)."""
    if count == 1:
        return x, y
    per = len(x) // count
    lo = index * per
    return x[lo:lo + per], y[lo:lo + per]


def _array_pipeline(images: np.ndarray, labels: np.ndarray, *,
                    batch_size: int, image_size: int, train: bool,
                    color_jitter_strength: float, seed: int,
                    shuffle: bool, aug_spec: str = "reference"
                    ) -> Callable[[int], Iterator[Batch]]:
    """tf.data pipeline over in-memory arrays -> numpy batch iterator.

    Train: two independently-augmented views; test: one resize applied to
    both view slots so eval code paths stay identical (the reference's eval
    also runs the full two-view forward, main.py:589-606)."""
    import tensorflow as tf

    from byol_tpu.data import augment

    def make(epoch: int) -> Iterator[Batch]:
        ds = tf.data.Dataset.from_tensor_slices(
            {"image": images, "label": labels.astype(np.int32),
             "index": np.arange(len(labels), dtype=np.int64)})
        if shuffle:
            ds = ds.shuffle(min(len(labels), 50_000), seed=seed + epoch,
                            reshuffle_each_iteration=False)

        def _map(ex):
            img = tf.image.convert_image_dtype(ex["image"], tf.float32)
            if train:
                s = tf.stack([tf.cast(ex["index"], tf.int32),
                              tf.constant(seed, tf.int32) + epoch])
                v1, v2 = augment.two_views(
                    img, image_size, s, color_jitter_strength,
                    spec=aug_spec)
            else:
                v1 = augment.test_resize(img, image_size)
                v2 = v1
            return {"view1": v1, "view2": v2, "label": ex["label"]}

        ds = ds.map(_map, num_parallel_calls=tf.data.AUTOTUNE)
        ds = ds.batch(batch_size, drop_remainder=train)
        ds = ds.prefetch(tf.data.AUTOTUNE)
        return ds.as_numpy_iterator()

    return make


def _native_pipeline(images: np.ndarray, labels: np.ndarray, *,
                     batch_size: int, image_size: int, train: bool,
                     color_jitter_strength: float, seed: int, shuffle: bool,
                     num_threads: int) -> Callable[[int], Iterator[Batch]]:
    """C++ host pipeline (data/native_aug.py) — the DALI-equivalent backend.

    Same iterator contract as the tf.data path: per-epoch reshuffle from
    (seed, epoch), two augmented views in train, resize-only eval,
    drop-remainder train batching."""
    from byol_tpu.data import native_aug

    labels = labels.astype(np.int32)

    def make(epoch: int) -> Iterator[Batch]:
        idx = np.arange(len(labels))
        if shuffle:
            np.random.RandomState(seed + epoch).shuffle(idx)
        n = len(idx)
        end = n - (n % batch_size) if train else n
        for lo in range(0, end, batch_size):
            take = idx[lo:lo + batch_size]
            imgs = images[take]
            if train:
                v1, v2 = native_aug.augment_two_views(
                    imgs, image_size,
                    color_jitter_strength=color_jitter_strength,
                    # epoch folded into the stream seed = set_all_epochs
                    seed=seed + 1_000_003 * epoch, index_base=int(lo),
                    num_threads=num_threads)
            else:
                v1 = native_aug.resize_batch(imgs, image_size,
                                             num_threads=num_threads)
                v2 = v1
            yield {"view1": v1, "view2": v2, "label": labels[take]}

    return make


def _device_pipeline(images: np.ndarray, labels: np.ndarray, *,
                     batch_size: int, image_size: int, train: bool,
                     color_jitter_strength: float, seed: int, shuffle: bool
                     ) -> Callable[[int], Iterator[Batch]]:
    """On-device (TPU) two-view augmentation backend — the DALI analog that
    actually uses the accelerator (data/device_augment.py).

    The host ships raw uint8 batches (4x less H2D bandwidth than float32
    views); crop/flip/jitter/grayscale/blur run on chip in one jitted vmapped
    program.  Train only — ``get_loader`` routes eval through the host
    resize path, where augmentation throughput is irrelevant."""
    from byol_tpu.core import rng as rng_lib
    from byol_tpu.data import device_augment

    labels = labels.astype(np.int32)

    def make(epoch: int) -> Iterator[Batch]:
        idx = np.arange(len(labels))
        if shuffle:
            np.random.RandomState(seed + epoch).shuffle(idx)
        n = len(idx)
        end = n - (n % batch_size) if train else n
        # per-epoch key stream: the set_all_epochs reseed (main.py:760)
        epoch_key = rng_lib.for_step(rng_lib.root_key(seed), epoch)
        for i, lo in enumerate(range(0, end, batch_size)):
            take = idx[lo:lo + batch_size]
            v1, v2 = device_augment.two_view_batch(
                rng_lib.for_step(epoch_key, i), images[take], image_size,
                strength=color_jitter_strength)
            yield {"view1": v1, "view2": v2, "label": labels[take]}

    return make


def _raw_pipeline(images: np.ndarray, labels: np.ndarray, *,
                  batch_size: int, seed: int, shuffle: bool
                  ) -> Callable[[int], Iterator[Batch]]:
    """Step-placement train pipeline: raw uint8 batches, no host-side
    augmentation at all (``augment_placement='step'``).

    Yields ``{'images': (B,H,W,C) uint8, 'label': (B,) int32}`` — the train
    step derives per-microbatch keys from its step counter and augments
    inside the accumulation scan (training/steps.py).  ~8x fewer H2D bytes
    than two float32 views, and the host's per-batch work collapses to an
    index gather."""
    labels = labels.astype(np.int32)
    if images.dtype != np.uint8:
        raise ValueError(
            f"augment_placement='step' ships raw uint8 pixels; this dataset "
            f"holds {images.dtype} arrays")

    def make(epoch: int) -> Iterator[Batch]:
        idx = np.arange(len(labels))
        if shuffle:
            np.random.RandomState(seed + epoch).shuffle(idx)
        n = len(idx)
        end = n - (n % batch_size)
        for lo in range(0, end, batch_size):
            take = idx[lo:lo + batch_size]
            yield {"images": images[take], "label": labels[take]}

    return make


def get_loader(cfg: Config, *, num_fake_samples: int = 512,
               num_synth_samples: Optional[int] = None,
               shard_eval: bool = False) -> LoaderBundle:
    """Dispatch on ``cfg.task.task``; see module docstring for the contract.

    Tasks: 'fake', 'synth', 'digits', 'cifar10', 'cifar100', 'mnist',
    'fashion_mnist', 'image_folder' (the reference's
    multi_augment_image_folder default, main.py:38-39).
    """
    task = cfg.task.task
    if num_synth_samples is None:   # explicit kwarg wins over the config
        num_synth_samples = cfg.task.num_synth_samples or 20_000
    # Reference task-name aliases (main.py:38-39; README.md:93): the DALI
    # variant maps to the native C++ backend for array tasks and to the
    # fused-decode tf.data path for image trees — ONE canonical augmentation
    # spec either way (Quirk Q4 deliberately not reproduced).
    if task == "multi_augment_image_folder":
        task = "image_folder"
    elif task == "dali_multi_augment_image_folder":
        task = "image_folder"
    index, count = _process_info()
    if cfg.task.batch_size % count != 0:
        raise ValueError(f"global batch {cfg.task.batch_size} not divisible "
                         f"by process count {count}")
    host_batch = cfg.task.batch_size // count

    # Resolve the effective backend and validate the aug spec BEFORE any
    # dataset download/load, so a bad combination fails fast.
    backend = cfg.task.data_backend
    if backend not in ("tf", "native", "device"):
        raise ValueError(f"unknown data_backend {backend!r} "
                         f"('tf'|'native'|'device')")
    if backend == "native":
        from byol_tpu.data import native_aug
        if not native_aug.available():
            # documented graceful degradation: no toolchain/binary -> tf.data
            print("byol_tpu: native data backend unavailable "
                  "(no g++/.so); falling back to tf.data")
            backend = "tf"
        elif task == "image_folder" and not native_aug.has_jpeg():
            print("byol_tpu: native backend built without libjpeg; "
                  "image_folder falls back to tf.data fused decode")
            backend = "tf"
    if cfg.regularizer.aug_spec != "reference" and backend != "tf":
        raise ValueError(
            f"aug_spec={cfg.regularizer.aug_spec!r} is implemented on the "
            f"tf data backend only (got data_backend={backend!r})")
    placement = cfg.task.augment_placement
    if placement not in ("loader", "step"):
        raise ValueError(f"unknown augment_placement {placement!r} "
                         f"('loader'|'step')")
    if placement == "step":
        if task == "image_folder":
            raise ValueError(
                "augment_placement='step' does not serve image_folder: "
                "decode is host-side and yields variable-size images; use "
                "the loader placement")
        if cfg.regularizer.aug_spec != "reference":
            raise ValueError(
                f"augment_placement='step' runs the canonical 'reference' "
                f"augmentation spec on device (got "
                f"aug_spec={cfg.regularizer.aug_spec!r})")
        if backend == "device":
            raise ValueError(
                "data_backend='device' (loader-dispatched on-chip augment) "
                "and augment_placement='step' (step-fused augment) are "
                "mutually exclusive; pick one")

    if task == "image_folder":
        if backend == "device":
            raise ValueError(
                "data_backend='device' does not serve image_folder (decode "
                "is inherently host-side); use 'tf' or 'native'")
        from byol_tpu.data.imagefolder import image_folder_loader
        return image_folder_loader(cfg, host_batch=host_batch,
                                   shard_eval=shard_eval, backend=backend)

    if task == "fake":
        size = cfg.task.image_size_override or 32
        x_tr, y_tr = readers.load_fake(num_fake_samples, size,
                                       seed=cfg.device.seed)
        x_te, y_te = readers.load_fake(max(num_fake_samples // 4, host_batch),
                                       size, seed=cfg.device.seed + 1)
        n_classes = 10
    elif task == "synth":
        # learnable procedural dataset (readers.load_synth) — the offline
        # stand-in for CIFAR-scale learning-dynamics evidence
        size = cfg.task.image_size_override or 32
        x_tr, y_tr = readers.load_synth(num_synth_samples, size,
                                        seed=cfg.device.seed, train=True)
        x_te, y_te = readers.load_synth(
            max(num_synth_samples // 10, host_batch), size,
            seed=cfg.device.seed, train=False)
        n_classes = 10
    elif task in readers.ARRAY_LOADERS:
        fn, n_classes = readers.ARRAY_LOADERS[task]
        x_tr, y_tr = fn(cfg.task.data_dir, train=True,
                        download=cfg.task.download)
        x_te, y_te = fn(cfg.task.data_dir, train=False,
                        download=cfg.task.download)
        size = cfg.task.image_size_override or x_tr.shape[1]
    else:
        raise ValueError(f"unknown task {task!r}")

    # Validation carve-out (reference main.py:421-423 contract): held out
    # BEFORE host sharding so every host agrees on the split; valid is then
    # sharded per host like train.
    x_va = y_va = None
    n_valid = 0
    if cfg.task.valid_fraction > 0:
        va_idx, tr_idx = carve_valid_split(
            len(x_tr), cfg.task.valid_fraction, cfg.device.seed)
        n_valid = len(va_idx)
        x_va, y_va = x_tr[va_idx], y_tr[va_idx]
        x_tr, y_tr = x_tr[tr_idx], y_tr[tr_idx]

    n_train, n_test = len(x_tr), len(x_te)
    x_trs, y_trs = _shard_arrays(x_tr, y_tr, index, count)
    if n_valid:
        x_va, y_va = _shard_arrays(x_va, y_va, index, count)
    if shard_eval:
        x_te, y_te = _shard_arrays(x_te, y_te, index, count)

    cj = cfg.regularizer.color_jitter_strength
    import functools
    if backend == "native":
        pipeline = functools.partial(
            _native_pipeline,
            num_threads=max(cfg.device.workers_per_replica, 1))
        test_pipeline = pipeline
    elif backend == "tf":
        pipeline = test_pipeline = functools.partial(
            _array_pipeline, aug_spec=cfg.regularizer.aug_spec)
    else:  # device
        # on-chip train augmentation; eval resize stays on host (its
        # throughput never gates the MXU)
        pipeline, test_pipeline = _device_pipeline, _array_pipeline
    if placement == "step":
        # raw uint8 train stream (the step augments); eval keeps the host
        # resize path of whatever backend resolved above
        make_train = _raw_pipeline(x_trs, y_trs, batch_size=host_batch,
                                   seed=cfg.device.seed, shuffle=True)
    else:
        make_train = pipeline(
            x_trs, y_trs, batch_size=host_batch, image_size=size, train=True,
            color_jitter_strength=cj, seed=cfg.device.seed, shuffle=True)
    return LoaderBundle(
        make_train_iter=make_train,
        make_test_iter=test_pipeline(
            x_te, y_te, batch_size=host_batch, image_size=size, train=False,
            color_jitter_strength=cj, seed=cfg.device.seed, shuffle=False),
        make_train_eval_iter=test_pipeline(
            x_trs, y_trs, batch_size=host_batch, image_size=size,
            train=False, color_jitter_strength=cj, seed=cfg.device.seed,
            shuffle=False),
        input_shape=(size, size, 3),
        num_train_samples=n_train,
        num_test_samples=n_test,
        output_size=n_classes,
        eval_sharded=shard_eval and count > 1,
        make_valid_iter=(test_pipeline(
            x_va, y_va, batch_size=host_batch, image_size=size, train=False,
            color_jitter_strength=cj, seed=cfg.device.seed, shuffle=False)
            if n_valid else None),
        num_valid_samples=n_valid,
    )
