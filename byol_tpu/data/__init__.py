from byol_tpu.data.loader import LoaderBundle, get_loader  # noqa: F401
from byol_tpu.data.prefetch import prefetch_to_mesh  # noqa: F401
