"""On-device (TPU) batched two-view augmentation — the DALI equivalent.

The reference offloads decode+augment to GPUs via NVIDIA DALI when host CPU
can't keep up (``dali_multi_augment_image_folder``,
/root/reference/main.py:356-382; README.md:90-93).  The TPU-native analog:
the host ships raw resized uint8 batches; crop/flip/jitter/grayscale/blur all
run ON CHIP inside one jitted, vmapped program — elementwise work fuses into
the surrounding step, the blur is a depthwise conv on the MXU, and every op
has static shapes (crop windows are realized with
``jax.image.scale_and_translate`` instead of dynamic slicing).

Unlike the reference's DALI path, which silently changes augmentation
hyperparameters (HFlip .2 vs .5, saturation .2s vs .8s, no blur — Quirk Q4,
accuracy caveat README.md:93), this path uses the SAME canonical parameters
as the host pipeline (data/augment.py).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def _uniform(key, lo=0.0, hi=1.0, shape=()):
    return jax.random.uniform(key, shape, minval=lo, maxval=hi)


def random_resized_crop(key, image: jnp.ndarray, size: int,
                        scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)
                        ) -> jnp.ndarray:
    """torchvision RandomResizedCrop with static output shape.

    Samples area in ``scale``·A and log-uniform aspect in ``ratio``; the
    (fractional) window is mapped to (size, size) by scale_and_translate —
    no dynamic shapes, so XLA tiles it cleanly."""
    h, w = image.shape[0], image.shape[1]
    k_area, k_ratio, k_y, k_x = jax.random.split(key, 4)
    area = _uniform(k_area, scale[0], scale[1]) * (h * w)
    log_r = _uniform(k_ratio, jnp.log(ratio[0]), jnp.log(ratio[1]))
    r = jnp.exp(log_r)
    cw = jnp.sqrt(area * r)
    ch = jnp.sqrt(area / r)
    # clamp to the image (the torchvision fallback-to-whole-image analog)
    cw = jnp.minimum(cw, w * 1.0)
    ch = jnp.minimum(ch, h * 1.0)
    y0 = _uniform(k_y, 0.0, h - ch)
    x0 = _uniform(k_x, 0.0, w - cw)
    sy, sx = size / ch, size / cw
    out = jax.image.scale_and_translate(
        image, (size, size, image.shape[2]), (0, 1),
        scale=jnp.stack([sy, sx]),
        translation=jnp.stack([-y0 * sy, -x0 * sx]),
        method="bilinear")
    return jnp.clip(out, 0.0, 1.0)


def _gray(image):
    lum = (0.2989 * image[..., 0] + 0.587 * image[..., 1]
           + 0.114 * image[..., 2])
    return lum[..., None]


def color_jitter(key, image: jnp.ndarray, strength: float) -> jnp.ndarray:
    """brightness/contrast/saturation (.8s) + hue (.2s), torch semantics
    (multiplicative brightness; blend-based contrast/saturation)."""
    b = c = s = 0.8 * strength
    hs = 0.2 * strength
    kb, kc, ks, kh = jax.random.split(key, 4)
    image = jnp.clip(image * _uniform(kb, max(0., 1 - b), 1 + b), 0., 1.)
    f = _uniform(kc, max(0., 1 - c), 1 + c)
    image = jnp.clip(f * image + (1 - f) * jnp.mean(_gray(image)), 0., 1.)
    f = _uniform(ks, max(0., 1 - s), 1 + s)
    image = jnp.clip(f * image + (1 - f) * _gray(image), 0., 1.)
    if hs > 0:
        # hue rotation in YIQ space (equivalent to HSV hue shift, cheaper
        # and branch-free on TPU)
        theta = _uniform(kh, -hs, hs) * 2.0 * jnp.pi
        yiq = jnp.einsum("hwc,cd->hwd", image,
                         jnp.array([[0.299, 0.596, 0.211],
                                    [0.587, -0.274, -0.523],
                                    [0.114, -0.322, 0.312]]))
        cos, sin = jnp.cos(theta), jnp.sin(theta)
        rot = jnp.array([[1, 0, 0], [0, cos, -sin], [0, sin, cos]],
                        dtype=image.dtype)
        yiq = jnp.einsum("hwd,de->hwe", yiq, rot)
        image = jnp.einsum("hwd,dc->hwc", yiq,
                           jnp.array([[1.0, 1.0, 1.0],
                                      [0.956, -0.272, -1.106],
                                      [0.621, -0.647, 1.703]]))
        image = jnp.clip(image, 0.0, 1.0)
    return image


def gaussian_blur(key, image: jnp.ndarray, kernel_size: int,
                  sigma_range=(0.1, 2.0)) -> jnp.ndarray:
    """Separable depthwise gaussian blur; per-image sigma."""
    k = max(int(kernel_size) | 1, 3)
    sigma = _uniform(key, *sigma_range)
    x = jnp.arange(-(k // 2), k // 2 + 1, dtype=image.dtype)
    g = jnp.exp(-(x ** 2) / (2.0 * sigma ** 2))
    g = g / jnp.sum(g)
    ch = image.shape[-1]
    r = k // 2
    # reflect-101 borders — keeps all three blur backends (tf host, C++
    # host, on-device) border-consistent; zero padding would dim border
    # pixels (see data/augment.py:gaussian_blur).
    img = jnp.pad(image, ((r, r), (r, r), (0, 0)), mode="reflect")[None]
    kx = jnp.tile(g.reshape(1, k, 1, 1), (1, 1, 1, ch))  # HWIO, grouped
    ky = jnp.tile(g.reshape(k, 1, 1, 1), (1, 1, 1, ch))
    dn = jax.lax.conv_dimension_numbers(img.shape, kx.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    img = jax.lax.conv_general_dilated(img, kx, (1, 1), "VALID",
                                       dimension_numbers=dn,
                                       feature_group_count=ch)
    img = jax.lax.conv_general_dilated(img, ky, (1, 1), "VALID",
                                       dimension_numbers=dn,
                                       feature_group_count=ch)
    return img[0]


def augment_one(key, image: jnp.ndarray, size: int,
                color_jitter_strength: float = 1.0) -> jnp.ndarray:
    """One view for one image (HWC float32 [0,1]); vmap over the batch."""
    ks = jax.random.split(key, 7)
    v = random_resized_crop(ks[0], image, size)
    v = jnp.where(_uniform(ks[1]) < 0.5, v[:, ::-1, :], v)
    v = jnp.where(_uniform(ks[2]) < 0.8,
                  color_jitter(ks[3], v, color_jitter_strength), v)
    v = jnp.where(_uniform(ks[4]) < 0.2, jnp.tile(_gray(v), (1, 1, 3)), v)
    # gate and sigma draw from independent keys (seed reuse would pin sigma
    # to a deterministic function of the gate draw)
    v = jnp.where(_uniform(ks[5]) < 0.5,
                  gaussian_blur(ks[6], v, int(0.1 * size)), v)
    return jnp.clip(v, 0.0, 1.0)


def two_view(key, images: jnp.ndarray, size: int, *,
             strength: float = 1.0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Traceable batched two-view program — the ONE augmentation function
    behind both placements (core/config.py ``augment_placement``): the
    loader path jit-dispatches it standalone (:func:`two_view_batch`) and
    the step-fused path traces it per microbatch inside the train step
    (training/steps.py), so identical keys provably yield identical views.

    images: (B, H, W, C) uint8 or float32 [0,1] -> two (B, size, size, C)
    float32 views.
    """
    if images.dtype == jnp.uint8:
        images = images.astype(jnp.float32) / 255.0
    b = images.shape[0]
    k1, k2 = jax.random.split(key)
    aug = jax.vmap(lambda k, im: augment_one(k, im, size, strength))
    v1 = aug(jax.random.split(k1, b), images)
    v2 = aug(jax.random.split(k2, b), images)
    return v1, v2


@functools.partial(jax.jit, static_argnums=(2,), static_argnames=("strength",))
def two_view_batch(key, images: jnp.ndarray, size: int, *,
                   strength: float = 1.0
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Standalone jitted dispatch of :func:`two_view` — the loader-placement
    backend (``--data-backend device``).  uint8 in, so the host→HBM transfer
    is 4x smaller than shipping floats (the DALI-style bandwidth win)."""
    return two_view(key, images, size, strength=strength)
