"""On-device (TPU) batched two-view augmentation — the DALI equivalent.

The reference offloads decode+augment to GPUs via NVIDIA DALI when host CPU
can't keep up (``dali_multi_augment_image_folder``,
/root/reference/main.py:356-382; README.md:90-93).  The TPU-native analog:
the host ships raw resized uint8 batches; crop/flip/jitter/grayscale/blur all
run ON CHIP inside one jitted, vmapped program — elementwise work fuses into
the surrounding step, the blur is a depthwise conv on the MXU, and every op
has static shapes (crop windows are realized with
``jax.image.scale_and_translate`` instead of dynamic slicing).

Unlike the reference's DALI path, which silently changes augmentation
hyperparameters (HFlip .2 vs .5, saturation .2s vs .8s, no blur — Quirk Q4,
accuracy caveat README.md:93), this path uses the SAME canonical parameters
as the host pipeline (data/augment.py).

Since ISSUE 14 every stochastic DRAW is factored away from its APPLY
(:func:`crop_window` / :func:`jitter_params` / :func:`blur_sigma` /
:func:`view_params` vs :func:`apply_crop` / :func:`apply_color_jitter` /
:func:`apply_gaussian_blur` / :func:`apply_view`): the fused Pallas
augmentation kernel (ops/fused_augment.py) draws its per-image parameters
from the SAME functions outside the ``pallas_call`` (host-RNG primitives do
not exist in-kernel — graphlint GL111) and applies the same arithmetic
in-kernel, so the two paths share every line that could drift.  The
factoring preserves the key streams and op order exactly (``augment_one``
splits the same key the same way as before), with ONE deliberate
numerical exception: the hue rotation is rewritten scalar-unrolled (a
kernel body cannot capture the constant YIQ matrices), replacing three
einsums with the equivalent per-channel arithmetic — identical math,
fp-rounding-level differences only (and on TPU the old einsums were
MXU-eligible, so pre-refactor seed-for-seed trajectories there are
reproduced to tolerance, not bit-for-bit).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


def _uniform(key, lo=0.0, hi=1.0, shape=()):
    return jax.random.uniform(key, shape, minval=lo, maxval=hi)


def crop_window(key, h: int, w: int, scale=(0.08, 1.0),
                ratio=(3 / 4, 4 / 3)):
    """Draw one RandomResizedCrop window: ``(y0, x0, ch, cw)`` fractional
    offsets/extents in source pixels (area in ``scale``·A, log-uniform
    aspect in ``ratio``, clamped to the image — the torchvision
    fallback-to-whole-image analog)."""
    k_area, k_ratio, k_y, k_x = jax.random.split(key, 4)
    area = _uniform(k_area, scale[0], scale[1]) * (h * w)
    log_r = _uniform(k_ratio, jnp.log(ratio[0]), jnp.log(ratio[1]))
    r = jnp.exp(log_r)
    cw = jnp.sqrt(area * r)
    ch = jnp.sqrt(area / r)
    cw = jnp.minimum(cw, w * 1.0)
    ch = jnp.minimum(ch, h * 1.0)
    y0 = _uniform(k_y, 0.0, h - ch)
    x0 = _uniform(k_x, 0.0, w - cw)
    return y0, x0, ch, cw


def apply_crop(image: jnp.ndarray, y0, x0, ch, cw, size: int) -> jnp.ndarray:
    """Map the (fractional) crop window to (size, size) by
    scale_and_translate — no dynamic shapes, so XLA tiles it cleanly."""
    sy, sx = size / ch, size / cw
    out = jax.image.scale_and_translate(
        image, (size, size, image.shape[2]), (0, 1),
        scale=jnp.stack([sy, sx]),
        translation=jnp.stack([-y0 * sy, -x0 * sx]),
        method="bilinear")
    return jnp.clip(out, 0.0, 1.0)


def random_resized_crop(key, image: jnp.ndarray, size: int,
                        scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)
                        ) -> jnp.ndarray:
    """torchvision RandomResizedCrop with static output shape."""
    y0, x0, ch, cw = crop_window(key, image.shape[0], image.shape[1],
                                 scale, ratio)
    return apply_crop(image, y0, x0, ch, cw, size)


def _gray(image):
    lum = (0.2989 * image[..., 0] + 0.587 * image[..., 1]
           + 0.114 * image[..., 2])
    return lum[..., None]


def apply_grayscale(image: jnp.ndarray) -> jnp.ndarray:
    """Three-channel grayscale (the torchvision RandomGrayscale branch)."""
    return jnp.tile(_gray(image), (1, 1, 3))


def jitter_params(key, strength: float):
    """Draw the color-jitter factors: multiplicative brightness, blend
    contrast/saturation factors, and the hue angle (inert when
    ``0.2 * strength == 0``)."""
    b = c = s = 0.8 * strength
    hs = 0.2 * strength
    kb, kc, ks, kh = jax.random.split(key, 4)
    fb = _uniform(kb, max(0., 1 - b), 1 + b)
    fc = _uniform(kc, max(0., 1 - c), 1 + c)
    fs = _uniform(ks, max(0., 1 - s), 1 + s)
    theta = _uniform(kh, -hs, hs) * 2.0 * jnp.pi
    return fb, fc, fs, theta


def apply_color_jitter(image: jnp.ndarray, fb, fc, fs, theta, *,
                       hue: bool) -> jnp.ndarray:
    """brightness/contrast/saturation (.8s) + hue (.2s), torch semantics
    (multiplicative brightness; blend-based contrast/saturation); pure
    arithmetic on pre-drawn factors, shared verbatim by the fused
    augmentation kernel body."""
    image = jnp.clip(image * fb, 0., 1.)
    image = jnp.clip(fc * image + (1 - fc) * jnp.mean(_gray(image)), 0., 1.)
    image = jnp.clip(fs * image + (1 - fs) * _gray(image), 0., 1.)
    if hue:
        # hue rotation in YIQ space (equivalent to HSV hue shift, cheaper
        # and branch-free on TPU), written in scalar-unrolled form: a
        # Pallas kernel body cannot capture array constants, and this
        # function IS the fused augmentation kernel's jitter stage
        # (ops/fused_augment.py) — scalar coefficients inline fine and the
        # channel mixes stay pure VPU arithmetic either way
        r, g, b_ = image[..., 0], image[..., 1], image[..., 2]
        y = 0.299 * r + 0.587 * g + 0.114 * b_
        i = 0.596 * r - 0.274 * g - 0.322 * b_
        q = 0.211 * r - 0.523 * g + 0.312 * b_
        cos, sin = jnp.cos(theta), jnp.sin(theta)
        i, q = cos * i + sin * q, -sin * i + cos * q
        image = jnp.stack([y + 0.956 * i + 0.621 * q,
                           y - 0.272 * i - 0.647 * q,
                           y - 1.106 * i + 1.703 * q], axis=-1)
        image = jnp.clip(image, 0.0, 1.0)
    return image


def color_jitter(key, image: jnp.ndarray, strength: float) -> jnp.ndarray:
    fb, fc, fs, theta = jitter_params(key, strength)
    return apply_color_jitter(image, fb, fc, fs, theta,
                              hue=0.2 * strength > 0)


def blur_sigma(key, sigma_range=(0.1, 2.0)):
    return _uniform(key, *sigma_range)


def apply_gaussian_blur(sigma, image: jnp.ndarray,
                        kernel_size: int) -> jnp.ndarray:
    """Separable depthwise gaussian blur with a pre-drawn sigma."""
    k = max(int(kernel_size) | 1, 3)
    x = jnp.arange(-(k // 2), k // 2 + 1, dtype=image.dtype)
    g = jnp.exp(-(x ** 2) / (2.0 * sigma ** 2))
    g = g / jnp.sum(g)
    ch = image.shape[-1]
    r = k // 2
    # reflect-101 borders — keeps all three blur backends (tf host, C++
    # host, on-device) border-consistent; zero padding would dim border
    # pixels (see data/augment.py:gaussian_blur).
    img = jnp.pad(image, ((r, r), (r, r), (0, 0)), mode="reflect")[None]
    kx = jnp.tile(g.reshape(1, k, 1, 1), (1, 1, 1, ch))  # HWIO, grouped
    ky = jnp.tile(g.reshape(k, 1, 1, 1), (1, 1, 1, ch))
    dn = jax.lax.conv_dimension_numbers(img.shape, kx.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    img = jax.lax.conv_general_dilated(img, kx, (1, 1), "VALID",
                                       dimension_numbers=dn,
                                       feature_group_count=ch)
    img = jax.lax.conv_general_dilated(img, ky, (1, 1), "VALID",
                                       dimension_numbers=dn,
                                       feature_group_count=ch)
    return img[0]


def gaussian_blur(key, image: jnp.ndarray, kernel_size: int,
                  sigma_range=(0.1, 2.0)) -> jnp.ndarray:
    """Separable depthwise gaussian blur; per-image sigma."""
    return apply_gaussian_blur(blur_sigma(key, sigma_range), image,
                               kernel_size)


class ViewParams(NamedTuple):
    """Every stochastic parameter one view draws, in augment_one's key
    order — the contract the fused kernel path (ops/fused_augment.py)
    consumes OUTSIDE its ``pallas_call``.  A pytree of scalars: vmap over
    a key batch for per-image parameter arrays."""

    y0: jnp.ndarray          # crop window (crop_window)
    x0: jnp.ndarray
    ch: jnp.ndarray
    cw: jnp.ndarray
    flip: jnp.ndarray        # bool gates
    jitter: jnp.ndarray
    fb: jnp.ndarray          # jitter factors (jitter_params)
    fc: jnp.ndarray
    fs: jnp.ndarray
    theta: jnp.ndarray
    gray: jnp.ndarray
    blur: jnp.ndarray
    sigma: jnp.ndarray       # blur sigma (blur_sigma)


def view_params(key, h: int, w: int,
                strength: float = 1.0) -> ViewParams:
    """Draw every parameter of one view from ``key`` — the exact split
    structure augment_one has always used (7-way split; crop/jitter
    subkeys split further inside their draw functions).  Gate and sigma
    draw from independent keys (seed reuse would pin sigma to a
    deterministic function of the gate draw)."""
    ks = jax.random.split(key, 7)
    y0, x0, ch, cw = crop_window(ks[0], h, w)
    fb, fc, fs, theta = jitter_params(ks[3], strength)
    return ViewParams(
        y0=y0, x0=x0, ch=ch, cw=cw,
        flip=_uniform(ks[1]) < 0.5,
        jitter=_uniform(ks[2]) < 0.8,
        fb=fb, fc=fc, fs=fs, theta=theta,
        gray=_uniform(ks[4]) < 0.2,
        blur=_uniform(ks[5]) < 0.5,
        sigma=blur_sigma(ks[6]))


def apply_view(p: ViewParams, image: jnp.ndarray, size: int, *,
               strength: float = 1.0) -> jnp.ndarray:
    """Apply one view's pre-drawn parameters (HWC float32 [0,1] in,
    (size, size, C) float32 out); pure arithmetic — no RNG."""
    v = apply_crop(image, p.y0, p.x0, p.ch, p.cw, size)
    v = jnp.where(p.flip, v[:, ::-1, :], v)
    v = jnp.where(p.jitter,
                  apply_color_jitter(v, p.fb, p.fc, p.fs, p.theta,
                                     hue=0.2 * strength > 0), v)
    v = jnp.where(p.gray, apply_grayscale(v), v)
    v = jnp.where(p.blur, apply_gaussian_blur(p.sigma, v, int(0.1 * size)),
                  v)
    return jnp.clip(v, 0.0, 1.0)


def augment_one(key, image: jnp.ndarray, size: int,
                color_jitter_strength: float = 1.0) -> jnp.ndarray:
    """One view for one image (HWC float32 [0,1]); vmap over the batch."""
    p = view_params(key, image.shape[0], image.shape[1],
                    color_jitter_strength)
    return apply_view(p, image, size, strength=color_jitter_strength)


def two_view(key, images: jnp.ndarray, size: int, *,
             strength: float = 1.0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Traceable batched two-view program — the ONE augmentation function
    behind both placements (core/config.py ``augment_placement``): the
    loader path jit-dispatches it standalone (:func:`two_view_batch`) and
    the step-fused path traces it per microbatch inside the train step
    (training/steps.py), so identical keys provably yield identical views.

    images: (B, H, W, C) uint8 or float32 [0,1] -> two (B, size, size, C)
    float32 views.
    """
    if images.dtype == jnp.uint8:
        images = images.astype(jnp.float32) / 255.0
    b = images.shape[0]
    k1, k2 = jax.random.split(key)
    aug = jax.vmap(lambda k, im: augment_one(k, im, size, strength))
    v1 = aug(jax.random.split(k1, b), images)
    v2 = aug(jax.random.split(k2, b), images)
    return v1, v2


@functools.partial(jax.jit, static_argnums=(2,), static_argnames=("strength",))
def two_view_batch(key, images: jnp.ndarray, size: int, *,
                   strength: float = 1.0
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Standalone jitted dispatch of :func:`two_view` — the loader-placement
    backend (``--data-backend device``).  uint8 in, so the host→HBM transfer
    is 4x smaller than shipping floats (the DALI-style bandwidth win)."""
    return two_view(key, images, size, strength=strength)
