"""ImageFolder pipeline: class-per-subdirectory image trees (ImageNet layout).

The reference's default task ``multi_augment_image_folder`` expects ``train/``
and ``test/`` ImageFolder roots (/root/reference/README.md:82) and leans on
NVIDIA DALI when host CPU decode becomes the bottleneck (main.py:356-382).
TPU-native replacements here (SURVEY.md §2.4 DALI row):

- fused ``decode_and_crop_jpeg``: the RandomResizedCrop window is sampled
  FIRST and only that window is decoded — the single biggest host-CPU win
  for JPEG trees;
- per-host file sharding by ``jax.process_index()`` (DistributedSampler
  analog);
- parallel interleaved reads + AUTOTUNE-parallel augmentation + prefetch;
  device transfer/double-buffering happens in the trainer
  (data/prefetch.py).
"""
from __future__ import annotations

import os
from typing import Callable, Iterator, List, Tuple

import numpy as np

from byol_tpu.core.config import Config

IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def scan_image_folder(root: str) -> Tuple[List[str], List[int], List[str]]:
    """-> (paths, labels, class_names); classes sorted for determinism."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {root}")
    paths, labels = [], []
    for li, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(IMG_EXTS):
                paths.append(os.path.join(cdir, fname))
                labels.append(li)
    return paths, labels, classes


def _decode_full(data, channels=3):
    import tensorflow as tf
    img = tf.io.decode_image(data, channels=channels, expand_animations=False)
    img.set_shape([None, None, channels])
    return tf.image.convert_image_dtype(img, tf.float32)


def _fused_decode_random_crop(data, seed, size: int,
                              scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    """Sample the crop window from the JPEG header, decode ONLY the window
    (tf.image.decode_and_crop_jpeg), then resize — DALI's fused
    decode+crop equivalent on the host."""
    import tensorflow as tf
    shape = tf.image.extract_jpeg_shape(data)
    bbox = tf.zeros((1, 1, 4), tf.float32)
    begin, sz, _ = tf.image.stateless_sample_distorted_bounding_box(
        shape, bounding_boxes=bbox, seed=seed, min_object_covered=0.0,
        aspect_ratio_range=ratio, area_range=scale, max_attempts=10,
        use_image_if_no_bounding_boxes=True)
    oy, ox, _ = tf.unstack(begin)
    th, tw, _ = tf.unstack(sz)
    img = tf.image.decode_and_crop_jpeg(data, [oy, ox, th, tw], channels=3)
    img = tf.image.convert_image_dtype(img, tf.float32)
    return tf.image.resize(img, (size, size), method="bilinear")


def _is_jpeg(path):
    import tensorflow as tf
    lower = tf.strings.lower(path)
    return tf.strings.regex_full_match(lower, r".*\.(jpg|jpeg)")


def image_folder_loader(cfg: Config, *, host_batch: int,
                        shard_eval: bool = False, backend: str = "tf"):
    """Build a LoaderBundle over train/ and test/ ImageFolder roots.

    ``backend='tf'``: tf.data with fused ``decode_and_crop_jpeg``.
    ``backend='native'``: the first-party C++ pipeline (data/native/) with
    libjpeg fused decode+crop — the DALI-equivalent that owns the whole
    decode→augment hot path without TF dispatch (reference main.py:356-382).
    """
    import jax
    import tensorflow as tf

    from byol_tpu.data import augment
    from byol_tpu.data.loader import LoaderBundle

    size = cfg.task.image_size_override or 224
    cj = cfg.regularizer.color_jitter_strength
    seed = cfg.device.seed
    index, count = jax.process_index(), jax.process_count()

    roots = {}
    for split in ("train", "test"):
        root = os.path.join(cfg.task.data_dir, split)
        if not os.path.isdir(root):
            raise FileNotFoundError(
                f"image_folder task expects {root}/<class>/<img> "
                f"(reference README.md:82)")
        roots[split] = scan_image_folder(root)
    tr_paths, tr_labels, classes = roots["train"]
    te_paths, te_labels, te_classes = roots["test"]
    if te_classes != classes:
        raise ValueError("train/ and test/ class sets differ")

    # Validation split (reference main.py:421-423): an on-disk valid/ root
    # wins; otherwise valid_fraction carves a seeded held-out head from the
    # train list BEFORE host sharding, so every host agrees on the split.
    va_paths, va_labels = [], []
    valid_root = os.path.join(cfg.task.data_dir, "valid")
    if os.path.isdir(valid_root):
        va_paths, va_labels, va_classes = scan_image_folder(valid_root)
        if va_classes != classes:
            raise ValueError("train/ and valid/ class sets differ")
    elif cfg.task.valid_fraction > 0:
        from byol_tpu.data.loader import carve_valid_split
        va_idx, tr_idx = carve_valid_split(
            len(tr_paths), cfg.task.valid_fraction, seed)
        va_paths = [tr_paths[i] for i in va_idx]
        va_labels = [tr_labels[i] for i in va_idx]
        tr_paths = [tr_paths[i] for i in tr_idx]
        tr_labels = [tr_labels[i] for i in tr_idx]
    n_train, n_test, n_valid = len(tr_paths), len(te_paths), len(va_paths)

    def shard(paths, labels):
        return paths[index::count], labels[index::count]

    tr_sh = shard(tr_paths, tr_labels)
    va_sh = shard(va_paths, va_labels)
    te_sh = shard(te_paths, te_labels) if shard_eval else (te_paths, te_labels)

    def make_native_iter(paths, labels, train: bool
                         ) -> Callable[[int], Iterator[dict]]:
        """C++ fused-JPEG pipeline iterator: threaded file reads, one
        native call per batch (decode window + augment in C++ threads), a
        depth-2 background prefetcher so host augment overlaps the train
        step.  Same contract as the tf.data path: per-epoch reshuffle from
        (seed, epoch), drop-remainder train batching, resize-only eval."""
        import concurrent.futures
        import queue as queue_lib
        import threading

        from byol_tpu.data import native_aug

        paths_t = np.asarray(paths)
        labels_t = np.asarray(labels, np.int32)
        workers = max(cfg.device.workers_per_replica, 1)

        def produce(epoch: int):
            idx = np.arange(len(labels_t))
            if train:
                np.random.RandomState(seed + epoch).shuffle(idx)
            n = len(idx)
            end = n - (n % host_batch) if train else n
            with concurrent.futures.ThreadPoolExecutor(workers) as pool:
                for lo in range(0, end, host_batch):
                    take = idx[lo:lo + host_batch]
                    blobs = list(pool.map(
                        lambda p: open(p, "rb").read(), paths_t[take]))
                    if train:
                        # process_index mixed into the seed: index_base is
                        # shard-LOCAL, so without it every host at the same
                        # epoch position would draw identical crop/jitter
                        # parameters for different images (ADVICE r4).  The
                        # C++ side multiplies seed by the splitmix64
                        # constant, so distinct seeds are disjoint stream
                        # families; single-host runs (index 0) keep the
                        # committed evidence streams unchanged.
                        v1, v2 = native_aug.jpeg_augment_two_views(
                            blobs, size, color_jitter_strength=cj,
                            seed=(seed + 1_000_003 * epoch
                                  + 7_919 * index),
                            index_base=int(lo), num_threads=workers)
                    else:
                        v1 = native_aug.jpeg_resize_batch(
                            blobs, size, num_threads=workers)
                        v2 = v1
                    yield {"view1": v1, "view2": v2,
                           "label": labels_t[take]}

        def make(epoch: int) -> Iterator[dict]:
            q: queue_lib.Queue = queue_lib.Queue(maxsize=2)
            DONE = object()
            stop = threading.Event()   # consumer abandoned the iterator

            def _put(item) -> bool:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        return True
                    except queue_lib.Full:
                        continue
                return False

            def worker():
                gen = produce(epoch)
                try:
                    for item in gen:
                        if not _put(item):
                            return       # abandoned: stop producing
                    _put(DONE)
                except BaseException as e:   # surface errors, don't hang
                    _put(e)
                finally:
                    gen.close()          # closes the read thread pool

            threading.Thread(target=worker, daemon=True).start()
            try:
                while True:
                    item = q.get()
                    if item is DONE:
                        return
                    if isinstance(item, BaseException):
                        raise item
                    yield item
            finally:
                # break early / GeneratorExit: release the producer thread
                # and its thread pool instead of leaking them blocked on a
                # full queue (each leak pins workers + two buffered batches)
                stop.set()

        return make

    def make_iter(paths, labels, train: bool
                  ) -> Callable[[int], Iterator[dict]]:
        if backend == "native":
            return make_native_iter(paths, labels, train)
        paths_t = np.asarray(paths)
        labels_t = np.asarray(labels, np.int32)

        def make(epoch: int):
            ds = tf.data.Dataset.from_tensor_slices(
                {"path": paths_t, "label": labels_t,
                 "index": np.arange(len(labels_t), dtype=np.int64)})
            if train:
                ds = ds.shuffle(min(len(labels_t), 100_000),
                                seed=seed + epoch,
                                reshuffle_each_iteration=False)

            def _load(ex):
                data = tf.io.read_file(ex["path"])
                if train:
                    # 100_003 * process_index: same cross-host
                    # decorrelation as the native path (ex["index"] is
                    # shard-local); epochs stay well below 100_003, so
                    # (epoch, host) seed pairs never collide
                    s0 = tf.stack([tf.cast(ex["index"], tf.int32),
                                   tf.constant(seed, tf.int32) + epoch
                                   + 100_003 * index])
                    # Proper seed splitting (not additive offsets, which
                    # collide across samples: i's view2 == (i+k)'s view1).
                    view_seeds = augment._split(s0, 2)
                    views = []
                    for vi, sv in enumerate(view_seeds):
                        s_crop, s_rest = augment._split(sv, 2)
                        crop = tf.cond(
                            _is_jpeg(ex["path"]),
                            lambda s=s_crop: _fused_decode_random_crop(
                                data, s, size),
                            lambda s=s_crop: augment.random_resized_crop(
                                _decode_full(data), size, s))
                        views.append(augment.post_crop_augment(
                            crop, size, s_rest, cj,
                            **augment.view_params(
                                cfg.regularizer.aug_spec, vi)))
                    return {"view1": views[0], "view2": views[1],
                            "label": ex["label"]}
                img = augment.test_resize(_decode_full(data), size)
                return {"view1": img, "view2": img, "label": ex["label"]}

            ds = ds.map(_load, num_parallel_calls=tf.data.AUTOTUNE)
            ds = ds.batch(host_batch, drop_remainder=train)
            ds = ds.prefetch(tf.data.AUTOTUNE)
            return ds.as_numpy_iterator()

        return make

    return LoaderBundle(
        make_train_iter=make_iter(*tr_sh, train=True),
        make_test_iter=make_iter(*te_sh, train=False),
        input_shape=(size, size, 3),
        num_train_samples=n_train,
        num_test_samples=n_test,
        output_size=len(classes),
        make_train_eval_iter=make_iter(*tr_sh, train=False),
        eval_sharded=shard_eval and count > 1,
        make_valid_iter=(make_iter(*va_sh, train=False) if n_valid
                         else None),
        num_valid_samples=n_valid,
    )
