"""Two-view SimCLR/BYOL augmentation pipeline (tf.data host path).

Reproduces the reference's torchvision transform stack exactly
(/root/reference/main.py:386-398):

  train: RandomResizedCrop(size)                       (scale .08-1, ratio 3/4-4/3)
         RandomHorizontalFlip(p=.5)
         ColorJitter(.8s, .8s, .8s, .2s) applied with p=.8
         RandomGrayscale(p=.2)
         GaussianBlur(kernel_size=int(.1*size), p=.5)  (datasets.utils contract,
                                                        main.py:384,396; sigma
                                                        ~ U(.1, 2) per SimCLR)
  test:  Resize(size) only — NO center crop and NO mean/std normalization
         (main.py:398; Quirk Q3), pixels stay in [0, 1] (contract enforced at
         main.py:486-490 and re-asserted by the loader here).

Deviation (documented): torchvision's ColorJitter applies its four sub-ops in
random order; here the order is fixed brightness→contrast→saturation→hue.
All randomness is stateless (seeded per-sample from (seed, epoch, index)) so
epoch reshuffling is deterministic — the ``set_all_epochs`` analog
(main.py:760) is just a different fold-in.

``aug_spec="paper"`` selects the BYOL paper's ASYMMETRIC recipe instead
(arXiv 2006.07733 App. B — the spec behind the 74.3% headline, which the
reference never implemented): jitter strengths (.4s, .4s, .2s, .1s); view 1
blurs with p=1.0 and never solarizes; view 2 blurs with p=0.1 and solarizes
(threshold 0.5) with p=0.2.  ``"reference"`` (default) keeps the symmetric
reference stack above.
"""
from __future__ import annotations

import functools
from typing import Tuple

import tensorflow as tf


def _uniform(seed, shape=(), lo=0.0, hi=1.0):
    return tf.random.stateless_uniform(shape, seed=seed, minval=lo, maxval=hi)


def _split(seed, n):
    """Derive n statistically-independent seeds from one (2,) int seed."""
    return tf.unstack(
        tf.random.stateless_uniform((n, 2), seed=seed, minval=None,
                                    maxval=None, dtype=tf.int32), axis=0)


def random_resized_crop(image: tf.Tensor, size: int, seed,
                        scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)) -> tf.Tensor:
    """torchvision RandomResizedCrop semantics via
    ``stateless_sample_distorted_bounding_box`` (area + aspect-ratio sampling
    with whole-image fallback), bilinear resize to (size, size)."""
    bbox = tf.zeros((1, 1, 4), tf.float32)
    begin, sz, _ = tf.image.stateless_sample_distorted_bounding_box(
        tf.shape(image), bounding_boxes=bbox, seed=seed,
        min_object_covered=0.0, aspect_ratio_range=ratio, area_range=scale,
        max_attempts=10, use_image_if_no_bounding_boxes=True)
    crop = tf.slice(image, begin, sz)
    return tf.image.resize(crop, (size, size), method="bilinear")


def _blend(a: tf.Tensor, b: tf.Tensor, factor: tf.Tensor) -> tf.Tensor:
    return tf.clip_by_value(factor * a + (1.0 - factor) * b, 0.0, 1.0)


# torchvision ColorJitter(.8s,.8s,.8s,.2s) — the reference stack
# (main.py:391); single source for every default below.
REFERENCE_JITTER = (0.8, 0.8, 0.8, 0.2)


def color_jitter(image: tf.Tensor, strength: float, seed,
                 factors=REFERENCE_JITTER) -> tf.Tensor:
    """torchvision ColorJitter(brightness, contrast, saturation, hue) =
    ``factors`` x ``strength``, with multiplicative brightness (torch
    semantics, not tf's additive one)."""
    b = factors[0] * strength
    c = factors[1] * strength
    s = factors[2] * strength
    h = factors[3] * strength
    seeds = _split(seed, 4)
    # brightness: img * U(max(0, 1-b), 1+b)
    image = tf.clip_by_value(
        image * _uniform(seeds[0], (), max(0.0, 1.0 - b), 1.0 + b), 0., 1.)
    # contrast: blend with mean of grayscale image
    gray = tf.image.rgb_to_grayscale(image)
    image = _blend(image, tf.reduce_mean(gray),
                   _uniform(seeds[1], (), max(0.0, 1.0 - c), 1.0 + c))
    # saturation: blend with grayscale
    image = _blend(image, tf.image.rgb_to_grayscale(image),
                   _uniform(seeds[2], (), max(0.0, 1.0 - s), 1.0 + s))
    # hue: rotate hue channel in HSV
    if h > 0:
        image = tf.image.stateless_random_hue(image, h, seeds[3])
        image = tf.clip_by_value(image, 0.0, 1.0)
    return image


def random_grayscale(image: tf.Tensor, seed, p: float = 0.2) -> tf.Tensor:
    gray = tf.tile(tf.image.rgb_to_grayscale(image), [1, 1, 3])
    return tf.where(_uniform(seed) < p, gray, image)


def solarize(image: tf.Tensor, threshold: float = 0.5) -> tf.Tensor:
    """Invert pixels above ``threshold`` (paper spec, view 2 only)."""
    return tf.where(image < threshold, image, 1.0 - image)


# Per-(spec, view) parameters.  The reference spec is symmetric
# (main.py:386-397); the paper spec is asymmetric (arXiv 2006.07733 App B).
_VIEW_PARAMS = {
    ("reference", 0): dict(jitter=REFERENCE_JITTER, blur_p=0.5,
                           solarize_p=0.0),
    ("reference", 1): dict(jitter=REFERENCE_JITTER, blur_p=0.5,
                           solarize_p=0.0),
    ("paper", 0): dict(jitter=(0.4, 0.4, 0.2, 0.1), blur_p=1.0,
                       solarize_p=0.0),
    ("paper", 1): dict(jitter=(0.4, 0.4, 0.2, 0.1), blur_p=0.1,
                       solarize_p=0.2),
}


def view_params(spec: str, view: int) -> dict:
    try:
        return _VIEW_PARAMS[(spec, view)]
    except KeyError:
        raise ValueError(f"unknown aug spec/view {(spec, view)!r}; specs: "
                         f"'reference' | 'paper', views: 0 | 1") from None


def gaussian_blur(image: tf.Tensor, kernel_size: int, seed,
                  sigma_range=(0.1, 2.0)) -> tf.Tensor:
    """Depthwise separable gaussian blur; kernel_size = int(.1 * image_size)
    per the reference's GaussianBlur(kernel_size, p=.5) (main.py:384,396)."""
    k = max(int(kernel_size) | 1, 3)  # odd, >= 3
    r = k // 2
    sigma = _uniform(seed, (), *sigma_range)
    x = tf.range(-r, r + 1, dtype=tf.float32)
    g = tf.exp(-(x ** 2) / (2.0 * sigma ** 2))
    g = g / tf.reduce_sum(g)
    ch = image.shape[-1] or 3
    kx = tf.tile(tf.reshape(g, (1, k, 1, 1)), [1, 1, ch, 1])
    ky = tf.tile(tf.reshape(g, (k, 1, 1, 1)), [1, 1, ch, 1])
    # reflect-101 borders (the cv2 GaussianBlur convention, matched by the
    # native C++ backend): zero padding would dim border pixels because the
    # kernel weights falling outside the image contribute nothing.
    img = tf.pad(image[tf.newaxis], [[0, 0], [r, r], [r, r], [0, 0]],
                 mode="REFLECT")
    img = tf.nn.depthwise_conv2d(img, kx, [1, 1, 1, 1], "VALID")
    img = tf.nn.depthwise_conv2d(img, ky, [1, 1, 1, 1], "VALID")
    return img[0]


def post_crop_augment(image: tf.Tensor, size: int, seed,
                      color_jitter_strength: float = 1.0, *,
                      jitter=REFERENCE_JITTER, blur_p: float = 0.5,
                      solarize_p: float = 0.0) -> tf.Tensor:
    """Everything after the crop: flip, jitter(p=.8), grayscale(p=.2),
    blur(p=blur_p), solarize(p=solarize_p), [0,1] clip.  Single source of
    truth shared by the host-array pipeline and the ImageFolder pipeline
    (whose crop is fused with JPEG decode).  The blur gate and blur sigma
    get INDEPENDENT seeds — reusing one seed would make sigma a
    deterministic function of the gate draw."""
    seeds = _split(seed, 7)
    image = tf.image.stateless_random_flip_left_right(image, seeds[0])
    image = tf.where(_uniform(seeds[1]) < 0.8,
                     color_jitter(image, color_jitter_strength, seeds[2],
                                  factors=jitter),
                     image)
    image = random_grayscale(image, seeds[3], p=0.2)
    image = tf.where(_uniform(seeds[4]) < blur_p,
                     gaussian_blur(image, int(0.1 * size), seeds[5]),
                     image)
    if solarize_p > 0.0:
        image = tf.where(_uniform(seeds[6]) < solarize_p,
                         solarize(image), image)
    image = tf.reshape(image, (size, size, 3))
    return tf.clip_by_value(image, 0.0, 1.0)


def train_augment(image: tf.Tensor, size: int, seed,
                  color_jitter_strength: float = 1.0, *,
                  spec: str = "reference", view: int = 0) -> tf.Tensor:
    """One augmented view: image float32 [0,1] HWC -> (size, size, 3)."""
    s_crop, s_rest = _split(seed, 2)
    image = random_resized_crop(image, size, s_crop)
    return post_crop_augment(image, size, s_rest, color_jitter_strength,
                             **view_params(spec, view))


def test_resize(image: tf.Tensor, size: int) -> tf.Tensor:
    """Resize only — no crop, no normalization (main.py:398, Quirk Q3)."""
    image = tf.image.resize(image, (size, size), method="bilinear")
    return tf.clip_by_value(tf.reshape(image, (size, size, 3)), 0.0, 1.0)


def two_views(image: tf.Tensor, size: int, seed,
              color_jitter_strength: float = 1.0,
              spec: str = "reference") -> Tuple[tf.Tensor, tf.Tensor]:
    """Two independently-augmented views of one image — the
    ``multi_augment_image_folder`` contract (main.py:475,579).  Views are
    asymmetric under ``spec='paper'`` (module docstring)."""
    s1, s2 = _split(seed, 2)
    return (train_augment(image, size, s1, color_jitter_strength,
                          spec=spec, view=0),
            train_augment(image, size, s2, color_jitter_strength,
                          spec=spec, view=1))
