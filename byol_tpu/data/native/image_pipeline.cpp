// Native host augmentation pipeline — the DALI-equivalent (C++).
//
// The reference offloads decode+augment to NVIDIA DALI (C++/CUDA) when the
// Python host pipeline can't feed the accelerators
// (/root/reference/main.py:356-382, README.md:90-93).  On TPU the augment
// math must stay on the host CPU (chips are fed via infeed, not CUDA), so
// the native escape hatch is a multithreaded C++ kernel over raw uint8
// batches: two independently-augmented float32 views per image, one pass,
// no Python/TF dispatch overhead per sample.
//
// Augmentation SPEC matches the canonical torchvision stack exactly
// (byol_tpu/data/augment.py; reference main.py:386-397):
//   RandomResizedCrop(size, scale=[.08,1], ratio=[3/4,4/3], bilinear)
//   HFlip(p=.5)
//   ColorJitter(brightness=.8s, contrast=.8s, saturation=.8s, hue=.2s) p=.8
//   RandomGrayscale(p=.2)
//   GaussianBlur(k=int(.1*size)|1>=3, sigma~U(.1,2), p=.5)
//   clip to [0,1]
// (unlike the reference's DALI path, which silently changed the
// hyperparameters — Quirk Q4 — this backend keeps the one canonical spec).
//
// Determinism: every (seed, sample_index, view) triple derives an
// independent splitmix64/xorshift PRNG stream, so epoch reshuffles are
// reproducible and views are decorrelated — same contract as the stateless
// TF path.
//
// JPEG path (BYOL_WITH_JPEG): the reference's DALI exists precisely for
// host-bound JPEG decode+augment at ImageNet scale (main.py:356-382,
// README.md:90-93).  Equivalent trick here, via libjpeg-turbo:
//   1. read ONLY the header for (h, w);
//   2. sample the RandomResizedCrop window in full-image coordinates;
//   3. decode ONLY that window — DCT-domain scaling (scale_num/8 chosen so
//      the decoded crop is ~>= the target size) + jpeg_crop_scanline column
//      cropping + jpeg_skip_scanlines row skipping, then abort the rest;
//   4. bilinear-resize the decoded window to (size, size) and run the same
//      post-crop augment chain as the array path (same PRNG draw order).
// This is the fused decode+crop DALI/tf.image.decode_and_crop_jpeg do; the
// DCT scaling trades a slight low-pass for O(crop*scale^2) work instead of
// O(image) — the standard ImageNet-pipeline tradeoff.
//
// Build: g++ -O3 -shared -fPIC -pthread -o libbyol_aug.so image_pipeline.cpp
// [-DBYOL_WITH_JPEG -ljpeg]
// (byol_tpu/data/native_aug.py compiles this lazily — first with libjpeg,
// falling back to no-JPEG, then to the tf.data path if no toolchain).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

#ifdef BYOL_WITH_JPEG
#include <csetjmp>
#include <cstdio>
#include <jpeglib.h>
#endif

namespace {

// ---- PRNG: splitmix64 seeding + xoshiro-style stream ----------------------
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed) {
    next();  // decorrelate nearby seeds
    next();
  }
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  // uniform in [0, 1)
  double uniform() { return (next() >> 11) * (1.0 / 9007199254740992.0); }
  double uniform(double lo, double hi) { return lo + (hi - lo) * uniform(); }
};

struct CropWindow {
  double y0, x0, ch, cw;  // fractional source window
};

// torchvision RandomResizedCrop window sampling: 10 area/ratio attempts,
// then center fallback.
CropWindow sample_crop(Rng& rng, int h, int w) {
  const double area = static_cast<double>(h) * w;
  for (int attempt = 0; attempt < 10; ++attempt) {
    double target_area = rng.uniform(0.08, 1.0) * area;
    double log_ratio = rng.uniform(std::log(3.0 / 4.0), std::log(4.0 / 3.0));
    double ratio = std::exp(log_ratio);
    double cw = std::sqrt(target_area * ratio);
    double ch = std::sqrt(target_area / ratio);
    if (cw <= w && ch <= h) {
      double y0 = rng.uniform(0.0, h - ch);
      double x0 = rng.uniform(0.0, w - cw);
      return {y0, x0, ch, cw};
    }
  }
  // fallback: central crop at the clamped aspect ratio (torchvision)
  double in_ratio = static_cast<double>(w) / h;
  double cw, ch;
  if (in_ratio < 3.0 / 4.0) {
    cw = w;
    ch = cw / (3.0 / 4.0);
  } else if (in_ratio > 4.0 / 3.0) {
    ch = h;
    cw = ch * (4.0 / 3.0);
  } else {
    cw = w;
    ch = h;
  }
  return {(h - ch) / 2.0, (w - cw) / 2.0, ch, cw};
}

// bilinear sample from uint8 HWC source into float [0,1] RGB
inline void bilinear_rgb(const uint8_t* src, int h, int w, double sy,
                         double sx, float out[3]) {
  sy = std::min(std::max(sy, 0.0), h - 1.0);
  sx = std::min(std::max(sx, 0.0), w - 1.0);
  int y0 = static_cast<int>(sy), x0 = static_cast<int>(sx);
  int y1 = std::min(y0 + 1, h - 1), x1 = std::min(x0 + 1, w - 1);
  double fy = sy - y0, fx = sx - x0;
  const double inv = 1.0 / 255.0;
  for (int c = 0; c < 3; ++c) {
    double v00 = src[(y0 * w + x0) * 3 + c];
    double v01 = src[(y0 * w + x1) * 3 + c];
    double v10 = src[(y1 * w + x0) * 3 + c];
    double v11 = src[(y1 * w + x1) * 3 + c];
    double top = v00 + (v01 - v00) * fx;
    double bot = v10 + (v11 - v10) * fx;
    out[c] = static_cast<float>((top + (bot - top) * fy) * inv);
  }
}

inline float clampf(float v, float lo, float hi) {
  return v < lo ? lo : (v > hi ? hi : v);
}

inline float gray_of(const float* px) {
  return 0.2989f * px[0] + 0.587f * px[1] + 0.114f * px[2];
}

// steps 2-5 of one augmented view, applied in-place to the cropped+resized
// float32 (size, size, 3) buffer.  ONE implementation shared by the
// uint8-array and JPEG paths so both draw from the PRNG in the same order
// (crop draws happen in sample_crop before this is called).
void post_crop_augment(float* dst, int size, float cj_strength, Rng& rng) {
  const int n = size * size;

  // 2) HFlip p=.5
  if (rng.uniform() < 0.5) {
    for (int y = 0; y < size; ++y) {
      float* row = dst + y * size * 3;
      for (int x = 0; x < size / 2; ++x) {
        for (int c = 0; c < 3; ++c)
          std::swap(row[x * 3 + c], row[(size - 1 - x) * 3 + c]);
      }
    }
  }

  // 3) ColorJitter p=.8 — brightness, contrast, saturation (.8s), hue (.2s);
  // fixed order matching byol_tpu/data/augment.py (documented deviation from
  // torchvision's random order).
  const double b = 0.8 * cj_strength, c_ = 0.8 * cj_strength,
               s_ = 0.8 * cj_strength, hs = 0.2 * cj_strength;
  // draw the gate AND the sub-draws from independent streams so disabled
  // branches don't shift downstream randomness
  bool do_jitter = rng.uniform() < 0.8;
  double f_b = rng.uniform(std::max(0.0, 1.0 - b), 1.0 + b);
  double f_c = rng.uniform(std::max(0.0, 1.0 - c_), 1.0 + c_);
  double f_s = rng.uniform(std::max(0.0, 1.0 - s_), 1.0 + s_);
  double theta = rng.uniform(-hs, hs) * 2.0 * M_PI;
  if (do_jitter) {
    // brightness (multiplicative, torch semantics)
    for (int i = 0; i < n * 3; ++i)
      dst[i] = clampf(dst[i] * static_cast<float>(f_b), 0.f, 1.f);
    // contrast: blend with mean gray
    double mean_gray = 0.0;
    for (int i = 0; i < n; ++i) mean_gray += gray_of(dst + i * 3);
    mean_gray /= n;
    for (int i = 0; i < n * 3; ++i)
      dst[i] = clampf(static_cast<float>(f_c * dst[i] +
                                         (1.0 - f_c) * mean_gray), 0.f, 1.f);
    // saturation: blend with per-pixel gray
    for (int i = 0; i < n; ++i) {
      float g = gray_of(dst + i * 3);
      for (int c = 0; c < 3; ++c)
        dst[i * 3 + c] = clampf(
            static_cast<float>(f_s * dst[i * 3 + c] + (1.0 - f_s) * g), 0.f,
            1.f);
    }
    // hue: YIQ rotation (same math as the on-device path)
    if (hs > 0.0) {
      const double cos_t = std::cos(theta), sin_t = std::sin(theta);
      for (int i = 0; i < n; ++i) {
        float r = dst[i * 3], g = dst[i * 3 + 1], bl = dst[i * 3 + 2];
        double yy = 0.299 * r + 0.587 * g + 0.114 * bl;
        double ii = 0.596 * r - 0.274 * g - 0.322 * bl;
        double qq = 0.211 * r - 0.523 * g + 0.312 * bl;
        double i2 = ii * cos_t - qq * sin_t;
        double q2 = ii * sin_t + qq * cos_t;
        dst[i * 3] = clampf(
            static_cast<float>(yy + 0.956 * i2 + 0.621 * q2), 0.f, 1.f);
        dst[i * 3 + 1] = clampf(
            static_cast<float>(yy - 0.272 * i2 - 0.647 * q2), 0.f, 1.f);
        dst[i * 3 + 2] = clampf(
            static_cast<float>(yy - 1.106 * i2 + 1.703 * q2), 0.f, 1.f);
      }
    }
  }

  // 4) RandomGrayscale p=.2
  if (rng.uniform() < 0.2) {
    for (int i = 0; i < n; ++i) {
      float g = gray_of(dst + i * 3);
      dst[i * 3] = dst[i * 3 + 1] = dst[i * 3 + 2] = g;
    }
  }

  // 5) GaussianBlur p=.5 (separable; sigma and gate from independent draws)
  bool do_blur = rng.uniform() < 0.5;
  double sigma = rng.uniform(0.1, 2.0);
  if (do_blur) {
    int k = static_cast<int>(0.1 * size) | 1;
    if (k < 3) k = 3;
    int r = k / 2;
    std::vector<float> g(k);
    float sum = 0.f;
    for (int i = 0; i < k; ++i) {
      double x = i - r;
      g[i] = static_cast<float>(std::exp(-(x * x) / (2.0 * sigma * sigma)));
      sum += g[i];
    }
    for (int i = 0; i < k; ++i) g[i] /= sum;
    std::vector<float> tmp(n * 3);
    // reflect-101 border indexing (cv2 GaussianBlur convention; keeps the
    // two DALI-analog backends bit-consistent with data/augment.py's
    // REFLECT-padded depthwise conv)
    auto reflect101 = [](int v, int n) {
      if (v < 0) v = -v;
      if (v >= n) v = 2 * n - 2 - v;
      return v;
    };
    // horizontal
    for (int y = 0; y < size; ++y) {
      for (int x = 0; x < size; ++x) {
        float acc[3] = {0, 0, 0};
        for (int t = -r; t <= r; ++t) {
          int xx = reflect101(x + t, size);
          const float* px = dst + (y * size + xx) * 3;
          for (int c = 0; c < 3; ++c) acc[c] += g[t + r] * px[c];
        }
        for (int c = 0; c < 3; ++c) tmp[(y * size + x) * 3 + c] = acc[c];
      }
    }
    // vertical
    for (int y = 0; y < size; ++y) {
      for (int x = 0; x < size; ++x) {
        float acc[3] = {0, 0, 0};
        for (int t = -r; t <= r; ++t) {
          int yy = reflect101(y + t, size);
          const float* px = tmp.data() + (yy * size + x) * 3;
          for (int c = 0; c < 3; ++c) acc[c] += g[t + r] * px[c];
        }
        for (int c = 0; c < 3; ++c)
          dst[(y * size + x) * 3 + c] = clampf(acc[c], 0.f, 1.f);
      }
    }
  }
}

// one augmented view: src uint8 (h, w, 3) -> dst float32 (size, size, 3)
void augment_one(const uint8_t* src, int h, int w, float* dst, int size,
                 float cj_strength, Rng& rng) {
  // 1) RandomResizedCrop (bilinear)
  CropWindow win = sample_crop(rng, h, w);
  double step_y = win.ch / size, step_x = win.cw / size;
  for (int y = 0; y < size; ++y) {
    for (int x = 0; x < size; ++x) {
      bilinear_rgb(src, h, w, win.y0 + (y + 0.5) * step_y - 0.5,
                   win.x0 + (x + 0.5) * step_x - 0.5, dst + (y * size + x) * 3);
    }
  }
  post_crop_augment(dst, size, cj_strength, rng);
}

// test-only resize (bilinear, whole image -> size x size), matching the
// reference's Resize-only eval transform (main.py:398)
void resize_one(const uint8_t* src, int h, int w, float* dst, int size) {
  double step_y = static_cast<double>(h) / size;
  double step_x = static_cast<double>(w) / size;
  for (int y = 0; y < size; ++y)
    for (int x = 0; x < size; ++x)
      bilinear_rgb(src, h, w, (y + 0.5) * step_y - 0.5,
                   (x + 0.5) * step_x - 0.5, dst + (y * size + x) * 3);
}

#ifdef BYOL_WITH_JPEG
// ---- libjpeg(-turbo) fused decode ----------------------------------------
struct JpegErrorMgr {
  jpeg_error_mgr mgr;
  jmp_buf setjmp_buffer;
};

void jpeg_error_exit(j_common_ptr cinfo) {
  JpegErrorMgr* err = reinterpret_cast<JpegErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}
void jpeg_silent(j_common_ptr, int) {}
void jpeg_silent_msg(j_common_ptr) {}

// RAII so longjmp error paths can't leak the decompress object
struct JpegDecoder {
  jpeg_decompress_struct cinfo;
  JpegErrorMgr jerr;
  bool live = false;
  JpegDecoder() {
    cinfo.err = jpeg_std_error(&jerr.mgr);
    jerr.mgr.error_exit = jpeg_error_exit;
    jerr.mgr.emit_message = jpeg_silent;
    jerr.mgr.output_message = jpeg_silent_msg;
    jpeg_create_decompress(&cinfo);
    live = true;
  }
  ~JpegDecoder() {
    if (live) jpeg_destroy_decompress(&cinfo);
  }
};

// Decode a rectangular window of a JPEG at DCT scale s/8.
//   win (fractional, FULL-RES coords) -> decoded uint8 RGB buffer `out`
//   covering at least the window at scale s/8; returns false on corrupt /
//   unsupported (CMYK etc.) input.  `bw/bh` = buffer dims; `by0/bx0` =
//   buffer origin in SCALED image coords.
bool jpeg_decode_window(const uint8_t* data, size_t len, const CropWindow& win,
                        int scale_num, std::vector<uint8_t>& out, int* bw,
                        int* bh, double* by0, double* bx0, double* sy_scale,
                        double* sx_scale) {
  JpegDecoder dec;
  jpeg_decompress_struct& cinfo = dec.cinfo;
  if (setjmp(dec.jerr.setjmp_buffer)) return false;
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) return false;
  cinfo.out_color_space = JCS_RGB;
  cinfo.scale_num = scale_num;
  cinfo.scale_denom = 8;
  cinfo.dct_method = JDCT_ISLOW;
  if (!jpeg_start_decompress(&cinfo)) return false;
  if (cinfo.output_components != 3) return false;  // CMYK etc.: caller falls back
  const int ow = cinfo.output_width, oh = cinfo.output_height;
  // full-res fractional window -> scaled coords (libjpeg scales by the
  // EXACT rational output_size/input_size, matching these factors)
  const double fy = static_cast<double>(oh) / cinfo.image_height;
  const double fx = static_cast<double>(ow) / cinfo.image_width;
  double y0s = win.y0 * fy, x0s = win.x0 * fx;
  double chs = win.ch * fy, cws = win.cw * fx;
  int y_lo = std::max(0, static_cast<int>(std::floor(y0s)));
  int y_hi = std::min(oh, static_cast<int>(std::ceil(y0s + chs)) + 1);
  JDIMENSION xoff = static_cast<JDIMENSION>(
      std::max(0, static_cast<int>(std::floor(x0s))));
  JDIMENSION xw = static_cast<JDIMENSION>(
      std::min(ow - static_cast<int>(xoff),
               static_cast<int>(std::ceil(cws)) + 2));
  // jpeg_crop_scanline rounds xoff DOWN to an iMCU boundary and widens xw
  // accordingly; it returns the adjusted values.
  jpeg_crop_scanline(&cinfo, &xoff, &xw);
  if (y_hi <= y_lo) y_hi = std::min(oh, y_lo + 1);
  out.resize(static_cast<size_t>(y_hi - y_lo) * xw * 3);
  if (y_lo > 0) jpeg_skip_scanlines(&cinfo, y_lo);
  JSAMPROW row;
  for (int y = y_lo; y < y_hi; ++y) {
    row = out.data() + static_cast<size_t>(y - y_lo) * xw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_abort_decompress(&cinfo);  // skip the remaining rows entirely
  *bw = static_cast<int>(xw);
  *bh = y_hi - y_lo;
  *by0 = y_lo;
  *bx0 = xoff;
  *sy_scale = fy;
  *sx_scale = fx;
  return true;
}

// pick the smallest DCT scale s/8 whose decoded window still has >= `size`
// pixels on its short side (never upscale past full resolution)
int pick_scale(double win_short, int size) {
  for (int s = 1; s <= 8; ++s) {
    if (win_short * s / 8.0 >= size) return s;
  }
  return 8;
}

// one augmented view straight from JPEG bytes; false -> caller must fall
// back (corrupt file / CMYK / not a JPEG)
bool jpeg_augment_one(const uint8_t* data, size_t len, float* dst, int size,
                      float cj_strength, Rng& rng) {
  // header-only pass for dimensions (cheap: no IDCT)
  int h, w;
  {
    JpegDecoder dec;
    if (setjmp(dec.jerr.setjmp_buffer)) return false;
    jpeg_mem_src(&dec.cinfo, const_cast<uint8_t*>(data),
                 static_cast<unsigned long>(len));
    if (jpeg_read_header(&dec.cinfo, TRUE) != JPEG_HEADER_OK) return false;
    h = dec.cinfo.image_height;
    w = dec.cinfo.image_width;
  }
  if (h <= 0 || w <= 0) return false;
  // 1) sample the crop in full-res coords (same draw order as the array
  // path), then decode only that window
  CropWindow win = sample_crop(rng, h, w);
  int scale = pick_scale(std::min(win.ch, win.cw), size);
  std::vector<uint8_t> buf;
  int bw, bh;
  double by0, bx0, fy, fx;
  if (!jpeg_decode_window(data, len, win, scale, buf, &bw, &bh, &by0, &bx0,
                          &fy, &fx))
    return false;
  // window in buffer coords
  const double wy0 = win.y0 * fy - by0, wx0 = win.x0 * fx - bx0;
  const double step_y = win.ch * fy / size, step_x = win.cw * fx / size;
  for (int y = 0; y < size; ++y) {
    for (int x = 0; x < size; ++x) {
      bilinear_rgb(buf.data(), bh, bw, wy0 + (y + 0.5) * step_y - 0.5,
                   wx0 + (x + 0.5) * step_x - 0.5, dst + (y * size + x) * 3);
    }
  }
  post_crop_augment(dst, size, cj_strength, rng);
  return true;
}

// eval: full-frame decode at the coarsest sufficient DCT scale + resize
// (reference Resize-only test transform, main.py:398)
bool jpeg_resize_one(const uint8_t* data, size_t len, float* dst, int size) {
  int h, w;
  {
    JpegDecoder dec;
    if (setjmp(dec.jerr.setjmp_buffer)) return false;
    jpeg_mem_src(&dec.cinfo, const_cast<uint8_t*>(data),
                 static_cast<unsigned long>(len));
    if (jpeg_read_header(&dec.cinfo, TRUE) != JPEG_HEADER_OK) return false;
    h = dec.cinfo.image_height;
    w = dec.cinfo.image_width;
  }
  CropWindow full{0.0, 0.0, static_cast<double>(h), static_cast<double>(w)};
  int scale = pick_scale(std::min(h, w), size);
  std::vector<uint8_t> buf;
  int bw, bh;
  double by0, bx0, fy, fx;
  if (!jpeg_decode_window(data, len, full, scale, buf, &bw, &bh, &by0, &bx0,
                          &fy, &fx))
    return false;
  resize_one(buf.data(), bh, bw, dst, size);
  return true;
}
#endif  // BYOL_WITH_JPEG

void run_threads(int n, int num_threads, const std::function<void(int)>& fn) {
  if (num_threads <= 1) {
    for (int i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int> cursor{0};
  std::vector<std::thread> pool;
  pool.reserve(num_threads);
  for (int t = 0; t < num_threads; ++t) {
    pool.emplace_back([&] {
      for (int i = cursor.fetch_add(1); i < n; i = cursor.fetch_add(1)) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Two independently-augmented views for a uint8 NHWC batch.
//   images: (n, h, w, 3) uint8; out1/out2: (n, size, size, 3) float32.
//   seed/index_base: deterministic per-sample streams (epoch reseed = new
//   index_base or seed, the set_all_epochs analog).
void byol_augment_two_views(const uint8_t* images, int n, int h, int w,
                            float* out1, float* out2, int size,
                            float cj_strength, uint64_t seed,
                            uint64_t index_base, int num_threads) {
  const size_t in_stride = static_cast<size_t>(h) * w * 3;
  const size_t out_stride = static_cast<size_t>(size) * size * 3;
  run_threads(n, num_threads, [&](int i) {
    const uint8_t* src = images + i * in_stride;
    uint64_t base = seed * 0x9e3779b97f4a7c15ULL + (index_base + i);
    Rng r1(base * 2 + 0), r2(base * 2 + 1);
    augment_one(src, h, w, out1 + i * out_stride, size, cj_strength, r1);
    augment_one(src, h, w, out2 + i * out_stride, size, cj_strength, r2);
  });
}

// Resize-only eval batch (reference test transform, main.py:398).
void byol_resize_batch(const uint8_t* images, int n, int h, int w, float* out,
                       int size, int num_threads) {
  const size_t in_stride = static_cast<size_t>(h) * w * 3;
  const size_t out_stride = static_cast<size_t>(size) * size * 3;
  run_threads(n, num_threads,
              [&](int i) { resize_one(images + i * in_stride, h, w,
                                      out + i * out_stride, size); });
}

// 1 when this build fuses JPEG decode (libjpeg linked), else 0 — lets the
// Python side route image trees to tf.data when the toolchain lacked jpeg.
int byol_has_jpeg(void) {
#ifdef BYOL_WITH_JPEG
  return 1;
#else
  return 0;
#endif
}

#ifdef BYOL_WITH_JPEG
// Two augmented views per JPEG, fused decode+crop (the DALI-analog entry
// point for image trees).  blob = concatenated JPEG byte streams;
// offsets/sizes (n) delimit them.  ok[i]=0 flags images this decoder can't
// serve (corrupt / CMYK / non-JPEG) — their outputs are zeroed and the
// caller re-decodes those few via its fallback path.
void byol_jpeg_augment_two_views(const uint8_t* blob, const uint64_t* offsets,
                                 const uint64_t* sizes, int n, float* out1,
                                 float* out2, int size, float cj_strength,
                                 uint64_t seed, uint64_t index_base,
                                 int num_threads, int32_t* ok) {
  const size_t out_stride = static_cast<size_t>(size) * size * 3;
  run_threads(n, num_threads, [&](int i) {
    const uint8_t* data = blob + offsets[i];
    const size_t len = sizes[i];
    uint64_t base = seed * 0x9e3779b97f4a7c15ULL + (index_base + i);
    Rng r1(base * 2 + 0), r2(base * 2 + 1);
    bool ok1 = jpeg_augment_one(data, len, out1 + i * out_stride, size,
                                cj_strength, r1);
    bool ok2 = ok1 && jpeg_augment_one(data, len, out2 + i * out_stride, size,
                                       cj_strength, r2);
    ok[i] = (ok1 && ok2) ? 1 : 0;
    if (!ok[i]) {
      std::memset(out1 + i * out_stride, 0, out_stride * sizeof(float));
      std::memset(out2 + i * out_stride, 0, out_stride * sizeof(float));
    }
  });
}

// Resize-only eval batch from JPEG bytes.
void byol_jpeg_resize_batch(const uint8_t* blob, const uint64_t* offsets,
                            const uint64_t* sizes, int n, float* out, int size,
                            int num_threads, int32_t* ok) {
  const size_t out_stride = static_cast<size_t>(size) * size * 3;
  run_threads(n, num_threads, [&](int i) {
    ok[i] = jpeg_resize_one(blob + offsets[i], sizes[i], out + i * out_stride,
                            size)
                ? 1
                : 0;
    if (!ok[i]) std::memset(out + i * out_stride, 0, out_stride * sizeof(float));
  });
}
#endif  // BYOL_WITH_JPEG

}  // extern "C"
