"""Device prefetch: double-buffer host batches into HBM.

The DALI/`prefetch_to_device` analog (SURVEY.md §2.4): while the TPU runs
step N, the next host batch is already being produced AND transferred, so
the MXU never waits on the host.  Works with any iterator of numpy pytrees;
placement uses the mesh ``data``-axis sharding so each device receives only
its shard.

Production runs on a BACKGROUND THREAD: the original implementation called
``next(iterator)`` synchronously in the consumer loop, so the host-side
augment/decode work (tf.data graph or the C++ pipeline — plus the
range-check/tap generators the trainer stacks on top) blocked the dispatch
thread between steps.  On a 1-core TPU host that serialization is the whole
ballgame: with production moved off-thread, augment/decode for batch N+1
overlaps both the device compute of batch N and its H2D transfer (numpy /
tf / device_put all release the GIL during the heavy parts).

Contract kept from the synchronous version:
- yields device-resident batches in exactly the iterator's order;
- at most ``size`` batches are in flight beyond the one being consumed;
- an exception raised by the source iterator (e.g. the trainer's [0,1]
  range check) propagates to the consumer — after the batches produced
  before it, exactly where the synchronous version would have raised;
- closing the generator (``break`` / ``.close()``) stops the producer
  thread promptly and joins it — no daemon-thread leaks into the next
  epoch's iterator.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Iterator, Optional

import numpy as np
from jax.sharding import Mesh

from byol_tpu.observability.meters import InputPipelineMeter
from byol_tpu.parallel.mesh import shard_batch_to_mesh

_END = object()          # producer sentinel: source iterator exhausted


class _Failure:
    """Carries a producer-side exception across the queue."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def _leaf_nbytes(v) -> int:
    """Byte size from ARRAY METADATA only — never materializes the value.
    ``np.asarray`` here would force a blocking D2H copy when the loader
    yields device arrays (the ``data_backend='device'`` path), serializing
    the very pipeline this module double-buffers."""
    nbytes = getattr(v, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    return int(np.prod(np.shape(v))) * np.dtype(
        getattr(v, "dtype", np.float32)).itemsize


def host_nbytes(batch) -> int:
    """Bytes one batch ships into the prefetch pipeline — the H2D payload
    the input-pipeline meter reports (uint8 raw batches are ~8x smaller
    than two float32 views at 224px; the meter makes that visible per
    run).  Caveat: with ``data_backend='device'`` the loader's batches are
    already device-resident views, so this counts the view payload rather
    than the smaller uint8 transfer the augment dispatch made — still
    metadata-only, no copy.  Shared with bench.py's per-row
    ``h2d_bytes_per_step`` so the two surfaces cannot drift."""
    if isinstance(batch, dict):
        return sum(_leaf_nbytes(v) for v in batch.values())
    return _leaf_nbytes(batch)


def prefetch_to_mesh(iterator: Iterator, mesh: Mesh, size: int = 2,
                     meter: Optional[InputPipelineMeter] = None,
                     recorder=None) -> Iterator:
    """Yield device-resident batches, keeping up to ``size`` in flight.

    ``meter`` (observability.meters.InputPipelineMeter): when given, the
    producer records each batch's host-byte payload + the queue depth it
    leaves, and the consumer records its blocking wait for the next batch
    (time-to-next-batch / starvation) — the input-pipeline health surface
    the trainer prints per epoch.

    ``recorder`` (observability.spans.SpanRecorder): when given, each
    consumer wait becomes an ``input/fill`` (first batch) or ``input/wait``
    span — the flight-recorder twin of the meter's aggregate, attributed
    to the ``input_wait`` goodput bucket.  The spans open in the CONSUMER
    thread (this generator's caller), so they never overlap the trainer's
    other top-level spans."""
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    # ``slots`` — not the queue's maxsize — is what bounds device residency:
    # each of the ``size`` slots covers one device-resident batch beyond the
    # consumed one, and the producer RESERVES its slot before device_put.
    # (Sharding first and then blocking on a bounded queue would pin a
    # size+1'th batch in HBM — ~1.2 GB/batch at effective-4096@224, on
    # exactly the memory-wall configs accumulation exists to fit.)
    q: "queue.Queue" = queue.Queue()
    slots = threading.Semaphore(size)
    stop = threading.Event()

    def produce():
        try:
            for batch in iterator:
                # Slot acquisition that notices consumer shutdown: a plain
                # blocking acquire would deadlock the join below if the
                # consumer left while all slots were held.
                while not slots.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                nbytes = host_nbytes(batch) if meter is not None else 0
                q.put(shard_batch_to_mesh(batch, mesh))
                if meter is not None:
                    meter.record_produced(nbytes, q.qsize())
            item = _END
        except BaseException as e:   # noqa: BLE001 — relayed, not dropped
            item = _Failure(e)
        # Sentinels bypass the slots (they hold no device memory) and the
        # queue is unbounded, so this put never blocks.
        q.put(item)

    thread = threading.Thread(target=produce, name="prefetch_to_mesh",
                              daemon=True)
    thread.start()
    if recorder is None:
        from byol_tpu.observability import spans as spans_lib
        recorder = spans_lib.NULL
    try:
        first = True
        while True:
            t0 = time.perf_counter() if meter is not None else 0.0
            with recorder.span("input/fill" if first else "input/wait"):
                item = q.get()
            if item is _END:
                return
            if isinstance(item, _Failure):
                raise item.exc
            if meter is not None:
                # Real batches only (blocking on the end-of-epoch sentinel
                # is not starvation), and the FIRST batch's wait is
                # pipeline fill (producer startup + producing batch 1) —
                # recorded separately so a healthy pipeline never reports
                # a starved step every epoch.
                dt = time.perf_counter() - t0
                if first:
                    meter.record_first_fill(dt)
                else:
                    meter.record_wait(dt)
            first = False
            # This batch is now "the one being consumed": free its slot so
            # the producer can stage the next one.
            slots.release()
            yield item
    finally:
        stop.set()
        thread.join(timeout=5.0)
