"""Device prefetch: double-buffer host batches into HBM.

The DALI/`prefetch_to_device` analog (SURVEY.md §2.4): while the TPU runs
step N, the next host batch is already being produced AND transferred, so
the MXU never waits on the host.  Works with any iterator of numpy pytrees;
placement uses the mesh ``data``-axis sharding so each device receives only
its shard.

Production runs on a BACKGROUND THREAD: the original implementation called
``next(iterator)`` synchronously in the consumer loop, so the host-side
augment/decode work (tf.data graph or the C++ pipeline — plus the
range-check/tap generators the trainer stacks on top) blocked the dispatch
thread between steps.  On a 1-core TPU host that serialization is the whole
ballgame: with production moved off-thread, augment/decode for batch N+1
overlaps both the device compute of batch N and its H2D transfer (numpy /
tf / device_put all release the GIL during the heavy parts).

Contract kept from the synchronous version:
- yields device-resident batches in exactly the iterator's order;
- at most ``size`` batches are in flight beyond the one being consumed;
- an exception raised by the source iterator (e.g. the trainer's [0,1]
  range check) propagates to the consumer — after the batches produced
  before it, exactly where the synchronous version would have raised;
- closing the generator (``break`` / ``.close()``) stops the producer
  thread promptly and joins it — no daemon-thread leaks into the next
  epoch's iterator.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator

from jax.sharding import Mesh

from byol_tpu.parallel.mesh import shard_batch_to_mesh

_END = object()          # producer sentinel: source iterator exhausted


class _Failure:
    """Carries a producer-side exception across the queue."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def prefetch_to_mesh(iterator: Iterator, mesh: Mesh, size: int = 2
                     ) -> Iterator:
    """Yield device-resident batches, keeping up to ``size`` in flight."""
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    # ``slots`` — not the queue's maxsize — is what bounds device residency:
    # each of the ``size`` slots covers one device-resident batch beyond the
    # consumed one, and the producer RESERVES its slot before device_put.
    # (Sharding first and then blocking on a bounded queue would pin a
    # size+1'th batch in HBM — ~1.2 GB/batch at effective-4096@224, on
    # exactly the memory-wall configs accumulation exists to fit.)
    q: "queue.Queue" = queue.Queue()
    slots = threading.Semaphore(size)
    stop = threading.Event()

    def produce():
        try:
            for batch in iterator:
                # Slot acquisition that notices consumer shutdown: a plain
                # blocking acquire would deadlock the join below if the
                # consumer left while all slots were held.
                while not slots.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                q.put(shard_batch_to_mesh(batch, mesh))
            item = _END
        except BaseException as e:   # noqa: BLE001 — relayed, not dropped
            item = _Failure(e)
        # Sentinels bypass the slots (they hold no device memory) and the
        # queue is unbounded, so this put never blocks.
        q.put(item)

    thread = threading.Thread(target=produce, name="prefetch_to_mesh",
                              daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, _Failure):
                raise item.exc
            # This batch is now "the one being consumed": free its slot so
            # the producer can stage the next one.
            slots.release()
            yield item
    finally:
        stop.set()
        thread.join(timeout=5.0)
