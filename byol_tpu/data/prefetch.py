"""Device prefetch: double-buffer host batches into HBM.

The DALI/`prefetch_to_device` analog (SURVEY.md §2.4): while the TPU runs
step N, the next host batch is already being transferred, so the MXU never
waits on PCIe/host.  Works with any iterator of numpy pytrees; placement uses
the mesh ``data``-axis sharding so each device receives only its shard.
"""
from __future__ import annotations

import collections
from typing import Iterator

import jax
from jax.sharding import Mesh

from byol_tpu.parallel.mesh import shard_batch_to_mesh


def prefetch_to_mesh(iterator: Iterator, mesh: Mesh, size: int = 2
                     ) -> Iterator:
    """Yield device-resident batches, keeping ``size`` in flight."""
    queue = collections.deque()

    def enqueue(n):
        for _ in range(n):
            batch = next(iterator, None)
            if batch is None:
                return
            queue.append(shard_batch_to_mesh(batch, mesh))

    enqueue(size)
    while queue:
        out = queue.popleft()
        enqueue(1)
        yield out
