"""Epoch metric accumulation + stdout logging + step timing.

Replaces the reference's dm-tree running-sum (main.py:607-608,634-635),
its per-epoch stdout line (main.py:638-643) and its coarse wall-clock
timing (main.py:572) with: a pytree accumulator (jax.tree_util — the
dm-tree TPU-native equivalent, SURVEY.md §2.4), the same log line format,
and a step timer reporting images/sec/chip — the BASELINE.json headline
metric the reference never measured (SURVEY.md §5.1).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, Optional

import jax
import numpy as np


class MetricAccumulator:
    """Running sum of metric pytrees, divided out at epoch end
    (main.py:607-608,634-635).

    The sum is accumulated with device ops (async dispatch) — no host sync
    per step, so the trainer's hot loop keeps running ahead of the chip;
    the only block is the ``result()`` readback at the epoch boundary.

    A metric dict containing ``_weight`` (per-batch valid-sample count, from
    pad+mask eval batching) is accumulated as a weighted mean instead: each
    metric is a mean over ``_weight`` samples, so the epoch value is
    sum(metric*w)/sum(w).  ``_weight`` never appears in ``result()``."""

    def __init__(self) -> None:
        self._sum: Optional[Any] = None
        self.count = 0

    def update(self, metrics: Any) -> None:
        if isinstance(metrics, dict) and "_weight" in metrics:
            w = metrics["_weight"]
            metrics = {k: (v if k == "_weight" else v * w)
                       for k, v in metrics.items()}
        if self._sum is None:
            self._sum = metrics
        else:
            self._sum = jax.tree_util.tree_map(
                lambda a, b: a + b, self._sum, metrics)
        self.count += 1

    def result(self) -> Dict[str, np.ndarray]:
        if self._sum is None:
            return {}
        if isinstance(self._sum, dict) and "_weight" in self._sum:
            total = float(np.asarray(self._sum["_weight"]))
            return {k: np.asarray(v) / max(total, 1.0)
                    for k, v in self._sum.items() if k != "_weight"}
        return jax.tree_util.tree_map(
            lambda s: np.asarray(s) / self.count, self._sum)

    def total_weight(self) -> Optional[float]:
        """Total valid-sample count when metrics carried ``_weight`` (pad+
        mask eval), else None — lets the epoch log report true samples."""
        if isinstance(self._sum, dict) and "_weight" in self._sum:
            return float(np.asarray(self._sum["_weight"]))
        return None


def epoch_log_line(prefix: str, epoch: int, num_samples: int,
                   elapsed_s: float, metrics: Dict[str, Any]) -> str:
    """The reference's one-line epoch summary (main.py:638-643):
    prefix, epoch, samples, seconds, loss, top1/top5."""
    def get(k):
        v = metrics.get(k)
        return float(np.asarray(v)) if v is not None else float("nan")
    return (f"{prefix}[Epoch {epoch}][{num_samples} samples]"
            f"[{elapsed_s:.2f} sec]: loss: {get('loss_mean'):.4f}\t"
            f"byol: {get('byol_loss_mean'):.4f}\t"
            f"linear: {get('linear_loss_mean'):.4f}\t"
            f"top1: {get('top1_mean'):.4f}\ttop5: {get('top5_mean'):.4f}")


class InputPipelineMeter:
    """Host input-pipeline health over one epoch (ISSUE 3 meters).

    Fed by ``prefetch_to_mesh``: the PRODUCER records how many host bytes
    each batch ships to the devices (the H2D payload) and the queue depth
    it leaves behind; the CONSUMER records how long it blocked waiting for
    the next device-resident batch (time-to-next-batch).  A wait above
    ``starvation_threshold_s`` counts as a STARVED step — the chip sat
    idle because the host pipeline could not keep up.

    Thread-safety: the producer thread writes byte/depth fields, the
    consumer thread writes wait fields; no field is written by both, and
    reads happen at the epoch boundary after iteration ends.
    """

    def __init__(self, starvation_threshold_s: float = 0.005) -> None:
        self.starvation_threshold_s = starvation_threshold_s
        self.h2d_bytes = 0           # host bytes shipped (producer)
        self.batches_produced = 0
        self._depth_sum = 0          # queue depth samples (producer)
        self.wait_seconds = 0.0      # consumer block time, total
        self.starved_seconds = 0.0   # consumer block time above threshold
        self.starved_steps = 0
        self.batches_consumed = 0
        self.first_fill_seconds = 0.0  # time-to-first-batch (pipeline
                                       # fill) — NOT starvation

    # ---- producer side ----------------------------------------------------
    def record_produced(self, nbytes: int, queue_depth: int) -> None:
        self.h2d_bytes += int(nbytes)
        self._depth_sum += int(queue_depth)
        self.batches_produced += 1

    # ---- consumer side ----------------------------------------------------
    def record_first_fill(self, seconds: float) -> None:
        """The epoch's first wait = producer startup + producing batch 1.
        Every pipeline pays it once; counting it as starvation would make
        a healthy run report a starved step per epoch."""
        self.first_fill_seconds += seconds
        self.batches_consumed += 1

    def record_wait(self, seconds: float) -> None:
        self.wait_seconds += seconds
        if seconds > self.starvation_threshold_s:
            self.starved_seconds += seconds
            self.starved_steps += 1
        self.batches_consumed += 1

    # ---- epoch-boundary readout -------------------------------------------
    def h2d_bytes_per_step(self) -> float:
        return (self.h2d_bytes / self.batches_produced
                if self.batches_produced else 0.0)

    def avg_queue_depth(self) -> float:
        return (self._depth_sum / self.batches_produced
                if self.batches_produced else 0.0)

    def result(self) -> Dict[str, float]:
        """Scalar dict for the grapher / epoch log."""
        return {"h2d_bytes_per_step": self.h2d_bytes_per_step(),
                "input_starved_seconds": self.starved_seconds,
                "input_starved_steps": float(self.starved_steps),
                "input_wait_seconds": self.wait_seconds,
                "input_first_fill_seconds": self.first_fill_seconds,
                "prefetch_queue_depth": self.avg_queue_depth()}


def input_log_line(epoch: int, meter: InputPipelineMeter) -> str:
    """One-line input-pipeline summary next to the train epoch line."""
    return (f"input[Epoch {epoch}]"
            f"[{meter.batches_consumed} batches]: "
            f"h2d: {meter.h2d_bytes_per_step() / 2 ** 20:.2f} MiB/step\t"
            f"starved: {meter.starved_seconds:.2f} sec "
            f"({meter.starved_steps} steps)\t"
            f"fill: {meter.first_fill_seconds:.2f} sec\t"
            f"queue depth: {meter.avg_queue_depth():.2f}")


class StepTimer:
    """images/sec/chip measured ONLY over host-synchronized intervals.

    Per-step host timestamps taken after async dispatch are meaningless —
    the host runs ahead of the chip, and on tunneled platforms (axon) even
    ``block_until_ready`` returns at dispatch-ack, so a dispatch-timed rate
    can overstate by orders of magnitude.  The trainer instead calls
    ``record_epoch`` with an elapsed time whose endpoint is a D2H metric
    READBACK (``MetricAccumulator.result()``), which cannot complete before
    every step in the epoch has: the resulting rate is honest end-to-end
    throughput including the input pipeline (the same sync discipline as
    bench.py's scalar readback)."""

    def __init__(self, global_batch: int, n_chips: int):
        self.global_batch = global_batch
        self.n_chips = max(n_chips, 1)
        self._rate = 0.0
        self._flops_per_sample: Optional[float] = None
        self._peak_tflops: Optional[float] = None
        # per-epoch dispatch timestamps for the step-time tail (bounded:
        # a pathological epoch must not grow host memory without limit)
        self._ticks: "deque[float]" = deque(maxlen=1 << 16)

    def set_flops(self, flops_per_sample: Optional[float],
                  peak_tflops: Optional[float]) -> None:
        """Arm MFU reporting (observability.flops); either None disarms."""
        self._flops_per_sample = flops_per_sample
        self._peak_tflops = peak_tflops

    def mfu(self) -> Optional[float]:
        from byol_tpu.observability.flops import mfu as _mfu
        return _mfu(self._rate, self._flops_per_sample, self._peak_tflops)

    def record_epoch(self, steps: int, elapsed_s: float) -> None:
        """Record one epoch's synchronized (steps, wall-clock) measurement;
        ``elapsed_s`` must end AFTER a device readback that depends on every
        step (see class docstring)."""
        if steps > 0 and elapsed_s > 0.0:
            self._rate = (self.global_batch * steps / elapsed_s
                          / self.n_chips)

    def images_per_sec_per_chip(self) -> float:
        """Most recent epoch's rate (0.0 before the first epoch ends)."""
        return self._rate

    # ---- step-time tail ---------------------------------------------------
    def tick(self) -> None:
        """Stamp one optimizer-step dispatch (one deque append — safe in
        the hot loop).  Consecutive tick intervals are DISPATCH-to-dispatch
        times: while the host runs ahead they understate true step time,
        but once the device queue applies backpressure they converge to
        it — the same signal the telemetry step_time_spike rule uses, and
        the only per-step timing a host can take without a sync.  The
        epoch MEAN stays the honest readback-synced number (record_epoch);
        these quantiles add the TAIL (p50/p99) that the mean hides."""
        self._ticks.append(time.perf_counter())

    def reset_ticks(self) -> None:
        """Start a fresh epoch window (epoch boundaries span eval/
        checkpoint — their gap must not pollute the next epoch's tail)."""
        self._ticks.clear()

    def epoch_step_quantiles(self) -> Optional[Dict[str, float]]:
        """p50/p99/max of this epoch's dispatch intervals, or None below
        3 intervals (a tail over one or two samples is noise, and the
        debug_step smoke has only one dispatch per epoch)."""
        if len(self._ticks) < 4:
            return None
        d = np.diff(np.asarray(self._ticks, np.float64))
        return {"step_time_p50_s": float(np.percentile(d, 50)),
                "step_time_p99_s": float(np.percentile(d, 99)),
                "step_time_max_s": float(d.max())}
