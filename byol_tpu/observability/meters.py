"""Epoch metric accumulation + stdout logging + step timing.

Replaces the reference's dm-tree running-sum (main.py:607-608,634-635),
its per-epoch stdout line (main.py:638-643) and its coarse wall-clock
timing (main.py:572) with: a pytree accumulator (jax.tree_util — the
dm-tree TPU-native equivalent, SURVEY.md §2.4), the same log line format,
and a step timer reporting images/sec/chip — the BASELINE.json headline
metric the reference never measured (SURVEY.md §5.1).
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax
import numpy as np


class MetricAccumulator:
    """Running sum of metric pytrees, divided out at epoch end
    (main.py:607-608,634-635).

    The sum is accumulated with device ops (async dispatch) — no host sync
    per step, so the trainer's hot loop keeps running ahead of the chip;
    the only block is the ``result()`` readback at the epoch boundary."""

    def __init__(self) -> None:
        self._sum: Optional[Any] = None
        self.count = 0

    def update(self, metrics: Any) -> None:
        if self._sum is None:
            self._sum = metrics
        else:
            self._sum = jax.tree_util.tree_map(
                lambda a, b: a + b, self._sum, metrics)
        self.count += 1

    def result(self) -> Dict[str, np.ndarray]:
        if self._sum is None:
            return {}
        return jax.tree_util.tree_map(
            lambda s: np.asarray(s) / self.count, self._sum)


def epoch_log_line(prefix: str, epoch: int, num_samples: int,
                   elapsed_s: float, metrics: Dict[str, Any]) -> str:
    """The reference's one-line epoch summary (main.py:638-643):
    prefix, epoch, samples, seconds, loss, top1/top5."""
    def get(k):
        v = metrics.get(k)
        return float(np.asarray(v)) if v is not None else float("nan")
    return (f"{prefix}[Epoch {epoch}][{num_samples} samples]"
            f"[{elapsed_s:.2f} sec]: loss: {get('loss_mean'):.4f}\t"
            f"byol: {get('byol_loss_mean'):.4f}\t"
            f"linear: {get('linear_loss_mean'):.4f}\t"
            f"top1: {get('top1_mean'):.4f}\ttop5: {get('top5_mean'):.4f}")


class StepTimer:
    """images/sec/chip over a sliding window; host-side, no device syncs
    (call .tick() after the async dispatch returns, and read .rate() only
    at epoch boundaries where metrics force a block anyway)."""

    def __init__(self, global_batch: int, n_chips: int, window: int = 50):
        self.global_batch = global_batch
        self.n_chips = max(n_chips, 1)
        self.window = window
        self._times = []

    def tick(self) -> None:
        self._times.append(time.perf_counter())
        if len(self._times) > self.window + 1:
            self._times.pop(0)

    def reset_window(self) -> None:
        """Call at epoch start so inter-epoch work (eval, checkpoint, TB
        flush) never lands inside a tick interval."""
        self._times = []

    def images_per_sec_per_chip(self) -> float:
        if len(self._times) < 2:
            return 0.0
        dt = self._times[-1] - self._times[0]
        steps = len(self._times) - 1
        return self.global_batch * steps / dt / self.n_chips
