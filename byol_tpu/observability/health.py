"""In-graph training-health diagnostics — the packed telemetry vector.

BYOL's signature failure mode is SILENT: the loss keeps falling while the
target network degenerates (representation collapse), trust ratios explode,
or the EMA target drifts — and the five epoch-mean scalars the trainer
reports would show nothing.  This module computes the per-step health
signals INSIDE the jitted train step (training/steps.py, gated by
``StepConfig.telemetry``) and packs them into one small float32 vector, so
observing a run costs a handful of reductions fused into the step and adds
ZERO host syncs — the readback is deferred and asynchronous
(observability/telemetry.py reads the vector back with >= interval-step
lag).

The packed layout is a versioned contract: ``HEALTH_FIELDS`` names every
slot, ``pack``/``unpack`` are the only writers/readers, and the JSONL run
log (observability/events.py) records the unpacked dict per sampled step.

Signals (one float32 each, ``len(HEALTH_FIELDS)`` total):

- ``grad_norm`` / ``update_norm`` / ``param_norm``: global l2 norms of the
  accumulated gradient, the post-LARS optimizer update, and the post-step
  online params — exploding/vanishing updates and parameter blowup.
- ``ema_drift`` / ``ema_drift_rel``: global l2 distance between the online
  and EMA target trees (and relative to ``param_norm``) — a target that
  stops tracking (tau pinned ~1 by a bad EMA-scaling config) or never
  lags (tau ~0) is visible immediately.
- ``trust_min`` / ``trust_median`` / ``trust_max``: LARS trust-ratio
  spread over the adapted layer groups (optim/lars.py
  ``trust_ratio_vector`` — the same per-leaf ratio the optimizer applies),
  the large-batch early-warning signal (LARS exists because per-layer
  |p|/|g| diverges at scale; a runaway max is how that failure starts).
- ``collapse_feature_std`` / ``collapse_cosine_mean``: the BYOL collapse
  signature on the STOP-GRAD target projections — mean per-feature std
  over the batch (collapse -> 0) and mean pairwise cosine similarity
  (collapse -> 1).
- ``nonfinite_count``: number of non-finite values in the gradient tree +
  the loss — the per-step in-graph replacement for blanket
  ``jax_debug_nans`` (which syncs every op); the host-side
  ``--nan-policy {warn,halt}`` keys off this slot.
- ``loss``: the step loss, so a sampled telemetry record is
  self-contained.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# The packed-vector layout contract.  Append-only: downstream readers
# (telemetry sink, run-log consumers) index by name via pack/unpack, and
# events.py stamps every record with the schema version.
HEALTH_FIELDS: Tuple[str, ...] = (
    "grad_norm",
    "update_norm",
    "param_norm",
    "ema_drift",
    "ema_drift_rel",
    "trust_min",
    "trust_median",
    "trust_max",
    "collapse_feature_std",
    "collapse_cosine_mean",
    "nonfinite_count",
    "loss",
)

_EPS = 1e-12


def global_norm(tree: Any) -> jnp.ndarray:
    """Global l2 norm over every leaf of a pytree (fp32 accumulation)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    total = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return jnp.sqrt(total)


def nonfinite_count(tree: Any) -> jnp.ndarray:
    """Number of non-finite (NaN/inf) scalars across a pytree, as fp32."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(~jnp.isfinite(l)).astype(jnp.float32)
               for l in leaves)


def collapse_stats(proj: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The BYOL collapse signature of a (B, D) projection batch.

    Returns ``(feature_std, cosine_mean)``:

    - ``feature_std``: mean over features of the per-feature std over the
      batch.  A collapsed representation (every input mapped to the same
      vector) drives this to 0.
    - ``cosine_mean``: mean pairwise cosine similarity between the B
      row-normalized projections, computed in closed form from the norm of
      the summed unit rows — O(B*D), no BxB similarity matrix:
      ``(||sum_i u_i||^2 - B) / (B * (B - 1))``.  Collapse drives it to 1.

    Computed on the STOP-GRAD target projections in the train step, so the
    diagnostic can never leak into the gradient.
    """
    p = proj.astype(jnp.float32)
    feature_std = jnp.mean(jnp.std(p, axis=0))
    b = p.shape[0]
    if b < 2:
        return feature_std, jnp.ones((), jnp.float32)
    u = p / (jnp.linalg.norm(p, axis=1, keepdims=True) + _EPS)
    s = jnp.sum(u, axis=0)
    cosine_mean = (jnp.sum(jnp.square(s)) - b) / (b * (b - 1))
    return feature_std, cosine_mean


def pack(values: Dict[str, Any]) -> jnp.ndarray:
    """Pack the named signals into the (len(HEALTH_FIELDS),) fp32 vector."""
    missing = set(HEALTH_FIELDS) - set(values)
    extra = set(values) - set(HEALTH_FIELDS)
    if missing or extra:
        raise ValueError(
            f"health vector fields mismatch: missing={sorted(missing)} "
            f"extra={sorted(extra)}")
    return jnp.stack([jnp.asarray(values[k], jnp.float32).reshape(())
                      for k in HEALTH_FIELDS])


def unpack(vec: Any) -> Dict[str, float]:
    """Host-side inverse of :func:`pack`: vector -> {field: python float}."""
    arr = np.asarray(vec, np.float64).reshape(-1)
    if arr.shape[0] != len(HEALTH_FIELDS):
        raise ValueError(
            f"health vector has {arr.shape[0]} slots; schema expects "
            f"{len(HEALTH_FIELDS)} ({HEALTH_FIELDS})")
    return {k: float(arr[i]) for i, k in enumerate(HEALTH_FIELDS)}


def health_stats(*, grads: Any, updates: Any, params: Any,
                 target_params: Any, loss: jnp.ndarray,
                 collapse: Tuple[jnp.ndarray, jnp.ndarray],
                 trust_ratios: jnp.ndarray) -> jnp.ndarray:
    """Assemble the packed health vector from one optimizer step's tensors.

    All inputs are traced values inside the jitted step; the result is a
    fresh (len(HEALTH_FIELDS),) fp32 array — a step OUTPUT, never an alias
    of the donated state (graphlint GL104 corpus pins the call pattern).

    ``collapse`` is ``collapse_stats(...)`` of the stop-grad target
    projections (computed per microbatch next to the forward, then
    mean-accumulated — recomputing it here would need the projections kept
    live across the accumulation scan, defeating the scan's memory win).
    ``trust_ratios`` is ``optim.lars.trust_ratio_vector(grads, params_pre)``
    — the per-layer-group ratios the LARS transform applies.
    """
    param_norm = global_norm(params)
    drift = global_norm(jax.tree_util.tree_map(
        lambda p, t: p.astype(jnp.float32) - t.astype(jnp.float32),
        params, target_params))
    feature_std, cosine_mean = collapse
    tr = trust_ratios.astype(jnp.float32)
    return pack({
        "grad_norm": global_norm(grads),
        "update_norm": global_norm(updates),
        "param_norm": param_norm,
        "ema_drift": drift,
        "ema_drift_rel": drift / (param_norm + _EPS),
        "trust_min": jnp.min(tr),
        "trust_median": jnp.median(tr),
        "trust_max": jnp.max(tr),
        "collapse_feature_std": feature_std,
        "collapse_cosine_mean": cosine_mean,
        "nonfinite_count": nonfinite_count((grads, loss)),
        "loss": loss,
    })
