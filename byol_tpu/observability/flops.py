"""Analytic FLOPs + MFU accounting (SURVEY.md §5.1 — absent in the
reference, whose only perf signal was a coarse per-epoch wall clock,
main.py:572,638-643).

Two sources, one convention (multiply-add = 2 FLOPs, matching the quoted
chip peaks):

- :func:`cost_analysis_flops` — XLA's HLO-level cost analysis of the
  actual jitted train step (``jit(f).lower(args).cost_analysis()``), which
  needs no hand table, covers every arch in the registry, and reflects the
  program that really runs (fused views, remat recompute is NOT counted by
  HLO analysis — it analyzes the unoptimized HLO — so remat configs report
  the logical model FLOPs, which is the MFU convention anyway);
- the hand table in ``bench.py`` (``_GMACS``) for the two headline archs,
  kept as the transparent, judge-checkable primary for benchmark artifacts.

``tests/test_observability.py`` pins the two sources against each other so
neither can silently drift.
"""
from __future__ import annotations

from typing import Optional

import jax

# bf16 peak TFLOP/s per chip, keyed by substring of device_kind.
PEAK_BF16_TFLOPS = (
    ("v5 lite", 197.0),   # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v6", 918.0),        # Trillium
    ("v4", 275.0),
    ("v3", 123.0),
)


def chip_peak_tflops(device_kind: Optional[str] = None) -> Optional[float]:
    if device_kind is None:
        device_kind = jax.devices()[0].device_kind
    kind = device_kind.lower()
    for key, peak in PEAK_BF16_TFLOPS:
        if key in kind:
            return peak
    return None


def cost_analysis_flops(jitted_fn, *args) -> Optional[float]:
    """Total FLOPs of one call of ``jitted_fn(*args)`` per XLA's HLO cost
    analysis, or None when the backend/version doesn't support it.

    Accepts the raw ``jax.jit`` object or a wrapper carrying
    ``__wrapped__`` (the trainer's mesh-scoping wrapper).  Lowering traces
    the function once (seconds) but does NOT compile or execute it, and
    donation annotations on the jit have no effect at lowering time.
    """
    # NB a raw jax.jit object ALSO carries __wrapped__ (the un-jitted
    # Python function, which has no .lower) — only unwrap when the object
    # itself cannot lower.
    fn = (jitted_fn if hasattr(jitted_fn, "lower")
          else getattr(jitted_fn, "__wrapped__", jitted_fn))
    try:
        analysis = fn.lower(*args).cost_analysis()
        if isinstance(analysis, (list, tuple)):
            # Some jax versions return one dict per device here.  Whether
            # those entries hold per-device or global FLOPs is
            # version-dependent, and guessing wrong silently skews MFU by
            # n_devices — disarm instead (mfu() treats None as unknown).
            # The pinned version returns a plain dict with GLOBAL flops
            # (tests/test_observability.py pins that accounting).
            return None
        flops = float(analysis["flops"])
        return flops if flops > 0 else None
    except Exception:
        return None


def mfu(images_per_sec_per_chip: float, flops_per_sample: Optional[float],
        peak_tflops: Optional[float]) -> Optional[float]:
    """Model FLOPs utilization of one chip; None when either term is
    unknown (CPU runs, unsupported cost analysis)."""
    if not flops_per_sample or not peak_tflops or \
            images_per_sec_per_chip <= 0:
        return None
    return images_per_sec_per_chip * flops_per_sample / (peak_tflops * 1e12)
