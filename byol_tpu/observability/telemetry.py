"""Asynchronous telemetry sink: lagged health readback + anomaly rules.

The train step computes the packed health vector IN-GRAPH
(observability/health.py); this module is the host side that reads it back
WITHOUT ever synchronizing the dispatch loop:

- ``offer(step, vec)`` enqueues the device vector every
  ``interval``-th optimizer step and reads back only entries OLDER than
  the newest one — so by the time a vector is materialized on the host, at
  least ``interval`` further steps have been dispatched and the readback
  finds a value that is (almost surely) already computed.  The hot loop
  never blocks on the current step; worst case it briefly joins an
  interval-old value.  The transfer is an EXPLICIT ``jax.device_get``, so
  the sink runs clean under ``jax.transfer_guard("disallow")`` (the
  ``guard_steps`` test fixture) — implicit-sync hygiene is preserved.
- ``hold(step, vec)``/``drain()`` support ``--telemetry epoch``: the
  trainer holds the latest vector (rebinding a tuple, no readback) and
  drains once at the epoch boundary — AFTER the epoch metric readback has
  already synchronized, so the epoch record is free.

Anomaly rules run over a ring buffer of processed records:

- ``nonfinite``: ``nonfinite_count > 0`` in the gradients/loss.  Under
  ``nan_policy='halt'`` the sink emits the anomaly + a halt event and
  raises :class:`NanHaltError` (the trainer adds a state-dump event) —
  the per-step, zero-sync replacement for blanket ``jax_debug_nans``.
- ``collapse``: the BYOL collapse signature — target-projection
  per-feature std below ``collapse_feature_std`` OR mean pairwise cosine
  above ``collapse_cosine`` — the failure the loss curve hides.
- ``step_time_spike``: seconds/optimizer-step (from the enqueue
  timestamps, i.e. dispatch-to-dispatch time) above ``step_time_spike``x
  the median of the ring — a wedging input pipeline or a slowing chip.
"""
from __future__ import annotations

import sys
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax
import numpy as np

from byol_tpu.observability import health as health_lib
from byol_tpu.observability.events import RunLog

NAN_POLICIES = ("warn", "halt")


class NanHaltError(RuntimeError):
    """A non-finite gradient/loss surfaced under ``--nan-policy halt``."""

    def __init__(self, step: int, record: Dict[str, float]):
        self.step = step
        self.record = record
        super().__init__(
            f"non-finite values in gradients/loss at optimizer step {step} "
            f"(nonfinite_count={record.get('nonfinite_count')}, "
            f"loss={record.get('loss')}); halting per --nan-policy halt")


class TelemetrySink:
    """Lagged readback + anomaly detection over the in-graph health vector.

    ``events`` (observability.events.RunLog, optional): every processed
    sample is emitted as a ``step`` event and every tripped rule as an
    ``anomaly`` event.  ``records`` is the ring buffer of processed
    samples (dicts keyed by HEALTH_FIELDS + ``step``/``sec_per_step``);
    ``anomalies`` accumulates every anomaly for the run.
    """

    def __init__(self, interval: int = 50, *, nan_policy: str = "warn",
                 events: Optional[RunLog] = None, ring: int = 128,
                 collapse_feature_std: float = 1e-3,
                 collapse_cosine: float = 0.995,
                 step_time_spike: float = 3.0,
                 verbose: bool = True) -> None:
        if interval < 1:
            raise ValueError(f"telemetry interval must be >= 1: {interval}")
        if nan_policy not in NAN_POLICIES:
            raise ValueError(
                f"unknown nan_policy {nan_policy!r}; one of {NAN_POLICIES}")
        self.interval = interval
        self.nan_policy = nan_policy
        self.events = events
        self.collapse_feature_std = collapse_feature_std
        self.collapse_cosine = collapse_cosine
        self.step_time_spike = step_time_spike
        self.verbose = verbose
        self.records: Deque[Dict[str, float]] = deque(maxlen=ring)
        self.anomalies: List[Dict[str, Any]] = []
        # (step, device vector, dispatch wall-time) awaiting readback
        self._pending: Deque[Tuple[int, Any, float]] = deque()
        self._held: Optional[Tuple[int, Any, float]] = None

    # ---- hot-loop side ----------------------------------------------------
    def offer(self, step: int, vec: Any,
              wall: Optional[float] = None) -> List[Dict[str, Any]]:
        """'step' mode: sample every ``interval``-th step; process only
        samples at least one interval old (the async-lag contract).
        Returns the anomalies found in the samples processed THIS call.
        ``wall`` overrides the dispatch timestamp (tests)."""
        if step % self.interval:
            return []
        self._pending.append(
            (step, vec, time.perf_counter() if wall is None else wall))
        out: List[Dict[str, Any]] = []
        while len(self._pending) > 1:
            out.extend(self._process(*self._pending.popleft()))
        return out

    def hold(self, step: int, vec: Any,
             wall: Optional[float] = None) -> None:
        """'epoch' mode: remember the newest vector without reading it;
        :meth:`drain` at the epoch boundary turns it into one record."""
        self._held = (step, vec,
                      time.perf_counter() if wall is None else wall)

    def drain(self) -> List[Dict[str, Any]]:
        """Process everything outstanding (epoch boundary / shutdown).
        Called after a synchronizing readback, so the device_gets here are
        free; anomalies found are returned (and halt still raises)."""
        out: List[Dict[str, Any]] = []
        while self._pending:
            out.extend(self._process(*self._pending.popleft()))
        if self._held is not None:
            held, self._held = self._held, None
            out.extend(self._process(*held))
        # drain marks an epoch boundary: the wall-clock gap to the next
        # epoch's first sample spans eval/valid/checkpoint, not training —
        # invalidate the timebase so that sample carries no sec_per_step
        # (a spurious step_time_spike every epoch would poison the one
        # anomaly feed this feature exists to keep trustworthy)
        if self.records:
            self.records[-1].pop("_wall", None)
        return out

    # ---- readback + rules -------------------------------------------------
    def _process(self, step: int, vec: Any,
                 wall: float) -> List[Dict[str, Any]]:
        # EXPLICIT transfer: legitimate under transfer_guard("disallow").
        arr = np.asarray(jax.device_get(vec), np.float32)
        rec: Dict[str, float] = {"step": float(step),
                                 **health_lib.unpack(arr)}
        prev = self.records[-1] if self.records else None
        if prev is not None and "_wall" in prev and step > prev["step"]:
            rec["sec_per_step"] = ((wall - prev["_wall"])
                                   / (step - prev["step"]))
        rec["_wall"] = wall
        anomalies = self._rules(step, rec)
        self.records.append(rec)
        public = {k: v for k, v in rec.items() if not k.startswith("_")}
        if self.events is not None:
            self.events.emit("step", step=step, health=public,
                             anomalies=[a["rule"] for a in anomalies])
            for a in anomalies:
                self.events.emit("anomaly", **a)
        self.anomalies.extend(anomalies)
        if self.verbose:
            for a in anomalies:
                print(f"telemetry: ANOMALY {a['rule']} at step {step}: "
                      f"{a['detail']}", file=sys.stderr)
        if rec["nonfinite_count"] > 0 and self.nan_policy == "halt":
            if self.events is not None:
                self.events.emit("halt", step=step, reason="nonfinite",
                                 health=public)
            raise NanHaltError(step, public)
        return anomalies

    def _rules(self, step: int,
               rec: Dict[str, float]) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []

        def anomaly(rule: str, detail: str) -> None:
            out.append({"step": step, "rule": rule, "detail": detail,
                        "health": {k: v for k, v in rec.items()
                                   if not k.startswith("_")}})

        if rec["nonfinite_count"] > 0:
            anomaly("nonfinite",
                    f"{rec['nonfinite_count']:.0f} non-finite value(s) in "
                    f"gradients/loss (loss={rec['loss']})")
        if (rec["collapse_feature_std"] < self.collapse_feature_std
                or rec["collapse_cosine_mean"] > self.collapse_cosine):
            anomaly("collapse",
                    "target projections collapsing: feature_std="
                    f"{rec['collapse_feature_std']:.3e} (< "
                    f"{self.collapse_feature_std}) or cosine_mean="
                    f"{rec['collapse_cosine_mean']:.4f} (> "
                    f"{self.collapse_cosine})")
        sec = rec.get("sec_per_step")
        history = [r["sec_per_step"] for r in self.records
                   if "sec_per_step" in r]
        if sec is not None and len(history) >= 5:
            med = float(np.median(history))
            if med > 0 and sec > self.step_time_spike * med:
                anomaly("step_time_spike",
                        f"{sec:.3f}s/step vs ring median {med:.3f}s "
                        f"(x{sec / med:.1f} > x{self.step_time_spike})")
        return out
