"""Span-based flight recorder: attribute every second of a run.

The run log (events.py) says WHAT happened; this module records WHERE the
time went.  A :class:`SpanRecorder` collects host-side begin/end spans —
monotonic clock (``time.perf_counter``), nestable, per-thread depth
tracking, bounded ring buffer — cheap enough to wrap every hot-loop phase
(input wait, train dispatch, epoch readback, eval, checkpoint, telemetry
readback, startup/compile) without moving the throughput needle (the
``bench.py --spans-ab`` budget is < 2%, same bar as telemetry).

Every span also opens the matching :func:`profiling.annotate` region
(``jax.profiler.TraceAnnotation``), so when an XLA trace is being captured
the host spans line up with device ops on the same timeline — the flight
recorder and the profiler tell one story.

Two consumers fold the ring:

- :mod:`byol_tpu.observability.goodput` partitions wall time into
  productive step time vs named badput buckets per epoch and per run;
- :func:`export_chrome_trace` writes a Chrome-trace-event JSON file
  (load it in ``chrome://tracing`` or https://ui.perfetto.dev) so a run's
  timeline is inspectable with zero custom tooling.

Spans-off contract: :data:`NULL` (a :class:`NullRecorder`) is a shared
no-op whose ``span()`` returns one reusable context manager — no clock
read, no allocation, no ring append — so ``--spans off`` leaves the hot
loop untouched (``tests/test_spans.py`` pins it).

Host-side ONLY: a span inside jit-traced code would run ONCE at trace
time and be constant-folded into the executable — it would measure
nothing.  graphlint GL101 flags host clocks and span entry points inside
traced scopes (``tests/graphlint_fixtures/bad_span_clock.py``).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from byol_tpu.observability import profiling

# default ring capacity: ~3 spans/step x 20k steps; beyond it the OLDEST
# spans are evicted (``dropped`` counts them) — the recorder must never
# grow without bound on a week-long run
_CAPACITY = 1 << 16


class Span:
    """One closed span: ``[t0, t1]`` on the perf_counter clock."""

    __slots__ = ("name", "t0", "t1", "tid", "depth", "seq", "attrs")

    def __init__(self, name: str, t0: float, t1: float, tid: int,
                 depth: int, seq: int, attrs: Optional[Dict[str, Any]]):
        self.name = name
        self.t0 = t0
        self.t1 = t1
        self.tid = tid
        self.depth = depth
        self.seq = seq
        self.attrs = attrs

    @property
    def seconds(self) -> float:
        return self.t1 - self.t0

    def __repr__(self) -> str:  # debugging/test-failure readability
        return (f"Span({self.name!r}, {self.seconds * 1e3:.3f}ms, "
                f"depth={self.depth}, seq={self.seq})")


class _ActiveSpan:
    """The context manager one ``span()`` call returns.  Closing appends
    the record; the span is also a ``profiling.annotate`` region so host
    phases show up in captured XLA traces."""

    __slots__ = ("_rec", "_name", "_attrs", "_t0", "_depth", "_ann")

    def __init__(self, rec: "SpanRecorder", name: str,
                 attrs: Optional[Dict[str, Any]]):
        self._rec = rec
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_ActiveSpan":
        local = self._rec._local
        self._depth = getattr(local, "depth", 0)
        local.depth = self._depth + 1
        self._ann = profiling.annotate(self._name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        self._ann.__exit__(exc_type, exc, tb)
        self._rec._local.depth = self._depth
        self._rec._append(Span(self._name, self._t0, t1,
                               threading.get_ident(), self._depth,
                               next(self._rec._seq), self._attrs))
        return False


class SpanRecorder:
    """Bounded, thread-safe-enough flight recorder.

    ``span(name, **attrs)`` returns a context manager; nesting tracks a
    per-thread depth so aggregators can attribute only TOP-LEVEL spans
    (nested spans would double-count their parents' wall time).  Appends
    are a deque push under the GIL; the only lock-worthy state (the seq
    counter) is an ``itertools.count``, which is atomic in CPython.
    """

    enabled = True

    def __init__(self, capacity: int = _CAPACITY) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._ring: "deque[Span]" = deque(maxlen=capacity)
        self._seq = itertools.count()
        self._total = 0
        self._local = threading.local()

    def span(self, name: str, **attrs: Any) -> _ActiveSpan:
        return _ActiveSpan(self, name, attrs or None)

    def _append(self, rec: Span) -> None:
        self._ring.append(rec)
        self._total += 1

    # ---- readout ----------------------------------------------------------
    @property
    def dropped(self) -> int:
        """Spans evicted by the ring bound (recorded minus retained)."""
        return max(0, self._total - len(self._ring))

    def records(self, since_seq: int = -1) -> List[Span]:
        """Snapshot of retained spans with ``seq > since_seq``, oldest
        first.  ``list(deque)`` is atomic under the GIL, so a snapshot
        taken while other threads append is consistent (it may simply
        miss spans that close after the copy)."""
        snap = list(self._ring)
        if since_seq < 0:
            return snap
        return [r for r in snap if r.seq > since_seq]

    def last_seq(self) -> int:
        snap = list(self._ring)
        return snap[-1].seq if snap else -1

    def clear(self) -> None:
        self._ring.clear()
        self._total = 0


class _NullSpan:
    """Shared no-op context manager — the whole spans-off hot path."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class NullRecorder:
    """Spans-off: ``span()`` hands back one shared no-op context manager —
    no clock read, no allocation, no ring append, no annotate region."""

    enabled = False
    capacity = 0
    dropped = 0

    def span(self, name: str, **attrs: Any) -> _NullSpan:
        return _NULL_SPAN

    def records(self, since_seq: int = -1) -> List[Span]:
        return []

    def last_seq(self) -> int:
        return -1

    def clear(self) -> None:
        pass


NULL = NullRecorder()

# Module-level default recorder: convenience for scripts/fixtures that
# want ``spans.span("...")`` without threading a recorder through every
# call.  Defaults to NULL (recording is an explicit opt-in); the trainer
# and the serving stack construct and pass their OWN recorders.
_default: Any = NULL


def set_default(recorder: Any) -> None:
    global _default
    _default = recorder


def get_default() -> Any:
    return _default


def span(name: str, **attrs: Any):
    """Record on the module default recorder (host-side code only — under
    a jit trace this runs once and measures nothing; graphlint GL101)."""
    return _default.span(name, **attrs)


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

def _json_safe(value: Any) -> Any:
    if isinstance(value, float):
        # strict-JSON discipline (GL110): a non-finite span attr must
        # not become a bare NaN token chrome://tracing refuses to load —
        # events.sanitize owns the float -> string mapping
        from byol_tpu.observability.events import sanitize
        return sanitize(value)
    if isinstance(value, (str, int, bool)) or value is None:
        return value
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    return str(value)


def export_chrome_trace(records: Iterable[Span], path: str, *,
                        process_name: str = "byol_tpu") -> int:
    """Write spans as Chrome trace events (the ``traceEvents`` JSON array
    format); returns the event count.  Timestamps are perf_counter-based
    microseconds — relative, which both ``chrome://tracing`` and Perfetto
    render fine.  One complete-event (``ph: "X"``) per span; a metadata
    event names the process so multi-file sessions stay legible."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    for r in sorted(records, key=lambda r: r.t0):
        ev: Dict[str, Any] = {
            "name": r.name,
            "cat": r.name.split("/", 1)[0],
            "ph": "X",
            "ts": r.t0 * 1e6,
            "dur": (r.t1 - r.t0) * 1e6,
            "pid": pid,
            "tid": r.tid,
        }
        if r.attrs:
            ev["args"] = _json_safe(r.attrs)
        events.append(ev)
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        # ts/dur come from perf_counter deltas (always finite) and attrs
        # pass through _json_safe — strict dump so nothing lenient slips
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f,
                  allow_nan=False)
        f.write("\n")
    return len(events) - 1
