"""Append-only, schema-versioned JSONL run log.

Every training run (trainer.fit) and every bench row (bench.py) emits the
SAME machine-readable event stream, so tooling that reads one run log reads
them all: a run-header event with the full config and environment, interval
step records carrying the unpacked health vector, epoch records folding in
the MetricAccumulator and InputPipelineMeter results, anomaly / checkpoint
/ halt events, and a run-end marker.

Format: one JSON object per line (newline-delimited), STRICT JSON: the
events most worth machine-reading are the failure records, and those are
exactly the ones carrying non-finite floats (a NaN loss in an anomaly
snapshot) — Python's lenient writer would emit bare ``NaN`` tokens that
jq/JS/serde reject.  :func:`_sanitize` maps non-finite floats to the
strings ``"NaN"`` / ``"Infinity"`` / ``"-Infinity"`` at emit time (the
envelope is dumped with ``allow_nan=False`` so nothing lenient can slip
through).  Line-buffered, append-only writes — a crash mid-run loses at
most the line being written, and every complete line is a complete event
(no trailing state, no footer to rewrite).  Each line stamps
``"v": SCHEMA_VERSION``; readers validate per-kind required fields via
:func:`validate_event`, and :func:`read_events` is the strict reader the
tests round-trip through.

This is the machine-facing complement of the Grapher's metrics.jsonl (a
flat scalar stream for plots): the run log carries STRUCTURED events — a
collapse anomaly is a typed record with the rule and the offending health
snapshot, not a scalar to eyeball.
"""
from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, Iterator, Optional

import numpy as np

SCHEMA_VERSION = 1

# kind -> required payload fields (beyond the envelope v/kind/t).
# Append-only like HEALTH_FIELDS: adding a kind or an OPTIONAL field is
# compatible; changing required fields bumps SCHEMA_VERSION.
EVENT_KINDS: Dict[str, tuple] = {
    "run_header": ("config", "jax_version", "backend"),
    "step": ("step", "health"),
    "epoch": ("epoch", "split", "metrics"),
    "anomaly": ("step", "rule"),
    "checkpoint": ("epoch",),
    "halt": ("step", "reason"),
    "state_dump": ("step",),
    "bench_row": ("config",),
    # serving/meter.py window snapshot: request count, coalesced-batch
    # count, and the latency tail — the serving analog of "step"/"epoch".
    # Additive kind (no SCHEMA_VERSION bump); optional payload carries
    # fill ratio, queue depth, the engine compile counter, and the
    # per-request lifecycle phase breakdown (``phase_ms``).
    "serve_stats": ("requests", "batches", "p50_ms", "p99_ms"),
    # observability/goodput.py wall-time partition (additive kinds):
    # one ``goodput`` event per epoch window + one run-scope total;
    # ``span_stats`` carries the window's per-span-name aggregates
    # (count / total seconds / p50 / p99 / max).  The partition identity
    # — productive + sum(badput) == wall — is validated below.
    "goodput": ("scope", "wall_seconds", "productive_seconds", "badput"),
    "span_stats": ("scope", "spans"),
    "run_end": (),
}

# run_header.sharding_plan (CompilePlan.describe()): OPTIONAL — bench
# headers have no mesh — but when present it must carry the full plan
# provenance, or a run log could claim a plan it cannot name.  Optional-
# field shape checks are additive (no SCHEMA_VERSION bump).
SHARDING_PLAN_FIELDS = ("mesh_shape", "axis_names", "zero1",
                        "donate_argnums")


def sanitize(obj: Any) -> Any:
    """JSON-strict deep copy of a payload: non-finite floats become the
    strings ``"NaN"`` / ``"Infinity"`` / ``"-Infinity"``.  Run on every
    event before ``json.dumps(..., allow_nan=False)`` so the lines a NaN
    run produces — the ones this log exists to capture — stay parseable
    by every standard JSON consumer, not just Python's lenient reader.

    This module OWNS the convention (GL110): every other strict-JSON
    writer — grapher metrics lines, span chrome-trace attrs, checkpoint
    meta.json, the wire /statsz endpoint — delegates here rather than
    growing a drift-prone copy of the mapping."""
    if isinstance(obj, dict):
        return {k: sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [sanitize(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return sanitize(obj.tolist())
    if isinstance(obj, (float, np.floating)):
        f = float(obj)
        if math.isnan(f):
            return "NaN"
        if math.isinf(f):
            return "Infinity" if f > 0 else "-Infinity"
        return f
    return obj


# internal call sites predate the public promotion
_sanitize = sanitize


def _json_default(obj: Any):
    """Serialize numpy/jax leaves that reach an event payload."""
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return _sanitize(obj)
    if isinstance(obj, np.ndarray):
        return _sanitize(obj)
    tolist = getattr(obj, "tolist", None)   # jax.Array and friends
    if callable(tolist):
        return _sanitize(tolist())
    raise TypeError(
        f"event payload value of type {type(obj).__name__} is not "
        "JSON-serializable")


def validate_event(event: Any) -> Dict[str, Any]:
    """Validate one event object against the schema; returns it.

    Raises ``ValueError`` on: non-dict, missing/mismatched schema version,
    unknown kind, or a missing required field for the kind.
    """
    if not isinstance(event, dict):
        raise ValueError(f"event must be a JSON object, got {type(event)}")
    v = event.get("v")
    if v != SCHEMA_VERSION:
        raise ValueError(
            f"event schema version {v!r} != supported {SCHEMA_VERSION}")
    kind = event.get("kind")
    if kind not in EVENT_KINDS:
        raise ValueError(
            f"unknown event kind {kind!r}; known: {sorted(EVENT_KINDS)}")
    missing = [f for f in EVENT_KINDS[kind] if f not in event]
    if missing:
        raise ValueError(
            f"event kind {kind!r} missing required field(s) {missing}")
    if kind == "run_header" and "sharding_plan" in event:
        sp = event["sharding_plan"]
        if not isinstance(sp, dict):
            raise ValueError(
                f"run_header.sharding_plan must be an object, got "
                f"{type(sp).__name__}")
        sp_missing = [f for f in SHARDING_PLAN_FIELDS if f not in sp]
        if sp_missing:
            raise ValueError(
                f"run_header.sharding_plan missing field(s) {sp_missing} "
                f"(expected {list(SHARDING_PLAN_FIELDS)})")
        if sp.get("zero1") not in ("off", "on"):
            raise ValueError(
                f"run_header.sharding_plan.zero1 must be 'off'|'on', got "
                f"{sp.get('zero1')!r}")
    if kind == "goodput":
        bp = event["badput"]
        if not isinstance(bp, dict):
            raise ValueError(
                f"goodput.badput must be an object of bucket seconds, got "
                f"{type(bp).__name__}")
        vals = [event["wall_seconds"], event["productive_seconds"],
                *bp.values()]
        if all(isinstance(v, (int, float)) and not isinstance(v, bool)
               for v in vals):
            # the accounting identity the whole feature exists to provide:
            # the partition must SUM to wall time (1% tolerance covers the
            # reader-side float round-trip; the writer computes it exactly)
            wall = float(event["wall_seconds"])
            total = (float(event["productive_seconds"])
                     + sum(float(v) for v in bp.values()))
            if abs(total - wall) > max(0.01 * abs(wall), 1e-6):
                raise ValueError(
                    f"goodput buckets sum to {total:.6f}s but wall is "
                    f"{wall:.6f}s (off by more than 1%): the partition "
                    "must be exhaustive (goodput.py fold contract)")
    return event


class RunLog:
    """Line-buffered append-only JSONL event writer.

    ``emit(kind, **payload)`` stamps the envelope (schema version, kind,
    wall time), validates, and writes one line.  Line buffering means each
    event reaches the OS on its own newline — crash-safe without fsync
    latency in the hot loop.  Open in append mode so a resumed run extends
    its predecessor's log instead of erasing the evidence.

    ``best_effort=True`` makes environment failures (OSError: disk full,
    NFS quota, read-only fs) — at CONSTRUCTION (makedirs/open) and on
    every write alike — disable the log with a one-line warning instead
    of propagating, so both emitters (trainer.fit, bench.py) get the
    'observability must never kill the hours-long run it observes'
    contract from one place.  Schema violations (ValueError) always
    raise: those are caller bugs, not environment weather.
    """

    def __init__(self, path: str, *, best_effort: bool = False) -> None:
        self.path = path
        self.best_effort = best_effort
        self.disabled = False
        self._f = None
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f = open(path, "a", buffering=1)
        except OSError as e:
            if not best_effort:
                raise
            self._write_failed(e)

    def _write_failed(self, exc: OSError) -> None:
        import sys
        self.disabled = True
        print(f"events: {self.path} failed ({exc!r}); run log "
              "disabled for the rest of the run", file=sys.stderr)
        try:
            if self._f is not None:
                self._f.close()
        except OSError:
            pass

    def emit(self, kind: str, **payload: Any) -> Dict[str, Any]:
        event = {"v": SCHEMA_VERSION, "kind": kind, "t": time.time(),
                 **payload}
        validate_event(event)
        if self.disabled:
            return event
        try:
            self._f.write(json.dumps(_sanitize(event), default=_json_default,
                                     allow_nan=False) + "\n")
        except OSError as e:
            if not self.best_effort:
                raise
            self._write_failed(e)
        return event

    def flush(self) -> None:
        if not self.disabled:
            self._f.flush()

    def close(self) -> None:
        if not self.disabled and self._f is not None and not self._f.closed:
            self._f.close()

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_events(path: str) -> Iterator[Dict[str, Any]]:
    """Strict reader: yields every event, validated; raises ``ValueError``
    naming the line number on a corrupt or schema-invalid line."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: corrupt JSONL line: {e}") from e
            try:
                yield validate_event(obj)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: {e}") from e
