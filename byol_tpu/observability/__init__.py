from byol_tpu.observability.grapher import Grapher, make_grid
from byol_tpu.observability.meters import (InputPipelineMeter,
                                           MetricAccumulator, StepTimer,
                                           epoch_log_line, input_log_line)
from byol_tpu.observability import (events, flops, goodput, health,
                                    profiling, spans, telemetry)

__all__ = ["Grapher", "make_grid", "InputPipelineMeter", "MetricAccumulator",
           "StepTimer", "epoch_log_line", "input_log_line", "events",
           "flops", "goodput", "health", "profiling", "spans", "telemetry"]
