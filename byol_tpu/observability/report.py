"""``python -m byol_tpu report <run.jsonl>`` — offline run analysis.

Renders, from the schema-versioned event log ALONE (no live process, no
accelerator — the log is the whole input):

1. the **goodput waterfall**: wall time partitioned into productive step
   time vs the named badput buckets (run scope, then per epoch), with the
   partition identity re-checked (productive + sum(badput) == wall to 1%);
2. the **step-time trend**: per-epoch p50/p99 dispatch-interval quantiles
   (the optional epoch-event fields meters.StepTimer records);
3. the **serving latency breakdown**: aggregated ``serve_stats`` windows —
   latency tail plus the per-request lifecycle phase means (queue /
   stage / dispatch / readback / deliver) when the meter recorded them;
4. the **anomaly timeline**: every ``anomaly`` / ``halt`` event with its
   rule and offending step.

Exit status: 0 when the log parses and every goodput partition checks out;
1 when the log carries no goodput events (nothing to report — run with
``--spans on``, the default) or a partition fails the 1% identity; 2 on
usage / unreadable file.  Works on ``run.jsonl``, ``bench_events.jsonl``
and ``serve.jsonl`` alike — sections render only when their events exist.
"""
from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional, Tuple

_BAR_WIDTH = 40


def _fmt_s(seconds: Any) -> str:
    try:
        return f"{float(seconds):9.2f}s"
    except (TypeError, ValueError):
        return f"{seconds!r:>10}"


def _bar(fraction: float, width: int = _BAR_WIDTH) -> str:
    n = max(0, min(width, int(round(fraction * width))))
    return "#" * n


def _num(v: Any) -> Optional[float]:
    """Payload float — events.py maps non-finite floats to strings, which
    render but never aggregate."""
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def _check_partition(ev: Dict[str, Any]) -> Optional[float]:
    """Relative partition error of one goodput event (None: non-numeric)."""
    wall = _num(ev.get("wall_seconds"))
    productive = _num(ev.get("productive_seconds"))
    badput = ev.get("badput") or {}
    vals = [_num(v) for v in badput.values()]
    if wall is None or productive is None or any(v is None for v in vals):
        return None
    total = productive + sum(vals)
    return abs(total - wall) / max(abs(wall), 1e-9)


def _render_waterfall(out: List[str], ev: Dict[str, Any],
                      label: str) -> bool:
    """Append one waterfall block; returns False when the partition fails
    the 1% identity."""
    wall = _num(ev.get("wall_seconds")) or 0.0
    productive = _num(ev.get("productive_seconds")) or 0.0
    badput: Dict[str, Any] = ev.get("badput") or {}
    err = _check_partition(ev)
    ok = err is None or err <= 0.01
    frac = productive / wall if wall > 0 else 0.0
    out.append(f"-- {label}: wall {_fmt_s(wall).strip()}, "
               f"goodput {frac:6.1%}"
               + (f", mfu {ev['mfu']:.1%}" if _num(ev.get("mfu")) else "")
               + ("" if ok else
                  f"   !! partition off by {err:.1%} (> 1%)"))
    rows = [("productive", productive)]
    rows += sorted(((k, _num(v) or 0.0) for k, v in badput.items()),
                   key=lambda kv: -kv[1])
    for name, secs in rows:
        share = secs / wall if wall > 0 else 0.0
        if name != "productive" and secs == 0.0:
            continue
        out.append(f"   {name:<20} {_fmt_s(secs)} {share:7.1%}  "
                   f"{_bar(share)}")
    if _num(ev.get("spans_dropped")):
        out.append(f"   (flight recorder dropped "
                   f"{int(ev['spans_dropped'])} spans — host_other "
                   "over-reads by their total)")
    return ok


def render(events: List[Dict[str, Any]], *,
           source: str = "") -> Tuple[str, int]:
    """The full report text + exit status for a parsed event list."""
    out: List[str] = []
    rc = 0
    header = next((e for e in events if e["kind"] == "run_header"), None)
    if header is not None:
        out.append(f"run: {header.get('run_name', '(unnamed)')}  "
                   f"backend={header.get('backend')}  "
                   f"jax={header.get('jax_version')}")

    goodputs = [e for e in events if e["kind"] == "goodput"]
    out.append("")
    out.append("== Goodput waterfall ==")
    if not goodputs:
        out.append("   no goodput events in this log — the run recorded "
                   "no spans (re-run with --spans on, the default)")
        rc = 1
    else:
        run_ev = next((e for e in goodputs if e.get("scope") == "run"),
                      goodputs[-1])
        if not _render_waterfall(out, run_ev, "run total"):
            rc = 1
        epoch_evs = [e for e in goodputs if e.get("scope") == "epoch"]
        if epoch_evs:
            out.append("")
            out.append("   epoch   wall      goodput  worst badput bucket")
            for ev in epoch_evs:
                err = _check_partition(ev)
                broken = err is not None and err > 0.01
                if broken:
                    rc = 1
                wall = _num(ev.get("wall_seconds")) or 0.0
                prod = _num(ev.get("productive_seconds")) or 0.0
                badput = {k: _num(v) or 0.0
                          for k, v in (ev.get("badput") or {}).items()}
                worst = max(badput.items(), key=lambda kv: kv[1],
                            default=("-", 0.0))
                frac = prod / wall if wall > 0 else 0.0
                out.append(f"   {ev.get('epoch', '?'):>5}  "
                           f"{_fmt_s(wall)} {frac:8.1%}  "
                           f"{worst[0]} ({worst[1]:.2f}s)"
                           + (f"   !! partition off by {err:.1%} (> 1%)"
                              if broken else ""))

    epochs = [e for e in events if e["kind"] == "epoch"
              and e.get("split") == "train"]
    trend = [(e.get("epoch"), _num(e.get("step_time_p50_s")),
              _num(e.get("step_time_p99_s"))) for e in epochs]
    trend = [t for t in trend if t[1] is not None and t[2] is not None]
    if trend:
        out.append("")
        out.append("== Step-time trend (dispatch intervals) ==")
        out.append("   epoch    p50        p99        p99/p50")
        for ep, p50, p99 in trend:
            out.append(f"   {ep:>5}  {p50 * 1e3:8.2f}ms {p99 * 1e3:8.2f}ms"
                       f"  {p99 / max(p50, 1e-12):7.2f}x")

    serves = [e for e in events if e["kind"] == "serve_stats"]
    lat = [(e, _num(e.get("p50_ms")), _num(e.get("p99_ms")))
           for e in serves]
    lat = [t for t in lat if t[1] is not None and t[2] is not None]
    if lat:
        out.append("")
        out.append("== Serving latency breakdown ==")
        reqs = sum(_num(e.get("requests")) or 0.0 for e, _, _ in lat)
        out.append(f"   {len(lat)} window(s), {int(reqs)} request(s); "
                   f"p50 {min(p for _, p, _ in lat):.2f}-"
                   f"{max(p for _, p, _ in lat):.2f}ms, "
                   f"p99 {min(p for _, _, p in lat):.2f}-"
                   f"{max(p for _, _, p in lat):.2f}ms")
        # lifecycle phase means, request-weighted across windows
        phase_tot: Dict[str, float] = {}
        phase_w = 0.0
        for e, _, _ in lat:
            pm = e.get("phase_ms") or {}
            w = _num(e.get("requests")) or 0.0
            if not pm or w <= 0:
                continue
            phase_w += w
            for k, v in pm.items():
                fv = _num(v)
                if fv is not None:
                    phase_tot[k] = phase_tot.get(k, 0.0) + fv * w
        if phase_w > 0:
            total_ms = sum(phase_tot.values()) / phase_w
            for k, v in phase_tot.items():
                mean = v / phase_w
                share = mean / total_ms if total_ms > 0 else 0.0
                out.append(f"   {k:<20} {mean:8.2f}ms {share:7.1%}  "
                           f"{_bar(share)}")
        # wire-layer block (serving/net): HTTP status histogram +
        # request-weighted read/parse/wait/write means across windows
        status_tot: Dict[str, float] = {}
        wire_tot: Dict[str, float] = {}
        wire_w = 0.0
        for e in serves:
            wire = e.get("wire") or {}
            w = _num(wire.get("http_requests")) or 0.0
            if w <= 0:
                continue
            wire_w += w
            for k, v in (wire.get("status") or {}).items():
                fv = _num(v)
                if fv is not None:
                    status_tot[k] = status_tot.get(k, 0.0) + fv
            for k, v in (wire.get("phase_ms") or {}).items():
                fv = _num(v)
                if fv is not None:
                    wire_tot[k] = wire_tot.get(k, 0.0) + fv * w
        if wire_w > 0:
            hist = "  ".join(f"{k}:{int(v)}"
                             for k, v in sorted(status_tot.items()))
            out.append(f"   wire: {int(wire_w)} HTTP answer(s)  [{hist}]")
            total_ms = sum(wire_tot.values()) / wire_w
            for k, v in sorted(wire_tot.items()):
                mean = v / wire_w
                share = mean / total_ms if total_ms > 0 else 0.0
                out.append(f"   wire/{k:<15} {mean:8.2f}ms {share:7.1%}  "
                           f"{_bar(share)}")

    anomalies = [e for e in events if e["kind"] in ("anomaly", "halt")]
    out.append("")
    out.append("== Anomaly timeline ==")
    if not anomalies:
        out.append("   none")
    else:
        for e in anomalies:
            rule = e.get("rule", e.get("reason", "?"))
            out.append(f"   step {e.get('step', '?'):>8}  "
                       f"{e['kind']:<8} {rule}  "
                       f"{str(e.get('detail', ''))[:80]}")
    if source:
        out.insert(0, f"goodput report — {source}")
    return "\n".join(out) + "\n", rc


def _read_for_report(path: str) -> List[Dict[str, Any]]:
    """Strict read, EXCEPT that a goodput event failing only its partition
    identity is kept: the violated waterfall is exactly what this command
    exists to show (rc 1 with the '!! partition off' diagnostic), and the
    strict reader raising would misreport it as an unreadable file (rc 2).
    Anything else invalid — corrupt JSON, schema drift — still raises."""
    import json

    from byol_tpu.observability.events import (EVENT_KINDS, SCHEMA_VERSION,
                                               validate_event)
    events: List[Dict[str, Any]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: corrupt JSONL line: {e}") from e
            try:
                validate_event(obj)
            except ValueError as e:
                # structurally complete goodput event => the only possible
                # failure left is the partition identity: keep it for the
                # renderer's diagnostic instead of dying here
                if not (isinstance(obj, dict)
                        and obj.get("kind") == "goodput"
                        and obj.get("v") == SCHEMA_VERSION
                        and all(k in obj
                                for k in EVENT_KINDS["goodput"])
                        and isinstance(obj.get("badput"), dict)):
                    raise ValueError(f"{path}:{lineno}: {e}") from e
            events.append(obj)
    return events


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__, file=sys.stderr)
        return 2
    path = argv[0]
    try:
        events = _read_for_report(path)
    except (OSError, ValueError) as e:
        print(f"report: cannot read {path}: {e}", file=sys.stderr)
        return 2
    text, rc = render(events, source=path)
    print(text, end="")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
