"""Unified metric writer — the ``helpers.grapher.Grapher`` contract.

Reference surface (SURVEY.md §2.3, §5.5; call sites
/root/reference/main.py:452-460,521,542-544,657,779,783):

  Grapher('tensorboard', logdir=...)   # visdom variant: documented delta —
  .add_scalar(key, value, step)        # visdom is dropped, TB covers it
  .add_image(key, grid, step)          #  (README.md:95-98 offers both)
  .add_text(key, text, step)
  .save(); .close()

Plotting rules reproduced from ``register_plots``/``register_images``
(main.py:502-544): only keys matching ``*_mean``/``*_scalar`` are plotted as
scalars, only ``*_img``/``*_imgs`` as images (first <=64 samples, downscaled
to <=64 px), and only process 0 writes (rank-0 discipline, main.py:452).

Backends: ``tensorboard`` (torch SummaryWriter), ``jsonl`` (newline-JSON for
machines), ``both`` (TB + jsonl — the default: committed evidence stays
greppable), ``null``.  All writes are host-side and O(scalar count) —
nothing here touches device buffers except the explicit image grids.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, Optional

import numpy as np

_SCALAR_RE = re.compile(r".*(_mean|_scalar)$")
_IMAGE_RE = re.compile(r".*_imgs?$")


def _finite_or_str(value: float) -> Any:
    """Strict-JSON scalar (GL110): a diverged run's NaN loss must land in
    metrics.jsonl as the string ``"NaN"`` — parseable evidence — not as a
    bare token that breaks every strict reader downstream.  Delegates to
    the convention's owner, :func:`observability.events.sanitize`."""
    from byol_tpu.observability.events import sanitize
    return sanitize(float(value))


def is_scalar_key(key: str) -> bool:
    return bool(_SCALAR_RE.match(key))


def is_image_key(key: str) -> bool:
    return bool(_IMAGE_RE.match(key))


class Grapher:
    """Facade over one of the writer backends; no-op off process 0."""

    def __init__(self, backend: str = "tensorboard", *, logdir: str = "runs",
                 run_name: str = "byol", enabled: Optional[bool] = None):
        if enabled is None:
            import jax
            enabled = jax.process_index() == 0
        self.enabled = enabled
        self.backend = backend if enabled else "null"
        self.logdir = os.path.join(logdir, run_name)
        self._tb = None
        self._jsonl = None
        if self.backend in ("tensorboard", "both"):
            from torch.utils.tensorboard import SummaryWriter
            os.makedirs(self.logdir, exist_ok=True)
            self._tb = SummaryWriter(log_dir=self.logdir)
        if self.backend in ("jsonl", "both"):
            os.makedirs(self.logdir, exist_ok=True)
            self._jsonl = open(os.path.join(self.logdir, "metrics.jsonl"),
                               "a", buffering=1)
        if self.backend not in ("tensorboard", "jsonl", "both", "null"):
            raise ValueError(f"unknown grapher backend {self.backend!r}")

    # -- primitive writes --------------------------------------------------
    def add_scalar(self, key: str, value: float, step: int) -> None:
        if self._tb is not None:
            self._tb.add_scalar(key, float(value), step)
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(
                {"t": time.time(), "step": step,
                 key: _finite_or_str(value)}, allow_nan=False) + "\n")

    def add_image(self, key: str, grid: np.ndarray, step: int) -> None:
        """grid: (H, W, C) float [0,1]."""
        if self._tb is not None:
            self._tb.add_image(key, np.asarray(grid), step,
                               dataformats="HWC")

    def add_text(self, key: str, text: str, step: int = 0) -> None:
        if self._tb is not None:
            self._tb.add_text(key, text, step)
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(
                {"t": time.time(), "step": step, key: text},
                allow_nan=False) + "\n")

    def save(self) -> None:
        if self._tb is not None:
            self._tb.flush()
        if self._jsonl is not None:
            self._jsonl.flush()

    def close(self) -> None:
        self.save()
        if self._tb is not None:
            self._tb.close()
        if self._jsonl is not None:
            self._jsonl.close()

    # -- reference plotting rules (main.py:502-544) ------------------------
    def register_plots(self, metrics: Dict[str, Any], step: int,
                       prefix: str = "train") -> None:
        """Post every ``*_mean``/``*_scalar`` entry as ``<prefix>_<key>``."""
        for key, value in metrics.items():
            if is_scalar_key(key):
                self.add_scalar(f"{prefix}_{key}", float(np.asarray(value)),
                                step)

    def register_images(self, images: Dict[str, Any], step: int,
                        prefix: str = "train", max_samples: int = 64,
                        max_px: int = 64) -> None:
        """Post ``*_img(s)`` batches as grids: first <=64 samples downscaled
        to <=64 px (main.py:524-544,649-655)."""
        for key, batch in images.items():
            if not is_image_key(key):
                continue
            arr = np.asarray(batch)
            if arr.ndim != 4:
                continue
            grid = make_grid(arr[:max_samples], max_px=max_px)
            self.add_image(f"{prefix}_{key}", grid, step)


def make_grid(batch: np.ndarray, max_px: int = 64) -> np.ndarray:
    """(N, H, W, C) [0,1] -> one square-ish (H', W', C) grid image."""
    n, h, w, c = batch.shape
    if max(h, w) > max_px:  # nearest-neighbor downscale, host-side
        stride = int(np.ceil(max(h, w) / max_px))
        batch = batch[:, ::stride, ::stride, :]
        n, h, w, c = batch.shape
    cols = int(np.ceil(np.sqrt(n)))
    rows = int(np.ceil(n / cols))
    grid = np.zeros((rows * h, cols * w, c), batch.dtype)
    for i in range(n):
        r, col = divmod(i, cols)
        grid[r * h:(r + 1) * h, col * w:(col + 1) * w] = batch[i]
    return np.clip(grid, 0.0, 1.0)
