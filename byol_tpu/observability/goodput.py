"""Goodput/badput accounting: fold flight-recorder spans into a wall-time
partition.

The metric that decides time-to-accuracy at scale is not peak step rate
but the FRACTION of wall time spent in productive device compute (PAPERS:
ImageNet-in-minutes 1709.05011, large-distributed-ConvNets 1711.00705 —
both spend their engineering budget on exactly the buckets below).  This
module turns the host-side spans (observability/spans.py) into that
number, per epoch and per run:

- **productive**: ``train/`` spans — the dispatch windows (host feeding
  the device) plus the epoch metric readback (host blocked on device
  compute that cannot complete before every step has; the StepTimer sync
  discipline makes this the honest device-busy proxy a host can see);
- **badput buckets** (named, additive):
  ``input_wait``       — blocked on the host input pipeline (``input/``);
  ``startup_compile``  — model/optimizer build, tracing, XLA compiles
                         (``startup/``);
  ``telemetry_readback`` — the telemetry sink's lagged device_get windows
                         (``telemetry/``);
  ``eval``             — eval/valid passes (``eval/``);
  ``checkpoint``       — checkpoint serialization stalls (``checkpoint/``);
  ``host_other``       — the unattributed remainder (python glue between
                         spans, logging, span ring eviction).

Only TOP-LEVEL spans (depth 0) are attributed — a nested span's time is
already inside its parent — and the partition is exact by construction:
``productive + sum(badput) == wall`` (events.py validates the identity to
1% on every ``goodput`` event, emit AND read).

One :class:`GoodputMeter` per run: ``fold()`` closes the current window
(epoch boundary), ``final()`` closes the tail and emits the run-scope
totals.  Windows are contiguous — the run wall clock is fully covered
from meter construction to ``final()``.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# span-name prefix -> badput bucket (first match wins; ``train/`` is
# productive, anything unmatched lands in host_other via the remainder)
BADPUT_PREFIXES = (
    ("input/", "input_wait"),
    ("startup/", "startup_compile"),
    ("telemetry/", "telemetry_readback"),
    ("eval/", "eval"),
    ("checkpoint/", "checkpoint"),
)
PRODUCTIVE_PREFIX = "train/"
OTHER_BUCKET = "host_other"

# the full bucket vocabulary, for docs/renderers (host_other always last)
BADPUT_BUCKETS = tuple(b for _, b in BADPUT_PREFIXES) + (OTHER_BUCKET,)


def bucket_of(name: str) -> Optional[str]:
    """Badput bucket for a span name; None = productive (``train/``) or
    unattributed (folded into host_other by the remainder arithmetic)."""
    for prefix, bucket in BADPUT_PREFIXES:
        if name.startswith(prefix):
            return bucket
    return None


def attribute(records: List[Any], wall: float
              ) -> Tuple[float, float, Dict[str, float]]:
    """Partition ``wall`` seconds over a window's DEPTH-0 spans; returns
    ``(wall, productive, badput)`` with the identity
    ``productive + sum(badput) == wall`` exact.  The unattributed
    remainder lands in ``host_other``; a (clock-jitter) negative
    remainder means attributed > wall, and the attributed total is
    reported as wall so the identity stays exact rather than lying by
    clamping."""
    top = [r for r in records if r.depth == 0]
    productive = 0.0
    badput: Dict[str, float] = {b: 0.0 for b in BADPUT_BUCKETS}
    for r in top:
        if r.name.startswith(PRODUCTIVE_PREFIX):
            productive += r.seconds
        else:
            badput[bucket_of(r.name) or OTHER_BUCKET] += r.seconds
    remainder = wall - productive - sum(badput.values())
    if remainder >= 0.0:
        badput[OTHER_BUCKET] += remainder
    else:
        wall = productive + sum(badput.values())
    return wall, productive, badput


def span_stats(records: List[Any]) -> Dict[str, Dict[str, float]]:
    """Per-name aggregate over a window of spans: count, total seconds,
    p50/p99/max milliseconds — the ``span_stats`` event payload."""
    by_name: Dict[str, List[float]] = {}
    for r in records:
        by_name.setdefault(r.name, []).append(r.seconds)
    out: Dict[str, Dict[str, float]] = {}
    for name, secs in sorted(by_name.items()):
        arr = np.asarray(secs, np.float64)
        out[name] = {
            "count": int(arr.size),
            "seconds": float(arr.sum()),
            "p50_ms": float(np.percentile(arr, 50) * 1e3),
            "p99_ms": float(np.percentile(arr, 99) * 1e3),
            "max_ms": float(arr.max() * 1e3),
        }
    return out


class GoodputMeter:
    """Folds a SpanRecorder's ring into contiguous goodput windows.

    Assumes the recorder's DEPTH-0 spans do not overlap in time — true for
    the trainer and bench (one consumer thread drives the phases; the
    prefetch generator's ``input/`` spans run in that same thread).  A
    recorder shared with concurrent depth-0 writers would double-count;
    the serving stack therefore keeps its own per-request accounting
    (serving/meter.py) instead of a GoodputMeter.
    """

    def __init__(self, recorder: Any) -> None:
        self._rec = recorder
        self._since = -1
        self._t_window = time.perf_counter()
        self._windows = 0
        self._run_wall = 0.0
        self._run_productive = 0.0
        self._run_badput: Dict[str, float] = {}

    # ---- window folding ---------------------------------------------------
    def fold(self, *, scope: str = "epoch", epoch: Optional[int] = None,
             mfu: Optional[float] = None, events: Optional[Any] = None,
             emit: bool = True, **extra: Any) -> Dict[str, Any]:
        """Close the current window: attribute its spans, accumulate run
        totals, optionally emit ``goodput`` + ``span_stats`` events.
        Returns the goodput payload."""
        now = time.perf_counter()
        wall = now - self._t_window
        self._t_window = now
        records = self._rec.records(since_seq=self._since)
        if records:
            self._since = max(r.seq for r in records)
        wall, productive, badput = attribute(records, wall)
        self._windows += 1
        self._run_wall += wall
        self._run_productive += productive
        for b, v in badput.items():
            self._run_badput[b] = self._run_badput.get(b, 0.0) + v
        payload: Dict[str, Any] = {
            "scope": scope,
            "wall_seconds": wall,
            "productive_seconds": productive,
            "badput": badput,
            "goodput_fraction": (productive / wall if wall > 0 else 0.0),
            **extra,
        }
        if epoch is not None:
            payload["epoch"] = epoch
        if mfu is not None:
            payload["mfu"] = mfu
        if self._rec.dropped:
            payload["spans_dropped"] = int(self._rec.dropped)
        if emit and events is not None:
            events.emit("goodput", **payload)
            stats = span_stats(records)
            if stats:
                ev: Dict[str, Any] = {"scope": scope, "spans": stats}
                if epoch is not None:
                    ev["epoch"] = epoch
                events.emit("span_stats", **ev)
        return payload

    # ---- end of run -------------------------------------------------------
    def final(self, *, events: Optional[Any] = None,
              mfu: Optional[float] = None, **extra: Any) -> Dict[str, Any]:
        """Absorb the tail window and emit the run-scope totals."""
        self.fold(scope="epoch_tail", events=events, emit=False)
        payload: Dict[str, Any] = {
            "scope": "run",
            "wall_seconds": self._run_wall,
            "productive_seconds": self._run_productive,
            "badput": dict(self._run_badput),
            "goodput_fraction": (self._run_productive / self._run_wall
                                 if self._run_wall > 0 else 0.0),
            "windows": self._windows,
            **extra,
        }
        if mfu is not None:
            payload["mfu"] = mfu
        if self._rec.dropped:
            payload["spans_dropped"] = int(self._rec.dropped)
        if events is not None:
            events.emit("goodput", **payload)
        return payload
