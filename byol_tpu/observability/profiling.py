"""Profiling hooks — jax.profiler integration.

The reference has no tracing at all (SURVEY.md §5.1: a single time.time()
per epoch plus cudnn.benchmark).  TPU-native profiling is first-class here:

- ``trace(logdir)``: capture an XLA/TPU trace viewable in TensorBoard's
  profile plugin or Perfetto;
- ``start_server(port)``: on-demand profiling of a live run from another
  machine (``jax.profiler.start_server`` — the production pod workflow);
- ``annotate(name)``: named host-side regions (TraceAnnotation) that show up
  in the timeline alongside device ops.
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


def start_server(port: int = 9999):
    """Expose this process to on-demand profile capture."""
    return jax.profiler.start_server(port)


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a device+host trace for the enclosed steps."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region in the profiler timeline."""
    return jax.profiler.TraceAnnotation(name)
