"""Collective-deadlock watchdog — the SURVEY.md §5.2 hygiene the reference
lacks entirely (a dead NCCL rank just hangs the job; the reference README
tells the operator to expect NCCL errors, README.md:42).

Under SPMD a lost host / wedged interconnect shows up as a collective that
never completes, which on the host side means the epoch's metric READBACK
never returns.  The watchdog arms a deadline around that readback: if no
progress is reported within ``timeout_s``, every thread's stack is dumped
(so the operator sees exactly which collective/readback is stuck) and the
process optionally dies so the scheduler can requeue it — hung-forever jobs
are the failure mode this prevents.

Built on ``faulthandler.dump_traceback_later`` — async-signal-safe, fires
even when the main thread is blocked inside an XLA runtime call (a plain
Python timer thread could not preempt that reliably... it could run, but
could not introspect the blocked frame; faulthandler dumps it).
"""
from __future__ import annotations

import faulthandler
import sys
from typing import Optional, TextIO


class Watchdog:
    """Progress watchdog: ``pet()`` before each potentially-blocking region
    (epoch readback, eval, checkpoint flush); if the next ``pet()`` or
    ``stop()`` doesn't arrive within ``timeout_s``, all thread stacks are
    dumped to ``file`` (stderr by default) and, when ``exit=True``, the
    process is killed with a nonzero status for the scheduler to requeue."""

    def __init__(self, timeout_s: float, *, exit: bool = True,
                 file: Optional[TextIO] = None) -> None:
        self.timeout_s = float(timeout_s)
        self.exit = exit
        self.file = file if file is not None else sys.stderr
        self._armed = False

    @property
    def enabled(self) -> bool:
        return self.timeout_s > 0

    def pet(self) -> None:
        """Report liveness; (re)arms the deadline."""
        if not self.enabled:
            return
        faulthandler.dump_traceback_later(
            self.timeout_s, repeat=False, file=self.file, exit=self.exit)
        self._armed = True

    def stop(self) -> None:
        """Disarm (end of training / controlled shutdown)."""
        if self._armed:
            faulthandler.cancel_dump_traceback_later()
            self._armed = False

    def __enter__(self) -> "Watchdog":
        self.pet()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
